"""Lane striping suite: weighted chunk scheduling over heterogeneous paths.

Covers the adaptive multi-lane layer (docs/DESIGN.md "Lanes & adaptive
striping") bottom-up:

  * spec parsing + config validation: the native TPUNET_LANES grammar and
    Config.from_env's loud gate agree, errors name the offending token/var;
  * stripe-map goldens: the pure chunk->stream derivation both engines run
    — equal weights reproduce the pre-lane uniform rotation bit-for-bit,
    weighted maps spread chunks proportionally, and an epoch bump
    mid-conversation re-derives deterministically from
    (len, min_chunksize, weights[epoch], cursor) alone;
  * live transfers: two-lane comms on loopback in THIS process, BASIC and
    EPOLL and cross-engine, CRC-verified — static weights produce exact
    byte shares, the WEIGHTS epoch protocol keeps both sides' layouts
    symmetric (any desync would corrupt payload bytes);
  * adaptation: a fault-injected delay on one lane demotes it (restripe
    events + weight gauges move) while every message stays bit-correct.
"""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

from tpunet import _native, transport

# ---------------------------------------------------------------------------
# Spec parsing (no sockets).


def test_lane_parse_normalizes_spec():
    lanes = transport.lane_parse("addr=127.0.0.1:w=4,addr=[::1]:w=3,w=2")
    assert lanes == [
        {"lane": 0, "addr": "127.0.0.1", "w": 4},
        {"lane": 1, "addr": "::1", "w": 3},
        {"lane": 2, "addr": None, "w": 2},
    ]
    assert transport.lane_parse("") == []


@pytest.mark.parametrize(
    "spec, token",
    [
        ("addr=nonsense:w=1", "nonsense"),
        ("w=0", "0"),
        ("w=256", "256"),
        ("w=4x", "4x"),
        ("flavor=spicy", "flavor"),
        ("w=1,,w=2", "empty lane"),
        ("addr=10.0.0.1:", "empty clause"),
        ("w", "key=value"),
    ],
)
def test_lane_parse_rejects_malformed(spec, token):
    with pytest.raises(_native.NativeError) as ei:
        transport.lane_parse(spec)
    assert ei.value.code == _native.TPUNET_ERR_INVALID
    assert token in str(ei.value)


# ---------------------------------------------------------------------------
# Config validation (the loud gate naming the var).


@pytest.mark.parametrize(
    "var, value, ok",
    [
        ("TPUNET_LANES", "addr=10.0.0.1:w=4,addr=10.0.1.1:w=1", True),
        ("TPUNET_LANES", "w=4,w=1", True),
        ("TPUNET_LANES", "addr=bogus:w=4", False),
        ("TPUNET_LANES", "w=0", False),
        ("TPUNET_LANES", "w=999", False),
        ("TPUNET_LANES", "flavor=spicy", False),
        ("TPUNET_LANES", "w=1,,w=2", False),
        ("TPUNET_LANE_ADAPT_MS", "50", True),
        ("TPUNET_LANE_ADAPT_MS", "0", False),
        ("TPUNET_LANE_ADAPT_MS", "-5", False),
    ],
)
def test_config_validates_lane_knobs(monkeypatch, var, value, ok):
    from tpunet.config import Config

    monkeypatch.setenv(var, value)
    if ok:
        Config.from_env()
    else:
        with pytest.raises(ValueError, match=var):
            Config.from_env()


def test_config_carries_lane_knobs(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_LANES", "w=4,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT", "0")
    monkeypatch.setenv("TPUNET_LANE_ADAPT_MS", "40")
    cfg = Config.from_env()
    assert cfg.lanes == "w=4,w=1"
    assert cfg.lane_adapt is False
    assert cfg.lane_adapt_ms == 40


# ---------------------------------------------------------------------------
# Stripe-map goldens: the derivation both sides run, pinned with no sockets.


def test_stripe_map_equal_weights_is_uniform_rotation():
    """Equal weights must reproduce the pre-lane cursor%nstreams rotation
    bit-for-bit — the wire-compat contract for default configs — across a
    (len, min_chunksize, nstreams, cursor) grid."""
    for n in (1, 2, 3, 4, 8):
        for length in (0, 1, 4096, 1 << 20, (8 << 20) + 13):
            for minc in (1 << 10, 1 << 20):
                for cursor in (0, 1, 7, 1000):
                    got = transport.stripe_map(length, minc, [1] * n, cursor)
                    csize = max(-(-length // n), minc) if length else minc
                    nchunks = -(-length // csize) if length else 0
                    assert got == [(cursor + i) % n for i in range(nchunks)], (
                        n, length, minc, cursor)


def test_stripe_map_weighted_goldens():
    """WRR slot tables are pinned literals: stride scheduling spreads the
    heavy lane across the period instead of bursting it. A message never
    has more than nstreams chunks (csize >= ceil(len/n)), so the table is
    observed by walking the persisted cursor across consecutive messages —
    exactly what the comms do."""
    # weights [4,1] -> period-5 table [0,0,1,0,0].
    table41 = [0, 0, 1, 0, 0]
    walk = []
    for c in range(0, 10, 2):  # five 2-chunk messages
        walk += transport.stripe_map(4 << 20, 1 << 20, [4, 1], cursor=c)
    assert walk == table41 * 2
    # weights [1,2,3] -> period-6 table [2,1,0,2,1,2].
    assert transport.stripe_map(6 << 20, 1 << 10, [1, 2, 3]) == [2, 1, 0]
    assert transport.stripe_map(6 << 20, 1 << 10, [1, 2, 3], cursor=3) == [2, 1, 2]
    # Cursor continuation: message 2 picks up exactly where message 1's
    # chunks left the rotation — the persisted-cursor fairness contract.
    msg1 = transport.stripe_map(4 << 20, 1 << 20, [4, 1], cursor=0)
    msg2 = transport.stripe_map(4 << 20, 1 << 20, [4, 1], cursor=len(msg1))
    assert msg1 + msg2 == table41[:4]


def test_stripe_map_shares_track_weights():
    for weights in ([4, 1], [1, 2, 3], [16, 1], [3, 3, 1]):
        counts = {i: 0 for i in range(len(weights))}
        cursor = 0
        total = 0
        for _ in range(200):  # cursor persists across messages, as in a comm
            m = transport.stripe_map(len(weights) << 20, 1 << 10, weights, cursor)
            cursor += len(m)
            total += len(m)
            for s in m:
                counts[s] += 1
        for i, w in enumerate(weights):
            share = counts[i] / total
            expect = w / sum(weights)
            assert abs(share - expect) < 0.02, (weights, i, share, expect)


def test_stripe_map_epoch_bump_mid_conversation():
    """A weight-vector epoch change between messages re-derives the layout
    from the NEW vector only — both sides compute the same maps from the
    same (len, min_chunksize, weights[epoch], cursor) inputs, before and
    after the bump."""
    cursor = 0
    epoch_a = [1, 1]
    epoch_b = [7, 2]
    msgs = [3 << 20, 5 << 20, 4 << 20]
    seen = []
    for i, length in enumerate(msgs):
        weights = epoch_a if i < 1 else epoch_b  # bump after message 0
        m = transport.stripe_map(length, 1 << 20, weights, cursor)
        m2 = transport.stripe_map(length, 1 << 20, weights, cursor)
        assert m == m2  # deterministic: "both sides" agree by construction
        cursor += len(m)
        seen.append(m)
    assert seen[0] == [0, 1]  # uniform rotation, 2 chunks of 1.5 MiB
    # Epoch B's table is [0,0,1,0,0,0,1,0,0] (period 9); cursor resumed at 2.
    assert seen[1] == [1, 0]
    assert seen[2] == [0, 0]


def test_stripe_map_rejects_malformed():
    for bad_weights in ([0], [256], []):
        with pytest.raises(_native.NativeError) as ei:
            transport.stripe_map(1 << 20, 1 << 20, bad_weights)
        assert ei.value.code == _native.TPUNET_ERR_INVALID
    with pytest.raises(_native.NativeError):
        transport.stripe_map(1 << 20, 0, [1])  # min_chunksize must be >= 1


# ---------------------------------------------------------------------------
# Live two-lane transfers on loopback (both engines in THIS process).


def _wire_pair(net_s, net_r):
    lc = net_r.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = net_s.connect(lc.handle)
    th.join()
    return sc, got["rc"], lc


def _lane_tx_bytes():
    from tpunet import telemetry

    out = {}
    for labels, value in telemetry.metrics().get(
            "tpunet_lane_bytes_total", {}).items():
        lab = telemetry.labels(labels)
        if lab.get("dir") == "tx":
            out[int(lab["lane"])] = int(value)
    return out


@pytest.mark.parametrize("engine", ["BASIC", "EPOLL"])
def test_static_weights_give_exact_byte_shares(monkeypatch, engine):
    """TPUNET_LANES=w=3,w=1 with adaptation off: CRC-verified transfers land
    exactly 3:1 bytes across the lanes on both engines. Content equality is
    the layout-symmetry proof — a receiver deriving a different chunk map
    would scatter payload bytes to wrong offsets."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_IMPLEMENT", engine)
    monkeypatch.setenv("TPUNET_LANES", "w=3,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT", "0")
    monkeypatch.setenv("TPUNET_MIN_CHUNKSIZE", str(64 << 10))
    monkeypatch.setenv("TPUNET_CRC", "1")
    telemetry.reset()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            src = np.arange(512 << 10, dtype=np.uint8)
            for _ in range(20):
                dst = np.zeros_like(src)
                r = rc.irecv(dst)
                sc.isend(src).wait(timeout=60)
                r.wait(timeout=60)
                np.testing.assert_array_equal(src, dst)
        finally:
            for c in (sc, rc, lc):
                c.close()
    lanes = _lane_tx_bytes()
    assert set(lanes) == {0, 1}
    # 20 msgs x 2 chunks walk the [0,0,1,0] table an integer number of
    # periods: the 3:1 split is exact, not approximate.
    assert lanes[0] == 3 * lanes[1], lanes


def test_cross_engine_lane_comm(monkeypatch):
    """A BASIC lane-mode sender striping into an EPOLL receiver: the lane
    protocol (preamble bit + WEIGHTS frames + slot-table walk) is engine-
    independent, like the rest of the wire contract."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_LANES", "w=2,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT", "0")
    monkeypatch.setenv("TPUNET_MIN_CHUNKSIZE", str(64 << 10))
    monkeypatch.setenv("TPUNET_CRC", "1")
    telemetry.reset()
    monkeypatch.setenv("TPUNET_IMPLEMENT", "BASIC")
    ns = Net()
    monkeypatch.setenv("TPUNET_IMPLEMENT", "EPOLL")
    nr = Net()
    sc, rc, lc = _wire_pair(ns, nr)
    try:
        src = np.arange(384 << 10, dtype=np.uint8)
        for _ in range(12):
            dst = np.zeros_like(src)
            r = rc.irecv(dst)
            sc.isend(src).wait(timeout=60)
            r.wait(timeout=60)
            np.testing.assert_array_equal(src, dst)
    finally:
        for c in (sc, rc, lc):
            c.close()
        ns.close()
        nr.close()
    lanes = _lane_tx_bytes()
    assert lanes[0] == 2 * lanes[1], lanes


@pytest.mark.parametrize("engine", ["BASIC", "EPOLL"])
def test_single_chunk_messages_rotate_lanes(monkeypatch, engine):
    """Small (single-chunk) messages take lane turns by weight across
    messages — the fairness rotation the paper pins, weighted. On BASIC
    this also exercises the lazy-recv path's WEIGHTS-frame handling."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_IMPLEMENT", engine)
    monkeypatch.setenv("TPUNET_LANES", "w=3,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT", "0")
    monkeypatch.setenv("TPUNET_CRC", "1")
    telemetry.reset()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            src = np.arange(8 << 10, dtype=np.uint8)  # single chunk
            for _ in range(16):
                dst = np.zeros_like(src)
                r = rc.irecv(dst)
                sc.isend(src).wait(timeout=60)
                r.wait(timeout=60)
                np.testing.assert_array_equal(src, dst)
        finally:
            for c in (sc, rc, lc):
                c.close()
    lanes = _lane_tx_bytes()
    assert lanes[0] == 3 * lanes[1], lanes


@pytest.mark.parametrize("engine", ["BASIC", "EPOLL"])
def test_adaptation_demotes_delayed_lane(monkeypatch, engine):
    """A fault-injected delay on lane 1 drives the adaptation loop: weight
    epochs get published (restripe counter), the slow lane's weight decays
    below the fast lane's, byte shares skew accordingly — and every message
    stays bit-correct under CRC through every re-stripe boundary."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_IMPLEMENT", engine)
    monkeypatch.setenv("TPUNET_LANES", "w=1,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT_MS", "20")
    monkeypatch.setenv("TPUNET_MIN_CHUNKSIZE", str(64 << 10))
    monkeypatch.setenv("TPUNET_CRC", "1")
    telemetry.reset()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            transport.fault_inject("stream=1:side=send:action=delay=3")
            src = np.arange(256 << 10, dtype=np.uint8)
            for _ in range(120):
                dst = np.zeros_like(src)
                r = rc.irecv(dst)
                sc.isend(src).wait(timeout=60)
                r.wait(timeout=60)
                np.testing.assert_array_equal(src, dst)
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                c.close()
    from tpunet import telemetry as t

    m = t.metrics()
    restripes = sum(m.get("tpunet_restripe_events_total", {}).values())
    assert restripes >= 1, "adaptation never published a weight epoch"
    weights = {}
    for labels, value in m.get("tpunet_lane_weight", {}).items():
        weights[int(t.labels(labels)["lane"])] = int(value)
    assert weights[0] > weights[1], weights
    lanes = _lane_tx_bytes()
    share_slow = lanes[1] / (lanes[0] + lanes[1])
    assert share_slow < 0.4, lanes  # decayed well below the uniform 50%


def test_min_rtt_gauge_exported(monkeypatch):
    """The TCP_INFO sampler exports tcpi_min_rtt per stream/dir — the
    observable per-path RTT floor (satellite)."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_IMPLEMENT", "BASIC")
    telemetry.reset()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            src = np.arange(1 << 20, dtype=np.uint8)
            dst = np.zeros_like(src)
            r = rc.irecv(dst)
            sc.isend(src).wait(timeout=60)
            r.wait(timeout=60)
        finally:
            for c in (sc, rc, lc):
                c.close()
    fam = telemetry.metrics().get("tpunet_stream_min_rtt_us", {})
    assert fam, "no tpunet_stream_min_rtt_us samples after loopback traffic"
    for labels in fam:
        lab = telemetry.labels(labels)
        assert "stream" in lab and lab.get("dir") in ("tx", "rx")
