"""Elastic churn engine: shrink/grow the world mid-run, counter-gated.

The churn suite (docs/DESIGN.md "Elastic churn"): scripted kill/join
sequences through the chaos grammar, the measured rewire pipeline
(detect/quiesce/rendezvous/rewire), CRC32C cross-rank parameter equality
after every rewire, shape re-derivation proven equal to fresh wiring, and
the serving tier's re-admission handshake. Together with
tests/churn_smoke.py this runs 6+ scripted churn events (mixed kill/join,
training + serving tiers) with zero corrupted results and every failure
mode typed.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from pathlib import Path

import numpy as np
import pytest

os.environ["JAX_PLATFORMS"] = "cpu"

from conftest import free_port  # noqa: E402

NPARAMS = 64
STEPS = 14
# The flagship script: member 2 SIGKILLs itself at step 3 (shrink 3 -> 2),
# member 3 requests entry once the job checkpoints step 6 (grow 2 -> 3).
FLAGSHIP_SPEC = ("churn:at_step=3:rank=2:action=kill;"
                 "churn:at_step=6:rank=3:action=join")


# ---------------------------------------------------------------------------
# Grammar: native parser, Python mirror, typed rejection.


def test_churn_script_native_parse_and_poll():
    from tpunet import _native, elastic, transport

    lib = _native.load()
    _native.check(lib.tpunet_c_fault_inject(
        b"churn:at_step=4:rank=3:action=kill;churn:at_step=8:rank=4:action=join"),
        "inject")
    try:
        assert elastic.churn_pending() == 2
        assert elastic.churn_action(3, 3) is None      # before at_step
        assert elastic.churn_action(4, 2) is None      # wrong member
        assert elastic.churn_action(5, 3) == "kill"    # >= at_step fires
        assert elastic.churn_action(5, 3) is None      # one-shot latch
        assert elastic.churn_pending() == 1
        assert elastic.churn_action(9, 4) == "join"
        assert elastic.churn_pending() == 0
    finally:
        transport.fault_clear()
    assert elastic.churn_pending() == 0  # clear wipes the script


def test_churn_script_wildcard_and_mixed_segment():
    from tpunet import _native, elastic, transport

    lib = _native.load()
    # A classic fault segment may ride along; churn rank=* matches anyone.
    _native.check(lib.tpunet_c_fault_inject(
        b"stream=1:action=close;churn:rank=*:action=kill"), "inject")
    try:
        assert elastic.churn_pending() == 1
        assert elastic.churn_action(0, 17) == "kill"
    finally:
        transport.fault_clear()


@pytest.mark.parametrize("spec", [
    "churn:at_step=1:action=nuke",        # unknown action
    "churn:at_step=1:rank=0",             # missing action
    "churn:badkey=1:action=kill",         # unknown key
    "churn:at_step=x:action=kill",        # bad number
    "stream=0:action=close;stream=1:action=close",  # two classic faults
    ";churn:action=kill",                 # empty segment
])
def test_churn_script_malformed_typed(spec):
    from tpunet import _native

    lib = _native.load()
    assert lib.tpunet_c_fault_inject(spec.encode()) == _native.TPUNET_ERR_INVALID
    assert _native.last_error()


def test_parse_churn_script_python_mirror():
    from tpunet import elastic

    events = elastic.parse_churn_script(FLAGSHIP_SPEC)
    assert events == [
        {"at_step": 3, "rank": 2, "action": "kill"},
        {"at_step": 6, "rank": 3, "action": "join"},
    ]
    # Classic segments are skipped; churn malformations raise ValueError.
    assert elastic.parse_churn_script("stream=1:action=close") == []
    with pytest.raises(ValueError, match="action"):
        elastic.parse_churn_script("churn:at_step=1:action=nuke")


# ---------------------------------------------------------------------------
# Knobs + typed rewire timeout.


def test_churn_knobs_registered_and_validated():
    from tpunet.config import Config

    cfg = Config.from_env()
    assert cfg.churn_grace_ms == 10_000
    assert cfg.rewire_timeout_ms == 120_000
    assert cfg.readmit_probe_ms == 500
    for var in ("TPUNET_CHURN_GRACE_MS", "TPUNET_REWIRE_TIMEOUT_MS",
                "TPUNET_READMIT_PROBE_MS"):
        os.environ[var] = "0"
        try:
            with pytest.raises(ValueError, match=var):
                Config.from_env()
        finally:
            os.environ.pop(var)


def test_rewire_timeout_typed(tmp_path):
    # A 1 ms rewire deadline cannot be met (finalize alone exceeds it):
    # the pipeline must fail with the TYPED RewireTimeoutError (-9), not
    # hang and not a bare RuntimeError.
    from tpunet import _native, elastic

    world = elastic.ElasticWorld(
        f"127.0.0.1:{free_port()}", 0, 1, directory=tmp_path,
        grace_ms=1, rewire_timeout_ms=1)
    world.create()
    try:
        with pytest.raises(_native.RewireTimeoutError):
            world.on_failure(_native.NativeError(-3, "synthetic comm loss"))
    finally:
        world.close()


def test_crc_check_passes_and_counts(tmp_path):
    from tpunet import elastic

    world = elastic.ElasticWorld(
        f"127.0.0.1:{free_port()}", 0, 1, directory=tmp_path)
    comm = world.create()
    try:
        params = np.arange(128, dtype=np.float32)
        d1 = world.crc_check(params)
        d2 = world.crc_check([params, params * 2])  # chained multi-array
        assert d1 != 0 and d2 != 0 and d1 != d2
        assert world.stats["crc_checks"] == 2
        assert comm.world_size == 1
    finally:
        world.close()


# ---------------------------------------------------------------------------
# The flagship: scripted kill -> shrink -> join -> grow on the training tier.


def _latest_step(ckpt: Path) -> int:
    steps = [int(p.stem.split("_")[1]) for p in ckpt.glob("step_*.npy")]
    return max(steps, default=-1)


def _grad(step: int, rank: int) -> np.ndarray:
    rng = np.random.default_rng(7 * step + rank)
    return rng.standard_normal(NPARAMS).astype(np.float32)


def _churn_env(spec: str) -> None:
    os.environ["TPUNET_FAULT_SPEC"] = spec
    os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = "30000"
    os.environ["TPUNET_CONNECT_RETRY_MS"] = "2000"
    # RST-independent detection bounds (the de-flaked fault-paths stance):
    # a SIGKILLed peer's verdict must arrive in seconds, not at TCP's mercy.
    os.environ["TPUNET_PROGRESS_TIMEOUT_MS"] = "10000"
    os.environ["TPUNET_KEEPALIVE_IDLE_S"] = "3"
    os.environ["TPUNET_KEEPALIVE_INTVL_S"] = "2"
    os.environ["TPUNET_KEEPALIVE_CNT"] = "2"


def _flagship_worker(member_id: int, world_size: int, port: int, q,
                     dirpath: str, joiner: bool) -> None:
    try:
        _churn_env(FLAGSHIP_SPEC)
        from tpunet import _native, elastic, telemetry

        ckpt = Path(dirpath)

        if joiner:
            # The joiner side of the script: arm it (no engine exists yet to
            # do so), then request entry once the job's CHECKPOINTED step
            # reaches the scripted at_step — the deterministic clock a
            # process outside the world can observe.
            _native.load().tpunet_c_fault_inject(FLAGSHIP_SPEC.encode())
            while True:
                latest = _latest_step(ckpt)
                if latest >= 0 and \
                        elastic.churn_action(latest, member_id) == "join":
                    break
                time.sleep(0.1)

        def train_once(world, comm):
            while True:
                latest = _latest_step(ckpt)
                if latest >= 0:
                    params = np.load(ckpt / f"step_{latest}.npy")
                    start = latest + 1
                else:
                    params = np.zeros(NPARAMS, np.float32)
                    start = 0
                if world.stats["rewires"]:
                    # The acceptance gate: CRC cross-rank equality after
                    # EVERY rewire, before another step runs.
                    world.crc_check(params)
                restart = False
                for step in range(start, STEPS):
                    if world.churn_action(step) == "kill":
                        os.kill(os.getpid(), signal.SIGKILL)
                    new = world.maybe_rewire(step)
                    if new is not None:
                        comm = new
                        restart = True
                        break
                    g = comm.all_reduce(_grad(step, comm.rank)) / comm.world_size
                    params = params - 0.1 * g
                    if comm.rank == 0:
                        tmp = ckpt / f".step_{step}.tmp.npy"
                        np.save(tmp, params)
                        os.replace(tmp, ckpt / f"step_{step}.npy")
                    comm.barrier()
                    world.step_ok()
                    if comm.world_size < world_size:
                        time.sleep(0.25)  # keep the join window real
                if not restart:
                    return params, comm.world_size, dict(world.stats)

        params, final_world, stats = elastic.run(
            train_once, coordinator=f"127.0.0.1:{port}",
            member_id=member_id, world_size=world_size, directory=dirpath,
            joiner=joiner, grace_ms=4000)
        m = telemetry.metrics()
        phases = {telemetry.labels(k)["phase"]: int(v)
                  for k, v in m["tpunet_rewire_duration_us_count"].items()}
        kinds = {telemetry.labels(k)["kind"]: int(v)
                 for k, v in m["tpunet_churn_events_total"].items()}
        gauge = int(next(iter(m["tpunet_world_size"].values())))
        sums = {telemetry.labels(k)["phase"]: float(v)
                for k, v in m["tpunet_rewire_duration_us_sum"].items()}
        q.put((member_id, ("OK", params.tolist(), final_world, phases,
                           kinds, gauge, stats, sums)))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((member_id, (f"FAIL {type(e).__name__}: {e}",
                           traceback.format_exc()[-800:])))


def test_scripted_kill_shrink_join_grow_training(tmp_path):
    """Kill -> shrink -> join -> grow, scripted entirely by the chaos
    grammar: member 2 dies at step 3 (survivors rewire to W=2 with
    measured phases), member 3 joins once the job checkpoints step 6
    (survivors grow back to W=3 without restarting the job), training
    re-shards via the checkpoint contract, and the CRC cross-rank gate
    passes after every rewire. Gates: final params bitwise-identical on
    every member, world back at 3 (comm AND the tpunet_world_size gauge),
    every rewire phase histogram non-empty, shrink+grow+join counted, and
    no rewire phase-sum exceeding TPUNET_REWIRE_TIMEOUT_MS."""
    import multiprocessing as mp
    import queue as queue_mod

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    vq = ctx.Queue()  # victim-only (mp.Queue SIGKILL write-lock hazard)
    port = free_port()
    procs = {
        0: ctx.Process(target=_flagship_worker,
                       args=(0, 3, port, q, str(tmp_path), False)),
        1: ctx.Process(target=_flagship_worker,
                       args=(1, 3, port, q, str(tmp_path), False)),
        2: ctx.Process(target=_flagship_worker,
                       args=(2, 3, port, vq, str(tmp_path), False)),
        3: ctx.Process(target=_flagship_worker,
                       args=(3, 3, port, q, str(tmp_path), True)),
    }
    for p in procs.values():
        p.start()
    results: dict = {}
    deadline = time.time() + 180
    try:
        while len(results) < 3 and time.time() < deadline:
            try:
                mid, payload = q.get(timeout=1.0)
                results[mid] = payload
            except queue_mod.Empty:
                pass
    finally:
        for p in procs.values():
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
                p.join()

    assert procs[2].exitcode == -signal.SIGKILL, \
        f"scripted kill never fired (exit {procs[2].exitcode})"
    bad = {m: v for m, v in results.items() if v[0] != "OK"}
    assert not bad, f"worker failures: {bad}"
    assert sorted(results) == [0, 1, 3], f"missing members: {results.keys()}"

    p0 = np.asarray(results[0][1], np.float32)
    for mid in (1, 3):
        np.testing.assert_array_equal(
            p0, np.asarray(results[mid][1], np.float32),
            err_msg=f"member {mid} diverged across churn")
    for mid, payload in results.items():
        _, _, final_world, phases, kinds, gauge, stats, sums = payload
        assert final_world == 3, f"member {mid} world {final_world} != 3"
        assert gauge == 3, f"member {mid} tpunet_world_size gauge {gauge}"
        assert all(phases.get(ph, 0) >= 1 for ph in
                   ("detect", "quiesce", "rendezvous", "rewire")), \
            f"member {mid} has empty rewire phases: {phases}"
        # Bounded recovery: no phase-sum beyond the (default) rewire
        # deadline — each rewire's four phases each ran under it.
        assert all(v < 120_000 * 1e3 for v in sums.values()), sums
        assert stats["crc_checks"] >= stats["rewires"] >= 1
        if mid == 3:
            assert kinds["join"] >= 1  # the joiner counts its own entry
        else:
            assert kinds["shrink"] == 1 and kinds["grow"] == 1, kinds
            assert kinds["join"] == 1, kinds  # survivors count the admit

    from tpunet.train.elastic import read_generation

    assert read_generation(tmp_path) >= 2  # shrink bump + grow bump


# ---------------------------------------------------------------------------
# Shape re-derivation: a W=8 -> 6 shrink equals fresh wiring at W=6.

REDERIVE_SPEC = ("churn:at_step=1:rank=3:action=kill;"
                 "churn:at_step=1:rank=7:action=kill")
_COUNT = 64 << 10  # 256 KiB f32 payload for the measured allreduces


def _shape_probe(comm) -> dict:
    """Counter + stripe-map fingerprint of the live shape: run the measured
    window (2 hier allreduces) against reset counters and snapshot what
    wiring-time state determines — dispatch selections, hier stage rounds,
    and the WRR stripe derivation both engines would use for this
    message."""
    from tpunet import telemetry, transport
    from tpunet.config import Config

    cfg = Config.from_env()
    arr = np.full(_COUNT, float(comm.rank + 1), np.float32)
    comm.all_reduce(arr)  # warmup: wires mesh/subgroups, runs the quiesce
    comm.barrier()
    telemetry.reset()
    out = None
    for _ in range(2):
        out = comm.all_reduce(arr)
    m = telemetry.metrics()
    comm.barrier()
    selected = {
        (telemetry.labels(k)["coll"], telemetry.labels(k)["algo"]): int(v)
        for k, v in m.get("tpunet_coll_algo_selected_total", {}).items()}
    steps = {telemetry.labels(k)["algo"]: int(v)
             for k, v in m.get("tpunet_coll_steps_total", {}).items()}
    stripe = transport.stripe_map(
        _COUNT * 4, cfg.min_chunksize, [1] * cfg.nstreams, 0)
    return {"selected": selected, "steps": steps, "stripe": stripe,
            "rank": comm.rank, "world": comm.world_size,
            "sum0": float(out[0])}


def _rederive_shrink_worker(member_id: int, world_size: int, port: int, q,
                            dirpath: str) -> None:
    try:
        _churn_env(REDERIVE_SPEC)
        # 2 fake hosts x 4 ranks; killing members 3 and 7 leaves 3 + 3 —
        # a uniform (H=2, R=3) topology the hier schedule re-derives.
        os.environ["TPUNET_HOST_ID"] = f"rederive{member_id // 4}"
        from tpunet import elastic

        world = elastic.ElasticWorld(
            f"127.0.0.1:{port}", member_id, world_size, directory=dirpath,
            algo="hier", grace_ms=5000)
        comm = world.create()
        probe = None
        for step in range(2):
            if world.churn_action(step) == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            try:
                comm.all_reduce(np.ones(16, np.float32))
                world.step_ok()
            except Exception as exc:  # noqa: BLE001 — classified below
                comm = world.on_failure(exc)
                break
        assert comm.world_size == 6, f"shrink missed: W={comm.world_size}"
        world.crc_check(np.ones(16, np.float32))
        probe = _shape_probe(comm)
        q.put((member_id, ("OK", probe)))
        world.close()
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((member_id, (f"FAIL {type(e).__name__}: {e}",
                           traceback.format_exc()[-800:])))


def _rederive_fresh_worker(rank: int, world_size: int, port: int, q) -> None:
    try:
        os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = "30000"
        os.environ["TPUNET_HOST_ID"] = f"rederive{rank // 3}"
        from tpunet import distributed

        comm = distributed.initialize(
            f"127.0.0.1:{port}", rank, world_size, algo="hier")
        probe = _shape_probe(comm)
        q.put((rank, ("OK", probe)))
        distributed.finalize()
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL {type(e).__name__}: {e}",
                      traceback.format_exc()[-800:])))


def _collect(procs: dict, queues: list, want: set, deadline_s: float) -> dict:
    import queue as queue_mod

    results: dict = {}
    deadline = time.time() + deadline_s
    try:
        while len(results) < len(want) and time.time() < deadline:
            for qq in queues:
                try:
                    mid, payload = qq.get(timeout=0.2)
                    if mid in want:
                        results[mid] = payload
                except queue_mod.Empty:
                    pass
    finally:
        for p in procs.values():
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
                p.join()
    return results


def test_shrink_rederives_shape_state_vs_fresh_wiring(tmp_path):
    """Frozen-state regressions become loud: after a scripted W=8 -> 6
    shrink on a 2-host fake split (one death per host -> uniform H=2,
    R=3), every survivor's dispatch-table selections
    (tpunet_coll_algo_selected_total), hier stage rounds
    (tpunet_coll_steps_total{algo="hier.*"}) and WRR stripe-map derivation
    (tpunet_c_stripe_map) must MATCH a fresh job wired directly at the
    same W=6 shape — the re-derivation inventory of DESIGN.md §12, pinned
    by counters rather than rhetoric."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    vq1, vq2 = ctx.Queue(), ctx.Queue()
    port = free_port()
    procs = {}
    for mid in range(8):
        qq = vq1 if mid == 3 else (vq2 if mid == 7 else q)
        procs[mid] = ctx.Process(
            target=_rederive_shrink_worker,
            args=(mid, 8, port, qq, str(tmp_path)))
        procs[mid].start()
    survivors = {0, 1, 2, 4, 5, 6}
    results = _collect(procs, [q], survivors, 180)
    assert procs[3].exitcode == -signal.SIGKILL
    assert procs[7].exitcode == -signal.SIGKILL
    bad = {m: v for m, v in results.items() if v[0] != "OK"}
    assert not bad, f"shrink-worker failures: {bad}"
    assert set(results) == survivors, f"missing: {survivors - set(results)}"

    # Fresh control at the SAME shape: W=6, hosts by new-rank // 3.
    ctx2 = mp.get_context("spawn")
    q2 = ctx2.Queue()
    port2 = free_port()
    fresh_procs = {
        r: ctx2.Process(target=_rederive_fresh_worker, args=(r, 6, port2, q2))
        for r in range(6)
    }
    for p in fresh_procs.values():
        p.start()
    fresh = _collect(fresh_procs, [q2], set(range(6)), 120)
    bad = {m: v for m, v in fresh.items() if v[0] != "OK"}
    assert not bad, f"fresh-control failures: {bad}"

    # Members sort to new ranks: {0,1,2,4,5,6} -> 0..5.
    new_rank = {m: i for i, m in enumerate(sorted(survivors))}
    for mid in sorted(survivors):
        got = results[mid][1]
        want = fresh[new_rank[mid]][1]
        assert got["rank"] == want["rank"] == new_rank[mid]
        assert got["world"] == want["world"] == 6
        assert got["selected"] == want["selected"], \
            f"member {mid}: dispatch selections diverge from fresh wiring " \
            f"({got['selected']} vs {want['selected']})"
        assert got["steps"] == want["steps"], \
            f"member {mid}: hier stage rounds diverge ({got['steps']} vs " \
            f"{want['steps']})"
        assert got["stripe"] == want["stripe"], "stripe-map derivation drifted"
        # hier actually engaged on the re-derived topology (not a silent
        # ring degrade): both stages ran, selection says hier.
        assert got["selected"].get(("allreduce", "hier")) == 2, got["selected"]
        assert got["steps"].get("hier.intra", 0) > 0
        assert got["steps"].get("hier.inter", 0) > 0
    # The reduction itself is correct post-shrink: sum over ranks+1 at W=6.
    for mid in survivors:
        assert results[mid][1]["sum0"] == sum(r + 1 for r in range(6))


# ---------------------------------------------------------------------------
# Serving tier: re-admission (unit + integration).


def _tiny_setup():
    import jax
    import jax.numpy as jnp

    from tpunet.models import Transformer

    model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, compute_dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    return model, params


def _oracle(model, params, prompt, n):
    import jax.numpy as jnp

    from tpunet.models import generate

    out = generate(model, params, jnp.asarray(prompt)[None], n)
    return np.asarray(out)[0, len(prompt):]


def test_router_readmission_rejoins_pool_and_serves(tmp_path):
    """Integration: the ONLY decode rank dies mid-window with a request in
    flight; the router (re-admission armed) keeps the wiring port open,
    the recovered host reconnects through the full hello re-handshake,
    re-enters the placement pool, and the stranded + remaining requests
    complete bitwise-correct (replay-from-retained-KV) on the readmitted
    rank. Counters: rank_failures == 1, readmissions == 1,
    tpunet_churn_events_total{kind="readmit"} advanced."""
    from tpunet import serve, telemetry

    model, params = _tiny_setup()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 64, 7).astype(np.int32) for _ in range(3)]
    lens = [6, 6, 6]

    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]
    flaky_done = threading.Event()

    def flaky_decode():
        worker = serve.connect_decode(addr, model, params, slots=1,
                                      max_len=40, kv_codec="f32")
        worker.serve(max_blocks=1)  # ingest one block, report nothing, die
        worker.close()
        flaky_done.set()

    def recovered_decode():
        flaky_done.wait(timeout=120)
        worker = serve.connect_decode(addr, model, params, slots=1,
                                      max_len=40, kv_codec="f32")
        try:
            worker.serve()
        finally:
            worker.close()

    telemetry.reset()
    th_flaky = threading.Thread(target=flaky_decode, daemon=True)
    th_flaky.start()
    prefill = serve.PrefillEngine(model, params, max_len=40)
    router = serve.Router(prefill, kv_codec="f32", retain_kv=True)
    router.accept_ranks(lsock, 1)
    router.enable_readmission(lsock)
    th_rec = threading.Thread(target=recovered_decode, daemon=True)
    th_rec.start()

    ids = [router.submit(p, n) for p, n in zip(prompts, lens)]
    results = router.run(timeout=240)
    router.shutdown()
    th_flaky.join(timeout=60)
    th_rec.join(timeout=60)

    assert sorted(results) == sorted(ids)
    for p, n, i in zip(prompts, lens, ids):
        assert len(results[i]) == n, "truncated stream across churn"
        np.testing.assert_array_equal(results[i], _oracle(model, params, p, n))
    assert router.stats["rank_failures"] == 1
    assert router.stats["readmissions"] == 1
    assert router.stats["replays_kv"] >= 1
    m = telemetry.metrics()
    kinds = {telemetry.labels(k)["kind"]: int(v)
             for k, v in m["tpunet_churn_events_total"].items()}
    assert kinds["readmit"] == 1, kinds
    router.close()
    lsock.close()


def test_router_readmission_signature_drift_typed():
    """Unit: a host rejoining with a DIFFERENT model configuration must
    fail the re-handshake typed — TierMismatchError on the router's
    poll_admissions() surface AND on the decode side — never a silent
    re-admission; a correct host afterwards is admitted."""
    from tpunet import serve
    from tpunet.serve import protocol as proto

    model, params = _tiny_setup()
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]
    prefill = serve.PrefillEngine(model, params, max_len=40)
    router = serve.Router(prefill, kv_codec="f32")
    router.enable_readmission(lsock)

    drift_err: list = []

    def drifted_decode():
        import jax
        import jax.numpy as jnp

        from tpunet.models import Transformer

        other = Transformer(vocab=64, d_model=16, n_layers=1, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        oparams = other.init(jax.random.PRNGKey(1),
                             np.zeros((1, 8), np.int32))["params"]
        try:
            serve.connect_decode(addr, other, oparams, slots=1, max_len=40,
                                 kv_codec="f32")
        except proto.TierMismatchError as e:
            drift_err.append(e)

    th = threading.Thread(target=drifted_decode, daemon=True)
    th.start()
    deadline = time.monotonic() + 60
    with pytest.raises(proto.TierMismatchError, match="signature"):
        while time.monotonic() < deadline:
            router.poll_admissions()  # raise_on_mismatch default: typed
            time.sleep(0.01)
    th.join(timeout=30)
    assert drift_err, "decode side was not told about the drift"
    assert router.stats["readmit_rejected"] == 1
    assert router.stats["readmissions"] == 0
    assert len(router._ranks) == 0  # NOT silently admitted

    # A correct host afterwards IS admitted.
    ok_box: list = []

    def correct_decode():
        worker = serve.connect_decode(addr, model, params, slots=1,
                                      max_len=40, kv_codec="f32")
        ok_box.append(worker)
        worker.serve(idle_timeout=0.5)
        worker.close()

    th2 = threading.Thread(target=correct_decode, daemon=True)
    th2.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not router.stats["readmissions"]:
        router.poll_admissions()
        time.sleep(0.01)
    th2.join(timeout=60)
    assert router.stats["readmissions"] == 1
    assert len(router._ranks) == 1 and router._ranks[0].alive
    router.close()
    lsock.close()
