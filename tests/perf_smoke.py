"""CI perf-smoke lane (not pytest-collected — run as a script).

A short loopback p2p transfer per engine, asserting syscalls/MiB stays under
a committed budget, plus a compressed-collectives byte check: a bf16-wire
allreduce must post <= 0.55x the f32 lane's wire bytes (counter-based via
tpunet_isend_nbytes — noise-immune where this box's GB/s is not). This is the regression tripwire for the vectored wire
path: a change that re-fragments it — separate syscalls for payload vs CRC
trailer, losing MSG_WAITALL on chunk reads, per-segment instead of
iovec-batched IO on EPOLL — moves syscalls/MiB by integer FACTORS, while
the 1-core CI box's GB/s swings ±20% on its own and can hide any throughput
regression. The counters come from tpunet_engine_syscalls_total{op,dir}
over the timed window (warmup excluded), via benchmarks.engine_p2p.

Budgets (16 MiB messages, nstreams=2, CRC off; PERF_NOTES round 6):
  BASIC: blocking IO — 1 sendmsg + 1 MSG_WAITALL recvmsg per chunk +
         per-message ctrl traffic => measured 0.19/MiB; budget 3.0 leaves
         jitter headroom while still catching any per-refill re-read
         pattern (a real-NIC-style 64 KiB refill cadence is 16/MiB).
  EPOLL: nonblocking IO moves only what's ready per syscall, so the count
         is readiness-dependent; measured 0.42/MiB with iovec batching
         (pre-vectored seed: ~0.5 at 128 MiB, worse at this size, plus a
         trailer syscall per chunk under CRC); budget 6.0.

Usage: python tests/perf_smoke.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.engine_p2p import run_engine  # noqa: E402

SIZE = 16 << 20
BUDGET_SYSCALLS_PER_MIB = {"BASIC": 3.0, "EPOLL": 6.0}

# Codec lane: bf16-wire allreduce must post at most this fraction of the
# f32 lane's wire bytes. The true ratio is 0.500 exactly (every ring hop
# halves); 0.55 leaves room only for the fixed non-payload traffic (ctrl
# frames are not counted in isend_nbytes, so in practice this is tight).
CODEC_SIZE = 8 << 20
CODEC_BUDGET = 0.55

# Dispatch lane: a small-message AllReduce at W=8 under algo=auto must run
# in <= 6 sequential wire rounds (binomial tree / halving-doubling) where
# the ring takes 2*(W-1) = 14 — the counter-verified step budget that
# carries the schedule work's perf claim (tpunet_coll_steps_total{algo}; a
# wire round is a number this box's GB/s noise cannot touch). Ring steps
# must be exactly ZERO over the measured collective.
DISPATCH_WORLD = 8
DISPATCH_SIZE = 4 << 10
DISPATCH_STEP_BUDGET = 6


def _codec_rank(rank, world, port, q, codec):
    try:
        os.environ["TPUNET_WIRE_DTYPE"] = codec
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        arr = np.full(CODEC_SIZE // 4, float(rank + 1), np.float32)
        comm.all_reduce(arr, inplace=True)  # warmup: wiring + scratch faults
        comm.barrier()
        telemetry.reset()
        comm.all_reduce(arr, inplace=True)
        # Posted wire payload over the measured allreduce: the histogram's
        # _sum series parses as its own family in telemetry.metrics().
        wire = int(sum(telemetry.metrics()["tpunet_isend_nbytes_sum"].values()))
        comm.close()
        q.put((rank, ("OK", wire)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", 0)))


def _codec_wire_bytes(codec: str) -> int:
    from benchmarks import check_rank_results, spawn_ranks

    results = check_rank_results(
        spawn_ranks(_codec_rank, 2, extra_args=(codec,), timeout=180))
    return results[0]


def _dispatch_rank(rank, world, port, q):
    try:
        # Single-stream, single-channel comms: W=8 wires a 7-peer mesh per
        # rank and CI's box is small; the step COUNT is invariant to both.
        os.environ["TPUNET_NSTREAMS"] = "1"
        os.environ["TPUNET_ASYNC_CHANNELS"] = "1"
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        arr = np.full(DISPATCH_SIZE // 4, float(rank + 1), np.float32)
        comm.all_reduce(arr)          # warmup: mesh wiring + quiesce
        comm.barrier()
        telemetry.reset()
        out = comm.all_reduce(arr)
        m = telemetry.metrics()
        comm.close()
        assert out[0] == sum(r + 1 for r in range(world))
        steps = {}
        for key, v in m.get("tpunet_coll_steps_total", {}).items():
            steps[telemetry.labels(key)["algo"]] = int(v)
        q.put((rank, ("OK", steps)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", {})))


def _dispatch_smoke(failures) -> None:
    from benchmarks import check_rank_results, spawn_ranks

    results = check_rank_results(
        spawn_ranks(_dispatch_rank, DISPATCH_WORLD, timeout=180))
    worst = 0
    for rank in range(DISPATCH_WORLD):
        steps = results[rank]
        ring = steps.get("ring", 0)
        non_ring = steps.get("rhd", 0) + steps.get("tree", 0)
        worst = max(worst, non_ring)
        if ring != 0:
            failures.append(
                f"dispatch: rank {rank} ran {ring} RING steps on a "
                f"{DISPATCH_SIZE}B allreduce — auto-selector not engaging")
        if not 1 <= non_ring <= DISPATCH_STEP_BUDGET:
            failures.append(
                f"dispatch: rank {rank} took {non_ring} wire steps, budget "
                f"{DISPATCH_STEP_BUDGET} (ring would be "
                f"{2 * (DISPATCH_WORLD - 1)})")
    print(f"[perf_smoke] dispatch: {DISPATCH_SIZE}B allreduce at "
          f"W={DISPATCH_WORLD} under algo=auto: <= {worst} wire steps/rank "
          f"(budget {DISPATCH_STEP_BUDGET}, ring would take "
          f"{2 * (DISPATCH_WORLD - 1)})")


def main() -> None:
    os.environ.setdefault("TPUNET_CRC", "0")
    failures = []
    for engine, budget in BUDGET_SYSCALLS_PER_MIB.items():
        r = run_engine(engine, nstreams=2, sizes=[SIZE], iters=4)
        spm = r[SIZE]["syscalls_per_mib"]
        bps = r[SIZE]["bytes_per_syscall"]
        print(f"[perf_smoke] {engine}: {spm} syscalls/MiB "
              f"({bps} B/syscall, budget {budget})")
        if spm is None or spm > budget:
            failures.append(f"{engine}: {spm} syscalls/MiB exceeds budget {budget}")

    _dispatch_smoke(failures)

    f32_bytes = _codec_wire_bytes("f32")
    bf16_bytes = _codec_wire_bytes("bf16")
    ratio = bf16_bytes / f32_bytes if f32_bytes else float("inf")
    print(f"[perf_smoke] codec: bf16 wire {bf16_bytes}B vs f32 {f32_bytes}B "
          f"-> {ratio:.3f}x (budget {CODEC_BUDGET})")
    if ratio > CODEC_BUDGET:
        failures.append(
            f"bf16 wire bytes {ratio:.3f}x of f32 exceeds {CODEC_BUDGET} — "
            "codec not engaging on the ring?")

    if failures:
        raise SystemExit("perf smoke FAILED — wire path re-fragmented?\n  "
                         + "\n  ".join(failures))
    print("perf_smoke OK")


if __name__ == "__main__":
    main()
