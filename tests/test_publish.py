"""Live weight updates (tpunet/serve/publish, DESIGN.md "Live weight
updates").

Coverage map:
  * Swap chaos grammar — native parser accept/reject, one-shot poll
    latch, pending counter, the Python mirror, and typed rejection of
    malformed specs (same strings on both sides of the ABI).
  * Protocol — SwapAnnounce pack/unpack goldens and typed refusals,
    HELLO weight-version ride-along in the class word's upper bytes.
  * Knobs — TPUNET_SWAP_TIMEOUT_MS / TPUNET_SWAP_CHUNK_BYTES /
    TPUNET_PUBLISH_CLASS registered, defaulted, range-validated.
  * Error path — -10 maps to the typed retryable WeightSwapError;
    receiver deadline/flatten truncation raise it, never hang.
  * Metrics — swap phase histogram, event counters, version gauge:
    observable, labeled, reset()-able.
  * THE PIN: a session admitted under v0 completes BITWISE on v0 while a
    mid-flight publication flips the fleet to v1 and new sessions serve
    v1 — both checked against single-version oracles (v1's oracle uses
    the bf16-ROUNDTRIPPED params: what every rank actually holds after
    the wire). The drained v0 then retires on both tiers.
  * CRC refusal: one receiver corrupting one byte refuses the flip
    FLEET-WIDE (typed, counted), v0 keeps serving bitwise, and the next
    (clean) attempt of the SAME version succeeds — retryability.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from conftest import free_port  # noqa: F401  (pins JAX_PLATFORMS=cpu first)

import jax
import jax.numpy as jnp

from tpunet import _native, serve, telemetry, transport
from tpunet.models import Transformer, generate
from tpunet.serve import protocol as proto
from tpunet.serve import publish

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _tiny_setup(seed=1):
    model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, compute_dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    params = model.init(jax.random.PRNGKey(seed), toks)["params"]
    return model, params


def _oracle(model, params, prompt, n):
    out = generate(model, params, jnp.asarray(prompt)[None], n)
    return np.asarray(out)[0, len(prompt):]


# ---------------------------------------------------------------------------
# Swap chaos grammar: native parser, Python mirror, typed rejection.


def test_swap_script_native_parse_and_poll():
    lib = _native.load()
    _native.check(lib.tpunet_c_fault_inject(
        b"swap:at_step=4:action=publish;swap:at_step=8:action=die"),
        "inject")
    try:
        assert publish.swap_pending() == 2
        assert publish.swap_action(3) is None       # before at_step
        assert publish.swap_action(5) == "publish"  # >= at_step fires
        assert publish.swap_action(5) is None       # one-shot latch
        assert publish.swap_pending() == 1
        assert publish.swap_action(9) == "die"
        assert publish.swap_pending() == 0
    finally:
        transport.fault_clear()
    assert publish.swap_pending() == 0  # clear wipes the script


def test_swap_script_rides_alongside_churn_and_classic_segments():
    lib = _native.load()
    _native.check(lib.tpunet_c_fault_inject(
        b"stream=1:action=close;churn:at_step=2:rank=0:action=kill;"
        b"swap:at_step=3:action=corrupt"), "inject")
    try:
        from tpunet import elastic

        assert publish.swap_pending() == 1
        assert elastic.churn_pending() == 1
        assert publish.swap_action(3) == "corrupt"
        assert elastic.churn_action(2, 0) == "kill"
    finally:
        transport.fault_clear()


@pytest.mark.parametrize("spec", [
    "swap:at_step=1:action=flip",      # unknown action
    "swap:at_step=1",                  # missing action
    "swap:badkey=1:action=publish",    # unknown key
    "swap:at_step=x:action=die",       # bad number
    "swap",                            # bare token
])
def test_swap_script_malformed_typed(spec):
    lib = _native.load()
    assert lib.tpunet_c_fault_inject(spec.encode()) == _native.TPUNET_ERR_INVALID
    assert _native.last_error()


def test_parse_swap_script_python_mirror():
    events = publish.parse_swap_script(
        "churn:at_step=1:rank=0:action=kill;"
        "swap:at_step=5:action=publish;swap:at_step=9:action=die")
    assert events == [{"at_step": 5, "action": "publish"},
                      {"at_step": 9, "action": "die"}]
    for bad in ("swap:at_step=1:action=flip", "swap:at_step=1",
                "swap:badkey=1:action=die", "swap:at_step"):
        with pytest.raises(ValueError):
            publish.parse_swap_script(bad)


# ---------------------------------------------------------------------------
# Protocol: SwapAnnounce, HELLO version ride-along.


def test_swap_announce_roundtrip():
    ann = proto.SwapAnnounce(7, 3, 2, 123457, 1 << 16, "bf16", 30_000,
                             "127.0.0.1:2947", traffic_class="bulk")
    out = proto.unpack_swap_begin(proto.pack_swap_begin(ann))
    assert (out.version, out.world, out.rank, out.nelems, out.chunk_bytes,
            out.codec, out.timeout_ms, out.coordinator, out.traffic_class) \
        == (7, 3, 2, 123457, 1 << 16, "bf16", 30_000, "127.0.0.1:2947",
            "bulk")


def test_swap_announce_typed_refusals():
    ann = proto.SwapAnnounce(1, 2, 1, 10, 4096, "bf16", 1000, "h:1")
    good = proto.pack_swap_begin(ann)
    with pytest.raises(proto.TierProtocolError):
        proto.unpack_swap_begin(good[:8])          # shorter than sub-header
    bad_codec = bytearray(good)
    bad_codec[proto._SWAP_HDR.size - 6] = 99       # codec id byte
    with pytest.raises(proto.TierProtocolError):
        proto.unpack_swap_begin(bytes(bad_codec))
    with pytest.raises(proto.TierProtocolError):
        # rank 0 is the publisher — never a receiver
        proto.unpack_swap_begin(
            proto._SWAP_HDR.pack(1, 2, 0, 10, 4096, 1, 1, 1000) + b"h:1")
    with pytest.raises(proto.TierProtocolError):
        # coordinator must be host:port
        proto.unpack_swap_begin(
            proto._SWAP_HDR.pack(1, 2, 1, 10, 4096, 1, 1, 1000) + b"nohost")
    with pytest.raises(ValueError):
        proto.pack_swap_begin(proto.SwapAnnounce(
            1, 2, 1, 10, 4096, "bf16", 1000, "h:1", traffic_class="warp"))


def test_hello_weight_version_rides_class_word():
    h = proto.Hello(proto.ROLE_DECODE, "int8", 4, 128, 64, 0xBEEF,
                    weight_version=3)
    out = proto.Hello.unpack(h.pack())
    assert out.weight_version == 3 and out.traffic_class == "latency"
    # An old build packs class-only (version 0): never a mismatch, the
    # router reads "needs catch-up".
    legacy = proto.Hello(proto.ROLE_DECODE, "int8", 4, 128, 64, 0xBEEF)
    assert proto.Hello.unpack(legacy.pack()).weight_version == 0
    with pytest.raises(ValueError):
        proto.Hello(proto.ROLE_DECODE, "int8", 4, 128, 64, 0,
                    weight_version=1 << 24)


# ---------------------------------------------------------------------------
# Knobs + typed error + metrics.


def test_swap_knobs_registered_and_validated(monkeypatch):
    from tpunet.config import Config

    cfg = Config.from_env()
    assert cfg.swap_timeout_ms == 30_000
    assert cfg.swap_chunk_bytes == 1 << 20
    assert cfg.publish_class == "bulk"
    monkeypatch.setenv("TPUNET_SWAP_TIMEOUT_MS", "5000")
    monkeypatch.setenv("TPUNET_SWAP_CHUNK_BYTES", "65536")
    monkeypatch.setenv("TPUNET_PUBLISH_CLASS", "control")
    cfg = Config.from_env()
    assert (cfg.swap_timeout_ms, cfg.swap_chunk_bytes, cfg.publish_class) \
        == (5000, 65536, "control")
    for var, bad in (("TPUNET_SWAP_TIMEOUT_MS", "0"),
                     ("TPUNET_SWAP_CHUNK_BYTES", "16"),
                     ("TPUNET_SWAP_CHUNK_BYTES", str(1 << 31)),
                     ("TPUNET_PUBLISH_CLASS", "fast")):
        with monkeypatch.context() as m:
            m.setenv(var, bad)
            with pytest.raises(ValueError, match=var):
                Config.from_env()


def test_weight_swap_error_is_typed_and_mapped():
    assert _native.TPUNET_ERR_WEIGHT_SWAP == -10
    with pytest.raises(publish.WeightSwapError):
        _native.check(_native.TPUNET_ERR_WEIGHT_SWAP, "probe")
    assert issubclass(publish.WeightSwapError, _native.NativeError)


def test_swap_metrics_accessors_and_reset():
    telemetry.reset()
    telemetry.swap_observe("broadcast", 1234)
    telemetry.swap_observe("flip", 77)
    telemetry.swap_event("commit")
    telemetry.weight_version(5)
    m = telemetry.metrics()
    counts = {telemetry.labels(k).get("phase"): v
              for k, v in m["tpunet_weight_swap_duration_us_count"].items()}
    assert counts["broadcast"] == 1 and counts["flip"] == 1
    assert counts["announce"] == 0 and counts["verify"] == 0
    events = {telemetry.labels(k).get("kind"): v
              for k, v in m["tpunet_swap_events_total"].items()}
    assert events["commit"] == 1 and events["abort"] == 0
    assert next(iter(m["tpunet_weight_version"].values())) == 5
    with pytest.raises(ValueError):
        telemetry.swap_observe("warmup", 1)
    with pytest.raises(ValueError):
        telemetry.swap_event("explode")
    telemetry.reset()
    m = telemetry.metrics()
    assert sum(m["tpunet_weight_swap_duration_us_count"].values()) == 0
    assert next(iter(m["tpunet_weight_version"].values())) == 0


# ---------------------------------------------------------------------------
# Receiver/helper failure paths: typed, bounded, never a hang.


def test_receiver_deadline_typed():
    model, params = _tiny_setup()
    ann = proto.SwapAnnounce(1, 2, 1, 64, 4096, "bf16", 1,
                             "127.0.0.1:1")  # 1ms deadline, no publisher
    recv = publish.WeightReceiver(ann, params)
    time.sleep(0.01)
    with pytest.raises(publish.WeightSwapError, match="deadline"):
        recv.pump()
    assert recv.staged is None
    recv.abort()  # idempotent


def test_unflatten_truncation_typed():
    model, params = _tiny_setup()
    flat = publish.flatten_params(params)
    with pytest.raises(publish.WeightSwapError, match="truncated"):
        publish.unflatten_params(params, flat[:-5])
    with pytest.raises(publish.WeightSwapError, match="consumes only"):
        publish.unflatten_params(
            params, np.concatenate([flat, np.zeros(3, np.float32)]))


def test_publish_version_must_increase():
    class _R:
        version = 3
    with pytest.raises(ValueError, match="must increase"):
        publish.WeightPublisher(_R()).publish(3, {})


def test_publish_abandons_wedged_broadcast_thread(monkeypatch):
    """A peer SIGKILLed at the wrong instant can wedge the native
    collective in a state even a force-close cannot error out of. The
    supervisor must then ABANDON the daemon thread past deadline+grace
    and raise typed — one leaked thread, never a wedged serving loop."""

    class _Rank:
        alive = True
        index = 0

    class _Prefill:
        model = None
        max_len = 8

    class _Router:
        version = 0
        _ranks = [_Rank()]
        _swap_status: dict = {}
        prefill = _Prefill()

        def poll(self):
            pass

    params = {"w": np.arange(8, dtype=np.float32)}
    pub = publish.WeightPublisher(_Router(), timeout_ms=150)
    wedge = threading.Event()
    # A broadcast parked beyond the reach of the deadline force-close
    # (cast_box never exposes a comm, so there is nothing to close).
    monkeypatch.setattr(pub, "_broadcast_to",
                        lambda *a, **k: wedge.wait())
    monkeypatch.setattr(publish, "_CAST_ABANDON_GRACE_S", 0.2)
    t0 = time.monotonic()
    with pytest.raises(publish.WeightSwapError, match="abandoned"):
        pub.publish(1, params, retries=0)
    assert time.monotonic() - t0 < 5.0, "abandon did not bound the wait"
    assert pub.stats["aborts"] == 1
    assert pub.phase is None
    wedge.set()  # release the deliberately-leaked daemon thread


# ---------------------------------------------------------------------------
# THE PIN: hot-swap with version-pinned drain, bitwise on both versions.


def _start_tier(model, params, *, slots, max_len=40):
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]
    worker_box = {}

    def decode_main():
        worker = serve.connect_decode(addr, model, params, slots=slots,
                                      max_len=max_len, kv_codec="f32")
        worker_box["worker"] = worker
        try:
            worker.serve()
        finally:
            worker.close()

    th = threading.Thread(target=decode_main, daemon=True)
    th.start()
    prefill = serve.PrefillEngine(model, params, max_len=max_len)
    router = serve.Router(prefill, kv_codec="f32")
    router.accept_ranks(lsock, 1)
    lsock.close()
    return router, worker_box, th


def test_hot_swap_pins_old_sessions_and_serves_new_on_v1():
    model, params0 = _tiny_setup(seed=1)
    _, params1 = _tiny_setup(seed=2)
    rt1 = publish.roundtrip_params(params1, "bf16")

    telemetry.reset()
    router, worker_box, th = _start_tier(model, params0, slots=1)
    try:
        rng = np.random.default_rng(3)
        filler_p = rng.integers(0, 64, 5).astype(np.int32)
        pinned_p = rng.integers(0, 64, 7).astype(np.int32)
        new_p = rng.integers(0, 64, 9).astype(np.int32)

        # Occupy the single slot, then admit a request that must WAIT —
        # it is pinned to v0 at admission and will decode after the flip.
        filler = router.submit(filler_p, 24)
        pinned = router.submit(pinned_p, 6)

        pub = serve.WeightPublisher(router, chunk_bytes=16384)
        pub.publish(1, params1)
        assert router.version == 1
        assert router._ranks[0].versions >= {0, 1}

        new = router.submit(new_p, 6)  # admitted under v1
        assert router._recs[new]["version"] == 1
        assert router._recs[pinned]["version"] == 0
        results = router.run(timeout=240)

        # Bitwise against single-version oracles: v0 requests on the
        # PRISTINE params (they never crossed the weight wire), the v1
        # request on the bf16-ROUNDTRIPPED checkpoint.
        np.testing.assert_array_equal(results[filler],
                                      _oracle(model, params0, filler_p, 24))
        np.testing.assert_array_equal(results[pinned],
                                      _oracle(model, params0, pinned_p, 6))
        np.testing.assert_array_equal(results[new],
                                      _oracle(model, rt1, new_p, 6))

        # Drained v0 retires on BOTH tiers (frontend engine dropped, the
        # decode rank told to drop its old server once locally drained).
        router.poll()
        assert 0 not in router._prefills and router.version == 1
        worker = worker_box["worker"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(worker._servers) != 1:
            router.poll()
            time.sleep(0.05)
        assert set(worker._servers) == {1}
        assert worker.version == 1 and worker.stats["swaps"] == 1

        # Every phase of the swap is observed and bounded.
        m = telemetry.metrics()
        counts = {telemetry.labels(k).get("phase"): v for k, v in
                  m["tpunet_weight_swap_duration_us_count"].items()}
        for phase in ("announce", "broadcast", "verify", "flip"):
            assert counts[phase] >= 1, f"phase {phase} never observed"
        sums = {telemetry.labels(k).get("phase"): v for k, v in
                m["tpunet_weight_swap_duration_us_sum"].items()}
        assert all(v < 30_000_000 for v in sums.values())
        events = {telemetry.labels(k).get("kind"): v for k, v in
                  m["tpunet_swap_events_total"].items()}
        assert events["publish"] >= 1 and events["commit"] >= 2
        assert events["abort"] == 0 and events["mismatch"] == 0
        assert next(iter(m["tpunet_weight_version"].values())) == 1
        assert router.stats["swaps"] == 1
        assert router.stats["rank_failures"] == 0
    finally:
        router.shutdown()
        th.join(timeout=60)
        router.close()


def test_crc_mismatch_refuses_flip_fleet_wide_then_retries_clean():
    model, params0 = _tiny_setup(seed=1)
    _, params1 = _tiny_setup(seed=2)

    telemetry.reset()
    router, worker_box, th = _start_tier(model, params0, slots=2)
    try:
        # Let the decode worker come up, then arm one-byte corruption on
        # the NEXT receiver (the scripted "corrupt" action's direct hook).
        deadline = time.monotonic() + 60
        while "worker" not in worker_box and time.monotonic() < deadline:
            time.sleep(0.01)
        worker = worker_box["worker"]
        worker._corrupt_next = True

        pub = serve.WeightPublisher(router, chunk_bytes=16384)
        with pytest.raises(publish.WeightSwapError, match="CRC32C"):
            pub.publish(1, params1, retries=0)

        # Flip refused FLEET-WIDE: both tiers still on v0, still serving.
        assert router.version == 0 and worker.version == 0
        rng = np.random.default_rng(5)
        p = rng.integers(0, 64, 6).astype(np.int32)
        rid = router.submit(p, 5)
        res = router.run(timeout=240)
        np.testing.assert_array_equal(res[rid],
                                      _oracle(model, params0, p, 5))

        m = telemetry.metrics()
        events = {telemetry.labels(k).get("kind"): v for k, v in
                  m["tpunet_swap_events_total"].items()}
        assert events["mismatch"] >= 1 and events["abort"] >= 1

        # Retryable: the SAME version publishes clean on the next attempt.
        pub.publish(1, params1)
        assert router.version == 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and worker.version != 1:
            router.poll()
            time.sleep(0.05)
        assert worker.version == 1
    finally:
        router.shutdown()
        th.join(timeout=60)
        router.close()
