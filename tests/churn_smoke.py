"""CI churn-smoke lane: scripted kill -> shrink -> join -> grow at W=4
(2x2 fake hosts, SHM on) — docs/DESIGN.md "Elastic churn".

The whole sequence is one chaos-grammar script (the default below, or the
env's TPUNET_FAULT_SPEC with ``--no-default-script``): member 3 SIGKILLs
itself at step 3, the survivors rewire to W=3 mid-run (training keeps
going from the checkpoint), member 4 requests entry once the job
checkpoints step 6, and the world grows back to W=4 without restarting
the job. Gates, by counters (the PR 3/5 epistemic stance):

  * ZERO CRC mismatches: every rank runs the CRC32C cross-rank parameter
    check after EVERY rewire (a WorldCorruptionError fails the lane).
  * tpunet_rewire_duration_us non-empty for EVERY phase (detect, quiesce,
    rendezvous, rewire) on every rewired rank, and no phase's total
    exceeding TPUNET_REWIRE_TIMEOUT_MS.
  * Final world size back at 4 — the live comm AND the tpunet_world_size
    gauge on every rank.
  * The scripted kill actually fired (victim exit code == -SIGKILL) and
    every member's final params are bitwise identical.

Run: python tests/churn_smoke.py   (exit 0 = pass)
"""

import os
import signal
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORLD = 4
STEPS = 14
NPARAMS = 256
DEFAULT_SPEC = ("churn:at_step=3:rank=3:action=kill;"
                "churn:at_step=6:rank=4:action=join")
REWIRE_TIMEOUT_MS = 120_000


def _host_of(member_id: int) -> int:
    # 2x2 fake hosts: members 0,1 on host 0; 2,3 on host 1. The joiner
    # (member 4) replaces the dead host-1 capacity so the grown world is a
    # uniform 2x2 split again.
    return 0 if member_id < 2 else 1


def _latest_step(ckpt: Path) -> int:
    steps = [int(p.stem.split("_")[1]) for p in ckpt.glob("step_*.npy")]
    return max(steps, default=-1)


def _rank(member_id: int, world: int, port: int, q, dirpath: str, spec: str,
          joiner: bool) -> None:
    try:
        os.environ.update({
            "TPUNET_FAULT_SPEC": spec,
            "TPUNET_SHM": "1",
            "TPUNET_HOST_ID": f"churnhost{_host_of(member_id)}",
            "TPUNET_NSTREAMS": "1",
            "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_BOOTSTRAP_TIMEOUT_MS": "30000",
            "TPUNET_CONNECT_RETRY_MS": "2000",
            # RST-independent peer-death bounds (keepalive + watchdog).
            "TPUNET_PROGRESS_TIMEOUT_MS": "10000",
            "TPUNET_KEEPALIVE_IDLE_S": "3",
            "TPUNET_KEEPALIVE_INTVL_S": "2",
            "TPUNET_KEEPALIVE_CNT": "2",
        })
        import numpy as np

        from tpunet import _native, elastic, telemetry

        ckpt = Path(dirpath)

        def grad(step, rank):
            rng = np.random.default_rng(11 * step + rank)
            return rng.standard_normal(NPARAMS).astype(np.float32)

        if joiner:
            _native.load().tpunet_c_fault_inject(spec.encode())
            while True:
                latest = _latest_step(ckpt)
                if latest >= 0 and \
                        elastic.churn_action(latest, member_id) == "join":
                    break
                time.sleep(0.1)

        def train_once(world_obj, comm):
            while True:
                latest = _latest_step(ckpt)
                if latest >= 0:
                    params = np.load(ckpt / f"step_{latest}.npy")
                    start = latest + 1
                else:
                    params = np.zeros(NPARAMS, np.float32)
                    start = 0
                if world_obj.stats["rewires"]:
                    world_obj.crc_check(params)  # the zero-corruption gate
                restart = False
                for step in range(start, STEPS):
                    if world_obj.churn_action(step) == "kill":
                        os.kill(os.getpid(), signal.SIGKILL)
                    new = world_obj.maybe_rewire(step)
                    if new is not None:
                        comm = new
                        restart = True
                        break
                    g = comm.all_reduce(grad(step, comm.rank)) / comm.world_size
                    params = params - 0.1 * g
                    if comm.rank == 0:
                        tmp = ckpt / f".step_{step}.tmp.npy"
                        np.save(tmp, params)
                        os.replace(tmp, ckpt / f"step_{step}.npy")
                    comm.barrier()
                    world_obj.step_ok()
                    if comm.world_size < WORLD:
                        time.sleep(0.25)  # keep the join window real
                if not restart:
                    return params, comm.world_size, dict(world_obj.stats)

        params, final_world, stats = elastic.run(
            train_once, coordinator=f"127.0.0.1:{port}",
            member_id=member_id, world_size=world, directory=dirpath,
            joiner=joiner, grace_ms=4000,
            rewire_timeout_ms=REWIRE_TIMEOUT_MS)
        m = telemetry.metrics()
        phases = {telemetry.labels(k)["phase"]: int(v)
                  for k, v in m["tpunet_rewire_duration_us_count"].items()}
        sums = {telemetry.labels(k)["phase"]: float(v)
                for k, v in m["tpunet_rewire_duration_us_sum"].items()}
        kinds = {telemetry.labels(k)["kind"]: int(v)
                 for k, v in m["tpunet_churn_events_total"].items()}
        gauge = int(next(iter(m["tpunet_world_size"].values())))
        shm_tx = sum(int(v) for k, v in
                     m.get("tpunet_shm_bytes_total", {}).items()
                     if telemetry.labels(k)["dir"] == "tx")
        q.put((member_id, ("OK", {
            "params": params.tolist(), "world": final_world, "gauge": gauge,
            "phases": phases, "sums": sums, "kinds": kinds, "stats": stats,
            "shm_tx": shm_tx,
        })))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((member_id, (f"ERR {type(e).__name__}: {e}",
                           traceback.format_exc()[-800:])))


def main() -> None:
    import multiprocessing as mp
    import queue as queue_mod
    import tempfile

    import numpy as np

    from benchmarks import free_port
    from tpunet import elastic

    spec = DEFAULT_SPEC
    if "--no-default-script" in sys.argv:
        spec = os.environ.get("TPUNET_FAULT_SPEC", "")
        if not spec:
            raise SystemExit("--no-default-script needs TPUNET_FAULT_SPEC set")
    events = elastic.parse_churn_script(spec)
    kills = [e["rank"] for e in events if e["action"] == "kill"]
    joins = [e["rank"] for e in events if e["action"] == "join"]
    if not kills or not joins:
        raise SystemExit(f"churn script needs >= 1 kill and >= 1 join: {spec}")

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    d = tempfile.mkdtemp(prefix="churn_smoke_")
    t0 = time.time()
    procs: dict = {}
    vqs: dict = {}
    for mid in range(WORLD):
        # Victims get a dedicated queue (mp.Queue SIGKILL write-lock hazard).
        qq = ctx.Queue() if mid in kills else q
        if mid in kills:
            vqs[mid] = qq
        procs[mid] = ctx.Process(target=_rank,
                                 args=(mid, WORLD, port, qq, d, spec, False))
    for mid in joins:
        procs[mid] = ctx.Process(target=_rank,
                                 args=(mid, WORLD, port, q, d, spec, True))
    for p in procs.values():
        p.start()

    expected = (set(range(WORLD)) | set(joins)) - set(kills)
    results: dict = {}
    deadline = time.time() + 240
    while len(results) < len(expected) and time.time() < deadline:
        try:
            mid, payload = q.get(timeout=1.0)
            results[mid] = payload
        except queue_mod.Empty:
            pass
    for p in procs.values():
        p.join(timeout=30)
        if p.is_alive():
            p.kill()
            p.join()

    failures: list = []
    for mid in kills:
        if procs[mid].exitcode != -signal.SIGKILL:
            failures.append(f"scripted kill of member {mid} never fired "
                            f"(exit {procs[mid].exitcode})")
    for mid, payload in sorted(results.items()):
        if payload[0] != "OK":
            failures.append(f"member {mid}: {payload[0]}\n{payload[1]}")
    missing = sorted(expected - results.keys())
    if missing:
        failures.append(f"members never reported: {missing}")

    if not failures:
        ref = np.asarray(results[min(expected)][1]["params"], np.float32)
        for mid in sorted(expected):
            r = results[mid][1]
            if not np.array_equal(
                    ref, np.asarray(r["params"], np.float32)):
                failures.append(f"member {mid}: params diverged across churn")
            if r["world"] != WORLD or r["gauge"] != WORLD:
                failures.append(
                    f"member {mid}: world {r['world']} / gauge {r['gauge']} "
                    f"!= {WORLD} — the world never came back")
            empty = [ph for ph in ("detect", "quiesce", "rendezvous", "rewire")
                     if r["phases"].get(ph, 0) < 1]
            if empty:
                failures.append(
                    f"member {mid}: empty tpunet_rewire_duration_us phases "
                    f"{empty} ({r['phases']})")
            over = {ph: v for ph, v in r["sums"].items()
                    if v >= REWIRE_TIMEOUT_MS * 1e3}
            if over:
                failures.append(
                    f"member {mid}: rewire phases exceeded "
                    f"TPUNET_REWIRE_TIMEOUT_MS: {over}")
            if r["stats"]["crc_checks"] < r["stats"]["rewires"]:
                failures.append(
                    f"member {mid}: {r['stats']['rewires']} rewires but only "
                    f"{r['stats']['crc_checks']} CRC checks — the "
                    f"zero-corruption gate did not run after every rewire")
            if r["shm_tx"] <= 0:
                failures.append(
                    f"member {mid}: SHM moved no bytes — the lane did not "
                    f"exercise churn over the SHM transport")

    dt = time.time() - t0
    if failures:
        print(f"churn_smoke FAILURES ({dt:.1f}s):")
        for f in failures:
            print(f"  - {f}")
        sys.exit(1)
    r0 = results[min(expected)][1]
    print(f"churn_smoke: OK in {dt:.1f}s — kill->shrink->join->grow at "
          f"W={WORLD} (2x2 fake hosts, SHM on): world back at {WORLD}, "
          f"{r0['stats']['rewires']} rewires/rank with all 4 phases timed, "
          f"{r0['stats']['crc_checks']} CRC cross-rank checks, 0 mismatches; "
          f"events {r0['kinds']}")


if __name__ == "__main__":
    main()
