"""Structured fuzzing of every Python parser that touches untrusted bytes.

The C++ parsers get libFuzzer/ASan (cpp/fuzz/, `make -C cpp fuzz-smoke`);
the Python side gets this hand-rolled equivalent — the image ships neither
hypothesis nor atheris, and a deterministic seeded mutator reproduces any
failure from its case index alone, which a coverage-guided fuzzer cannot
promise.

Contract under test: a parser handed arbitrary bytes either succeeds or
raises its TYPED error (ServeError subclasses for the serve frames,
ValueError for the script grammars and the postmortem loader). Anything
else — struct.error, a numpy ValueError, TypeError, IndexError — is a
crash an adversarial peer or a torn dump file can trigger at will. This
suite found three of those (now fixed, and pinned by the regression tests
at the bottom): oversized counts in unpack_block/unpack_result reached
np.frombuffer, a short Hello hit struct.error, and non-numeric dump fields
crashed diagnose() deep in the stall arithmetic.
"""

from __future__ import annotations

import json
import random
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpunet.elastic import parse_churn_script  # noqa: E402
from tpunet.serve import protocol as proto  # noqa: E402
from tpunet.serve.publish import parse_swap_script  # noqa: E402
from tools.postmortem import diagnose, load_dumps, phase_lattice  # noqa: E402

CASES = 400  # per target; the full file stays under a few seconds


def _mutate(rng: random.Random, base: bytes) -> bytes:
    """One structured mutation of a valid wire image: truncate, extend,
    byte-flip, zero a span, or splice random garbage — the shapes framing
    bugs actually take."""
    b = bytearray(base)
    op = rng.randrange(6)
    if op == 0 and b:
        del b[rng.randrange(len(b)):]                      # truncate tail
    elif op == 1:
        b += rng.randbytes(rng.randrange(1, 64))           # trailing junk
    elif op == 2 and b:
        for _ in range(rng.randrange(1, 8)):
            b[rng.randrange(len(b))] = rng.randrange(256)  # byte flips
    elif op == 3 and b:
        i = rng.randrange(len(b))
        j = min(len(b), i + rng.randrange(1, 16))
        b[i:j] = bytes(j - i)                              # zeroed span
    elif op == 4 and b:
        i = rng.randrange(len(b))
        b[i:i] = rng.randbytes(rng.randrange(1, 16))       # inserted garbage
    else:
        b = bytearray(rng.randbytes(rng.randrange(0, 96)))  # pure noise
    return bytes(b)


def _drive(parse, valid: bytes, allowed: tuple, seed: int) -> None:
    rng = random.Random(seed)
    parse(valid)  # the unmutated image must parse
    for i in range(CASES):
        payload = _mutate(rng, valid)
        try:
            parse(payload)
        except allowed:
            pass
        except Exception as e:  # noqa: BLE001 — the point of the test
            pytest.fail(
                f"case {i} (seed {seed}): {type(e).__name__}: {e} on "
                f"{payload[:64].hex()}... ({len(payload)}B) — untyped "
                f"escape from {parse.__name__}")


# ---------------------------------------------------------------------------
# Serve frames: ServeError (TierProtocolError / TierMismatchError) only.


def test_fuzz_hello_unpack():
    valid = proto.Hello(proto.ROLE_FRONTEND, "bf16", 8, 2048, 50304,
                        0x1234_5678_9ABC).pack()
    _drive(proto.Hello.unpack, valid, (proto.ServeError,), seed=0xE110)


def test_fuzz_unpack_block():
    valid = proto.pack_block(
        np.arange(7, dtype=np.int32), 16,
        np.arange(24, dtype=np.uint8), 6,
        np.linspace(-1, 1, 11).astype(np.float32), "f32")

    def parse(payload: bytes):
        return proto.unpack_block(payload, "f32")

    parse.__name__ = "unpack_block"
    _drive(parse, valid, (proto.ServeError,), seed=0xB10C)


def test_fuzz_unpack_result():
    valid = proto.pack_result(np.arange(9, dtype=np.int32), 0, 1234)
    _drive(proto.unpack_result, valid, (proto.ServeError,), seed=0x5E5)


def test_fuzz_unpack_swap_begin():
    valid = proto.pack_swap_begin(proto.SwapAnnounce(
        3, 4, 2, 1 << 20, 1 << 16, "bf16", 30_000, "10.0.0.1:7777"))
    _drive(proto.unpack_swap_begin, valid, (proto.ServeError,), seed=0x54A9)


# ---------------------------------------------------------------------------
# Script grammars: ValueError only.

_SCRIPT_TOKENS = ("churn", "swap", "at_step", "rank", "action", "kill",
                  "join", "publish", "corrupt", "die", "stream", "=", ":",
                  ";", "*", "0", "17", "-3", "9" * 30, "", " ", "\n", "\x00",
                  "actiön", "=:;")


def _random_script(rng: random.Random) -> str:
    return "".join(rng.choice(_SCRIPT_TOKENS) for _ in range(rng.randrange(0, 24)))


@pytest.mark.parametrize("parse", [parse_churn_script, parse_swap_script],
                         ids=["churn", "swap"])
def test_fuzz_script_grammars(parse):
    rng = random.Random(0x5C81)
    parse("churn:at_step=3:rank=1:action=kill;swap:at_step=5:action=publish")
    for i in range(CASES):
        spec = _random_script(rng)
        try:
            parse(spec)
        except ValueError:
            pass
        except Exception as e:  # noqa: BLE001
            pytest.fail(f"case {i}: {type(e).__name__}: {e} on {spec!r}")


# ---------------------------------------------------------------------------
# Postmortem loader: torn/hostile dump files -> ValueError naming the file,
# and whatever load_dumps accepts must flow through the whole analysis
# (phase_lattice + diagnose) without an exception.

_JUNK = (None, "x", "7f3a", -1, 0.5, [], {}, True, "phase_enter", 10**18)


def _valid_dump(rank: int) -> dict:
    ev = [{"t": 100 * i, "kind": k, "a": 7, "b": 41, "c": 4096, "d": i,
           "name": "rs"}
          for i, k in enumerate(("phase_enter", "phase_exit", "phase_enter"))]
    ev.append({"t": 500, "kind": "verdict", "name": "watchdog"})
    return {"schema": "tpunet-flightrec-v1", "rank": rank, "host": "00",
            "reason": "watchdog", "capacity": 64, "recorded": len(ev),
            "dropped": 0, "events": ev, "torn": 0}


def _mutate_json(rng: random.Random, d: dict) -> dict:
    d = json.loads(json.dumps(d))  # deep copy
    for _ in range(rng.randrange(1, 4)):
        op = rng.randrange(4)
        if op == 0:  # swap a top-level field for junk
            d[rng.choice(list(d))] = rng.choice(_JUNK)
        elif op == 1 and isinstance(d.get("events"), list) and d["events"]:
            ev = rng.choice(d["events"])
            if isinstance(ev, dict) and ev:
                ev[rng.choice(list(ev))] = rng.choice(_JUNK)
        elif op == 2 and isinstance(d.get("events"), list):
            d["events"].append(rng.choice(_JUNK))
        else:
            d.pop(rng.choice(list(d)), None)
    return d


def test_fuzz_postmortem_loader(tmp_path):
    rng = random.Random(0xD04D)
    for i in range(120):
        case = tmp_path / f"case{i}"
        case.mkdir()
        for rank in (0, 1):
            d = _valid_dump(rank)
            if rng.random() < 0.9:
                d = _mutate_json(rng, d)
            path = case / f"tpunet-flightrec-rank{rank}.json"
            raw = json.dumps(d)
            if rng.random() < 0.1:
                raw = raw[:rng.randrange(len(raw))]  # torn write
            path.write_text(raw)
        try:
            dumps = load_dumps([str(case)])
        except ValueError:
            continue  # typed rejection naming the file — the contract
        except Exception as e:  # noqa: BLE001
            pytest.fail(f"case {i}: load_dumps untyped {type(e).__name__}: {e}")
        try:
            diag = diagnose(dumps)
            assert isinstance(diag["lines"], list)
            phase_lattice(dumps)
        except Exception as e:  # noqa: BLE001
            pytest.fail(f"case {i}: accepted dump crashed analysis: "
                        f"{type(e).__name__}: {e}")


# ---------------------------------------------------------------------------
# Regressions: the concrete crashes this suite surfaced, pinned as typed.


def test_oversized_block_counts_are_typed():
    payload = proto._BLOCK_HDR.pack(10**6, 1, 0, 0, proto._CODEC_IDS["f32"])
    with pytest.raises(proto.TierProtocolError, match="prompt"):
        proto.unpack_block(payload, "f32")


def test_oversized_result_count_is_typed():
    payload = proto._RESULT_HDR.pack(10**6, 0, 0)
    with pytest.raises(proto.TierProtocolError, match="tokens"):
        proto.unpack_result(payload)


def test_short_hello_is_typed():
    with pytest.raises(proto.TierProtocolError, match="hello"):
        proto.Hello.unpack(b"\x00" * 5)


def test_postmortem_rejects_non_numeric_fields(tmp_path):
    d = _valid_dump(0)
    d["events"][0]["t"] = "not-a-time"
    f = tmp_path / "tpunet-flightrec-rank0.json"
    f.write_text(json.dumps(d))
    with pytest.raises(ValueError, match="rank0"):
        load_dumps([str(tmp_path)])


def test_postmortem_rejects_string_rank(tmp_path):
    d = _valid_dump(0)
    d["rank"] = "zero"
    f = tmp_path / "tpunet-flightrec-rank0.json"
    f.write_text(json.dumps(d))
    with pytest.raises(ValueError, match="rank"):
        load_dumps([str(tmp_path)])
