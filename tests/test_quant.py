"""Weight-only int8 quantization: round-trip bounds, model closeness,
decode/speculative composition, validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.models import (Transformer, dequantize_kernel, generate,
                           quantize_params, speculative_generate)
from tpunet.models.quant import quantize_kernel


def _tiny(**kw):
    kw.setdefault("vocab", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    return Transformer(**kw)


def _params(model, b=2, s=24, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(0), (b, s), 0, model.vocab)
    return model.init(jax.random.PRNGKey(seed), toks)["params"], toks


def test_kernel_roundtrip_bound():
    """Reconstruction error is bounded by half a quantization step per
    element — scale/2 per output channel."""
    w = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (64, 48)))
    qd = quantize_kernel(w)
    assert qd["q"].dtype == jnp.int8 and qd["scale"].shape == (48,)
    err = np.abs(np.asarray(dequantize_kernel(qd)) - w)
    assert (err <= np.asarray(qd["scale"])[None, :] / 2 + 1e-7).all()
    # Symmetric absmax: 127 is reached, -128 never is.
    assert int(np.asarray(qd["q"]).max()) == 127
    assert int(np.asarray(qd["q"]).min()) >= -127


def test_quantize_params_touches_only_dense_kernels():
    model = _tiny(n_kv_heads=2, mlp_impl="swiglu")
    params, _ = _params(model)
    qp = quantize_params(params)
    # embed + RMSNorm scales untouched, bit for bit.
    np.testing.assert_array_equal(np.asarray(qp["embed"]),
                                  np.asarray(params["embed"]))
    np.testing.assert_array_equal(
        np.asarray(qp["norm_f"]["scale"]), np.asarray(params["norm_f"]["scale"]))
    # Every Dense kernel became {q, scale}.
    attn = qp["block0"]["attn"]
    for name in ("q", "k", "v", "out"):
        assert set(attn[name]) == {"q", "scale"}
        assert attn[name]["q"].dtype == jnp.int8
    assert set(qp["block0"]["mlp"]["gate"]) == {"q", "scale"}
    assert set(qp["lm_head"]) == {"q", "scale"}


def test_quant_model_logits_close():
    """int8 weight-only logits track the fp model: tight relative error
    and near-total argmax agreement on random inputs."""
    model = _tiny()
    params, toks = _params(model)
    qmodel = model.clone(weight_quant="int8")
    qp = quantize_params(params)
    fp = model.apply({"params": params}, toks)
    qn = qmodel.apply({"params": qp}, toks)
    rel = np.abs(np.asarray(qn) - np.asarray(fp)).max() / (
        np.abs(np.asarray(fp)).max() + 1e-9)
    assert rel < 0.05, f"relative logit error {rel}"
    agree = (np.asarray(jnp.argmax(fp, -1)) ==
             np.asarray(jnp.argmax(qn, -1))).mean()
    assert agree > 0.9, f"argmax agreement {agree}"


def test_quant_decode_matches_quant_full_forward():
    """The quantized model's cached decode path reproduces its own full
    forward position-for-position — quantization composes with the cache
    machinery, not just the dense path."""
    model = _tiny(n_kv_heads=2)
    params, toks = _params(model)
    qmodel = model.clone(weight_quant="int8")
    qp = quantize_params(params)
    want = generate(qmodel, qp, toks, 8)
    # Re-run through chunked prefill: same machinery, same output.
    got = generate(qmodel, qp, toks, 8, prefill_chunk=7)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_quant_draft_keeps_target_distribution_exact():
    """The realistic cheap draft: the TARGET model, quantized. Speculative
    output with the int8 draft is bitwise the fp target's greedy output —
    quantization error moves only the acceptance rate."""
    model = _tiny()
    params, prompt = _params(model)
    qdraft = model.clone(weight_quant="int8")
    qp = quantize_params(params)
    want = generate(model, params, prompt, 12)
    got, stats = speculative_generate(
        model, params, qdraft, qp, prompt, 12, gamma=3, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # And it is a GOOD draft: near-fp logits -> high greedy agreement.
    assert float(stats["draft_accept_rate"]) > 0.6


def test_quant_validation():
    model = _tiny(weight_quant="fp4")
    with pytest.raises(ValueError, match="weight_quant"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    moe = _tiny(n_experts=2, weight_quant="int8")
    with pytest.raises(ValueError, match="MoE"):
        moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    fo = _tiny(weight_quant="int8")
    params, toks = _params(_tiny())
    with pytest.raises(ValueError, match="features_only"):
        fo.apply({"params": quantize_params(params)}, toks,
                 features_only=True)


def test_quant_tp_sharded_matches_single_replica():
    """int8 inference composes with Megatron TP: the partition rules map
    q like its kernel and the per-column scale with the output dim, so a
    dp x mdl sharded quantized generate() reproduces the single-replica
    quantized run. The per-column scale distributes over the row-parallel
    psum, so the only divergence is all-reduce float reassociation —
    asserted tie-tolerantly like the fp TP test."""
    from functools import partial

    from tpunet.models import generate, transformer_partition_rules
    from tpunet.parallel import batch_sharding, make_named_mesh, shard_params

    model = _tiny(n_kv_heads=2, weight_quant="int8")
    fp_model = _tiny(n_kv_heads=2)
    params, _ = _params(fp_model, b=4, s=12)
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (4, 12)), jnp.int32)
    qp = quantize_params(params)
    expected = generate(model, qp, toks, 6)

    mesh = make_named_mesh({"dp": 2, "mdl": 2})
    rules = transformer_partition_rules(tp_axis="mdl")
    shardings = shard_params(qp, mesh, rules)
    # The rules must actually shard the quant leaves (not fall through to
    # replicated): q of a column-parallel Dense splits its output dim.
    qkv_spec = shardings["block0"]["attn"]["q"]["q"].spec
    assert qkv_spec == jax.sharding.PartitionSpec(None, "mdl")
    scale_spec = shardings["block0"]["attn"]["q"]["scale"].spec
    assert scale_spec == jax.sharding.PartitionSpec("mdl")
    qp_sh = jax.device_put(qp, shardings)
    toks_sh = jax.device_put(toks, batch_sharding(mesh))
    with mesh:
        got = jax.jit(partial(generate, model, max_new_tokens=6))(
            qp_sh, toks_sh)
    assert got.shape == expected.shape
    np.testing.assert_array_equal(np.asarray(got[:, :12]), np.asarray(toks))
    for i in range(6):
        logits = model.apply({"params": qp}, got[:, : 12 + i])[:, -1, :]
        chosen = np.take_along_axis(
            np.asarray(logits), np.asarray(got[:, 12 + i])[:, None], axis=1
        )[:, 0]
        np.testing.assert_allclose(
            chosen, np.max(np.asarray(logits), axis=1), atol=1e-3)
