"""CI MoE-smoke lane: skewed dispatch vs a bulk tenant + exact hier-A2A bytes.

Two phases, both counter-gated (the PR 3/5 epistemic stance — nothing rides
wall-clock):

  1. TWO-TENANT QOS (W=2, flat, TPUNET_QOS_INFLIGHT_BYTES wire armed): each
     rank runs a LATENCY-class communicator carrying Zipf-skewed MoE
     dispatch/combine typed AllToAlls (tpunet.workloads.moe) against a
     concurrent BULK-class AllReduce tenant. Gates: the latency-class p99
     wire-credit queue wait stays inside the 100 ms bucket
     (tpunet_qos_queue_wait_us) while the bulk tenant completes its FULL
     AllReduce quota and its byte counters carry the full budget
     (tpunet_qos_bytes_total) — the DRR scheduler arbitrating a REAL
     competing workload, ISSUE 11's acceptance shape.

  2. EXACT HIER-A2A DCN BYTES (W=4 as 2x2 TPUNET_HOST_ID fake hosts,
     TPUNET_A2A_ALGO=hier): one dispatch-shaped f32 typed AllToAll must
     move EXACTLY the inter-stage-only figure per rank — intra (R-1)*H*B,
     inter R*(H-1)*B, flat 0 — via tpunet_a2a_bytes_total, with a2a.intra/
     a2a.inter round counts R-1 / H-1 in tpunet_coll_steps_total.

Run: python tests/moe_smoke.py   (exit 0 = pass)
"""

import multiprocessing as mp
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

P99_BUDGET_US = 100_000
STEPS = 8
BULK_MIN_ITERS = 4
BULK_BYTES = 4 << 20


def _p99_us(metrics, cls):
    from tpunet import telemetry

    buckets = []
    for key, value in metrics.get("tpunet_qos_queue_wait_us_bucket", {}).items():
        lab = telemetry.labels(key)
        if lab.get("class") != cls:
            continue
        le = lab["le"]
        buckets.append((float("inf") if le == "+Inf" else float(le), int(value)))
    buckets.sort()
    if not buckets or buckets[-1][1] == 0:
        return None
    total = buckets[-1][1]
    for bound, cum in buckets:
        if cum >= 0.99 * total:
            return bound
    return float("inf")


def _tenant_rank(rank, world, ports, q):
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_QOS_INFLIGHT_BYTES": "wire=256K",
            "TPUNET_QOS_WEIGHTS": "latency=8,bulk=1",
            "TPUNET_MOE_SKEW": "1.5",
        })
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator
        from tpunet.workloads import moe

        lat = Communicator(f"127.0.0.1:{ports[0]}", rank, world,
                           traffic_class="latency")
        blk = Communicator(f"127.0.0.1:{ports[1]}", rank, world,
                           traffic_class="bulk")
        rng = np.random.default_rng(17 + rank)
        disp = moe.MoeDispatcher(lat, d_model=64, capacity=256)
        grad = np.full(BULK_BYTES // 4, 0.25, np.float32)
        # Warmup wires meshes + channels on both comms, then reset counters.
        disp.dispatch(rng.standard_normal((8, 64)).astype(np.float32),
                      moe.route_tokens(8, world, rng=rng))
        disp.combine(np.zeros((world, 256, 64), np.float32))
        blk.all_reduce(np.ones(1024, np.float32))
        lat.barrier()
        telemetry.reset()

        stop = threading.Event()
        bulk_iters = [0]

        def bulk_loop():
            while not stop.is_set() or bulk_iters[0] < BULK_MIN_ITERS:
                blk.all_reduce(grad, inplace=True)
                bulk_iters[0] += 1

        bt = threading.Thread(target=bulk_loop, daemon=True)
        bt.start()
        for _ in range(STEPS):
            toks = rng.standard_normal((256, 64)).astype(np.float32)
            experts = moe.route_tokens(256, world, rng=rng)  # env skew
            expert_toks, _ = disp.dispatch(toks, experts)
            disp.combine(expert_toks)
        stop.set()
        bt.join(timeout=180)
        assert not bt.is_alive(), "bulk tenant wedged under contention"
        m = telemetry.metrics()
        by_class = {}
        for key, v in m.get("tpunet_qos_bytes_total", {}).items():
            lab = telemetry.labels(key)
            by_class[(lab["class"], lab["dir"])] = int(v)
        q.put((rank, {"ok": True,
                      "p99_lat": _p99_us(m, "latency"),
                      "bulk_iters": bulk_iters[0],
                      "bulk_tx": by_class.get(("bulk", "tx"), 0),
                      "lat_tx": by_class.get(("latency", "tx"), 0)}))
        lat.close()
        blk.close()
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, {"ok": False, "error": f"{type(e).__name__}: {e}",
                      "trace": traceback.format_exc()}))


def _hier_rank(rank, world, port, q):
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_SHM": "1", "TPUNET_A2A_ALGO": "hier",
            "TPUNET_HOST_ID": f"smokehost{rank // 2}",
        })
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        n = 16_384  # dispatch-shaped block: 64 KiB per (src, dst) pair
        send = np.stack([np.full(n, float(rank * world + j), np.float32)
                         for j in range(world)])
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            comm.barrier()
            telemetry.reset()
            got = comm.all_to_all_typed(send)
            m = telemetry.metrics()
        for j in range(world):
            assert got[j][0] == float(j * world + rank), (j, got[j][0])
        a2a = {}
        for key, v in m.get("tpunet_a2a_bytes_total", {}).items():
            lab = telemetry.labels(key)
            a2a[(lab["stage"], lab["dir"])] = int(v)
        steps = {telemetry.labels(k)["algo"]: int(v)
                 for k, v in m.get("tpunet_coll_steps_total", {}).items()}
        q.put((rank, {"ok": True, "a2a": a2a, "steps": steps, "B": n * 4}))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, {"ok": False, "error": f"{type(e).__name__}: {e}",
                      "trace": traceback.format_exc()}))


def _spawn(target, world, ports):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=target, args=(r, world, ports, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, res = q.get(timeout=300)
            results[rank] = res
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.kill()
    for r, v in sorted(results.items()):
        assert v.get("ok"), f"rank {r}: {v.get('error')}\n{v.get('trace', '')}"
    assert len(results) == world
    return results


def main() -> None:
    from conftest import free_port

    # Phase 1: two-tenant QoS arbitration, W=2 flat.
    world = 2
    res = _spawn(_tenant_rank, world, (free_port(), free_port()))
    for r, v in res.items():
        assert v["p99_lat"] is not None, f"rank {r}: latency class never gated"
        assert v["p99_lat"] <= P99_BUDGET_US, \
            f"rank {r}: dispatch p99 queue wait {v['p99_lat']}us over budget"
        assert v["bulk_iters"] >= BULK_MIN_ITERS, \
            f"rank {r}: bulk tenant starved ({v['bulk_iters']} iters)"
        # Full budget by counters: each AllReduce moves 2*(W-1)/W * S tx.
        expect = BULK_MIN_ITERS * BULK_BYTES * 2 * (world - 1) // world
        assert v["bulk_tx"] >= expect, \
            f"rank {r}: bulk moved {v['bulk_tx']}B < budget {expect}B"
        assert v["lat_tx"] > 0, f"rank {r}: dispatch moved no latency bytes"

    # Phase 2: exact inter-stage-only DCN bytes on the 2x2 split.
    world, hosts = 4, 2
    R, H = world // hosts, hosts
    res2 = _spawn(_hier_rank, world, free_port())
    for r, v in res2.items():
        B = v["B"]
        assert v["a2a"][("intra", "tx")] == (R - 1) * H * B, (r, v["a2a"])
        assert v["a2a"][("inter", "tx")] == R * (H - 1) * B, (r, v["a2a"])
        assert v["a2a"][("flat", "tx")] == 0, (r, v["a2a"])
        assert v["steps"].get("a2a.intra") == R - 1, v["steps"]
        assert v["steps"].get("a2a.inter") == H - 1, v["steps"]

    print(f"moe smoke OK: dispatch p99 queue wait <= "
          f"{max(v['p99_lat'] for v in res.values()):.0f}us with bulk at full "
          f"budget; hier-A2A DCN bytes exactly inter-stage-only "
          f"({res2[0]['a2a'][('inter', 'tx')]}B/rank on the 2x2 split)")


if __name__ == "__main__":
    main()
