"""GPipe pipeline-parallel schedule: numerics vs sequential execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.parallel import gpipe, make_named_mesh, stack_stage_params


D, FF = 16, 32


def _stage_fn(params, x):
    # Residual MLP block: (mb, d) -> (mb, d).
    h = jax.nn.gelu(x @ params["w1"])
    return x + h @ params["w2"]


def _stage_params(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (D, FF)) * 0.1,
        "w2": jax.random.normal(k2, (FF, D)) * 0.1,
    }


def _sequential(stacked, x):
    w = jax.tree.leaves(stacked)[0].shape[0]
    for s in range(w):
        x = _stage_fn(jax.tree.map(lambda a: a[s], stacked), x)
    return x


@pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (2, 4), (8, 8)])
def test_gpipe_matches_sequential(pp, microbatches):
    mesh = make_named_mesh({"pp": pp})
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(s)) for s in range(pp)]
    )
    x = jax.random.normal(jax.random.PRNGKey(99), (16, D))
    got = gpipe(_stage_fn, stacked, x, mesh, num_microbatches=microbatches)
    want = _sequential(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("remat", [False, True])
def test_gpipe_grad_matches_sequential(remat):
    # remat_stages changes what the backward SAVES, never what it computes:
    # gradients must match the sequential reference either way.
    pp = 4
    mesh = make_named_mesh({"pp": pp})
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(s)) for s in range(pp)]
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))

    def loss_pipe(p):
        return jnp.sum(
            gpipe(_stage_fn, p, x, mesh, num_microbatches=4,
                  remat_stages=remat) ** 2
        )

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        ),
        gp, gs,
    )


def test_gpipe_under_jit_with_dp():
    # pp x dp mesh: pipeline along pp while the batch is data-parallel.
    mesh = make_named_mesh({"pp": 4, "dp": 2})
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(s)) for s in range(4)]
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (8, D))
    f = jax.jit(lambda p, x: gpipe(_stage_fn, p, x, mesh, num_microbatches=4))
    np.testing.assert_allclose(
        np.asarray(f(stacked, x)), np.asarray(_sequential(stacked, x)),
        atol=1e-5, rtol=1e-5,
    )


def test_gpipe_validates_shapes():
    mesh = make_named_mesh({"pp": 4})
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(s)) for s in range(3)]  # wrong W
    )
    x = jnp.zeros((8, D))
    with pytest.raises(ValueError, match="pp axis size"):
        gpipe(_stage_fn, stacked, x, mesh, num_microbatches=4)
    ok = stack_stage_params([_stage_params(jax.random.PRNGKey(s)) for s in range(4)])
    with pytest.raises(ValueError, match="not divisible"):
        gpipe(_stage_fn, ok, x, mesh, num_microbatches=3)


def test_gpipe_dp_axis_shards_microbatch_rows():
    # Batch rows inside each microbatch sharded over dp; numerics must match
    # the replicated path and the sequential reference exactly.
    mesh = make_named_mesh({"pp": 4, "dp": 2})
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(s)) for s in range(4)]
    )
    x = jax.random.normal(jax.random.PRNGKey(42), (8, D))
    got = gpipe(_stage_fn, stacked, x, mesh, num_microbatches=4, dp_axis="dp")
    want = _sequential(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    # Gradients through the dp-sharded pipeline match sequential too (the
    # dp psum on the param transpose is inserted by shard_map autodiff).
    def loss_pipe(p):
        return jnp.sum(gpipe(_stage_fn, p, x, mesh, num_microbatches=4, dp_axis="dp") ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        gp, gs,
    )
