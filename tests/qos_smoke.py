"""CI QoS-smoke lane: two-tenant interleaved loopback under the DRR gate.

One process, two tenants sharing the process-wide QoS scheduler with a
256 KiB wire window and 8:1 latency:bulk weights: a bulk tenant flooding
1 MiB messages and a latency tenant interleaving 16 KiB pings. Gates, by
counters (the PR 3/5 epistemic stance — no loopback GB/s anywhere):

  * BOTH classes' byte counters are nonzero, tx AND rx — the rx side
    proves the receiver adopted the sender's preamble class nibble;
  * bulk moved its whole byte budget (every flood message completed —
    the DRR gate throttles ordering, never drops or starves);
  * the latency-class p99 wire-credit queue wait stays inside its budget
    (<= 100 ms bucket) while the bulk flood saturates the window;
  * the wire window ends fully drained (no leaked credit).

A second phase re-runs the bulk flood alone (same byte budget, no gate
contention) so the lane also pins that the gated bulk tenant moved the
same bytes as the solo baseline — budget parity by counters, which a
shared CI runner cannot noise out the way it noises throughput.

Run: python tests/qos_smoke.py   (exit 0 = pass)
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["TPUNET_QOS_INFLIGHT_BYTES"] = "wire=256K"
os.environ["TPUNET_QOS_WEIGHTS"] = "latency=8,bulk=1"
os.environ["TPUNET_MIN_CHUNKSIZE"] = str(128 << 10)

import numpy as np  # noqa: E402

N_BULK = 12
N_LAT = 48
BULK_BYTES = 1 << 20
LAT_BYTES = 16 << 10
P99_BUDGET_US = 100_000


def _class_series(metrics, family):
    from tpunet import telemetry

    out = {}
    for key, value in metrics.get(family, {}).items():
        lab = telemetry.labels(key)
        out[(lab.get("class"), lab.get("dir"))] = int(value)
    return out


def _p99_us(metrics, cls):
    from tpunet import telemetry

    buckets = []
    for key, value in metrics.get("tpunet_qos_queue_wait_us_bucket", {}).items():
        lab = telemetry.labels(key)
        if lab.get("class") != cls:
            continue
        le = lab["le"]
        buckets.append((float("inf") if le == "+Inf" else float(le), int(value)))
    buckets.sort()
    if not buckets or buckets[-1][1] == 0:
        return None
    total = buckets[-1][1]
    for bound, cum in buckets:
        if cum >= 0.99 * total:
            return bound
    return float("inf")


def _wire_pair(net):
    lc = net.listen()
    sc = net.connect(lc.handle)
    rc = lc.accept()
    return lc, sc, rc


def _flood(sc, rc, payload, n, timeout=180):
    errs = []

    def rx():
        buf = np.empty_like(payload)
        try:
            for _ in range(n):
                rc.irecv(buf).wait(timeout=timeout)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=rx, daemon=True)
    t.start()
    for _ in range(n):
        sc.isend(payload).wait(timeout=timeout)
    t.join(timeout=timeout)
    assert not t.is_alive() and not errs, (t.is_alive(), errs)


def main() -> None:
    from tpunet import telemetry
    from tpunet import transport as tp

    net_lat = tp.Net(traffic_class="latency")
    net_bulk = tp.Net(traffic_class="bulk")
    lat_comms = _wire_pair(net_lat)
    bulk_comms = _wire_pair(net_bulk)
    bulk_msg = np.full(BULK_BYTES, 3, np.uint8)
    lat_msg = np.full(LAT_BYTES, 9, np.uint8)

    # Phase 1: bulk alone (the no-contention baseline, counter-based).
    telemetry.reset()
    _flood(bulk_comms[1], bulk_comms[2], bulk_msg, N_BULK)
    base = _class_series(telemetry.metrics(), "tpunet_qos_bytes_total")
    assert base[("bulk", "tx")] >= N_BULK * BULK_BYTES, base

    # Phase 2: the two-tenant interleave.
    telemetry.reset()
    flood = threading.Thread(
        target=_flood, args=(bulk_comms[1], bulk_comms[2], bulk_msg, N_BULK),
        daemon=True)
    flood.start()
    _flood(lat_comms[1], lat_comms[2], lat_msg, N_LAT)
    flood.join(timeout=180)
    assert not flood.is_alive(), "bulk flood wedged under contention"

    m = telemetry.metrics()
    by = _class_series(m, "tpunet_qos_bytes_total")
    # Both classes moved bytes, both directions (rx = preamble class nibble).
    assert by[("latency", "tx")] >= N_LAT * LAT_BYTES, by
    assert by[("latency", "rx")] >= N_LAT * LAT_BYTES, by
    # Bulk moved its WHOLE budget under contention — same bytes as the solo
    # baseline phase: the gate reorders, it never starves or drops.
    assert by[("bulk", "tx")] >= N_BULK * BULK_BYTES, by
    assert by[("bulk", "rx")] >= N_BULK * BULK_BYTES, by
    assert by[("bulk", "tx")] >= base[("bulk", "tx")], (by, base)

    p99 = _p99_us(m, "latency")
    assert p99 is not None, "latency queue-wait histogram is empty"
    assert p99 <= P99_BUDGET_US, f"latency-class p99 queue wait {p99}us"
    assert _p99_us(m, "bulk") is not None, "bulk chunks were never gated"

    assert tp.qos_state()["wire_inflight"] == 0, "leaked wire credit"

    for c in lat_comms[::-1] + bulk_comms[::-1]:
        c.close()
    net_lat.close()
    net_bulk.close()
    print(f"qos smoke OK: latency p99 wait <= {p99:.0f}us, "
          f"latency {by[('latency', 'tx')]}B / bulk {by[('bulk', 'tx')]}B tx, "
          f"window drained")


if __name__ == "__main__":
    main()
