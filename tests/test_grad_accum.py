"""Gradient accumulation: k microbatches through a lax.scan must produce the
full-batch trajectory (equal microbatches make mean-of-means exact) while
keeping only one microbatch's activations live."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tpunet.models import Transformer
from tpunet.train import create_train_state, make_train_step


def _setup(vocab=41, batch=4):
    model = Transformer(vocab=vocab, d_model=16, n_layers=2, n_heads=2,
                        d_ff=32, compute_dtype=jnp.float32)
    tx = optax.sgd(0.05)  # linear in grads: accumulation parity is exact
    toks = jax.random.randint(jax.random.PRNGKey(3), (batch, 8), 0, vocab)
    labels = jnp.roll(toks, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
    return model, tx, state, toks, labels


@pytest.mark.parametrize("accum", [2, 4])
def test_accumulation_matches_full_batch(accum):
    model, tx, state, toks, labels = _setup()
    step1 = make_train_step(model, tx, donate=False)
    stepk = make_train_step(model, tx, donate=False, accum_steps=accum)

    s1, l1 = step1(state, toks, labels, jax.random.PRNGKey(9))
    sk, lk = stepk(state, toks, labels, jax.random.PRNGKey(9))
    np.testing.assert_allclose(float(l1), float(lk), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-7
        ),
        s1.params, sk.params,
    )


def test_accumulation_rejects_indivisible_batch():
    model, tx, state, toks, labels = _setup(batch=4)
    stepk = make_train_step(model, tx, donate=False, accum_steps=3)
    with pytest.raises(ValueError, match="divisible"):
        stepk(state, toks, labels, jax.random.PRNGKey(0))


def test_accumulation_moe_trains_finite():
    # MoE + accumulation is NOT bitwise full-batch equivalent (routing and
    # capacity are per-microbatch — documented); pin that it trains sanely.
    model = Transformer(vocab=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                        n_experts=4, compute_dtype=jnp.float32)
    tx = optax.sgd(0.05)
    toks = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0, 29)
    labels = jnp.roll(toks, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
    stepk = make_train_step(model, tx, donate=False, accum_steps=2)
    for s in range(2):
        state, loss = stepk(state, toks, labels, jax.random.PRNGKey(s))
        assert np.isfinite(float(loss))


def test_out_of_range_labels_match_optax():
    from tpunet.ops import blockwise_cross_entropy

    rng = np.random.default_rng(7)
    feats = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((8, 7)), jnp.float32)
    labels = jnp.asarray([-1, 0, 6, 7], jnp.int32)
    logits = jnp.dot(feats, kernel)
    want = np.asarray(optax.softmax_cross_entropy_with_integer_labels(logits, labels))
    got = np.asarray(blockwise_cross_entropy(feats, kernel, labels, block_vocab=4))
    # -1 wraps to 6, 7 is NaN — identical semantics.
    np.testing.assert_allclose(got[:3], want[:3], rtol=1e-6, atol=1e-6)
    assert np.isnan(got[3]) and np.isnan(want[3])


def test_accumulation_composes_with_fused_xent():
    model, tx, state, toks, labels = _setup()
    step1 = make_train_step(model, tx, donate=False)
    stepk = make_train_step(model, tx, donate=False, accum_steps=2,
                            fused_xent_block=16)
    s1, l1 = step1(state, toks, labels, jax.random.PRNGKey(1))
    sk, lk = stepk(state, toks, labels, jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(l1), float(lk), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s1.params, sk.params,
    )
