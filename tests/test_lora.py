"""LoRA adapters: init identity, frozen-base training, merge, QLoRA."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.models import (Transformer, generate, graft_base, lora_mask,
                           lora_optimizer, merge_lora, quantize_params)


def _tiny(**kw):
    kw.setdefault("vocab", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    return Transformer(**kw)


def _base(**kw):
    model = _tiny(**kw)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, model.vocab)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    return model, params, toks


def test_grafted_adapter_is_identity_at_init():
    """B = 0 at init, so the grafted adapted model is bitwise the base."""
    base_model, base_params, toks = _base()
    lmodel = base_model.clone(lora_rank=4)
    linit = lmodel.init(jax.random.PRNGKey(2), toks)["params"]
    lparams = graft_base(linit, base_params)
    want = base_model.apply({"params": base_params}, toks)
    got = lmodel.apply({"params": lparams}, toks)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # The adapter params exist where they should.
    attn_q = lparams["block0"]["attn"]["q"]
    assert set(attn_q) == {"base", "lora_a", "lora_b"}
    assert attn_q["lora_b"].shape == (4, 32)
    assert (np.asarray(attn_q["lora_b"]) == 0).all()


def test_masked_training_moves_only_adapters():
    """lora_optimizer (tx on adapters, set_to_zero elsewhere — NOT bare
    optax.masked, which would pass raw gradients through to the "frozen"
    base): loss drops while every base leaf (and embed/norms) stays
    bitwise frozen."""
    base_model, base_params, toks = _base()
    lmodel = base_model.clone(lora_rank=4)
    linit = lmodel.init(jax.random.PRNGKey(2), toks)["params"]
    params = graft_base(linit, base_params)
    mask = lora_mask(params)
    assert mask["block0"]["attn"]["q"]["lora_a"] is True
    assert mask["block0"]["attn"]["q"]["base"]["kernel"] is False
    assert mask["embed"] is False

    tx = lora_optimizer(optax.adam(5e-3), params)
    opt_state = tx.init(params)
    labels = jnp.roll(toks, -1, axis=1)

    def loss_fn(p):
        logits = lmodel.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        first = float(loss) if first is None else first
    assert float(loss) < first  # adapters learned something
    np.testing.assert_array_equal(
        np.asarray(params["block0"]["attn"]["q"]["base"]["kernel"]),
        np.asarray(base_params["block0"]["attn"]["q"]["kernel"]))
    np.testing.assert_array_equal(np.asarray(params["embed"]),
                                  np.asarray(base_params["embed"]))
    assert not (np.asarray(params["block0"]["attn"]["q"]["lora_b"])
                == 0).all()


def test_merge_lora_folds_exactly():
    """merge_lora produces a PLAIN tree whose outputs match the adapted
    model (fp math: A@B·scale folded into the kernel)."""
    base_model, base_params, toks = _base()
    lmodel = base_model.clone(lora_rank=4)
    linit = lmodel.init(jax.random.PRNGKey(2), toks)["params"]
    params = graft_base(linit, base_params)
    # Give the adapters nonzero content so the merge is non-trivial.
    params = jax.tree.map(lambda leaf, m: leaf + 0.01 if m else leaf,
                          params, lora_mask(params))
    merged = merge_lora(params)
    want = lmodel.apply({"params": params}, toks)
    got = base_model.apply({"params": merged}, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-5)
    # And generation with the adapted model works end to end.
    out = generate(lmodel, params, toks[:, :8], 4)
    assert out.shape == (2, 12)


def test_qlora_int8_base_fp_adapters():
    """weight_quant + lora_rank: int8 frozen base with fp adapters —
    grafts from quantize_params, is near the quant base at init (B = 0,
    exact), and merge is refused (int8 can't absorb the delta)."""
    base_model, base_params, toks = _base()
    qmodel = base_model.clone(weight_quant="int8", lora_rank=4)
    qinit = qmodel.init(jax.random.PRNGKey(2), toks)["params"]
    qparams = graft_base(qinit, quantize_params(base_params))
    node = qparams["block0"]["attn"]["q"]
    assert set(node) == {"base", "lora_a", "lora_b"}
    assert set(node["base"]) == {"q", "scale"}
    want = base_model.clone(weight_quant="int8").apply(
        {"params": quantize_params(base_params)}, toks)
    got = qmodel.apply({"params": qparams}, toks)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    with pytest.raises(ValueError, match="fp base"):
        merge_lora(qparams)


def test_lora_tp_rules_shard_the_adapted_tree():
    """transformer_partition_rules must reach through the 'base' nesting
    and shard the adapters by the Megatron LoRA convention (A replicated /
    B output-sharded for column-parallel; transposed for row-parallel) —
    and a dp x mdl sharded forward matches the single-replica one."""
    from tpunet.models import transformer_partition_rules
    from tpunet.parallel import make_named_mesh, shard_params

    base_model, base_params, toks = _base(n_kv_heads=2)
    lmodel = base_model.clone(lora_rank=4)
    linit = lmodel.init(jax.random.PRNGKey(2), toks)["params"]
    params = graft_base(linit, base_params)
    params = jax.tree.map(lambda leaf, m: leaf + 0.01 if m else leaf,
                          params, lora_mask(params))

    mesh = make_named_mesh({"dp": 2, "mdl": 2})
    rules = transformer_partition_rules(tp_axis="mdl")
    sh = shard_params(params, mesh, rules)
    P = jax.sharding.PartitionSpec
    attn_q = sh["block0"]["attn"]["q"]
    assert attn_q["base"]["kernel"].spec == P(None, "mdl")
    assert attn_q["lora_a"].spec == P()
    assert attn_q["lora_b"].spec == P(None, "mdl")
    out = sh["block0"]["attn"]["out"]
    assert out["base"]["kernel"].spec == P("mdl", None)
    assert out["lora_a"].spec == P("mdl", None)
    assert out["lora_b"].spec == P()

    expected = lmodel.apply({"params": params}, toks)
    params_sh = jax.device_put(params, sh)
    with mesh:
        got = jax.jit(lambda p, t: lmodel.apply({"params": p}, t))(
            params_sh, jnp.asarray(toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-4, rtol=1e-4)


def test_lora_features_only_guard():
    lmodel = _tiny(lora_rank=4)
    _, params, toks = _base()
    with pytest.raises(ValueError, match="lora_rank"):
        lmodel.apply({"params": params}, toks, features_only=True)


def test_lora_with_fit_and_checkpoint(tmp_path):
    """The PEFT workflow through the framework's own driver: graft a base,
    fit() with lora_optimizer (checkpoint cadence on the ADAPTED tree),
    resume exactly, and the base stays frozen through it all."""
    from tpunet.train import TrainState, fit, make_train_step

    base_model, base_params, toks = _base()
    lmodel = base_model.clone(lora_rank=4)
    linit = lmodel.init(jax.random.PRNGKey(2), toks)["params"]
    params = graft_base(linit, base_params)
    # make_train_step donates the state, and graft_base shares leaves with
    # base_params - snapshot the frozen reference to host BEFORE fitting.
    base_q_kernel = np.asarray(base_params["block0"]["attn"]["q"]["kernel"])
    tx = lora_optimizer(optax.adam(5e-3), params)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=tx.init(params))
    step = make_train_step(lmodel, tx)

    labels = jnp.roll(toks, -1, axis=1)

    def batches():
        while True:
            yield toks, labels

    ckpt = str(tmp_path / "ckpt")
    state = fit(state, step, batches(), steps=12, checkpoint_dir=ckpt,
                checkpoint_every=6)
    np.testing.assert_array_equal(
        np.asarray(state.params["block0"]["attn"]["q"]["base"]["kernel"]),
        base_q_kernel)
    trained_b = np.asarray(state.params["block0"]["attn"]["q"]["lora_b"])
    assert not (trained_b == 0).all()

    # Resume from the checkpoint into a fresh state skeleton (a NEW init:
    # the first fit donated the old leaves): the adapted (nested) tree
    # round-trips through orbax and training continues.
    skel = lmodel.init(jax.random.PRNGKey(3), toks)["params"]
    fresh = TrainState(step=jnp.zeros((), jnp.int32), params=skel,
                       opt_state=tx.init(skel))
    resumed = fit(fresh, step, batches(), steps=12, checkpoint_dir=ckpt)
    np.testing.assert_array_equal(
        np.asarray(resumed.params["block0"]["attn"]["q"]["lora_b"]),
        trained_b)


def test_qlora_training_step_with_float0():
    """The QLoRA gradient/apply path: allow_int gives float0 grads for the
    int8 base; lora_apply_updates leaves those leaves alone while the
    adapters move (plain optax.apply_updates would crash on float0)."""
    from tpunet.models import lora_apply_updates

    base_model, base_params, toks = _base()
    qlmodel = base_model.clone(weight_quant="int8", lora_rank=4)
    qinit = qlmodel.init(jax.random.PRNGKey(2), toks)["params"]
    params = graft_base(qinit, quantize_params(base_params))
    base_q = np.asarray(params["block0"]["attn"]["q"]["base"]["q"])
    tx = lora_optimizer(optax.adam(1e-2), params)
    opt_state = tx.init(params)
    labels = jnp.roll(toks, -1, axis=1)

    def loss_fn(p):
        logits = qlmodel.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    for _ in range(5):
        loss, g = jax.value_and_grad(loss_fn, allow_int=True)(params)
        updates, opt_state = tx.update(g, opt_state, params)
        params = lora_apply_updates(params, updates)
    node = params["block0"]["attn"]["q"]
    np.testing.assert_array_equal(np.asarray(node["base"]["q"]), base_q)
    assert node["base"]["q"].dtype == jnp.int8
    assert not (np.asarray(node["lora_b"]) == 0).all()


def test_qlora_trains_through_fit():
    """QLoRA through the standard driver: make_train_step differentiates a
    tree containing frozen int8 leaves (allow_int -> float0) and applies
    updates without touching them; fit() runs it. Covers both the single
    backward and the accum_steps scan."""
    from tpunet.train import TrainState, fit, make_train_step

    base_model, base_params, toks = _base()
    qlmodel = base_model.clone(weight_quant="int8", lora_rank=4)
    qinit = qlmodel.init(jax.random.PRNGKey(2), toks)["params"]
    qbase = quantize_params(base_params)
    params = graft_base(qinit, qbase)
    base_q = np.asarray(params["block0"]["attn"]["q"]["base"]["q"])
    tx = lora_optimizer(optax.adam(1e-2), params)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=tx.init(params))
    labels = jnp.roll(toks, -1, axis=1)

    def batches():
        while True:
            yield toks, labels

    for accum in (None, 2):
        step = make_train_step(qlmodel, tx, accum_steps=accum)
        state = fit(state, step, batches(), steps=int(state.step) + 4)
        node = state.params["block0"]["attn"]["q"]
        np.testing.assert_array_equal(np.asarray(node["base"]["q"]), base_q)
        assert node["base"]["q"].dtype == jnp.int8
        assert not (np.asarray(node["lora_b"]) == 0).all()


def _qlora_cross_host_worker(rank: int, world: int, port: int, q) -> None:
    # QLoRA + cross_host (ADVICE r4 #2): gradients contain float0 leaves
    # (frozen int8 base under allow_int) which the DCN tier must pass
    # through — both the single-vector ravel path and the bucketed path
    # used to crash at trace time on ravel/concatenate of float0.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np
        import optax

        from tpunet import distributed
        from tpunet.models import (Transformer, graft_base, lora_optimizer,
                                   quantize_params)
        from tpunet.train import TrainState, make_train_step

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        base_model = Transformer(vocab=32, d_model=16, n_layers=1, n_heads=2,
                                 d_ff=32, compute_dtype=jnp.float32)
        toks = jax.random.randint(jax.random.PRNGKey(10 + rank), (2, 8), 0, 32)
        labels = jnp.roll(toks, -1, axis=1)
        base_params = base_model.init(jax.random.PRNGKey(0), toks)["params"]
        qmodel = base_model.clone(weight_quant="int8", lora_rank=4)
        qinit = qmodel.init(jax.random.PRNGKey(2), toks)["params"]
        params = graft_base(qinit, quantize_params(base_params))
        frozen_q = np.asarray(params["block0"]["attn"]["q"]["base"]["q"])
        tx = lora_optimizer(optax.adam(5e-3), params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=tx.init(params))
        for bucket_bytes in (None, 1 << 10):
            step = make_train_step(qmodel, tx, cross_host=True, donate=False,
                                   bucket_bytes=bucket_bytes)
            s = state
            losses = []
            for i in range(3):
                s, loss = step(s, toks, labels, jax.random.PRNGKey(i))
                losses.append(float(loss))
            assert all(np.isfinite(l) for l in losses), (bucket_bytes, losses)
            assert losses[-1] < losses[0], (bucket_bytes, losses)
            # Frozen int8 base must be bit-identical after training.
            np.testing.assert_array_equal(
                np.asarray(s.params["block0"]["attn"]["q"]["base"]["q"]),
                frozen_q)
            # Adapters must be identical across ranks (coupled by the
            # reduced gradient).
            from jax.flatten_util import ravel_pytree

            from tpunet.interop import dcn_all_gather

            flat = ravel_pytree(
                [s.params["block0"]["attn"]["q"]["lora_a"],
                 s.params["block0"]["attn"]["q"]["lora_b"]])[0]
            gathered = np.asarray(jax.jit(dcn_all_gather)(flat))
            for r in range(1, world):
                np.testing.assert_array_equal(gathered[0], gathered[r])
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_qlora_cross_host_training_2proc():
    from conftest import run_spawn_workers

    run_spawn_workers(_qlora_cross_host_worker, 2)
