"""Chaos suite: deterministic fault injection, CRC32C wire integrity,
single-stream failover, and the collective progress watchdog.

The old fault-path tests SIGKILL real subprocesses mid-64MiB-allreduce
(tests/test_fault_paths.py) — worst-case wall clock, and no way to target a
SPECIFIC stream or byte offset. Here faults are armed through the native
fault-injection API (``tpunet.transport.fault_inject``), so each failure
mode is exercised surgically:

  * parser + CRC golden vectors: pure ctypes, no sockets (tier-1 fast);
  * transport-level failover / corruption / watchdog: two engines over
    loopback in THIS process, seconds each;
  * the chaos matrix: every injectable action on each data stream, under a
    real 2-rank allreduce — each case must end in a correct result
    (failover) or a typed error within a bounded wait. Never a hang, never
    a silent wrong answer.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from tpunet import _native, transport

# ---------------------------------------------------------------------------
# Fault-spec parser (no sockets).


def test_fault_spec_parser_accepts_valid_specs():
    for spec in (
        "stream=1:after_bytes=1M:action=close",
        "stream=*:side=recv:action=stall",
        "action=delay=50:after_bytes=256K",
        "action=corrupt",
        "side=send:stream=0:after_bytes=4096:action=close",
    ):
        transport.fault_inject(spec)
    transport.fault_clear()


@pytest.mark.parametrize(
    "spec, token",
    [
        ("nonsense", "nonsense"),
        ("stream=1", "action"),  # missing action clause
        ("action=explode", "explode"),
        ("action=delay", "delay"),  # delay without =<ms>
        ("stream=bogus:action=close", "bogus"),
        ("after_bytes=1X:action=close", "1X"),
        ("side=up:action=close", "up"),
        ("flavor=spicy:action=close", "flavor"),
    ],
)
def test_fault_spec_parser_rejects_malformed(spec, token):
    with pytest.raises(_native.NativeError) as ei:
        transport.fault_inject(spec)
    assert ei.value.code == _native.TPUNET_ERR_INVALID
    assert token in str(ei.value)
    transport.fault_clear()


# ---------------------------------------------------------------------------
# CRC32C golden vectors (no sockets).


def _crc32c_ref(data: bytes, crc: int = 0) -> int:
    """Bit-at-a-time reference (reflected poly 0x82F63B78) to cross-check the
    native table/hardware implementations on arbitrary inputs."""
    crc ^= 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


def test_crc32c_golden_vectors():
    # RFC 3720 B.4.
    assert transport.crc32c(b"123456789") == 0xE3069283
    assert transport.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert transport.crc32c(b"\xff" * 32) == 0x62A8AB43
    assert transport.crc32c(b"") == 0


def test_crc32c_matches_reference_and_chains():
    rng = np.random.default_rng(7)
    for n in (1, 7, 63, 1024):
        data = rng.integers(0, 256, n, np.uint8).tobytes()
        assert transport.crc32c(data) == _crc32c_ref(data)
    whole = b"tpunet chunk integrity"
    split = transport.crc32c(whole[7:], seed=transport.crc32c(whole[:7]))
    assert split == transport.crc32c(whole)


# ---------------------------------------------------------------------------
# Config validation (satellite: bad env values fail loudly, naming the var).


@pytest.mark.parametrize(
    "var, value, ok",
    [
        ("TPUNET_NSTREAMS", "0", False),
        ("TPUNET_NSTREAMS", "-3", False),
        ("TPUNET_NSTREAMS", "4", True),
        ("BAGUA_NET_NSTREAMS", "0", False),
        ("TPUNET_MIN_CHUNKSIZE", "-1", False),
        ("TPUNET_MIN_CHUNKSIZE", "0", False),
        ("TPUNET_MIN_CHUNKSIZE", "65536", True),
        ("TPUNET_KEEPALIVE_IDLE_S", "-5", False),
        ("TPUNET_KEEPALIVE_INTVL_S", "-1", False),
        ("TPUNET_KEEPALIVE_CNT", "-2", False),
        ("TPUNET_CONNECT_RETRY_MS", "-100", False),
        ("TPUNET_PROGRESS_TIMEOUT_MS", "-1", False),
        ("TPUNET_PROGRESS_TIMEOUT_MS", "5000", True),
        ("TPUNET_METRICS_PORT", "-1", False),
        ("TPUNET_METRICS_PORT", "65536", False),
        ("TPUNET_METRICS_PORT", "70000", False),
        ("TPUNET_METRICS_PORT", "0", True),
        ("TPUNET_METRICS_PORT", "9108", True),
        ("TPUNET_METRICS_PORT", "65535", True),
        ("TPUNET_REDUCE_THREADS", "-1", False),
        ("TPUNET_REDUCE_THREADS", "0", True),
        ("TPUNET_REDUCE_THREADS", "8", True),
    ],
)
def test_config_from_env_validates_ranges(monkeypatch, var, value, ok):
    from tpunet.config import Config

    monkeypatch.setenv(var, value)
    if ok:
        Config.from_env()
    else:
        with pytest.raises(ValueError, match=var):
            Config.from_env()


def test_config_nonnumeric_still_falls_back(monkeypatch):
    # Garbage stays fallback (native GetEnvU64 semantics) — only NUMERIC
    # out-of-range values are config errors.
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_NSTREAMS", "lots")
    assert Config.from_env().nstreams == 2


def test_config_carries_failure_model_knobs(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_CRC", "1")
    monkeypatch.setenv("TPUNET_PROGRESS_TIMEOUT_MS", "1234")
    monkeypatch.setenv("TPUNET_FAULT_SPEC", "stream=0:action=close")
    cfg = Config.from_env()
    assert cfg.crc is True
    assert cfg.progress_timeout_ms == 1234
    assert cfg.fault_spec == "stream=0:action=close"


# ---------------------------------------------------------------------------
# Transport-level chaos over loopback (two engines in THIS process).


def _wire_pair(net_s, net_r):
    lc = net_r.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = net_s.connect(lc.handle)
    th.join()
    return sc, got["rc"], lc


def test_single_stream_failover_keeps_transfer_intact(monkeypatch):
    """Kill data stream 1 mid-transfer: the message completes byte-exact via
    the ctrl-stream retransmit, the comm survives at reduced width, and the
    failover counter moves."""
    from tpunet import telemetry
    from tpunet.transport import Net

    before = telemetry.metrics().get("tpunet_stream_failovers_total", {})
    before_n = sum(before.values())
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            transport.fault_inject("stream=1:side=send:after_bytes=2M:action=close")
            for round_ in range(3):  # round 1 arms the byte counter, round 2 trips it
                src = np.frombuffer(
                    bytes((i * 31 + round_) & 0xFF for i in range(1 << 20)) * 8,
                    np.uint8,
                ).copy()
                dst = np.zeros_like(src)
                rreq = rc.irecv(dst)
                sreq = sc.isend(src)
                sreq.wait(timeout=60)
                got = rreq.wait(timeout=60)
                assert got == src.nbytes
                np.testing.assert_array_equal(src, dst)
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                c.close()
    after = telemetry.metrics().get("tpunet_stream_failovers_total", {})
    assert sum(after.values()) > before_n


def test_crc_detects_injected_corruption_without_disconnect(monkeypatch):
    """TPUNET_CRC=1: a flipped wire byte fails the REQUEST with a typed
    CorruptionError; the comm is not poisoned and the next message flows."""
    monkeypatch.setenv("TPUNET_CRC", "1")
    from tpunet.transport import Net

    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            # Clean CRC-verified roundtrip first.
            src = np.arange(1 << 20, dtype=np.uint8)
            dst = np.zeros_like(src)
            rreq = rc.irecv(dst)
            sc.isend(src).wait(timeout=60)
            assert rreq.wait(timeout=60) == src.nbytes
            np.testing.assert_array_equal(src, dst)

            transport.fault_inject("side=send:action=corrupt")
            dst2 = np.zeros_like(src)
            rreq = rc.irecv(dst2)
            sc.isend(src).wait(timeout=60)
            with pytest.raises(_native.CorruptionError, match="CRC32C"):
                rreq.wait(timeout=60)
            transport.fault_clear()

            # Not a disconnect: same comm, next message verifies clean.
            dst3 = np.zeros_like(src)
            rreq = rc.irecv(dst3)
            sc.isend(src).wait(timeout=60)
            assert rreq.wait(timeout=60) == src.nbytes
            np.testing.assert_array_equal(src, dst3)
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                c.close()


def test_progress_watchdog_times_out_typed(monkeypatch):
    """A recv with a silent peer raises ProgressTimeoutError within ~2x the
    configured window — the live-but-stuck-peer contract."""
    monkeypatch.setenv("TPUNET_PROGRESS_TIMEOUT_MS", "500")
    from tpunet.transport import Net

    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        dst = np.zeros(1 << 20, np.uint8)
        rreq = rc.irecv(dst)
        t0 = time.perf_counter()
        with pytest.raises(_native.ProgressTimeoutError, match="watchdog"):
            rreq.wait()  # native blocking wait; the watchdog bounds it
        assert time.perf_counter() - t0 < 10
        for c in (sc, rc, lc):
            try:
                c.close()
            except _native.NativeError:
                pass  # comm already aborted by the watchdog


# ---------------------------------------------------------------------------
# Chaos matrix: every action on each data stream under a 2-rank allreduce.
# Contract per case: a correct result (failover) or a typed error within a
# bounded wait — never a hang, never a silent wrong answer; with
# TPUNET_CRC=1 injected corruption is ALWAYS detected.


def _matrix_worker(rank: int, world: int, port: int, q, action: str, stream: int,
                   codec: str = "f32", algo: str = "auto") -> None:
    try:
        os.environ["TPUNET_PROGRESS_TIMEOUT_MS"] = "2500"
        os.environ["TPUNET_CRC"] = "1"
        os.environ["TPUNET_WIRE_DTYPE"] = codec
        os.environ["TPUNET_ALGO"] = algo
        from tpunet import _native as nat
        from tpunet import transport as tp
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        warm = comm.all_reduce(np.ones(4, np.float32))
        assert warm[0] == world
        comm.barrier()
        if rank == 1:
            act = "delay=30" if action == "delay" else action
            tp.fault_inject(f"stream={stream}:after_bytes=256K:action={act}")
        arr = np.full(1 << 20, float(rank + 1), np.float32)  # 4 MiB
        t0 = time.perf_counter()
        try:
            out = comm.all_reduce(arr)
            dt = time.perf_counter() - t0
            # int8-wire quantizes (1/254 of the block amax per hop); f32 and
            # bf16 represent 1.0 + 2.0 = 3.0 exactly.
            tol = 0.05 if codec == "int8" else 0.0
            correct = bool(np.all(np.abs(out - 3.0) <= tol))
            q.put((rank, f"OK correct={correct} dt={dt:.1f}"))
        except nat.NativeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"TYPED code={e.code} dt={dt:.1f}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))
    finally:
        try:
            from tpunet import transport as tp

            tp.fault_clear()
        except Exception:  # noqa: BLE001
            pass


@pytest.mark.parametrize("stream", [0, 1])
@pytest.mark.parametrize("action", ["close", "stall", "corrupt", "delay"])
def test_chaos_matrix_never_hangs_never_lies(action, stream):
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=_matrix_worker, args=(r, 2, port, q, action, stream))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == 2, f"missing rank report: {results}"
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        # A completed allreduce must be CORRECT — zero silent wrong answers.
        assert "correct=False" not in status, f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    statuses = " | ".join(results.values())
    if action == "delay":
        # Pure latency: both ranks succeed with correct results.
        assert all(s.startswith("OK correct=True") for s in results.values()), statuses
    if action == "stall":
        # Live-but-stuck: nobody succeeds silently; the watchdog's typed
        # timeout (code -5) shows up on at least one rank.
        assert all(s.startswith("TYPED") for s in results.values()), statuses
        assert f"code={_native.TPUNET_ERR_TIMEOUT}" in statuses, statuses
    if action == "corrupt":
        # CRC on: the corruption is always DETECTED — some rank reports the
        # typed corruption code; nobody reduces damaged data into a result.
        assert f"code={_native.TPUNET_ERR_CORRUPT}" in statuses, statuses


@pytest.mark.parametrize("algo", ["rhd", "tree"])
@pytest.mark.parametrize("action", ["close", "stall", "corrupt"])
def test_chaos_matrix_schedules(action, algo):
    """The failure-containment contract is schedule-independent: the rhd and
    tree AllReduce paths ride the SAME transport (per-chunk CRC32C, stream
    failover, progress watchdog), so every injected fault must still end in
    a correct result or a typed error within the bounded wait — the chaos
    coverage is no longer ring-only."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=_matrix_worker,
                    args=(r, 2, port, q, action, 0, "f32", algo))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == 2, f"missing rank report: {results}"
    statuses = " | ".join(results.values())
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        assert "correct=False" not in status, f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    if action == "stall":
        assert f"code={_native.TPUNET_ERR_TIMEOUT}" in statuses, statuses
    if action == "corrupt":
        assert f"code={_native.TPUNET_ERR_CORRUPT}" in statuses, statuses


@pytest.mark.parametrize("codec", ["bf16", "int8"])
@pytest.mark.parametrize("action", ["corrupt", "close"])
def test_chaos_crc_codec_matrix(action, codec):
    """TPUNET_CRC=1 x wire codec: the per-chunk CRC32C trailer protects the
    ENCODED frames too — a flipped wire byte on a compressed allreduce is
    always detected (typed corruption, never a silently wrong decode), and
    stream loss still fails over / errors out within the bounded wait."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=_matrix_worker, args=(r, 2, port, q, action, 0, codec))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, status = q.get(timeout=150)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == 2, f"missing rank report: {results}"
    statuses = " | ".join(results.values())
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        assert "correct=False" not in status, f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    if action == "corrupt":
        assert f"code={_native.TPUNET_ERR_CORRUPT}" in statuses, statuses


# ---------------------------------------------------------------------------
# Chaos matrix x lanes: lane death under the weighted stripe scheduler.


@pytest.mark.parametrize("action", ["close", "stall"])
def test_chaos_lane_death_fails_over_and_restripes(monkeypatch, action):
    """Weighted lane mode under lane death (docs/DESIGN.md "Lanes &
    adaptive striping"): killing the HEAVY lane mid-transfer must ride the
    PR 1 ctrl-retransmit failover and re-stripe every subsequent message
    onto the survivor, bit-correct under CRC; a stalled lane must surface
    the typed watchdog verdict within a bounded wait. Never a hang, never a
    silent wrong answer — same contract as the uniform chaos matrix."""
    from tpunet import telemetry
    from tpunet.transport import Net

    monkeypatch.setenv("TPUNET_LANES", "w=3,w=1")
    monkeypatch.setenv("TPUNET_LANE_ADAPT", "0")
    monkeypatch.setenv("TPUNET_MIN_CHUNKSIZE", str(64 << 10))
    monkeypatch.setenv("TPUNET_CRC", "1")
    monkeypatch.setenv("TPUNET_IMPLEMENT", "BASIC")
    if action == "stall":
        monkeypatch.setenv("TPUNET_PROGRESS_TIMEOUT_MS", "500")
    telemetry.reset()
    before_fo = sum(telemetry.metrics().get(
        "tpunet_stream_failovers_total", {}).values())
    with Net() as ns, Net() as nr:
        lc = nr.listen()
        got = {}
        th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
        th.start()
        sc = ns.connect(lc.handle)
        th.join()
        rc = got["rc"]
        try:
            # Target the heavy lane (stream 0, weight 3). The stall must
            # fire INSIDE the single probe message (its second chunk), so
            # its byte threshold sits below one chunk.
            after = "1M" if action == "close" else "256K"
            transport.fault_inject(
                f"stream=0:side=send:after_bytes={after}:action={action}")
            src = np.frombuffer(
                bytes((i * 13 + 7) & 0xFF for i in range(1 << 20)), np.uint8
            ).copy()
            if action == "close":
                for round_ in range(6):
                    dst = np.zeros_like(src)
                    rreq = rc.irecv(dst)
                    sc.isend(src).wait(timeout=60)
                    assert rreq.wait(timeout=60) == src.nbytes
                    np.testing.assert_array_equal(src, dst)
                after_fo = sum(telemetry.metrics().get(
                    "tpunet_stream_failovers_total", {}).values())
                assert after_fo > before_fo, "lane death never failed over"
                # Survivor-only striping: the retired lane moves no new bytes.
                lanes_before = {}
                for labels, value in telemetry.metrics().get(
                        "tpunet_lane_bytes_total", {}).items():
                    lanes_before[labels] = value
                dst = np.zeros_like(src)
                rreq = rc.irecv(dst)
                sc.isend(src).wait(timeout=60)
                rreq.wait(timeout=60)
                np.testing.assert_array_equal(src, dst)
            else:  # stall: typed watchdog verdict within a bounded wait
                t0 = time.perf_counter()
                dst = np.zeros_like(src)
                rreq = rc.irecv(dst)
                sreq = sc.isend(src)
                with pytest.raises(_native.ProgressTimeoutError):
                    sreq.wait()
                assert time.perf_counter() - t0 < 10
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                try:
                    c.close()
                except _native.NativeError:
                    pass

# ---------------------------------------------------------------------------
# Chaos matrix x SHM: faults acting on the shared-memory segment
# (docs/DESIGN.md "Intra-host shared memory").


def _shm_pair(monkeypatch, extra_env=None):
    monkeypatch.setenv("TPUNET_SHM", "1")
    for k, v in (extra_env or {}).items():
        monkeypatch.setenv(k, v)
    from tpunet.transport import Net

    ns, nr = Net(), Net()
    lc = nr.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = ns.connect(lc.handle)
    th.join()
    return ns, nr, lc, sc, got["rc"]


def test_chaos_shm_corrupt_detected_with_crc(monkeypatch):
    """A flipped byte in the ring segment (action=corrupt, applied to the
    RING copy under an original-bytes CRC trailer) surfaces as a typed
    CorruptionError — and the comm survives for the next message, the
    socket engines' containment contract carried onto the ring."""
    from tpunet import telemetry

    ns, nr, lc, sc, rc = _shm_pair(monkeypatch, {"TPUNET_CRC": "1"})
    telemetry.reset()
    try:
        src = np.frombuffer(
            bytes((i * 31 + 5) & 0xFF for i in range(1 << 22)), np.uint8).copy()
        transport.fault_inject(
            "stream=0:side=send:after_bytes=256K:action=corrupt")
        dst = np.zeros_like(src)
        rreq = rc.irecv(dst)
        sc.isend(src).wait(timeout=60)
        with pytest.raises(_native.CorruptionError):
            rreq.wait(timeout=60)
        transport.fault_clear()
        m = telemetry.metrics()
        assert sum(m.get("tpunet_crc_errors_total", {}).values()) >= 1
        # Containment: the SAME comm pair moves the next message intact.
        dst2 = np.zeros_like(src)
        rreq = rc.irecv(dst2)
        sc.isend(src).wait(timeout=60)
        assert rreq.wait(timeout=60) == src.nbytes
        np.testing.assert_array_equal(src, dst2)
        # The payload moved through the ring, not TCP.
        m = telemetry.metrics()
        assert sum(m.get("tpunet_shm_bytes_total", {}).values()) > 0
    finally:
        transport.fault_clear()
        for c in (sc, rc, lc):
            try:
                c.close()
            except _native.NativeError:
                pass
        ns.close()
        nr.close()


def test_chaos_shm_close_fails_over_to_tcp(monkeypatch):
    """action=close on the segment mid-transfer: the sender marks the ring
    dead, emits the 0xFE marker, and ships the remaining chunks — and every
    later message — over the ctrl TCP connection. Transfers stay
    bit-correct under CRC, the failover counter moves, and post-failover
    bytes land on the TCP counters (the segment is out of the picture)."""
    from tpunet import telemetry

    ns, nr, lc, sc, rc = _shm_pair(monkeypatch, {"TPUNET_CRC": "1"})
    telemetry.reset()
    try:
        transport.fault_inject(
            "stream=0:side=send:after_bytes=2500K:action=close")
        src = np.frombuffer(
            bytes((i * 13 + 7) & 0xFF for i in range(1 << 22)), np.uint8).copy()
        for _ in range(4):  # fault fires mid-message 1; 3 more ride ctrl TCP
            dst = np.zeros_like(src)
            rreq = rc.irecv(dst)
            sc.isend(src).wait(timeout=60)
            assert rreq.wait(timeout=60) == src.nbytes
            np.testing.assert_array_equal(src, dst)
        m = telemetry.metrics()
        assert sum(m.get("tpunet_stream_failovers_total", {}).values()) >= 1, \
            "segment close never failed over"
        shm = sum(m.get("tpunet_shm_bytes_total", {}).values())
        tcp = sum(m.get("tpunet_stream_rx_bytes", {}).values())
        assert shm > 0, "nothing moved through the ring before the fault"
        assert tcp >= 3 * src.nbytes, \
            f"post-failover messages not on TCP: shm={shm} tcp={tcp}"
    finally:
        transport.fault_clear()
        for c in (sc, rc, lc):
            try:
                c.close()
            except _native.NativeError:
                pass
        ns.close()
        nr.close()


def test_chaos_shm_stall_hits_watchdog(monkeypatch):
    """A stalled segment (live-but-stuck producer) is the progress
    watchdog's case: typed ProgressTimeoutError within a bounded wait,
    never a hang — the ring's futex parks notice the abort."""
    monkeypatch.setenv("TPUNET_PROGRESS_TIMEOUT_MS", "800")
    ns, nr, lc, sc, rc = _shm_pair(monkeypatch)
    try:
        transport.fault_inject(
            "stream=0:side=send:after_bytes=256K:action=stall")
        src = np.ones(1 << 22, np.uint8)  # 4 chunks: the stall fires inside
        t0 = time.perf_counter()
        sreq = sc.isend(src)
        with pytest.raises(_native.ProgressTimeoutError):
            sreq.wait()
        assert time.perf_counter() - t0 < 10
    finally:
        transport.fault_clear()
        for c in (sc, rc, lc):
            try:
                c.close()
            except _native.NativeError:
                pass
        ns.close()
        nr.close()


def _shm_death_victim(conn):
    os.environ["TPUNET_SHM"] = "1"
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(bytes(listen.handle))
    rc = listen.accept()
    buf = np.zeros(1 << 20, np.uint8)
    rc.recv(buf, timeout=60)  # consume one message, then die abruptly
    conn.send("got-one")
    os._exit(1)


def test_chaos_shm_peer_death_never_hangs():
    """Peer death mid-SHM-transfer: the survivor's futex waits detect the
    ctrl connection reset (the one signal a memory ring cannot carry) and
    fail typed within a bounded wait — watchdog not even required."""
    import multiprocessing as mp

    os.environ["TPUNET_SHM"] = "1"
    try:
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        proc = ctx.Process(target=_shm_death_victim, args=(child,))
        proc.start()
        from tpunet.transport import Net

        with Net() as net:
            sc = net.connect(parent.recv())
            src = np.ones(1 << 20, np.uint8)
            sc.isend(src).wait(timeout=60)
            assert parent.recv() == "got-one"
            proc.join(timeout=30)
            # Keep sending into the dead pair: ring space runs out (nobody
            # consumes) and the ctrl EOF turns it into a typed error — the
            # "never a hang" guarantee without any watchdog armed.
            t0 = time.perf_counter()
            with pytest.raises(_native.NativeError):
                for _ in range(64):  # > ring capacity worth of bytes
                    sc.isend(src).wait(timeout=60)
            assert time.perf_counter() - t0 < 60
            try:
                sc.close()
            except _native.NativeError:
                pass
    finally:
        os.environ.pop("TPUNET_SHM", None)


# ---------------------------------------------------------------------------
# Chaos matrix x hier: faults on the hierarchical schedule's DCN stage.


def _hier_chaos_worker(rank: int, world: int, port: int, q, action: str) -> None:
    try:
        os.environ.update({
            "TPUNET_PROGRESS_TIMEOUT_MS": "2500", "TPUNET_CRC": "1",
            "TPUNET_ALGO": "hier", "TPUNET_SHM": "1",
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_HOST_ID": f"chaoshost{rank // 2}",
        })
        from tpunet import _native as nat
        from tpunet import transport as tp
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        warm = comm.all_reduce(np.ones(4, np.float32))
        assert warm[0] == world
        comm.barrier()
        if rank == 1:
            # Fires during the measured allreduce; rank 1's cross-host
            # (DCN) sends happen in the inter stage.
            tp.fault_inject(f"stream=*:side=send:after_bytes=256K:action={action}")
        arr = np.full(1 << 20, float(rank + 1), np.float32)  # 4 MiB
        t0 = time.perf_counter()
        from tpunet import telemetry

        try:
            out = comm.all_reduce(arr)
            dt = time.perf_counter() - t0
            correct = bool(np.all(out == sum(r + 1.0 for r in range(world))))
            fo = int(sum(telemetry.metrics().get(
                "tpunet_stream_failovers_total", {}).values()))
            q.put((rank, f"OK correct={correct} fo={fo} dt={dt:.1f}"))
        except nat.NativeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"TYPED code={e.code} dt={dt:.1f}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))
    finally:
        try:
            from tpunet import transport as tp

            tp.fault_clear()
        except Exception:  # noqa: BLE001
            pass


@pytest.mark.parametrize("action", ["close", "stall"])
def test_chaos_hier_dcn_stage(action):
    """hier x {close, stall} on the DCN stage (W=4 as 2 fake hosts x 2):
    a lost or stalled inter-host path must end in a typed error (or a
    contained failover) within the bounded wait on every rank — the
    hierarchical schedule inherits the transport's failure model whole."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=_hier_chaos_worker, args=(r, 4, port, q, action))
        for r in range(4)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(4):
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == 4, f"missing rank report: {results}"
    statuses = " | ".join(f"{r}:{s}" for r, s in sorted(results.items()))
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        assert "correct=False" not in status, f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    # The fault cannot vanish: either a typed verdict surfaced somewhere,
    # or the segment failover CONTAINED it (close on an intra-host ring
    # fails over to the ctrl TCP path and the collective completes correct).
    if action == "stall":
        assert f"code={_native.TPUNET_ERR_TIMEOUT}" in statuses, statuses
    else:
        import re as _re

        contained = any(int(x) >= 1 for x in _re.findall(r"fo=(\d+)", statuses))
        assert "TYPED" in statuses or contained, statuses

# ---------------------------------------------------------------------------
# Chaos matrix x hierarchical AllToAll: faults on the DCN (inter) stage.


def _hier_a2a_chaos_worker(rank: int, world: int, port: int, q,
                           action: str) -> None:
    try:
        os.environ.update({
            "TPUNET_PROGRESS_TIMEOUT_MS": "2500", "TPUNET_CRC": "1",
            "TPUNET_A2A_ALGO": "hier", "TPUNET_SHM": "1",
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_HOST_ID": f"a2achaos{rank // 2}",
        })
        from tpunet import _native as nat
        from tpunet import transport as tp
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        n = 1 << 18  # 1 MiB blocks -> 4 MiB payload, several wire chunks
        send = np.stack([np.full(n, 100.0 * rank + j, np.float32)
                         for j in range(world)])
        warm = comm.all_to_all(send)
        for j in range(world):
            assert warm[j][0] == 100.0 * j + rank
        comm.barrier()
        if rank == 1:
            # Fires during the measured exchange; rank 1's cross-host
            # (DCN) sends happen in the a2a.inter stage.
            tp.fault_inject(f"stream=*:side=send:after_bytes=256K:action={action}")
        t0 = time.perf_counter()
        from tpunet import telemetry

        try:
            got = comm.all_to_all_typed(send)
            dt = time.perf_counter() - t0
            correct = all(bool(np.all(got[j] == 100.0 * j + rank))
                          for j in range(world))
            fo = int(sum(telemetry.metrics().get(
                "tpunet_stream_failovers_total", {}).values()))
            q.put((rank, f"OK correct={correct} fo={fo} dt={dt:.1f}"))
        except nat.NativeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"TYPED code={e.code} dt={dt:.1f}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))
    finally:
        try:
            from tpunet import transport as tp

            tp.fault_clear()
        except Exception:  # noqa: BLE001
            pass


@pytest.mark.parametrize("action", ["close", "stall", "corrupt"])
def test_chaos_hier_a2a_dcn_stage(action):
    """hier-A2A x {close, stall, corrupt} on the DCN stage (W=4 as 2x2 fake
    hosts): a lost, stalled or corrupted inter-host transpose path must end
    in a typed error (or a contained failover with a CORRECT result) within
    the bounded wait on every rank — the hierarchical AllToAll inherits the
    transport's failure model whole (ISSUE 11 chaos row)."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=_hier_a2a_chaos_worker, args=(r, 4, port, q, action))
        for r in range(4)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(4):
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == 4, f"missing rank report: {results}"
    statuses = " | ".join(f"{r}:{s}" for r, s in sorted(results.items()))
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        assert "correct=False" not in status, f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    if action == "stall":
        assert f"code={_native.TPUNET_ERR_TIMEOUT}" in statuses, statuses
    elif action == "corrupt":
        assert f"code={_native.TPUNET_ERR_CORRUPT}" in statuses, statuses
    else:
        import re as _re

        contained = any(int(x) >= 1 for x in _re.findall(r"fo=(\d+)", statuses))
        assert "TYPED" in statuses or contained, statuses


# ---------------------------------------------------------------------------
# Workload chaos rows (ISSUE 11): expert-shard loss + mid-pipeline death.


def _moe_chaos_worker(rank: int, world: int, port: int, q) -> None:
    try:
        os.environ.update({
            "TPUNET_PROGRESS_TIMEOUT_MS": "2500", "TPUNET_CRC": "1",
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
        })
        from tpunet import _native as nat
        from tpunet import transport as tp
        from tpunet.collectives import Communicator
        from tpunet.workloads import moe

        d_model, capacity, T = 64, 256, 512
        rng = np.random.default_rng(rank)
        comm = Communicator(f"127.0.0.1:{port}", rank, world,
                            traffic_class="latency")
        disp = moe.MoeDispatcher(comm, d_model=d_model, capacity=capacity)
        toks = rng.standard_normal((T, d_model)).astype(np.float32)
        experts = moe.route_tokens(T, world, 1.0, rng)
        disp.dispatch(toks, experts)  # warmup wires the mesh
        disp.combine(np.zeros((world, capacity, d_model), np.float32))
        comm.barrier()
        if rank == 1:
            # Expert-shard loss: the dispatch stream to/from rank 1 dies
            # mid-exchange (fault-injected close on its send side).
            tp.fault_inject("stream=*:side=send:after_bytes=64K:action=close")
        t0 = time.perf_counter()
        try:
            expert_toks, _ = disp.dispatch(toks, experts)
            disp.combine(expert_toks)
            dt = time.perf_counter() - t0
            q.put((rank, f"OK dt={dt:.1f}"))
        except nat.NativeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"TYPED code={e.code} dt={dt:.1f}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))
    finally:
        try:
            from tpunet import transport as tp

            tp.fault_clear()
        except Exception:  # noqa: BLE001
            pass


def test_chaos_moe_expert_shard_loss():
    """Expert-shard loss: a fault-injected close on a dispatch stream while
    an MoE dispatch A2A is in flight must produce a typed verdict
    (CorruptionError / dead-peer / watchdog) on every AFFECTED rank within
    the bounded wait — the dispatch can fail, it can never hang or hand
    back silently wrong expert inputs. Single-stream comms: a close IS a
    last-stream loss (no failover shield)."""
    import multiprocessing as mp

    from conftest import free_port

    world = 2
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_moe_chaos_worker, args=(r, world, port, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world, f"missing rank report: {results}"
    statuses = " | ".join(f"{r}:{s}" for r, s in sorted(results.items()))
    for rank, status in results.items():
        assert not status.startswith("FAIL"), f"rank {rank}: {status}"
        assert status.startswith(("OK", "TYPED")), f"rank {rank}: {status}"
    # The injected close cannot vanish: at least one rank fails typed.
    assert "TYPED" in statuses, statuses


def _pipe_death_worker(rank: int, world: int, port: int, q) -> None:
    try:
        os.environ.update({
            "TPUNET_PROGRESS_TIMEOUT_MS": "2500",
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_KEEPALIVE_IDLE_S": "1", "TPUNET_KEEPALIVE_INTVL_S": "1",
        })
        from tpunet import _native as nat
        from tpunet.collectives import Communicator
        from tpunet.workloads.pipeline import PipelineStage

        n = 1 << 16
        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        st = PipelineStage(comm)
        # One healthy microbatch proves the chain, then the middle stage
        # dies abruptly mid-pipeline.
        if st.is_first:
            st.isend(np.full(n, 7.0, np.float32)).wait()
        elif not st.is_last:
            buf = np.empty(n, np.float32)
            st.irecv(buf).wait()
            st.isend(buf + 1.0).wait()
        else:
            buf = np.empty(n, np.float32)
            st.irecv(buf).wait()
            assert buf[0] == 7.0 + (world - 2)
        comm.barrier()
        if rank == world // 2:
            os._exit(1)  # mid-pipeline rank death, no goodbye
        t0 = time.perf_counter()
        try:
            if st.is_first:
                # Keep feeding the dead stage: the send side must surface a
                # typed verdict (EOF / reset / watchdog), not wedge.
                for _ in range(64):
                    st.isend(np.full(n, 8.0, np.float32)).wait()
                    time.sleep(0.05)
                q.put((rank, "FAIL: sender never noticed the death"))
            else:
                buf = np.empty(n, np.float32)
                st.irecv(buf).wait()
                q.put((rank, "FAIL: receiver never noticed the death"))
        except nat.NativeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"TYPED code={e.code} dt={dt:.1f}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_chaos_pipeline_rank_death_fails_typed_never_hangs():
    """Mid-pipeline rank death (W=3, middle stage os._exit): both NEIGHBORS
    must surface a typed verdict — the receiver sees dead-peer EOF, the
    sender EOF/reset or the progress watchdog — within the bounded wait.
    Zero hangs: the chain inherits the transport's loud failure model
    (ISSUE 11 chaos row)."""
    import multiprocessing as mp

    from conftest import free_port

    world = 3
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_pipe_death_worker, args=(r, world, port, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world - 1):  # the dead rank reports nothing
            rank, status = q.get(timeout=150)  # the bounded-wait guarantee
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world - 1, f"missing rank report: {results}"
    for rank, status in results.items():
        assert status.startswith("TYPED"), f"rank {rank}: {status}"
