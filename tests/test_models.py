"""Model + trainer + parallel-layer tests on the virtual CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest

# conftest.py forces JAX_PLATFORMS=cpu + the 8-device XLA flag before any
# test module is imported.
import jax
import jax.numpy as jnp
import optax

from conftest import run_spawn_workers


def _tiny_model():
    from tpunet.models import VGG

    return VGG(cfg=(8, "M", 16, "M"), num_classes=10, hidden=32,
               compute_dtype=jnp.float32, classifier_dropout=0.0)


def test_vgg_forward_shape():
    model = _tiny_model()
    x = jnp.zeros((4, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    logits = model.apply({"params": params}, x)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_vgg16_param_count():
    """Full VGG16 has ~138M params — the architecture must be the real one."""
    from tpunet.models import vgg16

    model = vgg16(num_classes=1000)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 224, 224, 3)))["params"],
        jax.random.PRNGKey(0),
    )
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 130e6 < n_params < 145e6, f"got {n_params/1e6:.1f}M params"


def test_train_step_reduces_loss():
    from tpunet.train import create_train_state, make_train_step, synthetic_batch

    model = _tiny_model()
    tx = optax.sgd(5e-2, momentum=0.9)
    rng = np.random.default_rng(0)
    images, labels = synthetic_batch(rng, 16, 16, 10)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), jnp.asarray(images), tx)
    step = make_train_step(model, tx, donate=False)
    first = None
    for i in range(8):
        state, loss = step(state, jnp.asarray(images), jnp.asarray(labels), jax.random.PRNGKey(i))
        if first is None:
            first = float(loss)
    assert float(loss) < first, f"loss did not decrease: {first} -> {float(loss)}"


def test_partition_rules_shard_classifier():
    from tpunet.parallel import make_mesh, shard_params, vgg_partition_rules
    from jax.sharding import PartitionSpec as P

    model = _tiny_model()
    x = jnp.zeros((4, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    mesh = make_mesh(dp=4, mdl=2)
    shardings = shard_params(params, mesh, vgg_partition_rules())
    assert shardings["fc1"]["kernel"].spec == P(None, "mdl")
    assert shardings["fc2"]["kernel"].spec == P("mdl", None)
    assert shardings["conv0"]["kernel"].spec == P()  # replicated


def test_partition_rules_fall_back_when_indivisible():
    from tpunet.parallel import make_mesh, shard_params
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(dp=4, mdl=2)
    params = {"fc1": {"kernel": jnp.zeros((4, 3))}}  # 3 not divisible by mdl=2
    shardings = shard_params(params, mesh, [(r".*fc1/kernel", P(None, "mdl"))])
    assert shardings["fc1"]["kernel"].spec == P()


def test_dryrun_multichip_entrypoint():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_traces():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 1000)


def _dp_worker(rank: int, world: int, port: int, q) -> None:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from tpunet import distributed
        from tpunet.train import create_train_state, make_train_step, synthetic_batch

        distributed.initialize(f"127.0.0.1:{port}", rank, world)

        from tpunet.models import VGG

        model = VGG(cfg=(8, "M", 16, "M"), num_classes=10, hidden=32,
                    compute_dtype=jnp.float32, classifier_dropout=0.0)
        tx = optax.sgd(5e-2, momentum=0.9)
        # Same init on every rank (same seed), different data shards.
        data_rng = np.random.default_rng(1234 + rank)
        images, labels = synthetic_batch(data_rng, 8, 16, 10)
        state, _ = create_train_state(
            model, jax.random.PRNGKey(0), jnp.asarray(images), tx
        )
        step = make_train_step(model, tx, cross_host=True, donate=False)
        for i in range(3):
            state, loss = step(
                state, jnp.asarray(images), jnp.asarray(labels), jax.random.PRNGKey(i)
            )
        # After synced-gradient steps from identical init, params must be
        # identical across ranks (the DP invariant).
        from jax.flatten_util import ravel_pytree

        flat, _ = ravel_pytree(state.params)
        from tpunet.interop import dcn_all_gather

        all_params = np.asarray(dcn_all_gather(flat))
        for r in range(1, world):
            np.testing.assert_allclose(all_params[r], all_params[0], rtol=1e-6, atol=1e-7)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_two_process_dp_training_stays_synced():
    run_spawn_workers(_dp_worker, 2)
