"""Telemetry: metrics counters + trace spans across a real collective."""

from __future__ import annotations

import json
import os

from conftest import run_spawn_workers


def _worker(rank: int, world: int, port: int, q, trace_dir: str) -> None:
    try:
        os.environ["TPUNET_TRACE_DIR"] = trace_dir
        os.environ["TPUNET_RANK"] = str(rank)
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(
            coordinator=f"127.0.0.1:{port}", rank=rank, world_size=world
        )
        arr = np.ones(1 << 18, np.float32)
        out = comm.all_reduce(arr)
        assert out[0] == world

        m = telemetry.metrics()
        rank_key = (f'rank="{rank}"',)
        # A 2-rank ring AllReduce does 2(W-1)=2 sends and 2 recvs per rank.
        assert m["tpunet_isend_nbytes_count"][rank_key] >= 2
        assert m["tpunet_irecv_nbytes_count"][rank_key] >= 2
        assert m["tpunet_isend_nbytes_sum"][rank_key] >= arr.nbytes
        # Everything test()ed done: the in-flight gauge must be back to zero.
        assert m["tpunet_hold_on_request"][rank_key] == 0
        assert m["tpunet_failed_requests"][rank_key] == 0

        telemetry.flush_trace()
        comm.close()

        path = os.path.join(trace_dir, f"tpunet-trace-rank{rank}.json")
        assert os.path.exists(path), f"missing trace file {path}"
        text = open(path).read()
        assert '"isend-' in text and '"irecv-' in text
        # Spans must carry the reference's attributes (id, nbytes).
        first_span = json.loads(
            next(l for l in text.splitlines() if '"isend-' in l).rstrip(",")
        )
        assert first_span["args"]["nbytes"] > 0
        assert first_span["dur"] >= 0
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_and_trace(tmp_path):
    run_spawn_workers(_worker, 2, extra_args=(str(tmp_path),))


def test_metrics_text_parses_without_activity():
    from tpunet import telemetry

    text = telemetry.metrics_text()
    assert "tpunet_isend_nbytes_count" in text
    parsed = telemetry.metrics()
    assert any(k.startswith("tpunet_") for k in parsed)


def test_metrics_parser_accepts_label_less_lines(monkeypatch):
    """Prometheus exposition allows plain `name value` lines; the old
    mandatory-`{labels}` regex silently dropped them from metrics()."""
    from tpunet import telemetry

    sample = "\n".join(
        [
            "# TYPE tpunet_faults_injected counter",
            "tpunet_faults_injected 3",
            'tpunet_stream_failovers_total{rank="0"} 2',
            "tpunet_uptime_seconds 12.5",
            "tpunet_rate 6.02e+23",
            "not a metric line at all",
            "tpunet_bad_value{rank=\"0\"} oops",
        ]
    )
    monkeypatch.setattr(telemetry, "metrics_text", lambda: sample)
    parsed = telemetry.metrics()
    assert parsed["tpunet_faults_injected"][()] == 3.0
    assert parsed["tpunet_stream_failovers_total"][('rank="0"',)] == 2.0
    assert parsed["tpunet_uptime_seconds"][()] == 12.5
    assert parsed["tpunet_rate"][()] == 6.02e23
    assert "tpunet_bad_value" not in parsed
    # The native exposition's label-less faults total parses too.
    monkeypatch.undo()
    real = telemetry.metrics()
    assert () in real["tpunet_faults_injected"]


def _push_worker(rank: int, world: int, port: int, q) -> None:
    """Point the native pushgateway client at an in-process HTTP sink and
    check one push arrives (reference: Prometheus push thread with basic
    auth, nthread:183-211)."""
    try:
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        sink_port = srv.getsockname()[1]
        received: list[bytes] = []
        got_one = threading.Event()

        def serve():
            while not got_one.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                data = b""
                conn.settimeout(2)
                try:
                    while b"\r\n\r\n" not in data or len(data) < 200:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                except OSError:
                    pass
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 0\r\n\r\n")
                conn.close()
                received.append(data)
                if b"tpunet_" in data:
                    got_one.set()

        t = threading.Thread(target=serve, daemon=True)
        t.start()

        os.environ["TPUNET_METRICS_ADDR"] = f"user:pw@127.0.0.1:{sink_port}"
        os.environ["TPUNET_METRICS_INTERVAL_MS"] = "50"
        os.environ["TPUNET_RANK"] = str(rank)
        from tpunet import telemetry

        telemetry.metrics_text()  # constructs the singleton -> starts pusher
        assert got_one.wait(timeout=15), "no metrics push arrived"
        payload = b"".join(received)
        assert b"PUT /metrics/job/tpunet/rank/0" in payload
        assert b"Authorization: Basic " in payload
        assert b"tpunet_isend_nbytes_count" in payload
        srv.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_push():
    run_spawn_workers(_push_worker, 1)
