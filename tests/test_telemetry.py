"""Telemetry: metrics counters, TCP introspection, stage histograms, trace
spans (valid Chrome-trace JSON + cross-rank merge), scrape listener, reset."""

from __future__ import annotations

import json
import os

from conftest import free_port, run_spawn_workers


def _lint_exposition(text: str) -> None:
    """Prometheus text-format lint: every sample belongs to a family whose
    # TYPE line is adjacent to (immediately after) its # HELP line, and no
    sample appears before its family header."""
    import re

    line_re = re.compile(r"^(\w+)(?:\{[^}]*\})?\s+\S+$")
    pending_help: str | None = None
    current: str | None = None
    for line in text.splitlines():
        if line.startswith("# HELP "):
            pending_help = line.split()[2]
        elif line.startswith("# TYPE "):
            fam = line.split()[2]
            assert pending_help == fam, f"# TYPE {fam} not adjacent to its # HELP"
            current = fam
            pending_help = None
        elif line.strip():
            assert pending_help is None, f"HELP {pending_help} with no adjacent TYPE"
            m = line_re.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name = m.group(1)
            base = name
            for suf in ("_bucket", "_sum", "_count"):
                if current and name == current + suf:
                    base = current
            assert base == current, f"sample {name} outside its TYPE'd family ({current})"


def _worker(rank: int, world: int, port: int, q, trace_dir: str) -> None:
    try:
        os.environ["TPUNET_TRACE_DIR"] = trace_dir
        os.environ["TPUNET_RANK"] = str(rank)
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(
            coordinator=f"127.0.0.1:{port}", rank=rank, world_size=world
        )
        arr = np.ones(1 << 18, np.float32)
        out = comm.all_reduce(arr)
        assert out[0] == world

        m = telemetry.metrics()
        rank_key = (f'rank="{rank}"',)
        # A 2-rank ring AllReduce does 2(W-1)=2 sends and 2 recvs per rank.
        assert m["tpunet_isend_nbytes_count"][rank_key] >= 2
        assert m["tpunet_irecv_nbytes_count"][rank_key] >= 2
        assert m["tpunet_isend_nbytes_sum"][rank_key] >= arr.nbytes
        # Everything test()ed done: the in-flight gauge must be back to zero.
        assert m["tpunet_hold_on_request"][rank_key] == 0
        assert m["tpunet_failed_requests"][rank_key] == 0

        # TCP introspection: the sampler fires on the first chunk of each
        # stream, so per-stream gauges exist after one collective.
        for gauge in (
            "tpunet_stream_rtt_us",
            "tpunet_stream_retrans_total",
            "tpunet_stream_cwnd",
            "tpunet_stream_delivery_rate_bps",
        ):
            assert m.get(gauge), f"missing {gauge} after transfer: {sorted(m)}"
        # Fairness gauge present for both directions x all three traffic
        # classes (the QoS split: per-stream fairness reported WITHIN a
        # class), every series in (0, 1].
        fair = m["tpunet_stream_fairness_jain"]
        assert len(fair) == 6
        assert all(0.0 < v <= 1.0 for v in fair.values()), fair
        assert {telemetry.labels(k)["class"] for k in fair} == {
            "latency", "bulk", "control"}
        assert {telemetry.labels(k)["dir"] for k in fair} == {"tx", "rx"}
        # Stage-latency histograms: wire time observed for the ring messages,
        # and the numeric bucket view is monotonic with +Inf last.
        assert m["tpunet_req_wire_us_count"][rank_key] > 0
        assert m["tpunet_req_queue_us_count"][rank_key] > 0
        assert m["tpunet_req_total_us_count"][rank_key] > 0
        buckets = telemetry.histogram_buckets("tpunet_req_wire_us", m)
        assert buckets and buckets[-1][0] == float("inf")
        counts = [c for _, c in buckets]
        assert counts == sorted(counts) and counts[-1] > 0
        # The exposition is lint-clean (HELP/TYPE adjacent per family).
        _lint_exposition(telemetry.metrics_text())

        telemetry.flush_trace()
        comm.close()

        path = os.path.join(trace_dir, f"tpunet-trace-rank{rank}.json")
        assert os.path.exists(path), f"missing trace file {path}"
        # Golden: flush_trace() output is VALID Chrome-trace JSON.
        with open(path) as f:
            events = json.load(f)
        xspans = [e for e in events if e.get("ph") == "X"]
        for e in xspans:
            for field in ("name", "ts", "dur", "pid", "tid"):
                assert field in e, f"span missing {field}: {e}"
        isends = [e for e in xspans if e["name"].startswith("isend-")]
        irecvs = [e for e in xspans if e["name"].startswith("irecv-")]
        assert isends and irecvs
        assert isends[0]["args"]["nbytes"] > 0
        assert isends[0]["dur"] >= 0
        # Collective phase spans tagged with the cross-rank join key.
        colls = [e for e in xspans if "comm_id" in (e.get("args") or {})]
        assert any(e["name"] == "allreduce" for e in colls)
        assert any(e["name"].startswith("rs.") for e in colls)
        assert any(e["name"].startswith("ag.") for e in colls)
        for e in colls:
            assert "coll_seq" in e["args"]
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_and_trace(tmp_path):
    run_spawn_workers(_worker, 2, extra_args=(str(tmp_path),))
    # Cross-rank merge: both ranks' spans for the same (comm_id, coll_seq,
    # phase) land in ONE Perfetto-loadable timeline — and, with both workers
    # on one box (same host id), under ONE host track group with per-rank
    # thread tracks, instead of interleaving two top-level pid groups.
    from tpunet import telemetry

    merged_path = telemetry.merge_traces(str(tmp_path))
    with open(merged_path) as f:
        merged = json.load(f)
    by_tag: dict = {}
    host_pids: set = set()
    rank_tids: set = set()
    for ev in merged:
        args = ev.get("args") or {}
        if "comm_id" in args and "coll_seq" in args:
            assert args.get("host"), f"phase span missing host tag: {ev}"
            host_pids.add(ev["pid"])
            rank_tids.add(ev["tid"] // 1_000_000)
            by_tag.setdefault(
                (args["comm_id"], args["coll_seq"], ev["name"]), set()
            ).add(ev["tid"] // 1_000_000)
    assert by_tag, "no collective spans in merged trace"
    # Same box, same host id: one host group, both rank thread-track bands.
    assert host_pids == {1}, host_pids
    assert rank_tids == {0, 1}, rank_tids
    both = [tag for tag, tranks in by_tag.items() if tranks == {0, 1}]
    assert both, f"no tag present on both ranks: {by_tag}"
    # The per-host group metadata names the track.
    names = [e["args"]["name"] for e in merged
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert any(n.startswith("host ") for n in names), names
    # Alignment anchored the common tags; every event still has a timestamp.
    assert all("ts" in e for e in merged if e.get("ph") == "X")


def test_metrics_text_parses_without_activity():
    from tpunet import telemetry

    text = telemetry.metrics_text()
    assert "tpunet_isend_nbytes_count" in text
    parsed = telemetry.metrics()
    assert any(k.startswith("tpunet_") for k in parsed)
    _lint_exposition(text)


def test_metrics_parser_accepts_label_less_lines(monkeypatch):
    """Prometheus exposition allows plain `name value` lines; the old
    mandatory-`{labels}` regex silently dropped them from metrics()."""
    from tpunet import telemetry

    sample = "\n".join(
        [
            "# TYPE tpunet_faults_injected counter",
            "tpunet_faults_injected 3",
            'tpunet_stream_failovers_total{rank="0"} 2',
            "tpunet_uptime_seconds 12.5",
            "tpunet_rate 6.02e+23",
            "not a metric line at all",
            "tpunet_bad_value{rank=\"0\"} oops",
        ]
    )
    monkeypatch.setattr(telemetry, "metrics_text", lambda: sample)
    parsed = telemetry.metrics()
    assert parsed["tpunet_faults_injected"][()] == 3.0
    assert parsed["tpunet_stream_failovers_total"][('rank="0"',)] == 2.0
    assert parsed["tpunet_uptime_seconds"][()] == 12.5
    assert parsed["tpunet_rate"][()] == 6.02e23
    assert "tpunet_bad_value" not in parsed
    # The native exposition's label-less faults total parses too.
    monkeypatch.undo()
    real = telemetry.metrics()
    assert () in real["tpunet_faults_injected"]


def test_metrics_parser_preserves_label_order(monkeypatch):
    """Label tuples keep declaration order — sorting them made keys depend
    on label VALUES and scrambled le-bucket lookups."""
    from tpunet import telemetry

    sample = "\n".join(
        [
            'tpunet_demo_bucket{rank="0",le="200"} 1',
            'tpunet_demo_bucket{rank="0",le="1000"} 3',
            'tpunet_demo_bucket{rank="0",le="+Inf"} 4',
        ]
    )
    monkeypatch.setattr(telemetry, "metrics_text", lambda: sample)
    parsed = telemetry.metrics()
    assert ('rank="0"', 'le="200"') in parsed["tpunet_demo_bucket"]
    assert telemetry.labels(('rank="0"', 'le="200"')) == {"rank": "0", "le": "200"}
    buckets = telemetry.histogram_buckets("tpunet_demo", parsed)
    assert buckets == [(200.0, 1), (1000.0, 3), (float("inf"), 4)]


def _reset_worker(rank: int, world: int, port: int, q) -> None:
    """telemetry.reset() zeroes counters so warmups don't bleed into
    measurement windows (exercised over a real loopback transfer)."""
    try:
        import numpy as np

        from tpunet import telemetry
        from tpunet.transport import Net

        net = Net()
        listen = net.listen(0)
        rc_holder = {}
        import threading

        t = threading.Thread(target=lambda: rc_holder.update(rc=listen.accept()))
        t.start()
        sc = net.connect(listen.handle)
        t.join()
        rc = rc_holder["rc"]

        data = np.arange(1 << 20, dtype=np.uint8) % 251
        buf = np.zeros(1 << 20, dtype=np.uint8)
        req = rc.irecv(buf)
        sc.send(data, timeout=60)
        req.wait(timeout=60)

        m = telemetry.metrics()
        rank_key = (f'rank="{rank}"',)
        assert m["tpunet_isend_nbytes_count"][rank_key] >= 1
        assert m["tpunet_req_total_us_count"][rank_key] >= 1
        assert m.get("tpunet_stream_tx_bytes")

        telemetry.reset()
        m2 = telemetry.metrics()
        assert m2["tpunet_isend_nbytes_count"][rank_key] == 0
        assert m2["tpunet_irecv_nbytes_count"][rank_key] == 0
        assert m2["tpunet_req_total_us_count"][rank_key] == 0
        assert m2["tpunet_req_wire_us_count"][rank_key] == 0
        assert not m2.get("tpunet_stream_tx_bytes")  # zero slots are elided
        assert not m2.get("tpunet_stream_rtt_us")
        assert m2["tpunet_straggler_events_total"][rank_key] == 0

        # Counters keep working after a reset (a second transfer re-counts).
        req = rc.irecv(buf)
        sc.send(data, timeout=60)
        req.wait(timeout=60)
        m3 = telemetry.metrics()
        assert m3["tpunet_isend_nbytes_count"][rank_key] == 1

        sc.close()
        rc.close()
        listen.close()
        net.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_reset():
    run_spawn_workers(_reset_worker, 1)


# Families that legitimately do NOT sample zero after reset(). Every entry
# needs a reason; anything else nonzero after reset is a coverage bug the
# registry-driven test below reports by name.
_RESET_EXCEPTIONS = {
    # Jain fairness is a ratio in (0, 1]; the no-traffic value is a perfect 1.0.
    "tpunet_stream_fairness_jain": 1.0,
    # Encoded/payload wire ratio; identity (no codec engaged) reads 1.0.
    "tpunet_codec_wire_ratio": 1.0,
    # Deliberately NOT reset: it tracks live requests whose done events will
    # still arrive — zeroing mid-flight would wrap the clamp (metrics.cc).
    "tpunet_hold_on_request": None,
}


def _registry_reset_worker(rank: int, world: int, port: int, q, fams_json) -> None:
    """Registry-driven reset coverage: every family metrics.cc registers
    (parsed by tools/lint/metricsreg.py, passed in as JSON) samples zero
    after reset() — or appears in _RESET_EXCEPTIONS with a reason. A new
    family added without reset plumbing fails here by name, not by a
    dashboard going stale three PRs later."""
    try:
        import numpy as np

        from tpunet import telemetry
        from tpunet.transport import Net

        families = json.loads(fams_json)
        assert len(families) > 40, f"suspiciously small registry: {families}"

        net = Net()
        listen = net.listen(0)
        import threading

        rc_holder = {}
        t = threading.Thread(target=lambda: rc_holder.update(rc=listen.accept()))
        t.start()
        sc = net.connect(listen.handle)
        t.join()
        rc = rc_holder["rc"]
        data = np.arange(1 << 20, dtype=np.uint8) % 251
        buf = np.zeros(1 << 20, dtype=np.uint8)
        req = rc.irecv(buf)
        sc.send(data, timeout=60)
        req.wait(timeout=60)

        telemetry.reset()
        m = telemetry.metrics()
        bad = []
        for fam in families:
            if fam in _RESET_EXCEPTIONS and _RESET_EXCEPTIONS[fam] is None:
                continue
            want = _RESET_EXCEPTIONS.get(fam, 0)
            # Histogram series surface as separate top-level parser keys.
            for series in (fam, fam + "_bucket", fam + "_sum", fam + "_count"):
                for labels, value in m.get(series, {}).items():
                    if value != want:
                        bad.append(f"{series}{{{','.join(labels)}}} = {value} "
                                   f"(want {want} after reset)")
        assert not bad, "families nonzero after reset():\n  " + "\n  ".join(bad)

        sc.close()
        rc.close()
        listen.close()
        net.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_registry_reset_coverage():
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))
    from tools.lint.metricsreg import registry_families

    fams = sorted(registry_families(repo))
    run_spawn_workers(_registry_reset_worker, 1, extra_args=(json.dumps(fams),))


def _profile_worker(rank: int, world: int, port: int, q, trace_dir: str) -> None:
    """profile() enables tracing at RUNTIME (no TPUNET_TRACE_DIR at load)."""
    try:
        os.environ.pop("TPUNET_TRACE_DIR", None)
        import numpy as np

        from tpunet import telemetry
        from tpunet.transport import Net

        net = Net()
        listen = net.listen(0)
        import threading

        rc_holder = {}
        t = threading.Thread(target=lambda: rc_holder.update(rc=listen.accept()))
        t.start()
        sc = net.connect(listen.handle)
        t.join()
        rc = rc_holder["rc"]

        with telemetry.profile(trace_dir) as prof:
            data = np.arange(1 << 18, dtype=np.uint8) % 251
            buf = np.zeros(1 << 18, dtype=np.uint8)
            req = rc.irecv(buf)
            sc.send(data, timeout=60)
            req.wait(timeout=60)
        files = prof.rank_files()
        assert files, f"profile() wrote no trace files in {trace_dir}"
        with open(files[0]) as f:
            events = json.load(f)  # valid JSON after the context exits
        assert any(e.get("name", "").startswith("isend-") for e in events)

        # Tracing is OFF again after the context: a post-profile transfer
        # must not grow the trace file.
        size_before = os.path.getsize(files[0])
        req = rc.irecv(buf)
        sc.send(data, timeout=60)
        req.wait(timeout=60)
        telemetry.flush_trace()
        assert os.path.getsize(files[0]) == size_before

        sc.close()
        rc.close()
        listen.close()
        net.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_profile_context_manager(tmp_path):
    run_spawn_workers(_profile_worker, 1, extra_args=(str(tmp_path),))


def _scrape_worker(rank: int, world: int, port: int, q, scrape_port: str) -> None:
    """The on-demand /metrics listener serves a lint-clean exposition."""
    try:
        os.environ["TPUNET_METRICS_PORT"] = scrape_port
        os.environ["TPUNET_RANK"] = str(rank)
        import time

        from tpunet import telemetry

        telemetry.metrics_text()  # constructs the singleton -> starts listener
        deadline = time.monotonic() + 10
        text = None
        while time.monotonic() < deadline:
            try:
                text = telemetry.scrape(int(scrape_port))
                break
            except OSError:
                time.sleep(0.1)
        assert text is not None, "scrape listener never came up"
        assert "tpunet_isend_nbytes_count" in text
        assert "# HELP tpunet_isend_nbytes" in text
        _lint_exposition(text)

        # Framing: Prometheus scrapers key on the versioned Content-Type and
        # an exact Content-Length (the listener closes after one response).
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{scrape_port}/metrics", timeout=5) as r:
            body = r.read()
            assert r.headers["Content-Type"] == "text/plain; version=0.0.4"
            assert int(r.headers["Content-Length"]) == len(body)
        # Liveness endpoint: /healthz answers 200 "ok" without rendering the
        # full exposition — what a k8s probe polls at 1 Hz.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{scrape_port}/healthz", timeout=5) as r:
            body = r.read()
            assert r.status == 200
            assert body == b"ok\n"
            assert r.headers["Content-Type"] == "text/plain"
            assert int(r.headers["Content-Length"]) == len(body)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_scrape_listener():
    run_spawn_workers(_scrape_worker, 1, extra_args=(str(free_port()),))


def _push_worker(rank: int, world: int, port: int, q) -> None:
    """Point the native pushgateway client at an in-process HTTP sink and
    check one push arrives (reference: Prometheus push thread with basic
    auth, nthread:183-211)."""
    try:
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        sink_port = srv.getsockname()[1]
        received: list[bytes] = []
        got_one = threading.Event()

        def serve():
            while not got_one.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                data = b""
                conn.settimeout(2)
                try:
                    while b"\r\n\r\n" not in data or len(data) < 200:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                except OSError:
                    pass
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 0\r\n\r\n")
                conn.close()
                received.append(data)
                if b"tpunet_" in data:
                    got_one.set()

        t = threading.Thread(target=serve, daemon=True)
        t.start()

        os.environ["TPUNET_METRICS_ADDR"] = f"user:pw@127.0.0.1:{sink_port}"
        os.environ["TPUNET_METRICS_INTERVAL_MS"] = "50"
        os.environ["TPUNET_RANK"] = str(rank)
        from tpunet import telemetry

        telemetry.metrics_text()  # constructs the singleton -> starts pusher
        assert got_one.wait(timeout=15), "no metrics push arrived"
        payload = b"".join(received)
        assert b"PUT /metrics/job/tpunet/rank/0" in payload
        assert b"Authorization: Basic " in payload
        assert b"tpunet_isend_nbytes_count" in payload
        srv.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_push():
    run_spawn_workers(_push_worker, 1)


def _ephemeral_port_worker(rank: int, world: int, port: int, q) -> None:
    """TPUNET_METRICS_PORT=0 binds an EPHEMERAL port: the env still reads
    0, the bound port is learnable only via telemetry.metrics_port(), and
    scrape() with no argument finds it — the multi-tier-on-one-box
    contract (serving tiers each run their own listener with zero port
    bookkeeping)."""
    try:
        os.environ["TPUNET_METRICS_PORT"] = "0"
        os.environ["TPUNET_RANK"] = str(rank)

        from tpunet import telemetry

        telemetry.metrics_text()  # constructs the singleton -> binds
        bound = telemetry.metrics_port()
        assert bound > 0, "ephemeral bind did not happen"
        assert os.environ["TPUNET_METRICS_PORT"] == "0"  # env untouched
        text = telemetry.scrape()  # no port arg: native fallback
        assert "tpunet_serve_queue_depth" in text
        assert "tpunet_req_ttft_us_count" in text
        _lint_exposition(text)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_metrics_port_ephemeral_bind():
    run_spawn_workers(_ephemeral_port_worker, 1)


def test_serve_observe_validation():
    """The serving-tier SLO accessors reject unknown kinds/tiers loudly."""
    import pytest

    from tpunet import telemetry

    with pytest.raises(ValueError, match="kind"):
        telemetry.serve_observe("latency", 1)
    with pytest.raises(ValueError, match="tier"):
        telemetry.serve_queue_depth("edge", 1)
