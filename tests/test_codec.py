"""Compressed ring collectives (docs/DESIGN.md "Compressed collectives").

Coverage, socket-free first:
  * wire-size formulas per codec (bf16: 2n; int8: n + 4*ceil(n/256));
  * bf16 encode goldens — bitwise vs a python replication of the native
    RNE (bits + 0x7FFF + lsb), NaN/inf/-0.0 included, and roundtrip equal
    to an ml_dtypes bfloat16 cast on finite values — the wire values are
    the SAME bf16 the reduce kernels produce, by construction;
  * int8 block-scale goldens — the [f32 scale][int8 x 256] layout parsed by
    hand, the documented max-error bound |x - dec(enc(x))| <= amax/254 per
    block, the all-zero block, block-boundary sizes, and the
    non-finite-block -> NaN loudness contract.

Then with sockets (spawned ranks):
  * 2-rank compressed allreduce BYTE-EXACT against a separately-computed
    reference built from the same encode/decode primitives (both codecs,
    chunked and single-shot paths), plus cross-rank bit-identity;
  * 3-rank lane: the AG phase forwards ENCODED bytes verbatim, so every
    rank materializes identical values (sum and max ops);
  * codec-mismatch handshake raises CodecMismatchError on EVERY rank;
  * tpunet_codec_bytes_total / tpunet_codec_wire_ratio counters prove the
    bytes halved (bf16) / quartered (int8).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from conftest import run_spawn_workers
from tpunet import _native, transport

# ---------------------------------------------------------------------------
# Wire-size formulas.


@pytest.mark.parametrize("n", [0, 1, 7, 255, 256, 257, 1000, 4099])
def test_codec_wire_bytes_formulas(n):
    assert transport.codec_wire_bytes("f32", n) == 4 * n
    assert transport.codec_wire_bytes("bf16", n) == 2 * n
    assert transport.codec_wire_bytes("int8", n) == n + 4 * ((n + 255) // 256)


def test_codec_rejects_unknown_names():
    with pytest.raises(ValueError, match="unknown wire codec"):
        transport.codec_wire_bytes("fp8", 4)
    with pytest.raises(ValueError, match="unknown wire codec"):
        transport.codec_encode(np.zeros(4, np.float32), "bf-16")


# ---------------------------------------------------------------------------
# bf16 goldens.


def _f32_to_bf16_ref(f: np.ndarray) -> np.ndarray:
    """Python replication of the native RNE: bits + 0x7FFF + ((bits>>16)&1),
    keep the high half (mod 2^32) — the SAME arithmetic the bf16 reduce
    kernels use, so the wire values are pinned to the reduce goldens."""
    bits = f.view(np.uint32).astype(np.uint64)
    rounded = (bits + 0x7FFF + ((bits >> 16) & 1)) & 0xFFFFFFFF
    return (rounded >> 16).astype(np.uint16)


def test_bf16_encode_matches_rne_golden():
    rng = np.random.default_rng(20260804)
    x = (rng.standard_normal(4099) * 100).astype(np.float32)  # odd: SIMD tail
    x[rng.integers(0, x.size, 32)] = np.nan
    x[rng.integers(0, x.size, 32)] = np.inf
    x[rng.integers(0, x.size, 32)] = -np.inf
    x[rng.integers(0, x.size, 32)] = -0.0
    enc = transport.codec_encode(x, "bf16").view(np.uint16)
    np.testing.assert_array_equal(enc, _f32_to_bf16_ref(x))


def test_bf16_specials_roundtrip():
    sp = np.array([np.nan, np.inf, -np.inf, -0.0, 0.0, 1.0, 1.0 + 2**-8,
                   1.0 + 3 * 2**-9], np.float32)
    dec = transport.codec_decode(transport.codec_encode(sp, "bf16"), "bf16", sp.size)
    assert np.isnan(dec[0])
    assert dec[1] == np.inf and dec[2] == -np.inf
    assert dec[3] == 0.0 and np.signbit(dec[3])  # -0.0 keeps its sign
    assert dec[4] == 0.0 and not np.signbit(dec[4])
    assert dec[5] == 1.0
    assert dec[6] == 1.0  # RNE ties-to-even rounds the half-ulp down
    assert dec[7] == np.float32(1.0 + 2**-7)  # and the 3/2-ulp up


def test_bf16_roundtrip_matches_ml_dtypes():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.default_rng(7)
    x = (rng.standard_normal(10000) * 10).astype(np.float32)
    dec = transport.codec_decode(transport.codec_encode(x, "bf16"), "bf16", x.size)
    ref = x.astype(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(dec, ref)


# ---------------------------------------------------------------------------
# int8 block-scale goldens.


def _int8_blocks(enc: np.ndarray, n: int):
    """Parse the wire layout: per <=256-element block, [f32 scale][int8 x m]."""
    out = []
    off = 0
    done = 0
    while done < n:
        m = min(256, n - done)
        scale = enc[off:off + 4].view(np.float32)[0]
        q = enc[off + 4:off + 4 + m].view(np.int8)
        out.append((scale, q))
        off += 4 + m
        done += m
    assert off == enc.size
    return out


def test_int8_layout_and_scale_formula():
    rng = np.random.default_rng(3)
    x = (rng.standard_normal(600) * 9).astype(np.float32)
    enc = transport.codec_encode(x, "int8")
    for i, (scale, q) in enumerate(_int8_blocks(enc, x.size)):
        blk = x[i * 256:(i + 1) * 256]
        amax = np.max(np.abs(blk))
        assert scale == np.float32(amax) / np.float32(127.0)
        assert np.all(np.abs(q.astype(np.int32)) <= 127)
        # The block max must quantize to exactly +-127.
        assert np.max(np.abs(q.astype(np.int32))) == 127


@pytest.mark.parametrize("n", [1, 7, 255, 256, 257, 512, 513, 40001])
def test_int8_error_within_documented_bound(n):
    """Documented bound (docs/DESIGN.md): per element of a finite block,
    |x - dec(enc(x))| <= amax_block/254 — half a quantization step. The
    1e-4 relative slack covers the single-precision evaluation of
    x * (127/amax) inside the kernel."""
    rng = np.random.default_rng(n)
    x = (rng.standard_normal(n) * 17).astype(np.float32)
    dec = transport.codec_decode(transport.codec_encode(x, "int8"), "int8", n)
    err = np.abs(dec.astype(np.float64) - x.astype(np.float64))
    for off in range(0, n, 256):
        blk = slice(off, min(off + 256, n))
        bound = np.max(np.abs(x[blk])).astype(np.float64) / 254.0
        assert np.all(err[blk] <= bound * (1 + 1e-4) + 1e-30), (
            f"block at {off}: max err {err[blk].max()} > bound {bound}")


def test_int8_zero_block_is_exact():
    z = np.zeros(300, np.float32)
    enc = transport.codec_encode(z, "int8")
    np.testing.assert_array_equal(
        transport.codec_decode(enc, "int8", z.size), z)


def test_int8_nonfinite_block_decodes_nan_loudly():
    """A block holding inf/NaN cannot be represented; the whole block
    decodes to NaN instead of silently zeroing an overflowed gradient."""
    x = np.ones(300, np.float32)
    x[10] = np.inf
    dec = transport.codec_decode(transport.codec_encode(x, "int8"), "int8", x.size)
    assert np.all(np.isnan(dec[:256]))  # the poisoned block
    np.testing.assert_array_equal(dec[256:], x[256:])  # the clean one

    y = np.ones(10, np.float32)
    y[3] = np.nan
    dec = transport.codec_decode(transport.codec_encode(y, "int8"), "int8", y.size)
    assert np.all(np.isnan(dec))


# ---------------------------------------------------------------------------
# Config registration.


def test_config_registers_wire_dtype(monkeypatch):
    from tpunet.config import Config

    assert Config.from_env().wire_dtype == "f32"
    monkeypatch.setenv("TPUNET_WIRE_DTYPE", "bf16")
    assert Config.from_env().wire_dtype == "bf16"
    monkeypatch.setenv("TPUNET_WIRE_DTYPE", "bf-16")
    with pytest.raises(ValueError, match="TPUNET_WIRE_DTYPE"):
        Config.from_env()


# ---------------------------------------------------------------------------
# 2-rank compressed allreduce: byte-exact vs a separately-computed reference.


def _allreduce_worker(rank: int, world: int, port: int, q, codec: str,
                      chunk: int) -> None:
    try:
        os.environ["TPUNET_RING_CHUNKSIZE"] = str(chunk)
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world, wire_dtype=codec)
        assert comm.wire_dtype == codec, comm.wire_dtype
        rng = np.random.default_rng(rank)
        x = (rng.standard_normal(40001) * 3).astype(np.float32)
        out = comm.all_reduce(x)
        m = telemetry.metrics()
        codec_bytes = {
            (telemetry.labels(k).get("codec"), telemetry.labels(k).get("dir")): v
            for k, v in m.get("tpunet_codec_bytes_total", {}).items()
        }
        ratio = next(iter(m.get("tpunet_codec_wire_ratio", {}).values()), None)
        comm.close()
        # Queue payloads must pickle: ship plain arrays/floats.
        q.put((rank, ("OK", (x.tobytes(), out.tobytes(), codec_bytes, ratio))))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def _spawn_collect(target, world, extra):
    """run_spawn_workers variant that returns per-rank payloads."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=target, args=(r, world, port, q) + tuple(extra))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, payload = q.get(timeout=180)
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    for r, payload in results.items():
        assert isinstance(payload, tuple) and payload[0] == "OK", f"rank {r}: {payload}"
    assert len(results) == world
    return {r: payload[1] for r, payload in results.items()}


def _encdec(a: np.ndarray, codec: str) -> np.ndarray:
    return transport.codec_decode(transport.codec_encode(a, codec), codec, a.size)


@pytest.mark.parametrize("codec", ["bf16", "int8"])
@pytest.mark.parametrize("chunk", [16384, 8 << 20])  # pipelined + single-shot
def test_compressed_allreduce_2rank_byte_exact(codec, chunk):
    """W=2 model of the compressed ring, built from the SAME primitives the
    ring uses (codec_encode/codec_decode): the slice owner accumulates
    local + dec(enc(remote)) in f32 per pipeline chunk, and the AG phase
    distributes dec(enc(accum)) — every rank must hold exactly those bytes."""
    res = _spawn_collect(_allreduce_worker, 2, (codec, chunk))
    x = {r: np.frombuffer(res[r][0], np.float32) for r in res}
    out = {r: np.frombuffer(res[r][1], np.float32) for r in res}
    np.testing.assert_array_equal(out[0].view(np.uint32), out[1].view(np.uint32))

    n = x[0].size
    half = n // 2
    # Per-chunk element counts mirror the native CodecChunkElems: the WIRE
    # chunk rides TPUNET_RING_CHUNKSIZE, so bf16 packs chunk/2 elements and
    # int8 a block-rounded chunk.
    if codec == "bf16":
        ce = max(chunk // 2, 1)
    else:
        ce = max(chunk & ~255, 256)
    expect = np.empty(n, np.float32)
    for sl, owner in ((slice(0, half), 0), (slice(half, n), 1)):
        own = x[owner][sl]
        other = x[1 - owner][sl]
        acc = np.empty_like(own)
        for off in range(0, own.size, ce):
            c = slice(off, off + ce)
            acc[c] = own[c] + _encdec(np.ascontiguousarray(other[c]), codec)
        expect[sl] = _encdec(acc, codec)
    np.testing.assert_array_equal(out[0].view(np.uint32), expect.view(np.uint32))

    # Counters: wire bytes exactly halved (bf16) / quartered-ish (int8).
    codec_bytes, ratio = res[0][2], res[0][3]
    tx = codec_bytes.get((codec, "tx"), 0)
    assert tx == transport.codec_wire_bytes(codec, half) + \
        transport.codec_wire_bytes(codec, n - half)
    expect_ratio = 0.5 if codec == "bf16" else (
        transport.codec_wire_bytes("int8", n) / (4 * n))
    assert ratio == pytest.approx(expect_ratio, rel=0.01)


def _w3_worker(rank: int, world: int, port: int, q, codec: str) -> None:
    try:
        os.environ["TPUNET_RING_CHUNKSIZE"] = "16384"
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world, wire_dtype=codec)
        rng = np.random.default_rng(rank)
        x = (rng.standard_normal(10007) * 5).astype(np.float32)
        out_sum = comm.all_reduce(x)
        out_max = comm.all_reduce(x, op="max")
        comm.close()
        q.put((rank, ("OK", (x.tobytes(), out_sum.tobytes(), out_max.tobytes()))))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("codec", ["bf16", "int8"])
def test_compressed_allreduce_3rank_identical_and_bounded(codec):
    """W=3 exercises the AG phase's encoded-byte FORWARDING (slices travel
    verbatim hop to hop): all ranks bit-identical, error bounded by the
    per-hop quantization model."""
    res = _spawn_collect(_w3_worker, 3, (codec,))
    xs = [np.frombuffer(res[r][0], np.float32) for r in range(3)]
    sums = [np.frombuffer(res[r][1], np.float32) for r in range(3)]
    maxs = [np.frombuffer(res[r][2], np.float32) for r in range(3)]
    for r in (1, 2):
        np.testing.assert_array_equal(sums[0].view(np.uint32), sums[r].view(np.uint32))
        np.testing.assert_array_equal(maxs[0].view(np.uint32), maxs[r].view(np.uint32))
    exact = np.sum(xs, axis=0, dtype=np.float64)
    # 2 RS hops + 1 final quantize, each bounded by ~amax * (2^-8 for bf16,
    # 1/254 for int8); 0.05 * max|sum| is comfortably above both.
    assert np.max(np.abs(sums[0] - exact)) <= 0.05 * np.max(np.abs(exact))
    # max-op: per-hop error is absolute (a block-amax fraction), not
    # relative — small elements in a large-amax block wear the same bound.
    np.testing.assert_allclose(maxs[0], np.max(xs, axis=0), rtol=0,
                               atol=0.05 * np.max(np.abs(xs)))


# ---------------------------------------------------------------------------
# Codec-mismatch handshake.


def _mismatch_worker(rank: int, world: int, port: int, q) -> None:
    try:
        from tpunet.collectives import Communicator

        try:
            Communicator(f"127.0.0.1:{port}", rank, world,
                         wire_dtype="bf16" if rank == 0 else "f32")
            q.put((rank, "FAIL: no error raised"))
        except _native.CodecMismatchError as e:
            assert e.code == _native.TPUNET_ERR_CODEC
            assert "wire codec mismatch" in str(e)
            q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_codec_mismatch_raises_typed_error_on_every_rank():
    run_spawn_workers(_mismatch_worker, 2)


def test_unknown_wire_dtype_rejected_before_any_socket():
    from tpunet.collectives import Communicator

    with pytest.raises(_native.NativeError) as ei:
        Communicator("127.0.0.1:1", 0, 1, wire_dtype="fp8")
    assert ei.value.code == _native.TPUNET_ERR_INVALID
    assert "wire_dtype" in str(ei.value)


def test_world1_carries_codec_without_wire():
    from tpunet.collectives import Communicator

    comm = Communicator("127.0.0.1:1", 0, 1, wire_dtype="bf16")
    try:
        assert comm.wire_dtype == "bf16"
        x = np.arange(7, dtype=np.float32)
        np.testing.assert_array_equal(comm.all_reduce(x), x)  # self-loop: exact
    finally:
        comm.close()
