"""Multiprocess ring-collectives tests vs NumPy ground truth.

N real OS processes rendezvous through the TCP bootstrap on 127.0.0.1 and
run the same collective sequence; every rank checks results against a
locally-computed NumPy reference (it knows all ranks' seeds).
"""

from __future__ import annotations

import numpy as np
import pytest

from conftest import free_port, run_spawn_workers


def _rank_data(rank: int, n: int, dtype) -> np.ndarray:
    rng = np.random.default_rng(42 + rank)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.integers(-50, 50, size=n).astype(dtype)
    return rng.standard_normal(n).astype(dtype)


def _worker(rank: int, world: int, port: int, q, env: dict | None = None) -> None:
    try:
        import os

        for k, v in (env or {}).items():
            os.environ[k] = v
        import ml_dtypes

        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        n = 40_003  # odd on purpose: uneven ring slices

        # AllReduce sum f32 — bitwise-comparable because ring reduction order
        # is identical on every rank.
        mine = _rank_data(rank, n, np.float32)
        got = comm.all_reduce(mine, "sum")
        expect = sum(_rank_data(r, n, np.float32) for r in range(world))
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

        # AllReduce max f64.
        mine64 = _rank_data(rank, n, np.float64)
        got = comm.all_reduce(mine64, "max")
        expect = np.max([_rank_data(r, n, np.float64) for r in range(world)], axis=0)
        np.testing.assert_array_equal(got, expect)

        # AllReduce sum i64 — exact.
        mine_i = _rank_data(rank, n, np.int64)
        got = comm.all_reduce(mine_i, "sum")
        expect = sum(_rank_data(r, n, np.int64) for r in range(world))
        np.testing.assert_array_equal(got, expect)

        # AllReduce sum bf16 — loose tolerance (7-bit mantissa).
        bf = np.dtype(ml_dtypes.bfloat16)
        mine_bf = _rank_data(rank, 1024, np.float32).astype(bf)
        got = comm.all_reduce(mine_bf, "sum").astype(np.float32)
        expect = sum(_rank_data(r, 1024, np.float32).astype(bf).astype(np.float32)
                     for r in range(world))
        np.testing.assert_allclose(got, expect, rtol=0.1, atol=0.5)

        # ReduceScatter sum.
        per = 1000
        full = np.concatenate([_rank_data(rank, per, np.float32) + r for r in range(world)])
        got = comm.reduce_scatter(full.reshape(world, per), "sum")
        expect = sum(
            (_rank_data(r, per, np.float32) + rank) for r in range(world)
        )
        np.testing.assert_allclose(got.ravel(), expect, rtol=1e-5, atol=1e-5)

        # AllGather.
        shard = _rank_data(rank, 777, np.float32)
        got = comm.all_gather(shard)
        assert got.shape == (world, 777)
        for r in range(world):
            np.testing.assert_array_equal(got[r], _rank_data(r, 777, np.float32))

        # Broadcast from a non-zero root, > one pipeline chunk.
        root = world - 1
        if rank == root:
            payload = _rank_data(root, 3 * (1 << 20) // 4, np.float32)  # 3 MB
        else:
            payload = np.zeros(3 * (1 << 20) // 4, dtype=np.float32)
        got = comm.broadcast(payload, root=root)
        np.testing.assert_array_equal(got, _rank_data(root, 3 * (1 << 20) // 4, np.float32))

        # NeighborExchange: receive prev rank's array.
        mine_ne = _rank_data(rank, 5000, np.float32)
        got = comm.neighbor_exchange(mine_ne)
        prev = (rank - 1 + world) % world
        np.testing.assert_array_equal(got, _rank_data(prev, 5000, np.float32))

        # AllToAll: my send block j goes to rank j; my result block j is
        # rank j's block addressed to me. Verified against each peer's
        # deterministic construction.
        per_a2a = 257  # odd on purpose: non-round block bytes
        send = np.stack(
            [_rank_data(rank, per_a2a, np.float32) + j for j in range(world)]
        )
        got = comm.all_to_all(send)
        assert got.shape == send.shape
        for r in range(world):
            np.testing.assert_array_equal(
                got[r], _rank_data(r, per_a2a, np.float32) + rank
            )

        # Barrier (just must not hang or error).
        comm.barrier()

        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001 — report to parent
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [2, 4])
def test_ring_collectives(world):
    run_spawn_workers(_worker, world)


def _big_allreduce_worker(rank: int, world: int, port: int, q, env) -> None:
    try:
        import os

        for k, v in env.items():
            os.environ[k] = v
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        n = (16 << 20) // 4  # 16 MiB: crosses the parallel-reduce threshold
        mine = _rank_data(rank, n, np.float32)
        got = comm.all_reduce(mine, "sum", inplace=True)
        assert got is mine
        expect = sum(_rank_data(r, n, np.float32) for r in range(world))
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_parallel_reduce_and_inplace():
    # Force the fork-join reduce pool on (4 shards) regardless of host cores,
    # with a small ring chunk so many pipelined chunks hit the pool.
    env = {"TPUNET_REDUCE_THREADS": "4", "TPUNET_RING_CHUNKSIZE": str(4 << 20)}
    run_spawn_workers(_big_allreduce_worker, 2, extra_args=(env,))


def test_world_size_one_shortcuts():
    from tpunet.collectives import Communicator

    with Communicator(f"127.0.0.1:{free_port()}", 0, 1) as comm:
        x = np.arange(100, dtype=np.float32)
        np.testing.assert_array_equal(comm.all_reduce(x, "sum"), x)
        np.testing.assert_array_equal(comm.all_gather(x)[0], x)
        np.testing.assert_array_equal(comm.neighbor_exchange(x), x)
        np.testing.assert_array_equal(comm.all_to_all(x[None]), x[None])
        comm.barrier()


def test_unsupported_dtype_raises():
    from tpunet.collectives import Communicator

    with Communicator(f"127.0.0.1:{free_port()}", 0, 1) as comm:
        with pytest.raises(TypeError):
            comm.all_reduce(np.zeros(4, dtype=np.complex64))


def _a2a_worker(rank: int, world: int, port: int, q, env) -> None:
    try:
        import os

        for k, v in env.items():
            os.environ[k] = v
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        per = 4099  # non-round block bytes
        send = np.stack(
            [_rank_data(rank, per, np.float32) + j for j in range(world)]
        )
        got = comm.all_to_all(send)
        for r in range(world):
            np.testing.assert_array_equal(
                got[r], _rank_data(r, per, np.float32) + rank
            )
        comm.barrier()
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world,mode", [(3, "pairwise"), (4, "pairwise"), (4, "ring")])
def test_all_to_all_modes(world, mode):
    # Pairwise (direct per-peer mesh, O(W*B) wire bytes) must match the
    # ring-relay fallback bit for bit; W=3 exercises the odd-world mesh.
    run_spawn_workers(_a2a_worker, world, extra_args=({"TPUNET_A2A": mode},))


def _oop_multichunk_worker(rank: int, world: int, port: int, q, env) -> None:
    try:
        import os

        for k, v in env.items():
            os.environ[k] = v
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        # 2 MiB with a 64 KiB ring chunk: every ring slice is many pipelined
        # chunks, exercising the chunked ExchangeReduce with a DISTINCT
        # local operand (the zero-staging out-of-place path) at W>2 —
        # including the ReduceScatter partial ping-pong.
        n = (2 << 20) // 4
        mine = _rank_data(rank, n, np.float32)
        orig = mine.copy()
        got = comm.all_reduce(mine, "sum")  # out-of-place
        expect = sum(_rank_data(r, n, np.float32) for r in range(world))
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(mine, orig)  # input untouched

        rs_n = n - (n % world)
        got = comm.reduce_scatter(mine[:rs_n], "sum")
        shard = rs_n // world
        np.testing.assert_allclose(
            got, expect[:rs_n].reshape(world, shard)[rank],
            rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(mine, orig)
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [3, 4])
def test_out_of_place_multichunk_ring(world):
    run_spawn_workers(
        _oop_multichunk_worker, world,
        extra_args=({"TPUNET_RING_CHUNKSIZE": str(64 << 10)},))
