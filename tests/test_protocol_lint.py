"""The wire-contract registry checker (tools/protocol): clean on HEAD,
RED on seeded drift.

The live-tree gate itself runs in tests/test_lint.py (the protocol checker
is the fifth entry in tools.lint CHECKERS, so the parametrized clean-tree
test covers it). This file proves the checker can actually FIRE: each test
copies the real contract-bearing sources into a tmp tree, seeds ONE drift
of a distinct defect class — flag-bit collision, blob-offset overlap,
enum drift, struct-format drift, frame-type collision, grammar-token
mismatch — and asserts the checker names it. A checker that cannot go red
is decoration, not verification.
"""

from __future__ import annotations

import shutil
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.protocol import check_protocol  # noqa: E402

# Every file the checker reads; fixtures clone these so a seeded drift is
# the ONLY difference from HEAD.
_CONTRACT_FILES = (
    "cpp/src/wire.h",
    "cpp/src/wire.cc",
    "cpp/src/collectives.cc",
    "cpp/src/dispatch.h",
    "cpp/src/fault.h",
    "cpp/src/fault.cc",
    "cpp/include/tpunet/utils.h",
    "cpp/include/tpunet/qos.h",
    "tpunet/serve/protocol.py",
    "tpunet/serve/publish.py",
    "tpunet/elastic.py",
)


@pytest.fixture()
def tree(tmp_path):
    for rel in _CONTRACT_FILES:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    return tmp_path


def _seed(tree: Path, rel: str, old: str, new: str) -> None:
    path = tree / rel
    text = path.read_text()
    assert old in text, f"fixture drift: {old!r} no longer in {rel}"
    path.write_text(text.replace(old, new))


def test_fixture_tree_matches_head(tree):
    assert check_protocol(tree) == []


def test_fires_on_preamble_flag_bit_collision(tree):
    _seed(tree, "cpp/src/wire.h",
          "constexpr uint64_t kPreambleFlagShm = 1ull << 3;",
          "constexpr uint64_t kPreambleFlagShm = 1ull << 1;")
    v = check_protocol(tree)
    assert any("kPreambleFlagShm" in x and "spec" in x for x in v)
    assert any("collides" in x for x in v)


def test_fires_on_flag_inside_class_nibble(tree):
    _seed(tree, "cpp/src/wire.h",
          "constexpr uint64_t kPreambleFlagShm = 1ull << 3;",
          "constexpr uint64_t kPreambleFlagShm = 1ull << 9;")
    v = check_protocol(tree)
    assert any("class nibble" in x for x in v)


def test_fires_on_blob_offset_drift(tree):
    _seed(tree, "cpp/src/wire.h",
          "constexpr size_t kBlobOffQosClass = 6;",
          "constexpr size_t kBlobOffQosClass = 5;")
    v = check_protocol(tree)
    assert any("kBlobOffQosClass" in x for x in v)


def test_fires_on_unencoded_blob_field(tree):
    # The checker greps by name, so the seeded rename must not keep the
    # original as a substring.
    _seed(tree, "cpp/src/collectives.cc", "kBlobOffA2aAlgo", "kBlobOffZzzAlgo")
    v = check_protocol(tree)
    assert any("kBlobOffA2aAlgo" in x and "encode" in x for x in v)


def test_fires_on_ctrl_opcode_collision(tree):
    _seed(tree, "cpp/src/wire.h",
          "constexpr uint8_t kCtrlFrameNack = 0xFD;",
          "constexpr uint8_t kCtrlFrameNack = 0xFE;")
    v = check_protocol(tree)
    assert any("kCtrlFrameNack" in x for x in v)
    assert any("collides" in x for x in v)


def test_fires_on_wire_enum_drift(tree):
    _seed(tree, "cpp/src/fault.h", "kJoin = 2,", "kJoin = 3,")
    v = check_protocol(tree)
    assert any("ChurnAction" in x and "kJoin" in x for x in v)


def test_fires_on_serve_struct_format_drift(tree):
    _seed(tree, "tpunet/serve/protocol.py",
          '_RESULT_HDR = struct.Struct("<IIQ")',
          '_RESULT_HDR = struct.Struct("<III")')
    v = check_protocol(tree)
    assert any("_RESULT_HDR" in x for x in v)


def test_fires_on_serve_frame_type_drift(tree):
    _seed(tree, "tpunet/serve/protocol.py", "T_SWAP_RETIRE = 7", "T_SWAP_RETIRE = 9")
    v = check_protocol(tree)
    assert any("T_SWAP_RETIRE" in x for x in v)


def test_fires_on_new_constant_without_spec_entry(tree):
    # Two-sidedness: a NEW source constant with no spec entry is as red as a
    # spec entry the sources dropped.
    _seed(tree, "tpunet/serve/protocol.py", "T_SWAP_RETIRE = 7",
          "T_SWAP_RETIRE = 7\nT_SHINY_NEW = 12")
    v = check_protocol(tree)
    assert any("T_SHINY_NEW" in x and "no spec entry" in x for x in v)


def test_fires_on_chaos_token_mismatch(tree):
    _seed(tree, "tpunet/elastic.py",
          '_CHURN_ACTIONS = {0: None, 1: "kill", 2: "join"}',
          '_CHURN_ACTIONS = {0: None, 1: "kill", 2: "jion"}')
    v = check_protocol(tree)
    assert any("_CHURN_ACTIONS" in x or "jion" in x for x in v)


def test_fires_on_codec_id_mismatch(tree):
    _seed(tree, "tpunet/serve/protocol.py",
          '_CODEC_IDS = {"f32": 0, "bf16": 1, "int8": 2}',
          '_CODEC_IDS = {"f32": 0, "bf16": 2, "int8": 1}')
    v = check_protocol(tree)
    assert any("_CODEC_IDS" in x for x in v)


def test_fires_on_missing_contract_file(tree):
    (tree / "cpp/src/wire.h").unlink()
    v = check_protocol(tree)
    assert any("wire.h not found" in x for x in v)
