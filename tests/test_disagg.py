"""Disaggregated prefill/decode serving tier (tpunet/serve, DESIGN.md §10).

Coverage map:
  * KV-block codec goldens — shipped layout pinned byte-for-byte per wire
    dtype: f32 passthrough, bf16 RNE, int8 block-scale layout with scale
    blocks RESTARTING per KV block, the |err| <= amax/254 bound, and
    non-finite -> NaN-block loudness.
  * Tier wiring handshake — codec/model mismatches raise TYPED errors on
    BOTH ranks before any payload moves.
  * W=2 ship-and-adopt — a full loopback frontend+decode tier on the f32
    wire produces greedy outputs BITWISE-equal to single-host BatchServer
    (and the generate() oracle); int8 completes with the exact ~0.254x
    wire ratio by counters.
  * Failure containment — an abrupt decode-rank death mid-request is
    replayed from the retained KV block (or re-prefilled) on the
    surviving rank with zero corrupted/truncated streams; the real
    process-kill case is injected via the TPUNET_FAULT_SPEC grammar.
  * Router admission backpressure (typed RouterBusyError).
"""

from __future__ import annotations

import struct
import threading
import time

import numpy as np
import pytest

from conftest import free_port  # noqa: F401  (pins JAX_PLATFORMS=cpu first)

import jax
import jax.numpy as jnp

from tpunet import serve, telemetry, transport
from tpunet.models import BatchServer, Transformer, generate
from tpunet.serve import protocol as proto

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _tiny_model():
    return Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                       d_ff=64, compute_dtype=jnp.float32)


def _tiny_setup():
    model = _tiny_model()
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    return model, params


def _oracle(model, params, prompt, n):
    out = generate(model, params, jnp.asarray(prompt)[None], n)
    return np.asarray(out)[0, len(prompt):]


# ---------------------------------------------------------------------------
# KV-block codec goldens (no sockets, no jax compute).


def _fake_rows(plen, heads=4, dh=8, leaves=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((plen, heads, dh)).astype(np.float32)
            for _ in range(leaves)]


def test_kv_block_f32_is_exact_passthrough():
    rows = _fake_rows(7)
    wire = serve.encode_kv_block(rows, "f32")
    flat = np.concatenate([r.ravel() for r in rows])
    np.testing.assert_array_equal(wire.view(np.float32), flat)
    back = serve.decode_kv_block(wire, "f32", [r.shape for r in rows])
    for a, b in zip(back, rows):
        np.testing.assert_array_equal(a, b)


def test_kv_block_bf16_matches_codec_golden():
    rows = _fake_rows(5, seed=1)
    wire = serve.encode_kv_block(rows, "bf16")
    flat = np.concatenate([r.ravel() for r in rows])
    np.testing.assert_array_equal(wire, transport.codec_encode(flat, "bf16"))
    back = serve.decode_kv_block(wire, "bf16", [r.shape for r in rows])
    flat_back = np.concatenate([b.ravel() for b in back])
    np.testing.assert_array_equal(
        flat_back, transport.codec_decode(wire, "bf16", flat.size))


def test_kv_block_int8_layout_scale_blocks_restart_per_block():
    """Two different KV blocks encode INDEPENDENTLY: each block's first 4
    wire bytes are ITS OWN first-256-element scale (amax/127) — the scale
    blocks restart per KV block because a block is one encode call."""
    b1 = _fake_rows(8, seed=2)          # 1024 elems: 4 scale blocks
    b2 = [100.0 * r for r in _fake_rows(8, seed=3)]
    for rows in (b1, b2):
        flat = np.concatenate([r.ravel() for r in rows])
        wire = serve.encode_kv_block(rows, "int8")
        assert wire.size == flat.size + 4 * ((flat.size + 255) // 256)
        (scale0,) = struct.unpack("<f", wire[:4].tobytes())
        np.testing.assert_allclose(
            scale0, np.abs(flat[:256]).max() / 127, rtol=1e-6)
    # ...and the error bound survives the round trip, per 256-block.
    flat = np.concatenate([r.ravel() for r in b2])
    back = serve.decode_kv_block(
        serve.encode_kv_block(b2, "int8"), "int8", [r.shape for r in b2])
    flat_back = np.concatenate([b.ravel() for b in back])
    for off in range(0, flat.size, 256):
        blk = flat[off:off + 256]
        err = np.abs(flat_back[off:off + 256] - blk)
        assert err.max() <= np.abs(blk).max() / 254 + 1e-6


def test_kv_block_int8_nonfinite_is_loud():
    """A non-finite K/V value poisons its whole 256-element scale block to
    NaN — shipped corruption is LOUD, never a silently-clamped number."""
    rows = _fake_rows(8, seed=4)
    rows[1][3, 2, 5] = np.inf
    flat = np.concatenate([r.ravel() for r in rows])
    bad_block = int(np.flatnonzero(~np.isfinite(flat))[0]) // 256
    back = serve.decode_kv_block(
        serve.encode_kv_block(rows, "int8"), "int8", [r.shape for r in rows])
    flat_back = np.concatenate([b.ravel() for b in back])
    assert np.isnan(flat_back[bad_block * 256:(bad_block + 1) * 256]).all()
    finite = np.ones(flat.size, bool)
    finite[bad_block * 256:(bad_block + 1) * 256] = False
    assert np.isfinite(flat_back[finite]).all()


def test_kv_wire_bytes_sizing_and_model_signature():
    shapes = [(7, 4, 8)] * 4
    n = serve.kv_block_elems(shapes)
    assert n == 7 * 4 * 8 * 4
    assert serve.kv_wire_bytes("f32", shapes) == 4 * n
    assert serve.kv_wire_bytes("bf16", shapes) == 2 * n
    assert serve.kv_wire_bytes("int8", shapes) == n + 4 * ((n + 255) // 256)
    m1, m2 = _tiny_model(), Transformer(vocab=64, d_model=48, n_layers=2,
                                        n_heads=4, d_ff=64)
    assert serve.model_signature(m1) == serve.model_signature(_tiny_model())
    assert serve.model_signature(m1) != serve.model_signature(m2)


# ---------------------------------------------------------------------------
# Tier wiring handshake: typed mismatch on BOTH ranks.


def _handshake_both_sides(front_hello, back_hello):
    """Run the wiring handshake with the given hellos; returns the
    exception (or None) each side raised."""
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = ("127.0.0.1", lsock.getsockname()[1])
    errs = {}

    def back():
        with transport.Net() as net:
            try:
                link = proto.wire_decode(addr, net, back_hello, timeout=30)
                link.close()
                errs["back"] = None
            except Exception as e:  # noqa: BLE001
                errs["back"] = e

    th = threading.Thread(target=back)
    th.start()
    conn, _ = lsock.accept()
    with transport.Net() as net:
        try:
            link = proto.wire_frontend(conn, net, front_hello)
            link.close()
            errs["front"] = None
        except Exception as e:  # noqa: BLE001
            errs["front"] = e
        finally:
            conn.close()
    th.join(timeout=30)
    lsock.close()
    return errs


def test_tier_codec_mismatch_typed_on_both_ranks():
    sig = 0x1234
    front = proto.Hello(proto.ROLE_FRONTEND, "int8", 0, 64, 64, sig)
    back = proto.Hello(proto.ROLE_DECODE, "f32", 2, 64, 64, sig)
    errs = _handshake_both_sides(front, back)
    assert isinstance(errs["front"], serve.KVCodecMismatchError)
    assert isinstance(errs["back"], serve.KVCodecMismatchError)
    assert "int8" in str(errs["front"]) and "f32" in str(errs["front"])


def test_tier_model_signature_mismatch_typed_on_both_ranks():
    front = proto.Hello(proto.ROLE_FRONTEND, "int8", 0, 64, 64, 0xAAAA)
    back = proto.Hello(proto.ROLE_DECODE, "int8", 2, 64, 64, 0xBBBB)
    errs = _handshake_both_sides(front, back)
    assert isinstance(errs["front"], serve.TierMismatchError)
    assert isinstance(errs["back"], serve.TierMismatchError)


def test_tier_wiring_succeeds_and_frames_roundtrip():
    sig = 0x77
    front = proto.Hello(proto.ROLE_FRONTEND, "bf16", 0, 64, 64, sig)
    back = proto.Hello(proto.ROLE_DECODE, "bf16", 2, 64, 64, sig)
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = ("127.0.0.1", lsock.getsockname()[1])
    out = {}

    def back_side():
        with transport.Net() as net:
            link = proto.wire_decode(addr, net, back, timeout=30)
            out["frame"] = link.recv_frame(timeout=30)
            link.send_frame(proto.T_RESULT, 9,
                            proto.pack_result(np.arange(3, dtype=np.int32),
                                              0, 123))
            link.close()

    th = threading.Thread(target=back_side)
    th.start()
    conn, _ = lsock.accept()
    with transport.Net() as net:
        link = proto.wire_frontend(conn, net, front)
        conn.close()
        assert link.peer.slots == 2 and link.peer.kv_codec == "bf16"
        link.send_frame(proto.T_FIRST, 42, aux=7)
        ftype, rid, payload, tpot = link.recv_frame(timeout=30)
        assert ftype == proto.T_RESULT and rid == 9
        tokens, status, tpot_us = proto.unpack_result(payload)
        np.testing.assert_array_equal(tokens, [0, 1, 2])
        assert status == 0 and tpot_us == 123
        link.close()
    th.join(timeout=30)
    lsock.close()
    assert out["frame"][0] == proto.T_FIRST and out["frame"][1] == 42
    assert out["frame"][3] == 7


# ---------------------------------------------------------------------------
# W=2 ship-and-adopt: bitwise equality + wire-ratio counters.


def _start_tier(model, params, *, kv_codec, max_len=40, decode_slots=2,
                queue_limit=None, retain_kv=True):
    """One frontend (this thread) + one decode rank (worker thread) over
    real loopback transport comms; returns (router, worker_box, thread)."""
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]
    worker_box = {}

    def decode_main():
        worker = serve.connect_decode(addr, model, params,
                                      slots=decode_slots, max_len=max_len,
                                      kv_codec=kv_codec)
        worker_box["worker"] = worker
        try:
            worker.serve()
        finally:
            worker.close()  # engines torn down: no thread/socket leak
                            # into later (timing-sensitive) tests

    th = threading.Thread(target=decode_main, daemon=True)
    th.start()
    prefill = serve.PrefillEngine(model, params, max_len=max_len)
    router = serve.Router(prefill, kv_codec=kv_codec,
                          queue_limit=queue_limit, retain_kv=retain_kv)
    router.accept_ranks(lsock, 1)
    lsock.close()
    return router, worker_box, th


def _run_tier(model, params, prompts, lens, *, kv_codec, max_len=40,
              decode_slots=2, queue_limit=None, retain_kv=True):
    router, worker_box, th = _start_tier(
        model, params, kv_codec=kv_codec, max_len=max_len,
        decode_slots=decode_slots, queue_limit=queue_limit,
        retain_kv=retain_kv)
    ids = [router.submit(p, n) for p, n in zip(prompts, lens)]
    results = router.run(timeout=240)
    router.shutdown()
    th.join(timeout=60)
    router.close()
    return {i: results[i] for i in ids}, router, worker_box.get("worker")


def test_ship_and_adopt_bitwise_equal_single_host_f32():
    """The acceptance pin: a 2-rank loopback disaggregated serve on the
    f32 KV wire produces greedy outputs BITWISE-equal to single-host
    BatchServer (and therefore to generate()) — prefill-side computation,
    the shipped bytes, and the adopt path introduce zero drift."""
    model, params = _tiny_setup()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, n).astype(np.int32)
               for n in (5, 9, 13, 7)]
    lens = [8, 6, 8, 5]
    results, router, worker = _run_tier(model, params, prompts, lens,
                                        kv_codec="f32")
    # Single-host oracle: same requests through one BatchServer.
    srv = BatchServer(model, params, slots=2, max_len=40)
    sids = [srv.submit(p, n) for p, n in zip(prompts, lens)]
    single = srv.run()
    for (rid, sid, p, n) in zip(results, sids, prompts, lens):
        np.testing.assert_array_equal(results[rid], single[sid])
        np.testing.assert_array_equal(results[rid],
                                      _oracle(model, params, p, n))
    assert router.stats["completed"] == len(prompts)
    assert router.stats["rank_failures"] == 0
    assert worker.srv.stats["kv_adopts"] == len(prompts)
    assert worker.srv.stats["prefills"] == 0  # decode NEVER re-prefills


def test_ship_and_adopt_int8_wire_ratio_by_counters():
    """int8 KV shipping completes every request and the wire bytes are the
    codec's exact ratio (~0.254x payload) by the codec counters — the
    same counters that CI-gate the compressed collectives."""
    model, params = _tiny_setup()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 64, 8).astype(np.int32) for _ in range(3)]
    telemetry.reset()
    results, router, worker = _run_tier(model, params, prompts, [6, 6, 6],
                                        kv_codec="int8")
    assert all(len(v) == 6 for v in results.values())
    m = telemetry.metrics()
    ratio = next(iter(m["tpunet_codec_wire_ratio"].values()))
    # 8 tokens x 4 leaves x 32 = 1024 elems/block, a multiple of 256:
    # exactly (1024 + 16)/4096.
    np.testing.assert_allclose(ratio, 0.25390625, atol=2e-4)
    codec_tx = m["tpunet_codec_bytes_total"]
    int8_tx = sum(v for k, v in codec_tx.items()
                  if telemetry.labels(k).get("codec") == "int8"
                  and telemetry.labels(k).get("dir") == "tx")
    assert int8_tx == 3 * (1024 + 16)  # 3 blocks x 1040 wire bytes


def test_router_backpressure_typed():
    """With zero queue headroom and every decode slot busy, admission
    rejects with RouterBusyError (typed, retryable) instead of queueing
    unboundedly — and the tier still drains what it accepted."""
    model, params = _tiny_setup()
    rng = np.random.default_rng(2)
    p0 = rng.integers(0, 64, 6).astype(np.int32)
    router, _, th = _start_tier(model, params, kv_codec="f32",
                                decode_slots=1, queue_limit=0)
    rid = router.submit(p0, 4)  # occupies the single decode slot
    with pytest.raises(serve.RouterBusyError):
        router.submit(p0, 4)    # slot busy, zero queue headroom -> typed
    assert router.stats["rejected"] == 1
    results = router.run(timeout=240)
    np.testing.assert_array_equal(results[rid],
                                  _oracle(model, params, p0, 4))
    router.shutdown()
    th.join(timeout=60)
    router.close()


# ---------------------------------------------------------------------------
# Failure containment: decode-rank death mid-request.


@pytest.mark.parametrize("retain_kv", [True, False])
def test_decode_rank_death_replay_contained(retain_kv):
    """One decode rank dies ABRUPTLY with a shipped request unreported;
    the router contains it: the request replays on the surviving rank —
    from the retained KV block (retain_kv=True, no second prefill) or by
    re-prefilling — and every stream completes bitwise-correct."""
    model, params = _tiny_setup()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 64, 7).astype(np.int32) for _ in range(4)]
    lens = [6, 6, 6, 6]

    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]

    def flaky_decode():
        worker = serve.connect_decode(addr, model, params, slots=1,
                                      max_len=40, kv_codec="f32")
        # Ingest blocks but never report, then die with them in flight.
        worker.serve(max_blocks=1)
        worker.close()

    def healthy_decode():
        worker = serve.connect_decode(addr, model, params, slots=1,
                                      max_len=40, kv_codec="f32")
        try:
            worker.serve()
        finally:
            worker.close()

    th_flaky = threading.Thread(target=flaky_decode, daemon=True)
    th_flaky.start()
    prefill = serve.PrefillEngine(model, params, max_len=40)
    router = serve.Router(prefill, kv_codec="f32", retain_kv=retain_kv)
    router.accept_ranks(lsock, 1)
    th_healthy = threading.Thread(target=healthy_decode, daemon=True)
    th_healthy.start()
    router.accept_ranks(lsock, 1)
    lsock.close()

    ids = [router.submit(p, n) for p, n in zip(prompts, lens)]
    results = router.run(timeout=240)
    router.shutdown()
    th_flaky.join(timeout=60)
    th_healthy.join(timeout=60)

    assert sorted(results) == sorted(ids)  # nothing lost
    for p, n, i in zip(prompts, lens, ids):
        got = results[i]
        assert len(got) == n, "truncated stream"
        np.testing.assert_array_equal(got, _oracle(model, params, p, n))
    router.close()
    assert router.stats["rank_failures"] == 1
    if retain_kv:
        assert router.stats["replays_kv"] >= 1
        assert router.stats["replays_prefill"] == 0
    else:
        assert router.stats["replays_prefill"] >= 1


def _fault_spec_decode_child(rank: int, world: int, port: int, q,
                             fault_spec: str) -> None:
    """Spawned decode rank; arms TPUNET_FAULT_SPEC before any engine
    exists when given one (the chaos 'decode-rank kill'). The armed rank
    runs one data stream so the injected close is a LAST-stream loss —
    poison, not the single-stream failover a multi-stream comm survives —
    i.e. a process-death-shaped failure."""
    try:
        import os

        if fault_spec:
            os.environ["TPUNET_FAULT_SPEC"] = fault_spec
            os.environ["TPUNET_NSTREAMS"] = "1"
        import jax as _jax  # env pinned by conftest import at module load
        import jax.numpy as _jnp  # noqa: F401

        from tpunet import serve as _serve

        model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            d_ff=64, compute_dtype=_jnp.float32)
        toks = _jax.random.randint(_jax.random.PRNGKey(0), (2, 24), 0, 64)
        params = model.init(_jax.random.PRNGKey(1), toks)["params"]
        worker = _serve.connect_decode(f"127.0.0.1:{port}", model, params,
                                       slots=2, max_len=40, kv_codec="f32")
        worker.serve(idle_timeout=120)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        # The fault-armed rank is SUPPOSED to die; report how.
        q.put((rank, f"DEAD: {type(e).__name__}"))


def test_chaos_fault_spec_decode_kill_mid_request():
    """The acceptance chaos case: a decode rank killed mid-request via the
    TPUNET_FAULT_SPEC grammar (all its transport streams close after a
    byte budget — a process-death-shaped failure) while requests are in
    flight. Every request completes via replay-from-KV on the surviving
    rank; every output is bitwise the oracle's — zero corrupted or
    truncated streams."""
    import multiprocessing as mp

    model, params = _tiny_setup()
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, 64, 8).astype(np.int32) for _ in range(6)]
    lens = [6] * 6

    lsock = serve.Router.listen("127.0.0.1:0")
    port = lsock.getsockname()[1]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    # The faulty rank's REPORT stream (its only send stream) closes after
    # 100 data bytes — past its first request's FIRST+RESULT (~92B), so it
    # dies while reporting its SECOND request: a mid-request kill with
    # work provably in flight, whatever the scheduling interleave.
    spec = "stream=*:side=send:after_bytes=100:action=close"
    procs = [
        ctx.Process(target=_fault_spec_decode_child,
                    args=(0, 2, port, q, spec)),
        ctx.Process(target=_fault_spec_decode_child,
                    args=(1, 2, port, q, "")),
    ]
    for p in procs:
        p.start()
    try:
        prefill = serve.PrefillEngine(model, params, max_len=40)
        router = serve.Router(prefill, kv_codec="f32", retain_kv=True)
        router.accept_ranks(lsock, 2, timeout=240)
        lsock.close()
        ids = [router.submit(p, n) for p, n in zip(prompts, lens)]
        results = router.run(timeout=240)
        router.shutdown()

        assert sorted(results) == sorted(ids)
        for p, n, i in zip(prompts, lens, ids):
            got = results[i]
            assert len(got) == n, "truncated stream"
            np.testing.assert_array_equal(
                got, _oracle(model, params, p, n))
        assert router.stats["rank_failures"] == 1
        assert router.stats["replays_kv"] >= 1
        statuses = {}
        for _ in range(2):
            rank, status = q.get(timeout=120)
            statuses[rank] = status
        # The armed rank died by injection; the healthy rank drained clean.
        assert statuses[0].startswith("DEAD"), statuses
        assert statuses[1] == "OK", statuses
        router.close()
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()


# ---------------------------------------------------------------------------
# submit_kv validation surface.


def test_submit_kv_validation():
    model, params = _tiny_setup()
    srv = BatchServer(model, params, slots=1, max_len=24)
    shapes = srv.kv_leaf_shapes(5)
    assert shapes == [(5, 4, 8)] * 4
    rows = [np.zeros(s, np.float32) for s in shapes]
    logits = np.zeros(64, np.float32)
    with pytest.raises(ValueError, match="KV block 0"):
        srv.submit_kv(np.arange(5, dtype=np.int32), 4,
                      [np.zeros((5, 4, 7), np.float32)] + rows[1:], logits)
    with pytest.raises(ValueError, match="last_logits"):
        srv.submit_kv(np.arange(5, dtype=np.int32), 4, rows,
                      np.zeros(63, np.float32))
    with pytest.raises(ValueError, match="exceeds max_len"):
        srv.submit_kv(np.arange(5, dtype=np.int32), 40, rows, logits)
