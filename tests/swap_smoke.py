"""CI swap-smoke lane: zero-downtime live weight updates under open-loop
load — a clean hot-swap, then a hot-swap with a rank death mid-broadcast.

Fleet: frontend (router + prefill + WeightPublisher) in THIS process, TWO
decode ranks in spawned processes, QoS DRR gate armed (256 KiB wire
window) so the bulk-class weight broadcast actually contends with the
latency-class request traffic. The benchmarks.serve_load open-loop
harness offers fixed Poisson load; the armed chaos grammar schedules BOTH
publications (``swap:at_step=N:action=publish``).

**Window 1 — clean swap (the latency claim).** Checkpoint v1 publishes
mid-window with no faults. Gates: zero failed requests, zero rejections,
the MEDIAN TTFT blip is at most ONE histogram bucket (pre-swap p50
bucket vs whole-window p50 bucket) and a loose >=75% floor on TTFTs
within the 1 s SLO — the swap must be invisible to the typical request,
and a wedged serve loop (the bug class this lane exists to catch) would
push EVERY TTFT past the SLO, not a sliver. The tail itself is not
gated: a CI box running three jax compiles concurrently during the flip
smears 0-15% of samples past 1 s on scheduler luck alone (with ~100
samples the histogram p99 IS the max), and gating it would gate on the
box, not the code.

**Window 2 — death mid-broadcast (the robustness claim).** Checkpoint v2
publishes mid-window and the publisher's pump hook SIGKILLs decode rank B
once the publisher reports the broadcast in flight (``pub.phase``). The
publisher retries and commits on the
survivor; B respawns STALE (serving v0), is picked up by router
re-admission, and is caught up to v2 by ``catch_up()``. Gates: zero
FAILED requests across the death — every ADMITTED request completes
(replays land on the survivor; the drain proves no hang) — zero CRC
mismatches anywhere, exactly one rank failure / one readmission / one
catch-up / >=1 typed retry. Typed admission rejections are LEGAL in this
window (half the pool is dead and the harness is open-loop: backpressure
drops, not waits) but must stay a bounded minority of offered load; no
tail gate either — a killed rank's in-flight replays pay real recovery
latency, and pretending otherwise would gate on luck.

Fleet-wide postconditions: ``tpunet_weight_version`` reads v2 on EVERY
rank (frontend in-process, both decode tiers by /metrics scrape —
including the respawned one); the bulk class moved nonzero broadcast
bytes while the latency class's p99 queue wait stayed within the 100 ms
bucket; ``swap_pending() == 0`` (the armed script ran to completion).

Run: python tests/swap_smoke.py   (exit 0 = pass)
"""

import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Ephemeral /metrics in every process (children re-run this top level), CPU
# pin before any jax import, and the QoS gate armed so class accounting +
# queue-wait histograms are live while weights broadcast under load.
os.environ["TPUNET_METRICS_PORT"] = "0"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TPUNET_QOS_INFLIGHT_BYTES"] = "wire=256K"
os.environ["TPUNET_QOS_WEIGHTS"] = "latency=8,bulk=1"
# Arm the native progress watchdog + aggressive keepalive (the churn
# lane's settings): a SIGKILLed peer must surface TYPED in every blocked
# collective — the survivor's mid-swap receive pump included — instead
# of parking a serve loop until RST delivery. Without this, the
# survivor can miss every retry announce and the publication dies on
# bootstrap timeouts.
os.environ["TPUNET_PROGRESS_TIMEOUT_MS"] = "10000"
os.environ["TPUNET_KEEPALIVE_IDLE_S"] = "3"
os.environ["TPUNET_KEEPALIVE_INTVL_S"] = "2"
os.environ["TPUNET_KEEPALIVE_CNT"] = "2"

import numpy as np  # noqa: E402

SLOTS = 4
BUCKETS = (8, 16, 32)
MAX_NEW = 8
MAX_LEN = BUCKETS[-1] + MAX_NEW
KV_CODEC = "int8"
WINDOW_S = 12.0
RATE_RPS = 6.0
SWAP_AT_S = 3.0
SWAP_CHUNK = 8192       # small chunks -> several pump interleaves per attempt
TTFT_SLO_OK = 0.75      # window 1: loose floor on TTFTs within the 1 s SLO
P99_WAIT_BUDGET_US = 100_000


def _model_and_params(seed: int):
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from tpunet.models import Transformer

    model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, compute_dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    params = model.init(jax.random.PRNGKey(seed), toks)["params"]
    return model, params


def _decode_child(name: str, addr: str, port_q, stop_ev) -> None:
    try:
        from tpunet import serve, telemetry

        model, params = _model_and_params(seed=1)  # every child starts on v0
        worker = serve.connect_decode(addr, model, params, slots=SLOTS,
                                      max_len=MAX_LEN, kv_codec=KV_CODEC)
        port_q.put(("port", name, telemetry.metrics_port()))
        worker.serve()
        stop_ev.wait(timeout=240)  # hold the /metrics listener for scraping
        port_q.put(("done", name, worker.stats))
    except Exception as e:  # noqa: BLE001
        port_q.put(("error", name, f"{type(e).__name__}: {e}"))


def _scrape_series(text: str, family: str) -> dict:
    from tpunet import telemetry

    out = {}
    for line in text.splitlines():
        m = telemetry._LINE.match(line)
        if m and m.group(1) == family:
            lab = telemetry.labels(tuple((m.group(2) or "").split(",")))
            out[tuple(sorted(lab.items()))] = float(m.group(3))
    return out


def _scrape_one(text: str, family: str, **want) -> float:
    vals = [v for k, v in _scrape_series(text, family).items()
            if all((lk, lv) in k for lk, lv in want.items())]
    assert vals, f"{family} {want} absent from scrape"
    return sum(vals)


def _bucket_index(bounds, value: float) -> int:
    """Index of the histogram bucket a quantile landed in (inf -> past the
    last bound) — the unit the p99-blip gate is stated in."""
    for i, (le, _) in enumerate(bounds):
        if value <= le:
            return i
    return len(bounds)


def main() -> int:
    from benchmarks.serve_load import hist_quantile, run_load
    from tpunet import _native, serve, telemetry, transport
    from tpunet.serve import publish

    model, params_v0 = _model_and_params(seed=1)
    _, params_v1 = _model_and_params(seed=2)
    _, params_v2 = _model_and_params(seed=3)

    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]
    ctx = mp.get_context("spawn")
    port_q = ctx.Queue()
    stop_ev = ctx.Event()
    children = {
        name: ctx.Process(target=_decode_child,
                          args=(name, addr, port_q, stop_ev))
        for name in ("A", "B")
    }
    for child in children.values():
        child.start()
    try:
        prefill = serve.PrefillEngine(model, params_v0, max_len=MAX_LEN)
        router = serve.Router(prefill, kv_codec=KV_CODEC)
        router.accept_ranks(lsock, 2)
        router.enable_readmission(lsock)  # the killed rank rejoins here
        ports = {}
        for _ in range(2):
            kind, name, val = port_q.get(timeout=120)
            assert kind == "port", (name, val)
            ports[name] = val

        pub = serve.WeightPublisher(router, chunk_bytes=SWAP_CHUNK)
        # The chaos grammar schedules BOTH publications (step 1 -> window
        # 1's clean swap, step 2 -> window 2's kill swap); the completeness
        # gate at the end is swap_pending() == 0.
        lib = _native.load()
        _native.check(
            lib.tpunet_c_fault_inject(
                b"swap:at_step=1:action=publish;"
                b"swap:at_step=2:action=publish"),
            "inject")

        # Warm every prompt-length bucket on BOTH tiers, then reset so the
        # measured window starts clean.
        for b in BUCKETS:
            for _ in range(2):
                router.submit(np.zeros(b, np.int32), 2)
        router.run(timeout=240)
        telemetry.reset()
        print("swap_smoke: warmup done", flush=True)

        # ---- Window 1: clean hot-swap v0 -> v1 under load ----------------
        w1 = {"pre": None, "published": False}

        def on_tick_clean(elapsed: float, pump) -> None:
            if (not w1["published"] and elapsed >= SWAP_AT_S
                    and publish.swap_action(1) == "publish"):
                w1["pre"] = telemetry.histogram_buckets("tpunet_req_ttft_us")
                w1["published"] = True
                pub.publish(1, params_v1, pump=pump, warm_lengths=BUCKETS)

        res1 = run_load(router, duration_s=WINDOW_S, rate=RATE_RPS,
                        vocab=64, buckets=BUCKETS, new_range=(2, MAX_NEW),
                        session_prob=0.25, seed=11, on_tick=on_tick_clean)
        assert w1["published"], "scripted clean publish never fired"
        assert res1["failed"] == 0, res1
        assert res1["rejected"] == 0, res1
        assert res1["completed"] == res1["offered"] > 0, res1

        # Gate: clean-swap MEDIAN TTFT blip bounded by ONE histogram
        # bucket, plus the loose >=75% SLO floor (wedged-loop detector).
        post = telemetry.histogram_buckets("tpunet_req_ttft_us")
        pre_idx = _bucket_index(w1["pre"], hist_quantile(w1["pre"], 0.50))
        post_idx = _bucket_index(post, hist_quantile(post, 0.50))
        blip = post_idx - pre_idx
        assert blip <= 1, \
            f"clean-swap p50 TTFT blew {blip} buckets ({w1['pre']} -> {post})"
        assert res1["ttft_ok_frac"] >= TTFT_SLO_OK, res1
        assert router.version == 1, router.version
        print(f"swap_smoke: window 1 (clean swap) done: {res1}", flush=True)

        # ---- Window 2: hot-swap v1 -> v2 with rank B killed mid-broadcast
        w2 = {"published": False, "respawned": False, "caught": False,
              "killed": False}

        def pump_kill(pump):
            def inner():
                # Deterministic mid-transfer death: the first pump that
                # sees the publisher's broadcast in flight (past the
                # rendezvous — a kill DURING it would just time out the
                # bootstrap) SIGKILLs rank B.
                if (not w2["killed"]
                        and pub.phase in ("broadcast", "verify")
                        and children["B"].is_alive()):
                    w2["killed"] = True
                    children["B"].kill()  # decode rank death MID-BROADCAST
                pump()
            return inner

        def on_tick_kill(elapsed: float, pump) -> None:
            if (not w2["published"] and elapsed >= SWAP_AT_S
                    and publish.swap_action(2) == "publish"):
                w2["published"] = True
                pub.publish(2, params_v2, pump=pump_kill(pump),
                            warm_lengths=BUCKETS)
            elif w2["published"] and not w2["respawned"]:
                w2["respawned"] = True
                children["B2"] = ctx.Process(
                    target=_decode_child, args=("B2", addr, port_q, stop_ev))
                children["B2"].start()  # rejoins STALE: HELLO says v0
            elif w2["respawned"] and not w2["caught"]:
                router.poll_admissions(raise_on_mismatch=False)
                if router.stats["readmissions"] >= 1:
                    assert pub.catch_up(pump=pump) == 1
                    w2["caught"] = True

        res2 = run_load(router, duration_s=WINDOW_S, rate=RATE_RPS,
                        vocab=64, buckets=BUCKETS, new_range=(2, MAX_NEW),
                        session_prob=0.25, seed=13, on_tick=on_tick_kill)
        print(f"swap_smoke: window 2 (kill mid-broadcast) done: {res2} "
              f"caught={w2['caught']}", flush=True)

        # The spawn is slow on a loaded CI box: if the window closed before
        # the rejoin/catch-up landed, finish it now — the gates below still
        # prove the full kill -> readmit -> catch-up arc.
        deadline = time.monotonic() + 120
        while not w2["caught"] and time.monotonic() < deadline:
            router.poll_admissions(raise_on_mismatch=False)
            router.poll()
            if router.stats["readmissions"] >= 1:
                assert pub.catch_up(pump=router.poll) == 1
                w2["caught"] = True
            time.sleep(0.05)
        assert w2["published"], "scripted kill publish never fired"
        assert w2["caught"], "killed rank never rejoined / caught up"
        print("swap_smoke: stale rank caught up, scraping fleet", flush=True)
        kind, name, b2_port = port_q.get(timeout=120)
        assert kind == "port" and name == "B2", (kind, name, b2_port)
        ports["B2"] = b2_port

        # Gate: the swap and the rank death never cost an ADMITTED request
        # (the completed drain inside run_load already proved no hang).
        # Open-loop backpressure rejections are legal while half the pool
        # is dead — typed, counted, and bounded — never silent drops.
        assert res2["failed"] == 0, res2
        assert res2["completed"] > 0, res2
        assert res2["completed"] == res2["offered"] - res2["rejected"], res2
        assert res2["rejected"] * 2 < res2["offered"], res2

        # Gate: v2 live on EVERY rank — frontend in-process, both decode
        # tiers (survivor AND the respawned stale rank) by scrape.
        m = telemetry.metrics()
        assert next(iter(m["tpunet_weight_version"].values())) == 2, \
            "frontend gauge is not v2"
        scrapes = {name: telemetry.scrape(port=ports[name])
                   for name in ("A", "B2")}
        for name, text in scrapes.items():
            got = _scrape_one(text, "tpunet_weight_version")
            assert got == 2, f"rank {name} serves version {got}, want 2"

        # Gate: weight bytes rode the BULK class (tx at the publisher, rx
        # at the surviving receiver) while the latency class's p99 queue
        # wait stayed in budget under the armed DRR gate.
        bulk_tx = sum(v for k, v in m["tpunet_qos_bytes_total"].items()
                      if ("class", "bulk") in
                      tuple(sorted(telemetry.labels(k).items()))
                      and telemetry.labels(k).get("dir") == "tx")
        assert bulk_tx > 0, "publisher moved no bulk-class bytes"
        assert _scrape_one(scrapes["A"], "tpunet_qos_bytes_total",
                           **{"class": "bulk", "dir": "rx"}) > 0, \
            "survivor received no bulk-class bytes"
        lat_wait = [(float("inf") if lab.get("le") in ("+Inf", "Inf")
                     else float(lab["le"]), int(v))
                    for k, v in m.get(
                        "tpunet_qos_queue_wait_us_bucket", {}).items()
                    if (lab := telemetry.labels(k)).get("class") == "latency"]
        lat_wait = sorted(
            {le: c for le, c in sorted(lat_wait)}.items())
        assert lat_wait and lat_wait[-1][1] > 0, \
            "latency queue-wait histogram is empty"
        assert hist_quantile(lat_wait, 0.99) <= P99_WAIT_BUDGET_US, \
            f"latency p99 queue wait {hist_quantile(lat_wait, 0.99)}us"

        # Gate: zero CRC mismatches anywhere; the failure arc is exactly
        # one death, >=1 typed retry, one readmission, one catch-up; and
        # the armed script ran to completion.
        mism = sum(v for k, v in m["tpunet_swap_events_total"].items()
                   if telemetry.labels(k).get("kind") == "mismatch")
        assert mism == 0, f"{mism} CRC mismatches on the frontend"
        for name, text in scrapes.items():
            assert _scrape_one(text, "tpunet_swap_events_total",
                               kind="mismatch") == 0, \
                f"rank {name} saw a CRC mismatch"
        assert router.stats["rank_failures"] == 1, router.stats
        assert router.stats["readmissions"] == 1, router.stats
        assert pub.stats["retries"] >= 1, pub.stats
        assert pub.stats["catch_ups"] == 1, pub.stats
        assert router.version == 2
        assert publish.swap_pending() == 0, "armed swap script incomplete"

        router.shutdown()
        stop_ev.set()
        done = {}
        for _ in range(2):  # A and B2 report; killed B never does
            kind, name, payload = port_q.get(timeout=120)
            assert kind == "done", (name, payload)
            done[name] = payload
        assert done["A"]["swaps"] == 2, done   # flipped v1 AND v2
        assert done["B2"]["swaps"] == 1, done  # caught up straight to v2
        print(f"swap_smoke OK: {res1['completed']}+{res2['completed']} "
              f"requests, 0 failed, clean-swap p50 blip {blip} bucket(s), "
              f"ttft_ok={res1['ttft_ok_frac']}, v2 on 3/3 ranks, "
              f"bulk_tx={int(bulk_tx)}B, retries={pub.stats['retries']}, "
              f"decode_stats={done}")
        return 0
    finally:
        transport.fault_clear()
        stop_ev.set()
        for child in children.values():
            child.join(timeout=30)
            if child.is_alive():
                child.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
