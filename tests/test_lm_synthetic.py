"""Smoke test for the Transformer tokens/s benchmark."""

from benchmarks.lm_synthetic import _parse, run_benchmark


def test_single_process_tiny():
    args = _parse(
        [
            "--d-model", "32", "--layers", "1", "--heads", "2", "--vocab", "64",
            "--seq", "32", "--batch-size", "2", "--iters", "2",
            "--batches-per-iter", "1", "--warmup", "1", "--no-bf16",
        ]
    )
    rates = run_benchmark(args, emit=lambda *_: None)
    assert len(rates) == 2
    assert all(r > 0 for r in rates)


def test_z_loss_increases_loss_and_matches_across_paths():
    """z_loss adds z*mean(lse^2) on BOTH the plain and fused paths — the
    two must agree to rounding, and the term must be visible."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    model = Transformer(vocab=96, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                        compute_dtype=jnp.float32)
    tx = optax.sgd(1e-2)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 96)
    labels = jnp.roll(toks, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(1), toks, tx)

    losses = {}
    for name, kw in [("plain", {}), ("plain_z", {"z_loss": 1e-2}),
                     ("fused_z", {"z_loss": 1e-2, "fused_xent_block": 32})]:
        step = make_train_step(model, tx, donate=False, **kw)
        _, loss = step(state, toks, labels, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    assert losses["plain_z"] > losses["plain"]
    np.testing.assert_allclose(losses["fused_z"], losses["plain_z"],
                               rtol=1e-5)


def test_single_process_moe_top2():
    args = _parse(
        [
            "--d-model", "32", "--layers", "2", "--heads", "2", "--vocab", "64",
            "--seq", "32", "--batch-size", "2", "--iters", "2",
            "--batches-per-iter", "1", "--warmup", "1", "--no-bf16",
            "--experts", "4", "--moe-top-k", "2",
        ]
    )
    rates = run_benchmark(args, emit=lambda *_: None)
    assert len(rates) == 2 and all(r > 0 for r in rates)
