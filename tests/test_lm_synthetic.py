"""Smoke test for the Transformer tokens/s benchmark."""

from benchmarks.lm_synthetic import _parse, run_benchmark


def test_single_process_tiny():
    args = _parse(
        [
            "--d-model", "32", "--layers", "1", "--heads", "2", "--vocab", "64",
            "--seq", "32", "--batch-size", "2", "--iters", "2",
            "--batches-per-iter", "1", "--warmup", "1", "--no-bf16",
        ]
    )
    rates = run_benchmark(args, emit=lambda *_: None)
    assert len(rates) == 2
    assert all(r > 0 for r in rates)
