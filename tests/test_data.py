"""Input-pipeline tests: .bin packing, windowing, dp-sharded batching,
host->device prefetch."""

import numpy as np
import pytest

from tpunet.data import TokenDataset, pack_documents, prefetch_to_device, token_batches


@pytest.fixture()
def bin_path(tmp_path):
    path = str(tmp_path / "toks.bin")
    docs = [list(range(1, 8)), list(range(10, 14)), list(range(20, 30))]
    total = pack_documents(iter(docs), path, vocab=64, eos_id=0)
    assert total == 7 + 4 + 10 + 3  # + one eos per doc
    return path


def test_pack_and_window_layout(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    # Flat stream: 1..7,0,10..13,0,20..29,0 -> 24 tokens -> 5 windows of 4+1.
    assert ds.n_windows == 5
    np.testing.assert_array_equal(ds.window(0), [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(ds.window(1), [5, 6, 7, 0, 10])
    inputs, labels = ds.batch(np.array([0, 1]))
    np.testing.assert_array_equal(inputs, [[1, 2, 3, 4], [5, 6, 7, 0]])
    np.testing.assert_array_equal(labels, [[2, 3, 4, 5], [6, 7, 0, 10]])


def test_pack_rejects_out_of_vocab(tmp_path):
    with pytest.raises(ValueError, match="outside"):
        pack_documents(iter([[70]]), str(tmp_path / "bad.bin"), vocab=64)


def test_pack_rejects_ids_that_would_wrap_in_storage_dtype(tmp_path):
    # vocab 60000 selects uint16 storage; 70000 would wrap to 4464 and pass
    # a post-cast check. The range check must run on the un-cast values.
    with pytest.raises(ValueError, match="outside"):
        pack_documents(iter([[70000]]), str(tmp_path / "w.bin"), vocab=60000)
    with pytest.raises(ValueError, match="outside"):
        pack_documents(iter([[-1]]), str(tmp_path / "n.bin"), vocab=60000)
    with pytest.raises(ValueError, match="eos_id"):
        pack_documents(iter([[1]]), str(tmp_path / "e.bin"), vocab=64, eos_id=64)


def test_dp_sharded_batches_disjoint_and_covering(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)  # 5 windows
    seen = []
    for rank in range(2):
        for inputs, labels in token_batches(
            ds, batch=1, rank=rank, world=2, seed=7, epochs=1
        ):
            assert inputs.shape == (1, 4) and labels.shape == (1, 4)
            seen.append(inputs[0].tolist())
    # 2 ranks x 2 batches of 1 = 4 of the 5 windows, all distinct.
    assert len(seen) == 4
    assert len({tuple(r) for r in seen}) == 4


def test_batches_deterministic_from_seed(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    a = [x[0].tolist() for x in token_batches(ds, 2, seed=3, epochs=2)]
    b = [x[0].tolist() for x in token_batches(ds, 2, seed=3, epochs=2)]
    assert a == b
    c = [x[0].tolist() for x in token_batches(ds, 2, seed=4, epochs=2)]
    assert a != c


def test_epochs_reshuffle(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    per_epoch = [x[0].tolist() for x in token_batches(ds, 2, seed=0, epochs=2)]
    assert len(per_epoch) == 4  # 2 per epoch (5 windows // batch 2)
    assert per_epoch[:2] != per_epoch[2:]  # epoch feeds the permutation


def test_prefetch_matches_plain_iteration(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    plain = list(token_batches(ds, 1, seed=1, epochs=2))
    pre = list(prefetch_to_device(token_batches(ds, 1, seed=1, epochs=2), size=2))
    assert len(pre) == len(plain)
    for (pi, pl), (qi, ql) in zip(plain, pre):
        np.testing.assert_array_equal(pi, np.asarray(qi))
        np.testing.assert_array_equal(pl, np.asarray(ql))
    # device-resident output
    assert hasattr(pre[0][0], "devices")


def test_prefetch_abandoned_consumer_releases_worker(bin_path):
    import threading
    import time

    ds = TokenDataset(bin_path, seq=4, vocab=64)
    # Infinite source, tiny queue: without the stop signal the worker would
    # block forever on the full queue after the consumer walks away.
    it = prefetch_to_device(token_batches(ds, 1, seed=1), size=1)
    next(it)
    before = {t.name for t in threading.enumerate()}
    assert any(n.startswith("tpunet-prefetch") for n in before)
    it.close()  # GeneratorExit -> finally -> stop + drain
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        alive = [
            t for t in threading.enumerate()
            if t.name.startswith("tpunet-prefetch") and t.is_alive()
        ]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive


def test_prefetch_propagates_source_errors():
    def bad():
        yield np.zeros((2, 2))
        raise RuntimeError("loader exploded")

    it = prefetch_to_device(bad(), size=1)
    next(it)
    with pytest.raises(RuntimeError, match="loader exploded"):
        next(it)


def test_prefetch_with_sharding(bin_path):
    import jax
    from tpunet.parallel import batch_sharding, make_named_mesh

    mesh = make_named_mesh({"dp": 2})
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    out = list(
        prefetch_to_device(
            token_batches(ds, 2, seed=1, epochs=1),
            size=2,
            sharding=batch_sharding(mesh),
        )
    )
    assert out
    inputs, _ = out[0]
    assert len(inputs.sharding.device_set) == 2


def test_batch_rejects_out_of_range_ids(bin_path):
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    with pytest.raises(IndexError):
        ds.batch(np.array([0, ds.n_windows]))
    with pytest.raises(IndexError):
        ds.batch(np.array([-1]))
    with pytest.raises(ValueError):
        ds.batch(np.array([[0, 1]]))  # not 1-D


def test_batch_matches_per_window_gather(bin_path):
    # The vectorized fancy-index gather must agree with window() row by row.
    ds = TokenDataset(bin_path, seq=4, vocab=64)
    idx = np.array([3, 0, 2])
    inputs, labels = ds.batch(idx)
    for row, i in enumerate(idx):
        w = ds.window(int(i))
        np.testing.assert_array_equal(inputs[row], w[:-1])
        np.testing.assert_array_equal(labels[row], w[1:])


def test_byte_tokenizer_roundtrip_and_packing(tmp_path):
    """Lossless on arbitrary UTF-8, specials above the byte range, and the
    full text -> pack_documents -> TokenDataset -> decode loop closes."""
    from tpunet.data import ByteTokenizer, TokenDataset, pack_documents

    tok = ByteTokenizer()
    texts = ["hello world", "ünïcödé 漢字 🙂", ""]
    for t in texts:
        assert tok.decode(tok.encode(t)) == t
    ids = tok.encode("hi", eos=True)
    assert ids.tolist() == [104, 105, tok.eos_id]
    assert tok.decode(ids) == "hi"  # specials dropped on decode
    bos = ByteTokenizer(add_bos=True).encode("a")
    assert bos.tolist() == [256, 97]
    # Out-of-range ids (a sampler under a larger model vocab) are dropped.
    assert tok.decode(np.asarray([300, 104, -1, 105])) == "hi"

    path = str(tmp_path / "corpus.bin")
    n = pack_documents((tok.encode(t) for t in texts if t), path,
                       vocab=tok.vocab, eos_id=tok.eos_id)
    ds = TokenDataset(path, seq=8, vocab=tok.vocab)
    # window(i) is (seq+1,) with a one-token label overlap — drop it when
    # reassembling the stream.
    flat = np.concatenate([ds.window(i)[:-1] for i in range(ds.n_windows)])
    assert n >= flat.size
    text = tok.decode(flat)
    assert "hello world" in text and "漢字" in text
