"""Schedule suite: ring vs recursive halving-doubling vs binomial tree.

Cross-algorithm BIT-equality: the test data is integer-valued f32, so every
summation order is exact — any byte difference between schedules is an
indexing/offset bug, never float noise. W in {2, 3, 4, 8} covers the rhd
power-of-2 fast path, the non-power-of-2 fold-in (W=3), and the
acceptance-scale world (W=8). The codec lane checks the documented error
bounds, cross-rank bit-identity (encoded atoms forward verbatim on every
schedule), and the EXACT wire-byte ratios (0.500x bf16 / 0.25390625x int8)
by the native codec counters. The dispatch lane pins the auto-selector's
counter-verified step budget — the tentpole perf claim: small-message
AllReduce at W=8 in <= 6 wire rounds vs the ring's 14 — and the
TPUNET_DISPATCH_TABLE override path.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np
import pytest

from conftest import run_spawn_workers

# One comm per schedule, sequential, on coordinator port+offset (the
# bootstrap frees its listener right after wiring, so offsets never clash).
_ALGOS = ("ring", "rhd", "tree")


def _int_valued(rank: int, n: int) -> np.ndarray:
    """Integer-valued f32: exact under any summation order."""
    rng = np.random.default_rng(1234 + rank)
    return rng.integers(-50, 50, size=n).astype(np.float32)


def _equality_worker(rank: int, world: int, port: int, q, env) -> None:
    try:
        for k, v in env.items():
            os.environ[k] = v
        from tpunet.collectives import Communicator

        n = 40_003  # odd on purpose: uneven slices/halves/atoms
        mine = _int_valued(rank, n)
        expect = sum(_int_valued(r, n) for r in range(world))
        results = {}
        for ai, algo in enumerate(_ALGOS):
            with Communicator(f"127.0.0.1:{port + ai}", rank, world,
                              algo=algo) as comm:
                got = comm.all_reduce(mine, "sum")
                np.testing.assert_array_equal(got, expect)  # exact, so also
                results[algo] = got.tobytes()               # cross-rank equal
                # i64 rides the same schedules (no codec, 8-byte elements).
                got_i = comm.all_reduce(mine.astype(np.int64), "sum")
                np.testing.assert_array_equal(got_i, expect.astype(np.int64))
                # max exercises a non-sum op through every reduce path.
                got_m = comm.all_reduce(mine, "max")
                np.testing.assert_array_equal(
                    got_m, np.max([_int_valued(r, n) for r in range(world)], axis=0))
        assert results["ring"] == results["rhd"], "ring vs rhd bytes differ"
        assert results["ring"] == results["tree"], "ring vs tree bytes differ"
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [2, 3, 4, 8])
def test_cross_algo_bit_equality(world):
    # W=8 spawns 8 ranks each wiring a 7-peer mesh; single-stream comms keep
    # the fd/thread bill sane on the CI box without changing any byte moved.
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"}
    run_spawn_workers(_equality_worker, world, extra_args=(env,))


# ---------------------------------------------------------------------------
# Codec x schedule: error bounds, cross-rank bit-identity, exact wire ratios.

# count chosen so every halving segment and tree payload stays a multiple of
# the int8 scale block (256): the per-hop encodings then tile exactly and the
# wire-byte ratio is EXACTLY n_wire/n_payload with zero padding slack.
_CODEC_COUNT = 65_536
_RATIO = {"bf16": 0.5, "int8": (_CODEC_COUNT + 4 * (_CODEC_COUNT // 256)) /
          (4.0 * _CODEC_COUNT)}


def _codec_worker(rank: int, world: int, port: int, q, codec, algo) -> None:
    try:
        os.environ["TPUNET_NSTREAMS"] = "1"
        os.environ["TPUNET_ASYNC_CHANNELS"] = "1"
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        n = _CODEC_COUNT
        mine = (_int_valued(rank, n) / 8.0).astype(np.float32)
        expect = sum((_int_valued(r, n) / 8.0).astype(np.float32)
                     for r in range(world))
        with Communicator(f"127.0.0.1:{port}", rank, world,
                          wire_dtype=codec, algo=algo) as comm:
            comm.all_reduce(mine, "sum")  # warmup: mesh wiring + scratch
            comm.barrier()
            telemetry.reset()
            got = comm.all_reduce(mine, "sum")
            m = telemetry.metrics()
            ratio = next(iter(m.get("tpunet_codec_wire_ratio", {}).values()))
        # Documented per-hop bounds: bf16 RNE <= amax*2^-8, int8 <=
        # amax/254, over <= log2(W)+1 quantizations; values are <= ~50, so
        # 0.5 covers both with margin while catching any indexing bug.
        np.testing.assert_allclose(got, expect, atol=0.5)
        # The wire-byte ratio is EXACT on every schedule (CI-gated claim):
        # every f32 hop ships encoded, block-aligned frames. 1e-6 is the
        # exposition's own print precision (%.6f), not a real tolerance.
        assert abs(ratio - _RATIO[codec]) < 1e-6, \
            f"{algo}/{codec} wire ratio {ratio} != {_RATIO[codec]}"
        q.put((rank, ("OK", zlib.crc32(got.tobytes()))))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", 0)))


@pytest.mark.parametrize("algo", ["rhd", "tree"])
@pytest.mark.parametrize("codec", ["bf16", "int8"])
def test_codec_schedules_bounded_and_bit_identical(codec, algo):
    import multiprocessing as mp

    from conftest import free_port

    world = 4  # power of two: every rank decodes the same encoded atoms
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_codec_worker, args=(r, world, port, q, codec, algo))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=150)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world
    for rank, (status, _) in results.items():
        assert status == "OK", f"rank {rank}: {status}"
    crcs = {crc for _, crc in results.values()}
    assert len(crcs) == 1, \
        f"{algo}/{codec} results differ across ranks: {results}"


# ---------------------------------------------------------------------------
# Auto-selector: counter-verified step budget + dispatch-table override.


def _steps_worker(rank: int, world: int, port: int, q, nbytes, env,
                  expect_algo) -> None:
    try:
        os.environ["TPUNET_NSTREAMS"] = "1"
        os.environ["TPUNET_ASYNC_CHANNELS"] = "1"
        for k, v in env.items():
            os.environ[k] = v
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        n = nbytes // 4
        arr = np.full(n, float(rank + 1), np.float32)
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            comm.all_reduce(arr, "sum")  # warmup: wires mesh + quiesce
            comm.barrier()
            telemetry.reset()
            got = comm.all_reduce(arr, "sum")
            m = telemetry.metrics()
        assert got[0] == sum(r + 1 for r in range(world))
        # All series emit (including hier.intra/hier.inter at zero) — build
        # the dict from the exposition instead of a fixed key set.
        steps = {}
        for key, v in m.get("tpunet_coll_steps_total", {}).items():
            algo = telemetry.labels(key)["algo"]
            steps[algo] = steps.get(algo, 0) + int(v)
        selected = {}
        for key, v in m.get("tpunet_coll_algo_selected_total", {}).items():
            ld = telemetry.labels(key)
            if ld["coll"] == "allreduce":
                selected[ld["algo"]] = int(v)
        q.put((rank, ("OK", steps, selected, expect_algo)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", {}, {}, expect_algo)))


def _run_steps_case(world, nbytes, env, expect_algo, max_steps):
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_steps_worker,
                         args=(r, world, port, q, nbytes, env, expect_algo))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=150)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world
    for rank, (status, steps, selected, _) in results.items():
        assert status == "OK", f"rank {rank}: {status}"
        # The selector must have kept the measured allreduce OFF the ring...
        assert steps["ring"] == 0, f"rank {rank} ran ring steps: {steps}"
        # ...and the resolved schedule within the log-depth step budget.
        assert 1 <= steps[expect_algo] <= max_steps, f"rank {rank}: {steps}"
        assert selected.get(expect_algo, 0) >= 1, f"rank {rank}: {selected}"


def test_auto_selector_small_message_step_budget():
    """THE acceptance gate: a 4 KiB AllReduce at W=8 under algo=auto runs
    <= 6 wire rounds (binomial tree; the ring would take 14), proven by
    tpunet_coll_steps_total — the counter carries the claim, not GB/s."""
    _run_steps_case(world=8, nbytes=4096, env={}, expect_algo="tree",
                    max_steps=6)


def test_auto_selector_medium_message_uses_rhd():
    """64 KiB at W=8 lands in the halving-doubling band: 2*log2(8) = 6
    rounds, still under the <= 6 budget the ISSUE pins for <= 64 KiB."""
    _run_steps_case(world=8, nbytes=64 * 1024, env={}, expect_algo="rhd",
                    max_steps=6)


def test_dispatch_table_overrides_builtins(tmp_path):
    """A TPUNET_DISPATCH_TABLE entry re-routes a size the built-ins would
    give to the ring (W=2 defaults to ring for everything): the table wins,
    counter-verified."""
    table = {"version": 1, "entries": [
        {"coll": "allreduce", "world": 2, "max_bytes": 1 << 20, "algo": "tree"},
    ]}
    path = tmp_path / "dispatch.json"
    path.write_text(json.dumps(table))
    _run_steps_case(world=2, nbytes=4096,
                    env={"TPUNET_DISPATCH_TABLE": str(path)},
                    expect_algo="tree", max_steps=2)


def _mismatch_worker(rank: int, world: int, port: int, q) -> None:
    try:
        from tpunet import _native
        from tpunet.collectives import Communicator

        try:
            Communicator(f"127.0.0.1:{port}", rank, world,
                         algo="tree" if rank == 0 else "ring")
            q.put((rank, "FAIL: mismatch accepted"))
        except _native.NativeError as e:
            q.put((rank, f"TYPED code={e.code}" if "algo mismatch" in str(e)
                   else f"FAIL: wrong error {e}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_algo_mismatch_fails_every_rank_typed():
    """Ranks pinned to different schedules would deadlock mid-collective;
    the wiring handshake fails BOTH ranks with a typed error instead."""
    import multiprocessing as mp

    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_mismatch_worker, args=(r, 2, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, status = q.get(timeout=60)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    for rank, status in results.items():
        assert status.startswith("TYPED"), f"rank {rank}: {status}"


def test_unknown_algo_rejected_before_any_socket():
    from tpunet import _native
    from tpunet.collectives import Communicator

    with pytest.raises(_native.NativeError, match="unknown algo"):
        Communicator("127.0.0.1:1", 0, 1, algo="star")


# ---------------------------------------------------------------------------
# Hierarchical two-level schedule: W=4 as 2 fake hosts x 2 ranks
# (TPUNET_HOST_ID override), intra stages over SHM, inter stage over TCP.
# The counters carry the acceptance claim: per-rank DCN (TCP) wire bytes
# under hier <= 0.55x the flat ring's, results byte-identical to the ring
# oracle on every rank.


def _hier_worker(rank: int, world: int, port: int, q, algo, codec, n) -> None:
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_SHM": "1",
            # 2 ranks per fake "host": hosts [0, 0, 1, 1].
            "TPUNET_HOST_ID": f"fakehost{rank // 2}",
        })
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        mine = _int_valued(rank, n)
        if codec != "f32":
            mine = (mine / 8.0).astype(np.float32)
        with Communicator(f"127.0.0.1:{port}", rank, world,
                          wire_dtype=codec, algo=algo) as comm:
            comm.all_reduce(mine, "sum")  # warmup: wires SHM rings + mesh
            comm.barrier()
            telemetry.reset()
            got = comm.all_reduce(mine, "sum")
            m = telemetry.metrics()
        steps = {}
        for key, v in m.get("tpunet_coll_steps_total", {}).items():
            lab = telemetry.labels(key)["algo"]
            steps[lab] = steps.get(lab, 0) + int(v)
        # Per-rank DCN proxy: TCP tx bytes (all classes) — the SHM counters
        # are deliberately a separate family, so this split is exact.
        tcp_tx = sum(int(v) for key, v in
                     m.get("tpunet_qos_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        shm_tx = sum(int(v) for key, v in
                     m.get("tpunet_shm_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        q.put((rank, ("OK", steps, tcp_tx, shm_tx, zlib.crc32(got.tobytes()),
                      got.tobytes() if rank == 0 else b"")))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",)))


def _run_hier_case(algo, codec, n):
    import multiprocessing as mp

    from conftest import free_port

    world = 4
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_hier_worker,
                         args=(r, world, port, q, algo, codec, n))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=180)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world
    for rank, status in results.items():
        assert status[0] == "OK", f"rank {rank}: {status[0]}"
    return results


def test_hier_cuts_dcn_bytes_and_matches_ring_oracle():
    """THE acceptance gate: at W=4 (2 fake hosts x 2 ranks), every rank's
    DCN (TCP) wire bytes under hier are <= 0.55x the flat ring's per-rank
    bytes, the intra stages moved through SHM (nonzero tpunet_shm_bytes),
    and results are byte-identical to the ring oracle on all ranks."""
    n = 1 << 18  # 1 MiB payload
    ring = _run_hier_case("ring", "f32", n)
    # algo=AUTO here doubles as the built-in auto-upgrade gate: a large
    # AllReduce on a >= 2-host uniform topology must resolve to hier with
    # no pinning (ApplyHierPolicy) — the step asserts below prove it ran.
    hier = _run_hier_case("auto", "f32", n)
    # Flat ring: every rank ships 2(W-1)/W * S to its next hop; with hosts
    # [0,0,1,1] the cross-host hops (ranks 1 and 3) are the DCN bytes.
    ring_dcn = max(status[2] for status in ring.values())
    assert ring_dcn >= int(1.4 * n * 4), ring_dcn  # ~1.5x S on crossers
    # Integer-valued f32: exact under any summation order, so hier is
    # byte-identical to the ring oracle (and across all ranks).
    assert len({s[4] for s in ring.values()} | {s[4] for s in hier.values()}) == 1
    for rank, status in hier.items():
        _, steps, tcp_tx, shm_tx, _, _ = status
        assert tcp_tx <= 0.55 * ring_dcn, \
            f"rank {rank}: hier DCN bytes {tcp_tx} vs ring {ring_dcn}"
        assert shm_tx > 0, f"rank {rank}: intra stage moved no SHM bytes"
        assert steps.get("hier.inter", 0) >= 1, steps
        assert steps.get("hier.intra", 0) >= 1, steps
        assert steps.get("ring", 0) == 0, steps


@pytest.mark.parametrize("codec", ["bf16", "int8"])
def test_hier_codec_bounded_and_bit_identical(codec):
    """Codec x hier: the inter (DCN) stage ships encoded with f32
    accumulation and verbatim-forwarded encoded segments, so results stay
    bit-identical across all 4 ranks and inside the documented error bound
    (values <= ~50/8; bf16 RNE + int8 amax/254 over <= H quantizations)."""
    results = _run_hier_case("hier", codec, _CODEC_COUNT)
    crcs = {s[4] for s in results.values()}
    assert len(crcs) == 1, f"hier/{codec} results differ across ranks"
    got = np.frombuffer(results[0][5], np.float32)
    expect = sum((_int_valued(r, _CODEC_COUNT) / 8.0).astype(np.float32)
                 for r in range(4))
    np.testing.assert_allclose(got, expect, atol=0.5)


def test_hier_on_flat_topology_runs_ring():
    """hier pinned on a single-host (flat) topology degrades to the ring —
    the counter records what RAN, and results stay correct."""
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
           "TPUNET_ALGO": "hier"}
    run_spawn_workers(_flat_hier_worker, 2, extra_args=(env,))


def _flat_hier_worker(rank: int, world: int, port: int, q, env) -> None:
    try:
        for k, v in env.items():
            os.environ[k] = v
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        arr = np.full(1024, float(rank + 1), np.float32)
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            telemetry.reset()
            got = comm.all_reduce(arr, "sum")
            m = telemetry.metrics()
        assert got[0] == sum(r + 1 for r in range(world))
        steps = {telemetry.labels(k)["algo"]: int(v)
                 for k, v in m.get("tpunet_coll_steps_total", {}).items()}
        assert steps.get("ring", 0) >= 1, steps
        assert steps.get("hier.inter", 0) == 0, steps
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_config_registers_schedule_knobs(monkeypatch, tmp_path):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_ALGO", "rhd")
    assert Config.from_env().algo == "rhd"
    monkeypatch.setenv("TPUNET_ALGO", "mesh")
    with pytest.raises(ValueError, match="TPUNET_ALGO"):
        Config.from_env()
    monkeypatch.setenv("TPUNET_ALGO", "auto")
    monkeypatch.setenv("TPUNET_DISPATCH_TABLE", str(tmp_path / "missing.json"))
    with pytest.raises(ValueError, match="TPUNET_DISPATCH_TABLE"):
        Config.from_env()
    ok = tmp_path / "ok.json"
    ok.write_text('{"version": 1, "entries": []}')
    monkeypatch.setenv("TPUNET_DISPATCH_TABLE", str(ok))
    assert Config.from_env().dispatch_table == str(ok)
