"""Zero-copy hot path tests (vectored wire I/O + SIMD reduce).

Three pins:
  * cross-engine interop matrix — BASIC/EPOLL senders and receivers in every
    combination, CRC on and off, stay byte-exact: the vectored senders (one
    sendmsg per [payload | crc trailer] chunk; iovec-cursor batching on
    EPOLL) changed SYSCALL shape, not wire bytes, so v3 peers interop.
  * golden frame capture — a raw-socket receiver captures exactly what each
    engine's sender puts on the wire for one message and asserts it is
    byte-identical to the segmented layout (preamble, 8-byte BE ctrl length
    frame, payload, 4-byte BE CRC32C trailer) AND identical across engines.
  * SIMD-vs-scalar reduce goldens — the AVX2 kernels must be bitwise equal
    to the scalar ground truth for f32 (all ops, NaN/inf payloads included)
    and bf16 (round-to-nearest-even), and the fork-join sharding must not
    change results.
"""

from __future__ import annotations

import os
import socket
import struct
import threading

import numpy as np
import pytest

from tpunet import transport

HANDLE_SIZE = 64


def _wire_pair(net_s, net_r):
    lc = net_r.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = net_s.connect(lc.handle)
    th.join()
    return sc, got["rc"], lc


def _pattern(n: int, salt: int = 0) -> np.ndarray:
    return np.frombuffer(
        bytes(((i * 131 + salt) ^ (i >> 8)) & 0xFF for i in range(min(n, 4096)))
        * (n // min(n, 4096) + 1),
        np.uint8,
    )[:n].copy()


# ---------------------------------------------------------------------------
# Cross-engine interop matrix.


@pytest.mark.parametrize("crc", [False, True], ids=["crc0", "crc1"])
@pytest.mark.parametrize("recv_engine", ["BASIC", "EPOLL"])
@pytest.mark.parametrize("send_engine", ["BASIC", "EPOLL"])
def test_cross_engine_interop_matrix(monkeypatch, send_engine, recv_engine, crc):
    """Every (sender, receiver, CRC) combination transfers byte-exact,
    including a multi-chunk message — the shared wire contract survives the
    vectored-IO rewrite on both engines."""
    from tpunet.transport import Net

    # The CRC flag is the SENDER's to advertise (preamble kPreambleFlagCrc);
    # set it for both instances anyway so the intent is unambiguous.
    monkeypatch.setenv("TPUNET_CRC", "1" if crc else "0")
    monkeypatch.setenv("TPUNET_NSTREAMS", "2")
    monkeypatch.setenv("TPUNET_IMPLEMENT", send_engine)
    ns = Net()
    monkeypatch.setenv("TPUNET_IMPLEMENT", recv_engine)
    nr = Net()
    try:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            # 1 B (single chunk), 64 KiB (single chunk), 3 MiB (multi-chunk
            # at nstreams=2 / min_chunksize=1MiB).
            for salt, size in enumerate((1, 1 << 16, 3 << 20)):
                src = _pattern(size, salt)
                dst = np.zeros_like(src)
                rreq = rc.irecv(dst)
                sreq = sc.isend(src)
                sreq.wait(timeout=60)
                assert rreq.wait(timeout=60) == size
                np.testing.assert_array_equal(src, dst)
        finally:
            for c in (sc, rc, lc):
                c.close()
    finally:
        ns.close()
        nr.close()


# ---------------------------------------------------------------------------
# Golden frame capture: the vectored sender's wire bytes, observed raw.


def _handle_for(port: int) -> bytes:
    """A rendezvous handle (raw sockaddr_in, zero-padded to 64B) pointing at
    a 127.0.0.1 port this test controls."""
    sa = (
        struct.pack("=H", socket.AF_INET)
        + struct.pack("!H", port)
        + socket.inet_aton("127.0.0.1")
    )
    return sa + b"\x00" * (HANDLE_SIZE - len(sa))


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise AssertionError(f"peer closed after {len(buf)}/{n} bytes")
        buf += got
    return buf


def _capture_one_send(monkeypatch, engine: str, crc: bool, payload: bytes) -> dict:
    """Accept an engine's connect bundle on a raw socket, let it isend one
    message, and return the captured preamble fields + ctrl frame + data
    stream bytes."""
    monkeypatch.setenv("TPUNET_IMPLEMENT", engine)
    monkeypatch.setenv("TPUNET_CRC", "1" if crc else "0")
    monkeypatch.setenv("TPUNET_NSTREAMS", "1")  # all chunks on stream 0, in order
    from tpunet.transport import Net

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]
    net = Net()
    out = {}
    try:
        sc = net.connect(_handle_for(port))
        conns = {}
        ctrl = None
        for _ in range(2):  # nstreams=1 data conns + 1 ctrl conn
            c, _addr = srv.accept()
            pre = _read_exact(c, 48)
            magic, _bundle, sid, nstreams, _mcs, flags = struct.unpack("!6Q", pre)
            assert magic >> 8 == 0x7470756E65743103 >> 8  # "tpunet" + v3
            if sid == nstreams:
                ctrl = c
            else:
                conns[sid] = c
        assert ctrl is not None and 0 in conns
        out["flags"] = flags

        req = sc.isend(np.frombuffer(payload, np.uint8))
        frame = _read_exact(ctrl, 8)
        out["frame"] = frame
        (length,) = struct.unpack("!Q", frame)
        assert length == len(payload)
        out["data"] = _read_exact(conns[0], length + (4 if crc else 0))
        req.wait(timeout=30)
        # Nothing may trail the chunk: re-fragmentation aside, the sender
        # must not interleave any extra framing on the data stream.
        conns[0].settimeout(0.2)
        try:
            extra = conns[0].recv(64)
        except socket.timeout:
            extra = b""
        assert extra == b""
        sc.close()
        for c in (ctrl, *conns.values()):
            c.close()
    finally:
        net.close()
        srv.close()
    return out


@pytest.mark.parametrize("crc", [False, True], ids=["crc0", "crc1"])
def test_golden_frame_capture_sender_bytes(monkeypatch, crc):
    """Both engines' vectored senders put EXACTLY the segmented layout on the
    wire: [payload] or [payload || crc32c_be(payload)] on the data stream and
    a bare 8-byte BE length frame on ctrl — and are byte-identical to each
    other."""
    payload = bytes(_pattern(96 * 1024, salt=7))
    caps = {eng: _capture_one_send(monkeypatch, eng, crc, payload)
            for eng in ("BASIC", "EPOLL")}
    expect = payload + (
        struct.pack("!I", transport.crc32c(payload)) if crc else b""
    )
    for eng, cap in caps.items():
        assert cap["frame"] == struct.pack("!Q", len(payload)), eng
        assert cap["data"] == expect, f"{eng} wire bytes diverge from golden"
        assert (cap["flags"] & 1) == (1 if crc else 0), eng
    assert caps["BASIC"]["data"] == caps["EPOLL"]["data"]
    assert caps["BASIC"]["frame"] == caps["EPOLL"]["frame"]


# ---------------------------------------------------------------------------
# SIMD-vs-scalar reduce equivalence goldens.


def _f32_scalar_ref(a: np.ndarray, b: np.ndarray, op: str) -> np.ndarray:
    """Bitwise replication of the native SCALAR kernel for f32: IEEE
    elementwise sum/prod; min/max via the (b<a)?b:a / (a<b)?b:a ternaries
    (NaN in either operand -> comparison false -> a survives)."""
    with np.errstate(invalid="ignore", over="ignore"):
        if op == "sum":
            return a + b
        if op == "prod":
            return a * b
        if op == "min":
            return np.where(b < a, b, a)
        if op == "max":
            return np.where(a < b, b, a)
    raise AssertionError(op)


def _bf16_to_f32(u: np.ndarray) -> np.ndarray:
    return (u.astype(np.uint32) << 16).view(np.float32)


def _f32_to_bf16(f: np.ndarray) -> np.ndarray:
    """The native kernel's RNE: bits + 0x7FFF + ((bits >> 16) & 1), high
    half (mod 2^32, like the C uint32_t arithmetic)."""
    bits = f.view(np.uint32).astype(np.uint64)
    rounded = (bits + 0x7FFF + ((bits >> 16) & 1)) & 0xFFFFFFFF
    return (rounded >> 16).astype(np.uint16)


def _f32_cases(rng) -> list[np.ndarray]:
    n = 4099  # odd: exercises the SIMD tail
    a = rng.standard_normal(n).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    for arr in (a, b):
        arr[rng.integers(0, n, 64)] = np.nan
        arr[rng.integers(0, n, 64)] = np.inf
        arr[rng.integers(0, n, 64)] = -np.inf
        arr[rng.integers(0, n, 64)] = -0.0
    return [a, b]


@pytest.mark.parametrize("op", ["sum", "prod", "min", "max"])
def test_reduce_f32_matches_scalar_golden(op):
    """Native reduce (SIMD path where the CPU has AVX2) is BITWISE equal to
    the scalar ground truth on f32, NaN/inf/-0.0 payloads included."""
    a, b = _f32_cases(np.random.default_rng(20260804))
    dst = np.empty_like(a)
    transport.reduce_into(dst, a, b, "f32", op)
    expect = _f32_scalar_ref(a, b, op)
    np.testing.assert_array_equal(dst.view(np.uint32), expect.view(np.uint32))


@pytest.mark.parametrize("op", ["sum", "prod", "min", "max"])
def test_reduce_bf16_matches_scalar_golden(op):
    """bf16 reduce: widen to f32, op with scalar semantics, RNE-narrow —
    bitwise, including NaN/inf encodings."""
    rng = np.random.default_rng(42)
    n = 2053
    a = rng.integers(0, 1 << 16, n).astype(np.uint16)
    b = rng.integers(0, 1 << 16, n).astype(np.uint16)
    dst = np.empty_like(a)
    transport.reduce_into(dst, a, b, "bf16", op)
    expect = _f32_to_bf16(_f32_scalar_ref(_bf16_to_f32(a), _bf16_to_f32(b), op))
    np.testing.assert_array_equal(dst, expect)


def test_reduce_inplace_alias_and_other_dtypes():
    """dst aliasing a (the ring's in-place accumulate) works; the non-SIMD
    dtypes route through the scalar kernel correctly."""
    a = np.arange(1000, dtype=np.int32)
    b = np.arange(1000, dtype=np.int32)[::-1].copy()
    transport.reduce_into(a, a, b, "i32", "sum")
    np.testing.assert_array_equal(a, np.full(1000, 999, np.int32))
    x = np.arange(17, dtype=np.float64)
    y = np.arange(17, dtype=np.float64)[::-1].copy()
    d = np.empty_like(x)
    transport.reduce_into(d, x, y, "f64", "max")
    np.testing.assert_array_equal(d, np.maximum(x, y))
    u = np.arange(256, dtype=np.uint8)
    v = np.full(256, 7, np.uint8)
    transport.reduce_into(u, u, v, "u8", "min")
    np.testing.assert_array_equal(u, np.minimum(np.arange(256), 7).astype(np.uint8))


def _threaded_reduce_worker(q) -> None:
    try:
        import numpy as np

        from tpunet import transport as t

        rng = np.random.default_rng(7)
        n = (6 << 20) // 4  # 6 MiB of f32: above the 4 MiB fan-out threshold
        a = rng.standard_normal(n).astype(np.float32)
        b = rng.standard_normal(n).astype(np.float32)
        a[:17] = np.nan
        dst = np.empty_like(a)
        t.reduce_into(dst, a, b, "f32", "sum")
        np.testing.assert_array_equal(
            dst.view(np.uint32), (a + b).view(np.uint32))
        q.put(("ok", None))
    except Exception as e:  # noqa: BLE001
        q.put(("err", repr(e)))


def test_reduce_threaded_sharding_equivalent():
    """TPUNET_REDUCE_THREADS=4 fork-join sharding produces the same bits as
    the elementwise reference on a >4 MiB buffer (spawned so the env is read
    at pool construction)."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_threaded_reduce_worker, args=(q,))
    env_before = os.environ.get("TPUNET_REDUCE_THREADS")
    os.environ["TPUNET_REDUCE_THREADS"] = "4"
    try:
        p.start()
        tag, detail = q.get(timeout=120)
    finally:
        p.join(timeout=30)
        if p.is_alive():
            p.kill()
        if env_before is None:
            os.environ.pop("TPUNET_REDUCE_THREADS", None)
        else:
            os.environ["TPUNET_REDUCE_THREADS"] = env_before
    assert tag == "ok", detail


def test_reduce_rejects_bad_args():
    a = np.zeros(4, np.float32)
    with pytest.raises(ValueError):
        transport.reduce_into(a, a, a, "f16")
    with pytest.raises(ValueError):
        transport.reduce_into(a, a, a, "f32", "avg")
    with pytest.raises(ValueError):
        transport.reduce_into(a, a, np.zeros(5, np.float32), "f32")


# ---------------------------------------------------------------------------
# Syscall counters: the budget the perf-smoke lane enforces exists and moves.


def test_engine_syscall_counters_move_and_reset():
    from tpunet import telemetry
    from tpunet.transport import Net

    telemetry.reset()
    parsed = telemetry.metrics().get("tpunet_engine_syscalls_total", {})
    # All four op series present even at zero (derivations never divide by a
    # missing series).
    assert len(parsed) == 4
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            src = _pattern(1 << 20)
            dst = np.zeros_like(src)
            rreq = rc.irecv(dst)
            sreq = sc.isend(src)
            sreq.wait(timeout=60)
            rreq.wait(timeout=60)
            np.testing.assert_array_equal(src, dst)
        finally:
            for c in (sc, rc, lc):
                c.close()
    moved = sum(telemetry.metrics().get("tpunet_engine_syscalls_total", {}).values())
    assert moved > 0
    telemetry.reset()
    assert sum(
        telemetry.metrics().get("tpunet_engine_syscalls_total", {}).values()) == 0
