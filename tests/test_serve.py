"""Continuous batching: per-row cache parity and the slot server.

Ground truth for every server output is single-sequence `generate()` on
the same prompt with the same params — a slot's tokens must not depend on
what the other slots are doing (different lengths, refills, garbage
decoding in idle rows).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.models import BatchServer, Transformer, generate


def _tiny(**kw):
    kw.setdefault("vocab", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    return Transformer(**kw)


def _setup(**kw):
    model = _tiny(**kw)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, model.vocab)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    return model, params


def _oracle(model, params, prompt, n, **kw):
    out = generate(model, params, jnp.asarray(prompt)[None], n, **kw)
    return np.asarray(out)[0, len(prompt):]


def test_per_row_cache_matches_scalar_when_aligned():
    """With every row at the same offset, the per-row path is the scalar
    path with a broadcast index — same cache contents, same logits."""
    from tpunet.models.generate import init_cache

    model, params = _setup()
    toks = jax.random.randint(jax.random.PRNGKey(2), (3, 10), 0, 64)
    scalar = model.clone(decode=True)
    perrow = model.clone(decode=True, per_row_cache=True)
    c1 = init_cache(scalar, 3, 16)
    c2 = init_cache(perrow, 3, 16)
    l1, m1 = scalar.apply({"params": params, "cache": c1}, toks,
                          mutable=["cache"])
    l2, m2 = perrow.apply({"params": params, "cache": c2}, toks,
                          mutable=["cache"])
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    k1 = m1["cache"]["block0"]["attn"]["cached_key"]
    k2 = m2["cache"]["block0"]["attn"]["cached_key"]
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))
    assert m2["cache"]["block0"]["attn"]["cache_index"].shape == (3,)


@pytest.mark.parametrize("steps_per_call", [1, 4, 16])
def test_server_matches_generate_mixed_lengths(steps_per_call):
    """Slots running DIFFERENT prompt lengths concurrently each reproduce
    their own single-sequence generate() output — at every window size
    (steps_per_call coarsens scheduling granularity, never tokens)."""
    model, params = _setup(n_kv_heads=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, n).astype(np.int32)
               for n in (5, 9, 13)]
    srv = BatchServer(model, params, slots=3, max_len=40,
                      steps_per_call=steps_per_call)
    ids = [srv.submit(p, 8) for p in prompts]
    results = srv.run()
    assert sorted(results) == sorted(ids)
    for p, i in zip(prompts, ids):
        np.testing.assert_array_equal(results[i], _oracle(model, params, p, 8))


def test_server_slot_refill_more_requests_than_slots():
    """6 requests through 2 slots: refills reuse dead rows (stale K/V
    above the new frontier, stale index reset) and every output still
    matches its oracle."""
    model, params = _setup()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 64, 4 + (i % 3)).astype(np.int32)
               for i in range(6)]
    lens = [6, 3, 9, 4, 7, 5]
    srv = BatchServer(model, params, slots=2, max_len=24)
    ids = [srv.submit(p, n) for p, n in zip(prompts, lens)]
    results = srv.run()
    assert sorted(results) == sorted(ids)
    for p, n, i in zip(prompts, lens, ids):
        np.testing.assert_array_equal(results[i], _oracle(model, params, p, n))


def test_server_eos_frees_slot_early():
    """A request hitting eos retires immediately (possibly at its very
    first, prefill-sampled token) and its output matches the eos-pinned
    oracle up to its own length."""
    model, params = _setup()
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 64, 6).astype(np.int32) for _ in range(4)]
    eos = 7
    srv = BatchServer(model, params, slots=2, max_len=24, eos_id=eos)
    ids = [srv.submit(p, 10) for p in prompts]
    results = srv.run()
    for p, i in zip(prompts, ids):
        want = _oracle(model, params, p, 10, eos_id=eos)
        got = results[i]
        assert len(got) <= 10
        np.testing.assert_array_equal(got, want[:len(got)])
        if len(got) < 10:
            assert got[-1] == eos  # early retirement only ever at eos


def test_server_sampled_rows_are_independent():
    """Sampling mode smoke: outputs are in-vocab and each request
    completes at its requested length."""
    model, params = _setup()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 64, 5).astype(np.int32) for _ in range(3)]
    srv = BatchServer(model, params, slots=2, max_len=24, temperature=0.9,
                      top_k=8, rng=jax.random.PRNGKey(9))
    ids = [srv.submit(p, 6) for p in prompts]
    results = srv.run()
    for i in ids:
        assert results[i].shape == (6,)
        assert ((results[i] >= 0) & (results[i] < 64)).all()


def test_run_returns_requests_finished_at_prefill():
    """max_new=1 retires during submit()'s prefill; run() must still
    return it (the done buffer drains even with nothing live)."""
    model, params = _setup()
    p = np.random.default_rng(5).integers(0, 64, 6).astype(np.int32)
    srv = BatchServer(model, params, slots=1, max_len=16)
    rid = srv.submit(p, 1)
    results = srv.run()
    np.testing.assert_array_equal(results[rid], _oracle(model, params, p, 1))


def test_serve_bench_cli(capsys):
    # --reps 1: the median/IQR code path is identical at any reps;
    # 7 interleaved passes would add CI time with no assertion power.
    from benchmarks.serve_bench import main as bench_main

    bench_main(["--requests", "4", "--slots", "2", "--prompt", "8",
                "--new-min", "2", "--new-max", "6", "--steps-per-call", "4",
                "--d", "32", "--layers", "1", "--heads", "2", "--ff", "64",
                "--vocab", "64", "--reps", "1"])
    import json

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["serve_tok_s"] > 0 and out["lockstep_tok_s"] > 0
    assert out["serve_micro_steps"] > 0
    assert out["sched_win"] > 0


def test_server_validation():
    model, params = _setup()
    srv = BatchServer(model, params, slots=1, max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(np.zeros(10, np.int32), 10)
    with pytest.raises(ValueError, match="1-D"):
        srv.submit(np.zeros((2, 3), np.int32), 2)
    with pytest.raises(ValueError, match="slots"):
        BatchServer(model, params, slots=0, max_len=16)
    with pytest.raises(ValueError, match="dense model"):
        BatchServer(_tiny(n_experts=2), params, slots=1, max_len=16)


def test_server_composes_with_quant_and_window():
    """BatchServer x int8 weights x GQA x sliding window: each slot still
    reproduces its own single-sequence quantized generate()."""
    from tpunet.models import quantize_params

    model = _tiny(n_kv_heads=2, attn_window=10, weight_quant="int8")
    _, params = _setup(n_kv_heads=2, attn_window=10)
    qp = quantize_params(params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 64, n).astype(np.int32) for n in (6, 11)]
    srv = BatchServer(model, qp, slots=2, max_len=32, steps_per_call=4)
    ids = [srv.submit(p, 7) for p in prompts]
    results = srv.run()
    for p, i in zip(prompts, ids):
        np.testing.assert_array_equal(results[i], _oracle(model, qp, p, 7))


def test_text_in_text_out_end_to_end(tmp_path):
    """The whole stack on raw text: ByteTokenizer -> pack_documents ->
    TokenDataset -> fit() (loss drops) -> BatchServer serves a learned
    byte continuation of a repeating corpus."""
    import optax

    from tpunet.data import ByteTokenizer, TokenDataset, pack_documents
    from tpunet.train import create_train_state, fit, make_train_step

    tok = ByteTokenizer()
    path = str(tmp_path / "corpus.bin")
    pack_documents([tok.encode("abcdefgh" * 200)], path, vocab=tok.vocab)
    ds = TokenDataset(path, seq=16, vocab=tok.vocab)
    model = _tiny(vocab=tok.vocab, d_model=48)
    inputs, _ = ds.batch(np.arange(4))
    state, _ = create_train_state(
        model, jax.random.PRNGKey(0), jnp.asarray(inputs), optax.adam(3e-3))
    step = make_train_step(model, optax.adam(3e-3))

    def batches():
        rng = np.random.default_rng(0)
        while True:
            x, y = ds.batch(rng.choice(ds.n_windows, 4))
            yield jnp.asarray(x), jnp.asarray(y)

    losses = []
    state = fit(state, step, batches(), steps=150,
                log_every=150, log_fn=lambda rec: losses.append(rec))
    assert losses and losses[-1]["loss"] < 0.6  # learned the cycle

    srv = BatchServer(model, state.params, slots=2, max_len=40)
    rid = srv.submit(tok.encode("abcdefghabc"), 8)
    out = srv.run()[rid]
    assert tok.decode(out) == "defghabc"  # exact byte continuation


def test_run_pipeline_and_coalesce_match_default():
    # pipeline>=2 (in-flight windows + dispatch-time occupancy snapshots +
    # deferred prefill tokens) and refill_coalesce>1 (held refills) must
    # not change greedy outputs — each request's tokens depend only on its
    # own prefix. This is the parity the chip serve step (pipeline=2)
    # leans on.
    model, params = _setup()
    prompts = [np.arange(1, 7 + i) % 50 for i in range(5)]
    news = [3, 9, 5, 12, 1]

    def serve(pipeline, coalesce):
        srv = BatchServer(model, params, slots=2, max_len=24,
                          temperature=0.0, steps_per_call=4,
                          refill_coalesce=coalesce)
        ids = [srv.submit(p, n) for p, n in zip(prompts, news)]
        res = srv.run(pipeline=pipeline)
        return [res[i].tolist() for i in ids]

    base = serve(1, 1)
    assert serve(2, 1) == base
    assert serve(3, 1) == base
    assert serve(1, 2) == base
    assert serve(2, 2) == base


def test_run_pipeline_with_eos_matches_default():
    model, params = _setup()
    eos = 7
    prompts = [np.arange(2, 8), np.arange(3, 9), np.arange(1, 7)]

    def serve(pipeline):
        srv = BatchServer(model, params, slots=2, max_len=30,
                          temperature=0.0, steps_per_call=4, eos_id=eos,
                          refill_coalesce=pipeline)  # exercise both knobs
        ids = [srv.submit(p, 12) for p in prompts]
        res = srv.run(pipeline=pipeline)
        return [res[i].tolist() for i in ids]

    base = serve(1)
    out2 = serve(2)
    assert out2 == base
    for toks in base:
        assert eos not in toks[:-1]  # nothing after a (possible) eos


# --- speculative continuous batching (round 5) ----------------------------
# BatchServer(draft_model=...) turns each decode window into speculative
# rounds: draft gamma, verify in one target forward, commit each row's OWN
# accepted prefix. Exactness oracle: greedy tokens must equal generate()'s
# per request, whatever the draft proposes.


def _spec_srv(model, params, draft, dparams, reqs, **kw):
    srv = BatchServer(model, params, draft_model=draft, draft_params=dparams,
                      **kw)
    ids = [srv.submit(p, n) for p, n in reqs]
    return srv, ids, srv.run()


@pytest.mark.parametrize("steps_per_call,pipeline", [
    (1, 1),
    # Multi-round windows exercise the per-round absorb loop and the
    # mid-window retirement break; pipeline=2 exercises in-flight
    # speculative windows + deferred refill tokens + the dispatch-time
    # occupancy snapshot discarding recycled rows' rounds.
    (4, 1),
    (2, 2),
])
def test_spec_server_greedy_matches_generate_mixed_lengths(
        steps_per_call, pipeline):
    model, params = _setup()
    draft = _tiny(n_layers=1)
    dparams = draft.init(jax.random.PRNGKey(9),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, 50, 5 + i % 3).astype(np.int32), n)
            for i, n in enumerate([4, 11, 6, 13, 3, 8])]
    srv = BatchServer(model, params, draft_model=draft,
                      draft_params=dparams, slots=2, max_len=24,
                      temperature=0.0, gamma=3,
                      steps_per_call=steps_per_call)
    ids = [srv.submit(p, n) for p, n in reqs]
    res = srv.run(pipeline=pipeline)
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            np.asarray(res[rid]), _oracle(model, params, p, n))
    assert srv.stats["spec_rounds"] > 0


def test_spec_server_windowed_ring_matches_generate():
    # Windowed target + draft: the server speculates on the ROLLING RING
    # cache (gamma + 1 <= window) with per-round stash/restore, and the
    # greedy outputs still match generate() exactly.
    model, params = _setup(attn_window=8)
    draft = _tiny(n_layers=1, attn_window=8)
    dparams = draft.init(jax.random.PRNGKey(9),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(1)
    reqs = [(rng.integers(0, 50, 6).astype(np.int32), n)
            for n in [5, 12, 7]]
    srv, ids, res = _spec_srv(model, params, draft, dparams, reqs,
                              slots=2, max_len=24, temperature=0.0, gamma=3)
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            np.asarray(res[rid]), _oracle(model, params, p, n))
    # ring actually backs the server cache
    assert all(leaf.shape[1] == 8 for leaf in jax.tree.leaves(srv._cache)
               if leaf.ndim == 4)


def test_spec_server_quant_self_draft_accepts_and_matches():
    # int8 self-draft: acceptance should be HIGH (the draft agrees with
    # its own fp source), so rounds commit multiple tokens — and outputs
    # stay exactly generate()'s.
    from tpunet.models import quantize_params

    model, params = _setup()
    qmodel = model.clone(weight_quant="int8")
    qparams = quantize_params(params)
    rng = np.random.default_rng(2)
    reqs = [(rng.integers(0, 50, 6).astype(np.int32), 12) for _ in range(3)]
    srv, ids, res = _spec_srv(model, params, qmodel, qparams, reqs,
                              slots=2, max_len=24, temperature=0.0, gamma=4)
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            np.asarray(res[rid]), _oracle(model, params, p, n))
    tok_per_round = (srv.stats["spec_committed"]
                     / max(srv.stats["spec_rounds"], 1))
    assert tok_per_round > 2.0, srv.stats


def test_spec_server_eos_cuts_mid_round():
    model, params = _setup()
    draft = _tiny(n_layers=1)
    dparams = draft.init(jax.random.PRNGKey(9),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    p = np.arange(2, 8).astype(np.int32)
    ref = _oracle(model, params, p, 12)
    eos = int(ref[4])  # force a mid-stream retirement
    first = int(np.nonzero(np.asarray(ref) == eos)[0][0])
    want = list(ref[:first + 1])  # cut at the FIRST occurrence
    srv = BatchServer(model, params, slots=1, max_len=24, temperature=0.0,
                      eos_id=eos, draft_model=draft, draft_params=dparams,
                      gamma=3)
    rid = srv.submit(p, 12)
    res = srv.run()
    assert list(res[rid]) == want


def test_spec_server_sampled_runs_and_validates():
    model, params = _setup()
    draft = _tiny(n_layers=1)
    dparams = draft.init(jax.random.PRNGKey(9),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    srv = BatchServer(model, params, slots=2, max_len=20, temperature=0.8,
                      top_k=8, draft_model=draft, draft_params=dparams,
                      gamma=2)
    ids = [srv.submit(np.arange(1, 7), 8) for _ in range(3)]
    res = srv.run()
    for rid in ids:
        assert res[rid].shape == (8,)
        assert ((res[rid] >= 0) & (res[rid] < model.vocab)).all()
    with pytest.raises(ValueError, match="draft_model and draft_params"):
        BatchServer(model, params, slots=1, max_len=8, draft_model=draft)
    with pytest.raises(ValueError, match="vocab"):
        BatchServer(model, params, slots=1, max_len=8,
                    draft_model=_tiny(vocab=32), draft_params=dparams)
