"""CI telemetry smoke lane (not pytest-collected — run as a script).

One process, one loopback transfer, with tracing AND the /metrics scrape
listener live from the start: asserts non-empty trace spans (valid JSON +
merge_traces output), the TCP-introspection gauges and stage histograms in
the scraped exposition, and that the exposition passes the text-format lint.

Usage: TPUNET_SMOKE_DIR=/tmp/tpunet-smoke python tests/telemetry_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading

SMOKE_DIR = os.environ.get("TPUNET_SMOKE_DIR", "/tmp/tpunet-smoke")
TRACE_DIR = os.path.join(SMOKE_DIR, "traces")
os.makedirs(TRACE_DIR, exist_ok=True)

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from conftest import free_port  # noqa: E402
from test_telemetry import _lint_exposition  # noqa: E402

SCRAPE_PORT = free_port()
# Both sinks must be configured before the native library constructs its
# telemetry singleton (first tpunet import below).
os.environ["TPUNET_TRACE_DIR"] = TRACE_DIR
os.environ["TPUNET_METRICS_PORT"] = str(SCRAPE_PORT)

import numpy as np  # noqa: E402

from tpunet import telemetry  # noqa: E402
from tpunet.transport import Net  # noqa: E402


def main() -> None:
    net = Net()
    listen = net.listen(0)
    holder: dict = {}
    t = threading.Thread(target=lambda: holder.update(rc=listen.accept()))
    t.start()
    sc = net.connect(listen.handle)
    t.join()
    rc = holder["rc"]

    data = np.arange(4 << 20, dtype=np.uint8) % 251
    buf = np.zeros(4 << 20, dtype=np.uint8)
    for _ in range(4):
        req = rc.irecv(buf)
        sc.send(data, timeout=120)
        req.wait(timeout=120)
    assert np.array_equal(buf, data), "smoke transfer corrupted"

    # Non-empty spans, valid JSON at flush, and a loadable merged timeline.
    telemetry.flush_trace()
    files = sorted(
        os.path.join(TRACE_DIR, f) for f in os.listdir(TRACE_DIR)
        if f.startswith("tpunet-trace-rank")
    )
    assert files, f"no trace files in {TRACE_DIR}"
    spans = [e for f in files for e in json.load(open(f)) if e.get("ph") == "X"]
    assert spans, "trace files contain no spans"
    merged = telemetry.merge_traces(TRACE_DIR)
    assert json.load(open(merged)), "merged trace is empty"

    # Live scrape: lint-clean exposition carrying the deep-observability
    # families this lane exists to guard.
    text = telemetry.scrape(SCRAPE_PORT)
    _lint_exposition(text)
    for needle in (
        "tpunet_stream_rtt_us",
        "tpunet_stream_fairness_jain",
        "tpunet_req_wire_us_bucket",
        "tpunet_req_queue_us_bucket",
    ):
        assert needle in text, f"scrape missing {needle}"

    sc.close()
    rc.close()
    listen.close()
    net.close()
    print(f"telemetry smoke OK: {len(files)} trace file(s), {len(spans)} spans, "
          f"scrape {len(text)}B on :{SCRAPE_PORT}")


if __name__ == "__main__":
    main()
