"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh (no TPU pod in CI) — the env
must be set before the first jax import anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
