"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh (no TPU pod in CI) — the env
must be set before the first jax import anywhere in the test process.
"""

import os

# Force, don't setdefault: the driver environment pins JAX_PLATFORMS=axon
# (the tunneled TPU), but the suite must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize hook runs at interpreter start and overrides
# jax_platforms to "axon,cpu" via jax.config.update — env alone cannot win.
# Counter-override before any backend initializes, or every jax.devices()
# call tries to bring up the TPU tunnel (and hangs the suite if it's down).
# Guarded so the non-JAX tests (transport/collectives) still run without jax.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass


import socket  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flightrec_dumps_to_tmp(tmp_path, monkeypatch):
    """Route flight-recorder verdict dumps through the test's tmp dir.

    Chaos/CRC tests trip DumpOnVerdict in the native layer, whose fallback
    dump path is the CWD — which under pytest is the repo root. The dedicated
    TPUNET_FLIGHTREC_DIR knob redirects ONLY the dump path (unlike
    TPUNET_TRACE_DIR it does not enable span tracing), and spawned worker
    processes inherit it through the env."""
    monkeypatch.setenv("TPUNET_FLIGHTREC_DIR", str(tmp_path))


def free_port() -> int:
    """Shared helper: an ephemeral 127.0.0.1 port for bootstrap coordinators."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_spawn_workers(target, world: int, timeout: float = 180.0, extra_args=()):
    """Spawn `world` processes running target(rank, world, port, queue, *extra)
    and assert every rank reports 'OK'. Shared by the multiprocess suites."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=target, args=(r, world, port, q) + tuple(extra_args))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=timeout)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert all(v == "OK" for v in results.values()), f"worker failures: {results}"
    assert len(results) == world
