"""Schedule-accounting evidence for the zigzag balance claim.

The 1-chip sandbox serializes ring ranks, so contiguous and zigzag causal
ring attention show the same wall-clock there (both layouts compute the
same total FLOPs). The claim that zigzag cuts the MULTI-chip critical path
is pure lockstep-schedule structure; these tests pin it mechanically —
under BOTH cost models (executed-dense, the wall-clock one; useful-FLOPs,
the idealized one) — and bind the accounting to the mode function the
real kernels branch on.
"""

import jax.numpy as jnp
import pytest

from benchmarks.cp_balance import (chunk_flops, compare, layout_chunks,
                                   step_work, summarize)

WORLDS = [2, 4, 8, 16]


@pytest.mark.parametrize("world", WORLDS)
def test_flops_total_is_layout_invariant(world):
    """Both layouts compute the same causal mask — identical useful FLOPs
    (2W^2 chunk-units: C(2W,2) full pairs + 2W half-diagonals). The
    layouts differ only in who does the work when."""
    cont = summarize(world, "contiguous", "flops")
    zig = summarize(world, "zigzag", "flops")
    assert cont["total_units"] == zig["total_units"] == 2.0 * world * world


@pytest.mark.parametrize("world", WORLDS)
@pytest.mark.parametrize("cost", ["executed", "flops"])
def test_zigzag_is_balanced(world, cost):
    """Useful FLOPs: exactly 2.0 units per rank per step (the four-case
    table in zigzag_attention.py's docstring) — the slowest rank IS the
    mean rank. Executed-dense: the same plus ONE extra unit on each rank's
    own diagonal step (both diagonal chunk-blocks dispatch dense), so rank
    totals are all 2W+1 — balanced to within that single unit."""
    per_step = step_work(world, "zigzag", cost)
    if cost == "flops":
        assert all(u == 2.0 for row in per_step for u in row)
    else:
        for i, row in enumerate(per_step):
            # Rank i holds its own shard at step t=0 (src == my).
            assert row[0] == 3.0
            assert all(u == 2.0 for u in row[1:])
    zig = summarize(world, "zigzag", cost)
    assert zig["slowest_over_mean"] == 1.0


@pytest.mark.parametrize("world", WORLDS)
def test_contiguous_concentrates_on_the_last_rank(world):
    """No rank skips its own diagonal step, so executed totals are
    4, 8, ... 4W (the kernel dispatches the diagonal shard-block dense);
    useful-FLOP totals are 2, 6, ... 4W-2 (half the diagonal is masked).
    Either way the last rank does ~W times the first rank's work — the
    imbalance the zigzag layout exists to fix."""
    cont_x = summarize(world, "contiguous", "executed")
    assert cont_x["rank_work_units"] == [4.0 * (i + 1) for i in range(world)]
    cont_f = summarize(world, "contiguous", "flops")
    assert cont_f["rank_work_units"] == [4.0 * i + 2.0 for i in range(world)]


@pytest.mark.parametrize("world", WORLDS)
def test_critical_path_cut(world):
    """Executed-dense (wall-clock-proportional): contiguous pays a dense
    shard-block every step = 4W units; zigzag pays 2W+1. The cut
    4W/(2W+1) is what a multi-chip wall-clock A/B of THESE kernels would
    measure: 1.6x at W=2, 1.78x at W=4, 2x from below as W grows.
    Useful-FLOPs (idealized diagonal kernel): (4W-2)/2W = 2 - 1/W."""
    cx = compare(world, "executed")
    assert cx["contiguous"]["critical_path_units"] == 4.0 * world
    assert cx["zigzag"]["critical_path_units"] == 2.0 * world + 1.0
    assert cx["critical_path_cut"] == pytest.approx(
        4.0 * world / (2.0 * world + 1.0), abs=1e-4)
    cf = compare(world, "flops")
    assert cf["contiguous"]["critical_path_units"] == 4.0 * world - 2.0
    assert cf["zigzag"]["critical_path_units"] == 2.0 * world
    assert cf["critical_path_cut"] == pytest.approx(2.0 - 1.0 / world)


@pytest.mark.parametrize("world", WORLDS)
def test_contiguous_accounting_matches_kernel_mode_function(world):
    """Bind the accounting to the code: at shard granularity the
    contiguous schedule dispatches exactly what
    ring_attention.causal_block_mode selects — and the executed cost is
    the dispatch structure itself (full and diag BOTH run the dense
    shard-block: 4 chunk-units; only skip runs nothing), while the
    useful-FLOP cost halves the diagonal (full=4, diag=2, skip=0)."""
    from tpunet.parallel.ring_attention import causal_block_mode

    per_step_x = step_work(world, "contiguous", "executed")
    per_step_f = step_work(world, "contiguous", "flops")
    executed_units = {0: 4.0, 1: 4.0, 2: 0.0}
    flops_units = {0: 4.0, 1: 2.0, 2: 0.0}
    for i in range(world):
        for t in range(world):
            s = (i - t) % world
            mode = int(causal_block_mode(jnp.int32(s), jnp.int32(i)))
            assert per_step_x[i][t] == executed_units[mode]
            assert per_step_f[i][t] == flops_units[mode]


@pytest.mark.parametrize("world", WORLDS)
def test_zigzag_static_skip_case(world):
    """The a_lo x b_hi quadrant NEVER computes (zigzag_attention.py's
    trace-time skip): rank i's early chunk vs any held late chunk is
    always fully in the future."""
    chunks = layout_chunks(world, "zigzag")
    for i in range(world):
        a_lo = chunks[i][0]
        for s in range(world):
            b_hi = chunks[s][1]
            assert chunk_flops(a_lo, b_hi) == 0.0


def test_layout_chunks_match_zigzag_order():
    """The accounting's chunk assignment is the real layout: pairs (i,
    2W-1-i) in exactly zigzag_chunk_order's interleaving."""
    from tpunet.parallel.zigzag_attention import zigzag_chunk_order

    for world in WORLDS:
        flat = [c for pair in layout_chunks(world, "zigzag") for c in pair]
        assert flat == zigzag_chunk_order(world)


def test_cli_prints_one_json_line(capsys):
    import json

    from benchmarks.cp_balance import main

    main(["--worlds", "4"])
    out = json.loads(capsys.readouterr().out.strip())
    by = {(c["cost"], c["world"]): c for c in out["comparisons"]}
    assert by[("executed", 4)]["critical_path_cut"] == pytest.approx(16 / 9,
                                                                     abs=1e-4)
    assert by[("flops", 4)]["critical_path_cut"] == 1.75
