"""Intra-host shared-memory transport (TPUNET_SHM=1, cpp/src/shm_engine.cc).

Host-locality unit tests (host-id derivation, the TPUNET_HOST_ID fake-host
override, Config knob registration), 2-process SHM loopback transfers with
counter proof that the payload rode the ring segment and ZERO TCP data
bytes, and the forced-split paths (TPUNET_SHM=0 / mismatched fake hosts)
falling back to TCP transparently.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import free_port  # noqa: F401  (shared harness import path)

SWEEP = [0, 8, 777, 1 << 20, (1 << 24) + 13]  # wrap-exercising sizes
SWEEP_SMALL = [0, 8, 777, 1 << 20]  # routing-proof lanes skip the wrap size


def _host_id_in_subprocess(env: dict) -> int:
    """HostId() as seen by a fresh process (the id is cached per process, so
    override tests need isolation)."""
    code = (
        "from tpunet import _native; lib = _native.load(); "
        "print(lib.tpunet_c_host_id())"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env},
        capture_output=True, text=True, check=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
    )
    return int(out.stdout.strip().splitlines()[-1])


def test_host_id_stable_and_nonzero():
    """Derivation: boot-id/hostname hash — stable across processes on one
    box, never zero (0 would read as 'no identity' in the handshake)."""
    env = {"TPUNET_HOST_ID": ""}
    a = _host_id_in_subprocess(env)
    b = _host_id_in_subprocess(env)
    assert a != 0
    assert a == b, "host id must be identical for two processes on one host"


def test_host_id_override_splits_hosts():
    """TPUNET_HOST_ID is the fake-host knob: different strings hash to
    different ids (testable multi-'host' topologies on one box), equal
    strings to equal ids, and any override differs from the natural id."""
    natural = _host_id_in_subprocess({"TPUNET_HOST_ID": ""})
    ha = _host_id_in_subprocess({"TPUNET_HOST_ID": "hostA"})
    ha2 = _host_id_in_subprocess({"TPUNET_HOST_ID": "hostA"})
    hb = _host_id_in_subprocess({"TPUNET_HOST_ID": "hostB"})
    assert ha == ha2
    assert ha != hb
    assert ha != natural and hb != natural
    assert ha != 0 and hb != 0


def test_config_registers_shm_knobs(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_SHM", "1")
    monkeypatch.setenv("TPUNET_HOST_ID", "boxA")
    monkeypatch.setenv("TPUNET_SHM_RING_BYTES", str(1 << 20))
    cfg = Config.from_env()
    assert cfg.shm is True
    assert cfg.host_id == "boxA"
    assert cfg.shm_ring_bytes == 1 << 20
    # Range validation names the offending var (PR-1 validator stance).
    monkeypatch.setenv("TPUNET_SHM_RING_BYTES", "1024")  # < 64K floor
    with pytest.raises(ValueError, match="TPUNET_SHM_RING_BYTES"):
        Config.from_env()
    monkeypatch.setenv("TPUNET_SHM_RING_BYTES", str(1 << 31))  # > 1G cap
    with pytest.raises(ValueError, match="TPUNET_SHM_RING_BYTES"):
        Config.from_env()


# ---------------------------------------------------------------------------
# 2-process loopback transfers.


def _receiver(conn, env: dict, sizes: list) -> None:
    os.environ.update(env)
    from tpunet import telemetry
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(bytes(listen.handle))
    rc = listen.accept()
    ok = True
    for i, size in enumerate(sizes):
        buf = np.zeros(size + 64, dtype=np.uint8)  # oversized on purpose
        got = rc.recv(buf, timeout=60)
        exp = np.arange(size, dtype=np.uint64).astype(np.uint8)
        if got != size or not np.array_equal(buf[:size], exp):
            ok = False
            break
    m = telemetry.metrics()
    shm_rx = sum(int(v) for k, v in m.get("tpunet_shm_bytes_total", {}).items()
                 if telemetry.labels(k)["dir"] == "rx")
    tcp_rx = sum(int(v) for v in m.get("tpunet_stream_rx_bytes", {}).values())
    conn.send(("OK" if ok else "CORRUPT", shm_rx, tcp_rx))
    rc.close()
    listen.close()
    net.close()


def _sender(conn, env: dict, sizes: list) -> None:
    os.environ.update(env)
    from tpunet import telemetry
    from tpunet.transport import Net

    net = Net()
    sc = net.connect(conn.recv())
    for size in sizes:
        data = np.arange(size, dtype=np.uint64).astype(np.uint8)
        assert sc.send(data, timeout=60) == size
    m = telemetry.metrics()
    shm_tx = sum(int(v) for k, v in m.get("tpunet_shm_bytes_total", {}).items()
                 if telemetry.labels(k)["dir"] == "tx")
    wakeups = sum(int(v) for v in m.get("tpunet_shm_wakeups_total", {}).values())
    conn.send(("OK", shm_tx, wakeups))
    sc.close()
    net.close()


def _run_pair(env_recv: dict, env_send: dict, sizes: list = SWEEP):
    ctx = mp.get_context("spawn")
    pr, cr = ctx.Pipe()
    ps, cs = ctx.Pipe()
    r = ctx.Process(target=_receiver, args=(cr, env_recv, sizes))
    s = ctx.Process(target=_sender, args=(cs, env_send, sizes))
    r.start()
    s.start()
    try:
        handle = pr.recv()
        ps.send(handle)
        recv_res = pr.recv()
        send_res = ps.recv()
    finally:
        for p in (r, s):
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert recv_res[0] == "OK", recv_res
    assert send_res[0] == "OK", send_res
    return recv_res, send_res


TOTAL = sum(SWEEP)
TOTAL_SMALL = sum(SWEEP_SMALL)


@pytest.mark.parametrize("crc", [0, 1])
def test_shm_loopback_sweep_rides_the_ring(crc):
    """Same host, TPUNET_SHM=1: every payload byte moves through the ring
    segment (tpunet_shm_bytes_total == payload total on both sides), the
    TCP data-stream byte counters stay at EXACTLY zero, CRC trailers
    compose (sizes cover zero-byte, sub-chunk, multi-chunk, and ring-wrap
    transfers — the posted recv buffers are oversized on purpose, pinning
    the LEN-frame semantics), and the futex waiter-count gate keeps the
    wakeup count streaming-scale (far under one wake per chunk — the
    ring's syscalls/MiB analogue, reported by engine_p2p --engines SHM)."""
    env = {"TPUNET_SHM": "1", "TPUNET_CRC": str(crc)}
    (_, shm_rx, tcp_rx), (_, shm_tx, wakeups) = _run_pair(env, env)
    assert shm_rx == TOTAL, (shm_rx, TOTAL)
    assert shm_tx == TOTAL, (shm_tx, TOTAL)
    assert tcp_rx == 0, f"intra-host transfer moved {tcp_rx} TCP bytes"
    assert wakeups <= 2 * (TOTAL // (1 << 20) + len(SWEEP)), wakeups


def test_shm_fake_host_split_falls_back_to_tcp():
    """Forced split: mismatched TPUNET_HOST_ID values nack the segment
    handshake and the pair runs over TCP transparently — zero SHM bytes,
    full payload on the TCP counters, same data integrity."""
    (_, shm_rx, tcp_rx), (_, shm_tx, _) = _run_pair(
        {"TPUNET_SHM": "1", "TPUNET_HOST_ID": "hostA"},
        {"TPUNET_SHM": "1", "TPUNET_HOST_ID": "hostB"},
        sizes=SWEEP_SMALL,
    )
    assert shm_rx == 0 and shm_tx == 0
    assert tcp_rx == TOTAL_SMALL, (tcp_rx, TOTAL_SMALL)


def test_shm_disabled_is_plain_tcp():
    """TPUNET_SHM=0 (the default): nothing touches the SHM counters and the
    existing TCP path is byte-identical to a pre-SHM build."""
    env = {"TPUNET_SHM": "0"}
    (_, shm_rx, tcp_rx), (_, shm_tx, _) = _run_pair(env, env, sizes=SWEEP_SMALL)
    assert shm_rx == 0 and shm_tx == 0
    assert tcp_rx == TOTAL_SMALL
