"""Ulysses (all-to-all) sequence parallelism parity — both tiers.

ICI tier: `ulysses_self_attention` on the virtual CPU mesh vs full attention.
DCN tier: `dcn_ulysses_attention` across real processes over the transport's
native AllToAll, vs the single-host reference sliced to each rank's shard.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

# Module level so mp-spawn children (which re-import this module) also pin
# JAX to CPU — the axon sitecustomize hook force-selects the TPU otherwise.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from conftest import run_spawn_workers  # noqa: E402

from tpunet.ops import attention_reference  # noqa: E402
from tpunet.parallel import make_named_mesh, ulysses_self_attention  # noqa: E402


def _qkv(rng, b, s, h, d, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(causal):
    mesh = make_named_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(0), 4, 32, 4, 8)  # heads % sp == 0
    out = ulysses_self_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_with_tp_heads():
    # Heads split over tp, then further over sp by the all-to-all.
    mesh = make_named_mesh({"dp": 2, "sp": 2, "tp": 2})
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 16, 4, 8)
    out = ulysses_self_attention(q, k, v, mesh, causal=True, tp_axis="tp")
    ref = attention_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_grad_matches():
    mesh = make_named_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(3), 2, 32, 4, 8)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_self_attention(q, k, v, mesh, causal=True, dp_axis=None) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ulysses_head_divisibility_error():
    mesh = make_named_mesh({"sp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 32, 4, 8)  # 4 heads, sp=8
    with pytest.raises(ValueError, match="divisible"):
        ulysses_self_attention(q, k, v, mesh, dp_axis=None)


# -- DCN tier ---------------------------------------------------------------

B, S, H, D = 2, 32, 4, 8


def _full_qkv():
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32) for k in ks)


def _worker(rank: int, world: int, port: int, q, causal: bool) -> None:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")

        from tpunet import distributed
        from tpunet.ops import attention_reference
        from tpunet.parallel import dcn_ulysses_attention

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        qf, kf, vf = _full_qkv()  # same on every rank (same seed)
        s_local = S // world
        sl = slice(rank * s_local, (rank + 1) * s_local)

        fn = jax.jit(lambda a, b, c: dcn_ulysses_attention(a, b, c, causal=causal))
        got = fn(qf[:, sl], kf[:, sl], vf[:, sl])

        want = attention_reference(qf, kf, vf, causal)[:, sl]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("causal", [False, True])
def test_dcn_ulysses_2proc(causal):
    run_spawn_workers(_worker, 2, extra_args=(causal,))


def test_dcn_ulysses_4proc_causal():
    run_spawn_workers(_worker, 4, extra_args=(True,))


def _model_worker(rank: int, world: int, port: int, q) -> None:
    # Full Transformer with attn_impl="dcn_ulysses": each rank's logits on
    # its sequence shard must equal the single-host reference model's logits
    # sliced to that shard (global rotary + full-sequence causality).
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.models import Transformer

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        kw = dict(vocab=32, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                  compute_dtype=jnp.float32)
        ref_model = Transformer(attn_impl="reference", **kw)
        uly_model = Transformer(attn_impl="dcn_ulysses", **kw)

        toks = jax.random.randint(jax.random.PRNGKey(3), (2, S), 0, 32)
        params = ref_model.init(jax.random.PRNGKey(4), toks)["params"]
        want = ref_model.apply({"params": params}, toks)

        s_local = S // world
        sl = slice(rank * s_local, (rank + 1) * s_local)
        got = uly_model.apply({"params": params}, toks[:, sl])
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want[:, sl]), atol=1e-4, rtol=1e-4
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_transformer_dcn_ulysses_2proc():
    run_spawn_workers(_model_worker, 2)
