"""JAX ↔ tpunet interop tests: numeric parity of DCN collectives vs
`jax.lax` ground truth, inside jit, including gradients.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

# Module level so mp-spawn children (which re-import this module) also pin
# JAX to the virtual CPU mesh — the axon sitecustomize hook force-selects
# the TPU tunnel otherwise (see conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import free_port, run_spawn_workers  # noqa: E402


def _rank_arr(rank: int, n: int = 4096) -> np.ndarray:
    rng = np.random.default_rng(100 + rank)
    return rng.standard_normal(n).astype(np.float32)


def test_world1_psum_identity_and_grad():
    import jax
    import jax.numpy as jnp

    from tpunet import distributed
    from tpunet.interop import dcn_all_gather, dcn_psum

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    x = jnp.asarray(_rank_arr(0))

    y = jax.jit(dcn_psum)(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    g = jax.grad(lambda v: dcn_psum(v).sum())(x)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(x))

    gathered = jax.jit(dcn_all_gather)(x)
    assert gathered.shape == (1,) + x.shape
    distributed.finalize()


def _psum_worker(rank: int, world: int, port: int, q) -> None:
    try:
        import jax
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import (
            dcn_all_gather,
            dcn_pmean,
            dcn_psum,
            dcn_reduce_scatter,
        )

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        x = jnp.asarray(_rank_arr(rank))

        # psum under jit vs numpy ground truth.
        y = jax.jit(dcn_psum)(x)
        expect = sum(_rank_arr(r) for r in range(world))
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)

        # pmean.
        m = jax.jit(dcn_pmean)(x)
        np.testing.assert_allclose(np.asarray(m), expect / world, rtol=1e-5, atol=1e-5)

        # gradient of sum(psum(x)): cotangent all-reduced -> world * ones.
        g = jax.jit(jax.grad(lambda v: dcn_psum(v).sum()))(x)
        np.testing.assert_allclose(np.asarray(g), world * np.ones_like(x), rtol=1e-6)

        # all_gather parity.
        ag = jax.jit(dcn_all_gather)(x)
        for r in range(world):
            np.testing.assert_array_equal(np.asarray(ag)[r], _rank_arr(r))

        # reduce_scatter parity.
        rs = jax.jit(dcn_reduce_scatter)(x)
        shard = 4096 // world
        np.testing.assert_allclose(
            np.asarray(rs), expect[rank * shard : (rank + 1) * shard], rtol=1e-5, atol=1e-5
        )

        # non-sum reduction op.
        from tpunet.interop import dcn_all_reduce

        mx = jax.jit(lambda v: dcn_all_reduce(v, "max"))(x)
        np.testing.assert_array_equal(
            np.asarray(mx), np.max([_rank_arr(r) for r in range(world)], axis=0)
        )

        # broadcast from the last rank.
        from tpunet.interop import dcn_barrier, dcn_broadcast, dcn_neighbor_exchange

        root = world - 1
        payload = x if rank == root else jnp.zeros_like(x)
        bc = jax.jit(lambda v: dcn_broadcast(v, root))(payload)
        np.testing.assert_array_equal(np.asarray(bc), _rank_arr(root))

        # neighbor exchange: get prev rank's array.
        ne = jax.jit(dcn_neighbor_exchange)(x)
        np.testing.assert_array_equal(np.asarray(ne), _rank_arr((rank - 1 + world) % world))

        dcn_barrier()

        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_two_process_psum_parity_vs_lax():
    """2 processes run dcn collectives; the parent independently computes
    `jax.lax.psum` over a 2-device CPU mesh on the same per-rank arrays and
    the results must match."""
    import jax
    import jax.numpy as jnp

    world = 2
    run_spawn_workers(_psum_worker, world)

    # lax.psum ground truth over 2 virtual CPU devices (same math XLA would
    # run in-pod): stacking both ranks' arrays and psumming over the device
    # axis must equal what the DCN ring produced (checked in-worker vs the
    # same numpy expectation).
    stacked = jnp.stack([jnp.asarray(_rank_arr(r)) for r in range(world)])
    lax_result = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(stacked)
    expect = sum(_rank_arr(r) for r in range(world))
    np.testing.assert_allclose(np.asarray(lax_result[0]), expect, rtol=1e-5, atol=1e-5)


def test_psum_requires_initialize():
    import jax.numpy as jnp

    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.finalize()
    with pytest.raises(RuntimeError, match="initialize"):
        dcn_psum(jnp.ones(4))


def test_two_communicator_async_registry_no_collision():
    """Two live Communicators issue native tickets that both count from 1;
    the pending-async registry must key by (comm, ticket) so interleaved
    start/finish pairs resolve to the right communicator's buffer."""
    from tpunet.collectives import Communicator
    from tpunet.interop import _pop_pending, _register_pending, dcn_async_stats

    comm_a = Communicator(f"127.0.0.1:{free_port()}", 0, 1)
    comm_b = Communicator(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        xa = _rank_arr(0)
        xb = -2.0 * _rank_arr(0)
        ta = _register_pending(comm_a, comm_a.iall_reduce(xa.copy()))
        tb = _register_pending(comm_b, comm_b.iall_reduce(xb.copy()))
        # Native tickets are per-comm sequential: identical numerically.
        assert ta == tb
        assert dcn_async_stats()["in_flight"] >= 2
        # Finish in reverse order; each must get its own comm's data.
        np.testing.assert_array_equal(_pop_pending(comm_b, tb).wait(), xb)
        np.testing.assert_array_equal(_pop_pending(comm_a, ta).wait(), xa)
        # A finish against the wrong comm (stale ticket) fails loudly.
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="no pending async"):
            _pop_pending(comm_a, ta)
    finally:
        comm_a.close()
        comm_b.close()


def _ticket_after_worker(rank: int, world: int, port: int, q) -> None:
    """`after=` threads through the TICKET API: the start/finish callbacks
    become consumers of earlier FFI results (and the ticket/finish result
    are legal FFI `after=` operands), so a rank-asymmetric trace can bridge
    the two ordering machineries by data flow instead of reading a
    documented hazard."""
    try:
        import jax
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import (
            dcn_all_gather,
            dcn_all_reduce,
            dcn_all_reduce_finish,
            dcn_all_reduce_start,
        )

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        x = jnp.asarray(_rank_arr(rank, 1024))

        def prog(v):
            a = dcn_all_reduce(v, "sum")                    # FFI path
            t = dcn_all_reduce_start(2.0 * v, after=(a,))   # pinned after a
            g = dcn_all_gather(v, after=(t,))               # pinned after start
            r = dcn_all_reduce_finish(t, v, after=(g,))     # pinned after gather
            return a, g, r

        a, g, r = jax.jit(prog)(x)
        expect = sum(_rank_arr(s, 1024) for s in range(world))
        np.testing.assert_allclose(np.asarray(a), expect, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(r), 2.0 * expect, rtol=1e-5,
                                   atol=1e-5)
        for s in range(world):
            np.testing.assert_array_equal(np.asarray(g)[s], _rank_arr(s, 1024))
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_ticket_after_bridges_ffi_ordering():
    run_spawn_workers(_ticket_after_worker, 2)
