"""ZeRO-1 optimizer-state sharding over the DCN tier: trajectory parity with
the replicated cross-host path, and the memory claim (opt state / world).

The reference transport carried whatever NCCL sent; its parent project's
sharded/quantized optimizers lived a layer above (SURVEY §2.3). tpunet owns
that layer, so the capability lands here: reduce-scatter grads, update a
parameter shard, all-gather params (tpunet/train/trainer.py
make_zero_train_step)."""

from __future__ import annotations

import os

import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import run_spawn_workers  # noqa: E402


def _worker(rank: int, world: int, port: int, q) -> None:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax
        from jax.flatten_util import ravel_pytree

        from tpunet import distributed
        from tpunet.models import Transformer
        from tpunet.train import (create_train_state, create_zero_train_state,
                                  make_train_step, make_zero_train_step)

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        model = Transformer(vocab=37, d_model=16, n_layers=2, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        # adamw: params-dependent update (weight decay) + stateful moments —
        # the hardest case for shard/full parity.
        tx = optax.adamw(3e-3)
        toks = jax.random.randint(jax.random.PRNGKey(100 + rank), (2, 8), 0, 37)
        labels = jnp.roll(toks, -1, axis=1)

        state_full, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
        state_zero, _ = create_zero_train_state(model, jax.random.PRNGKey(0), toks, tx)
        step_full = make_train_step(model, tx, cross_host=True, donate=False)
        step_zero = make_zero_train_step(model, tx, donate=False)

        # Optimizer-state memory actually shrinks by ~world (mod the count
        # scalar and shard padding).
        full_elems = sum(np.size(x) for x in jax.tree.leaves(state_full.opt_state))
        zero_elems = sum(np.size(x) for x in jax.tree.leaves(state_zero.opt_state))
        n_params = sum(np.size(x) for x in jax.tree.leaves(state_full.params))
        assert zero_elems <= full_elems / world + world + 8, (
            f"zero opt state {zero_elems} vs full {full_elems} (world {world}, "
            f"params {n_params})"
        )

        for s in range(3):
            state_full, loss_f = step_full(state_full, toks, labels,
                                           jax.random.PRNGKey(s))
            state_zero, loss_z = step_zero(state_zero, toks, labels,
                                           jax.random.PRNGKey(s))
            np.testing.assert_allclose(float(loss_f), float(loss_z), rtol=1e-6)

        pf = np.asarray(ravel_pytree(state_full.params)[0])
        pz = np.asarray(ravel_pytree(state_zero.params)[0])
        np.testing.assert_allclose(pz, pf, rtol=2e-6, atol=2e-7)

        # Ranks agree bitwise on the zero path's params (the all-gather is
        # the only source of each rank's out-of-shard values).
        from tpunet.interop import dcn_all_gather

        allp = np.asarray(jax.jit(dcn_all_gather)(jnp.asarray(pz)))
        for r in range(1, world):
            np.testing.assert_array_equal(allp[0], allp[r])

        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, f"FAIL: {type(e).__name__}: {e}\n"
                     f"{traceback.format_exc()[-500:]}"))


def test_zero1_parity_2proc():
    run_spawn_workers(_worker, 2)


def test_zero1_parity_3proc():
    # Odd world: exercises shard padding (param count % 3 != 0).
    run_spawn_workers(_worker, 3)


def test_zero_state_checkpoint_roundtrip(tmp_path):
    # The sharded opt state (flat vectors, not a params-shaped pytree) must
    # survive the orbax checkpoint layer exactly — elastic resume at fixed
    # world depends on it.
    import jax
    import jax.numpy as jnp
    import optax

    from tpunet import distributed
    from tpunet.models import Transformer
    from tpunet.train import (create_zero_train_state, make_zero_train_step,
                              restore_pytree, save_pytree)
    from conftest import free_port

    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        model = Transformer(vocab=17, d_model=8, n_layers=1, n_heads=1,
                            d_ff=16, compute_dtype=jnp.float32)
        tx = optax.adamw(1e-2)
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 4), 0, 17)
        labels = jnp.roll(toks, -1, axis=1)
        state, _ = create_zero_train_state(model, jax.random.PRNGKey(0), toks, tx)
        step = make_zero_train_step(model, tx, donate=False)
        state, _ = step(state, toks, labels, jax.random.PRNGKey(1))

        save_pytree(tmp_path / "zstate", state)
        template, _ = create_zero_train_state(model, jax.random.PRNGKey(2), toks, tx)
        restored = restore_pytree(tmp_path / "zstate", template)

        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state, restored,
        )
        # And the restored state steps identically.
        s1, l1 = step(state, toks, labels, jax.random.PRNGKey(3))
        s2, l2 = step(restored, toks, labels, jax.random.PRNGKey(3))
        np.testing.assert_array_equal(float(l1), float(l2))
    finally:
        distributed.finalize()


def test_zero_requires_distributed():
    import optax
    import pytest

    from tpunet.models import Transformer
    from tpunet.train import make_zero_train_step

    model = Transformer(vocab=8, d_model=8, n_layers=1, n_heads=1, d_ff=8)
    with pytest.raises(RuntimeError, match="initialize"):
        make_zero_train_step(model, optax.sgd(0.1))
