"""Nonblocking collectives (iall_reduce tickets) + bucketed gradient overlap."""

from __future__ import annotations

import os

import numpy as np
import pytest

# Module level so mp-spawn children also pin JAX to CPU (see conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import free_port, run_spawn_workers  # noqa: E402


def _rank_data(rank: int, n: int, salt: int = 0) -> np.ndarray:
    rng = np.random.default_rng(1000 + 10 * salt + rank)
    return rng.standard_normal(n).astype(np.float32)


def _worker(rank: int, world: int, port: int, q) -> None:
    try:
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)

        # Submit three nonblocking all-reduces back-to-back, then wait them
        # in REVERSE order — execution is submission-ordered, waits are free.
        n = 50_000
        results = [comm.iall_reduce(_rank_data(rank, n, salt=s)) for s in range(3)]
        for s in (2, 1, 0):
            got = results[s].wait()
            expect = sum(_rank_data(r, n, salt=s) for r in range(world))
            np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

        # wait() is idempotent; a second .wait() returns the same array.
        assert results[0].wait() is results[0].wait()

        # A blocking collective after (and between) async work fences first.
        pending = comm.iall_reduce(_rank_data(rank, n, salt=7))
        sync = comm.all_reduce(_rank_data(rank, n, salt=8))
        np.testing.assert_allclose(
            sync, sum(_rank_data(r, n, salt=8) for r in range(world)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            pending.wait(), sum(_rank_data(r, n, salt=7) for r in range(world)),
            rtol=1e-5, atol=1e-5,
        )
        comm.barrier()
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_iall_reduce_2proc():
    run_spawn_workers(_worker, 2)


def _mixed_form_worker(rank: int, world: int, port: int, q) -> None:
    # MPI/NCCL matching rule: a BLOCKING all_reduce on one rank pairs with a
    # NONBLOCKING iall_reduce+wait on another. With multi-channel dispatch
    # this only holds because the blocking form consumes the same ticket
    # sequence (regression: rank 1's blocking-only loop never wired the
    # async channels, deadlocking rank 0's channel wiring accept).
    try:
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        for s in range(5):
            x = _rank_data(rank, 20_000, salt=s)
            if rank % 2 == 0:
                got = comm.iall_reduce(x).wait()
            else:
                got = comm.all_reduce(x)
            expect = sum(_rank_data(r, 20_000, salt=s) for r in range(world))
            np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
        comm.barrier()
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_mixed_blocking_async_pairing_2proc():
    run_spawn_workers(_mixed_form_worker, 2)


def test_iall_reduce_channel_sweep_2proc():
    # The ticket->channel round-robin must agree across ranks for any channel
    # count: run the same out-of-order-wait worker on a 1-ring (serial, the
    # round-2 behavior) and a 4-ring communicator. Spawn children inherit the
    # env; the C++ layer reads TPUNET_ASYNC_CHANNELS once per process.
    for nch in ("1", "4"):
        old = os.environ.get("TPUNET_ASYNC_CHANNELS")
        os.environ["TPUNET_ASYNC_CHANNELS"] = nch
        try:
            run_spawn_workers(_worker, 2)
        finally:
            if old is None:
                del os.environ["TPUNET_ASYNC_CHANNELS"]
            else:
                os.environ["TPUNET_ASYNC_CHANNELS"] = old


def test_bogus_ticket_errors():
    from tpunet.collectives import Communicator

    with Communicator(f"127.0.0.1:{free_port()}", 0, 1) as comm:
        res = comm.iall_reduce(np.ones(8, np.float32))
        np.testing.assert_allclose(res.wait(), np.ones(8))
        # Unknown ticket and double-wait (through the raw ABI) both error.
        assert comm._lib.tpunet_comm_ticket_wait(comm._id, 999_999) != 0
        assert comm._lib.tpunet_comm_ticket_wait(comm._id, res._ticket) != 0


def _bucketed_worker(rank: int, world: int, port: int, q) -> None:
    # Bucketed nonblocking gradient sync must (a) produce the same params as
    # the single-vector blocking path, (b) actually put >=2 buckets in
    # flight, (c) keep ranks bitwise-identical.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from tpunet import distributed, interop
        from tpunet.models import Transformer
        from tpunet.train import create_train_state, make_train_step

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        model = Transformer(vocab=32, d_model=16, n_layers=2, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        tx = optax.sgd(0.05)
        toks = jax.random.randint(jax.random.PRNGKey(10 + rank), (2, 8), 0, 32)
        labels = jnp.roll(toks, -1, axis=1)
        state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)

        # 4 KiB buckets over a ~23K-param model -> several buckets.
        step_b = make_train_step(model, tx, cross_host=True, donate=False,
                                 bucket_bytes=4096)
        step_p = make_train_step(model, tx, cross_host=True, donate=False)

        interop.dcn_async_stats_reset()
        s_b, loss_b = step_b(state, toks, labels, jax.random.PRNGKey(1))
        jax.block_until_ready(s_b)
        stats = interop.dcn_async_stats()
        assert stats["max_in_flight"] >= 2, stats
        assert stats["in_flight"] == 0, stats

        s_p, loss_p = step_p(state, toks, labels, jax.random.PRNGKey(1))
        jax.block_until_ready(s_p)
        np.testing.assert_allclose(float(loss_b), float(loss_p), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
            ),
            s_b.params, s_p.params,
        )

        # Ranks stay in lockstep after a few more bucketed steps.
        for i in range(3):
            s_b, loss = step_b(s_b, toks, labels, jax.random.PRNGKey(2 + i))
            assert np.isfinite(float(loss))
        from jax.flatten_util import ravel_pytree

        flat = ravel_pytree(s_b.params)[0]
        all_params = np.asarray(jax.jit(interop.dcn_all_gather)(flat))
        for r in range(1, world):
            np.testing.assert_array_equal(all_params[0], all_params[r])
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_bucketed_overlap_training_2proc():
    run_spawn_workers(_bucketed_worker, 2)


def test_bucket_bytes_requires_cross_host():
    import jax.numpy as jnp
    import optax

    from tpunet.models import Transformer
    from tpunet.train import make_train_step

    model = Transformer(vocab=16, d_model=8, n_layers=1, n_heads=2, d_ff=16,
                        compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="cross_host"):
        make_train_step(model, optax.sgd(0.1), bucket_bytes=1 << 20)
