"""Cross-rank hang postmortem (tools/postmortem.py, docs/DESIGN.md §6c).

The headline scenario: a W=4 ring allreduce with one rank's send stalled by
fault injection hits the progress watchdog; every rank's flight recorder
dumps at the verdict site; the postmortem merges the four dumps and NAMES
the wedged rank and phase. Plus deterministic unit tests of the lattice and
diagnosis over hand-built dumps (synthetic dumps make the corner cases —
behind ranks, bootstrap hangs — reproducible without faulting real wires).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import pytest

from conftest import run_spawn_workers

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.postmortem import diagnose, load_dumps, phase_lattice  # noqa: E402


# ---------------------------------------------------------------------------
# Synthetic dumps: deterministic lattice/diagnosis pinning.


def _dump(rank, events, host="deadbeef00000000"):
    return {"schema": "tpunet-flightrec-v1", "rank": rank, "host": host,
            "reason": "watchdog", "capacity": 1024, "recorded": len(events),
            "dropped": 0, "events": events, "torn": 0}


def _phase(kind, t, comm, seq, name, step, nbytes=4096):
    return {"t": t, "kind": kind, "a": comm, "b": seq, "c": nbytes,
            "d": step, "name": name}


def test_phase_lattice_pairs_enter_exit():
    d = _dump(0, [
        _phase("phase_enter", 100, 7, 41, "rs", 0),
        _phase("phase_exit", 200, 7, 41, "rs", 0),
        _phase("phase_enter", 210, 7, 41, "rs", 1),
    ])
    lat = phase_lattice([d])
    spans = lat[(7, 41)][0]
    assert len(spans) == 2
    assert spans[0] == {"name": "rs", "step": 0, "enter_t": 100,
                        "exit_t": 200, "nbytes": 4096}
    assert spans[1]["exit_t"] is None  # still open: the wedge signature


def test_diagnose_names_stalled_rank_and_phase():
    # rank 0 completed the frontier; rank 1 wedged in rs.2; rank 2 never
    # entered it (its newest collective is coll_seq=40).
    dumps = [
        _dump(0, [_phase("phase_enter", 100, 7, 41, "rs", s)
                  for s in range(3)] +
                 [_phase("phase_exit", 110 + s, 7, 41, "rs", s)
                  for s in range(3)]),
        _dump(1, [_phase("phase_enter", 100, 7, 41, "rs", 2),
                  {"t": 5000000, "kind": "verdict", "a": 3,
                   "name": "watchdog"}]),
        _dump(2, [_phase("phase_enter", 90, 7, 40, "ag", 1),
                  _phase("phase_exit", 95, 7, 40, "ag", 1)]),
    ]
    diag = diagnose(dumps)
    assert diag["frontier"] == {"comm_id": 7, "coll_seq": 41}
    assert diag["stalled"] == [{"rank": 1, "phase": "rs.2", "coll_seq": 41,
                                "since_us": 5000000 - 100}]
    assert diag["behind"] == [{"rank": 2, "last_coll_seq": 40}]
    assert diag["complete"] == [0]
    assert diag["verdicts"] == [{"rank": 1, "reason": "watchdog",
                                 "t": 5000000}]
    joined = "\n".join(diag["lines"])
    assert "rank 1 in rs.2" in joined and "wedged" in joined


def test_diagnose_bootstrap_hang():
    # No phase events at all: the job died before its first collective.
    dumps = [_dump(0, [{"t": 10, "kind": "verdict", "a": 3,
                        "name": "watchdog"}])]
    diag = diagnose(dumps)
    assert diag["frontier"] is None
    assert "predates the first collective" in diag["lines"][0]


def test_load_dumps_rejects_wrong_schema(tmp_path):
    p = tmp_path / "tpunet-flightrec-rank0.json"
    p.write_text(json.dumps({"schema": "nope", "events": []}))
    with pytest.raises(ValueError, match="tpunet-flightrec-v1"):
        load_dumps([str(tmp_path)])


# ---------------------------------------------------------------------------
# The real thing: W=4 stalled collective -> watchdog -> 4 dumps -> diagnosis.


def _hang_worker(rank: int, world: int, port: int, q, tmpdir) -> None:
    try:
        os.environ.update({
            "TPUNET_TRACE_DIR": tmpdir,
            "TPUNET_RANK": str(rank),
            "TPUNET_PROGRESS_TIMEOUT_MS": "2500",
            "TPUNET_ALGO": "ring",
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
        })
        import numpy as np

        from tpunet import _native as nat
        from tpunet import telemetry
        from tpunet import transport as tp
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        warm = comm.all_reduce(np.ones(4, np.float32))
        assert warm[0] == world
        comm.barrier()
        if rank == 1:
            # Rank 1's ring sends die after 256KiB of the measured 4MiB
            # allreduce: its neighbor starves mid reduce-scatter, the stall
            # propagates, and every watchdog fires.
            tp.fault_inject("stream=*:side=send:after_bytes=256K:action=stall")
        arr = np.full(1 << 20, float(rank + 1), np.float32)
        try:
            comm.all_reduce(arr)
            q.put((rank, "FAIL: stalled allreduce completed"))
            return
        except nat.NativeError:
            pass
        # The watchdog's verdict dump is the native path under test; a rank
        # that got a secondary error (peer teardown) instead snapshots on
        # demand so the postmortem always sees all four ranks.
        path = os.path.join(tmpdir, f"tpunet-flightrec-rank{rank}.json")
        if not os.path.exists(path):
            telemetry.flightrec_dump(tmpdir, reason="teardown")
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))
    finally:
        try:
            from tpunet import transport as tp

            tp.fault_clear()
        except Exception:  # noqa: BLE001
            pass


def test_hang_postmortem_w4(tmp_path):
    run_spawn_workers(_hang_worker, 4, extra_args=(str(tmp_path),))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(list(tmp_path.glob("tpunet-flightrec-rank*.json"))) >= 4:
            break
        time.sleep(0.1)
    dumps = load_dumps([str(tmp_path)])
    assert len(dumps) == 4, [d["_path"] for d in dumps]
    assert [d["rank"] for d in dumps] == [0, 1, 2, 3]

    diag = diagnose(dumps)
    assert diag["frontier"] is not None
    # At least one watchdog verdict made it into a ring (the native
    # dump-at-raise-site path, not the python fallback).
    assert any(v["reason"] == "watchdog" for v in diag["verdicts"]), \
        diag["verdicts"]
    # The diagnosis names wedged ranks in a reduce-scatter/allgather phase.
    wedged = diag["stalled"] + diag["behind"]
    assert wedged, diag["lines"]
    for s in diag["stalled"]:
        assert s["phase"].split(".")[0] in ("rs", "ag", "allreduce"), s
    joined = "\n".join(diag["lines"])
    assert "diagnosis:" in joined

    # The merged Perfetto timeline ingests the same dumps (satellite c).
    from tpunet import telemetry

    out = telemetry.merge_traces(str(tmp_path))
    with open(out) as f:
        merged = json.load(f)
    names = {e.get("name", "") for e in merged if e.get("ph") == "i"}
    assert any(n.startswith(("phase_enter", "wire_", "verdict"))
               for n in names), sorted(names)[:20]
