"""The protocol model checker (tools/model): harness semantics, green on
HEAD, and — the part that makes it verification rather than documentation —
RED on every seeded mutation.

Three layers:
  * Harness unit tests: BFS invariant/deadlock/livelock detection and
    counterexample traces on toy models, so a harness regression cannot hide
    behind the real models staying green.
  * HEAD gates: all five protocol models explore exhaustively with zero
    violations (the same gate CI applies via ``python -m tools.model --all``).
  * Sharpness gates: every entry in every model's MUTATIONS table — each a
    named real-world protocol bug — must turn the checker red. A property
    that no seeded bug can violate is not being checked.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.model import Model, all_models, all_mutations, explore  # noqa: E402


# ---------------------------------------------------------------------------
# Harness semantics on toy models.


def test_explore_reports_invariant_with_minimal_trace():
    # 0 -> 1 -> 2, invariant breaks at 2; also a direct 0 -> 2 shortcut, so
    # BFS must report the 1-step trace, not the 2-step one.
    m = Model(
        "toy", lambda: [0],
        lambda s: ([("step", s + 1)] if s < 2 else []) + ([("skip", 2)] if s == 0 else []),
        lambda s: "boom" if s == 2 else None,
        lambda s: True)
    r = explore(m)
    assert not r.ok and r.error.kind == "invariant"
    assert [lbl for lbl, _ in r.error.trace] == ["<init>", "skip"]


def test_explore_reports_deadlock_only_when_not_done():
    stuck = Model("stuck", lambda: [0], lambda s: [], lambda s: None,
                  lambda s: False)
    r = explore(stuck)
    assert not r.ok and r.error.kind == "deadlock"
    quiesced = Model("quiesced", lambda: [0], lambda s: [], lambda s: None,
                     lambda s: True)
    assert explore(quiesced).ok


def test_explore_detects_nonprogress_cycle_as_livelock():
    # 0 <-> 1 spin, marked non-progress; state 2 (real work) is reachable so
    # the graph is not a deadlock.
    m = Model(
        "spin", lambda: [0],
        lambda s: [("spin", 1 - s), ("work", 2)] if s in (0, 1) else [],
        lambda s: None, lambda s: s == 2,
        progress=lambda label: label != "spin")
    r = explore(m)
    assert not r.ok and r.error.kind == "livelock"
    assert "spin" in r.error.message


def test_explore_enforces_state_budget():
    unbounded = Model("big", lambda: [0], lambda s: [("inc", s + 1)],
                      lambda s: None, lambda s: False)
    with pytest.raises(RuntimeError, match="state space exceeds"):
        explore(unbounded, max_states=100)


# ---------------------------------------------------------------------------
# HEAD gates: exhaustive and clean.


@pytest.mark.parametrize("name", sorted(all_models()))
def test_model_green_on_head(name):
    r = explore(all_models()[name]())
    assert r.ok, f"{name}: {r.error.render()}"
    assert r.states > 10, f"{name} explored only {r.states} states — shape degenerate?"


def test_every_model_ships_mutations():
    muts = all_mutations()
    assert set(muts) == set(all_models())
    for name, table in muts.items():
        assert len(table) >= 3, f"{name} has {len(table)} seeded bugs, want >= 3"


# ---------------------------------------------------------------------------
# Sharpness gates: every seeded bug is caught.

_ALL = [(m, mut) for m, muts in sorted(all_mutations().items()) for mut in muts]


@pytest.mark.parametrize("name,mutation", _ALL,
                         ids=[f"{m}.{mut}" for m, mut in _ALL])
def test_mutation_turns_checker_red(name, mutation):
    r = explore(all_models()[name](mutation))
    assert not r.ok, (
        f"seeded bug {name}.{mutation} survived exhaustive exploration "
        f"({r.states} states) — the model lost the property that bug violates")
    assert r.error.trace, "counterexample must carry a trace"


def test_unknown_mutation_is_rejected():
    for name, factory in all_models().items():
        with pytest.raises(ValueError, match="unknown mutation"):
            factory("no_such_bug")


# ---------------------------------------------------------------------------
# CLI: the CI lane's exact invocations.


def _cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, "-m", "tools.model", *args],
                          cwd=REPO, capture_output=True, text=True, timeout=300)


def test_cli_all_exits_zero_on_head():
    p = _cli("--all")
    assert p.returncode == 0, p.stdout + p.stderr
    assert p.stdout.count(" ok ") == len(all_models())


def test_cli_mutate_exits_one_when_caught():
    p = _cli("--mutate", "drr.strict_latency")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "caught" in p.stdout


def test_cli_mutations_lists_every_seeded_bug():
    p = _cli("--mutations")
    assert p.returncode == 0
    for name, mut in _ALL:
        assert f"{name}.{mut}:" in p.stdout
