"""KV-cache decode + generation tests, and the GQA/SwiGLU model variants.

Ground truth for every decode test is the ordinary full-sequence forward:
the cache path must reproduce it position-for-position (same params), and
greedy generation must match an oracle loop that re-runs the full model on
the growing sequence each step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.models import Transformer, generate, init_cache
from tpunet.train import create_train_state, make_train_step


def _tiny(**kw):
    kw.setdefault("vocab", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    return Transformer(**kw)


def _params(model, b=2, s=24, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(0), (b, s), 0, model.vocab)
    return model.init(jax.random.PRNGKey(seed), toks)["params"], toks


@pytest.mark.parametrize("n_kv_heads,attn_window", [
    (None, None),
    (2, None),
    # GQA's grouped-einsum decode (the cache is contracted directly, never
    # group-repeated in HBM) composed with the sliding-window mask.
    (2, 6),
])
def test_decode_cache_matches_full_forward(n_kv_heads, attn_window):
    model = _tiny(n_kv_heads=n_kv_heads, attn_window=attn_window)
    params, toks = _params(model)
    full = model.apply({"params": params}, toks)  # (b, s, vocab)

    dm = model.clone(decode=True)
    cache = init_cache(model, toks.shape[0], toks.shape[1])
    outs = []
    for i in range(toks.shape[1]):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i : i + 1],
            mutable=["cache"],
        )
        cache = mut["cache"]
        outs.append(step[:, 0])
    stepwise = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(stepwise), np.asarray(full), atol=2e-4, rtol=2e-4
    )


def test_prefill_then_step_matches_full_forward():
    model = _tiny()
    params, toks = _params(model)
    full = model.apply({"params": params}, toks)

    dm = model.clone(decode=True)
    p = 16
    cache = init_cache(model, toks.shape[0], toks.shape[1])
    pre, mut = dm.apply(
        {"params": params, "cache": cache}, toks[:, :p], mutable=["cache"]
    )
    cache = mut["cache"]
    np.testing.assert_allclose(
        np.asarray(pre), np.asarray(full[:, :p]), atol=2e-4, rtol=2e-4
    )
    for i in range(p, toks.shape[1]):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i : i + 1],
            mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, i]), atol=2e-4, rtol=2e-4
        )


def test_greedy_generate_matches_full_forward_oracle():
    # The cache path and the full forward differ by float-reassociation
    # noise (~1e-5 on logits), which a tiny random model's near-ties can
    # turn into different argmaxes. The correctness property is therefore:
    # every generated token is a NEAR-argmax of the cacheless full model's
    # next-token logits on the exact prefix generate() actually produced.
    model = _tiny()
    params, _ = _params(model)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, model.vocab)
    n_new = 6
    out = generate(model, params, prompt, n_new)
    assert out.shape == (2, 5 + n_new)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    for i in range(n_new):
        logits = model.apply({"params": params}, out[:, : 5 + i])[:, -1, :]
        chosen = np.take_along_axis(
            np.asarray(logits), np.asarray(out[:, 5 + i])[:, None], axis=1
        )[:, 0]
        top = np.max(np.asarray(logits), axis=1)
        np.testing.assert_allclose(chosen, top, atol=1e-3)


def test_generate_eos_pins_tail():
    model = _tiny()
    params, _ = _params(model)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (3, 4), 0, model.vocab)
    out = generate(model, params, prompt, 8, temperature=1.0,
                   rng=jax.random.PRNGKey(7), eos_id=0)
    gen = np.asarray(out[:, 4:])
    for row in gen:
        hit = np.flatnonzero(row == 0)
        if hit.size:
            assert np.all(row[hit[0]:] == 0)


def test_generate_moe_model_runs():
    model = _tiny(n_experts=4, moe_every=1)
    params, _ = _params(model)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0, model.vocab)
    out = generate(model, params, prompt, 4)
    assert out.shape == (2, 8)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < model.vocab))


def test_gqa_param_shapes_and_causality():
    model = _tiny(n_kv_heads=2)
    params, toks = _params(model)
    att = params["block0"]["attn"]
    assert att["q"]["kernel"].shape == (32, 32)      # 4 heads x 8
    assert att["k"]["kernel"].shape == (32, 16)      # 2 kv heads x 8
    assert att["v"]["kernel"].shape == (32, 16)
    base = model.apply({"params": params}, toks)
    toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % 64)
    pert = model.apply({"params": params}, toks2)
    np.testing.assert_allclose(
        np.asarray(base[0, :-1]), np.asarray(pert[0, :-1]), atol=1e-6
    )


def test_gqa_decode_cache_holds_kv_heads_only():
    model = _tiny(n_kv_heads=2)
    cache = init_cache(model, 2, 16)
    ck = cache["block0"]["attn"]["cached_key"]
    assert ck.shape == (2, 16, 2, 8)


def test_gqa_flash_matches_reference_impl():
    ref = _tiny(n_kv_heads=2, attn_impl="reference")
    fla = _tiny(n_kv_heads=2, attn_impl="flash")
    params, toks = _params(ref, b=1, s=128)
    np.testing.assert_allclose(
        np.asarray(fla.apply({"params": params}, toks)),
        np.asarray(ref.apply({"params": params}, toks)),
        atol=2e-2, rtol=2e-2,
    )


def test_swiglu_forward_and_train_step():
    model = _tiny(mlp_impl="swiglu")
    params, toks = _params(model)
    assert "gate" in params["block0"]["mlp"]
    logits = model.apply({"params": params}, toks)
    assert bool(jnp.all(jnp.isfinite(logits)))

    state, _ = create_train_state(
        model, jax.random.PRNGKey(0), toks, optax.adamw(1e-3)
    )
    step = make_train_step(model, optax.adamw(1e-3))
    labels = jnp.roll(toks, -1, axis=1)
    losses = []
    for i in range(4):
        state, loss = step(state, toks, labels, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_decode_past_capacity_poisons_output():
    model = _tiny()
    params, toks = _params(model)
    dm = model.clone(decode=True)
    cache = init_cache(model, 2, 4)  # capacity 4
    for i in range(4):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i : i + 1],
            mutable=["cache"],
        )
        cache = mut["cache"]
        assert bool(jnp.all(jnp.isfinite(step)))
    over, _ = dm.apply(
        {"params": params, "cache": cache}, toks[:, 4:5], mutable=["cache"]
    )
    assert bool(jnp.all(jnp.isnan(over)))  # loud, not silently-wrong


def test_decode_rejects_sequence_parallel_attn_impls():
    from tpunet.parallel import make_named_mesh

    mesh = make_named_mesh({"sp": 2})
    model = _tiny(attn_impl="ring", mesh=mesh)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    with pytest.raises(ValueError, match="decode=True does not support"):
        model.clone(decode=True).init(jax.random.PRNGKey(1), toks)


def test_bad_remat_policy_raises_even_without_remat():
    model = _tiny(remat=False, remat_policy="dot")  # typo'd policy
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    with pytest.raises(ValueError, match="remat_policy"):
        model.init(jax.random.PRNGKey(1), toks)


def test_swiglu_tp_rules_cover_gate():
    from tpunet.models import transformer_partition_rules
    import re

    rules = transformer_partition_rules(tp_axis="mdl")
    path = "block0/mlp/gate/kernel"
    assert any(re.fullmatch(pat, path) for pat, _ in rules)


def test_sliding_window_model_flash_matches_reference():
    ref = _tiny(attn_window=12, attn_impl="reference")
    fla = _tiny(attn_window=12, attn_impl="flash")
    params, toks = _params(ref, b=1, s=128)
    np.testing.assert_allclose(
        np.asarray(fla.apply({"params": params}, toks)),
        np.asarray(ref.apply({"params": params}, toks)),
        atol=2e-2, rtol=2e-2,
    )


def test_sliding_window_limits_receptive_field():
    # With window=4 and 2 layers, logits at position p depend on at most the
    # previous 2*(4-1) positions; perturbing an older token changes nothing.
    model = _tiny(attn_window=4)
    params, toks = _params(model, b=1, s=24)
    base = model.apply({"params": params}, toks)
    far = toks.at[0, 2].set((toks[0, 2] + 1) % 64)
    pert = model.apply({"params": params}, far)
    np.testing.assert_allclose(
        np.asarray(base[0, -1]), np.asarray(pert[0, -1]), atol=1e-5
    )


def test_sliding_window_decode_matches_full_forward():
    model = _tiny(attn_window=6)
    params, toks = _params(model)
    full = model.apply({"params": params}, toks)
    dm = model.clone(decode=True)
    cache = init_cache(model, toks.shape[0], toks.shape[1])
    for i in range(toks.shape[1]):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i : i + 1],
            mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, i]),
            atol=2e-4, rtol=2e-4,
        )


def test_sliding_window_rejects_sequence_parallel_impls():
    from tpunet.parallel import make_named_mesh

    mesh = make_named_mesh({"sp": 2})
    model = _tiny(attn_impl="ring", mesh=mesh, attn_window=8)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    with pytest.raises(ValueError, match="attn_window"):
        model.init(jax.random.PRNGKey(1), toks)


def test_top_k_sampling_restricts_support():
    # With top_k=1, sampling at any temperature IS greedy: every draw must
    # equal the argmax continuation.
    model = _tiny()
    params, toks = _params(model)
    prompt = toks[:, :8]
    greedy = generate(model, params, prompt, 6)
    for seed in range(3):
        out = generate(model, params, prompt, 6, temperature=1.5,
                       top_k=1, rng=jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(greedy))


def test_top_p_keeps_top_token_and_restricts_support():
    # top_p -> 0 keeps only the nucleus head: again greedy, at any
    # temperature and seed (the top token must always survive the mask).
    model = _tiny()
    params, toks = _params(model)
    prompt = toks[:, :8]
    greedy = generate(model, params, prompt, 6)
    for seed in range(3):
        out = generate(model, params, prompt, 6, temperature=2.0,
                       top_p=1e-6, rng=jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(greedy))
    # And a loose-p run still produces valid tokens.
    out = generate(model, params, prompt, 6, temperature=1.0, top_p=0.9,
                   rng=jax.random.PRNGKey(7))
    assert int(out.max()) < model.vocab and int(out.min()) >= 0


def test_sampling_knob_validation():
    model = _tiny()
    params, toks = _params(model)
    prompt = toks[:, :4]
    with pytest.raises(ValueError, match="temperature"):
        generate(model, params, prompt, 2, top_k=5)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, 2, temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, 2, temperature=1.0, top_p=0.0)


def test_gqa_sliding_window_flash_matches_reference():
    # The combined kernel program (kv-head index maps + window k-loop
    # bounds) — parity at the model level, matching the new smoke entry.
    ref = _tiny(n_kv_heads=2, attn_window=12, attn_impl="reference")
    fla = _tiny(n_kv_heads=2, attn_window=12, attn_impl="flash")
    params, toks = _params(ref, b=1, s=128)
    np.testing.assert_allclose(
        np.asarray(fla.apply({"params": params}, toks)),
        np.asarray(ref.apply({"params": params}, toks)),
        atol=2e-2, rtol=2e-2,
    )


def test_generate_tp_dp_sharded_matches_replicated():
    """Multi-chip inference: generate() jitted over a dp x mdl mesh with
    Megatron-sharded params (and a GQA cache sharded along with its kv
    heads). GSPMD propagates the param shardings through prefill, the
    cache update loop, and the lm head; no inference-specific partition
    code exists or is needed. The mdl all-reduce reassociates float sums
    (~1e-6 logit noise), so — like the greedy oracle test above — the
    assertion is tie-tolerant: every sharded token must be a NEAR-argmax
    of the replicated model's logits on the sharded run's own prefix."""
    from functools import partial

    from tpunet.models import transformer_partition_rules
    from tpunet.parallel import batch_sharding, make_named_mesh, shard_params

    model = _tiny(n_kv_heads=2)
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (4, 12)), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    expected = generate(model, params, toks, 6)

    mesh = make_named_mesh({"dp": 2, "mdl": 2})
    rules = transformer_partition_rules(tp_axis="mdl")
    shardings = shard_params(params, mesh, rules)
    params_sh = jax.device_put(params, shardings)
    toks_sh = jax.device_put(toks, batch_sharding(mesh))
    with mesh:
        got = jax.jit(partial(generate, model, max_new_tokens=6))(
            params_sh, toks_sh)
    assert got.shape == expected.shape
    np.testing.assert_array_equal(np.asarray(got[:, :12]), np.asarray(toks))
    for i in range(6):
        logits = model.apply({"params": params}, got[:, : 12 + i])[:, -1, :]
        chosen = np.take_along_axis(
            np.asarray(logits), np.asarray(got[:, 12 + i])[:, None], axis=1
        )[:, 0]
        np.testing.assert_allclose(
            chosen, np.max(np.asarray(logits), axis=1), atol=1e-3)

# --- rolling-window ring-buffer KV cache (round-5 verdict item 2) ---------
# attn_window + decode defaults to a TRUE ring buffer: leaves sized
# min(window, capacity), writes at pos mod window, decode contraction over
# window (+ s) entries. Parity oracle is the full-capacity masked cache
# (decode_ring_cache=False — the round-4 implementation).


def test_ring_cache_leaf_shapes_bounded_by_window():
    ring_cache = init_cache(_tiny(attn_window=6), 2, 24)
    masked_cache = init_cache(
        _tiny(attn_window=6, decode_ring_cache=False), 2, 24)
    ring_caps = [leaf.shape[1] for leaf in jax.tree.leaves(ring_cache)
                 if leaf.ndim == 4]
    masked_caps = [leaf.shape[1] for leaf in jax.tree.leaves(masked_cache)
                   if leaf.ndim == 4]
    assert ring_caps and all(c == 6 for c in ring_caps)
    assert masked_caps and all(c == 24 for c in masked_caps)
    # A window wider than the capacity degenerates to the full cache.
    wide = init_cache(_tiny(attn_window=100), 2, 24)
    assert all(leaf.shape[1] == 24 for leaf in jax.tree.leaves(wide)
               if leaf.ndim == 4)


def test_ring_cache_generate_matches_masked_cache():
    model = _tiny(attn_window=6)
    params, _ = _params(model)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 9), 0, 64)
    masked = model.clone(decode_ring_cache=False)
    greedy_ring = generate(model, params, prompt, max_new_tokens=15,
                           temperature=0.0)
    greedy_masked = generate(masked, params, prompt, max_new_tokens=15,
                             temperature=0.0)
    assert jnp.array_equal(greedy_ring, greedy_masked)
    # Chunked prefill drives s>1 steps through the ring (pre-write snapshot
    # + in-step k/v) — must stay exact.
    chunked = generate(model, params, prompt, max_new_tokens=15,
                       temperature=0.0, prefill_chunk=4)
    assert jnp.array_equal(chunked, greedy_masked)
    # Sampling: identical rng + identical logits => identical draws.
    s_ring = generate(model, params, prompt, max_new_tokens=15,
                      temperature=0.8, top_k=8, rng=jax.random.PRNGKey(7))
    s_masked = generate(masked, params, prompt, max_new_tokens=15,
                        temperature=0.8, top_k=8, rng=jax.random.PRNGKey(7))
    assert jnp.array_equal(s_ring, s_masked)


def test_ring_cache_never_overflows_past_window():
    # The masked cache poisons past capacity; the ring never overflows —
    # a generation 5x the window long stays finite and position-exact
    # against the full-sequence forward at every step.
    model = _tiny(attn_window=4)
    params, toks = _params(model, s=20)
    full = model.apply({"params": params}, toks)
    dm = model.clone(decode=True)
    cache = init_cache(model, 2, 4)  # ring capacity = window only
    for i in range(20):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i: i + 1],
            mutable=["cache"],
        )
        cache = mut["cache"]
        assert bool(jnp.all(jnp.isfinite(step)))
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, i]),
            atol=2e-4, rtol=2e-4,
        )


def test_ring_cache_gqa_per_row_rows_independent():
    # Per-row ring (the serving substrate): rows at DIFFERENT offsets wrap
    # independently; each row's step logits match the full forward at its
    # own position.
    model = _tiny(attn_window=5, n_kv_heads=2)
    params, toks = _params(model, b=2, s=16)
    full = model.apply({"params": params}, toks)
    dm = model.clone(decode=True, per_row_cache=True)
    cache = init_cache(dm, 2, 5)
    # Advance row 0 by 3 tokens first (rows diverge), then walk both.
    from tpunet.models.generate import _set_cache_index
    for i in range(3):
        _, mut = dm.apply(
            {"params": params, "cache": cache},
            jnp.stack([toks[0, i: i + 1], toks[1, 0:1]]), mutable=["cache"])
        cache = mut["cache"]
    # Reset row 1 to 0 (recycled serve slot); row 0 keeps its offset.
    cache = _set_cache_index(cache, jnp.array([3, 0], jnp.int32))
    for i in range(10):
        step, mut = dm.apply(
            {"params": params, "cache": cache},
            jnp.stack([toks[0, 3 + i: 4 + i], toks[1, i: i + 1]]),
            mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step[0, 0]), np.asarray(full[0, 3 + i]),
            atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(
            np.asarray(step[1, 0]), np.asarray(full[1, i]),
            atol=2e-4, rtol=2e-4)


def test_speculative_windowed_model_keeps_full_cache():
    # Rollback rewrites cache_index; a ring would have overwritten history.
    # speculative_generate must therefore run windowed models on the
    # full-capacity masked cache — shape-checked here; exactness is covered
    # in test_speculative.py's windowed cases.
    from tpunet.models.generate import speculative_generate

    model = _tiny(attn_window=8)
    draft = _tiny(n_layers=1, attn_window=8)
    params, _ = _params(model)
    dparams, _ = _params(draft)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0, 64)
    out = speculative_generate(
        model, params, draft, dparams, prompt, max_new_tokens=8, gamma=2,
        temperature=0.0)
    ref = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    assert jnp.array_equal(out[:, :ref.shape[1]], ref)


def test_ring_cache_window_wider_than_capacity_poisons_past_cap():
    # cap < window: the ring wraps BEFORE the window does — eviction would
    # silently corrupt in-window history, so the loud NaN-poison past
    # capacity must survive in ring mode too.
    model = _tiny(attn_window=100)
    params, toks = _params(model, s=12)
    dm = model.clone(decode=True)
    cache = init_cache(model, 2, 8)  # capacity 8 < window 100
    for i in range(8):
        step, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, i: i + 1],
            mutable=["cache"])
        cache = mut["cache"]
        assert bool(jnp.all(jnp.isfinite(step)))
    over, _ = dm.apply(
        {"params": params, "cache": cache}, toks[:, 8:9], mutable=["cache"])
    assert bool(jnp.all(jnp.isnan(over)))
