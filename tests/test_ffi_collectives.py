"""XLA FFI custom-call collectives (round 5): the zero-copy CPU path.

dcn_all_reduce lowers to a native XLA custom call on the CPU backend
(cpp/src/xla_ffi.cc) instead of the io_callback host bridge — same
semantics, no host staging copies. These tests pin: path activation,
multi-tensor ordering across ranks, dtype coverage, the elastic
communicator swap under an already-compiled executable, and the
io_callback fallback when the path is disabled.
"""

from __future__ import annotations

import os

import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402

from conftest import free_port, run_spawn_workers  # noqa: E402


def _ffi_present() -> bool:
    from tpunet import _native

    return hasattr(_native.load(), "TpunetFfiAllReduce")


pytestmark = pytest.mark.skipif(
    not _ffi_present(),
    reason="libtpunet.so built without jaxlib FFI headers")


def test_ffi_path_is_active_on_cpu():
    from tpunet.interop import _ffi_available

    assert _ffi_available()


def test_ffi_lowering_contains_custom_call():
    # The jitted psum must lower to the custom call, not the host callback.
    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        txt = jax.jit(dcn_psum).lower(jnp.ones((4,), jnp.float32)).as_text()
        assert "tpunet_all_reduce" in txt
        assert "io_callback" not in txt
    finally:
        distributed.finalize()


def test_ffi_dtypes_and_zero_size_world1():
    import ml_dtypes

    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        for dt in (jnp.float32, jnp.int32, ml_dtypes.bfloat16, jnp.uint8):
            x = jnp.arange(7).astype(dt)
            y = jax.jit(dcn_psum)(x)
            np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        # f64/i64 need x64 mode or they silently downcast to f32/i32 and
        # dtype codes 1/4 would never be exercised. jax.enable_x64 moved out
        # of the top-level namespace on the 0.4.x line.
        from jax.experimental import enable_x64

        with enable_x64():
            for dt in (jnp.float64, jnp.int64):
                x = jnp.arange(7).astype(dt)
                assert x.dtype == dt
                y = jax.jit(dcn_psum)(x)
                np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        z = jax.jit(dcn_psum)(jnp.zeros((0,), jnp.float32))
        assert z.shape == (0,)
    finally:
        distributed.finalize()


def test_ffi_elastic_comm_swap_under_compiled_executable():
    # THE elastic guarantee: the executable caches no communicator id —
    # the handler resolves the process default at call time, so replacing
    # the communicator (recovery) under an already-compiled step works.
    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.finalize()
    fn = jax.jit(dcn_psum)
    x = jnp.arange(5, dtype=jnp.float32)

    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))
    distributed.finalize()

    # Destroyed comm must fail loudly, not dereference a dead id.
    with pytest.raises(Exception, match="default communicator|initialize"):
        fn(x).block_until_ready()

    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)  # NEW comm
    try:
        np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))
    finally:
        distributed.finalize()


def test_ffi_disabled_falls_back_to_io_callback():
    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    old = os.environ.get("TPUNET_FFI_COLLECTIVES")
    os.environ["TPUNET_FFI_COLLECTIVES"] = "0"
    # The flag is read at TRACE time and traces are cached per function
    # object — drop them so the toggle actually re-lowers (process-level
    # config; mid-process toggling is a test-only move).
    jax.clear_caches()
    try:
        txt = jax.jit(dcn_psum).lower(jnp.ones((4,), jnp.float32)).as_text()
        assert "tpunet_all_reduce" not in txt
        x = jnp.arange(4, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(jax.jit(dcn_psum)(x)),
                                      np.asarray(x))
    finally:
        if old is None:
            del os.environ["TPUNET_FFI_COLLECTIVES"]
        else:
            os.environ["TPUNET_FFI_COLLECTIVES"] = old
        jax.clear_caches()
        distributed.finalize()


def _ordering_worker(rank: int, world: int, port: int, q) -> None:
    # Several independent FFI collectives inside ONE jit: the compiled
    # schedule must issue them in the same order on every rank (identical
    # HLO -> deterministic schedule), or the single-threaded ring comm
    # would cross-match different collectives and corrupt/deadlock.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import dcn_all_reduce, dcn_pmean, dcn_psum

        distributed.initialize(f"127.0.0.1:{port}", rank, world)

        a = jnp.full((64,), float(rank + 1), jnp.float32)
        b = jnp.arange(33, dtype=jnp.float32) * (rank + 1)
        c = jnp.full((7,), rank + 1, jnp.int32)

        @jax.jit
        def mixed(a, b, c):
            s1 = dcn_psum(a)                      # f32
            s2 = dcn_all_reduce(b, "max")         # f32 max
            s3 = dcn_psum(c.astype(jnp.float32))  # converted
            s4 = dcn_pmean(a * 2.0)
            return s1, s2, s3, s4

        for _ in range(3):  # repeat: the schedule must be stable run-to-run
            s1, s2, s3, s4 = mixed(a, b, c)
            tot = sum(range(1, world + 1))
            np.testing.assert_allclose(np.asarray(s1), np.full(64, tot),
                                       rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(s2), np.arange(33, dtype=np.float32) * world,
                rtol=1e-6)
            np.testing.assert_allclose(np.asarray(s3), np.full(7, tot),
                                       rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(s4), np.full(64, 2.0 * tot / world), rtol=1e-6)

        # Gradient through the FFI custom call (custom_vjp wraps it).
        g = jax.grad(lambda v: dcn_psum(v).sum())(a)
        np.testing.assert_array_equal(np.asarray(g),
                                      np.full(64, float(world)))

        # Every OTHER FFI collective in one jit, interleaved — the full
        # zoo must stay order-coherent across ranks too.
        from tpunet.interop import (dcn_all_gather, dcn_all_to_all,
                                    dcn_broadcast, dcn_neighbor_exchange,
                                    dcn_reduce_scatter)

        v = jnp.arange(2 * world * 3, dtype=jnp.float32).reshape(
            2 * world, 3) * (rank + 1)

        @jax.jit
        def zoo(v):
            g1 = dcn_all_gather(v[0])            # (world, 3)
            rs = dcn_reduce_scatter(v)           # (2, 3) summed shard
            bc = dcn_broadcast(v[1], root=0)
            ne = dcn_neighbor_exchange(v[2])
            a2a = dcn_all_to_all(v[:world])
            return g1, rs, bc, ne, a2a

        g1, rs, bc, ne, a2a = zoo(v)
        base = np.arange(2 * world * 3, dtype=np.float32).reshape(
            2 * world, 3)
        np.testing.assert_allclose(
            np.asarray(g1), np.stack([base[0] * (r + 1)
                                      for r in range(world)]))
        tot = sum(range(1, world + 1))
        np.testing.assert_allclose(
            np.asarray(rs), base[2 * rank: 2 * rank + 2] * tot)
        np.testing.assert_allclose(np.asarray(bc), base[1] * 1.0)  # root 0
        prev = (rank - 1 + world) % world
        np.testing.assert_allclose(np.asarray(ne), base[2] * (prev + 1))
        np.testing.assert_allclose(
            np.asarray(a2a), np.stack([base[rank] * (r + 1)
                                       for r in range(world)]))

        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_ffi_multi_tensor_ordering_3proc():
    run_spawn_workers(_ordering_worker, 3)


def test_ffi_error_is_classified_as_comm_failure():
    # The handler mirrors NativeError's "tpunet native <op> failed" text so
    # elastic recovery's is_comm_failure string-match keeps working when
    # the failure surfaces as XlaRuntimeError from the custom call.
    from tpunet import distributed
    from tpunet.interop import _ffi_available, _jax_ffi_mod
    from tpunet.train.elastic import is_comm_failure

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        assert _ffi_available()
        bad = _jax_ffi_mod().ffi_call(
            "tpunet_all_reduce",
            jax.ShapeDtypeStruct((4,), jnp.float32), has_side_effect=True)
        with pytest.raises(Exception) as ei:
            bad(jnp.ones((4,), jnp.float32),
                dtype=np.int64(99), op=np.int64(0))  # invalid dtype code
        assert is_comm_failure(ei.value), str(ei.value)
    finally:
        distributed.finalize()


def test_ffi_every_target_in_lowering():
    # Each dcn_* must lower to ITS custom call on the CPU backend — a
    # silent fall-through to io_callback on any one op would quietly
    # reintroduce the 3-copy bridge tax there.
    from tpunet import distributed
    from tpunet.interop import (dcn_all_gather, dcn_all_to_all,
                                dcn_broadcast, dcn_neighbor_exchange,
                                dcn_psum, dcn_reduce_scatter)

    distributed.finalize()
    distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    try:
        x = jnp.ones((4, 2), jnp.float32)
        for fn, target in (
            (dcn_psum, "tpunet_all_reduce"),
            (dcn_all_gather, "tpunet_all_gather"),
            (dcn_reduce_scatter, "tpunet_reduce_scatter"),
            (dcn_broadcast, "tpunet_broadcast"),
            (dcn_neighbor_exchange, "tpunet_neighbor_exchange"),
        ):
            txt = jax.jit(fn).lower(x).as_text()
            assert target in txt, (target, txt[:500])
        txt = jax.jit(dcn_all_to_all).lower(
            jnp.ones((1, 4), jnp.float32)).as_text()
        assert "tpunet_all_to_all" in txt
    finally:
        distributed.finalize()


def _asymmetric_chain_worker(rank: int, world: int, port: int, q) -> None:
    # Rank-ASYMMETRIC trace (rank-dependent constants baked in) issuing two
    # data-independent neighbor exchanges: exactly the pattern that
    # cross-matched on the FFI path in dcn_ring_attention (round-5 bug).
    # after=(ea,) makes ea an operand of the second custom call, pinning
    # the order (optimization_barrier demonstrably does NOT); the
    # packed-exchange alternative is covered by test_dcn_ring_attention.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import dcn_neighbor_exchange

        distributed.initialize(f"127.0.0.1:{port}", rank, world)

        a = jnp.full((32,), 10.0 * (rank + 1), jnp.float32)
        b = jnp.full((32,), 100.0 * (rank + 1), jnp.float32)

        @jax.jit
        def ring_like(a, b):
            # rank-dependent constant makes per-rank HLO differ
            a = a + float(rank)
            ea = dcn_neighbor_exchange(a)
            eb = dcn_neighbor_exchange(b, after=(ea,))
            return ea, eb

        for _ in range(3):
            ea, eb = ring_like(a, b)
            prev = (rank - 1 + world) % world
            np.testing.assert_allclose(
                np.asarray(ea), np.full(32, 10.0 * (prev + 1) + prev))
            np.testing.assert_allclose(
                np.asarray(eb), np.full(32, 100.0 * (prev + 1)))
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_ffi_rank_asymmetric_trace_with_after_kwarg_4proc():
    run_spawn_workers(_asymmetric_chain_worker, 4)
