"""CI lanes-smoke lane: adaptive two-lane striping under an asymmetric path.

One process, two-lane loopback comms (BASIC engine) with a deterministic
3 ms delay fault on lane 1's send side — the deliberately asymmetric path.
Two phases, gated by counters (the PR 3/5 epistemic stance — no loopback
GB/s anywhere):

  * adaptive: TPUNET_LANE_ADAPT=1 must publish at least one weight epoch
    (tpunet_restripe_events_total >= 1), demote the delayed lane's weight
    below the fast lane's, and converge steady-state byte shares
    (tpunet_lane_bytes_total over a post-convergence window) to within 10%
    of the per-lane delivery-rate ratio (tpunet_lane_rate_bps);
  * uniform control: TPUNET_LANE_ADAPT=0 with equal weights pins ~50/50
    byte shares — the scheduler the adaptive path must beat, and the proof
    the skew above came from the weights, not the fault.

Every message is CRC-verified and content-checked: a sender/receiver layout
desync through any re-stripe boundary would corrupt payload bytes.

Run: python tests/lanes_smoke.py   (exit 0 = pass)
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["TPUNET_IMPLEMENT"] = "BASIC"
os.environ["TPUNET_LANES"] = "w=1,w=1"
os.environ["TPUNET_LANE_ADAPT_MS"] = "20"
os.environ["TPUNET_MIN_CHUNKSIZE"] = str(64 << 10)
os.environ["TPUNET_CRC"] = "1"

import numpy as np  # noqa: E402

MSG_BYTES = 256 << 10
CONVERGE_MSGS = 150
MEASURE_MSGS = 120
SHARE_BAND = 0.10


def _wire_pair(net_s, net_r):
    lc = net_r.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = net_s.connect(lc.handle)
    th.join()
    return sc, got["rc"], lc


def _run_msgs(sc, rc, n):
    src = np.arange(MSG_BYTES, dtype=np.uint8)
    for i in range(n):
        dst = np.zeros_like(src)
        r = rc.irecv(dst)
        sc.isend(src).wait(timeout=60)
        r.wait(timeout=60)
        assert np.array_equal(src, dst), f"payload corrupt at message {i}"


def _lane_gauge(metrics, family):
    from tpunet import telemetry

    out = {}
    for key, value in metrics.get(family, {}).items():
        lab = telemetry.labels(key)
        if "lane" in lab and lab.get("dir") in (None, "tx"):
            out[int(lab["lane"])] = int(value)
    return out


def main() -> int:
    from tpunet import telemetry, transport
    from tpunet.transport import Net

    failures = []

    def gate(cond, msg):
        print(("PASS " if cond else "FAIL ") + msg)
        if not cond:
            failures.append(msg)

    # ---- Phase 1: adaptive striping against the delayed lane -------------
    telemetry.reset()
    t0 = time.perf_counter()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            transport.fault_inject("stream=1:side=send:action=delay=3")
            _run_msgs(sc, rc, CONVERGE_MSGS)  # convergence window
            m = telemetry.metrics()
            restripes = sum(m.get("tpunet_restripe_events_total", {}).values())
            weights = _lane_gauge(m, "tpunet_lane_weight")
            gate(restripes >= 1,
                 f"adaptive scheduler published a weight epoch (restripes={restripes})")
            gate(weights.get(0, 0) > weights.get(1, 0),
                 f"delayed lane demoted below the fast lane (weights={weights})")
            # Steady-state window: counters measure shares AFTER convergence.
            telemetry.reset()
            _run_msgs(sc, rc, MEASURE_MSGS)
            m = telemetry.metrics()
            lanes = _lane_gauge(m, "tpunet_lane_bytes_total")
            rates = _lane_gauge(m, "tpunet_lane_rate_bps")
            total = sum(lanes.values())
            share_slow = lanes.get(1, 0) / total if total else 1.0
            rate_total = sum(rates.values())
            rate_share_slow = rates.get(1, 0) / rate_total if rate_total else 0.5
            gate(total > 0 and rate_total > 0,
                 f"lane byte/rate counters populated (bytes={lanes}, rates={rates})")
            gate(abs(share_slow - rate_share_slow) <= SHARE_BAND,
                 f"byte share tracks delivery-rate ratio within {SHARE_BAND:.0%} "
                 f"(share_slow={share_slow:.3f}, rate_share_slow={rate_share_slow:.3f})")
            gate(share_slow < 0.35,
                 f"slow lane carries well under uniform's 50% (share={share_slow:.3f})")
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                c.close()
    adaptive_s = time.perf_counter() - t0

    # ---- Phase 2: uniform control (same fault, adaptation off) -----------
    os.environ["TPUNET_LANE_ADAPT"] = "0"
    telemetry.reset()
    t0 = time.perf_counter()
    with Net() as ns, Net() as nr:
        sc, rc, lc = _wire_pair(ns, nr)
        try:
            transport.fault_inject("stream=1:side=send:action=delay=3")
            _run_msgs(sc, rc, MEASURE_MSGS)
            m = telemetry.metrics()
            lanes = _lane_gauge(m, "tpunet_lane_bytes_total")
            total = sum(lanes.values())
            share_slow = lanes.get(1, 0) / total if total else 0.0
            gate(abs(share_slow - 0.5) <= 0.02,
                 f"uniform control pins ~50/50 (share_slow={share_slow:.3f})")
            restripes = sum(m.get("tpunet_restripe_events_total", {}).values())
            gate(restripes == 0,
                 f"uniform control never re-stripes (restripes={restripes})")
        finally:
            transport.fault_clear()
            for c in (sc, rc, lc):
                c.close()
    uniform_s = time.perf_counter() - t0
    # Informational (wall clock is noisy on CI; counters carry the gates):
    # the uniform control inherits the slow lane's completion time.
    print(f"INFO adaptive window {adaptive_s:.2f}s vs uniform window "
          f"{uniform_s:.2f}s for the same byte budget")

    if failures:
        print(f"\nlanes_smoke: {len(failures)} gate(s) FAILED")
        return 1
    print("\nlanes_smoke: all gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
