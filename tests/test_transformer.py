"""Transformer family tests: forward numerics, TP/SP/EP shardings, training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.models import Transformer, transformer_partition_rules
from tpunet.parallel import batch_sharding, make_named_mesh, replicated, shard_params
from tpunet.train import TrainState, create_train_state, make_train_step


def _tiny(attn_impl="reference", mesh=None, n_experts=0, **kw):
    return Transformer(
        vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        n_experts=n_experts, compute_dtype=jnp.float32,
        attn_impl=attn_impl, mesh=mesh, **kw,
    )


def _tokens(rng, b, s, vocab=64):
    return jax.random.randint(rng, (b, s), 0, vocab)


def test_forward_shapes_dense():
    model = _tiny()
    toks = _tokens(jax.random.PRNGKey(0), 2, 16)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("moe_top_k,capacity_factor", [(1, 1.25), (2, 2.0)])
def test_forward_moe_and_aux_loss(moe_top_k, capacity_factor):
    model = _tiny(n_experts=4, moe_every=1, moe_top_k=moe_top_k,
                  capacity_factor=capacity_factor)
    toks = _tokens(jax.random.PRNGKey(0), 2, 16)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    logits, state = model.apply({"params": params}, toks, mutable=["intermediates"])
    assert logits.shape == (2, 16, 64)
    assert bool(jnp.all(jnp.isfinite(logits)))
    aux = jax.tree.leaves(state["intermediates"])
    assert len(aux) == 2  # both blocks MoE
    # Switch aux loss is >= 1 at uniform routing, finite always.
    assert all(np.isfinite(float(a)) for a in aux)


def test_causality():
    # Changing a future token must not change earlier logits.
    model = _tiny()
    toks = _tokens(jax.random.PRNGKey(0), 1, 16)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    base = model.apply({"params": params}, toks)
    toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % 64)
    pert = model.apply({"params": params}, toks2)
    np.testing.assert_allclose(
        np.asarray(base[0, :-1]), np.asarray(pert[0, :-1]), atol=1e-6
    )
    assert not np.allclose(np.asarray(base[0, -1]), np.asarray(pert[0, -1]))


def test_ring_attn_matches_reference_model():
    mesh = make_named_mesh({"dp": 2, "sp": 4})
    ref_model = _tiny("reference")
    ring_model = _tiny("ring", mesh=mesh)
    toks = _tokens(jax.random.PRNGKey(0), 2, 32)
    params = ref_model.init(jax.random.PRNGKey(1), toks)["params"]
    ref = ref_model.apply({"params": params}, toks)
    ring = ring_model.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_flash_attn_matches_reference_model():
    ref_model = _tiny("reference")
    flash_model = _tiny("flash")
    toks = _tokens(jax.random.PRNGKey(2), 1, 128)
    params = ref_model.init(jax.random.PRNGKey(1), toks)["params"]
    np.testing.assert_allclose(
        np.asarray(flash_model.apply({"params": params}, toks)),
        np.asarray(ref_model.apply({"params": params}, toks)),
        atol=1e-4, rtol=1e-4,
    )


def test_tp_sharded_forward_matches():
    # Megatron TP over mdl: sharded forward == replicated forward.
    mesh = make_named_mesh({"dp": 4, "mdl": 2})
    model = _tiny()
    toks = _tokens(jax.random.PRNGKey(0), 4, 16)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    expected = model.apply({"params": params}, toks)

    rules = transformer_partition_rules(tp_axis="mdl")
    shardings = shard_params(params, mesh, rules)
    params_sh = jax.device_put(params, shardings)
    toks_sh = jax.device_put(toks, batch_sharding(mesh))
    with mesh:
        got = jax.jit(lambda p, t: model.apply({"params": p}, t))(params_sh, toks_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("moe_top_k,capacity_factor", [(1, 1.25), (2, 2.0)])
def test_ep_sharded_moe_forward_matches(moe_top_k, capacity_factor):
    # Expert weights over ep axis; dispatch einsums become all-to-alls.
    mesh = make_named_mesh({"dp": 2, "ep": 4})
    model = _tiny(n_experts=4, moe_every=1, moe_top_k=moe_top_k,
                  capacity_factor=capacity_factor)
    toks = _tokens(jax.random.PRNGKey(0), 2, 16)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    expected = model.apply({"params": params}, toks)

    rules = transformer_partition_rules(tp_axis=None, ep_axis="ep")
    shardings = shard_params(params, mesh, rules)
    params_sh = jax.device_put(params, shardings)
    toks_sh = jax.device_put(toks, batch_sharding(mesh))
    with mesh:
        got = jax.jit(lambda p, t: model.apply({"params": p}, t))(params_sh, toks_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("n_experts", [0, 4])
def test_remat_matches_no_remat(n_experts):
    # Rematerialization must not change values — forward or gradients —
    # including the MoE path (sown aux loss under the lifted remat).
    plain = _tiny(n_experts=n_experts, moe_every=1)
    remat = _tiny(remat=True, n_experts=n_experts, moe_every=1)
    toks = _tokens(jax.random.PRNGKey(0), 2, 16)
    labels = jnp.roll(toks, -1, axis=1)
    params = plain.init(jax.random.PRNGKey(1), toks)["params"]

    np.testing.assert_allclose(
        np.asarray(remat.apply({"params": params}, toks)),
        np.asarray(plain.apply({"params": params}, toks)),
        atol=1e-6,
    )

    if n_experts:
        # The sown moe_aux_loss must survive the lifted remat transform and
        # carry the same values.
        _, ip = plain.apply({"params": params}, toks, mutable=["intermediates"])
        _, ir = remat.apply({"params": params}, toks, mutable=["intermediates"])
        aux_p = sorted(float(a) for a in jax.tree.leaves(ip["intermediates"]))
        aux_r = sorted(float(a) for a in jax.tree.leaves(ir["intermediates"]))
        assert len(aux_r) == len(aux_p) > 0
        np.testing.assert_allclose(aux_r, aux_p, atol=1e-6)

    def loss_fn(model):
        def f(p):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        return f

    g_plain = jax.grad(loss_fn(plain))(params)
    g_remat = jax.grad(loss_fn(remat))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        ),
        g_plain, g_remat,
    )


def test_train_step_includes_moe_aux_loss():
    """The Switch balancing term must reach the training loss (ADVICE r1):
    the same step with a larger moe_aux_weight must report a larger loss."""
    model = _tiny(n_experts=4, moe_every=1)
    tx = optax.adam(1e-2)
    toks = _tokens(jax.random.PRNGKey(0), 4, 16)
    labels = jnp.roll(toks, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(1), toks, tx)
    losses = {}
    for w in (0.0, 10.0):
        step = make_train_step(model, tx, donate=False, moe_aux_weight=w)
        _, loss = step(state, toks, labels, jax.random.PRNGKey(0))
        losses[w] = float(loss)
    # aux loss is e*sum(frac_tokens*frac_probs) >= 1 > 0, so weight 10 must
    # add a visible amount over weight 0.
    assert losses[10.0] > losses[0.0] + 1.0


@pytest.mark.parametrize("n_experts,moe_top_k", [(0, 1), (4, 1), (4, 2)])
def test_train_step_loss_decreases(n_experts, moe_top_k):
    model = _tiny(n_experts=n_experts, moe_top_k=moe_top_k,
                  capacity_factor=2.0 if moe_top_k > 1 else 1.25)
    tx = optax.adam(1e-2)
    toks = _tokens(jax.random.PRNGKey(0), 4, 16)
    labels = jnp.roll(toks, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(1), toks, tx)
    step = make_train_step(model, tx, donate=False)
    losses = []
    s = state
    for i in range(5):
        s, loss = step(s, toks, labels, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_flash_block_size_plumbing():
    """Non-default flash tile sizes thread Transformer -> Block ->
    SelfAttention -> flash_attention and keep parity with the reference
    path (the knob exists so an on-chip block sweep can be APPLIED —
    256 is Mosaic-legal on compiled TPU, unlike sub-128 tiles)."""
    kw = dict(vocab=64, d_model=128, n_layers=1, n_heads=2, d_ff=128,
              compute_dtype=jnp.bfloat16)
    m = Transformer(attn_impl="flash", flash_block_q=256, flash_block_k=256,
                    **kw)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (1, 256)), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), toks)
    ref = Transformer(attn_impl="reference", **kw)
    a, b = m.apply(params, toks), ref.apply(params, toks)
    err = float(jnp.max(jnp.abs(a - b)) / jnp.maximum(jnp.max(jnp.abs(b)), 1.0))
    assert err < 0.03, f"flash block_q/k=256 parity {err}"


def test_flash_block_size_validation():
    """Explicit tile sizes that would be silently ignored (untileable ->
    reference fallback; non-lane-aligned -> Mosaic clamp) fail loud."""
    kw = dict(vocab=64, d_model=128, n_layers=1, n_heads=2, d_ff=128,
              attn_impl="flash", compute_dtype=jnp.bfloat16)
    toks = jnp.zeros((1, 256), jnp.int32)
    with pytest.raises(ValueError, match="reference path"):
        Transformer(flash_block_q=128, flash_block_k=256, **kw).init(
            jax.random.PRNGKey(0), toks)  # bq % bk != 0
    with pytest.raises(ValueError, match="Mosaic-legal"):
        Transformer(flash_block_q=64, flash_block_k=64, **kw).init(
            jax.random.PRNGKey(0), toks)  # bq not a multiple of 128


def test_flash_block_size_decode_exempt():
    """decode=True never routes cached steps through the flash kernel, so
    swept tile sizes must not break generation (s=1 steps and arbitrary
    prompt lengths are legal there)."""
    from tpunet.models import generate

    m = Transformer(vocab=64, d_model=64, n_layers=1, n_heads=2, d_ff=64,
                    attn_impl="flash", flash_block_q=256, flash_block_k=256,
                    compute_dtype=jnp.float32)
    # Params come from a tileable training-shape init (real usage: train at
    # the swept seq, then decode arbitrary prompts).
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 256), jnp.int32))["params"]
    prompt = jnp.zeros((1, 5), jnp.int32)  # length 5: untileable on purpose
    out = generate(m, params, prompt, 3)
    assert out.shape == (1, 8)



def test_moe_top_k_equals_experts_is_dense_mixture():
    """Closed form: with top_k == n_experts and ample capacity nothing is
    dropped and the renormalized gates ARE the softmax probs, so the MoE
    output must equal the dense probs-weighted mixture of every expert."""
    from tpunet.models.transformer import MoeMlp

    e, d, f = 3, 8, 16
    m = MoeMlp(n_experts=e, d_ff=f, capacity_factor=float(e),
               compute_dtype=jnp.float32, top_k=e)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, d), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)

    p = variables["params"]
    xt = x.reshape(-1, d)
    probs = jax.nn.softmax(xt @ p["router"], axis=-1)  # (t, e)
    dense = jnp.zeros_like(xt)
    for j in range(e):
        hj = jax.nn.gelu(xt @ p["wi"][j])
        dense = dense + probs[:, j:j + 1] * (hj @ p["wo"][j])
    np.testing.assert_allclose(
        np.asarray(y.reshape(-1, d)), np.asarray(dense), atol=1e-5, rtol=1e-5)



def test_moe_top_k_validation():
    from tpunet.models.transformer import MoeMlp

    x = jnp.zeros((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="top_k"):
        MoeMlp(n_experts=4, d_ff=8, top_k=5).init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="top_k"):
        MoeMlp(n_experts=4, d_ff=8, top_k=0).init(jax.random.PRNGKey(0), x)
