"""Elastic recovery: a rank dies mid-training, a replacement joins, the ring
rebuilds at a new generation, and training resumes from the checkpoint on
the exact trajectory of a run that never failed.

The reference's whole failure story is a panic (SURVEY §5); tpunet's fault
tests (test_fault_paths.py) already pin "peer death -> typed error on every
rank". This file pins the recovery half built on top of that contract
(tpunet/train/elastic.py)."""

from __future__ import annotations

import os
import signal

import numpy as np

from conftest import free_port

STEPS = 12
DIE_STEP = 5
WORLD = 3
NPARAMS = 256


def _grad(step: int, rank: int) -> np.ndarray:
    rng = np.random.default_rng(7 * step + rank)
    return rng.standard_normal(NPARAMS).astype(np.float32)


def _latest_step(ckpt) -> int:
    steps = [int(p.stem.split("_")[1]) for p in ckpt.glob("step_*.npy")]
    return max(steps, default=-1)


def _elastic_worker(rank: int, world: int, port: int, q, dirpath: str,
                    die: bool) -> None:
    try:
        from pathlib import Path

        from tpunet.train.elastic import run_elastic

        ckpt = Path(dirpath)

        def train_once(comm, gen):
            latest = _latest_step(ckpt)
            if latest >= 0:
                params = np.load(ckpt / f"step_{latest}.npy")
                start = latest + 1
            else:
                params = np.zeros(NPARAMS, np.float32)
                start = 0
            for step in range(start, STEPS):
                if die and step == DIE_STEP:
                    os.kill(os.getpid(), signal.SIGKILL)
                g = comm.all_reduce(_grad(step, rank)) / world
                params = params - 0.1 * g
                if rank == 0:
                    tmp = ckpt / f".step_{step}.tmp.npy"
                    np.save(tmp, params)
                    os.replace(tmp, ckpt / f"step_{step}.npy")
                comm.barrier()  # checkpoint visible before anyone advances
            return params

        params = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=4,
        )
        q.put((rank, ("OK", params.tolist())))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def _expected_params() -> np.ndarray:
    params = np.zeros(NPARAMS, np.float32)
    for step in range(STEPS):
        g = np.sum([_grad(step, r) for r in range(WORLD)], axis=0,
                   dtype=np.float32) / WORLD
        params = params - 0.1 * g
    return params


def test_rank_death_rebuild_and_exact_resume(tmp_path):
    import multiprocessing as mp

    # Window ordering matters: a replacement that read a stale generation
    # probes a dead coordinator port and must give up FAST (connect retry),
    # while survivors parked at the new generation's rendezvous must wait
    # LONGER than that probe (bootstrap timeout) — otherwise they burn their
    # restart budget bumping generations the replacement can never catch.
    os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = "30000"
    os.environ["TPUNET_CONNECT_RETRY_MS"] = "2000"
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        port = free_port()
        procs = {
            r: ctx.Process(
                target=_elastic_worker,
                args=(r, WORLD, port, q, str(tmp_path), r == 1),
            )
            for r in range(WORLD)
        }
        for p in procs.values():
            p.start()

        # Supervise: when the victim exits without reporting, respawn it
        # (without the die flag) — the job-scheduler half of elasticity.
        respawned = False
        results = {}
        import queue as queue_mod
        import time

        deadline = time.time() + 240
        while len(results) < WORLD and time.time() < deadline:
            try:
                rank, payload = q.get(timeout=1.0)
                results[rank] = payload
            except queue_mod.Empty:
                pass
            victim = procs[1]
            if not respawned and not victim.is_alive() and 1 not in results:
                victim.join()
                assert victim.exitcode == -signal.SIGKILL
                procs[1] = ctx.Process(
                    target=_elastic_worker,
                    args=(1, WORLD, port, q, str(tmp_path), False),
                )
                procs[1].start()
                respawned = True
        for p in procs.values():
            p.join(timeout=30)
            if p.is_alive():
                p.kill()

        assert respawned, "victim never died — test exercised nothing"
        assert len(results) == WORLD, f"missing ranks: {sorted(results)}"
        bad = {r: v for r, v in results.items() if v[0] != "OK"}
        assert not bad, f"worker failures: {bad}"

        # Recovery happened: the generation advanced past 0.
        from tpunet.train.elastic import read_generation

        assert read_generation(tmp_path) >= 1

        # All ranks bitwise identical (lockstep held through the rebuild),
        # and equal to the analytic trajectory to float32 rounding — the
        # analytic sum orders additions differently than the ring (1-ulp
        # noise), but a lost or double-replayed step would be off by ~0.1
        # per step, 6 orders of magnitude beyond this tolerance.
        expect = _expected_params()
        final = {r: np.asarray(v[1], np.float32) for r, v in results.items()}
        for r in range(1, WORLD):
            np.testing.assert_array_equal(
                final[r], final[0], err_msg=f"rank {r} != rank 0 after recovery"
            )
        np.testing.assert_allclose(final[0], expect, rtol=5e-6, atol=5e-7)
    finally:
        os.environ.pop("TPUNET_BOOTSTRAP_TIMEOUT_MS", None)
        os.environ.pop("TPUNET_CONNECT_RETRY_MS", None)
