"""Elastic recovery: a rank dies mid-training, a replacement joins, the ring
rebuilds at a new generation, and training resumes from the checkpoint on
the exact trajectory of a run that never failed.

The reference's whole failure story is a panic (SURVEY §5); tpunet's fault
tests (test_fault_paths.py) already pin "peer death -> typed error on every
rank". This file pins the recovery half built on top of that contract
(tpunet/train/elastic.py)."""

from __future__ import annotations

import os
import signal

import numpy as np

from conftest import free_port

STEPS = 12
DIE_STEP = 5
WORLD = 3
NPARAMS = 256


def _supervise_with_respawn(worker, world: int, victim: int | None,
                            dirpath: str, deadline_s: float,
                            respawn: bool = True):
    """Spawn `world` workers (victim gets die=True); with `respawn`, restart
    the victim once after it dies (the job-scheduler half of elasticity),
    else leave it dead (shrink policy). victim=None runs a clean control
    job: nobody dies, all ranks must report. Collects each expected rank's
    queue payload and asserts none failed. Returns {rank: payload}.

    The rendezvous timing knobs matter: a replacement that read a stale
    generation probes a dead coordinator port and must give up FAST (connect
    retry), while survivors parked at the new generation's rendezvous must
    wait LONGER than that probe (bootstrap timeout) — otherwise they burn
    their restart budget bumping generations the replacement cannot catch.
    """
    import multiprocessing as mp
    import queue as queue_mod
    import time

    os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = "30000"
    os.environ["TPUNET_CONNECT_RETRY_MS"] = "2000"
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        port = free_port()
        procs = {
            r: ctx.Process(target=worker, args=(r, world, port, q, dirpath, r == victim))
            for r in range(world)
        }
        for p in procs.values():
            p.start()

        expected = (set(range(world)) if respawn or victim is None
                    else set(range(world)) - {victim})
        respawned = False
        victim_died = False
        results: dict = {}
        deadline = time.time() + deadline_s
        while len(expected - results.keys()) > 0 and time.time() < deadline:
            try:
                rank, payload = q.get(timeout=1.0)
                results[rank] = payload
            except queue_mod.Empty:
                pass
            if (victim is not None and not victim_died
                    and not procs[victim].is_alive()
                    and victim not in results):
                # A worker that failed (rather than SIGKILLed itself) queues
                # its FAIL payload and exits 0 — drain before asserting the
                # exitcode, or the traceback in the queue would be masked.
                try:
                    while True:
                        rank, payload = q.get_nowait()
                        results[rank] = payload
                except queue_mod.Empty:
                    pass
                if victim in results:
                    continue
                procs[victim].join()
                # Recorded BEFORE the cleanup loop's p.kill() can also
                # produce -SIGKILL — this is the real "victim died" signal.
                assert procs[victim].exitcode == -signal.SIGKILL
                victim_died = True
                if respawn:
                    procs[victim] = ctx.Process(
                        target=worker,
                        args=(victim, world, port, q, dirpath, False),
                    )
                    procs[victim].start()
                    respawned = True
        for p in procs.values():
            p.join(timeout=30)
            if p.is_alive():
                p.kill()

        # Worker failures FIRST: their payload carries the real traceback,
        # and any later assertion (died, missing) is usually downstream of
        # the same root cause.
        bad = {r: v for r, v in results.items() if v[0] != "OK"}
        assert not bad, f"worker failures: {bad}"
        if victim is not None:
            assert victim_died, "victim never died — test exercised nothing"
            if respawn:
                assert respawned
        missing = sorted(expected - results.keys())
        assert not missing, f"missing ranks: {missing}"
        return results
    finally:
        os.environ.pop("TPUNET_BOOTSTRAP_TIMEOUT_MS", None)
        os.environ.pop("TPUNET_CONNECT_RETRY_MS", None)


def _grad(step: int, rank: int) -> np.ndarray:
    rng = np.random.default_rng(7 * step + rank)
    return rng.standard_normal(NPARAMS).astype(np.float32)


def _latest_step(ckpt) -> int:
    steps = [int(p.stem.split("_")[1]) for p in ckpt.glob("step_*.npy")]
    return max(steps, default=-1)


def _elastic_worker(rank: int, world: int, port: int, q, dirpath: str,
                    die: bool) -> None:
    try:
        from pathlib import Path

        from tpunet.train.elastic import run_elastic

        ckpt = Path(dirpath)

        def train_once(comm, gen):
            latest = _latest_step(ckpt)
            if latest >= 0:
                params = np.load(ckpt / f"step_{latest}.npy")
                start = latest + 1
            else:
                params = np.zeros(NPARAMS, np.float32)
                start = 0
            for step in range(start, STEPS):
                if die and step == DIE_STEP:
                    os.kill(os.getpid(), signal.SIGKILL)
                g = comm.all_reduce(_grad(step, rank)) / world
                params = params - 0.1 * g
                if rank == 0:
                    tmp = ckpt / f".step_{step}.tmp.npy"
                    np.save(tmp, params)
                    os.replace(tmp, ckpt / f"step_{step}.npy")
                comm.barrier()  # checkpoint visible before anyone advances
            return params

        params = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=4,
        )
        q.put((rank, ("OK", params.tolist())))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def _expected_params() -> np.ndarray:
    params = np.zeros(NPARAMS, np.float32)
    for step in range(STEPS):
        g = np.sum([_grad(step, r) for r in range(WORLD)], axis=0,
                   dtype=np.float32) / WORLD
        params = params - 0.1 * g
    return params


def _shrink_worker(rank: int, world: int, port: int, q, dirpath: str,
                   die: bool) -> None:
    # Shrink policy: NO replacement ever comes; survivors must re-rank and
    # continue at world-1. Gradients key off comm.rank (the per-generation
    # rank), so the post-shrink trajectory is analytically reproducible.
    try:
        from pathlib import Path

        from tpunet.train.elastic import run_elastic

        ckpt = Path(dirpath)

        def train_once(comm, gen):
            w, r = comm.world_size, comm.rank
            latest = _latest_step(ckpt)
            if latest >= 0:
                params = np.load(ckpt / f"step_{latest}.npy")
                start = latest + 1
            else:
                params = np.zeros(NPARAMS, np.float32)
                start = 0
            for step in range(start, STEPS):
                if die and step == DIE_STEP:
                    os.kill(os.getpid(), signal.SIGKILL)
                g = comm.all_reduce(_grad(step, r)) / w
                params = params - 0.1 * g
                if r == 0:
                    tmp = ckpt / f".step_{step}.tmp.npy"
                    np.save(tmp, params)
                    os.replace(tmp, ckpt / f"step_{step}.npy")
                comm.barrier()
            return params, w

        (params, final_world) = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=3,
            allow_shrink=True,
            shrink_grace_s=3.0,
            min_world=2,
        )
        q.put((rank, ("OK", params.tolist(), final_world)))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def test_shrink_requires_advertise_host_on_nonloopback(tmp_path):
    # Defaulting to the original coordinator's host would re-elect the new
    # coordinator onto the machine whose death we are shrinking around.
    import pytest

    from tpunet.train.elastic import run_elastic

    with pytest.raises(ValueError, match="advertise_host"):
        run_elastic(lambda c, g: None, coordinator="10.0.0.1:29500", rank=0,
                    world_size=2, directory=tmp_path, allow_shrink=True)


def _cascade_worker(rank: int, world: int, port: int, q, dirpath: str,
                    die_step) -> None:
    # die_step: step at which THIS member SIGKILLs itself (None = survivor).
    # Gradients key off comm.rank, so each membership phase is analytic.
    try:
        from pathlib import Path

        from tpunet.train.elastic import run_elastic

        ckpt = Path(dirpath)

        def train_once(comm, gen):
            w, r = comm.world_size, comm.rank
            latest = _latest_step(ckpt)
            params = (np.load(ckpt / f"step_{latest}.npy") if latest >= 0
                      else np.zeros(NPARAMS, np.float32))
            for step in range(latest + 1, STEPS):
                if die_step is not None and step == die_step:
                    os.kill(os.getpid(), signal.SIGKILL)
                g = comm.all_reduce(_grad(step, r)) / w
                params = params - 0.1 * g
                if r == 0:
                    tmp = ckpt / f".step_{step}.tmp.npy"
                    np.save(tmp, params)
                    os.replace(tmp, ckpt / f"step_{step}.npy")
                comm.barrier()
            return params, w

        params, final_world = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=4,
            allow_shrink=True,
            shrink_grace_s=3.0,
            min_world=1,
        )
        q.put((rank, ("OK", params.tolist(), final_world)))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def test_cascading_shrink_to_solo(tmp_path):
    # Two failures in sequence: 3 ranks -> rank 1 dies at step 5 (shrink to
    # world 2) -> member 2 dies at step 8 (shrink to world 1) -> member 0
    # finishes SOLO on the exact three-phase analytic trajectory.
    import multiprocessing as mp
    import queue as queue_mod
    import time

    os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = "30000"
    os.environ["TPUNET_CONNECT_RETRY_MS"] = "2000"
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        # Dedicated queue per victim (mp.Queue SIGKILL write-lock hazard —
        # see _prewiring_victim in test_fault_paths.py).
        vq1, vq2 = ctx.Queue(), ctx.Queue()
        port = free_port()
        procs = {
            0: ctx.Process(target=_cascade_worker,
                           args=(0, WORLD, port, q, str(tmp_path), None)),
            1: ctx.Process(target=_cascade_worker,
                           args=(1, WORLD, port, vq1, str(tmp_path), 5)),
            2: ctx.Process(target=_cascade_worker,
                           args=(2, WORLD, port, vq2, str(tmp_path), 8)),
        }
        for p in procs.values():
            p.start()
        result = None
        deadline = time.time() + 240
        while result is None and time.time() < deadline:
            try:
                result = q.get(timeout=1.0)
            except queue_mod.Empty:
                pass
        for p in procs.values():
            p.join(timeout=30)
            if p.is_alive():
                p.kill()

        assert result is not None, "survivor never reported"
        rank, payload = result
        assert rank == 0 and payload[0] == "OK", payload
        assert payload[2] == 1, f"final world {payload[2]} != 1 (solo)"
        assert procs[1].exitcode == -signal.SIGKILL
        assert procs[2].exitcode == -signal.SIGKILL

        # Three-phase analytic trajectory: W=3 for steps 0-4, W=2 (members
        # {0,2} -> ranks {0,1}) for 5-7, W=1 for 8-11.
        params = np.zeros(NPARAMS, np.float32)
        for step in range(STEPS):
            w = 3 if step < 5 else (2 if step < 8 else 1)
            g = np.sum([_grad(step, r) for r in range(w)], axis=0,
                       dtype=np.float32) / w
            params = params - 0.1 * g
        np.testing.assert_allclose(np.asarray(payload[1], np.float32), params,
                                   rtol=5e-6, atol=5e-7)
    finally:
        os.environ.pop("TPUNET_BOOTSTRAP_TIMEOUT_MS", None)
        os.environ.pop("TPUNET_CONNECT_RETRY_MS", None)


def test_shrink_to_survivors(tmp_path):
    results = _supervise_with_respawn(
        _shrink_worker, world=WORLD, victim=1, dirpath=str(tmp_path),
        deadline_s=240, respawn=False,
    )
    assert results[0][2] == 2 and results[2][2] == 2, "world did not shrink to 2"

    # Analytic two-phase trajectory: steps 0..DIE_STEP-1 averaged over 3
    # ranks; steps DIE_STEP.. averaged over the re-ranked survivors
    # {0,2} -> new ranks {0,1}. Ring sum order differs from np.sum by
    # ~1 ulp, hence the tight-but-not-bitwise tolerance (a lost or
    # double step would be ~0.1 off).
    params = np.zeros(NPARAMS, np.float32)
    for step in range(STEPS):
        w = WORLD if step < DIE_STEP else 2
        g = np.sum([_grad(step, r) for r in range(w)], axis=0,
                   dtype=np.float32) / w
        params = params - 0.1 * g
    final = {r: np.asarray(v[1], np.float32) for r, v in results.items()}
    np.testing.assert_array_equal(final[0], final[2])
    np.testing.assert_allclose(final[0], params, rtol=5e-6, atol=5e-7)


def _jax_elastic_worker(rank: int, world: int, port: int, q, dirpath: str,
                        die: bool) -> None:
    # The full stack under elasticity: jitted cross-host train step (interop
    # io_callback -> native ring), orbax checkpoints, and failure surfacing
    # as XlaRuntimeError WRAPPING the native error — the string-match half of
    # is_comm_failure, which the transport-level test never exercises.
    try:
        from pathlib import Path

        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from tpunet.models import Transformer
        from tpunet.train import (create_train_state, make_train_step,
                                  restore_pytree, run_elastic, save_pytree)

        ckpt = Path(dirpath)
        steps = 8
        model = Transformer(vocab=32, d_model=16, n_layers=2, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        tx = optax.sgd(0.05)
        toks = jax.random.randint(jax.random.PRNGKey(10 + rank), (2, 8), 0, 32)
        labels = jnp.roll(toks, -1, axis=1)

        def train_once(comm, gen):
            state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
            done = [int(p.name.split("_")[1]) for p in ckpt.glob("jstep_*")]
            start = max(done, default=-1) + 1
            if start > 0:
                state = restore_pytree(ckpt / f"jstep_{start - 1}", state)
            step = make_train_step(model, tx, cross_host=True, donate=False)
            for s in range(start, steps):
                if die and s == 3:
                    os.kill(os.getpid(), signal.SIGKILL)
                state, loss = step(state, toks, labels, jax.random.PRNGKey(s))
                assert np.isfinite(float(loss))
                if rank == 0 and not (ckpt / f"jstep_{s}").exists():
                    save_pytree(ckpt / f"jstep_{s}", state)
                comm.barrier()
            return state

        state = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=3,
        )
        from jax.flatten_util import ravel_pytree

        flat = np.asarray(ravel_pytree(state.params)[0])
        q.put((rank, ("OK", flat[:64].tolist())))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def test_jax_trainer_elastic_recovery(tmp_path):
    # This test pins the FULL-STACK recovery path (XlaRuntimeError
    # classification, jit step across generations, orbax restore); numeric
    # exactness vs an uninterrupted run is the transport-level sibling's job
    # (its analytic _expected_params check). Here: ranks in lockstep, a
    # recovery actually happened, and no step was skipped on resume (every
    # per-step checkpoint exists — a start-index off-by-one leaves a hole).
    results = _supervise_with_respawn(
        _jax_elastic_worker, world=2, victim=1, dirpath=str(tmp_path),
        deadline_s=300,
    )
    np.testing.assert_array_equal(
        np.asarray(results[0][1]), np.asarray(results[1][1]),
        err_msg="ranks diverged after jax-trainer recovery",
    )
    from tpunet.train.elastic import read_generation

    assert read_generation(tmp_path) >= 1
    missing = [s for s in range(8) if not (tmp_path / f"jstep_{s}").exists()]
    assert not missing, f"steps never checkpointed (skipped on resume?): {missing}"


def test_rank_death_rebuild_and_exact_resume(tmp_path):
    results = _supervise_with_respawn(
        _elastic_worker, world=WORLD, victim=1, dirpath=str(tmp_path),
        deadline_s=240,
    )

    # Recovery happened: the generation advanced past 0.
    from tpunet.train.elastic import read_generation

    assert read_generation(tmp_path) >= 1

    # All ranks bitwise identical (lockstep held through the rebuild),
    # and equal to the analytic trajectory to float32 rounding — the
    # analytic sum orders additions differently than the ring (1-ulp
    # noise), but a lost or double-replayed step would be off by ~0.1
    # per step, 6 orders of magnitude beyond this tolerance.
    expect = _expected_params()
    final = {r: np.asarray(v[1], np.float32) for r, v in results.items()}
    for r in range(1, WORLD):
        np.testing.assert_array_equal(
            final[r], final[0], err_msg=f"rank {r} != rank 0 after recovery"
        )
    np.testing.assert_allclose(final[0], expect, rtol=5e-6, atol=5e-7)


def _fit_elastic_worker(rank: int, world: int, port: int, q, dirpath: str,
                        die: bool) -> None:
    # VERDICT r3 item 7: the elastic train callback is the REAL training
    # driver — fit() with its checkpoint manager, cadence, and resume — not
    # a bespoke inline loop. Each member checkpoints into its own orbax dir
    # (member-keyed: stable across generations even when shrink reassigns
    # comm ranks); on (re)entry every rank restores from the MOST ADVANCED
    # member dir — all dirs hold the same bitwise trajectory in dp lockstep,
    # and rendezvous has already settled every live process's async saves
    # (fit closes its manager on the way out), so the choice is stable.
    try:
        from pathlib import Path

        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from tpunet.models import Transformer
        from tpunet.train import (CheckpointManager, create_train_state, fit,
                                  make_train_step, run_elastic)

        steps, die_at = 6, 3
        base = Path(dirpath)
        model = Transformer(vocab=32, d_model=16, n_layers=2, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        tx = optax.sgd(0.05)

        def batches(comm_rank):
            s = 0
            while True:
                rng = np.random.default_rng((123 + comm_rank, s))
                toks = rng.integers(0, 32, (2, 8)).astype(np.int32)
                yield toks, np.roll(toks, -1, axis=1)
                s += 1

        def restore_most_advanced(state):
            best, best_dir = -1, None
            for d in sorted(base.glob("orbax_m*")):
                with CheckpointManager(str(d)) as mgr:
                    latest = mgr.latest_step()
                if latest is not None and latest > best:
                    best, best_dir = latest, d
            if best_dir is not None:
                with CheckpointManager(str(best_dir)) as mgr:
                    state = mgr.restore_latest(state) or state
            return state

        def train_once(comm, gen):
            init_toks = next(batches(comm.rank))[0]
            state, _ = create_train_state(
                model, jax.random.PRNGKey(0), jnp.asarray(init_toks), tx)
            state = restore_most_advanced(state)
            step = make_train_step(model, tx, cross_host=True, donate=False)

            def hook(m):
                if die and m["step"] == die_at:
                    os.kill(os.getpid(), signal.SIGKILL)

            state = fit(
                state, step, batches(comm.rank), steps=steps,
                rng=jax.random.PRNGKey(0),
                checkpoint_dir=str(base / f"orbax_m{rank}"),
                checkpoint_every=1, log_every=1, log_fn=hook,
                skip_batches_on_resume=True,
            )
            return state, comm.world_size

        state, final_world = run_elastic(
            train_once,
            coordinator=f"127.0.0.1:{port}",
            rank=rank,
            world_size=world,
            directory=dirpath,
            max_restarts=3,
            allow_shrink=world > 2,
            min_world=1,
            shrink_grace_s=5.0,
        )
        from jax.flatten_util import ravel_pytree

        flat = np.asarray(ravel_pytree(state.params)[0])
        q.put((rank, ("OK", (flat[:64].tolist(), int(state.step), final_world))))
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",
                      traceback.format_exc()[-600:])))


def test_fit_under_elastic_exact_resume(tmp_path):
    # SIGKILL mid-fit at full world: the victim dies inside fit()'s step
    # loop (before that step's checkpoint lands), a replacement respawns,
    # and the final params match a control run that never failed — BITWISE.
    # This pins the whole composition: fit's cadence saves, the
    # most-advanced-member restore, skip_batches_on_resume stream
    # realignment, and run_elastic's generation rebuild.
    crash_dir = tmp_path / "crash"
    ctrl_dir = tmp_path / "ctrl"
    crash_dir.mkdir()
    ctrl_dir.mkdir()
    results = _supervise_with_respawn(
        _fit_elastic_worker, world=2, victim=1, dirpath=str(crash_dir),
        deadline_s=300,
    )
    from tpunet.train.elastic import read_generation

    assert read_generation(crash_dir) >= 1
    control = _supervise_with_respawn(
        _fit_elastic_worker, world=2, victim=None, dirpath=str(ctrl_dir),
        deadline_s=240)

    crash_params = {r: np.asarray(v[1][0], np.float32) for r, v in results.items()}
    ctrl_params = {r: np.asarray(v[1][0], np.float32) for r, v in control.items()}
    np.testing.assert_array_equal(
        crash_params[0], crash_params[1],
        err_msg="ranks diverged after fit-under-elastic recovery")
    np.testing.assert_array_equal(
        crash_params[0], ctrl_params[0],
        err_msg="recovered trajectory != uninterrupted control run")
    assert all(v[1][1] == 6 for v in results.values())  # full schedule ran
    assert all(v[1][2] == 2 for v in results.values())  # world preserved


def test_fit_under_elastic_shrink(tmp_path):
    # SIGKILL mid-fit with shrink policy (world 3 -> 2): survivors seal a
    # smaller membership, restore the most advanced member checkpoint, and
    # finish the schedule in lockstep at world-1. (The trajectory legally
    # deviates from an uninterrupted run after the shrink point — the mean
    # gradient is over fewer ranks — so the exactness assertion here is
    # lockstep + schedule completion + world, not control equality.)
    results = _supervise_with_respawn(
        _fit_elastic_worker, world=3, victim=2, dirpath=str(tmp_path),
        deadline_s=300, respawn=False,
    )
    from tpunet.train.elastic import read_generation

    assert read_generation(tmp_path) >= 1
    final = {r: np.asarray(v[1][0], np.float32) for r, v in results.items()}
    np.testing.assert_array_equal(
        final[0], final[1], err_msg="survivors diverged after shrink")
    assert all(v[1][1] == 6 for v in results.values())
    assert all(v[1][2] == 2 for v in results.values())  # shrank 3 -> 2
