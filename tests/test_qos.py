"""Transport QoS (docs/DESIGN.md "Transport QoS").

Socket-free first:
  * config registration: TPUNET_TRAFFIC_CLASS / TPUNET_QOS_WEIGHTS /
    TPUNET_QOS_INFLIGHT_BYTES validate loudly (ValueError naming the var);
  * DRR arithmetic goldens through ``tpunet_c_qos_drr_golden`` — strict
    control priority, the weighted latency/bulk interleave, FIFO within a
    class — pure arithmetic, no sockets, no clocks;
  * ``qos_state()`` echoes the native scheduler's parsed config.

Then with sockets (spawned workers, so per-process env snapshots arm the
scheduler before any native call):
  * traffic-class negotiation mismatch fails typed on BOTH ranks (the
    codec/algo-handshake stance);
  * admission backpressure: an isend over the class budget raises
    QosAdmissionError (-8) with NOTHING enqueued, and admits again once the
    in-flight send is consumed;
  * the serve router treats that error as retry-front-of-queue, not a rank
    death;
  * two-tenant contention on one gated engine process: both classes' byte
    counters move (rx proves the preamble class nibble), and the
    latency-class p99 wire-credit queue wait stays inside its budget while
    a bulk tenant floods the window;
  * chaos: a fault-injected stream close that kills a bulk data stream
    mid-flood must not stall the latency lane — held credits are released
    on failure (starvation freedom under failover).
"""

from __future__ import annotations

import os
import threading
import types
from collections import deque

import numpy as np
import pytest

from conftest import run_spawn_workers
from tpunet import _native, transport

# ---------------------------------------------------------------------------
# Config registration (loud-validation contract).


def test_config_registers_traffic_class(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_TRAFFIC_CLASS", "latency")
    assert Config.from_env().traffic_class == "latency"
    monkeypatch.setenv("TPUNET_TRAFFIC_CLASS", "express")
    with pytest.raises(ValueError, match="TPUNET_TRAFFIC_CLASS"):
        Config.from_env()


def test_config_validates_qos_weights(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_QOS_WEIGHTS", "latency=8,bulk=2")
    assert Config.from_env().qos_weights == "latency=8,bulk=2"
    for bad in ("latency=0", "express=3", "latency", "latency=ten"):
        monkeypatch.setenv("TPUNET_QOS_WEIGHTS", bad)
        with pytest.raises(ValueError, match="TPUNET_QOS_WEIGHTS"):
            Config.from_env()


def test_config_validates_qos_inflight_bytes(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_QOS_INFLIGHT_BYTES", "latency=64K,bulk=4M,wire=1M")
    assert Config.from_env().qos_inflight_bytes == "latency=64K,bulk=4M,wire=1M"
    for bad in ("bulk=lots", "bulk", "turbo=1M"):
        monkeypatch.setenv("TPUNET_QOS_INFLIGHT_BYTES", bad)
        with pytest.raises(ValueError, match="TPUNET_QOS_INFLIGHT_BYTES"):
            Config.from_env()


def test_net_rejects_unknown_traffic_class():
    with pytest.raises(ValueError, match="traffic_class"):
        transport.Net(traffic_class="express")


# ---------------------------------------------------------------------------
# DRR arithmetic goldens (tpunet_c_qos_drr_golden — no sockets).


def test_drr_strict_control_priority_and_preemption():
    # bulk arrived FIRST; control jumps everything, latency (weight 2)
    # preempts bulk, bulk drains last — one-chunk window.
    order = transport.qos_drr_golden(
        "latency=2,bulk=1", "wire=64K",
        "bulk:64K,latency:64K,control:64K,latency:64K")
    assert order == ["control", "latency", "latency", "bulk"]


def test_drr_weighted_interleave_golden():
    # Sustained 2-class contention at weights 2:1, equal 64K chunks: the
    # scheduler must produce exactly the 2:1 interleave until the latency
    # queue drains, then serve the bulk tail.
    chunks = ",".join(["latency:64K"] * 6 + ["bulk:64K"] * 6)
    order = transport.qos_drr_golden("latency=2,bulk=1", "wire=64K", chunks)
    assert order == ["latency", "latency", "bulk"] * 3 + ["bulk"] * 3


def test_drr_equal_weights_alternate():
    chunks = "latency:64K,bulk:64K,latency:64K,bulk:64K"
    order = transport.qos_drr_golden("latency=1,bulk=1", "wire=64K", chunks)
    assert order == ["latency", "bulk", "latency", "bulk"]


def test_drr_fifo_within_class_and_big_chunk_liveness():
    # FIFO within a class, and a chunk LARGER than the window still grants
    # (empty-wire liveness rule) instead of wedging the simulation.
    order = transport.qos_drr_golden(
        "latency=1,bulk=1", "wire=64K", "bulk:128K,latency:64K")
    assert order == ["latency", "bulk"]


def test_drr_golden_rejects_malformed_specs():
    with pytest.raises(_native.NativeError) as ei:
        transport.qos_drr_golden("latency=0", "wire=64K", "bulk:1K")
    assert ei.value.code == _native.TPUNET_ERR_INVALID
    with pytest.raises(_native.NativeError):
        transport.qos_drr_golden("", "", "bulk:1K")  # no window
    with pytest.raises(_native.NativeError):
        transport.qos_drr_golden("", "wire=64K", "express:1K")


def test_qos_state_echoes_defaults():
    st = transport.qos_state()
    assert st["weights"] == {"latency": 8, "bulk": 1, "control": 1}
    assert st["wire_window"] == 0  # gate off by default
    assert set(st["budgets"]) == {"latency", "bulk", "control"}


# ---------------------------------------------------------------------------
# Traffic-class negotiation: mismatch fails typed on EVERY rank.


def _class_mismatch_worker(rank: int, world: int, port: int, q) -> None:
    try:
        from tpunet.collectives import Communicator

        try:
            Communicator(f"127.0.0.1:{port}", rank, world,
                         traffic_class="latency" if rank == 0 else "bulk")
            q.put((rank, "FAIL: no error raised"))
        except _native.NativeError as e:
            assert e.code == _native.TPUNET_ERR_INVALID, e.code
            assert "traffic class mismatch" in str(e), str(e)
            q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_traffic_class_mismatch_typed_on_both_ranks():
    run_spawn_workers(_class_mismatch_worker, 2)


def test_unknown_traffic_class_rejected_before_any_socket():
    from tpunet.collectives import Communicator

    with pytest.raises(_native.NativeError) as ei:
        Communicator("127.0.0.1:1", 0, 1, traffic_class="express")
    assert ei.value.code == _native.TPUNET_ERR_INVALID
    assert "traffic_class" in str(ei.value)


# ---------------------------------------------------------------------------
# Admission backpressure (spawned: the budget env must precede native load).


def _admission_worker(rank: int, world: int, port: int, q) -> None:
    try:
        os.environ["TPUNET_QOS_INFLIGHT_BYTES"] = "bulk=64K"
        from tpunet import transport as tp

        net = tp.Net(traffic_class="bulk")
        lc = net.listen()
        sc = net.connect(lc.handle)
        rc = lc.accept()
        payload = np.full(64 << 10, 7, np.uint8)
        # First send fills the whole 64K budget (idle classes admit even
        # oversize); it is NOT consumed yet, so the budget stays charged.
        req1 = sc.isend(payload)
        try:
            sc.isend(payload)
            q.put((rank, "FAIL: second isend admitted over budget"))
            return
        except _native.QosAdmissionError as e:
            assert e.code == _native.TPUNET_ERR_QOS_ADMISSION, e.code
            assert "bulk" in str(e) and "TPUNET_QOS_INFLIGHT_BYTES" in str(e)
        # Drain + consume: the budget frees at test()/wait() consumption,
        # after which the class admits again.
        buf = np.zeros_like(payload)
        rc.irecv(buf).wait(timeout=30)
        req1.wait(timeout=30)
        req3 = sc.isend(payload)
        rc.irecv(buf).wait(timeout=30)
        req3.wait(timeout=30)
        assert bytes(buf) == bytes(payload)
        for c in (sc, rc, lc):
            c.close()
        net.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_admission_backpressure_typed_and_retryable():
    run_spawn_workers(_admission_worker, 1)


# ---------------------------------------------------------------------------
# Serve router: admission backpressure = retry front-of-queue, not a death.


class _StubPrefill:
    max_len = 16
    model = types.SimpleNamespace(vocab=8)


class _BouncingLink:
    """send_frame raises QosAdmissionError once, then accepts and answers
    every BLOCK with a RESULT frame."""

    def __init__(self):
        self.peer = types.SimpleNamespace(slots=2)
        self.sent = []
        self.bounced = 0
        self._frames = deque()

    def send_frame(self, ftype, rid, payload=b"", aux=0, timeout=60.0):
        from tpunet.serve import protocol as proto

        if self.bounced == 0:
            self.bounced += 1
            raise _native.QosAdmissionError(
                _native.TPUNET_ERR_QOS_ADMISSION, "isend")
        self.sent.append((ftype, rid))
        if ftype == proto.T_BLOCK:
            self._frames.append(
                (proto.T_RESULT, rid,
                 proto.pack_result(np.arange(3, dtype=np.int32), 0, 5), 0))

    def poll(self):
        return self._frames.popleft() if self._frames else None

    def close(self):
        pass


def test_router_replays_on_admission_backpressure(monkeypatch):
    from tpunet.serve import router as router_mod

    router = router_mod.Router(_StubPrefill(), kv_codec="f32")
    try:
        link = _BouncingLink()
        router._ranks.append(router_mod._Rank(link, 0))
        monkeypatch.setattr(router, "_build_payload", lambda rec: b"payload")
        rid = router.submit([1, 2, 3], 4)
        # The bounced frame must be requeued with the rank still alive.
        assert router.stats["qos_backpressure"] == 1
        assert router.stats["rank_failures"] == 0
        assert router._ranks[0].alive
        results = router.run(timeout=30)
        assert list(results[rid]) == [0, 1, 2]
        assert router.stats["rank_failures"] == 0
        assert link.bounced == 1 and len(link.sent) == 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# Two-tenant contention + chaos (spawned: gate env precedes native load).


def _p99_us(metrics: dict, family: str, cls: str):
    """p99 upper bound (the smallest histogram bucket bound covering 99% of
    samples) for one class's series; None when the series is empty."""
    from tpunet import telemetry

    rows = metrics.get(family + "_bucket", {})
    buckets = []
    for key, value in rows.items():
        lab = telemetry.labels(key)
        if lab.get("class") != cls:
            continue
        le = lab["le"]
        bound = float("inf") if le in ("+Inf", "Inf") else float(le)
        buckets.append((bound, int(value)))
    buckets.sort()
    if not buckets or buckets[-1][1] == 0:
        return None
    total = buckets[-1][1]
    for bound, cum in buckets:
        if cum >= 0.99 * total:
            return bound
    return float("inf")


def _qos_bytes(metrics: dict) -> dict:
    from tpunet import telemetry

    out = {}
    for key, value in metrics.get("tpunet_qos_bytes_total", {}).items():
        lab = telemetry.labels(key)
        out[(lab["class"], lab["dir"])] = int(value)
    return out


def _run_two_tenants(q, rank, *, fault_spec: str | None, engine: str = "BASIC"):
    """One process, two tenants: a latency-class P2P pinger and a bulk-class
    flooder sharing the gated process-wide QoS scheduler."""
    os.environ["TPUNET_IMPLEMENT"] = engine
    os.environ["TPUNET_QOS_INFLIGHT_BYTES"] = "wire=256K"
    os.environ["TPUNET_QOS_WEIGHTS"] = "latency=8,bulk=1"
    os.environ["TPUNET_MIN_CHUNKSIZE"] = str(128 << 10)
    os.environ["TPUNET_NSTREAMS"] = "1"
    from tpunet import telemetry
    from tpunet import transport as tp

    net_lat = tp.Net(traffic_class="latency")  # wired with nstreams=1
    os.environ["TPUNET_NSTREAMS"] = "2"
    net_bulk = tp.Net(traffic_class="bulk")    # wired with nstreams=2

    lat_l = net_lat.listen()
    lat_s = net_lat.connect(lat_l.handle)
    lat_r = lat_l.accept()
    bulk_l = net_bulk.listen()
    bulk_s = net_bulk.connect(bulk_l.handle)
    bulk_r = bulk_l.accept()

    if fault_spec:
        # Armed AFTER wiring: the spec names data-stream 1, which only the
        # bulk comm has (the latency comm is single-stream) — the closed
        # stream is guaranteed to be a bulk lane.
        tp.fault_inject(fault_spec)

    bulk_msg = np.full(1 << 20, 3, np.uint8)
    lat_msg = np.full(16 << 10, 9, np.uint8)
    n_bulk, n_lat = 8, 40
    errors: list[str] = []

    def bulk_rx():
        buf = np.empty_like(bulk_msg)
        for _ in range(n_bulk):
            bulk_r.irecv(buf).wait(timeout=120)

    def bulk_tx():
        for _ in range(n_bulk):
            bulk_s.isend(bulk_msg).wait(timeout=120)

    def lat_rx():
        buf = np.empty_like(lat_msg)
        for _ in range(n_lat):
            lat_r.irecv(buf).wait(timeout=120)
        if bytes(buf) != bytes(lat_msg):
            errors.append("latency payload corrupted")

    threads = [threading.Thread(target=f, daemon=True)
               for f in (bulk_rx, bulk_tx, lat_rx)]
    for t in threads:
        t.start()
    # Latency pings interleave with the bulk flood on the caller thread.
    for _ in range(n_lat):
        lat_s.isend(lat_msg).wait(timeout=120)
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "tenant thread wedged"
    assert not errors, errors

    m = telemetry.metrics()
    tp.fault_clear()
    for c in (lat_s, lat_r, lat_l, bulk_s, bulk_r, bulk_l):
        c.close()
    net_lat.close()
    net_bulk.close()
    return m


def _contention_worker(rank: int, world: int, port: int, q,
                       engine: str = "BASIC") -> None:
    try:
        m = _run_two_tenants(q, rank, fault_spec=None, engine=engine)
        by = _qos_bytes(m)
        # Both tenants moved bytes under their OWN class, tx and rx — the
        # rx side proves the receiver adopted the preamble class nibble.
        assert by[("latency", "tx")] >= 40 * (16 << 10), by
        assert by[("latency", "rx")] >= 40 * (16 << 10), by
        assert by[("bulk", "tx")] >= 8 * (1 << 20), by
        assert by[("bulk", "rx")] >= 8 * (1 << 20), by
        assert by[("control", "tx")] == 0, by
        # Gated chunks recorded their credit waits; the latency lane's p99
        # stays inside its budget despite the bulk flood saturating the
        # 256K window (the whole point of the DRR gate).
        p99 = _p99_us(m, "tpunet_qos_queue_wait_us", "latency")
        assert p99 is not None, "latency queue-wait histogram is empty"
        assert p99 <= 100_000, f"latency-class p99 queue wait {p99}us"
        assert _p99_us(m, "tpunet_qos_queue_wait_us", "bulk") is not None
        # reset() must cover every new per-class family (the warmup /
        # measure separation the counter-based claims depend on).
        from tpunet import telemetry

        telemetry.reset()
        m2 = telemetry.metrics()
        assert all(v == 0 for v in _qos_bytes(m2).values())
        assert all(
            v == 0
            for v in m2.get("tpunet_qos_queue_wait_us_count", {}).values())
        assert all(
            v == 0 for v in m2.get("tpunet_qos_preempts_total", {}).values())
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("engine", ["BASIC", "EPOLL"])
def test_two_tenant_contention_counters_and_bounded_wait(engine):
    # Both engines run the same gated two-tenant interleave: BASIC gates in
    # its blocking stream workers, EPOLL through the nonblocking
    # ticket/park path in its event loop.
    run_spawn_workers(_contention_worker, 1, timeout=300,
                      extra_args=(engine,))


def _chaos_worker(rank: int, world: int, port: int, q) -> None:
    try:
        from tpunet import telemetry

        m = _run_two_tenants(
            q, rank,
            fault_spec="stream=1:side=send:after_bytes=2M:action=close")
        # The bulk comm lost a data stream mid-flood and failed over; the
        # latency lane still completed every ping within its budget —
        # credits held by the dying stream were released, not leaked.
        failovers = sum(
            int(v) for v in m.get("tpunet_stream_failovers_total", {}).values())
        assert failovers >= 1, "fault never fired (no failover recorded)"
        p99 = _p99_us(m, "tpunet_qos_queue_wait_us", "latency")
        assert p99 is not None and p99 <= 100_000, p99
        by = _qos_bytes(m)
        assert by[("latency", "rx")] >= 40 * (16 << 10), by
        # The wire window must end fully drained (no leaked credit).
        st = telemetry  # noqa: F841 — namespace kept for symmetry
        from tpunet.transport import qos_state

        assert qos_state()["wire_inflight"] == 0
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_chaos_bulk_stream_close_does_not_stall_latency_lane():
    run_spawn_workers(_chaos_worker, 1, timeout=300)
