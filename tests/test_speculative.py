"""Speculative decoding — exactness and mechanics.

Three layers of evidence that `speculative_generate` preserves the target
model's distribution:
1. the core accept/residual rule is Monte-Carlo-verified to reproduce the
   target distribution exactly (the Leviathan identity), independent of
   any model;
2. greedy end-to-end output is bitwise `generate`'s, for arbitrary-quality
   drafts (draft quality must affect only throughput);
3. a draft identical to the target accepts every proposal (accept rate 1),
   pinning the acceptance plumbing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.models import Transformer, generate, speculative_generate
from tpunet.models.generate import (_leading_accepts, _residual_probs,
                                    filtered_logits)


def _tiny(**kw):
    kw.setdefault("vocab", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    return Transformer(**kw)


def _params(model, b=2, s=24, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(0), (b, s), 0, model.vocab)
    return model.init(jax.random.PRNGKey(seed), toks)["params"], toks


def test_accept_residual_rule_reproduces_target_exactly():
    """The identity min(q, p) + (1 - sum min(p, q)) * residual = p, run as
    the actual sampled process: draft from q, accept with prob min(1,
    p/q), else sample the residual. Empirical marginal must match p to
    Monte-Carlo accuracy — this is the theorem the whole scheme rests on,
    tested with no model in the loop."""
    v = 5
    rng = np.random.default_rng(0)
    p = rng.dirichlet(np.ones(v))
    q = rng.dirichlet(np.ones(v))
    n = 200_000
    key = jax.random.PRNGKey(1)
    kd, ka, kr = jax.random.split(key, 3)
    draft = jax.random.categorical(kd, jnp.log(jnp.asarray(q))[None, :],
                                   shape=(n,))
    u = jax.random.uniform(ka, (n,))
    accept = u * jnp.asarray(q)[draft] < jnp.asarray(p)[draft]
    res = _residual_probs(jnp.asarray(p)[None, :], jnp.asarray(q)[None, :])
    resample = jax.random.categorical(kr, jnp.log(res), shape=(n,))
    tok = jnp.where(accept, draft, resample)
    emp = np.bincount(np.asarray(tok), minlength=v) / n
    np.testing.assert_allclose(emp, p, atol=5e-3)
    # Acceptance rate matches its closed form sum min(p, q).
    assert np.asarray(accept).mean() == pytest.approx(
        np.minimum(p, q).sum(), abs=5e-3)


def test_residual_probs_identical_dists_falls_back_to_p():
    p = jnp.asarray([[0.5, 0.25, 0.25]])
    np.testing.assert_allclose(np.asarray(_residual_probs(p, p)), p)


def test_leading_accepts():
    acc = jnp.asarray([[True, True, False, True],
                       [False, True, True, True],
                       [True, True, True, True]])
    assert _leading_accepts(acc).tolist() == [2, 0, 4]


@pytest.mark.parametrize("gamma", [1, 2, 4])
@pytest.mark.parametrize("draft_kind", ["smaller", "different"])
def test_greedy_bitwise_matches_generate(gamma, draft_kind):
    """Greedy speculative output == ancestral greedy, token for token, for
    drafts of arbitrary quality — a bad draft may only slow things down."""
    model = _tiny()
    params, prompt = _params(model)
    if draft_kind == "smaller":
        draft = _tiny(n_layers=1)
        draft_params, _ = _params(draft, seed=7)
    else:  # same shape, unrelated weights: a pathologically bad draft
        draft = _tiny()
        draft_params, _ = _params(draft, seed=99)
    want = generate(model, params, prompt, 12)
    got = speculative_generate(model, params, draft, draft_params, prompt,
                               12, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_self_draft_accepts_everything():
    """draft == target => p == q at every position => accept prob 1: every
    round commits gamma+1 tokens and the accept rate reads 1.0."""
    model = _tiny()
    params, prompt = _params(model)
    gamma, new = 3, 13
    out, stats = speculative_generate(
        model, params, model, params, prompt, new, gamma=gamma,
        temperature=0.8, rng=jax.random.PRNGKey(5), return_stats=True)
    assert out.shape == (prompt.shape[0], prompt.shape[1] + new)
    assert int(stats["rounds"]) == -(-(new - 1) // (gamma + 1))  # ceil
    assert float(stats["draft_accept_rate"]) == 1.0
    assert (np.asarray(out) < model.vocab).all() and (np.asarray(out) >= 0).all()


def test_sampled_marginal_matches_generate():
    """Distributional end-to-end check: over a large batch of identical
    prompts, the marginal distribution of each generated position must
    match ancestral sampling's (total variation within Monte-Carlo
    noise), with an imperfect draft forcing real rejections."""
    model = _tiny(vocab=16, d_model=16, n_layers=1, n_heads=2, d_ff=32)
    draft = _tiny(vocab=16, d_model=16, n_layers=1, n_heads=2, d_ff=32)
    params, _ = _params(model, b=1, s=4)
    draft_params, _ = _params(draft, b=1, s=4, seed=123)
    b = 4096
    prompt = jnp.tile(jnp.asarray([[3, 1, 2, 7]], jnp.int32), (b, 1))
    new, t = 3, 1.0
    anc = generate(model, params, prompt, new, temperature=t,
                   rng=jax.random.PRNGKey(11))
    spec = speculative_generate(model, params, draft, draft_params, prompt,
                                new, gamma=2, temperature=t,
                                rng=jax.random.PRNGKey(22))
    for pos in range(new):
        a = np.bincount(np.asarray(anc)[:, 4 + pos], minlength=16) / b
        s = np.bincount(np.asarray(spec)[:, 4 + pos], minlength=16) / b
        tvd = 0.5 * np.abs(a - s).sum()
        assert tvd < 0.05, f"position {pos}: TVD {tvd}"


def test_eos_pins_tail():
    """Once a row emits eos, everything after is eos — including tokens
    committed in the same speculative block."""
    model = _tiny(vocab=8)
    params, _ = _params(model, b=3, s=6)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 6), 0, 8)
    draft = _tiny(vocab=8, n_layers=1)
    draft_params, _ = _params(draft, b=3, s=6, seed=9)
    out = np.asarray(speculative_generate(
        model, params, draft, draft_params, prompt, 16, gamma=3, eos_id=5))
    for row in out:
        gen = row[6:]
        hits = np.nonzero(gen == 5)[0]
        if hits.size:
            assert (gen[hits[0]:] == 5).all()
    # And greedy-with-eos still matches ancestral greedy-with-eos.
    want = np.asarray(generate(model, params, prompt, 16, eos_id=5))
    np.testing.assert_array_equal(out, want)


def test_gqa_window_draft_composes():
    """Speculative decode composes with the GQA + sliding-window cache
    variants (the decode block step handles both)."""
    model = _tiny(n_kv_heads=2, attn_window=8)
    params, prompt = _params(model)
    draft = _tiny(n_layers=1, n_kv_heads=2, attn_window=8)
    draft_params, _ = _params(draft, seed=3)
    want = generate(model, params, prompt, 10)
    got = speculative_generate(model, params, draft, draft_params, prompt,
                               10, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("chunk", [4, 5, 12, 100])
def test_chunked_prefill_parity(chunk):
    """prefill_chunk re-blocks the same computation: bitwise-equal output
    for dividing chunks (4 and 12 — both end the scan on rem == 0), a
    non-dividing chunk (5, remainder block), and an oversized chunk (100
    >= p, the unchunked fast path), on both generators, incl. a
    GQA+window model."""
    model = _tiny(n_kv_heads=2, attn_window=10)
    params, prompt = _params(model)  # p = 24
    draft = _tiny(n_layers=1, n_kv_heads=2, attn_window=10)
    draft_params, _ = _params(draft, seed=3)

    want = generate(model, params, prompt, 8)
    got = generate(model, params, prompt, 8, prefill_chunk=chunk)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    want_s = generate(model, params, prompt, 8, temperature=0.7,
                      rng=jax.random.PRNGKey(4))
    got_s = generate(model, params, prompt, 8, temperature=0.7,
                     rng=jax.random.PRNGKey(4), prefill_chunk=chunk)
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))

    want_sp = speculative_generate(model, params, draft, draft_params,
                                   prompt, 8, gamma=2)
    got_sp = speculative_generate(model, params, draft, draft_params,
                                  prompt, 8, gamma=2, prefill_chunk=chunk)
    np.testing.assert_array_equal(np.asarray(got_sp), np.asarray(want_sp))


def test_flash_prefill_matches_reference_prefill():
    """attn_impl="flash" routes the empty-cache prefill through the Pallas
    kernel (interpreted on CPU); generation must agree with the reference-
    impl model token-for-token at a tileable prompt length — the two
    prefills differ only in attention blocking."""
    ref = _tiny(n_kv_heads=2)
    fla = _tiny(n_kv_heads=2, attn_impl="flash")
    params, _ = _params(ref, s=128)
    prompt = jax.random.randint(jax.random.PRNGKey(0), (2, 128), 0, 64)
    want = generate(ref, params, prompt, 6)
    got = generate(fla, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prefill_mode_poisons_on_nonempty_cache():
    """prefill=True is an empty-cache contract: applying a prefill clone
    to a cache mid-stream computes block-only attention that ignores the
    committed context — poisoned to NaN, same discipline as overflow."""
    from tpunet.models import init_cache

    model = _tiny()
    params, toks = _params(model)
    pm = model.clone(decode=True, prefill=True)
    cache = init_cache(model, 2, 40)
    _, mut = pm.apply({"params": params, "cache": cache}, toks,
                      mutable=["cache"])  # idx 0: fine
    logits, _ = pm.apply({"params": params, "cache": mut["cache"]},
                         toks[:, :4], mutable=["cache"])  # idx 24: poisoned
    assert np.isnan(np.asarray(logits)).all()


def test_prefill_chunk_validation():
    model = _tiny()
    params, prompt = _params(model)
    with pytest.raises(ValueError, match="prefill_chunk"):
        generate(model, params, prompt, 4, prefill_chunk=0)


def test_validation_errors():
    model = _tiny()
    params, prompt = _params(model)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(model, params, model, params, prompt, 4, gamma=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        speculative_generate(model, params, model, params, prompt, 0)
    with pytest.raises(ValueError, match="top_k"):
        speculative_generate(model, params, model, params, prompt, 4, top_k=3)


def test_filtered_logits_shared_helper():
    """generate() and speculative_generate() must sample through the SAME
    filter chain — pin the helper's semantics: top-k keeps exactly k,
    top-p keeps the smallest prefix reaching p, composed k-then-p."""
    logits = jnp.asarray([[2.0, 1.0, 0.5, 0.0, -1.0]])
    out = filtered_logits(logits, 1.0, 3, None)
    assert (np.asarray(out[0]) == -np.inf).sum() == 2
    out = filtered_logits(logits, 1.0, None, 0.6)
    keep = np.isfinite(np.asarray(out[0]))
    probs = np.asarray(jax.nn.softmax(logits[0]))
    order = np.argsort(-probs)
    cum = 0.0
    expect = np.zeros(5, bool)
    for i in order:
        expect[i] = True
        cum += probs[i]
        if cum >= 0.6:
            break
    np.testing.assert_array_equal(keep, expect)


def test_all_inference_features_compose_greedy_exact():
    """The whole inference feature matrix in ONE configuration: GQA x
    sliding window x chunked prefill x speculative decoding with an int8
    quantized self-draft - greedy output must still be bitwise the plain
    fp generate()'s."""
    from tpunet.models import quantize_params

    model = _tiny(n_kv_heads=2, attn_window=12)
    params, prompt = _params(model)
    qdraft = model.clone(weight_quant="int8")
    qp = quantize_params(params)
    want = generate(model, params, prompt, 10)
    got = speculative_generate(
        model, params, qdraft, qp, prompt, 10, gamma=3, prefill_chunk=7)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_per_row_speculative_bitwise_and_fewer_rounds():
    """per_row=True: every row commits its OWN accepted prefix - output
    still bitwise generate()'s, and (greedy being deterministic) the
    round count can only improve on lockstep (lockstep progress per round
    is the batch min, per-row progress is each row's own)."""
    model = _tiny()
    params, _ = _params(model, b=3)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (3, 24), 0, 64)
    draft = _tiny(n_layers=1)
    draft_params, _ = _params(draft, seed=7)
    want = generate(model, params, prompt, 14)
    got_ls, st_ls = speculative_generate(
        model, params, draft, draft_params, prompt, 14, gamma=3,
        return_stats=True)
    got_pr, st_pr = speculative_generate(
        model, params, draft, draft_params, prompt, 14, gamma=3,
        per_row=True, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got_ls), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_pr), np.asarray(want))
    assert int(st_pr["rounds"]) <= int(st_ls["rounds"])
    assert 0.0 <= float(st_pr["draft_accept_rate"]) <= 1.0


def test_per_row_speculative_eos_and_sampling():
    """per_row composes with eos pinning (bitwise vs the eos oracle in
    greedy) and runs in sampling mode with in-vocab output."""
    model = _tiny(vocab=8)
    params, _ = _params(model, b=3, s=6)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 6), 0, 8)
    draft = _tiny(vocab=8, n_layers=1)
    draft_params, _ = _params(draft, b=3, s=6, seed=9)
    want = np.asarray(generate(model, params, prompt, 12, eos_id=5))
    got = np.asarray(speculative_generate(
        model, params, draft, draft_params, prompt, 12, gamma=3,
        eos_id=5, per_row=True))
    np.testing.assert_array_equal(got, want)

    out = speculative_generate(
        model, params, draft, draft_params, prompt, 9, gamma=2,
        temperature=0.8, per_row=True, rng=jax.random.PRNGKey(3))
    o = np.asarray(out)
    assert o.shape == (3, 15) and ((o >= 0) & (o < 8)).all()


def test_per_row_speculative_with_quant_draft_and_chunked_prefill():
    """per_row x int8 self-draft x chunked prefill: still bitwise."""
    from tpunet.models import quantize_params

    model = _tiny(n_kv_heads=2)
    params, prompt = _params(model)
    qdraft = model.clone(weight_quant="int8")
    qp = quantize_params(params)
    want = generate(model, params, prompt, 10)
    got = speculative_generate(model, params, qdraft, qp, prompt, 10,
                               gamma=3, per_row=True, prefill_chunk=7)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --- speculative x rolling-window ring cache (round 5) --------------------
# With gamma + 1 <= window, speculation runs on the RING cache: the round
# stashes the slots it overwrites and restores the rejected span
# (_spec_ring_stash/_spec_ring_restore). Oracle: the identical model with
# decode_ring_cache=False (full-capacity masked cache, round-4 rollback).


def _ring_pair(window=8, **kw):
    model = _tiny(n_kv_heads=2, attn_window=window, **kw)
    draft = _tiny(n_layers=1, n_kv_heads=2, attn_window=window, **kw)
    params, _ = _params(model)
    dparams, _ = _params(draft, seed=3)
    return model, draft, params, dparams


def test_spec_ring_cache_matches_masked_cache_greedy():
    model, draft, params, dparams = _ring_pair()
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0, 64)
    kw = dict(max_new_tokens=12, gamma=3, temperature=0.0)
    ring = speculative_generate(model, params, draft, dparams, prompt, **kw)
    masked = speculative_generate(
        model.clone(decode_ring_cache=False), params,
        draft.clone(decode_ring_cache=False), dparams, prompt, **kw)
    assert jnp.array_equal(ring, masked)


def test_spec_ring_cache_matches_masked_cache_sampled_per_row():
    model, draft, params, dparams = _ring_pair()
    prompt = jax.random.randint(jax.random.PRNGKey(6), (3, 6), 0, 64)
    for per_row in (False, True):
        kw = dict(max_new_tokens=12, gamma=3, temperature=0.9, top_k=8,
                  rng=jax.random.PRNGKey(11), per_row=per_row)
        ring = speculative_generate(model, params, draft, dparams, prompt,
                                    **kw)
        masked = speculative_generate(
            model.clone(decode_ring_cache=False), params,
            draft.clone(decode_ring_cache=False), dparams, prompt, **kw)
        assert jnp.array_equal(ring, masked), f"per_row={per_row}"


def test_spec_ring_cache_matches_plain_generate():
    # End-to-end exactness: ring-cache speculation == plain generate()
    # greedy (the strongest oracle — no shared code with the spec loop).
    model, draft, params, dparams = _ring_pair()
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 6), 0, 64)
    out = speculative_generate(model, params, draft, dparams, prompt,
                               max_new_tokens=10, gamma=2, temperature=0.0)
    ref = generate(model, params, prompt, max_new_tokens=10, temperature=0.0)
    assert jnp.array_equal(out[:, :ref.shape[1]], ref)


def test_spec_narrow_window_falls_back_to_masked_cache():
    # gamma + 1 > window: a round's writes would lap the ring (duplicate
    # slots in the stash scatter) — the masked full-capacity cache is the
    # correct substrate, and results still match plain generate().
    model, draft, params, dparams = _ring_pair(window=4)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 6), 0, 64)
    out = speculative_generate(model, params, draft, dparams, prompt,
                               max_new_tokens=8, gamma=4, temperature=0.0)
    ref = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    assert jnp.array_equal(out[:, :ref.shape[1]], ref)
