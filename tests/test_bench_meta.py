"""Unit tests for benchmark metadata helpers (no hardware needed)."""

from benchmarks.tpu_headline import PEAK_FLOPS, _peak_for


def test_peak_exact_known_kinds():
    assert _peak_for("TPU v4") == 275e12
    assert _peak_for("TPU v5 lite") == 197e12
    assert _peak_for("TPU v5p") == 459e12
    assert _peak_for("TPU v6 lite") == 918e12
    assert _peak_for("TPU v6e") == 918e12
    assert _peak_for("TPU v3") == 123e12 / 2
    assert _peak_for("TPU v2") == 45e12 / 2


def test_peak_normalization():
    # prefix strip + case-insensitive
    assert _peak_for("tpu v5p") == 459e12
    assert _peak_for("  TPU V4 ") == 275e12


def test_peak_unknown_is_none():
    # Unknown kinds must NOT substring-match onto a wrong row (the round-2
    # failure mode: "v5" caught any future v5 variant).
    assert _peak_for("TPU v7x") is None
    assert _peak_for("TPU v5 mega") is None
    assert _peak_for("gpu a100") is None


def test_table_values_positive():
    assert all(v > 0 for v in PEAK_FLOPS.values())


def test_peak_tile_index_suffix_stripped():
    # Axon-tunneled chips suffix a tile index onto the kind.
    assert _peak_for("TPU v5 lite0") == 197e12
    assert _peak_for("TPU v6 lite1") == 918e12
    assert _peak_for("TPU v5p0") == 459e12
    # A kind that legitimately ends in a digit is matched exactly first.
    assert _peak_for("TPU v4") == 275e12


def test_model_tier_gating():
    import json
    import unittest.mock as mock

    import bench

    calls = []

    class _P:
        returncode = 0
        stdout = json.dumps({"platform": "x"})
        stderr = ""

    def record(cmd, **kw):
        calls.append(cmd)
        return _P

    # Broken flash smoke still attempts the TPU tier, with reference attn.
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "tpu", "flash_fwd": "boom",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "reference"
    assert calls[0][calls[0].index("--platform") + 1] == "tpu"

    # Smoke infra failure (error dict): TPU attempt survives.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"error": "kernel smoke failed: timeout"})
    assert calls[0][calls[0].index("--platform") + 1] == "tpu"
    assert calls[0][calls[0].index("--attn") + 1] == "reference"

    # A smoke that silently ran on CPU must NOT green-light flash.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "cpu", "flash_fwd": "ok",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "reference"

    # All green on-chip: flash.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "tpu", "flash_fwd": "ok",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "flash"

    # TPU down: only the CPU attempt runs.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(False, None)
    assert all(c[c.index("--platform") + 1] == "cpu" for c in calls)


def test_finalize_drains_pending_async():
    from conftest import free_port

    from tpunet import distributed
    from tpunet.interop import (
        _register_pending,
        dcn_async_stats,
        dcn_async_stats_reset,
    )
    import numpy as np

    dcn_async_stats_reset()
    distributed.finalize()
    comm = distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    _register_pending(comm, comm.iall_reduce(np.ones(16, np.float32)))
    assert dcn_async_stats()["in_flight"] == 1
    distributed.finalize()  # must drop the stale entry, not leak it
    assert dcn_async_stats()["in_flight"] == 0


def test_decode_bench_cli(capsys):
    import json

    from benchmarks.decode_bench import main as decode_main

    decode_main([
        "--d", "64", "--layers", "2", "--heads", "4", "--ff", "128",
        "--vocab", "256", "--batch", "2", "--prompt", "8", "--new", "4",
        "--kv-heads", "2", "--iters", "1",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["decode_tok_s"] > 0
    assert out["kv_heads"] == 2
    assert out["platform"] == "cpu"
