"""Unit tests for benchmark metadata helpers (no hardware needed)."""

from benchmarks.tpu_headline import PEAK_FLOPS, _peak_for


def test_peak_exact_known_kinds():
    assert _peak_for("TPU v4") == 275e12
    assert _peak_for("TPU v5 lite") == 197e12
    assert _peak_for("TPU v5p") == 459e12
    assert _peak_for("TPU v6 lite") == 918e12
    assert _peak_for("TPU v6e") == 918e12
    assert _peak_for("TPU v3") == 123e12 / 2
    assert _peak_for("TPU v2") == 45e12 / 2


def test_peak_normalization():
    # prefix strip + case-insensitive
    assert _peak_for("tpu v5p") == 459e12
    assert _peak_for("  TPU V4 ") == 275e12


def test_peak_unknown_is_none():
    # Unknown kinds must NOT substring-match onto a wrong row (the round-2
    # failure mode: "v5" caught any future v5 variant).
    assert _peak_for("TPU v7x") is None
    assert _peak_for("TPU v5 mega") is None
    assert _peak_for("gpu a100") is None


def test_table_values_positive():
    assert all(v > 0 for v in PEAK_FLOPS.values())


def test_peak_tile_index_suffix_stripped():
    # Axon-tunneled chips suffix a tile index onto the kind.
    assert _peak_for("TPU v5 lite0") == 197e12
    assert _peak_for("TPU v6 lite1") == 918e12
    assert _peak_for("TPU v5p0") == 459e12
    # A kind that legitimately ends in a digit is matched exactly first.
    assert _peak_for("TPU v4") == 275e12


def test_model_tier_gating():
    import json
    import unittest.mock as mock

    import bench

    calls = []

    class _P:
        returncode = 0
        stdout = json.dumps({"platform": "x"})
        stderr = ""

    def record(cmd, **kw):
        calls.append(cmd)
        return _P

    # Broken flash smoke still attempts the TPU tier, with reference attn.
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "tpu", "flash_fwd": "boom",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "reference"
    assert calls[0][calls[0].index("--platform") + 1] == "tpu"

    # Smoke infra failure (error dict): TPU attempt survives.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"error": "kernel smoke failed: timeout"})
    assert calls[0][calls[0].index("--platform") + 1] == "tpu"
    assert calls[0][calls[0].index("--attn") + 1] == "reference"

    # A smoke that silently ran on CPU must NOT green-light flash.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "cpu", "flash_fwd": "ok",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "reference"

    # All green on-chip: flash.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(True, {"platform": "tpu", "flash_fwd": "ok",
                                 "flash_bwd": "ok"})
    assert calls[0][calls[0].index("--attn") + 1] == "flash"

    # TPU down: only the CPU attempt runs.
    calls.clear()
    with mock.patch("subprocess.run", side_effect=record):
        bench._model_tier(False, None)
    assert all(c[c.index("--platform") + 1] == "cpu" for c in calls)


def test_measurement_staleness_fresh_at_head():
    import subprocess

    import bench

    head = subprocess.run(
        ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
        cwd=bench.os.path.dirname(bench.os.path.abspath(bench.__file__)),
    ).stdout.strip()
    out = bench._measurement_staleness(head)
    # A measurement taken at HEAD is stale only if the working tree has
    # uncommitted edits under the measured paths (possible mid-development).
    assert out["stale"] == bool(out.get("uncommitted_files"))
    assert out["changed_files"] == []


def _have_commit(sha: str) -> bool:
    import subprocess

    import bench

    return subprocess.run(
        ["git", "cat-file", "-e", f"{sha}^{{commit}}"], capture_output=True,
        cwd=bench.os.path.dirname(bench.os.path.abspath(bench.__file__)),
    ).returncode == 0


def test_measurement_staleness_old_commit_flags_kernel_changes():
    import pytest

    import bench

    # 1a53401 predates the round-3 GQA/window/decode kernel rewrite; the
    # diff over the measured paths MUST flag it (this is the exact rot the
    # round-3 verdict caught in the hand-written "unchanged since" claim).
    if not _have_commit("1a53401"):  # shallow clone: history not reachable
        pytest.skip("historical commit 1a53401 not in this clone")
    out = bench._measurement_staleness("1a53401")
    assert out["stale"] is True
    assert "tpunet/ops/flash_attention.py" in out["changed_files"]


def test_measurement_staleness_prose_commit_still_parses():
    import pytest

    import bench

    # The commit field may carry trailing prose (old files); first token wins.
    if not _have_commit("1a53401"):
        pytest.skip("historical commit 1a53401 not in this clone")
    out = bench._measurement_staleness("1a53401 (some stale prose)")
    assert out["stale"] is True


def test_measurement_staleness_synthetic_repo(tmp_path):
    """History-independent coverage: a tmp repo with a measured-path edit
    after the measured commit must flag stale; one without must not."""
    import subprocess

    import bench

    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True,
                       capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "tpunet" / "ops").mkdir(parents=True)
    (tmp_path / "tpunet" / "ops" / "k.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "base")
    base = subprocess.run(["git", "rev-parse", "HEAD"], cwd=tmp_path,
                          capture_output=True, text=True).stdout.strip()
    with unittest_chdir(bench, tmp_path):
        out = bench._measurement_staleness(base)
        assert out["stale"] is False and out["changed_files"] == []
        (tmp_path / "tpunet" / "ops" / "k.py").write_text("x = 2\n")
        git("commit", "-qam", "kernel change")
        out = bench._measurement_staleness(base)
        assert out["stale"] is True
        assert out["changed_files"] == ["tpunet/ops/k.py"]


class unittest_chdir:
    """Point bench._measurement_staleness's repo root at a tmp repo (it
    derives the root from bench.__file__, so patch the module attr)."""

    def __init__(self, bench_mod, path):
        self.bench, self.path = bench_mod, path

    def __enter__(self):
        self._old = self.bench.__file__
        self.bench.__file__ = str(self.path / "bench.py")

    def __exit__(self, *exc):
        self.bench.__file__ = self._old


def test_measurement_staleness_bad_input():
    import bench

    assert bench._measurement_staleness(None)["stale"] is None
    assert bench._measurement_staleness("")["stale"] is None
    assert bench._measurement_staleness("nothex000")["stale"] is None


def test_finalize_drains_pending_async():
    from conftest import free_port

    from tpunet import distributed
    from tpunet.interop import (
        _register_pending,
        dcn_async_stats,
        dcn_async_stats_reset,
    )
    import numpy as np

    dcn_async_stats_reset()
    distributed.finalize()
    comm = distributed.initialize(f"127.0.0.1:{free_port()}", 0, 1)
    _register_pending(comm, comm.iall_reduce(np.ones(16, np.float32)))
    assert dcn_async_stats()["in_flight"] == 1
    distributed.finalize()  # must drop the stale entry, not leak it
    assert dcn_async_stats()["in_flight"] == 0


def test_decode_bench_cli(capsys):
    import json

    from benchmarks.decode_bench import main as decode_main

    decode_main([
        "--d", "64", "--layers", "2", "--heads", "4", "--ff", "128",
        "--vocab", "256", "--batch", "2", "--prompt", "8", "--new", "4",
        "--kv-heads", "2", "--iters", "1",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["decode_tok_s"] > 0
    assert out["kv_heads"] == 2
    assert out["platform"] == "cpu"


def test_decode_bench_window(capsys):
    import json

    from benchmarks.decode_bench import main as decode_main

    decode_main([
        "--d", "64", "--layers", "2", "--heads", "4", "--ff", "128",
        "--vocab", "256", "--batch", "2", "--prompt", "8", "--new", "4",
        "--window", "6", "--iters", "1",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["decode_tok_s"] > 0
    assert out["window"] == 6


def test_decode_bench_speculative(capsys):
    import json

    from benchmarks.decode_bench import main as decode_main

    decode_main([
        "--d", "64", "--layers", "2", "--heads", "4", "--ff", "128",
        "--vocab", "256", "--batch", "2", "--prompt", "8", "--new", "6",
        "--iters", "1", "--spec-gamma", "2", "--draft-layers", "1",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    spec = out["speculative"]
    assert spec["gamma"] == 2 and spec["draft_layers"] == 1
    assert spec["spec_tok_s_floor"] > 0
    # The ceiling commits gamma+1 tokens per round by construction.
    assert spec["spec_tok_s_ceiling"] >= spec["spec_tok_s_floor"]
    assert 0.0 <= spec["accept_rate_floor"] <= 1.0
    assert spec["rounds"] >= 1


def test_decode_bench_quant_and_quant_draft(capsys):
    import json

    from benchmarks.decode_bench import main as decode_main

    decode_main([
        "--d", "64", "--layers", "2", "--heads", "4", "--ff", "128",
        "--vocab", "256", "--batch", "2", "--prompt", "8", "--new", "6",
        "--iters", "1", "--quant", "int8", "--spec-gamma", "2",
        "--spec-draft", "quant",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["quant"]["dtype"] == "int8"
    assert out["quant"]["decode_tok_s"] > 0
    spec = out["speculative"]
    assert spec["draft"] == "quant" and "draft_layers" not in spec
    assert "accept_rate" in spec and "accept_rate_floor" not in spec
    assert spec["spec_tok_s"] > 0 and spec["vs_plain"] > 0


def test_mfu_attribution_cpu_smoke(capsys):
    import json

    from benchmarks.mfu_attribution import main as attr_main

    attr_main(["--d", "64", "--layers", "2", "--ff", "128", "--heads", "4",
               "--vocab", "256", "--batch", "2", "--seq", "128", "--fp32",
               "--iters", "2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(out["segments"]) == {"attn", "qkvo", "ffn", "xent", "adamw"}
    assert out["full_step_ms"] > 0
    # The per-segment model must reconcile with the measured step to
    # first order even on CPU (no remat there, so expected ~= blocks
    # fwd+bwd + xent + opt).
    assert out["expected_full_ms"] > 0


def test_kernel_smoke_window_entries_cpu():
    from benchmarks.kernel_smoke import run_smoke

    out = run_smoke()
    for k in ("flash_fwd", "flash_bwd", "flash_gqa_fwd", "flash_gqa_bwd",
              "flash_window_fwd", "flash_window_bwd",
              "flash_gqa_window_fwd", "flash_gqa_window_bwd"):
        assert out[k] == "ok", f"{k}: {out[k]}"


def test_chip_session_measured_distillation(tmp_path, monkeypatch):
    import json

    from benchmarks import chip_session as cs

    measured = tmp_path / "tpu_measured.json"
    monkeypatch.setattr(cs, "MEASURED", str(measured))

    # All-error session must write NOTHING (a dead tunnel cannot clobber
    # the previous good measurement).
    cs._write_measured({"kernels": {"error": "tunnel died"}})
    assert not measured.exists()

    # A real partial session writes the fields it has, bare commit hash.
    raw = {
        "kernels": {"platform": "tpu", "flash_fwd": "ok", "flash_bwd": "ok",
                    "flash_window_fwd": "ok"},
        "headline": {"platform": "tpu", "device_kind": "TPU v5 lite",
                     "attn": "flash", "tokens_per_s": 17000.0, "mfu": 0.41,
                     "vgg_img_per_s": 950.0},
        "decode_gqa": {"platform": "tpu", "decode_tok_s": 1234.5,
                       "wall_s": 1.2, "kv_heads": 4, "window": None,
                       "batch": 8, "prompt": 512, "new": 256},
        "block_sweep_s2048": {"error": "timed out after 1800s"},
        "headline_tuned": {"platform": "tpu", "tokens_per_s": 18000.0,
                           "mfu": 0.43, "block_q": 256, "block_k": 256},
        "attribution": {"error": "timed out after 2400s"},
    }
    cs._write_measured(raw)
    out = json.loads(measured.read_text())
    assert out["tokens_per_s"] == 17000.0
    assert out["headline_tuned"]["mfu"] == 0.43
    assert "attribution" not in out  # errored steps never leak
    assert out["kernels"]["flash_window_fwd"] == "ok"
    assert out["decode"]["decode_gqa"]["decode_tok_s"] == 1234.5
    assert "block_sweep_s2048" not in out  # errored steps are not measured
    assert " " not in out["measured_commit"]  # bare hash, no prose

    # Overwrite at a different commit backs the old file up first.
    prev_commit = out["measured_commit"]
    monkeypatch.setattr(cs, "_head_commit", lambda: "fffffff")
    cs._write_measured(raw)
    backup = json.loads((tmp_path / "tpu_measured_prev.json").read_text())
    assert backup["measured_commit"] == prev_commit


def test_chip_session_resume_survives_artifact_commits(monkeypatch):
    """A commit that only records measurement artifacts must NOT invalidate
    the session cache (the first cut compared commit hashes, so committing
    a session's own results discarded the session); an edit to the measured
    code or a step script must."""
    from benchmarks import chip_session as cs

    import bench

    fps = cs._step_fingerprints()
    results = {"kernels": {"flash_fwd": "ok"},
               "decode_mha": {"decode_tok_s": 1.0}}
    good = {"commit": "abc1234", "step_fps": dict(fps), "results": results}

    # Per-step fingerprints: timeouts excluded (orchestration), the step
    # LIST excluded (adding a step must not discard other steps' cache),
    # argv edits invalidate only their own step.
    orig_steps, orig_tuned = cs.STEPS, cs.TUNED_HEADLINE_ARGV
    k0, a0, t0 = orig_steps[0]
    monkeypatch.setattr(cs, "STEPS", [(k0, a0, t0 + 1)] + orig_steps[1:])
    assert cs._step_fingerprints() == fps  # timeout bump: no change
    monkeypatch.setattr(cs, "STEPS",
                        [(k0, a0 + ["--x"], t0)] + orig_steps[1:])
    fps2 = cs._step_fingerprints()
    assert fps2[k0] != fps[k0]
    assert {k: v for k, v in fps2.items() if k != k0} == \
           {k: v for k, v in fps.items() if k != k0}
    monkeypatch.setattr(cs, "STEPS", orig_steps)
    monkeypatch.setattr(cs, "TUNED_HEADLINE_ARGV",
                        orig_tuned + ["--seq", "8192"])
    fps3 = cs._step_fingerprints()
    assert fps3["headline_tuned"] != fps["headline_tuned"]
    assert fps3["kernels"] == fps["kernels"]
    monkeypatch.setattr(cs, "TUNED_HEADLINE_ARGV", orig_tuned)
    assert cs._step_fingerprints() == fps

    # Session-wide gates: legacy file (no fps) and dirty-at-measurement
    # resume nothing; staleness must be checked over bench's paths PLUS
    # the step scripts.
    seen = {}

    def fake_staleness(commit, paths=bench.MEASURED_PATHS):
        seen["commit"], seen["paths"] = commit, paths
        return {"stale": False, "changed_files": []}

    monkeypatch.setattr(bench, "_measurement_staleness", fake_staleness)
    assert cs._resumable_results(good) == results  # clean -> all resume
    assert seen["commit"] == "abc1234"
    assert "benchmarks/decode_bench.py" in seen["paths"]
    assert set(bench.MEASURED_PATHS) <= set(seen["paths"])
    assert cs._resumable_results({"commit": "abc1234",
                                  "results": results}) == {}  # legacy
    assert cs._resumable_results(
        {**good, "dirty": ["tpunet/ops/flash_attention.py"]}) == {}

    # A single step's stale fingerprint drops THAT step only.
    one_off = {**good, "step_fps": {**fps, "decode_mha": "0" * 16}}
    assert cs._resumable_results(one_off) == {"kernels": results["kernels"]}

    # Any reported staleness (or undecidable None) resumes nothing.
    monkeypatch.setattr(
        bench, "_measurement_staleness",
        lambda c, paths=None: {"stale": True,
                               "changed_files": ["tpunet/ops/x.py"]})
    assert cs._resumable_results(good) == {}
    monkeypatch.setattr(
        bench, "_measurement_staleness",
        lambda c, paths=None: {"stale": None, "error": "git timeout"})
    assert cs._resumable_results(good) == {}


def test_chip_session_demoted_cache_does_not_stick():
    """A step that wants flash but cached a smoke-demoted reference run
    must re-measure; matching attn (or no attn axis) stays cached."""
    from benchmarks import chip_session as cs

    flash_step = next((k, c) for k, c, _ in cs.STEPS
                      if k == "prefill_ttft_flash")
    ref_step = next((k, c) for k, c, _ in cs.STEPS
                    if k == "prefill_ttft_ref")
    assert cs._wanted_attn(*flash_step) == "flash"
    assert cs._wanted_attn(*ref_step) is None  # no --attn flag: unchecked
    assert cs._wanted_attn("headline", ["-m", "x"]) == "flash"
    assert cs._wanted_attn("decode_mha", ["-m", "x"]) is None

    demoted = {"platform": "tpu", "attn": "reference", "decode_tok_s": 1.0}
    good = {"platform": "tpu", "attn": "flash", "decode_tok_s": 1.0}
    assert cs._cache_satisfies("flash", demoted) is False
    assert cs._cache_satisfies("flash", good) is True
    assert cs._cache_satisfies(None, demoted) is True
    assert cs._cache_satisfies("flash", {"error": "boom"}) is False
    assert cs._cache_satisfies("flash", None) is False


def test_chip_session_dirty_tree_is_recorded(tmp_path, monkeypatch):
    """_persist must record uncommitted measured-path edits and the
    measured file must surface them — a bare hash alone would claim clean
    provenance for a dirty-tree measurement."""
    import json

    from benchmarks import chip_session as cs

    monkeypatch.setattr(cs, "RAW", str(tmp_path / "raw.json"))
    monkeypatch.setattr(cs, "MEASURED", str(tmp_path / "measured.json"))
    monkeypatch.setattr(cs, "_dirty_measured_paths",
                        lambda: ["tpunet/ops/flash_attention.py"])
    raw = {"headline": {"platform": "tpu", "device_kind": "TPU v5 lite",
                        "attn": "flash", "tokens_per_s": 1.0, "mfu": 0.1,
                        "vgg_img_per_s": 1.0}}
    cs._persist(raw)
    rec = json.loads((tmp_path / "raw.json").read_text())
    assert rec["dirty"] == ["tpunet/ops/flash_attention.py"]
    assert rec["step_fps"] == cs._step_fingerprints()
    assert cs._resumable_results(rec) == {}
    measured = json.loads((tmp_path / "measured.json").read_text())
    assert measured["uncommitted_at_measurement"] == [
        "tpunet/ops/flash_attention.py"]

    # Clean tree: no dirty key, resume allowed (staleness permitting).
    monkeypatch.setattr(cs, "_dirty_measured_paths", lambda: [])
    cs._persist(raw)
    rec = json.loads((tmp_path / "raw.json").read_text())
    assert "dirty" not in rec
    measured = json.loads((tmp_path / "measured.json").read_text())
    assert "uncommitted_at_measurement" not in measured
    # The dirty->clean provenance flip must have backed the old file up.
    backup = json.loads((tmp_path / "measured_prev.json").read_text())
    assert backup["uncommitted_at_measurement"] == [
        "tpunet/ops/flash_attention.py"]


def test_dirty_scan_undecidable_is_conservative(monkeypatch):
    """git failure during the dirty scan must record a sentinel (blocks
    resume, surfaces in the measured file) and must not let
    _measurement_staleness report a clean verdict."""
    import bench
    from benchmarks import chip_session as cs

    monkeypatch.setattr(bench, "_dirty_paths", lambda paths, repo=None: None)
    dirty = cs._dirty_measured_paths()
    assert dirty and "undecidable" in dirty[0]

    head = bench.subprocess.run(
        ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
        cwd=bench.os.path.dirname(bench.os.path.abspath(bench.__file__)),
    ).stdout.strip()
    out = bench._measurement_staleness(head)
    assert out["stale"] is None  # clean diff + failed scan = undecidable
    assert "status" in out["error"]


def test_profile_capture_cpu(tmp_path, capsys):
    import json

    from benchmarks.profile_capture import main as prof_main

    prof_main(["--out", str(tmp_path / "tr"), "--steps", "2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["platform"] == "cpu"
    assert out["files"] >= 1  # the runtime wrote trace artifacts
    assert out["step_ms"] > 0


def test_decode_tier_gating():
    import json
    import unittest.mock as mock

    import bench

    def fake(stdout):
        class _P:
            returncode = 0
            stderr = ""
        _P.stdout = json.dumps(stdout)
        return lambda *a, **k: _P

    # Chip up + TPU model tier + TPU decode: kept.
    with mock.patch("subprocess.run",
                    side_effect=fake({"platform": "tpu", "decode_tok_s": 9})):
        out = bench._decode_tier(True, {"platform": "tpu"})
    assert out["decode_tok_s"] == 9

    # decode_bench silently fell back to CPU (tunnel dropped mid-bench):
    # the datapoint must be DROPPED, not published as on-chip.
    with mock.patch("subprocess.run",
                    side_effect=fake({"platform": "cpu", "decode_tok_s": 9})):
        assert bench._decode_tier(True, {"platform": "tpu"}) is None

    # No TPU model tier -> never even attempts the subprocess.
    with mock.patch("subprocess.run", side_effect=AssertionError):
        assert bench._decode_tier(True, {"platform": "cpu"}) is None
        assert bench._decode_tier(False, None) is None
