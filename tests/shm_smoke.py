"""CI SHM-smoke lane: W=4 single-host allreduce with intra-host shared
memory (docs/DESIGN.md "Intra-host shared memory").

Two phases:

  * SINGLE HOST (the real deployment shape): all four ranks share the
    box's host id, so under `algo=hier` the topology post-pass resolves to
    the ring — running entirely over SHM ring segments. Gates, by counters
    (the PR 3/5 epistemic stance): TCP engine bytes in the measured window
    are EXACTLY 0 (every intra-host byte rode shared memory), SHM bytes
    equal the ring's 2(W-1)/W * S per rank per iteration, and wall-clock
    busbw meets or beats the flat-ring TCP-loopback control moving the
    same payload (interleaved reps, medians) — SHM's box-speed claim:
    the TCP stack and its syscalls leave the intra-host path.

  * FAKE-HOST SPLIT (2 "hosts" x 2 ranks via TPUNET_HOST_ID): `hier`
    engages for real — intra stages on the rings, inter stage on TCP —
    and per-rank DCN (TCP) bytes land at EXACTLY the inter stage's S/R
    per iteration, <= 0.55x the flat ring's per-rank bytes (the
    hierarchy's wire claim; any intra byte leaking onto TCP breaks the
    equality).

Run: python tests/shm_smoke.py   (exit 0 = pass)
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COUNT = 1 << 20  # 4 MiB payload
ITERS = 6
REPS = 3
WORLD = 4


def _rank(rank: int, world: int, port: int, q, mode: str) -> None:
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
        })
        if mode == "shm":  # single host: hier resolves to ring-over-SHM
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_ALGO"] = "hier"
        elif mode == "split":  # 2 fake hosts x 2 ranks: hier engages
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_ALGO"] = "hier"
            os.environ["TPUNET_HOST_ID"] = f"smokehost{rank // 2}"
        else:  # "tcp": flat-ring TCP-loopback control
            os.environ["TPUNET_SHM"] = "0"
            os.environ["TPUNET_ALGO"] = "ring"
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        arr = np.full(COUNT, float(rank + 1), np.float32)
        comm.all_reduce(arr)  # warmup: wires rings/mesh/segments
        comm.barrier()
        telemetry.reset()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = comm.all_reduce(arr)
        dt = time.perf_counter() - t0
        m = telemetry.metrics()  # counters read BEFORE any barrier token
        comm.barrier()
        comm.close()
        assert out[0] == sum(r + 1 for r in range(world))
        tcp_tx = sum(int(v) for key, v in
                     m.get("tpunet_qos_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        shm_tx = sum(int(v) for key, v in
                     m.get("tpunet_shm_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        q.put((rank, ("OK", {"dt": dt, "tcp_tx": tcp_tx, "shm_tx": shm_tx})))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", {})))


def main() -> None:
    from benchmarks import check_rank_results, spawn_ranks

    failures: list = []
    S = COUNT * 4
    times = {"shm": [], "tcp": []}
    ring_dcn_max = 0
    for rep in range(REPS):  # interleaved: drift hits both lanes equally
        for mode in ("shm", "tcp"):
            res = check_rank_results(
                spawn_ranks(_rank, WORLD, extra_args=(mode,), timeout=300))
            times[mode].append(max(r["dt"] for r in res.values()))
            if mode == "tcp":
                ring_dcn_max = max(ring_dcn_max,
                                   max(r["tcp_tx"] for r in res.values()))
                continue
            # Ring over SHM: 2(W-1)/W * S per rank per iteration, and the
            # intra-host stage (here: everything) moved ZERO TCP bytes.
            want_shm = ITERS * 2 * (WORLD - 1) * S // WORLD
            for rank, r in sorted(res.items()):
                if r["tcp_tx"] != 0:
                    failures.append(
                        f"rep {rep} rank {rank}: single-host allreduce moved "
                        f"{r['tcp_tx']} TCP bytes (want exactly 0)")
                if r["shm_tx"] != want_shm:
                    failures.append(
                        f"rep {rep} rank {rank}: SHM tx {r['shm_tx']} != "
                        f"{want_shm}")

    # Fake-host split: hier engages; DCN bytes exactly the inter stage.
    res = check_rank_results(
        spawn_ranks(_rank, WORLD, extra_args=("split",), timeout=300))
    hier_dcn = ITERS * S // 2  # S/R per rank per iteration, R = H = 2
    for rank, r in sorted(res.items()):
        if r["tcp_tx"] != hier_dcn:
            failures.append(
                f"split rank {rank}: TCP tx {r['tcp_tx']} != inter-stage-only "
                f"{hier_dcn} — intra bytes leaked onto TCP")
        if r["shm_tx"] != ITERS * S:
            failures.append(
                f"split rank {rank}: SHM tx {r['shm_tx']} != {ITERS * S}")
    if not hier_dcn <= 0.55 * ring_dcn_max:
        failures.append(
            f"hier per-rank DCN bytes {hier_dcn} > 0.55x flat ring's "
            f"{ring_dcn_max}")

    med_shm = statistics.median(times["shm"])
    med_tcp = statistics.median(times["tcp"])
    if med_shm > med_tcp:
        failures.append(
            f"SHM busbw below the TCP-loopback control: median "
            f"{med_shm:.3f}s vs {med_tcp:.3f}s for the same payload")
    print(f"shm_smoke: ring-over-SHM median {med_shm:.3f}s vs TCP-loopback "
          f"control {med_tcp:.3f}s over {REPS} interleaved reps "
          f"({ITERS}x{S >> 20} MiB, W={WORLD}); split-topology per-rank DCN "
          f"bytes {hier_dcn} vs flat ring {ring_dcn_max} "
          f"({hier_dcn / ring_dcn_max:.2f}x)")
    if failures:
        print("shm_smoke FAILURES:")
        for f in failures:
            print(f"  - {f}")
        sys.exit(1)
    print("shm_smoke: OK")


if __name__ == "__main__":
    main()
