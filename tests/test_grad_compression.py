"""bf16-compressed cross-host gradient sync: parity and convergence."""

from __future__ import annotations

import os

import numpy as np
import pytest

# Module level so mp-spawn children also pin JAX to CPU (see conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import run_spawn_workers  # noqa: E402


def test_rejects_unknown_compression():
    import jax.numpy as jnp
    import optax

    from tpunet.models import Transformer
    from tpunet.train import make_train_step

    model = Transformer(vocab=16, d_model=8, n_layers=1, n_heads=2, d_ff=16,
                        compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="grad_compression"):
        make_train_step(model, optax.sgd(0.1), grad_compression="fp8")


def _worker(rank: int, world: int, port: int, q, mode: str = "python") -> None:
    try:
        if mode == "wire":
            # Native wire codec: the ring compresses f32 payloads itself; the
            # trainer must detect it and skip its own bf16 cast (one cast
            # path, not two).
            os.environ["TPUNET_WIRE_DTYPE"] = "bf16"
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from tpunet import distributed
        from tpunet.models import Transformer
        from tpunet.train import create_train_state, make_train_step

        comm = distributed.initialize(f"127.0.0.1:{port}", rank, world)
        assert comm.wire_dtype == ("bf16" if mode == "wire" else "f32")
        model = Transformer(vocab=32, d_model=16, n_layers=1, n_heads=2,
                            d_ff=32, compute_dtype=jnp.float32)
        tx = optax.sgd(0.05)
        # Different data per rank — the DCN pmean is what couples them.
        toks = jax.random.randint(jax.random.PRNGKey(10 + rank), (2, 8), 0, 32)
        labels = jnp.roll(toks, -1, axis=1)
        state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
        step = make_train_step(model, tx, cross_host=True, donate=False,
                               grad_compression="bf16")
        losses = []
        s = state
        for i in range(4):
            s, loss = step(s, toks, labels, jax.random.PRNGKey(i))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses), losses
        assert losses[-1] < losses[0], losses

        # Params must remain bitwise-identical across ranks after sync'd
        # steps (same init, same reduced gradient on every rank).
        from jax.flatten_util import ravel_pytree

        from tpunet.interop import dcn_all_gather

        flat = ravel_pytree(s.params)[0]
        all_params = np.asarray(jax.jit(dcn_all_gather)(flat))
        for r in range(1, world):
            np.testing.assert_array_equal(all_params[0], all_params[r])

        if mode == "wire":
            # Prove the sync actually rode the native codec: the wire-byte
            # counters moved and the ratio shows the halving.
            from tpunet import telemetry

            m = telemetry.metrics()
            tx = sum(v for k, v in m.get("tpunet_codec_bytes_total", {}).items()
                     if telemetry.labels(k).get("codec") == "bf16"
                     and telemetry.labels(k).get("dir") == "tx")
            assert tx > 0, "trainer did not route through the wire codec"
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_bf16_compressed_training_2proc():
    """Pure-Python fallback lane: f32-wire communicator, trainer casts to
    bf16 in JAX around the DCN pmean (the pre-codec behavior)."""
    run_spawn_workers(_worker, 2, extra_args=("python",))


def test_bf16_wire_codec_training_2proc():
    """Native-codec lane: same trainer flag, but the communicator compresses
    on the wire — the trainer ships f32 and the ring quantizes at the hops
    (f32 accumulation). Same convergence and cross-rank bit-identity
    contract as the python lane, plus counter proof it used the wire."""
    run_spawn_workers(_worker, 2, extra_args=("wire",))
