"""MoE dispatch/combine + pipeline stage driver (tpunet.workloads).

The workload tier is pure-Python over public tpunet APIs, so most of the
suite runs without a socket (routing/packing determinism, slot
bookkeeping, overflow drops); the multiprocess lanes pin end-to-end
dispatch->expert->combine correctness and the directed microbatch chain
across stages.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from conftest import run_spawn_workers

from tpunet.workloads import moe


# ---------------------------------------------------------------------------
# Routing: Zipf skew model.


def test_zipf_weights_shape_and_skew():
    w0 = moe.zipf_weights(8, 0.0)
    np.testing.assert_allclose(w0, np.full(8, 1 / 8))  # skew 0 = uniform
    w2 = moe.zipf_weights(8, 2.0)
    assert abs(w2.sum() - 1.0) < 1e-12
    assert np.all(np.diff(w2) < 0), "popularity must fall with rank"
    assert w2[0] > 4 * w2[-1], "skew=2 must concentrate load"
    with pytest.raises(ValueError):
        moe.zipf_weights(0, 1.0)
    with pytest.raises(ValueError):
        moe.zipf_weights(4, -1.0)


def test_route_tokens_skew_and_env_default(monkeypatch):
    rng = np.random.default_rng(3)
    e = moe.route_tokens(5000, 4, 3.0, rng)
    assert e.shape == (5000,) and e.min() >= 0 and e.max() < 4
    counts = np.bincount(e, minlength=4)
    # skew 3: the hottest expert takes a clear majority
    assert counts.max() > 0.5 * 5000
    # skew rides TPUNET_MOE_SKEW when not passed (the registered knob)
    monkeypatch.setenv("TPUNET_MOE_SKEW", "0.0")
    e0 = moe.route_tokens(8000, 4, rng=np.random.default_rng(4))
    c0 = np.bincount(e0, minlength=4)
    assert c0.max() < 0.35 * 8000, "skew=0 from env should be near-uniform"


# ---------------------------------------------------------------------------
# Packing: capacity, drops, slot bookkeeping (socket-free via W=1 comm).


def _w1_comm():
    from conftest import free_port

    from tpunet.collectives import Communicator

    return Communicator(f"127.0.0.1:{free_port()}", 0, 1)


def test_pack_capacity_overflow_drops_loudly():
    comm = _w1_comm()
    try:
        d = moe.MoeDispatcher(comm, d_model=4, capacity=2)
        toks = np.arange(12, dtype=np.float32).reshape(3, 4)
        buf, counts = d.pack(toks, np.zeros(3, np.int64))
        assert counts.tolist() == [2]  # third token dropped, not mixed in
        assert d.tokens_dropped == 1 and d.tokens_routed == 3
        np.testing.assert_array_equal(buf[0, 0], toks[0])
        np.testing.assert_array_equal(buf[0, 1], toks[1])
        with pytest.raises(ValueError):
            d.pack(toks, np.array([0, 0, 5]))  # expert id out of range
        with pytest.raises(ValueError):
            d.pack(toks[:, :2], np.zeros(3, np.int64))  # wrong d_model
    finally:
        comm.close()


def test_single_rank_dispatch_combine_roundtrip():
    comm = _w1_comm()
    try:
        d = moe.MoeDispatcher(comm, d_model=8, capacity=16)
        rng = np.random.default_rng(0)
        toks = rng.standard_normal((10, 8)).astype(np.float32)
        expert_toks, counts = d.dispatch(toks, np.zeros(10, np.int64))
        assert counts.tolist() == [10]
        out = d.combine(expert_toks * 3.0)
        np.testing.assert_allclose(out, toks * 3.0, rtol=1e-6)
        assert d.drop_fraction == 0.0
    finally:
        comm.close()


# ---------------------------------------------------------------------------
# Multi-rank: dispatch -> expert -> combine end to end.


def _moe_worker(rank, world, port, q, env):
    try:
        os.environ.update(env)
        from tpunet.collectives import Communicator

        d_model, capacity, T = 8, 8, 16
        rng = np.random.default_rng(100 + rank)
        toks = rng.standard_normal((T, d_model)).astype(np.float32)
        experts = moe.route_tokens(T, world, 1.0, rng)
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            d = moe.MoeDispatcher(comm, d_model=d_model, capacity=capacity)
            expert_toks, counts_by_src = d.dispatch(toks, experts)
            # Expert applies a rank-stamped transform so combine provably
            # visited the RIGHT expert: out = in * (expert_rank + 2).
            out = d.combine(expert_toks * float(rank + 2))
        # Validate against local bookkeeping: every kept token came back
        # through its expert's transform; dropped tokens stayed zero.
        kept = d._kept
        for i in range(T):
            if kept[i]:
                np.testing.assert_allclose(
                    out[i], toks[i] * float(experts[i] + 2), rtol=1e-5)
            else:
                assert np.all(out[i] == 0.0)
        # counts_by_src[s] bounded by capacity, and my own column matches
        # my local pack counts for my expert.
        assert counts_by_src.max() <= capacity
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [2, 4])
def test_moe_dispatch_combine_multi_rank(world):
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"}
    run_spawn_workers(_moe_worker, world, extra_args=(env,))


def test_moe_dispatch_combine_hier_typed():
    """The whole stack at once: 2x2 fake hosts, hier A2A, int8 typed wire —
    combine results stay inside the documented per-block error bound."""
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
           "TPUNET_SHM": "1", "TPUNET_A2A_ALGO": "hier",
           "TPUNET_WIRE_DTYPE": "int8"}
    run_spawn_workers(_moe_typed_worker, 4, extra_args=(env,))


def _moe_typed_worker(rank, world, port, q, env):
    try:
        os.environ.update(env)
        os.environ["TPUNET_HOST_ID"] = f"moewl{rank // 2}"
        from tpunet.collectives import Communicator

        d_model, capacity, T = 16, 8, 16
        rng = np.random.default_rng(200 + rank)
        toks = rng.standard_normal((T, d_model)).astype(np.float32)
        experts = moe.route_tokens(T, world, 1.0, rng)
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            d = moe.MoeDispatcher(comm, d_model=d_model, capacity=capacity)
            expert_toks, _ = d.dispatch(toks, experts)
            out = d.combine(expert_toks)
        kept = d._kept
        # Two wire hops (dispatch + combine), each |err| <= amax/254 per
        # block; values are standard-normal, so 0.05 is a generous-but-
        # bug-catching bound.
        for i in range(T):
            if kept[i]:
                np.testing.assert_allclose(out[i], toks[i], atol=0.05)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


# ---------------------------------------------------------------------------
# Pipeline stage driver.


def test_ticket_after_ordering_unit():
    from tpunet.workloads.pipeline import Ticket

    order = []

    class FakeReq:
        def __init__(self, name):
            self.name = name

        def wait(self, timeout=None):
            order.append(self.name)
            return 0

        def test(self):
            return True, 0

    t1 = Ticket(FakeReq("a"))
    t2 = Ticket(FakeReq("b"), deps=(t1,))
    t3 = Ticket(FakeReq("c"), deps=(t2, t1))
    t3.wait()
    assert order == ["a", "b", "c"], order  # deps settle first, once each
    assert t1.done() and t2.done() and t3.done()


def _pipe_worker(rank, world, port, q, env):
    try:
        os.environ.update(env)
        from tpunet.collectives import Communicator
        from tpunet.workloads.pipeline import PipelineStage

        n_micro, n = 6, 1024
        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        with PipelineStage(comm) as st:
            # first/last sanity + misdirected transfers fail loudly
            assert st.is_first == (rank == 0)
            assert st.is_last == (rank == world - 1)
            if st.is_last:
                try:
                    st.isend(np.zeros(4, np.float32))
                    raise AssertionError("last stage isend must raise")
                except RuntimeError:
                    pass
            if st.is_first:
                mbs = [np.full(n, 10.0 * i, np.float32) for i in range(n_micro)]
                out = st.run(lambda x: x + 1.0, microbatches=mbs)
                assert out is None
            else:
                out = st.run(lambda x: x + 1.0, n_micro=n_micro, mb_shape=(n,))
            if st.is_last:
                assert len(out) == n_micro
                for i, y in enumerate(out):
                    assert np.all(y == 10.0 * i + world), (i, y[0])
        comm.close()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [2, 3])
def test_pipeline_microbatch_chain(world):
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"}
    run_spawn_workers(_pipe_worker, world, extra_args=(env,))


def test_pipeline_chain_across_fake_hosts():
    """Stage boundaries crossing a TPUNET_HOST_ID split: stage links between
    fake hosts ride TCP, the chain still verifies end to end."""
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
           "TPUNET_SHM": "1"}
    run_spawn_workers(_pipe_split_worker, 4, extra_args=(env,))


def _pipe_split_worker(rank, world, port, q, env):
    os.environ["TPUNET_HOST_ID"] = f"pipewl{rank // 2}"
    _pipe_worker(rank, world, port, q, env)
