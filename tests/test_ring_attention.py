"""Ring attention (sequence parallelism) numerics on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.ops import attention_reference
from tpunet.parallel import make_named_mesh, ring_self_attention


def _qkv(rng, b, s, h, d, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(causal):
    mesh = make_named_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(0), 4, 32, 2, 8)
    out = ring_self_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_with_tp_heads():
    # Sequence over sp AND heads over tp simultaneously.
    mesh = make_named_mesh({"dp": 2, "sp": 2, "tp": 2})
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 16, 4, 8)
    out = ring_self_attention(q, k, v, mesh, causal=True, tp_axis="tp")
    ref = attention_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_sp_only_long_sequence():
    # All 8 devices on sp — the pure long-context configuration.
    mesh = make_named_mesh({"sp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 64, 2, 16)
    out = ring_self_attention(q, k, v, mesh, causal=True, dp_axis=None)
    ref = attention_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grad_matches(causal):
    mesh = make_named_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(3), 2, 32, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal) ** 2)

    gring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gring, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ring_under_jit_bf16():
    mesh = make_named_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 32, 2, 8, jnp.bfloat16)
    f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh, causal=True, dp_axis=None))
    out = f(q, k, v)
    ref = attention_reference(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )
