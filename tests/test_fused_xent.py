"""Blockwise fused cross-entropy: exact parity with the materialized-logits
path — values AND gradients — across block sizes, dtypes, and the trainer
integration (including MoE aux-loss collection through features_only)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tpunet.ops import blockwise_cross_entropy


def _ref_loss(feats, kernel, labels):
    logits = jnp.dot(feats, kernel, preferred_element_type=jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


@pytest.mark.parametrize("block", [16, 64, 100, 256])
def test_value_and_grad_parity(block):
    # vocab=100 with block=16 exercises the padded final block; block=256
    # exercises block > vocab clamping.
    rng = np.random.default_rng(0)
    feats = jnp.asarray(rng.standard_normal((48, 32)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((32, 100)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 100, 48), jnp.int32)

    got = blockwise_cross_entropy(feats, kernel, labels, block_vocab=block)
    want = _ref_loss(feats, kernel, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)

    def fused_mean(f, k):
        return blockwise_cross_entropy(f, k, labels, block_vocab=block).mean()

    def ref_mean(f, k):
        return _ref_loss(f, k, labels).mean()

    gf_f, gk_f = jax.grad(fused_mean, argnums=(0, 1))(feats, kernel)
    gf_r, gk_r = jax.grad(ref_mean, argnums=(0, 1))(feats, kernel)
    np.testing.assert_allclose(np.asarray(gf_f), np.asarray(gf_r),
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gk_f), np.asarray(gk_r),
                               rtol=2e-5, atol=1e-6)


def test_bf16_feats():
    rng = np.random.default_rng(1)
    feats = jnp.asarray(rng.standard_normal((16, 24)), jnp.bfloat16)
    kernel = jnp.asarray(rng.standard_normal((24, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, 16), jnp.int32)
    got = blockwise_cross_entropy(feats, kernel, labels, block_vocab=32)
    want = _ref_loss(feats, kernel.astype(jnp.bfloat16), labels)
    # bf16 matmuls with f32 accumulation on both sides.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    assert got.dtype == jnp.float32


@pytest.mark.parametrize("experts", [0, 4])
def test_train_step_parity(experts):
    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    model = Transformer(vocab=53, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                        n_experts=experts, compute_dtype=jnp.float32)
    tx = optax.adamw(3e-3)
    toks = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, 53)
    labels = jnp.roll(toks, -1, axis=1)
    state0, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)

    step_ref = make_train_step(model, tx, donate=False)
    step_fus = make_train_step(model, tx, donate=False, fused_xent_block=16)

    s_r, s_f = state0, state0
    for s in range(2):
        s_r, loss_r = step_ref(s_r, toks, labels, jax.random.PRNGKey(s))
        s_f, loss_f = step_fus(s_f, toks, labels, jax.random.PRNGKey(s))
        np.testing.assert_allclose(float(loss_r), float(loss_f), rtol=1e-6)

    # Post-adamw tolerance: the fused path's per-block dkernel matmuls sum
    # in a different order (~1e-7 grad noise), which adam's 1/sqrt(nu)
    # amplifies on near-zero second moments in early steps. A structural
    # error (wrong block, dropped label) would be off by ~1e-1.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=3e-5
        ),
        s_r.params, s_f.params,
    )


def test_no_full_logits_in_jaxpr():
    # The memory claim, checked structurally: no intermediate of shape
    # (N, vocab) appears in the fused jaxpr (the reference path has one).
    rng = np.random.default_rng(2)
    n_tok, d, vocab, block = 64, 16, 1000, 100
    feats = jnp.asarray(rng.standard_normal((n_tok, d)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((d, vocab)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, n_tok), jnp.int32)

    def mean_loss(f, k):
        return blockwise_cross_entropy(f, k, labels, block_vocab=block).mean()

    jaxpr = jax.make_jaxpr(jax.grad(mean_loss, argnums=(0, 1)))(feats, kernel)

    def shapes(jp):
        for eqn in jp.eqns:
            for v in eqn.outvars:
                if hasattr(v.aval, "shape"):
                    yield tuple(v.aval.shape)
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    yield from shapes(sub.jaxpr)

    assert (n_tok, vocab) not in set(shapes(jaxpr.jaxpr)), (
        "fused path materialized full logits"
    )


def test_return_lse_matches_dense_logsumexp():
    import jax

    feats = jax.random.normal(jax.random.PRNGKey(0), (12, 16), jnp.float32)
    kernel = jax.random.normal(jax.random.PRNGKey(1), (16, 50), jnp.float32) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (12,), 0, 50)
    nll, lse = blockwise_cross_entropy(feats, kernel, labels, block_vocab=16,
                                       return_lse=True)
    dense = jax.scipy.special.logsumexp(feats @ kernel, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(dense),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(nll),
        np.asarray(blockwise_cross_entropy(feats, kernel, labels,
                                           block_vocab=16)),
        atol=1e-6)
