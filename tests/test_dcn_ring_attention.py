"""Cross-host (multi-process) ring attention parity vs full attention."""

from __future__ import annotations

import os

import numpy as np
import pytest

# Module level so mp-spawn children (which re-import this module) also pin
# JAX to CPU — the axon sitecustomize hook force-selects the TPU otherwise.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import run_spawn_workers  # noqa: E402

B, S, H, D = 2, 32, 2, 8  # full (unsharded) attention problem


def _full_qkv():
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32) for k in ks)


def _worker(rank: int, world: int, port: int, q, causal: bool) -> None:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.ops import attention_reference
        from tpunet.parallel import dcn_ring_attention

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        qf, kf, vf = _full_qkv()  # same on every rank (same seed)
        s_local = S // world
        sl = slice(rank * s_local, (rank + 1) * s_local)

        fn = jax.jit(lambda a, b, c: dcn_ring_attention(a, b, c, causal=causal))
        got = fn(qf[:, sl], kf[:, sl], vf[:, sl])

        want = attention_reference(qf, kf, vf, causal)[:, sl]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("causal", [False, True])
def test_dcn_ring_attention_2proc(causal):
    run_spawn_workers(_worker, 2, extra_args=(causal,))


def test_dcn_ring_attention_4proc_causal():
    run_spawn_workers(_worker, 4, extra_args=(True,))


def _model_worker(rank: int, world: int, port: int, q) -> None:
    # Full Transformer with sequence sharded across processes: each rank's
    # logits on its shard must equal the single-host reference model's
    # logits sliced to that shard (global rotary + ring causality).
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.models import Transformer

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        kw = dict(vocab=32, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                  compute_dtype=jnp.float32)
        ref_model = Transformer(attn_impl="reference", **kw)
        dcn_model = Transformer(attn_impl="dcn_ring", **kw)

        toks = jax.random.randint(jax.random.PRNGKey(3), (2, S), 0, 32)
        params = ref_model.init(jax.random.PRNGKey(4), toks)["params"]
        want = ref_model.apply({"params": params}, toks)

        s_local = S // world
        sl = slice(rank * s_local, (rank + 1) * s_local)
        got = dcn_model.apply({"params": params}, toks[:, sl])
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want[:, sl]), atol=1e-4, rtol=1e-4
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_transformer_dcn_ring_2proc():
    run_spawn_workers(_model_worker, 2)


def _zigzag_worker(rank: int, world: int, port: int, q) -> None:
    # Balanced cross-host layout: rank holds chunks (rank, 2W-1-rank) of the
    # zigzag-permuted sequence; gathered outputs un-permute to the full
    # causal reference.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")

        from tpunet import distributed
        from tpunet.ops import attention_reference
        from tpunet.parallel import dcn_zigzag_attention, to_zigzag

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        qf, kf, vf = _full_qkv()
        qz, kz, vz = (to_zigzag(x, world) for x in (qf, kf, vf))
        s_local = S // world
        sl = slice(rank * s_local, (rank + 1) * s_local)

        fn = jax.jit(dcn_zigzag_attention)
        got = fn(qz[:, sl], kz[:, sl], vz[:, sl])

        want = to_zigzag(attention_reference(qf, kf, vf, True), world)[:, sl]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_dcn_zigzag_2proc():
    run_spawn_workers(_zigzag_worker, 2)


def test_dcn_zigzag_4proc():
    run_spawn_workers(_zigzag_worker, 4)


def _zigzag_model_worker(rank: int, world: int, port: int, q) -> None:
    # Full Transformer with attn_impl="dcn_zigzag": each rank's logits on its
    # zigzag shard must equal the single-host reference model's logits,
    # zigzag-permuted and sliced to that shard (rotary uses zigzag_positions).
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.models import Transformer
        from tpunet.parallel import to_zigzag

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        kw = dict(vocab=32, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                  compute_dtype=jnp.float32)
        ref_model = Transformer(attn_impl="reference", **kw)
        zz_model = Transformer(attn_impl="dcn_zigzag", **kw)

        seq = 32
        toks = jax.random.randint(jax.random.PRNGKey(5), (2, seq), 0, 32)
        params = ref_model.init(jax.random.PRNGKey(0), toks)["params"]
        want = to_zigzag(ref_model.apply({"params": params}, toks), world)

        s_local = seq // world
        sl = slice(rank * s_local, (rank + 1) * s_local)
        toks_zz = to_zigzag(toks, world)
        got = zz_model.apply({"params": params}, toks_zz[:, sl])
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want[:, sl]), atol=3e-5, rtol=3e-5
        )
        distributed.finalize()
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_dcn_zigzag_transformer_2proc():
    run_spawn_workers(_zigzag_model_worker, 2)
