"""Checkpoint/resume roundtrip tests (orbax-backed)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.models import Transformer
from tpunet.train import (
    CheckpointManager,
    TrainState,
    create_train_state,
    make_train_step,
    restore_pytree,
    save_pytree,
)


@pytest.fixture
def tiny_state():
    model = Transformer(vocab=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
                        compute_dtype=jnp.float32)
    toks = jnp.zeros((2, 8), jnp.int32)
    tx = optax.adam(1e-3)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), toks, tx)
    return model, tx, state, toks


def _assert_tree_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


def test_manager_roundtrip_and_retention(tmp_path, tiny_state):
    model, tx, state, toks = tiny_state
    step = make_train_step(model, tx, donate=False)
    labels = jnp.zeros((2, 8), jnp.int32)

    with CheckpointManager(tmp_path / "ckpt", max_to_keep=2) as mgr:
        states = {}
        s = state
        for i in range(3):
            s, _ = step(s, toks, labels, jax.random.PRNGKey(i))
            mgr.save(i, s)
            states[i] = s
        mgr.wait_until_finished()
        # Retention: only the last 2 remain.
        assert mgr.all_steps() == [1, 2]
        assert mgr.latest_step() == 2

        restored = mgr.restore_latest(state)
        _assert_tree_equal(restored.params, states[2].params)
        _assert_tree_equal(restored.opt_state, states[2].opt_state)
        assert int(restored.step) == int(states[2].step)


def test_restore_latest_empty_dir(tmp_path, tiny_state):
    _, _, state, _ = tiny_state
    with CheckpointManager(tmp_path / "none") as mgr:
        assert mgr.restore_latest(state) is None


def test_resume_training_continues(tmp_path, tiny_state):
    # Save mid-training, restore into a FRESH state, verify identical
    # continuation (exact resume incl. optimizer momentum).
    model, tx, state, toks = tiny_state
    step = make_train_step(model, tx, donate=False)
    labels = jnp.roll(toks, -1, axis=1)

    s = state
    for i in range(2):
        s, _ = step(s, toks, labels, jax.random.PRNGKey(i))
    save_pytree(tmp_path / "mid", s._asdict())

    cont_a, loss_a = step(s, toks, labels, jax.random.PRNGKey(9))

    fresh = restore_pytree(tmp_path / "mid", state._asdict())
    fresh_state = TrainState(**fresh)
    cont_b, loss_b = step(fresh_state, toks, labels, jax.random.PRNGKey(9))

    assert float(loss_a) == float(loss_b)
    _assert_tree_equal(cont_a.params, cont_b.params)
