"""Perf-regression sentry (benchmarks/sentry.py): deterministic claim
evaluation over canned measurements. Goes green on numbers the checked-in
baseline accepts, RED on the impossible fixture baseline — proving the CI
gate can actually fail, not just rubber-stamp. The live measurement path
(spawned engines + collectives) runs in the CI sentry_smoke lane, not here."""

from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from benchmarks.sentry import GROUPS, check, main  # noqa: E402

# Healthy numbers (the live 2026-08 measurement, see docs/SENTRY_BASELINE.json's
# comment) — every claim in the checked-in baseline accepts these.
HEALTHY = {
    "basic_syscalls_per_mib": 0.188,
    "epoll_syscalls_per_mib": 0.414,
    "basic_busbw_gbps": 1.8,
    "codec_wire_ratio_bf16_over_f32": 0.5,
    "ring_steps_w4": 6,
    "hier_dcn_fraction_w4": 0.3333,
}


def _baseline():
    with open(REPO / "docs" / "SENTRY_BASELINE.json") as f:
        return json.load(f)


def _red_baseline():
    with open(REPO / "tests" / "fixtures" / "sentry_red_baseline.json") as f:
        return json.load(f)


def test_groups_cover_all_baseline_claims():
    """Every baseline claim maps to a measurement group (else a regression
    in it could never re-measure) and every HEALTHY key is claimed."""
    group_keys = {k for keys in GROUPS.values() for k in keys}
    for key in _baseline()["claims"]:
        assert key in group_keys, f"claim {key} has no measurement group"
    assert set(HEALTHY) == set(_baseline()["claims"])


def test_sentry_green_on_healthy_measurements():
    verdict = check(_baseline(), measurements=HEALTHY)
    assert verdict["ok"], verdict["claims"]
    assert all(c["verdict"] == "ok" for c in verdict["claims"].values())


def test_sentry_red_on_impossible_fixture():
    """The same healthy numbers violate every claim of the red fixture —
    the sentry must fail loudly (exit 1 through main) with per-claim
    REGRESSION verdicts, no re-measure in canned mode."""
    verdict = check(_red_baseline(), measurements=HEALTHY)
    assert not verdict["ok"]
    regressions = [k for k, c in verdict["claims"].items()
                   if c["verdict"] == "REGRESSION"]
    assert set(regressions) == set(_red_baseline()["claims"])
    # max/min/equals violations all render a human-readable detail.
    assert "!=" in verdict["claims"]["ring_steps_w4"]["detail"]
    assert ">" in verdict["claims"]["basic_syscalls_per_mib"]["detail"]


def test_sentry_cli_red_exit_code(tmp_path):
    meas = tmp_path / "meas.json"
    meas.write_text(json.dumps(HEALTHY))
    out = tmp_path / "verdict.json"
    rc = main(["--check",
               "--baseline", str(REPO / "tests" / "fixtures" /
                                 "sentry_red_baseline.json"),
               "--measurements", str(meas), "--json", str(out)])
    assert rc == 1
    verdict = json.loads(out.read_text())
    assert not verdict["ok"]

    rc = main(["--check", "--measurements", str(meas)])
    assert rc == 0  # checked-in baseline accepts the healthy numbers


def test_sentry_single_regression_is_isolated():
    """One bad number reds only its own claim."""
    bad = dict(HEALTHY, codec_wire_ratio_bf16_over_f32=1.0)  # codec gone
    verdict = check(_baseline(), measurements=bad)
    assert not verdict["ok"]
    wrong = {k for k, c in verdict["claims"].items()
             if c["verdict"] == "REGRESSION"}
    assert wrong == {"codec_wire_ratio_bf16_over_f32"}


def test_sentry_missing_measurement_is_a_regression():
    part = {k: v for k, v in HEALTHY.items() if k != "ring_steps_w4"}
    verdict = check(_baseline(), measurements=part)
    assert not verdict["ok"]
    assert verdict["claims"]["ring_steps_w4"]["detail"] == "no measurement"


def test_sentry_rejects_unknown_schema():
    with pytest.raises(ValueError, match="tpunet-sentry-v1"):
        check({"schema": "tpunet-sentry-v2", "claims": {}},
              measurements=HEALTHY)
