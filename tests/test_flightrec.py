"""Flight recorder (docs/DESIGN.md §6c): ring wraparound, dump-under-fire
torn-read accounting, SIGUSR2 snapshots, disabled mode, and the counter
timeseries sampler. The cross-rank hang postmortem lives in
tests/test_postmortem.py; this file pins the single-rank recorder itself."""

from __future__ import annotations

import json
import os
import signal
import time

from conftest import run_spawn_workers


def _loopback_transfers(n: int, size: int = 1 << 18):
    """Drive n loopback transfers through the native wire path, so the
    recorder accumulates wire/req events. Returns after the data landed."""
    import numpy as np

    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    import threading

    rc_holder = {}
    t = threading.Thread(target=lambda: rc_holder.update(rc=listen.accept()))
    t.start()
    sc = net.connect(listen.handle)
    t.join()
    rc = rc_holder["rc"]
    data = np.arange(size, dtype=np.uint8) % 251
    buf = np.zeros(size, dtype=np.uint8)
    for _ in range(n):
        req = rc.irecv(buf)
        sc.send(data, timeout=60)
        req.wait(timeout=60)
    sc.close()
    rc.close()
    listen.close()
    net.close()


def _wraparound_worker(rank: int, world: int, port: int, q, tmpdir) -> None:
    """Tiny ring (64 slots) + enough traffic to lap it several times: the
    dump must report recorded > capacity, dropped = recorded - capacity,
    and carry exactly `capacity` events — the newest window, not garbage."""
    try:
        os.environ["TPUNET_FLIGHTREC_EVENTS"] = "64"
        os.environ["TPUNET_TRACE_DIR"] = tmpdir
        os.environ["TPUNET_RANK"] = str(rank)
        from tpunet import telemetry

        _loopback_transfers(40)

        recorded, capacity = telemetry.flightrec_stats()
        assert capacity == 64, f"pow2 ring capacity: {capacity}"
        assert recorded > capacity, f"ring never wrapped: {recorded}"

        # On-demand dump to an explicit directory.
        path = telemetry.flightrec_dump(tmpdir, reason="unit-test")
        assert os.path.dirname(path) == tmpdir
        with open(path) as f:
            d = json.load(f)
        assert d["schema"] == "tpunet-flightrec-v1"
        assert d["rank"] == rank
        assert d["reason"] == "unit-test"
        assert d["capacity"] == 64
        assert d["recorded"] > 64
        assert d["dropped"] == d["recorded"] - 64
        assert len(d["events"]) == 64
        # Quiesced dump: no slot was mid-write.
        assert d["torn"] == 0
        kinds = {ev["kind"] for ev in d["events"]}
        assert kinds & {"wire_send", "wire_recv", "req_start", "req_done"}, kinds
        ts = [ev["t"] for ev in d["events"]]
        assert ts == sorted(ts), "ring replay must be time-ordered"

        # SIGUSR2: the async-signal-safe handler overwrites the default dump
        # path; poll because delivery may land on another thread.
        os.kill(os.getpid(), signal.SIGUSR2)
        default = os.path.join(tmpdir, f"tpunet-flightrec-rank{rank}.json")
        deadline = time.monotonic() + 10
        sig = None
        while time.monotonic() < deadline:
            try:
                with open(default) as f:
                    sig = json.load(f)
                if sig.get("reason") == "sigusr2":
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        assert sig and sig["reason"] == "sigusr2", f"no SIGUSR2 dump: {sig}"
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_flightrec_wraparound_and_sigusr2(tmp_path):
    run_spawn_workers(_wraparound_worker, 1, extra_args=(str(tmp_path),))


def _disabled_worker(rank: int, world: int, port: int, q, tmpdir) -> None:
    """TPUNET_FLIGHTREC_EVENTS=0 compiles the recorder out at runtime: a
    dump request is a typed error, not a zero-event file."""
    try:
        os.environ["TPUNET_FLIGHTREC_EVENTS"] = "0"
        from tpunet import _native, telemetry

        _loopback_transfers(2)
        try:
            telemetry.flightrec_dump(tmpdir)
            q.put((rank, "FAIL: dump succeeded with recorder disabled"))
            return
        except _native.NativeError:
            pass
        # The never-raises verdict hook degrades to None, not an exception.
        assert telemetry.flightrec_dump_verdict("unit") is None
        assert telemetry.flightrec_stats() == (0, 0)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_flightrec_disabled(tmp_path):
    run_spawn_workers(_disabled_worker, 1, extra_args=(str(tmp_path),))


def _torn_worker(rank: int, world: int, port: int, q, tmpdir) -> None:
    """Dump while the wire keeps recording: every snapshot must parse as
    valid JSON with sane accounting. Torn slots (writer mid-flight during
    the copy) are counted, never emitted as garbage events."""
    try:
        os.environ["TPUNET_FLIGHTREC_EVENTS"] = "256"
        os.environ["TPUNET_TRACE_DIR"] = tmpdir
        import threading

        from tpunet import telemetry

        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                _loopback_transfers(4, size=1 << 14)

        t = threading.Thread(target=hammer)
        t.start()
        try:
            torn_total = 0
            for i in range(10):
                path = telemetry.flightrec_dump(tmpdir, reason=f"fire-{i}")
                with open(path) as f:
                    d = json.load(f)  # must parse even mid-traffic
                assert len(d["events"]) <= d["capacity"]
                assert d["torn"] >= 0
                torn_total += d["torn"]
                for ev in d["events"]:
                    assert isinstance(ev["t"], int) and ev["kind"], ev
        finally:
            stop.set()
            t.join(timeout=30)
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_flightrec_dump_under_fire(tmp_path):
    run_spawn_workers(_torn_worker, 1, extra_args=(str(tmp_path),))


def _ts_worker(rank: int, world: int, port: int, q, tmpdir) -> None:
    """TPUNET_TS_INTERVAL_MS>0 appends full-exposition snapshots as JSONL —
    the measurement history the perf sentry and dashboards replay."""
    try:
        os.environ["TPUNET_TS_INTERVAL_MS"] = "50"
        os.environ["TPUNET_TRACE_DIR"] = tmpdir
        os.environ["TPUNET_RANK"] = str(rank)
        from tpunet import telemetry

        telemetry.metrics_text()  # construct the singleton -> sampler starts
        _loopback_transfers(2)
        path = os.path.join(tmpdir, f"tpunet-ts-rank{rank}.jsonl")
        deadline = time.monotonic() + 15
        lines = []
        while time.monotonic() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    lines = [ln for ln in f.read().splitlines() if ln.strip()]
                if len(lines) >= 3:
                    break
            time.sleep(0.05)
        assert len(lines) >= 3, f"sampler wrote {len(lines)} lines"
        last_t = -1
        for ln in lines:
            snap = json.loads(ln)  # every line is one standalone JSON object
            assert snap["t_us"] > last_t
            last_t = snap["t_us"]
            assert "tpunet_isend_nbytes" in snap["exposition"]
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_counter_timeseries_sampler(tmp_path):
    run_spawn_workers(_ts_worker, 1, extra_args=(str(tmp_path),))
