"""Fault paths: dead peers, connect retry, and error surfacing into JAX.

The reference's failure model was 108 unwrap-panics and silent hangs
(SURVEY §5, reference nthread:396-401); these tests pin the build's
contract instead: a peer dying mid-collective produces a bounded, typed
error on the survivors — including through the io_callback seam into a
jitted program — and transient rendezvous failures retry with backoff.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from conftest import free_port  # noqa: E402


def _bound_death_detection() -> None:
    """Make peer-death verdicts deterministic under load (the documented
    PR 10/11 flake): a SIGKILLed peer's RST can arrive arbitrarily late on
    a loaded box, and a survivor blocked in recv would sit the full 120s
    test budget waiting for it. Arm the RST-independent detectors the
    failure model already ships — the progress watchdog (zero bytes moved
    for a window -> typed ProgressTimeoutError, classified like a dead
    peer) and short TCP keepalive — in the WORKER processes, before any
    engine exists. The verdict is then bounded at ~20s whether or not the
    kernel ever delivers the RST; which typed error wins the race is
    deliberately unasserted (both are the contract)."""
    os.environ.setdefault("TPUNET_PROGRESS_TIMEOUT_MS", "20000")
    os.environ.setdefault("TPUNET_KEEPALIVE_IDLE_S", "5")
    os.environ.setdefault("TPUNET_KEEPALIVE_INTVL_S", "2")
    os.environ.setdefault("TPUNET_KEEPALIVE_CNT", "3")


def _victim(rank: int, world: int, port: int, q) -> None:
    # Rank 1 starts an allreduce and is SIGKILLed by the parent mid-flight.
    _bound_death_detection()
    from tpunet.collectives import Communicator

    comm = Communicator(f"127.0.0.1:{port}", rank, world)
    comm.barrier()
    q.put((rank, "ready"))
    arr = np.ones((64 << 20) // 4, np.float32)  # 64 MiB: long enough to die in
    while True:  # loop until killed
        comm.all_reduce(arr)


def _survivor(rank: int, world: int, port: int, q) -> None:
    try:
        _bound_death_detection()
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        comm.barrier()
        q.put((rank, "ready"))
        arr = np.ones((64 << 20) // 4, np.float32)
        t0 = time.perf_counter()
        try:
            while True:
                comm.all_reduce(arr)
                if time.perf_counter() - t0 > 120:
                    q.put((rank, "FAIL: no error after peer death"))
                    return
        except RuntimeError as e:
            dt = time.perf_counter() - t0
            q.put((rank, f"OK error after {dt:.1f}s: {str(e)[:80]}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def _prewiring_victim(rank: int, world: int, port: int, q) -> None:
    # Dies after Init but BEFORE the first allreduce — so the survivor's
    # lazy channel wiring (first collective) must fail with a typed error
    # when it connects to the dead peer's closed listener, never hang.
    # `q` is the victim's OWN queue, not shared with the survivor: a
    # SIGKILL landing between the feeder thread's pipe write and its
    # release of the queue's cross-process write lock would wedge every
    # other writer forever — and on a 1-core box the parent reliably wakes
    # from q.get (the pipe write) BEFORE that release, so kill-after-get
    # hits the window ~half the time. Dedicated queue = no shared lock.
    _bound_death_detection()
    from tpunet.collectives import Communicator

    comm = Communicator(f"127.0.0.1:{port}", rank, world)
    comm.barrier()
    q.put((rank, "ready"))
    time.sleep(600)  # parent SIGKILLs long before this


def _prewiring_survivor(rank: int, world: int, port: int, q, go) -> None:
    try:
        _bound_death_detection()
        os.environ["TPUNET_CONNECT_RETRY_MS"] = "3000"
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        comm.barrier()
        q.put((rank, "ready"))
        # Block until the parent confirms the victim is DEAD — a sleep here
        # races: wiring against a still-alive-but-about-to-die victim blocks
        # in accept (its backlog accepts our connect, no reply ever comes)
        # instead of exercising the connect-refused path this test pins.
        go.get(timeout=120)
        arr = np.ones(4096, np.float32)
        t0 = time.perf_counter()
        try:
            comm.iall_reduce(arr).wait()
            q.put((rank, "FAIL: no error from wiring against a dead peer"))
        except RuntimeError as e:
            q.put((rank, f"OK error after {time.perf_counter() - t0:.1f}s: "
                         f"{str(e)[:80]}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_peer_death_before_channel_wiring_errors_cleanly():
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    vq = ctx.Queue()  # victim-only: see _prewiring_victim on why not shared
    go = ctx.Queue()
    port = free_port()
    surv = ctx.Process(target=_prewiring_survivor, args=(0, 2, port, q, go))
    vict = ctx.Process(target=_prewiring_victim, args=(1, 2, port, vq))
    try:
        surv.start()
        vict.start()
        ready = {q.get(timeout=120)[0], vq.get(timeout=120)[0]}
        assert ready == {0, 1}
        vict.kill()  # before the survivor's first collective wires channels
        vict.join(timeout=30)
        go.put("victim dead")  # release the survivor into channel wiring
        rank, status = q.get(timeout=120)
        surv.join(timeout=30)
        assert rank == 0 and status.startswith("OK error"), status
    finally:
        # A startup failure must not leave the 600s-sleeping victim (or a
        # wedged survivor) blocking pytest exit.
        for p in (surv, vict):
            if p.pid is None:  # start() itself failed: nothing to reap
                continue
            if p.is_alive():
                p.kill()
            p.join(timeout=10)


def test_peer_death_mid_allreduce_errors_cleanly():
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    surv = ctx.Process(target=_survivor, args=(0, 2, port, q))
    vict = ctx.Process(target=_victim, args=(1, 2, port, q))
    surv.start()
    vict.start()
    ready = {q.get(timeout=120)[0], q.get(timeout=120)[0]}
    assert ready == {0, 1}
    time.sleep(0.3)  # let an allreduce get going
    vict.kill()  # SIGKILL: no goodbye, sockets RST on close
    rank, status = q.get(timeout=120)
    surv.join(timeout=30)
    vict.join(timeout=30)
    assert rank == 0 and status.startswith("OK error"), status


def _jax_survivor(rank: int, world: int, port: int, q) -> None:
    try:
        _bound_death_detection()
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import dcn_psum

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        fn = jax.jit(dcn_psum)
        x = jnp.ones((16 << 20) // 4, jnp.float32)  # 16 MiB
        np.asarray(fn(x))  # warm compile + one good sync
        q.put((rank, "ready"))
        t0 = time.perf_counter()
        try:
            while True:
                np.asarray(fn(x))
                if time.perf_counter() - t0 > 120:
                    q.put((rank, "FAIL: no exception after peer death"))
                    return
        except Exception as e:  # noqa: BLE001 — XlaRuntimeError wraps ours
            q.put((rank, f"OK raised {type(e).__name__} after "
                         f"{time.perf_counter() - t0:.1f}s"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def _jax_victim(rank: int, world: int, port: int, q) -> None:
    _bound_death_detection()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpunet import distributed
    from tpunet.interop import dcn_psum

    distributed.initialize(f"127.0.0.1:{port}", rank, world)
    fn = jax.jit(dcn_psum)
    x = jnp.ones((16 << 20) // 4, jnp.float32)
    np.asarray(fn(x))
    q.put((rank, "ready"))
    while True:
        np.asarray(fn(x))


def test_peer_death_surfaces_as_jax_exception():
    # The io_callback seam must turn the transport error into a Python
    # exception out of the jitted program — not a wedge.
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    surv = ctx.Process(target=_jax_survivor, args=(0, 2, port, q))
    vict = ctx.Process(target=_jax_victim, args=(1, 2, port, q))
    surv.start()
    vict.start()
    ready = set()
    for _ in range(2):
        ready.add(q.get(timeout=240)[0])
    assert ready == {0, 1}
    time.sleep(0.3)
    vict.kill()
    rank, status = q.get(timeout=240)
    surv.join(timeout=30)
    vict.join(timeout=30)
    assert rank == 0 and status.startswith("OK raised"), status


def _async_survivor(rank: int, world: int, port: int, q) -> None:
    # Nonblocking tickets in flight when the peer dies: the first failing
    # wait raises, the REST are dropped un-waited. The AsyncResult finalizer
    # must quiesce them so process exit doesn't free buffers under the
    # native worker thread (regression: exit-time SIGSEGV).
    try:
        _bound_death_detection()
        from tpunet.collectives import Communicator

        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        comm.barrier()
        q.put((rank, "ready"))
        arr = np.ones((32 << 20) // 4, np.float32)
        t0 = time.perf_counter()
        try:
            while time.perf_counter() - t0 < 120:
                rs = [comm.iall_reduce(arr) for _ in range(3)]
                for r in rs:
                    r.wait()
            q.put((rank, "FAIL: no error after peer death"))
        except RuntimeError:
            q.put((rank, "OK errored"))  # unwaited rs members drop here
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def _async_victim(rank: int, world: int, port: int, q) -> None:
    _bound_death_detection()
    from tpunet.collectives import Communicator

    comm = Communicator(f"127.0.0.1:{port}", rank, world)
    comm.barrier()
    q.put((rank, "ready"))
    arr = np.ones((32 << 20) // 4, np.float32)
    while True:
        comm.all_reduce(arr)


def test_peer_death_with_unwaited_async_tickets_exits_cleanly():
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    surv = ctx.Process(target=_async_survivor, args=(0, 2, port, q))
    vict = ctx.Process(target=_async_victim, args=(1, 2, port, q))
    surv.start()
    vict.start()
    ready = {q.get(timeout=120)[0], q.get(timeout=120)[0]}
    assert ready == {0, 1}
    time.sleep(0.5)
    vict.kill()
    rank, status = q.get(timeout=120)
    assert rank == 0 and status == "OK errored", status
    surv.join(timeout=60)
    vict.join(timeout=30)
    # The regression: survivor used to die with SIGSEGV (-11) at exit.
    assert surv.exitcode == 0, f"survivor exitcode {surv.exitcode}"


def _ipv4_handle(port: int) -> bytes:
    # sockaddr_in marshaled as the 64-byte wire handle: family (host order),
    # BE port, 127.0.0.1.
    return (struct.pack("=H", socket.AF_INET) + struct.pack("!H", port)
            + socket.inet_aton("127.0.0.1")).ljust(64, b"\0")


def test_connect_retries_until_listener_appears():
    # Nothing listens at connect() time; a plain acceptor shows up ~1s
    # later. The engine's backoff retry must bridge the gap.
    from tpunet.transport import Net

    port = free_port()
    accepted = {}

    def late_listener():
        time.sleep(1.0)
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))
        s.listen(16)
        conns = []
        s.settimeout(20)
        try:
            while True:
                c, _ = s.accept()
                conns.append(c)
                accepted["n"] = len(conns)
        except TimeoutError:
            pass
        finally:
            for c in conns:
                c.close()
            s.close()

    th = threading.Thread(target=late_listener, daemon=True)
    th.start()
    os.environ["TPUNET_CONNECT_RETRY_MS"] = "15000"
    try:
        with Net() as net:
            t0 = time.perf_counter()
            sc = net.connect(_ipv4_handle(port))
            dt = time.perf_counter() - t0
            assert dt >= 0.8, f"connected before the listener existed? {dt}"
            sc.close()
    finally:
        os.environ.pop("TPUNET_CONNECT_RETRY_MS", None)
    assert accepted.get("n", 0) >= 1


def test_connect_fails_cleanly_when_nothing_ever_listens():
    from tpunet.transport import Net

    os.environ["TPUNET_CONNECT_RETRY_MS"] = "1000"
    try:
        with Net() as net:
            t0 = time.perf_counter()
            with pytest.raises(RuntimeError, match="connect"):
                net.connect(_ipv4_handle(free_port()))
            assert time.perf_counter() - t0 < 10
    finally:
        os.environ.pop("TPUNET_CONNECT_RETRY_MS", None)
