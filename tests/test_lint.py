"""Invariant lint suite (tools/lint): live-tree gates + negative fixtures.

Two halves:
  * The live-tree tests run all four checkers against THIS repository and
    require zero violations — the same gate the CI analysis lane applies via
    ``python -m tools.lint``.
  * The negative-fixture tests synthesize minimal broken trees (an
    unregistered env var, a duplicated/misnamed metric family, a mismatched
    error code, a missing ctypes binding) and prove each checker actually
    FIRES on its defect class — a checker that cannot go red is decoration.

Plus the Config.from_env validation surface the env checker forced into
existence: the observability/wire-timeout knobs now raise ValueError naming
the offending variable (PR-1 convention) instead of flowing into the native
layer unchecked.
"""

from __future__ import annotations

import os
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.lint import CHECKERS, run_all  # noqa: E402
from tools.lint.cabi import check_c_abi  # noqa: E402
from tools.lint.envvars import check_env_registry  # noqa: E402
from tools.lint.errcodes import check_error_codes  # noqa: E402
from tools.lint.metricsreg import check_metric_registry  # noqa: E402


# ---------------------------------------------------------------------------
# Live tree: every invariant must hold on the repository as committed.


@pytest.mark.parametrize("name", sorted(CHECKERS))
def test_live_tree_is_clean(name):
    violations = CHECKERS[name](REPO)
    assert violations == [], (
        f"checker {name} found drift in the live tree:\n  " + "\n  ".join(violations)
    )


def test_run_all_covers_every_checker():
    results = run_all(REPO)
    assert set(results) == set(CHECKERS)


# ---------------------------------------------------------------------------
# Negative fixtures: each checker must fire on its seeded defect.


def _write(root: Path, rel: str, content: str) -> None:
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(content))


def test_env_checker_fires_on_unregistered_var(tmp_path):
    _write(tmp_path, "cpp/src/x.cc", '''
        #include "tpunet/utils.h"
        uint64_t f() { return GetEnvU64("TPUNET_FAKE_KNOB", 1); }
    ''')
    _write(tmp_path, "tpunet/config.py", '''
        # registry mentions only TPUNET_REAL_KNOB
        REAL = "TPUNET_REAL_KNOB"
    ''')
    _write(tmp_path, "docs/DESIGN.md", "`TPUNET_REAL_KNOB` is documented here.\n")
    violations = check_env_registry(tmp_path)
    assert any("TPUNET_FAKE_KNOB" in v and "neither registered" in v for v in violations)
    # ...and the same var is also flagged as undocumented.
    assert any("TPUNET_FAKE_KNOB" in v and "docs" in v for v in violations)


def test_env_checker_fires_on_undocumented_registered_var(tmp_path):
    _write(tmp_path, "tpunet/config.py", 'KNOB = "TPUNET_DOCLESS_KNOB"\n')
    _write(tmp_path, "docs/DESIGN.md", "nothing to see\n")
    violations = check_env_registry(tmp_path)
    assert any("TPUNET_DOCLESS_KNOB" in v and "docs" in v for v in violations)


def test_env_checker_ignores_comment_mentions(tmp_path):
    _write(tmp_path, "cpp/src/x.cc", '''
        // A comment naming GetEnv("TPUNET_ONLY_IN_COMMENT") must not count
        int f() { return 0; }
    ''')
    assert check_env_registry(tmp_path) == []


_METRICS_FIXTURE = '''
    #include "tpunet/telemetry.h"
    void emit_all() {
      family("tpunet_thing_total", "counter", "a thing");
      family("tpunet_thing_total", "counter", "declared twice");
      family("tpunet_widget", "gauge", "no unit suffix");
      emit("tpunet_thing_total{rank=\\"%lld\\"} %llu\\n", rank, v);
      emit("tpunet_thing_total{rank=\\"%lld\\",dir=\\"tx\\"} %llu\\n", rank, v);
      emit("tpunet_ghost_total{rank=\\"%lld\\"} %llu\\n", rank, v);
    }
'''


def test_metric_checker_fires_on_each_defect_class(tmp_path):
    _write(tmp_path, "cpp/src/metrics.cc", _METRICS_FIXTURE)
    _write(tmp_path, "tpunet/telemetry.py", 'NAME = "tpunet_missing_total"\n')
    violations = check_metric_registry(tmp_path)
    joined = "\n".join(violations)
    assert "tpunet_thing_total is registered more than once" in joined
    assert "tpunet_widget has no unit suffix" in joined
    assert "tpunet_thing_total emits inconsistent label sets" in joined
    assert "tpunet_ghost_total is emitted in metrics.cc but never registered" in joined
    assert "tpunet_missing_total which does not exist" in joined


def test_errcode_checker_fires_on_orphans_and_mismatch(tmp_path):
    _write(tmp_path, "cpp/include/tpunet/c_api.h", '''
        #define TPUNET_OK 0
        #define TPUNET_ERR_INNER -3
        #define TPUNET_ERR_FROB -7
    ''')
    _write(tmp_path, "tpunet/_native.py", '''
        TPUNET_OK = 0
        TPUNET_ERR_INNER = -99
        TPUNET_ERR_PHANTOM = -42
        _TYPED_ERRORS = {}
    ''')
    violations = check_error_codes(tmp_path)
    joined = "\n".join(violations)
    assert "TPUNET_ERR_FROB" in joined and "no constant" in joined      # C-only orphan
    assert "TPUNET_ERR_PHANTOM" in joined and "not in" in joined        # Python-only orphan
    assert "TPUNET_ERR_INNER value mismatch" in joined                  # value drift
    assert "TPUNET_ERR_FROB" in joined and "typed exception" in joined  # missing typed class


def test_cabi_checker_fires_on_missing_definition_and_binding(tmp_path):
    _write(tmp_path, "cpp/include/tpunet/c_api.h", '''
        int32_t tpunet_c_frobnicate(void);
        int32_t tpunet_c_real(void);
    ''')
    _write(tmp_path, "cpp/src/c_api.cc", '''
        int32_t tpunet_c_real(void) { return 0; }
        int32_t tpunet_c_secret(void) { return 0; }
    ''')
    _write(tmp_path, "tpunet/_native.py", '''
        lib.tpunet_c_real.argtypes = []
        lib.tpunet_c_unbound_ghost.argtypes = []
    ''')
    violations = check_c_abi(tmp_path)
    joined = "\n".join(violations)
    assert "tpunet_c_frobnicate is declared in c_api.h but has no definition" in joined
    assert "tpunet_c_secret is defined in cpp/src but not declared" in joined
    assert "tpunet_c_frobnicate is declared in c_api.h but has no ctypes binding" in joined
    assert "lib.tpunet_c_unbound_ghost" in joined


def test_cabi_checker_does_not_mistake_calls_for_definitions(tmp_path):
    _write(tmp_path, "cpp/include/tpunet/c_api.h", "int32_t tpunet_c_only_called(void);\n")
    _write(tmp_path, "cpp/src/shim.cc", '''
        void consumer() { (void)tpunet_c_only_called(); }
    ''')
    violations = check_c_abi(tmp_path)
    assert any("tpunet_c_only_called is declared in c_api.h but has no definition" in v
               for v in violations)


# ---------------------------------------------------------------------------
# Config.from_env validation for the vars the env checker surfaced as
# previously unvalidated (same ValueError-naming-the-var convention as the
# PR-1/PR-3 validators).


def _from_env(**env):
    from tpunet.config import Config

    saved = {}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        return Config.from_env()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize(
    "var,bad",
    [
        ("TPUNET_TCPINFO_INTERVAL_MS", "-1"),
        ("TPUNET_FAIRNESS_WINDOW_MS", "-5"),
        ("TPUNET_STRAGGLER_FACTOR", "-2"),
        ("TPUNET_STRAGGLER_MIN_RTT_US", "-1"),
        ("TPUNET_METRICS_INTERVAL_MS", "0"),
        ("TPUNET_HANDSHAKE_TIMEOUT_MS", "0"),
        ("TPUNET_BOOTSTRAP_TIMEOUT_MS", "0"),
        ("TPUNET_RING_CHUNKSIZE", "0"),
        ("TPUNET_ASYNC_CHANNELS", "0"),
    ],
)
def test_config_rejects_out_of_range_naming_the_var(var, bad):
    with pytest.raises(ValueError, match=var):
        _from_env(**{var: bad})


def test_config_accepts_defaults_and_zero_disables():
    cfg = _from_env(
        TPUNET_TCPINFO_INTERVAL_MS="0",   # 0 = sampler off, legal
        TPUNET_STRAGGLER_FACTOR="0",      # 0 = detector off, legal
        TPUNET_DEBUG="1",
        TPUNET_REDUCE_SIMD="0",
        TPUNET_FFI_COLLECTIVES="0",
    )
    assert cfg.tcpinfo_interval_ms == 0
    assert cfg.straggler_factor == 0
    assert cfg.debug is True
    assert cfg.reduce_simd is False
    assert cfg.ffi_collectives is False


def test_config_new_fields_defaults():
    cfg = _from_env()
    assert cfg.tcpinfo_interval_ms == 100
    assert cfg.fairness_window_ms == 1000
    assert cfg.straggler_factor == 3
    assert cfg.straggler_min_rtt_us == 1000
    assert cfg.metrics_interval_ms == 1000
    assert cfg.handshake_timeout_ms == 10_000
    assert cfg.bootstrap_timeout_ms == 120_000
    assert cfg.debug is False
    assert cfg.reduce_simd is True
    assert cfg.ffi_collectives is True
