"""Smoke test for the VGG synthetic img/s benchmark (reference headline)."""

from benchmarks.vgg_synthetic import _parse, run_benchmark


def test_single_process_tiny():
    args = _parse(
        [
            "--width-mult", "0.0625", "--image-size", "32", "--classes", "16",
            "--batch-size", "4", "--iters", "2", "--batches-per-iter", "1",
            "--warmup", "1", "--no-bf16",
        ]
    )
    rates = run_benchmark(args, emit=lambda *_: None)
    assert len(rates) == 2
    assert all(r > 0 for r in rates)
