"""Loopback transport tests over the C ABI + ctypes binding.

This is the multi-process harness the reference never had (SURVEY §4 gap):
two real OS processes on 127.0.0.1 running listen/connect/accept +
isend/irecv size sweeps with payload verification (BASELINE config 1).
"""

from __future__ import annotations

import gc
import multiprocessing as mp
import os

import numpy as np
import pytest

# Sizes: 8 B .. 16 MB (powers of 4) + oddball non-aligned sizes; the full
# 8B-128MB x2 sweep lives in the bench CLI.
SWEEP_SIZES = [0, 8, 128, 2048, 32768, 524288, 1 << 20, (1 << 24) + 13, 777]


def _pattern(size: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=size, dtype=np.uint8)


def _receiver_proc(conn, nstreams: int) -> None:
    os.environ["TPUNET_NSTREAMS"] = str(nstreams)
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(listen.handle)
    rc = listen.accept()
    ok = True
    for i, size in enumerate(SWEEP_SIZES):
        buf = np.zeros(size + 64, dtype=np.uint8)  # oversized on purpose
        got = rc.recv(buf, timeout=60)
        expect = _pattern(size, seed=1000 + i)
        if got != size or not np.array_equal(buf[:size], expect):
            ok = False
            break
    conn.send("OK" if ok else "CORRUPT")
    rc.close()
    listen.close()
    net.close()


@pytest.mark.parametrize("nstreams", [1, 2, 4])
def test_loopback_sweep(nstreams, monkeypatch):
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_receiver_proc, args=(child, nstreams))
    proc.start()
    try:
        handle = parent.recv()
        # monkeypatch (not a bare os.environ write): a leaked TPUNET_NSTREAMS
        # shadows the BAGUA_NET_NSTREAMS fallback that test_chaos's config
        # validation cases exercise — env hygiene IS the test contract here
        # (caught by running the suites in non-alphabetical order).
        monkeypatch.setenv("TPUNET_NSTREAMS", str(nstreams))
        from tpunet.transport import Net

        net = Net()
        sc = net.connect(handle)
        for i, size in enumerate(SWEEP_SIZES):
            data = _pattern(size, seed=1000 + i)
            sent = sc.send(data, timeout=60)
            assert sent == size
        assert parent.recv() == "OK"
        sc.close()
        net.close()
    finally:
        proc.join(timeout=30)
        if proc.is_alive():
            proc.kill()
            pytest.fail("receiver process hung")
    assert proc.exitcode == 0


def _pin_receiver(conn) -> None:
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(listen.handle)
    rc = listen.accept()
    buf = np.zeros(1 << 22, dtype=np.uint8)
    got = rc.recv(buf, timeout=60)
    expect = _pattern(1 << 22, seed=7)
    conn.send("OK" if (got == len(expect) and np.array_equal(buf, expect)) else "CORRUPT")
    rc.close()
    listen.close()
    net.close()


def test_request_pins_buffer_until_done():
    """The Request must keep the send buffer alive: drop the caller's only
    reference right after isend and force GC while the transfer runs."""
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_pin_receiver, args=(child,))
    proc.start()
    try:
        handle = parent.recv()
        from tpunet.transport import Net

        net = Net()
        sc = net.connect(handle)
        data = _pattern(1 << 22, seed=7)
        req = sc.isend(data)
        del data  # request's pin is now the only live reference
        gc.collect()
        req.wait(timeout=60)
        assert parent.recv() == "OK"
        sc.close()
        net.close()
    finally:
        proc.join(timeout=30)
        if proc.is_alive():
            proc.kill()
            pytest.fail("receiver process hung")
    assert proc.exitcode == 0


def _fair_receiver(conn, nstreams: int, engine: str) -> None:
    os.environ["TPUNET_NSTREAMS"] = str(nstreams)
    os.environ["TPUNET_IMPLEMENT"] = engine
    import numpy as np

    from tpunet import telemetry
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(listen.handle)
    rc = listen.accept()
    nmsgs = 8 * nstreams
    for _ in range(nmsgs):
        buf = np.zeros(4096, dtype=np.uint8)
        assert rc.recv(buf, timeout=60) == 4096
    m = telemetry.metrics()
    conn.send(m.get("tpunet_stream_rx_bytes", {}))
    rc.close()
    listen.close()
    net.close()


def _fair_sender(conn, nstreams: int, engine: str) -> None:
    os.environ["TPUNET_NSTREAMS"] = str(nstreams)
    os.environ["TPUNET_IMPLEMENT"] = engine
    import numpy as np

    from tpunet import telemetry
    from tpunet.transport import Net

    handle = conn.recv()
    net = Net()
    sc = net.connect(handle)
    nmsgs = 8 * nstreams
    data = np.arange(4096, dtype=np.uint8) % 251
    for _ in range(nmsgs):
        assert sc.send(data, timeout=60) == 4096
    m = telemetry.metrics()
    conn.send(m.get("tpunet_stream_tx_bytes", {}))
    sc.close()
    net.close()


@pytest.mark.parametrize("engine", ["BASIC", "EPOLL"])
def test_single_chunk_messages_rotate_streams(engine):
    """The fairness property that is the reference's whole point (SURVEY hard
    part 4): the rotating round-robin cursor persists ACROSS messages, so
    single-chunk messages spread evenly over all data streams instead of
    pinning stream 0 (the reference TOKIO engine's bias, tokio:392-404).
    Observed end-to-end via the per-stream byte counters."""
    nstreams = 4
    ctx = mp.get_context("spawn")
    r_parent, r_child = ctx.Pipe()
    s_parent, s_child = ctx.Pipe()
    rproc = ctx.Process(target=_fair_receiver, args=(r_child, nstreams, engine))
    sproc = ctx.Process(target=_fair_sender, args=(s_child, nstreams, engine))
    rproc.start()
    sproc.start()
    try:
        handle = r_parent.recv()
        s_parent.send(handle)
        tx = s_parent.recv()
        rx = r_parent.recv()
    finally:
        for p in (rproc, sproc):
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
                pytest.fail("fairness worker hung")
    assert rproc.exitcode == 0 and sproc.exitcode == 0
    # 8*nstreams single-chunk (4 KiB < min_chunksize) messages must land
    # 8 per stream on every one of the nstreams streams — exactly.
    per_stream = 8 * 4096
    for side, stats in (("tx", tx), ("rx", rx)):
        assert len(stats) == nstreams, f"{side}: {stats}"
        for labels, value in stats.items():
            assert value == per_stream, f"{side} uneven: {stats}"


def test_devices_and_properties():
    from tpunet.transport import Net

    with Net() as net:
        n = net.devices()
        assert n >= 1
        props = net.properties(0)
        assert props["name"]
        assert props["speed_mbps"] > 0
        assert props["max_comms"] == 65536
        assert props["ptr_support"] == 1


def test_connect_bad_handle_fails():
    from tpunet import _native
    from tpunet.transport import Net

    with Net() as net:
        # AF_INET sockaddr pointing at a port nothing listens on.
        import socket
        import struct

        sa = struct.pack("!HHI", socket.AF_INET, 1, 0)  # wrong byte order on purpose
        handle = (sa + b"\x00" * 64)[:64]
        with pytest.raises(_native.NativeError):
            net.connect(handle)


def test_double_close_rejected():
    from tpunet import _native
    from tpunet.transport import Net

    with Net() as net:
        listen = net.listen(0)
        listen.close()
        with pytest.raises(_native.NativeError):
            listen.close()


def _epoll_receiver(conn, sizes, seed_base: int, env: dict) -> None:
    """Shared EPOLL-engine receiver: verify `sizes` messages against their
    posted-order patterns under the given env (one helper for the inline
    on/off sweep and the pipelined-ordering stress test)."""
    os.environ["TPUNET_IMPLEMENT"] = "EPOLL"
    os.environ.update(env)
    from tpunet.transport import Net

    net = Net()
    listen = net.listen(0)
    conn.send(listen.handle)
    rc = listen.accept()
    ok = "OK"
    for i, size in enumerate(sizes):
        buf = np.zeros(size + 32, dtype=np.uint8)
        got = rc.recv(buf, timeout=120)
        expect = _pattern(size, seed=seed_base + i)
        if got != size or not np.array_equal(buf[:size], expect):
            ok = f"CORRUPT at {i}"
            break
    conn.send(ok)
    rc.close()
    listen.close()
    net.close()


INLINE_SWEEP_SIZES = [0, 8, 4096, 1 << 20, (1 << 22) + 5]


@pytest.mark.parametrize("inline", ["0", "1"])
def test_epoll_inline_on_and_off(inline, monkeypatch):
    """The EPOLL inline fast path AND its escape hatch
    (TPUNET_EPOLL_INLINE=0, the pure event-loop path) both move a size
    sweep correctly — inline-off is the documented fallback for inline
    bugs, so it gets CI coverage too."""
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(
        target=_epoll_receiver,
        args=(child, INLINE_SWEEP_SIZES, 4000,
              {"TPUNET_EPOLL_INLINE": inline}))
    proc.start()
    try:
        handle = parent.recv()
        monkeypatch.setenv("TPUNET_IMPLEMENT", "EPOLL")
        monkeypatch.setenv("TPUNET_EPOLL_INLINE", inline)
        from tpunet.transport import Net

        net = Net()
        sc = net.connect(handle)
        for i, size in enumerate(INLINE_SWEEP_SIZES):
            assert sc.send(_pattern(size, seed=4000 + i), timeout=60) == size
        assert parent.recv() == "OK"
        sc.close()
        net.close()
    finally:
        proc.join(timeout=30)
        if proc.is_alive():
            proc.kill()
            pytest.fail("receiver process hung")
    assert proc.exitcode == 0


def test_epoll_inline_queued_ordering_under_pipeline(monkeypatch):
    """Hammer the inline<->queued transition: a deep pipeline of
    random-size isends means some messages start inline (comm idle), some
    queue behind in-flight ones, and some start inline again after a
    drain. Ctrl-frame order MUST match post order throughout — the
    receiver verifies every payload against its posted sequence."""
    rng = np.random.default_rng(42)
    sizes = [int(s) for s in rng.integers(0, 1 << 18, size=60)]
    sizes[7] = 0  # zero-byte in the middle of the stream
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_epoll_receiver, args=(child, sizes, 9000, {}))
    proc.start()
    try:
        handle = parent.recv()
        monkeypatch.setenv("TPUNET_IMPLEMENT", "EPOLL")
        from tpunet.transport import Net

        net = Net()
        sc = net.connect(handle)
        pending = []
        for i, size in enumerate(sizes):
            pending.append(sc.isend(_pattern(size, seed=9000 + i)))
            if i % 9 == 8:  # periodic drain: the NEXT send goes inline again
                for r in pending:
                    r.wait(timeout=120)
                pending.clear()
        for r in pending:
            r.wait(timeout=120)
        assert parent.recv() == "OK"
        sc.close()
        net.close()
    finally:
        proc.join(timeout=30)
        if proc.is_alive():
            proc.kill()
            pytest.fail("receiver process hung")
    assert proc.exitcode == 0
