"""Zigzag (striped) causal ring attention: exact parity with full causal
attention after layout round-trip, gradients included, across world sizes."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpunet.ops import attention_reference
from tpunet.parallel import (from_zigzag, make_named_mesh, to_zigzag,
                             zigzag_positions, zigzag_self_attention)

B, H, DH = 2, 4, 8


def _qkv(key, seq):
    ks = jax.random.split(key, 3)
    shape = (B, seq, H, DH)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_layout_roundtrip():
    x = jnp.arange(2 * 32 * 3).reshape(2, 32, 3)
    for w in (1, 2, 4, 8):
        np.testing.assert_array_equal(
            np.asarray(from_zigzag(to_zigzag(x, w), w)), np.asarray(x)
        )


def test_zigzag_positions_match_layout():
    # zigzag_positions(i) must name exactly the global rows device i holds
    # after to_zigzag + contiguous sharding.
    w, seq = 4, 32
    rows = jnp.arange(seq)[None, :, None]  # (1, seq, 1)
    zz = np.asarray(to_zigzag(rows, w))[0, :, 0]
    local = seq // w
    for i in range(w):
        got = np.asarray(zigzag_positions(w, seq, i))
        np.testing.assert_array_equal(got, zz[i * local:(i + 1) * local])


@pytest.mark.parametrize("w", [1, 2, 4])
def test_matches_full_causal_attention(w):
    mesh = make_named_mesh({"sp": w})
    seq = 8 * 2 * w  # chunks of 8
    q, k, v = _qkv(jax.random.PRNGKey(0), seq)
    want = attention_reference(q, k, v, causal=True)

    qz, kz, vz = (to_zigzag(x, w) for x in (q, k, v))
    out = zigzag_self_attention(qz, kz, vz, mesh, dp_axis=None, sp_axis="sp")
    got = from_zigzag(out, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_grad_matches_full_causal():
    w = 4
    mesh = make_named_mesh({"sp": w})
    seq = 4 * 2 * w
    q, k, v = _qkv(jax.random.PRNGKey(3), seq)

    def loss_zz(q, k, v):
        qz, kz, vz = (to_zigzag(x, w) for x in (q, k, v))
        out = zigzag_self_attention(qz, kz, vz, mesh, dp_axis=None, sp_axis="sp")
        return jnp.sum(from_zigzag(out, w) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_transformer_zigzag_matches_reference():
    # Same params, tokens fed in zigzag order to the zigzag model: logits
    # must be the zigzag permutation of the reference model's logits (every
    # non-attention layer is permutation-equivariant along seq; rotary uses
    # the explicit natural positions).
    from tpunet.models import Transformer

    w = 4
    mesh = make_named_mesh({"sp": w})
    seq = 4 * 2 * w
    kw = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
              compute_dtype=jnp.float32)
    ref = Transformer(attn_impl="reference", **kw)
    zz = Transformer(attn_impl="zigzag", mesh=mesh, sp_axis="sp",
                     dp_axis=None, **kw)

    toks = jax.random.randint(jax.random.PRNGKey(7), (2, seq), 0, 64)
    params = ref.init(jax.random.PRNGKey(0), toks)["params"]

    logits_ref = ref.apply({"params": params}, toks)
    toks_zz = to_zigzag(toks, w)
    logits_zz = zz.apply({"params": params}, toks_zz)
    np.testing.assert_allclose(
        np.asarray(logits_zz), np.asarray(to_zigzag(logits_ref, w)),
        rtol=3e-5, atol=3e-5,
    )


def test_zigzag_with_dp_axis():
    # dp x sp mesh: batch sharded over dp while the zigzag ring runs over sp.
    w = 4
    mesh = make_named_mesh({"dp": 2, "sp": w})
    seq = 4 * 2 * w
    q, k, v = _qkv(jax.random.PRNGKey(9), seq)
    want = attention_reference(q, k, v, causal=True)
    qz, kz, vz = (to_zigzag(x, w) for x in (q, k, v))
    out = zigzag_self_attention(qz, kz, vz, mesh, dp_axis="dp", sp_axis="sp")
    np.testing.assert_allclose(
        np.asarray(from_zigzag(out, w)), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_rejects_odd_shard():
    mesh = make_named_mesh({"sp": 2})
    q, k, v = _qkv(jax.random.PRNGKey(1), 6)  # 3 per shard: not a pair
    with pytest.raises(ValueError, match="even"):
        zigzag_self_attention(q, k, v, mesh, dp_axis=None, sp_axis="sp")
