"""Smoke + property test for the stream-fairness benchmark."""

from benchmarks.fairness import jain


def test_jain_index_math():
    assert jain([100, 100, 100, 100]) == 1.0
    assert abs(jain([400, 0, 0, 0]) - 0.25) < 1e-9
    assert jain([]) == 0.0
    assert jain([0, 0]) == 0.0


def test_fairness_end_to_end():
    # Small run: the rotating round-robin cursor must spread single-chunk
    # messages near-perfectly across streams (the reference's core claim).
    # pytest's capture swallows the table output.
    from benchmarks.fairness import main

    j = main(["--nstreams", "4", "--messages", "64", "--size", "1024"])
    assert j > 0.99, f"fairness index {j} — striping is not rotating"


def test_fairness_ring_world_4():
    # W>2 ring: all ranks stripe concurrently; fairness must hold under
    # contention on every rank (worst-rank Jain is the reported index).
    from benchmarks.fairness import main

    j = main(["--world", "4", "--nstreams", "4", "--messages", "200",
              "--size", "4096"])
    assert j > 0.99, f"worst-rank fairness {j} under 4-ring contention"


def test_busbw_alltoall_smoke():
    # The alltoall op moves correct blocks under both impls: the sweep
    # worker asserts block provenance (block j carries rank j's value), a
    # failing rank makes main() sys.exit(1). The table itself prints in
    # the rank-0 child, so "no SystemExit" IS the assertion here.
    import os
    import sys
    import unittest.mock as mock

    from benchmarks.busbw_sweep import main

    for impl in ("pairwise", "ring"):
        os.environ["TPUNET_A2A"] = impl
        try:
            with mock.patch.object(sys, "argv", [
                    "busbw_sweep", "--op", "alltoall", "-n", "3",
                    "-b", "64K", "-e", "64K", "--iters", "1"]):
                main()
        finally:
            os.environ.pop("TPUNET_A2A", None)
