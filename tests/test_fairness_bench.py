"""Smoke + property test for the stream-fairness benchmark."""

from benchmarks.fairness import jain


def test_jain_index_math():
    assert jain([100, 100, 100, 100]) == 1.0
    assert abs(jain([400, 0, 0, 0]) - 0.25) < 1e-9
    assert jain([]) == 0.0
    assert jain([0, 0]) == 0.0


def test_fairness_end_to_end():
    # Small run: the rotating round-robin cursor must spread single-chunk
    # messages near-perfectly across streams (the reference's core claim).
    # pytest's capture swallows the table output.
    from benchmarks.fairness import main

    j = main(["--nstreams", "4", "--messages", "64", "--size", "1024"])
    assert j > 0.99, f"fairness index {j} — striping is not rotating"
