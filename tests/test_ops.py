"""Numerics tests for tpunet.ops (Pallas kernels, interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.ops import attention_reference, flash_attention


def _qkv(rng, b, s, h, d, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 128, 2, 16)
    out = flash_attention(q, k, v, causal, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 64, 4, 32, jnp.bfloat16)
    out = flash_attention(q, k, v, True, block_q=16, block_k=16)
    ref = attention_reference(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_uneven_falls_back():
    # 100 doesn't tile by 32 — must silently take the reference path.
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 100, 1, 8)
    out = flash_attention(q, k, v, False, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 64, 2, 8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("bq,bk", [(32, 16), (16, 16), (64, 32)])
def test_flash_grad_unequal_blocks(bq, bk):
    # The dkv kernel's causal q-block lower bound must be right for every
    # block_q/block_k ratio the fwd accepts (block_q % block_k == 0).
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 64, 2, 8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, bq, bk) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_flash_causal_cross_attention_falls_back():
    # sq != sk under causal would run the kernel's k-loop out of bounds;
    # must take the reference path and stay correct.
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(ks[0], (1, 128, 2, 8))
    k = jax.random.normal(ks[1], (1, 64, 2, 8))
    v = jax.random.normal(ks[2], (1, 64, 2, 8))
    out = flash_attention(q, k, v, True, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_grad_ragged_fallback():
    # 100 doesn't tile: the VJP must take the einsum fallback and still match.
    q, k, v = _qkv(jax.random.PRNGKey(6), 1, 100, 1, 8)
    gf = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True, 32, 32) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(attention_reference(q, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-5)


def test_flash_grad_bf16_under_jit():
    q, k, v = _qkv(jax.random.PRNGKey(7), 1, 64, 2, 16, jnp.bfloat16)

    @jax.jit
    def g(q, k, v):
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, 32, 32).astype(jnp.float32) ** 2
        ), argnums=(0, 1, 2))(q, k, v)

    gf = g(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        attention_reference(q, k, v, True).astype(jnp.float32) ** 2
    ), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-1, rtol=1e-1
        )


def test_flash_under_jit():
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 64, 1, 16)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 32, 32))
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(attention_reference(q, k, v, True)),
        atol=2e-5, rtol=2e-5,
    )


def _gqa_ref(q, k, v, causal):
    group = q.shape[2] // k.shape[2]
    return attention_reference(
        q, jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2), causal
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_repeated_reference(causal):
    rng = jax.random.split(jax.random.PRNGKey(10), 3)
    q = jax.random.normal(rng[0], (2, 128, 8, 16))
    k = jax.random.normal(rng[1], (2, 128, 2, 16))
    v = jax.random.normal(rng[2], (2, 128, 2, 16))
    out = flash_attention(q, k, v, causal, block_q=32, block_k=32)
    ref = _gqa_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_grads_match_repeated_reference(causal):
    rng = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(rng[0], (1, 64, 4, 8))
    k = jax.random.normal(rng[1], (1, 64, 2, 8))
    v = jax.random.normal(rng[2], (1, 64, 2, 8))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_gqa_ref(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == k.shape and gf[2].shape == v.shape
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_gqa_ragged_falls_back():
    rng = jax.random.split(jax.random.PRNGKey(12), 3)
    q = jax.random.normal(rng[0], (1, 100, 4, 8))  # 100: no tiling
    k = jax.random.normal(rng[1], (1, 100, 2, 8))
    v = jax.random.normal(rng[2], (1, 100, 2, 8))

    out = flash_attention(q, k, v, True, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_gqa_ref(q, k, v, True)), atol=2e-5, rtol=2e-5
    )
    g = jax.grad(lambda k: jnp.sum(flash_attention(q, k, v, True, 32, 32)))(k)
    gr = jax.grad(lambda k: jnp.sum(_gqa_ref(q, k, v, True)))(k)
    assert g.shape == k.shape
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=5e-5, rtol=5e-5)


def test_flash_rejects_indivisible_heads():
    rng = jax.random.split(jax.random.PRNGKey(13), 3)
    q = jax.random.normal(rng[0], (1, 64, 4, 8))
    k = jax.random.normal(rng[1], (1, 64, 3, 8))
    v = jax.random.normal(rng[2], (1, 64, 3, 8))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, True)


@pytest.mark.parametrize("window", [1, 7, 32, 64, 200])
def test_flash_window_matches_reference(window):
    q, k, v = _qkv(jax.random.PRNGKey(20), 1, 128, 2, 16)
    out = flash_attention(q, k, v, True, 32, 32, window=window)
    ref = attention_reference(q, k, v, True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [16, 48])
def test_flash_window_grads_match_reference(window):
    q, k, v = _qkv(jax.random.PRNGKey(21), 1, 128, 2, 8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 32, 32,
                                       window=window) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, True, window=window) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_window_with_gqa():
    rng = jax.random.split(jax.random.PRNGKey(22), 3)
    q = jax.random.normal(rng[0], (1, 128, 4, 8))
    k = jax.random.normal(rng[1], (1, 128, 2, 8))
    v = jax.random.normal(rng[2], (1, 128, 2, 8))
    out = flash_attention(q, k, v, True, 32, 32, window=40)
    ref = attention_reference(q, jnp.repeat(k, 2, axis=2),
                              jnp.repeat(v, 2, axis=2), True, window=40)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gk = jax.grad(lambda k: jnp.sum(
        flash_attention(q, k, v, True, 32, 32, window=40)))(k)
    gkr = jax.grad(lambda k: jnp.sum(attention_reference(
        q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2), True,
        window=40)))(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gkr),
                               atol=5e-5, rtol=5e-5)


def test_flash_window_requires_causal():
    q, k, v = _qkv(jax.random.PRNGKey(23), 1, 64, 1, 8)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, False, window=8)
