"""fit() driver tests: schedule counting, checkpoint cadence, exact resume,
data-pipeline composition."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.data import TokenDataset, pack_documents, token_batches
from tpunet.models import Transformer
from tpunet.train import CheckpointManager, create_train_state, fit, make_train_step


@pytest.fixture()
def setup(tmp_path):
    path = str(tmp_path / "toks.bin")
    rng = np.random.default_rng(0)
    pack_documents(iter([rng.integers(0, 64, 600).tolist()]), path, vocab=64)
    ds = TokenDataset(path, seq=16, vocab=64)
    model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                        compute_dtype=jnp.float32)
    tx = optax.adamw(1e-3)
    first, _ = next(token_batches(ds, batch=4, seed=0))
    state, _ = create_train_state(model, jax.random.PRNGKey(0),
                                  jnp.asarray(first), tx)
    step = make_train_step(model, tx, donate=False)
    return ds, state, step


def _batches(ds):
    return token_batches(ds, batch=4, seed=0)


def test_fit_runs_schedule_and_checkpoints(setup, tmp_path):
    ds, state, step = setup
    out = fit(state, step, _batches(ds), steps=7,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3)
    assert int(out.step) == 7
    mgr = CheckpointManager(str(tmp_path / "ck"))
    try:
        # Saves at 3, 6 and the forced final at 7.
        assert mgr.latest_step() == 7
        assert 3 in mgr.all_steps() and 6 in mgr.all_steps()
    finally:
        mgr.close()


def test_fit_resume_counts_total_schedule_not_additional(setup, tmp_path):
    ds, state, step = setup
    ck = str(tmp_path / "ck")
    mid = fit(state, step, _batches(ds), steps=4, checkpoint_dir=ck)
    assert int(mid.step) == 4
    # Re-enter with the SAME schedule: resumes at 4, runs only 4..6.
    out = fit(state, step, _batches(ds), steps=6, checkpoint_dir=ck)
    assert int(out.step) == 6


def test_fit_resume_trajectory_matches_uninterrupted(setup, tmp_path):
    ds, state, step = setup
    straight = fit(state, step, _batches(ds), steps=6)
    ck = str(tmp_path / "ck2")
    fit(state, step, _batches(ds), steps=3, checkpoint_dir=ck)
    # skip_batches_on_resume lines the deterministic stream up with the
    # interrupted position, so the resumed trajectory is EXACTLY the
    # uninterrupted one (same batches, same fold_in(rng, step) keys).
    resumed = fit(state, step, _batches(ds), steps=6, checkpoint_dir=ck,
                  skip_batches_on_resume=True)
    assert int(resumed.step) == int(straight.step) == 6
    for a, b in zip(jax.tree.leaves(resumed.params),
                    jax.tree.leaves(straight.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_fit_final_step_on_cadence_does_not_crash(setup, tmp_path):
    # steps divisible by checkpoint_every: the in-loop save already wrote
    # the final step; the forced final save must not re-save it (orbax
    # raises StepAlreadyExistsError on duplicates even with force=True).
    ds, state, step = setup
    out = fit(state, step, _batches(ds), steps=6,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3)
    assert int(out.step) == 6
    mgr = CheckpointManager(str(tmp_path / "ck"))
    try:
        assert mgr.latest_step() == 6
    finally:
        mgr.close()


def test_fit_prefetch_param(setup):
    ds, state, step = setup
    out = fit(state, step, _batches(ds), steps=3, prefetch=2)
    assert int(out.step) == 3


def test_fit_stops_at_data_exhaustion(setup):
    import itertools

    ds, state, step = setup
    few = list(itertools.islice(_batches(ds), 2))  # the stream is infinite
    out = fit(state, step, iter(few), steps=100)
    assert int(out.step) == 2


def test_fit_logs(setup):
    ds, state, step = setup
    seen = []
    fit(state, step, _batches(ds), steps=4, log_every=2, log_fn=seen.append)
    assert [m["step"] for m in seen] == [2, 4]
    assert all(np.isfinite(m["loss"]) for m in seen)


def test_fit_zero_steps_still_checkpoints(setup, tmp_path):
    ds, state, step = setup
    ck = str(tmp_path / "ck")
    with pytest.warns(UserWarning, match="0 steps"):
        out = fit(state, step, iter([]), steps=5, checkpoint_dir=ck)
    assert int(out.step) == 0
    mgr = CheckpointManager(ck)
    try:
        # The degenerate run must leave a detectable artifact, not nothing.
        assert mgr.latest_step() == 0
    finally:
        mgr.close()


def test_fit_eval_hook_cadence_and_final(setup):
    ds, state, step = setup
    records = []

    def eval_fn(st):
        # Minimal probe: records WHICH state the hook saw (cadence is the
        # property under test; real callers run a jitted eval step here).
        return {"seen_step": int(st.step)}

    fit(state, step, _batches(ds), steps=7, eval_every=3,
        eval_fn=eval_fn, log_fn=records.append)
    evals = [m for m in records if "eval" in m]
    # Cadence at 3 and 6, final at 7 — the eval sees the CURRENT state.
    assert [m["step"] for m in evals] == [3, 6, 7]
    assert all(m["eval"]["seen_step"] == m["step"] for m in evals)


def test_fit_eval_fn_final_only(setup):
    ds, state, step = setup
    records = []
    fit(state, step, _batches(ds), steps=4, eval_fn=lambda st: {"ok": 1},
        log_fn=records.append)
    evals = [m for m in records if "eval" in m]
    assert [m["step"] for m in evals] == [4]


def test_fit_eval_no_double_eval_on_exhaustion(setup):
    import itertools

    ds, state, step = setup
    records = []
    few = list(itertools.islice(_batches(ds), 6))  # exhausts AT an eval point
    fit(state, step, iter(few), steps=10, eval_every=3,
        eval_fn=lambda st: {"n": 1}, log_fn=records.append)
    evals = [m["step"] for m in records if "eval" in m]
    assert evals == [3, 6]  # step 6: cadence eval only, not a duplicate final
