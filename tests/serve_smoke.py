"""CI serve-smoke lane: 2-process loopback disaggregated serving.

Frontend (router + prefill) in THIS process, one decode rank in a spawned
process; 16 requests ship their KV blocks on the int8 wire. Asserts the
claims the serving tier makes (docs/DESIGN.md §10):

  * every request completes (complete token arrays, correct lengths);
  * the TTFT and TPOT histograms are non-empty on the frontend;
  * the int8 KV wire ratio is the codec's exact number by counters
    (~0.254x payload — tpunet_codec_wire_ratio, tx-side in this process);
  * BOTH tiers are scrapeable on one box via TPUNET_METRICS_PORT=0
    ephemeral binds (the decode tier's port learned only through
    telemetry.metrics_port()).

Run: python tests/serve_smoke.py   (exit 0 = pass)
"""

import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Ephemeral /metrics listener in BOTH processes (the spawned child re-runs
# this module's top level before the target executes, so the env applies
# there too — as does the CPU-mesh pin, which must precede any jax import).
os.environ["TPUNET_METRICS_PORT"] = "0"
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

N_REQUESTS = 16
MAX_NEW = 4
SLOTS = 4
MAX_LEN = 48
KV_CODEC = "int8"


def _model_and_params():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from tpunet.models import Transformer

    model = Transformer(vocab=64, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, compute_dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    return model, params


def _decode_child(addr: str, port_q, stop_ev) -> None:
    try:
        from tpunet import serve, telemetry

        model, params = _model_and_params()
        worker = serve.connect_decode(addr, model, params, slots=SLOTS,
                                      max_len=MAX_LEN, kv_codec=KV_CODEC)
        port_q.put(("port", telemetry.metrics_port()))
        worker.serve()
        # Keep the process (and its /metrics listener) alive until the
        # frontend has scraped this tier.
        stop_ev.wait(timeout=120)
        port_q.put(("done", worker.stats))
    except Exception as e:  # noqa: BLE001
        port_q.put(("error", f"{type(e).__name__}: {e}"))


def main() -> int:
    from tpunet import serve, telemetry

    model, params = _model_and_params()
    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]

    ctx = mp.get_context("spawn")
    port_q = ctx.Queue()
    stop_ev = ctx.Event()
    child = ctx.Process(target=_decode_child, args=(addr, port_q, stop_ev))
    child.start()
    try:
        prefill = serve.PrefillEngine(model, params, max_len=MAX_LEN)
        telemetry.reset()  # engine wiring noise out of the measured window
        router = serve.Router(prefill, kv_codec=KV_CODEC)
        router.accept_ranks(lsock, 1)
        kind, decode_port = port_q.get(timeout=120)
        assert kind == "port", decode_port

        rng = np.random.default_rng(7)
        lengths = (6, 9, 12, 15)
        ids = [_submit_with_backpressure(
                   router,
                   rng.integers(0, 64, lengths[i % len(lengths)]).astype(np.int32),
                   MAX_NEW)
               for i in range(N_REQUESTS)]
        results = router.run(timeout=240)
        assert sorted(results) == sorted(ids)
        assert all(len(v) == MAX_NEW for v in results.values()), \
            "truncated stream detected"

        m = telemetry.metrics()
        ttft = sum(m["tpunet_req_ttft_us_count"].values())
        tpot = sum(m["tpunet_req_tpot_us_count"].values())
        assert ttft >= N_REQUESTS, f"TTFT histogram has {ttft} samples"
        assert tpot >= N_REQUESTS, f"TPOT histogram has {tpot} samples"
        ratio = next(iter(m["tpunet_codec_wire_ratio"].values()))
        assert 0.25 <= ratio <= 0.26, \
            f"int8 KV wire ratio {ratio} not ~0.254x payload"

        # Both tiers scrapeable on one box: frontend via its own ephemeral
        # bind, decode via the port only metrics_port() could reveal.
        front = telemetry.scrape()
        assert "tpunet_req_ttft_us_count" in front
        back = telemetry.scrape(port=decode_port)
        assert "tpunet_serve_queue_depth" in back
        rx = [v for k, v in _parse_codec(back).items()
              if k == ("int8", "rx")]
        assert rx and rx[0] > 0, "decode tier shows no int8 rx bytes"

        router.shutdown()
        stop_ev.set()
        kind, payload = port_q.get(timeout=120)
        assert kind == "done", payload
        print(f"serve_smoke OK: {len(results)} requests, ttft={ttft} "
              f"tpot={tpot} wire_ratio={ratio:.6f} "
              f"decode_stats={payload}")
        return 0
    finally:
        stop_ev.set()
        child.join(timeout=30)
        if child.is_alive():
            child.kill()


def _submit_with_backpressure(router, prompt, max_new, timeout=240.0):
    """Retry admission on RouterBusyError — the client-side half of the
    backpressure contract (poll drains retirements, freeing slots)."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        try:
            return router.submit(prompt, max_new)
        except Exception as e:
            from tpunet import serve

            if not isinstance(e, serve.RouterBusyError):
                raise
            if time.monotonic() > deadline:
                raise
            router.poll()
            time.sleep(0.005)


def _parse_codec(text: str) -> dict:
    from tpunet import telemetry

    out = {}
    for line in text.splitlines():
        m = telemetry._LINE.match(line)
        if not m or m.group(1) != "tpunet_codec_bytes_total":
            continue
        labels = telemetry.labels(tuple((m.group(2) or "").split(",")))
        out[(labels.get("codec"), labels.get("dir"))] = float(m.group(3))
    return out


if __name__ == "__main__":
    sys.exit(main())
