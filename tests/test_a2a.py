"""Hierarchical AllToAll + typed payloads (docs/DESIGN.md "Hierarchical
AllToAll").

The acceptance gates, all counter-based (tpunet_a2a_bytes_total — never
wall-clock):

  * bit-identity vs the pairwise oracle at W in {2, 4, 8} x {f32, bf16,
    int8} x fake-host splits — the typed contract (encode once at the
    source, decode once at the destination, scale blocks restarting per
    (src, dst) block) makes every route produce the SAME bytes;
  * exact DCN byte accounting at W=4 as 2x2 fake hosts: the flat pairwise
    mesh ships (W-1)*B per rank, hier's inter stage exactly R*(H-1)*B —
    and typed bf16/int8 payloads push the hier DCN bytes to <= 0.6x the
    flat mesh's (the ISSUE 11 acceptance bound; int8 measures ~0.17x);
  * dispatch: auto upgrades to hier_a2a on a profitable topology, degrades
    to pairwise on a flat one, TPUNET_A2A_ALGO mismatches fail every rank
    typed at wiring (half a world per schedule deadlocks — so it never
    starts), and async AllToAll tickets ride the dedicated mesh queue so
    they overlap ring AllReduce tickets.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from conftest import free_port, run_spawn_workers


def _blocks(rank: int, world: int, n: int) -> np.ndarray:
    rng = np.random.default_rng(4200 + rank)
    return rng.standard_normal((world, n)).astype(np.float32)


def _spawn(target, world, args=(), timeout=240):
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=target, args=(r, world, port, q) + tuple(args))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world):
            rank, status = q.get(timeout=timeout)
            results[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    assert len(results) == world
    return results


# ---------------------------------------------------------------------------
# Bit-identity vs the pairwise oracle, W x codec x fake-host splits.


def _identity_worker(rank, world, port, q, codec, hosts, n):
    try:
        os.environ.update({"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"})
        if hosts > 1:
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_HOST_ID"] = f"a2ahost{rank // (world // hosts)}"
        from tpunet.collectives import Communicator

        send = _blocks(rank, world, n)
        out = {}
        # The override is re-read at every communicator creation and rides
        # the wiring handshake, so one process can run both schedules
        # back to back on consecutive coordinator ports.
        for i, algo in enumerate(("pairwise", "hier")):
            os.environ["TPUNET_A2A_ALGO"] = algo
            with Communicator(f"127.0.0.1:{port + i}", rank, world,
                              wire_dtype=codec) as comm:
                out[algo] = comm.all_to_all_typed(send)
        assert out["pairwise"].tobytes() == out["hier"].tobytes(), \
            f"{codec}: hier route produced different bytes than pairwise"
        q.put((rank, ("OK", out["pairwise"].tobytes(), send.tobytes())))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",)))


@pytest.mark.parametrize("codec", ["f32", "bf16", "int8"])
@pytest.mark.parametrize("world,hosts", [(2, 2), (4, 2), (8, 2)])
def test_typed_a2a_bit_identity_vs_pairwise_oracle(world, hosts, codec):
    n = 1031  # odd on purpose: int8 scale blocks must restart per block
    results = _spawn(_identity_worker, world, (codec, hosts, n))
    for rank, status in results.items():
        assert status[0] == "OK", f"rank {rank}: {status[0]}"
    sends = {r: np.frombuffer(results[r][2], np.float32).reshape(world, n)
             for r in results}
    from tpunet import transport as tp

    for r, status in results.items():
        got = np.frombuffer(status[1], np.float32).reshape(world, n)
        for j in range(world):
            blk = sends[j][r]
            if j == r or codec == "f32":
                # self block (and every f32 block) arrives EXACT
                expect = blk
            else:
                # one encode at the source, one decode at the destination —
                # recomputable outside any socket
                expect = tp.codec_decode(
                    tp.codec_encode(np.ascontiguousarray(blk), codec), codec, n)
            assert got[j].tobytes() == expect.tobytes(), \
                f"rank {r} block {j} ({codec}) mismatches the codec oracle"


# ---------------------------------------------------------------------------
# Exact DCN byte accounting + the <= 0.6x acceptance bound at W=4 as 2x2.


def _bytes_worker(rank, world, port, q, algo, codec, hosts, n):
    try:
        os.environ.update({"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
                           "TPUNET_A2A_ALGO": algo})
        if hosts > 1:
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_HOST_ID"] = f"byhost{rank // (world // hosts)}"
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        send = _blocks(rank, world, n)
        with Communicator(f"127.0.0.1:{port}", rank, world,
                          wire_dtype=codec) as comm:
            comm.barrier()
            telemetry.reset()
            got = comm.all_to_all_typed(send)
            m = telemetry.metrics()
        a2a = {}
        for key, v in m.get("tpunet_a2a_bytes_total", {}).items():
            lab = telemetry.labels(key)
            a2a[(lab["stage"], lab["dir"])] = int(v)
        steps = {telemetry.labels(k)["algo"]: int(v)
                 for k, v in m.get("tpunet_coll_steps_total", {}).items()}
        codec_tx = sum(int(v) for key, v in
                       m.get("tpunet_codec_bytes_total", {}).items()
                       if telemetry.labels(key)["dir"] == "tx")
        ratio = next(iter(m.get("tpunet_codec_wire_ratio", {}).values()), None)
        q.put((rank, ("OK", a2a, steps, got.tobytes(), codec_tx, ratio)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}",)))


def _run_bytes(algo, codec, hosts, world=4, n=4096):
    results = _spawn(_bytes_worker, world, (algo, codec, hosts, n))
    for rank, status in results.items():
        assert status[0] == "OK", f"rank {rank}: {status[0]}"
    return results


def test_hier_a2a_exact_bytes_and_acceptance_bound():
    """THE ISSUE 11 gate: at W=4 as 2x2 fake hosts the flat pairwise mesh
    ships exactly (W-1)*B DCN bytes per rank; hier's DCN (inter) stage
    ships exactly R*(H-1)*B_wire — equal to the cross-host payload lower
    bound for f32, and <= 0.6x the flat mesh's bytes for typed bf16/int8
    payloads (the codec multiplies the aggregation win). Every figure from
    tpunet_a2a_bytes_total, nothing from wall-clock."""
    from tpunet import transport as tp

    world, hosts, n = 4, 2, 4096
    R, H = world // hosts, hosts
    B = n * 4
    flat = _run_bytes("pairwise", "f32", hosts=1, world=world, n=n)
    flat_dcn = flat[0][1][("flat", "tx")]
    assert flat_dcn == (world - 1) * B, flat[0][1]

    hier = _run_bytes("hier", "f32", hosts=hosts, world=world, n=n)
    for rank, status in hier.items():
        a2a, steps = status[1], status[2]
        # Exact stage figures: intra (R-1)*H*B, inter R*(H-1)*B, flat 0.
        assert a2a[("intra", "tx")] == (R - 1) * H * B, (rank, a2a)
        assert a2a[("inter", "tx")] == R * (H - 1) * B, (rank, a2a)
        assert a2a[("flat", "tx")] == 0, (rank, a2a)
        assert steps.get("a2a.intra", 0) == R - 1, steps
        assert steps.get("a2a.inter", 0) == H - 1, steps
    # f32 results byte-identical to the pairwise oracle on every rank.
    flat_res = {r: s[3] for r, s in flat.items()}
    # (flat ran without the host split; same world, same data, same result)
    for rank, status in hier.items():
        assert status[3] == flat_res[rank], f"rank {rank}: hier != pairwise"

    for codec in ("bf16", "int8"):
        w = tp.codec_wire_bytes(codec, n)
        typed = _run_bytes("hier", codec, hosts=hosts, world=world, n=n)
        for rank, status in typed.items():
            a2a = status[1]
            assert a2a[("inter", "tx")] == R * (H - 1) * w, (codec, rank, a2a)
            ratio = a2a[("inter", "tx")] / flat_dcn
            assert ratio <= 0.6, \
                f"{codec}: hier DCN bytes {ratio:.3f}x flat exceeds the 0.6x bound"
            # Typed-A2A wire bytes feed the codec accounting like RS/AG
            # hops (the old A2A bypassed it entirely): W-1 blocks encoded
            # at exactly w bytes each, and the wire-ratio gauge shows the
            # encoded/payload quotient.
            assert status[4] == (world - 1) * w, (codec, rank, status[4])
            assert abs(status[5] - w / (4.0 * n)) < 1e-6, (codec, status[5])


# ---------------------------------------------------------------------------
# Dispatch: auto upgrade, flat degrade, table routing, mismatch handshake.


def _select_worker(rank, world, port, q, env, hosts, expect_algo):
    try:
        os.environ.update({"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"})
        os.environ.update(env)
        if hosts > 1:
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_HOST_ID"] = f"selhost{rank // (world // hosts)}"
        from tpunet import telemetry
        from tpunet.collectives import Communicator

        send = _blocks(rank, world, 256)
        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            comm.barrier()
            telemetry.reset()
            got = comm.all_to_all(send)
            m = telemetry.metrics()
        sel = {}
        for key, v in m.get("tpunet_coll_algo_selected_total", {}).items():
            lab = telemetry.labels(key)
            if lab["coll"] == "alltoall" and int(v):
                sel[lab["algo"]] = int(v)
        assert sel.get(expect_algo, 0) >= 1, f"selected {sel}, want {expect_algo}"
        # correctness regardless of route
        for j in range(world):
            assert np.array_equal(got[j], _blocks(j, world, 256)[rank])
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_a2a_auto_upgrades_on_profitable_topology():
    """Built-in auto on a 2x2 fake-host split resolves the AllToAll to the
    hierarchical transpose with no pinning (ApplyHierPolicy), counter-
    verified via tpunet_coll_algo_selected_total{coll="alltoall"}."""
    results = _spawn(_select_worker, 4, ({}, 2, "hier_a2a"))
    for rank, status in results.items():
        assert status == "OK", f"rank {rank}: {status}"


def test_a2a_hier_degrades_to_pairwise_on_flat_topology():
    """TPUNET_A2A_ALGO=hier on a single-host (flat) topology runs the
    pairwise mesh — the counter records what RAN."""
    results = _spawn(_select_worker, 2, ({"TPUNET_A2A_ALGO": "hier"}, 1,
                                         "pairwise"))
    for rank, status in results.items():
        assert status == "OK", f"rank {rank}: {status}"


def test_a2a_dispatch_table_routes_alltoall(tmp_path):
    """A TPUNET_DISPATCH_TABLE entry with coll="alltoall" re-routes the
    exchange (here onto the ring relay) — the per-size selector covers the
    third collective kind."""
    table = {"version": 1, "entries": [
        {"coll": "alltoall", "world": 2, "max_bytes": 0, "algo": "ring"},
    ]}
    path = tmp_path / "a2a_dispatch.json"
    path.write_text(json.dumps(table))
    results = _spawn(_select_worker, 2,
                     ({"TPUNET_DISPATCH_TABLE": str(path)}, 1, "ring"))
    for rank, status in results.items():
        assert status == "OK", f"rank {rank}: {status}"


def _mismatch_worker(rank, world, port, q):
    try:
        os.environ["TPUNET_A2A_ALGO"] = "hier" if rank == 0 else "pairwise"
        from tpunet import _native
        from tpunet.collectives import Communicator

        try:
            Communicator(f"127.0.0.1:{port}", rank, world)
            q.put((rank, "FAIL: mismatch accepted"))
        except _native.NativeError as e:
            q.put((rank, f"TYPED code={e.code}" if "a2a algo mismatch" in str(e)
                   else f"FAIL: wrong error {e}"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def test_a2a_algo_mismatch_fails_every_rank_typed():
    """Half a world on the pairwise mesh and half on the two-stage
    transpose deadlocks mid-collective; the wiring handshake (blob byte 7)
    fails BOTH ranks typed instead."""
    results = _spawn(_mismatch_worker, 2, timeout=60)
    for rank, status in results.items():
        assert status.startswith("TYPED"), f"rank {rank}: {status}"


def test_unknown_a2a_algo_rejected_before_any_socket():
    from tpunet import _native
    from tpunet.collectives import Communicator

    os.environ["TPUNET_A2A_ALGO"] = "star"
    try:
        with pytest.raises(_native.NativeError, match="unknown a2a algo"):
            Communicator("127.0.0.1:1", 0, 2)
    finally:
        os.environ.pop("TPUNET_A2A_ALGO", None)


# ---------------------------------------------------------------------------
# Async: AllToAll tickets ride the mesh queue and overlap ring tickets.


def _async_worker(rank, world, port, q, env):
    try:
        for k, v in env.items():
            os.environ[k] = v
        from tpunet.collectives import Communicator

        n = 8192
        send = _blocks(rank, world, n)
        red = np.full(1 << 16, float(rank + 1), np.float32)  # 256 KiB -> ring
        with Communicator(f"127.0.0.1:{port}", rank, world,
                          algo="ring") as comm:
            comm.all_reduce(red)  # warmup wires channels
            comm.barrier()
            # Interleave: ring AllReduce tickets and an AllToAll ticket are
            # OUTSTANDING TOGETHER; the A2A rides the dedicated mesh queue
            # (disjoint comms), so neither waits for the other's queue.
            r1 = comm.iall_reduce(red)
            ra = comm.iall_to_all(send)
            r2 = comm.iall_reduce(red)
            got_a = ra.wait()
            got_1, got_2 = r1.wait(), r2.wait()
        expect_red = sum(float(r + 1) for r in range(world))
        assert np.all(got_1 == expect_red) and np.all(got_2 == expect_red)
        for j in range(world):
            assert np.array_equal(got_a[j], _blocks(j, world, n)[rank]), j
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [2, 4])
def test_iall_to_all_overlaps_ring_tickets(world):
    env = {"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "2"}
    run_spawn_workers(_async_worker, world, extra_args=(env,))


# ---------------------------------------------------------------------------
# Config registration.


def test_config_registers_a2a_and_moe_knobs(monkeypatch):
    from tpunet.config import Config

    monkeypatch.setenv("TPUNET_A2A_ALGO", "hier")
    assert Config.from_env().a2a_algo == "hier"
    monkeypatch.setenv("TPUNET_A2A_ALGO", "mesh")
    with pytest.raises(ValueError, match="TPUNET_A2A_ALGO"):
        Config.from_env()
    monkeypatch.setenv("TPUNET_A2A_ALGO", "auto")
    monkeypatch.setenv("TPUNET_MOE_SKEW", "1.5")
    assert Config.from_env().moe_skew == 1.5
    monkeypatch.setenv("TPUNET_MOE_SKEW", "-0.5")
    with pytest.raises(ValueError, match="TPUNET_MOE_SKEW"):
        Config.from_env()
    monkeypatch.setenv("TPUNET_MOE_SKEW", "garbage")  # GetEnvU64 stance
    assert Config.from_env().moe_skew == 1.0
