"""Chip-sizing sweep for the TPU headline: chained-timing MFU per config."""
import sys, time, json
import jax, jax.numpy as jnp
import numpy as np
import optax

from tpunet.models import Transformer
from tpunet.train import create_train_state, make_train_step

CONFIGS = [
    # (d_model, layers, d_ff, heads, batch, seq, remat)
    (2048, 12, 8192, 16, 8, 2048, True),
    (2048, 12, 8192, 16, 16, 2048, True),
    (2048, 16, 8192, 16, 8, 2048, True),
    (4096, 4, 16384, 32, 8, 2048, True),
]
which = [int(x) for x in sys.argv[1:]] or list(range(len(CONFIGS)))

for ci in which:
    d, L, ff, h, b, s, remat = CONFIGS[ci]
    cfg = dict(vocab=32000, d_model=d, n_layers=L, n_heads=h, d_ff=ff)
    model = Transformer(compute_dtype=jnp.bfloat16, attn_impl="flash", remat=remat, **cfg)
    tx = optax.adamw(3e-4)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg["vocab"], (b, s)), jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    try:
        state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)
        step = make_train_step(model, tx)  # donate=True: real-training memory profile
        key = jax.random.PRNGKey(1)
        # warmup: compile + 1 run, hard-synced by transfer
        state, loss = step(state, tokens, labels, key)
        lv = float(loss)
        K = 8
        t0 = time.perf_counter()
        for _ in range(K):
            state, loss = step(state, tokens, labels, key)
        lv = float(loss)  # single sync: loss depends on the whole chain via state
        dt = (time.perf_counter() - t0) / K
    except Exception as e:
        print(json.dumps({"cfg": ci, "error": str(e)[:200]}), flush=True)
        continue
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    n_matmul = n_params - cfg["vocab"] * cfg["d_model"]
    fpt = 6 * n_matmul + 12 * L * s * d
    fps = fpt * b * s
    mfu = fps / dt / 197e12
    print(json.dumps({"cfg": ci, "d": d, "L": L, "ff": ff, "b": b, "s": s,
                      "params_M": round(n_params / 1e6, 1),
                      "step_s": round(dt, 4),
                      "tok_s": round(b * s / dt, 1),
                      "tflops": round(fps / dt / 1e12, 1),
                      "mfu": round(mfu, 4), "loss": round(lv, 3)}), flush=True)
