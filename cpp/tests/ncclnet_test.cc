// tpunet — drop-in validation of the ncclNet-shaped vtable (BASELINE
// config 1). Loads build/libtpunet.so the way an NCCL-style loader would
// (dlopen + dlsym "ncclNetPlugin_v4", fallback probe of "_v3", SURVEY §1 L5),
// then drives a loopback isend/irecv sweep purely through the vtable — no
// tpunet headers other than the compat ABI are used past this point.
#include <dlfcn.h>
#include <string.h>

#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

#include "tpunet/ncclnet_compat.h"

static int g_failures = 0;

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);    \
      ++g_failures;                                                      \
    }                                                                    \
  } while (0)

static int g_log_lines = 0;
static void TestLogger(ncclDebugLogLevel, unsigned long, const char*, int,
                       const char*, ...) {
  ++g_log_lines;
}

static void WaitDone(const ncclNet_v4_t* net, void* req, int* size) {
  int done = 0;
  while (!done) {
    if (net->test(req, &done, size) != ncclSuccess) {
      fprintf(stderr, "FAIL: vtable test() errored\n");
      ++g_failures;
      return;
    }
  }
}

int main(int argc, char** argv) {
  const char* so = argc > 1 ? argv[1] : "build/libtpunet.so";
  void* lib = dlopen(so, RTLD_NOW | RTLD_LOCAL);
  if (lib == nullptr) {
    fprintf(stderr, "FAIL: dlopen(%s): %s\n", so, dlerror());
    return 1;
  }
  auto* net = static_cast<ncclNet_v4_t*>(dlsym(lib, "ncclNetPlugin_v4"));
  auto* net3 = static_cast<ncclNet_v3_t*>(dlsym(lib, "ncclNetPlugin_v3"));
  CHECK(net != nullptr);
  CHECK(net3 != nullptr);
  if (net == nullptr || net3 == nullptr) return 1;
  CHECK(strcmp(net->name, "TPUNet") == 0);
  CHECK(strcmp(net3->name, "TPUNet") == 0);

  CHECK(net->init(TestLogger) == ncclSuccess);
  CHECK(g_log_lines > 0);

  int ndev = 0;
  CHECK(net->devices(&ndev) == ncclSuccess);
  CHECK(ndev >= 1);
  ncclNetProperties_v4_t props = {};
  CHECK(net->getProperties(0, &props) == ncclSuccess);
  CHECK(props.name != nullptr && props.name[0] != '\0');
  CHECK(props.ptrSupport == NCCL_PTR_HOST);
  CHECK(props.maxComms > 0);
  CHECK(net->getProperties(ndev + 7, &props) == ncclInvalidArgument);

  // Loopback rendezvous through the 64-byte opaque handle.
  unsigned char handle[NCCL_NET_HANDLE_MAXSIZE] = {0};
  void* listenComm = nullptr;
  void* sendComm = nullptr;
  void* recvComm = nullptr;
  CHECK(net->listen(0, handle, &listenComm) == ncclSuccess);
  CHECK(listenComm != nullptr);
  std::thread acceptor(
      [&] { CHECK(net->accept(listenComm, &recvComm) == ncclSuccess); });
  CHECK(net->connect(0, handle, &sendComm) == ncclSuccess);
  acceptor.join();
  CHECK(sendComm != nullptr && recvComm != nullptr);

  // regMr contract: host pointers fine (mhandle null), CUDA rejected.
  void* mhandle = reinterpret_cast<void*>(0xdead);
  CHECK(net->regMr(sendComm, handle, 64, NCCL_PTR_HOST, &mhandle) ==
        ncclSuccess);
  CHECK(mhandle == nullptr);
  CHECK(net->regMr(sendComm, handle, 64, NCCL_PTR_CUDA, &mhandle) !=
        ncclSuccess);
  CHECK(net->deregMr(sendComm, nullptr) == ncclSuccess);
  // No device memory -> flush paths must refuse.
  void* freq = nullptr;
  CHECK(net->iflush(recvComm, handle, 64, nullptr, &freq) != ncclSuccess);
  CHECK(net3->flush(recvComm, handle, 64, nullptr) != ncclSuccess);

  // Size sweep with payload verification; recv posts a larger buffer and the
  // true size must come back from test() (ctrl-frame semantics, SURVEY §2.2).
  for (int size : {0, 1, 8, 4096, 1 << 20, 5000000}) {
    std::vector<unsigned char> src(size), dst(size + 64, 0xAA);
    for (int i = 0; i < size; ++i) src[i] = static_cast<unsigned char>(i * 37 + 11);
    void* sreq = nullptr;
    void* rreq = nullptr;
    CHECK(net->irecv(recvComm, dst.data(), static_cast<int>(dst.size()),
                     nullptr, &rreq) == ncclSuccess);
    CHECK(net->isend(sendComm, src.data(), size, nullptr, &sreq) ==
          ncclSuccess);
    CHECK(sreq != nullptr && rreq != nullptr);
    int sent = -1, got = -1;
    WaitDone(net, sreq, &sent);
    WaitDone(net, rreq, &got);
    CHECK(sent == size);
    CHECK(got == size);
    CHECK(size == 0 || memcmp(src.data(), dst.data(), size) == 0);
    for (size_t i = size; i < dst.size(); ++i) CHECK(dst[i] == 0xAA);
  }

  // NCCL keeps up to 8 requests in flight per comm (NCCL_NET_MAX_REQUESTS).
  constexpr int kInflight = NCCL_NET_MAX_REQUESTS;
  constexpr int kMsg = 65536;
  std::vector<std::vector<unsigned char>> srcs(kInflight), dsts(kInflight);
  void* sreqs[kInflight];
  void* rreqs[kInflight];
  for (int i = 0; i < kInflight; ++i) {
    srcs[i].assign(kMsg, static_cast<unsigned char>(i + 1));
    dsts[i].assign(kMsg, 0);
    CHECK(net->irecv(recvComm, dsts[i].data(), kMsg, nullptr, &rreqs[i]) ==
          ncclSuccess);
  }
  for (int i = 0; i < kInflight; ++i) {
    CHECK(net->isend(sendComm, srcs[i].data(), kMsg, nullptr, &sreqs[i]) ==
          ncclSuccess);
  }
  for (int i = 0; i < kInflight; ++i) {
    int n = 0;
    WaitDone(net, sreqs[i], &n);
    WaitDone(net, rreqs[i], &n);
    CHECK(n == kMsg);
    CHECK(memcmp(srcs[i].data(), dsts[i].data(), kMsg) == 0);
  }

  CHECK(net->closeSend(sendComm) == ncclSuccess);
  CHECK(net->closeRecv(recvComm) == ncclSuccess);
  CHECK(net->closeListen(listenComm) == ncclSuccess);
  // Stale handles are invalid-argument, not a crash.
  CHECK(net->closeSend(sendComm) == ncclInvalidArgument);

  dlclose(lib);
  if (g_failures == 0) {
    printf("OK: ncclNet vtable drop-in tests passed\n");
    return 0;
  }
  printf("FAILED: %d check(s)\n", g_failures);
  return 1;
}
