// Collectives self-test: W ranks as THREADS of one process so the sanitizer
// lanes (tsan/asan) can see every cross-rank interaction in the collectives
// layer — ReducePool fork-join, the async ticket worker, comm teardown. The
// Python suite runs these paths multi-process where TSAN is blind.
//
// Coverage: all_reduce (sum, with TPUNET_REDUCE_THREADS>1), reduce_scatter,
// all_gather, broadcast, all_to_all, neighbor_exchange, barrier, and
// overlapping iall_reduce tickets waited out of order, then teardown while
// a ticket is still in flight on one rank (wait-then-destroy on the other).

#include <atomic>
#include <chrono>
#include <cinttypes>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tpunet/c_api.h"

namespace {

constexpr int kWorld = 3;
constexpr uint64_t kCount = 40000;  // spans multiple ring chunks

std::atomic<int> g_failures{0};

#define CHECK_MSG(cond, ...)                                      \
  do {                                                            \
    if (!(cond)) {                                                \
      std::fprintf(stderr, "FAIL %s:%d: ", __FILE__, __LINE__);   \
      std::fprintf(stderr, __VA_ARGS__);                          \
      std::fprintf(stderr, "\n");                                 \
      g_failures.fetch_add(1);                                    \
      return;                                                     \
    }                                                             \
  } while (0)

#define CHECK_OK(expr) CHECK_MSG((expr) == 0, "%s -> %s", #expr, tpunet_c_last_error())

// Compressed-collectives lane (docs/DESIGN.md "Compressed collectives"):
// per codec, an f32 allreduce + reduce_scatter over the quantized ring —
// error-bounded vs the exact sum, cross-rank BIT-IDENTICAL (checked via a
// CRC32C allgather), wire_dtype getter agreeing — plus the negotiation
// failure path: ranks configured with different codecs ALL fail with
// TPUNET_ERR_CODEC. Runs under asan/tsan with the small ring chunks set in
// main(), so the chunked encode/fused-decode-reduce pipeline really cycles.
void codec_rank_main(int rank, int base_port) {
  const char* codecs[2] = {"bf16", "int8"};
  for (int ci = 0; ci < 2; ++ci) {
    std::string coord = "127.0.0.1:" + std::to_string(base_port + 1 + ci);
    uintptr_t comm = 0;
    CHECK_OK(tpunet_comm_create_ex(coord.c_str(), rank, kWorld, codecs[ci], nullptr, nullptr, &comm));
    int32_t wd = -1;
    CHECK_OK(tpunet_comm_wire_dtype(comm, &wd));
    CHECK_MSG(wd == ci + 1, "wire_dtype %d != %d for %s", wd, ci + 1, codecs[ci]);

    std::vector<float> send(kCount), recv(kCount);
    for (uint64_t i = 0; i < kCount; ++i) send[i] = float(rank + 1) + float(i % 7);
    CHECK_OK(tpunet_comm_all_reduce(comm, send.data(), recv.data(), kCount, 0, 0));
    for (uint64_t i = 0; i < kCount; ++i) {
      // Exact sum <= 24; per-hop quantization error is <= amax*2^-8 (bf16)
      // or amax/254 (int8) over <= W hops — 0.5 covers both with margin.
      float expect = float(kWorld * (kWorld + 1) / 2) + float(kWorld * (i % 7));
      CHECK_MSG(std::fabs(recv[i] - expect) < 0.5f, "%s all_reduce[%" PRIu64 "] %f != %f",
                codecs[ci], i, double(recv[i]), double(expect));
    }
    // Cross-rank bit-identity: every rank must hold the SAME quantized
    // bytes (the AG phase forwards encoded frames verbatim).
    uint32_t crc = tpunet_c_crc32c(recv.data(), kCount * 4, 0);
    std::vector<uint32_t> crcs(kWorld, 0);
    CHECK_OK(tpunet_comm_all_gather(comm, &crc, crcs.data(), sizeof(crc)));
    for (int r = 0; r < kWorld; ++r) {
      CHECK_MSG(crcs[r] == crc, "%s result bytes differ between rank %d and %d",
                codecs[ci], rank, r);
    }

    // reduce_scatter rides the same compressed RS pipeline.
    const uint64_t rc = 4096;
    std::vector<float> rs_in(kWorld * rc), rs_out(rc);
    for (uint64_t i = 0; i < rs_in.size(); ++i) rs_in[i] = float(rank) + float(i % 11);
    CHECK_OK(tpunet_comm_reduce_scatter(comm, rs_in.data(), rs_out.data(), rc, 0, 0));
    for (uint64_t i = 0; i < rc; ++i) {
      float expect = float(kWorld * (kWorld - 1) / 2) +
                     float(kWorld) * float((rank * rc + i) % 11);
      CHECK_MSG(std::fabs(rs_out[i] - expect) < 0.5f, "%s reduce_scatter[%" PRIu64 "]",
                codecs[ci], i);
    }
    CHECK_OK(tpunet_comm_destroy(&comm));
  }

  // Negotiation failure: rank 0 asks for bf16, everyone else f32 — every
  // rank must get the typed mismatch, nobody may wedge or succeed.
  {
    std::string coord = "127.0.0.1:" + std::to_string(base_port + 3);
    uintptr_t comm = 0;
    int32_t rcv = tpunet_comm_create_ex(coord.c_str(), rank, kWorld,
                                        rank == 0 ? "bf16" : "f32", nullptr,
                                        nullptr, &comm);
    CHECK_MSG(rcv == TPUNET_ERR_CODEC, "expected TPUNET_ERR_CODEC, got %d (%s)",
              rcv, tpunet_c_last_error());
  }

  // Unknown codec name fails before any socket exists.
  {
    uintptr_t comm = 0;
    int32_t rcv = tpunet_comm_create_ex("127.0.0.1:1", rank, 1, "fp8", nullptr, nullptr, &comm);
    CHECK_MSG(rcv == TPUNET_ERR_INVALID, "expected INVALID for fp8, got %d", rcv);
  }
}

// Schedule lane: the same f32 allreduce pinned to each schedule (ring /
// recursive halving-doubling / binomial tree) must produce BYTE-IDENTICAL
// results — the data is integer-valued, so every summation order is exact
// and any divergence is an indexing/offset bug, not float noise. W=3
// exercises the rhd non-power-of-2 fold and the uneven tree. Also pins the
// algo-mismatch handshake (typed failure on EVERY rank, nobody wedges).
void schedule_rank_main(int rank, int base_port) {
  const char* algos[3] = {"ring", "rhd", "tree"};
  std::vector<float> results[3];
  for (int ai = 0; ai < 3; ++ai) {
    std::string coord = "127.0.0.1:" + std::to_string(base_port + 4 + ai);
    uintptr_t comm = 0;
    CHECK_OK(tpunet_comm_create_ex(coord.c_str(), rank, kWorld, "f32",
                                   algos[ai], nullptr, &comm));
    std::vector<float> send(kCount), recv(kCount);
    for (uint64_t i = 0; i < kCount; ++i)
      send[i] = float(rank + 1) + float(i % 23);
    CHECK_OK(tpunet_comm_all_reduce(comm, send.data(), recv.data(), kCount, 0, 0));
    for (uint64_t i = 0; i < kCount; ++i) {
      float expect = float(kWorld * (kWorld + 1) / 2) + float(kWorld * (i % 23));
      CHECK_MSG(recv[i] == expect, "%s all_reduce[%" PRIu64 "] %f != %f",
                algos[ai], i, double(recv[i]), double(expect));
    }
    // Broadcast rides the schedule dispatch too (tree for small payloads).
    std::vector<uint8_t> bc(2048, rank == 1 ? uint8_t(0x5A) : uint8_t(0));
    CHECK_OK(tpunet_comm_broadcast(comm, bc.data(), bc.size(), 1));
    CHECK_MSG(bc[0] == 0x5A && bc[2047] == 0x5A, "%s broadcast corrupted",
              algos[ai]);
    results[ai] = recv;
    CHECK_OK(tpunet_comm_destroy(&comm));
  }
  CHECK_MSG(memcmp(results[0].data(), results[1].data(), kCount * 4) == 0,
            "ring vs rhd results differ");
  CHECK_MSG(memcmp(results[0].data(), results[2].data(), kCount * 4) == 0,
            "ring vs tree results differ");

  // Algo negotiation failure: rank 0 pins tree, everyone else ring — every
  // rank must fail typed at wiring, before any schedule could half-run.
  {
    std::string coord = "127.0.0.1:" + std::to_string(base_port + 7);
    uintptr_t comm = 0;
    int32_t rcv = tpunet_comm_create_ex(coord.c_str(), rank, kWorld, nullptr,
                                        rank == 0 ? "tree" : "ring", nullptr,
                                        &comm);
    CHECK_MSG(rcv == TPUNET_ERR_INVALID,
              "expected TPUNET_ERR_INVALID for algo mismatch, got %d (%s)", rcv,
              tpunet_c_last_error());
  }

  // Traffic-class negotiation failure: rank 0 wires the latency lane,
  // everyone else bulk — typed on every rank, nobody wedges (half a group
  // on another QoS lane would unbalance the scheduler silently).
  {
    std::string coord = "127.0.0.1:" + std::to_string(base_port + 8);
    uintptr_t comm = 0;
    int32_t rcv = tpunet_comm_create_ex(coord.c_str(), rank, kWorld, nullptr,
                                        nullptr,
                                        rank == 0 ? "latency" : "bulk", &comm);
    CHECK_MSG(rcv == TPUNET_ERR_INVALID,
              "expected TPUNET_ERR_INVALID for class mismatch, got %d (%s)",
              rcv, tpunet_c_last_error());
  }

  // Unknown traffic class fails before any socket exists.
  {
    uintptr_t comm = 0;
    int32_t rcv = tpunet_comm_create_ex("127.0.0.1:1", rank, 1, nullptr,
                                        nullptr, "express", &comm);
    CHECK_MSG(rcv == TPUNET_ERR_INVALID, "expected INVALID for express, got %d",
              rcv);
  }

  // Unknown algo name fails before any socket exists.
  {
    uintptr_t comm = 0;
    int32_t rcv =
        tpunet_comm_create_ex("127.0.0.1:1", rank, 1, nullptr, "star", nullptr, &comm);
    CHECK_MSG(rcv == TPUNET_ERR_INVALID, "expected INVALID for star, got %d", rcv);
  }
}

void rank_main(int rank, const std::string& coordinator) {
  uintptr_t comm = 0;
  CHECK_OK(tpunet_comm_create(coordinator.c_str(), rank, kWorld, &comm));

  // all_reduce(sum) f32, out-of-place + in-place.
  std::vector<float> send(kCount), recv(kCount);
  for (uint64_t i = 0; i < kCount; ++i) send[i] = float(rank + 1) + float(i % 7);
  CHECK_OK(tpunet_comm_all_reduce(comm, send.data(), recv.data(), kCount, 0, 0));
  for (uint64_t i = 0; i < kCount; ++i) {
    float expect = float(kWorld * (kWorld + 1) / 2) + float(kWorld * (i % 7));
    CHECK_MSG(std::fabs(recv[i] - expect) < 1e-3f, "all_reduce[%" PRIu64 "] %f != %f",
              i, double(recv[i]), double(expect));
  }
  CHECK_OK(tpunet_comm_all_reduce(comm, send.data(), send.data(), kCount, 0, 0));
  CHECK_MSG(std::fabs(send[0] - recv[0]) < 1e-3f, "in-place mismatch");

  // reduce_scatter: world*rc elements -> rank's rc slice of the sum.
  const uint64_t rc = 1024;
  std::vector<float> rs_in(kWorld * rc), rs_out(rc);
  for (uint64_t i = 0; i < rs_in.size(); ++i) rs_in[i] = float(rank) + float(i);
  CHECK_OK(tpunet_comm_reduce_scatter(comm, rs_in.data(), rs_out.data(), rc, 0, 0));
  for (uint64_t i = 0; i < rc; ++i) {
    float expect = float(kWorld * (kWorld - 1) / 2) + float(kWorld) * float(rank * rc + i);
    CHECK_MSG(std::fabs(rs_out[i] - expect) < 1e-2f, "reduce_scatter[%" PRIu64 "]", i);
  }

  // all_gather bytes.
  std::vector<uint8_t> ag_in(512, uint8_t(0x40 + rank)), ag_out(kWorld * 512);
  CHECK_OK(tpunet_comm_all_gather(comm, ag_in.data(), ag_out.data(), 512));
  for (int r = 0; r < kWorld; ++r)
    CHECK_MSG(ag_out[r * 512] == uint8_t(0x40 + r), "all_gather rank %d block", r);

  // broadcast from root 1.
  std::vector<uint8_t> bc(777, uint8_t(rank == 1 ? 0xAB : 0));
  CHECK_OK(tpunet_comm_broadcast(comm, bc.data(), bc.size(), 1));
  CHECK_MSG(bc[0] == 0xAB && bc[776] == 0xAB, "broadcast payload");

  // all_to_all: block j for rank j.
  std::vector<uint8_t> a2a_in(kWorld * 256), a2a_out(kWorld * 256);
  for (int j = 0; j < kWorld; ++j)
    std::memset(a2a_in.data() + j * 256, 0x10 * (rank + 1) + j, 256);
  CHECK_OK(tpunet_comm_all_to_all(comm, a2a_in.data(), a2a_out.data(), 256));
  for (int j = 0; j < kWorld; ++j)
    CHECK_MSG(a2a_out[j * 256] == uint8_t(0x10 * (j + 1) + rank),
              "all_to_all block from rank %d", j);
  // In-place: sendbuf == recvbuf (pairwise path must stage outgoing blocks).
  CHECK_OK(tpunet_comm_all_to_all(comm, a2a_in.data(), a2a_in.data(), 256));
  for (int j = 0; j < kWorld; ++j)
    CHECK_MSG(a2a_in[j * 256] == uint8_t(0x10 * (j + 1) + rank),
              "in-place all_to_all block from rank %d", j);

  // Typed all_to_all: f32 blocks (codec f32 here -> exact); the typed
  // entry point and its per-block geometry run under the sanitizers.
  const uint64_t tn = 321;  // odd: blocks must not assume alignment
  std::vector<float> t_in(kWorld * tn), t_out(kWorld * tn);
  for (int j = 0; j < kWorld; ++j)
    for (uint64_t i = 0; i < tn; ++i)
      t_in[j * tn + i] = float(rank * 100 + j) + float(i) / 8.0f;
  CHECK_OK(tpunet_comm_all_to_all_typed(comm, t_in.data(), t_out.data(), tn, 0));
  for (int j = 0; j < kWorld; ++j)
    for (uint64_t i = 0; i < tn; ++i)
      CHECK_MSG(t_out[j * tn + i] == float(j * 100 + rank) + float(i) / 8.0f,
                "typed all_to_all block from rank %d elem %" PRIu64, j, i);

  // Async all_to_all ticket outstanding TOGETHER with a ring AllReduce
  // ticket — the mesh-queue overlap contract (tickets on disjoint comms).
  {
    std::vector<float> red(8192, float(rank + 1));
    uint64_t t_red = 0, t_a2a = 0;
    std::vector<uint8_t> ai(kWorld * 128), ao(kWorld * 128);
    for (int j = 0; j < kWorld; ++j)
      std::memset(ai.data() + j * 128, 0x20 * (rank + 1) + j, 128);
    CHECK_OK(tpunet_comm_iall_reduce(comm, red.data(), red.data(), 8192, 0, 0,
                                     &t_red));
    CHECK_OK(tpunet_comm_iall_to_all(comm, ai.data(), ao.data(), 128, &t_a2a));
    CHECK_OK(tpunet_comm_ticket_wait(comm, t_a2a));
    CHECK_OK(tpunet_comm_ticket_wait(comm, t_red));
    CHECK_MSG(std::fabs(red[0] - float(kWorld * (kWorld + 1) / 2)) < 1e-3f,
              "overlapped iall_reduce result");
    for (int j = 0; j < kWorld; ++j)
      CHECK_MSG(ao[j * 128] == uint8_t(0x20 * (j + 1) + rank),
                "iall_to_all block from rank %d", j);
  }

  // neighbor exchange.
  std::vector<uint8_t> ne_in(300, uint8_t(rank)), ne_out(400);
  uint64_t got = 0;
  CHECK_OK(tpunet_comm_neighbor_exchange(comm, ne_in.data(), ne_in.size(),
                                         ne_out.data(), ne_out.size(), &got));
  CHECK_MSG(got == 300 && ne_out[0] == uint8_t((rank + kWorld - 1) % kWorld),
            "neighbor_exchange");

  // Overlapping async tickets waited in reverse order.
  const uint64_t ac = 8192;
  std::vector<std::vector<float>> abufs;
  std::vector<uint64_t> tickets;
  for (int s = 0; s < 3; ++s) {
    abufs.emplace_back(ac, float(rank + 1) * float(s + 1));
    uint64_t t = 0;
    CHECK_OK(tpunet_comm_iall_reduce(comm, abufs[s].data(), abufs[s].data(),
                                     ac, 0, 0, &t));
    tickets.push_back(t);
  }
  for (int s = 2; s >= 0; --s) {
    CHECK_OK(tpunet_comm_ticket_wait(comm, tickets[s]));
    float expect = float(kWorld * (kWorld + 1) / 2) * float(s + 1);
    CHECK_MSG(std::fabs(abufs[s][0] - expect) < 1e-3f, "iall_reduce s=%d", s);
  }

  // ticket_test polling path.
  uint64_t t = 0;
  std::vector<float> last(ac, 1.0f);
  CHECK_OK(tpunet_comm_iall_reduce(comm, last.data(), last.data(), ac, 0, 0, &t));
  uint8_t done = 0;
  CHECK_OK(tpunet_comm_ticket_test(comm, t, &done));  // may or may not be done
  CHECK_OK(tpunet_comm_ticket_wait(comm, t));
  CHECK_OK(tpunet_comm_barrier(comm));

  // Teardown with a ticket still outstanding: destroy must terminate on
  // every interleaving — job drained by the worker, failed while queued, or
  // cut short by a peer's teardown (comm poisoning turns that into a typed
  // error, not a hang; the main() watchdog converts any regression here
  // into a test failure). Buffers stay alive across destroy per the
  // contract. No wait: the ticket is abandoned deliberately.
  uint64_t t2 = 0;
  std::vector<float> tail(ac, 2.0f);
  CHECK_OK(tpunet_comm_iall_reduce(comm, tail.data(), tail.data(), ac, 0, 0, &t2));
  CHECK_OK(tpunet_comm_destroy(&comm));
}

}  // namespace

int main() {
  // Exercise the fork-join reduce pool under the sanitizer.
  setenv("TPUNET_REDUCE_THREADS", "2", 1);
  // Small ring chunks so the pipelined transfer||reduce path really cycles.
  setenv("TPUNET_RING_CHUNKSIZE", "16384", 1);

  const char* port_env = getenv("TPUNET_TEST_PORT");
  int base_port = port_env ? atoi(port_env) : 29517;
  std::string coordinator = "127.0.0.1:" + std::to_string(base_port);

  // A failed check on one rank-thread leaves its peers blocked in the next
  // collective (no data-plane timeout); without a watchdog that is a CI
  // hang, not an exit-1.
  std::atomic<bool> finished{false};
  std::thread watchdog([&finished] {
    for (int i = 0; i < 2400 && !finished.load(); ++i)
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    if (!finished.load()) {
      std::fprintf(stderr, "FAILED: watchdog timeout (rank deadlock)\n");
      std::_Exit(2);
    }
  });

  std::vector<std::thread> ranks;
  ranks.reserve(kWorld);
  for (int r = 0; r < kWorld; ++r)
    ranks.emplace_back(rank_main, r, coordinator);
  for (auto& th : ranks) th.join();

  // Compressed-collectives lane (fresh comms on base_port+1..+3).
  ranks.clear();
  for (int r = 0; r < kWorld; ++r)
    ranks.emplace_back(codec_rank_main, r, base_port);
  for (auto& th : ranks) th.join();

  // Schedule lane: ring vs rhd vs tree bit-equality + algo handshake
  // (fresh comms on base_port+4..+8).
  ranks.clear();
  for (int r = 0; r < kWorld; ++r)
    ranks.emplace_back(schedule_rank_main, r, base_port);
  for (auto& th : ranks) th.join();

  finished.store(true);
  watchdog.join();

  if (g_failures.load() != 0) {
    std::fprintf(stderr, "FAILED: %d check(s)\n", g_failures.load());
    return 1;
  }
  std::printf("OK: all collectives tests passed (%d ranks in-process)\n", kWorld);
  return 0;
}
