// tpunet C++ unit + loopback self-test binary.
// Covers the reference's unit surface (utils.rs:263-314 test_parse /
// test_socket_handle / test_chunks) plus what the reference lacked (SURVEY
// §4 gap): an in-process loopback listen/connect/accept + isend/irecv sweep
// with payload verification, zero-byte messages, oversized recv buffers, and
// 8 in-flight requests (NCCL_NET_MAX_REQUESTS depth).
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <vector>

#include "tpunet/c_api.h"
#include "tpunet/net.h"
#include "tpunet/qos.h"
#include "tpunet/utils.h"

using namespace tpunet;

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

#define CHECK_OK(status)                                                   \
  do {                                                                     \
    Status s_ = (status);                                                  \
    if (!s_.ok()) {                                                        \
      fprintf(stderr, "FAIL %s:%d: status = %s\n", __FILE__, __LINE__,     \
              s_.msg.c_str());                                             \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

static void TestChunkMath() {
  // Mirrors reference utils.rs:298-313 incl. the min_chunksize clamp.
  CHECK(ChunkSize(100, 1, 4) == 25);
  CHECK(ChunkSize(101, 1, 4) == 26);
  CHECK(ChunkSize(100, 1000, 4) == 1000);
  CHECK(ChunkSize(0, 7, 4) == 7);
  CHECK(ChunkCount(100, 25) == 4);
  CHECK(ChunkCount(101, 26) == 4);
  CHECK(ChunkCount(100, 1000) == 1);
  CHECK(ChunkCount(0, 7) == 0);
  // Sender/receiver symmetry: any (len, min, n) must give both sides the
  // same partition covering the buffer exactly.
  for (size_t len : {1ul, 7ul, 4096ul, 1048575ul, 1048577ul, 9999999ul}) {
    for (size_t n : {1ul, 2ul, 3ul, 8ul}) {
      size_t cs = ChunkSize(len, 65536, n);
      size_t cnt = ChunkCount(len, cs);
      CHECK(cnt <= n);
      CHECK(cnt * cs >= len);
      CHECK(cnt == 0 || (cnt - 1) * cs < len);
    }
  }
}

static void TestBE() {
  uint8_t buf[8];
  EncodeU64BE(0x0123456789abcdefull, buf);
  CHECK(buf[0] == 0x01 && buf[7] == 0xef);
  CHECK(DecodeU64BE(buf) == 0x0123456789abcdefull);
  EncodeU64BE(0, buf);
  CHECK(DecodeU64BE(buf) == 0);
}

static void TestParse() {
  // Mirrors reference utils.rs:268-284.
  UserPassAddr r;
  CHECK(ParseUserPassAndAddr("admin:pass123@10.0.0.1:9091", &r));
  CHECK(r.user == "admin" && r.pass == "pass123" && r.addr == "10.0.0.1:9091");
  CHECK(ParseUserPassAndAddr("10.0.0.1:9091", &r));
  CHECK(r.user.empty() && r.pass.empty() && r.addr == "10.0.0.1:9091");
  CHECK(!ParseUserPassAndAddr("", &r));
}

static void TestSocketIO() {
  int fds[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, fds) == 0);
  std::vector<uint8_t> payload(1 << 20);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 31 + 7);
  std::thread writer([&] { CHECK_OK(WriteAll(fds[0], payload.data(), payload.size())); });
  std::vector<uint8_t> got(payload.size());
  CHECK_OK(ReadExact(fds[1], got.data(), got.size()));
  writer.join();
  CHECK(memcmp(payload.data(), got.data(), payload.size()) == 0);
  // EOF detection.
  ::close(fds[0]);
  uint8_t b;
  CHECK(!ReadExact(fds[1], &b, 1).ok());
  ::close(fds[1]);
}

static void TestInterfaces() {
  auto nics = FindInterfaces();
  CHECK(!nics.empty());
  for (const auto& n : nics) {
    CHECK(!n.name.empty());
    CHECK(n.addrlen > 0);
  }
}

static void TestCrc32c() {
  // RFC 3720 B.4 golden vectors.
  CHECK(Crc32c("123456789", 9) == 0xE3069283u);
  uint8_t zeros[32] = {0};
  CHECK(Crc32c(zeros, sizeof(zeros)) == 0x8A9136AAu);
  uint8_t ffs[32];
  memset(ffs, 0xFF, sizeof(ffs));
  CHECK(Crc32c(ffs, sizeof(ffs)) == 0x62A8AB43u);
  CHECK(Crc32c(nullptr, 0) == 0);
  // Chaining across a split equals one pass (seeded form).
  const char* s = "tpunet chunk integrity";
  uint32_t whole = Crc32c(s, strlen(s));
  uint32_t part = Crc32c(s, 7);
  CHECK(Crc32c(s + 7, strlen(s) - 7, part) == whole);
  // The C ABI wrapper agrees with the library function.
  CHECK(tpunet_c_crc32c("123456789", 9, 0) == 0xE3069283u);
}

static void TestFaultSpecParser() {
  // Valid specs arm cleanly through the C ABI (then always clear).
  CHECK(tpunet_c_fault_inject("stream=1:after_bytes=1M:action=close") == TPUNET_OK);
  CHECK(tpunet_c_fault_inject("stream=*:side=recv:action=stall") == TPUNET_OK);
  CHECK(tpunet_c_fault_inject("action=delay=50:after_bytes=256K") == TPUNET_OK);
  CHECK(tpunet_c_fault_inject("action=corrupt") == TPUNET_OK);
  CHECK(tpunet_c_fault_inject(nullptr) == TPUNET_OK);  // NULL clears
  // Malformed specs are typed invalid-argument failures.
  CHECK(tpunet_c_fault_inject("nonsense") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("stream=1") == TPUNET_ERR_INVALID);          // no action
  CHECK(tpunet_c_fault_inject("action=explode") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("action=delay") == TPUNET_ERR_INVALID);     // no ms
  CHECK(tpunet_c_fault_inject("stream=bogus:action=close") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("after_bytes=1X:action=close") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("side=up:action=close") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_clear() == TPUNET_OK);
}

static void TestChurnScript() {
  // Churn segments arm the step-polled script (docs/DESIGN.md "Elastic
  // churn"); a classic fault segment may ride along in the same script.
  CHECK(tpunet_c_fault_inject(
            "churn:at_step=4:rank=3:action=kill;"
            "churn:at_step=8:rank=4:action=join") == TPUNET_OK);
  CHECK(tpunet_c_churn_pending() == 2);
  CHECK(tpunet_c_churn_poll(3, 3) == 0);   // before at_step
  CHECK(tpunet_c_churn_poll(4, 2) == 0);   // wrong member
  CHECK(tpunet_c_churn_poll(5, 3) == 1);   // kill fires at step >= at_step
  CHECK(tpunet_c_churn_poll(5, 3) == 0);   // one-shot latch
  CHECK(tpunet_c_churn_pending() == 1);
  CHECK(tpunet_c_churn_poll(9, 4) == 2);   // join
  CHECK(tpunet_c_churn_pending() == 0);
  CHECK(tpunet_c_fault_inject("stream=1:action=close;churn:rank=*:action=kill")
        == TPUNET_OK);
  CHECK(tpunet_c_churn_pending() == 1);
  CHECK(tpunet_c_churn_poll(0, 17) == 1);  // rank=* matches anyone
  // Malformed churn segments (and double classic faults) are typed.
  CHECK(tpunet_c_fault_inject("churn:action=nuke") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("churn:at_step=1") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("churn:bad=1:action=kill") == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_fault_inject("action=close;action=close") == TPUNET_ERR_INVALID);
  // Clearing wipes the script with the fault slot.
  CHECK(tpunet_c_fault_inject("churn:action=join") == TPUNET_OK);
  CHECK(tpunet_c_fault_clear() == TPUNET_OK);
  CHECK(tpunet_c_churn_pending() == 0);
}

// Wire a fresh BASIC<->BASIC loopback pair; returns comm ids through refs.
static void WireLoopback(Net* snet, Net* rnet, uint64_t* send_id, uint64_t* recv_id,
                         uint64_t* listen_id) {
  SocketHandle handle;
  CHECK_OK(rnet->listen(0, &handle, listen_id));
  std::thread acceptor([&] { CHECK_OK(rnet->accept(*listen_id, recv_id)); });
  CHECK_OK(snet->connect(0, handle, send_id));
  acceptor.join();
}

// Single-stream failover: kill one data stream mid-message with an injected
// fault; the transfer must still complete with intact payload and the comm
// must keep working at reduced width. Exercises the NACK/FAILOVER marker
// protocol end to end (this is what the sanitizer lanes pin down).
static void TestStreamFailover(bool crc) {
  setenv("TPUNET_CRC", crc ? "1" : "0", 1);
  fprintf(stderr, "  failover: close on data stream 1 (crc=%d)\n", crc ? 1 : 0);
  auto snet = CreateBasicEngine();
  auto rnet = CreateBasicEngine();
  uint64_t send_id = 0, recv_id = 0, listen_id = 0;
  WireLoopback(snet.get(), rnet.get(), &send_id, &recv_id, &listen_id);

  CHECK(tpunet_c_fault_inject("stream=1:side=send:after_bytes=2M:action=close") == TPUNET_OK);
  const size_t kSize = 16 << 20;  // 2 chunks of 8MiB at nstreams=2
  std::vector<uint8_t> src(kSize), dst(kSize, 0);
  for (size_t i = 0; i < kSize; ++i) src[i] = static_cast<uint8_t>(i * 13 + 5);
  uint64_t sreq = 0, rreq = 0;
  CHECK_OK(rnet->irecv(recv_id, dst.data(), dst.size(), &rreq));
  CHECK_OK(snet->isend(send_id, src.data(), src.size(), &sreq));
  size_t got = 0;
  CHECK_OK(snet->wait(sreq, nullptr));
  CHECK_OK(rnet->wait(rreq, &got));
  CHECK(got == kSize);
  CHECK(memcmp(src.data(), dst.data(), kSize) == 0);
  CHECK(tpunet_c_fault_clear() == TPUNET_OK);

  // The comm survives at reduced width: a second transfer works.
  std::vector<uint8_t> src2(3 << 20, 0x5A), dst2(3 << 20, 0);
  CHECK_OK(rnet->irecv(recv_id, dst2.data(), dst2.size(), &rreq));
  CHECK_OK(snet->isend(send_id, src2.data(), src2.size(), &sreq));
  CHECK_OK(snet->wait(sreq, nullptr));
  CHECK_OK(rnet->wait(rreq, &got));
  CHECK(got == src2.size());
  CHECK(memcmp(src2.data(), dst2.data(), src2.size()) == 0);

  CHECK_OK(snet->close_send(send_id));
  CHECK_OK(rnet->close_recv(recv_id));
  CHECK_OK(rnet->close_listen(listen_id));
  unsetenv("TPUNET_CRC");
}

// Injected wire corruption with CRC on: the receiving REQUEST fails with a
// typed kCorruption error, the comm does NOT disconnect, and the next
// message flows clean.
static void TestCorruptionDetected() {
  setenv("TPUNET_CRC", "1", 1);
  fprintf(stderr, "  corruption: flipped byte under TPUNET_CRC=1\n");
  auto snet = CreateBasicEngine();
  auto rnet = CreateBasicEngine();
  uint64_t send_id = 0, recv_id = 0, listen_id = 0;
  WireLoopback(snet.get(), rnet.get(), &send_id, &recv_id, &listen_id);

  CHECK(tpunet_c_fault_inject("side=send:action=corrupt") == TPUNET_OK);
  std::vector<uint8_t> src(4 << 20, 0xA7), dst(4 << 20, 0);
  uint64_t sreq = 0, rreq = 0;
  CHECK_OK(rnet->irecv(recv_id, dst.data(), dst.size(), &rreq));
  CHECK_OK(snet->isend(send_id, src.data(), src.size(), &sreq));
  CHECK_OK(snet->wait(sreq, nullptr));
  Status rs = rnet->wait(rreq, nullptr);
  CHECK(!rs.ok());
  CHECK(rs.kind == ErrorKind::kCorruption);
  CHECK(rs.msg.find("CRC32C") != std::string::npos);
  CHECK(tpunet_c_fault_clear() == TPUNET_OK);

  // Not a disconnect: the same comm carries the next message.
  std::vector<uint8_t> src2(1 << 20, 0x3C), dst2(1 << 20, 0);
  size_t got = 0;
  CHECK_OK(rnet->irecv(recv_id, dst2.data(), dst2.size(), &rreq));
  CHECK_OK(snet->isend(send_id, src2.data(), src2.size(), &sreq));
  CHECK_OK(snet->wait(sreq, nullptr));
  CHECK_OK(rnet->wait(rreq, &got));
  CHECK(got == src2.size());
  CHECK(memcmp(src2.data(), dst2.data(), src2.size()) == 0);

  CHECK_OK(snet->close_send(send_id));
  CHECK_OK(rnet->close_recv(recv_id));
  CHECK_OK(rnet->close_listen(listen_id));
  unsetenv("TPUNET_CRC");
}

// Progress watchdog: a recv with no sender traffic gets a typed kTimeout
// within ~2x the window — never a hang (live-but-stuck peer model).
static void TestProgressWatchdog(const char* impl) {
  setenv("TPUNET_PROGRESS_TIMEOUT_MS", "300", 1);
  fprintf(stderr, "  watchdog: silent peer on %s times out typed\n", impl);
  auto make = [&]() {
    return strcmp(impl, "EPOLL") == 0 ? CreateEpollEngine() : CreateBasicEngine();
  };
  auto snet = make();
  auto rnet = make();
  uint64_t send_id = 0, recv_id = 0, listen_id = 0;
  WireLoopback(snet.get(), rnet.get(), &send_id, &recv_id, &listen_id);

  std::vector<uint8_t> dst(1 << 20, 0);
  uint64_t rreq = 0;
  CHECK_OK(rnet->irecv(recv_id, dst.data(), dst.size(), &rreq));
  auto t0 = std::chrono::steady_clock::now();
  Status rs = rnet->wait(rreq, nullptr);
  double dt = std::chrono::duration<double>(std::chrono::steady_clock::now() - t0).count();
  CHECK(!rs.ok());
  CHECK(rs.kind == ErrorKind::kTimeout);
  CHECK(dt < 5.0);  // 300ms window, generous slack for sanitizer lanes

  snet->close_send(send_id);  // comm already aborted by the watchdog
  rnet->close_recv(recv_id);
  rnet->close_listen(listen_id);
  unsetenv("TPUNET_PROGRESS_TIMEOUT_MS");
}

static void WaitDone(Net* net, uint64_t req, size_t* nbytes) {
  bool done = false;
  while (!done) {
    Status s = net->test(req, &done, nbytes);
    if (!s.ok()) {
      fprintf(stderr, "FAIL: test() error: %s\n", s.msg.c_str());
      ++g_failures;
      return;
    }
  }
}

// Loopback sweep between a sender engine and a receiver engine (they may be
// the same object, or different engines — the shared wire protocol makes
// BASIC and EPOLL interoperable, unlike the reference's BASIC/TOKIO pair).
static void TestEngineLoopback(Net* snet, Net* rnet, const char* label) {
  fprintf(stderr, "  loopback: %s\n", label);
  CHECK(snet->devices() >= 1);
  NetProperties props;
  CHECK_OK(snet->get_properties(0, &props));
  CHECK(!props.name.empty());

  SocketHandle handle;
  uint64_t listen_id = 0, send_id = 0, recv_id = 0;
  CHECK_OK(rnet->listen(0, &handle, &listen_id));
  std::thread acceptor([&] { CHECK_OK(rnet->accept(listen_id, &recv_id)); });
  CHECK_OK(snet->connect(0, handle, &send_id));
  acceptor.join();

  // Size sweep with payload verification; recv buffer deliberately larger.
  for (size_t size : {0ul, 1ul, 8ul, 100ul, 4096ul, 1048576ul, 5000000ul}) {
    std::vector<uint8_t> src(size), dst(size + 64, 0xAA);
    for (size_t i = 0; i < size; ++i) src[i] = static_cast<uint8_t>(i * 131 + 17);
    uint64_t sreq = 0, rreq = 0;
    CHECK_OK(rnet->irecv(recv_id, dst.data(), dst.size(), &rreq));
    CHECK_OK(snet->isend(send_id, src.data(), src.size(), &sreq));
    size_t sent = 0, got = 0;
    WaitDone(snet, sreq, &sent);
    WaitDone(rnet, rreq, &got);
    CHECK(sent == size);
    CHECK(got == size);  // true size from ctrl frame, not posted buffer size
    // size==0: an empty vector's data() may be null, which memcmp's
    // nonnull contract forbids (UBSAN) — nothing to compare anyway.
    CHECK(size == 0 || memcmp(src.data(), dst.data(), size) == 0);
    for (size_t i = size; i < dst.size(); ++i) CHECK(dst[i] == 0xAA);
  }

  // 8 in-flight requests per comm (NCCL_NET_MAX_REQUESTS, nccl_types.h:50).
  constexpr int kInflight = 8;
  constexpr size_t kMsg = 65536;
  std::vector<std::vector<uint8_t>> srcs(kInflight), dsts(kInflight);
  std::vector<uint64_t> sreqs(kInflight), rreqs(kInflight);
  for (int i = 0; i < kInflight; ++i) {
    srcs[i].assign(kMsg, static_cast<uint8_t>(i + 1));
    dsts[i].assign(kMsg, 0);
    CHECK_OK(rnet->irecv(recv_id, dsts[i].data(), kMsg, &rreqs[i]));
  }
  for (int i = 0; i < kInflight; ++i) {
    CHECK_OK(snet->isend(send_id, srcs[i].data(), kMsg, &sreqs[i]));
  }
  for (int i = 0; i < kInflight; ++i) {
    size_t n = 0;
    WaitDone(snet, sreqs[i], &n);
    WaitDone(rnet, rreqs[i], &n);
    CHECK(n == kMsg);
    CHECK(memcmp(srcs[i].data(), dsts[i].data(), kMsg) == 0);
  }

  CHECK_OK(snet->close_send(send_id));
  CHECK_OK(rnet->close_recv(recv_id));
  CHECK_OK(rnet->close_listen(listen_id));
}

// ---- Transport QoS (include/tpunet/qos.h) ---------------------------------

static void TestQosParsing() {
  QosConfig cfg;
  CHECK_OK(ParseQosWeights("latency=8,bulk=2,control=3", &cfg));
  CHECK(cfg.weights[0] == 8 && cfg.weights[1] == 2 && cfg.weights[2] == 3);
  CHECK_OK(ParseQosInflightBytes("latency=64K,bulk=4M,wire=1M", &cfg));
  CHECK(cfg.budgets[0] == (64u << 10) && cfg.budgets[1] == (4u << 20));
  CHECK(cfg.wire_window == (1u << 20));
  CHECK(!ParseQosWeights("express=1", &cfg).ok());
  CHECK(!ParseQosWeights("latency=0", &cfg).ok());
  CHECK(!ParseQosInflightBytes("bulk=lots", &cfg).ok());
  CHECK(!ParseQosInflightBytes("bulk", &cfg).ok());
  TrafficClass tc;
  CHECK(ParseTrafficClass("latency", &tc) && tc == TrafficClass::kLatency);
  CHECK(ParseTrafficClass("control", &tc) && tc == TrafficClass::kControl);
  CHECK(!ParseTrafficClass("express", &tc));
}

static void TestQosDrrGolden() {
  char out[512];
  // Strict control priority + weighted latency preemption over an
  // earlier-queued bulk chunk, one-chunk window.
  int32_t n = tpunet_c_qos_drr_golden(
      "latency=2,bulk=1", "wire=64K",
      "bulk:64K,latency:64K,control:64K,latency:64K", out, sizeof(out));
  CHECK(n > 0 && std::string(out) == "control,latency,latency,bulk");
  // Sustained contention: the 2:1 weighted interleave, then the drain.
  n = tpunet_c_qos_drr_golden(
      "latency=2,bulk=1", "wire=64K",
      "latency:64K,latency:64K,latency:64K,latency:64K,"
      "bulk:64K,bulk:64K,bulk:64K,bulk:64K",
      out, sizeof(out));
  CHECK(n > 0 &&
        std::string(out) ==
            "latency,latency,bulk,latency,latency,bulk,bulk,bulk");
  // Malformed specs are typed INVALID.
  CHECK(tpunet_c_qos_drr_golden("latency=0", "wire=64K", "bulk:1", out,
                                sizeof(out)) == TPUNET_ERR_INVALID);
  CHECK(tpunet_c_qos_drr_golden("", "", "bulk:1", out, sizeof(out)) ==
        TPUNET_ERR_INVALID);
}

static void TestQosSchedulerConcurrent() {
  // Thread-storm over one gated scheduler so tsan/asan see the DRR pump,
  // the ticket paths and admission under real interleavings. Every
  // acquired byte is released; the scheduler must end drained.
  QosConfig cfg;
  cfg.wire_window = 128 << 10;
  cfg.budgets[1] = 1 << 20;  // bulk admission budget
  QosScheduler qos(cfg);
  std::atomic<bool> aborted{false};
  std::atomic<uint64_t> granted{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&, t] {
      TrafficClass cls = (t % 2 == 0) ? TrafficClass::kLatency
                                      : TrafficClass::kBulk;
      for (int i = 0; i < 200; ++i) {
        uint64_t bytes = 16 << 10;
        if (t == 3) {
          // Ticket path (the EPOLL shape): try, then poll until granted.
          uint64_t ticket = 0;
          if (!qos.TryAcquireWire(cls, bytes, &ticket)) {
            while (!qos.PollTicket(ticket)) {
              std::this_thread::yield();
            }
          }
        } else {
          CHECK(qos.AcquireWire(cls, bytes, &aborted));
        }
        granted.fetch_add(bytes);
        qos.ReleaseWire(cls, bytes);
        uint64_t rec = 0;
        if (qos.AdmitMessage(cls, 4096, &rec).ok()) {
          qos.FinishMessage(cls, rec);
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  CHECK(granted.load() == 4ull * 200 * (16 << 10));
  CHECK(qos.AdmittedBytes(TrafficClass::kBulk) == 0);
  // Abort path: a waiter parked behind a held window must return false
  // promptly once its abort flag flips.
  uint64_t hold = 120 << 10;
  CHECK(qos.AcquireWire(TrafficClass::kBulk, hold, nullptr));
  std::atomic<bool> dead{false};
  std::thread waiter([&] {
    CHECK(!qos.AcquireWire(TrafficClass::kLatency, 64 << 10, &dead));
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(20));
  dead.store(true);
  waiter.join();
  qos.ReleaseWire(TrafficClass::kBulk, hold);
}

static void TestQosAdmissionBudget() {
  QosConfig cfg;
  cfg.budgets[static_cast<int>(TrafficClass::kBulk)] = 1 << 20;
  QosScheduler qos(cfg);
  uint64_t a = 0, b = 0, c = 0;
  // First message admits even oversize (liveness when idle).
  CHECK_OK(qos.AdmitMessage(TrafficClass::kBulk, 2 << 20, &a));
  CHECK(a == (2u << 20));
  // Over budget with bytes in flight: typed backpressure, nothing charged.
  Status st = qos.AdmitMessage(TrafficClass::kBulk, 1, &b);
  CHECK(st.kind == ErrorKind::kQosAdmission && b == 0);
  // Unbudgeted class is never charged.
  CHECK_OK(qos.AdmitMessage(TrafficClass::kLatency, 8 << 20, &c));
  CHECK(c == 0);
  qos.FinishMessage(TrafficClass::kBulk, a);
  CHECK_OK(qos.AdmitMessage(TrafficClass::kBulk, 1024, &b));
  CHECK(b == 1024);
  qos.FinishMessage(TrafficClass::kBulk, b);
}

int main() {
  TestChunkMath();
  TestBE();
  TestParse();
  TestSocketIO();
  TestInterfaces();
  TestCrc32c();
  TestFaultSpecParser();
  TestChurnScript();
  TestQosParsing();
  TestQosDrrGolden();
  TestQosSchedulerConcurrent();
  TestQosAdmissionBudget();
  {
    auto basic = CreateBasicEngine();
    TestEngineLoopback(basic.get(), basic.get(), "BASIC <-> BASIC");
  }
  {
    auto ep = CreateEpollEngine();
    TestEngineLoopback(ep.get(), ep.get(), "EPOLL <-> EPOLL");
  }
  {
    // Cross-engine interop both ways — the wire protocol is shared.
    auto basic = CreateBasicEngine();
    auto ep = CreateEpollEngine();
    TestEngineLoopback(basic.get(), ep.get(), "BASIC -> EPOLL");
    TestEngineLoopback(ep.get(), basic.get(), "EPOLL -> BASIC");
  }
  // Failure-containment layer (fault injection, CRC32C, failover, watchdog).
  TestStreamFailover(/*crc=*/false);
  TestStreamFailover(/*crc=*/true);
  TestCorruptionDetected();
  TestProgressWatchdog("BASIC");
  TestProgressWatchdog("EPOLL");
  {
    // CRC on, no faults: clean sweep still verifies (trailers negotiated).
    setenv("TPUNET_CRC", "1", 1);
    auto basic = CreateBasicEngine();
    auto ep = CreateEpollEngine();
    TestEngineLoopback(basic.get(), basic.get(), "BASIC <-> BASIC (CRC)");
    TestEngineLoopback(ep.get(), ep.get(), "EPOLL <-> EPOLL (CRC)");
    TestEngineLoopback(basic.get(), ep.get(), "BASIC -> EPOLL (CRC)");
    unsetenv("TPUNET_CRC");
  }
  if (g_failures == 0) {
    printf("OK: all C++ engine tests passed\n");
    return 0;
  }
  printf("FAILED: %d check(s)\n", g_failures);
  return 1;
}
