// Fuzz CheckPeerBootstrapBlob (wire.cc): the 16-byte bootstrap blob is the
// first peer-controlled payload the collectives handshake validates, and
// its error path stringifies enum bytes from the untrusted side. Input is
// split into our blob (first 16 bytes) and the peer's (next 16). The
// acceptance contract: the verdict is OK exactly when the config bytes
// (offsets 0..7 — everything but the host id) agree.
#include <cassert>
#include <cstring>

#include "../src/wire.h"
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  FuzzCanary(data, size);
  if (size < 2 * tpunet::kBootstrapBlobLen) return 0;
  const uint8_t* mine = data;
  const uint8_t* theirs = data + tpunet::kBootstrapBlobLen;
  tpunet::Status s = tpunet::CheckPeerBootstrapBlob(mine, theirs, 0, 1);
  bool config_match =
      std::memcmp(mine, theirs, tpunet::kBlobOffHostId) == 0;
  assert(s.ok() == config_match);
  return 0;
}
