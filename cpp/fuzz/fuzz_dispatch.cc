// Fuzz ParseDispatchTable (dispatch.cc): the busbw_sweep --emit-dispatch
// JSON is the one file-format parser in the tree — operator-supplied, so
// arbitrarily malformed. A malformed table must come back as a typed
// Invalid status, never a crash; an accepted table must contain only
// resolved (non-auto is not required, but in-range) entries.
#include <cassert>
#include <string>

#include "../src/dispatch.h"
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  FuzzCanary(data, size);
  std::string json(reinterpret_cast<const char*>(data), size);
  tpunet::DispatchTable table;
  tpunet::Status s = tpunet::ParseDispatchTable(json, &table);
  if (s.ok()) {
    for (const auto& e : table.entries) {
      // Both enums are uint8_t, so only the upper bound needs asserting.
      assert(static_cast<int>(e.algo) < tpunet::kCollAlgoCount);
      assert(static_cast<int>(e.coll) < tpunet::kCollKindCount);
      assert(e.world >= 0);  // 0 is the "any world" wildcard
    }
  }
  return 0;
}
