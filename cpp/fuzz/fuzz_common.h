// Shared scaffolding for the parser fuzz harnesses (docs/DESIGN.md
// "Protocol registry & model checking").
//
// Every harness defines LLVMFuzzerTestOneInput and compiles two ways:
//
//   * libFuzzer (`make -C cpp fuzz`): clang -fsanitize=fuzzer,address —
//     coverage-guided, corpora under cpp/fuzz/corpus/<target>/.
//   * standalone replay (`make -C cpp fuzz-smoke`): any compiler,
//     -DFUZZ_STANDALONE adds a main() that replays every file named on the
//     command line through the harness once. This is the ASan smoke lane
//     that runs where clang is absent, and the CI regression replayer.
//
// FuzzCanary() is the lane's RED self-proof: with TPUNET_FUZZ_CANARY set in
// the environment, an input starting with "CANARY!!" traps. CI replays
// cpp/fuzz/canary-input through one harness with the variable set and
// asserts the process DIES — a smoke lane that cannot detect a crash is
// green paint, not a sanitizer.
#ifndef TPUNET_FUZZ_COMMON_H_
#define TPUNET_FUZZ_COMMON_H_

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size);

inline void FuzzCanary(const uint8_t* data, size_t size) {
  if (size >= 8 && std::memcmp(data, "CANARY!!", 8) == 0 &&
      std::getenv("TPUNET_FUZZ_CANARY") != nullptr) {
    __builtin_trap();
  }
}

#ifdef FUZZ_STANDALONE
#include <cstdio>
#include <vector>

int main(int argc, char** argv) {
  int replayed = 0;
  for (int i = 1; i < argc; ++i) {
    FILE* f = std::fopen(argv[i], "rb");
    if (f == nullptr) {
      std::fprintf(stderr, "fuzz: cannot open %s\n", argv[i]);
      return 2;
    }
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> buf(n > 0 ? static_cast<size_t>(n) : 0);
    if (n > 0 && std::fread(buf.data(), 1, buf.size(), f) != buf.size()) {
      std::fprintf(stderr, "fuzz: short read on %s\n", argv[i]);
      std::fclose(f);
      return 2;
    }
    std::fclose(f);
    LLVMFuzzerTestOneInput(buf.data(), buf.size());
    ++replayed;
  }
  std::printf("fuzz: replayed %d inputs clean\n", replayed);
  return 0;
}
#endif  // FUZZ_STANDALONE

#endif  // TPUNET_FUZZ_COMMON_H_
