// Fuzz the two pure preamble parsers (wire.h): CheckWireMagic over the
// first 8 untrusted bytes a listener reads, ParsePreambleBytes over the
// full 48. Beyond crash-freedom, asserts the parser's own acceptance
// contract: an accepted preamble always satisfies the documented bounds.
#include <cassert>

#include "../src/wire.h"
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  FuzzCanary(data, size);
  if (size >= 8) {
    (void)tpunet::CheckWireMagic(data);
  }
  if (size >= tpunet::kPreambleBytes) {
    tpunet::Preamble p;
    tpunet::Status s = tpunet::ParsePreambleBytes(data, &p);
    if (s.ok()) {
      // The wire contract an accepting parse vouches for (wire.cc):
      // stream count bounded, stream id within the bundle, nonzero chunk
      // size, and nstreams == 0 only on an SHM hello.
      assert(p.nstreams <= tpunet::kMaxStreams);
      assert(p.stream_id <= p.nstreams);
      assert(p.min_chunksize != 0);
      assert(p.nstreams != 0 || (p.flags & tpunet::kPreambleFlagShm) != 0);
    }
  }
  return 0;
}
