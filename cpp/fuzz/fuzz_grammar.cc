// Fuzz every text micro-grammar an operator can feed the transport through
// the environment: the chaos script (TPUNET_FAULT — classic fault + churn +
// swap segments, fault.cc), the QoS weights/window specs
// (TPUNET_QOS_WEIGHTS / TPUNET_QOS_INFLIGHT_BYTES, qos.cc), and the lane
// spec (TPUNET_LANES, wire.cc). All four parsers are pure by contract;
// malformed input must come back as a typed Invalid status naming the
// offending token, never as a crash or an out-of-range config.
#include <cassert>
#include <string>
#include <vector>

#include "../src/fault.h"
#include "../src/wire.h"
#include "fuzz_common.h"
#include "tpunet/qos.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  FuzzCanary(data, size);
  std::string spec(reinterpret_cast<const char*>(data), size);

  tpunet::FaultSpec fault;
  bool has_fault = false;
  std::vector<tpunet::ChurnEvent> churn;
  std::vector<tpunet::SwapEvent> swap;
  (void)tpunet::ParseFaultScript(spec, &fault, &has_fault, &churn, &swap);

  tpunet::QosConfig qos;
  (void)tpunet::ParseQosWeights(spec, &qos);
  (void)tpunet::ParseQosInflightBytes(spec, &qos);

  std::vector<tpunet::LaneSpec> lanes;
  tpunet::Status s = tpunet::ParseLaneSpec(spec, &lanes);
  if (s.ok()) {
    for (const auto& l : lanes) {
      assert(l.weight >= 1 && l.weight <= tpunet::kMaxLaneWeight);
    }
  }
  return 0;
}
