// Fuzz DecodeCtrlFrame (wire.h), the single in-tree classifier of ctrl
// stream u64s. The decode must be TOTAL (every u64 lands in exactly one
// kind) and must round-trip through the matching Pack* helper — drift
// between the two is a protocol desync the type system cannot see.
#include <cassert>
#include <cstring>

#include "../src/wire.h"
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  FuzzCanary(data, size);
  for (size_t off = 0; off + 8 <= size; off += 8) {
    uint64_t frame;
    std::memcpy(&frame, data + off, 8);
    tpunet::CtrlFrameView v = tpunet::DecodeCtrlFrame(frame);
    switch (v.kind) {
      case tpunet::CtrlFrameKind::kLen:
        assert(frame < tpunet::kMaxCtrlLen);
        assert(v.len == frame);
        break;
      case tpunet::CtrlFrameKind::kNack:
        assert(tpunet::PackCtrlFrame(tpunet::kCtrlFrameNack, v.stream,
                                     v.arg) == frame);
        break;
      case tpunet::CtrlFrameKind::kFailover:
        assert(tpunet::PackCtrlFrame(tpunet::kCtrlFrameFailover, v.stream,
                                     v.arg) == frame);
        break;
      case tpunet::CtrlFrameKind::kWeights:
        assert(tpunet::PackWeightsFrame(v.nstreams, v.epoch) == frame);
        assert(v.nstreams == tpunet::WeightsFrameCount(frame));
        assert(v.epoch == tpunet::WeightsFrameEpoch(frame));
        break;
      case tpunet::CtrlFrameKind::kBogus: {
        uint8_t op = static_cast<uint8_t>(frame >> 56);
        assert(frame >= tpunet::kMaxCtrlLen);
        assert(op != tpunet::kCtrlFrameNack &&
               op != tpunet::kCtrlFrameFailover &&
               op != tpunet::kCtrlFrameWeights);
        break;
      }
    }
  }
  return 0;
}
