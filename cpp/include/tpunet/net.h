// tpunet — abstract point-to-point DCN transport interface.
//
// TPU-native re-design of the reference transport trait
// (reference: src/interface.rs:34-74 `trait Net`, :3-11 `BaguaNetError`,
// :13-22 `NCCLNetProperties`, :24-27 `SocketHandle`). Semantics match the
// reference: device enumeration, listen/connect/accept rendezvous, non-blocking
// isend/irecv returning request ids, `test()` polling for completion, close.
// Engines must tolerate >= 8 in-flight requests per comm (reference:
// cc/nccl_types.h:50 NCCL_NET_MAX_REQUESTS).
#ifndef TPUNET_NET_H_
#define TPUNET_NET_H_

#include <netinet/in.h>
#include <sys/socket.h>

#include <cstddef>
#include <cstdint>
#include <memory>
#include <string>
#include <thread>

namespace tpunet {

// Error taxonomy mirrors reference interface.rs:3-11 {IOError, TCPError,
// InnerError}, plus kInvalidArgument so programmer errors (stale/unknown ids,
// bad device index) are distinguishable from transport failures at the ABI,
// plus the failure-model kinds (docs/DESIGN.md "Failure model"):
//   kCorruption — a per-chunk CRC32C mismatch (TPUNET_CRC=1): the payload is
//     wrong but the stream framing is intact, so the REQUEST fails while the
//     comm stays usable (not a disconnect).
//   kTimeout — the progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS) saw a
//     request move zero bytes for a full window: a live-but-stuck peer,
//     classified upstream like a dead one (elastic rebuild).
//   kVersion — the peer speaks a different tpunet wire framing version
//     (preamble magic prefix matched, version byte did not).
//   kCodec — the ranks of a collective group disagree on the wire
//     compression codec (TPUNET_WIRE_DTYPE / wire_dtype); raised at
//     communicator wiring time by the codec-byte handshake, before any
//     data could be mis-decoded (docs/DESIGN.md "Compressed collectives").
//   kQosAdmission — QoS admission control rejected a send: the traffic
//     class's in-flight byte budget (TPUNET_QOS_INFLIGHT_BYTES) is full.
//     Pure backpressure — NOTHING was enqueued; retry after in-flight work
//     drains (docs/DESIGN.md "Transport QoS").
enum class ErrorKind : int32_t {
  kOk = 0,
  kIOError = 1,
  kTCPError = 2,
  kInnerError = 3,
  kInvalidArgument = 4,
  kCorruption = 5,
  kTimeout = 6,
  kVersion = 7,
  kCodec = 8,
  kQosAdmission = 9,
};

struct Status {
  ErrorKind kind = ErrorKind::kOk;
  std::string msg;

  bool ok() const { return kind == ErrorKind::kOk; }
  static Status Ok() { return Status{}; }
  static Status IO(std::string m) { return Status{ErrorKind::kIOError, std::move(m)}; }
  static Status TCP(std::string m) { return Status{ErrorKind::kTCPError, std::move(m)}; }
  static Status Inner(std::string m) { return Status{ErrorKind::kInnerError, std::move(m)}; }
  static Status Invalid(std::string m) { return Status{ErrorKind::kInvalidArgument, std::move(m)}; }
  static Status Corruption(std::string m) { return Status{ErrorKind::kCorruption, std::move(m)}; }
  static Status Timeout(std::string m) { return Status{ErrorKind::kTimeout, std::move(m)}; }
  static Status Version(std::string m) { return Status{ErrorKind::kVersion, std::move(m)}; }
  static Status Codec(std::string m) { return Status{ErrorKind::kCodec, std::move(m)}; }
  static Status QosAdmission(std::string m) {
    return Status{ErrorKind::kQosAdmission, std::move(m)};
  }
};

// Reference: interface.rs:13-22 NCCLNetProperties.
struct NetProperties {
  std::string name;
  std::string pci_path;
  uint64_t guid = 0;
  int32_t ptr_support = 1;  // host memory only (NCCL_PTR_HOST)
  int32_t speed_mbps = 10000;
  int32_t port = 0;
  int32_t max_comms = 65536;  // reference: nthread_per_socket_backend.rs:100
};

// Opaque rendezvous handle: a serialized sockaddr, must fit the reference's
// 64-byte NCCL handle budget (reference: cc/nccl_types.h:44
// NCCL_NET_HANDLE_MAXSIZE=64, src/lib.rs:121-124 SocketHandleC).
constexpr size_t kHandleSize = 64;
struct SocketHandle {
  sockaddr_storage addr = {};  // only first kHandleSize bytes travel the wire
  socklen_t addrlen = 0;
};
static_assert(sizeof(sockaddr_in6) <= kHandleSize, "handle must fit sockaddr");

// Abstract transport. All ids are process-local opaque tokens. Thread-safety:
// all methods may be called concurrently from different threads; `accept`
// blocks until a peer connects.
class Net {
 public:
  virtual ~Net() = default;

  virtual int32_t devices() = 0;
  virtual Status get_properties(int32_t dev, NetProperties* props) = 0;

  // Bind a listening socket on device `dev`; return the rendezvous handle the
  // caller ships out-of-band to the sender, plus a listen-comm id for accept().
  virtual Status listen(int32_t dev, SocketHandle* handle, uint64_t* listen_comm) = 0;
  // Establish the multi-stream connection bundle to a remote handle
  // (nstreams data conns + 1 ctrl conn; see wire protocol in basic_engine.cc).
  virtual Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) = 0;
  // Accept one sender's bundle on a listen comm. Blocks.
  virtual Status accept(uint64_t listen_comm, uint64_t* recv_comm) = 0;

  // Post a send/recv; returns immediately with a request id polled via test().
  // The caller must keep `data` alive/pinned until test() reports done
  // (reference contract: src/lib.rs:251,279).
  virtual Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) = 0;
  // The posted recv buffer may be larger than the incoming message; the actual
  // size comes from the ctrl-stream length frame and is reported by test().
  virtual Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) = 0;
  // Poll a request. On done=true the request id is consumed (freed).
  virtual Status test(uint64_t request, bool* done, size_t* nbytes) = 0;
  // Block until the request settles, then consume it like a done test().
  // Engines override with a condvar park (a test() poll loop starves the
  // worker threads of CPU on small hosts); the base fallback polls.
  virtual Status wait(uint64_t request, size_t* nbytes) {
    bool done = false;
    while (true) {
      Status st = test(request, &done, nbytes);
      if (!st.ok() || done) return st;
      std::this_thread::yield();
    }
  }

  virtual Status close_send(uint64_t send_comm) = 0;
  virtual Status close_recv(uint64_t recv_comm) = 0;
  virtual Status close_listen(uint64_t listen_comm) = 0;

  // QoS traffic class carried by every comm this engine CONNECTS (the
  // class nibble rides the preamble flags word, so the far side's recv
  // comm adopts it — sender's class wins, like nstreams/min_chunksize).
  // Values are TrafficClass ints (qos.h: 0 latency, 1 bulk, 2 control);
  // out-of-range is clamped to bulk. Set it before connect(); default is
  // TPUNET_TRAFFIC_CLASS (bulk). docs/DESIGN.md "Transport QoS".
  virtual void set_traffic_class(int32_t cls) { (void)cls; }
  virtual int32_t traffic_class() const { return 1; /* bulk */ }
};

// Factory. Engine selected by env TPUNET_IMPLEMENT in {"BASIC" (default),
// "EPOLL"} (reference seam: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT). With
// TPUNET_SHM=1 the selected engine is additionally fronted by the
// shared-memory engine: same-host peers (HostId() equality, verified in
// the SHM hello handshake) move payloads through a mmap'd per-pair ring
// segment; everything else falls through to `inner` transparently.
std::unique_ptr<Net> CreateEngine();
std::unique_ptr<Net> CreateBasicEngine();
std::unique_ptr<Net> CreateEpollEngine();
std::unique_ptr<Net> CreateShmEngine(std::unique_ptr<Net> inner);

}  // namespace tpunet

#endif  // TPUNET_NET_H_
