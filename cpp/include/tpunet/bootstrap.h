// tpunet bootstrap — out-of-band rendezvous for collective groups.
//
// The reference relied on NCCL's bootstrap to ship its 64-byte listen handle
// between ranks (SURVEY §2.2 step 1; reference README.md:20-45 runs under
// mpirun). tpunet owns this layer: a tiny TCP coordinator (rank 0) that
// supports fixed-size AllGather rounds, used to exchange transport handles
// when building communicators, plus a barrier.
#ifndef TPUNET_BOOTSTRAP_H_
#define TPUNET_BOOTSTRAP_H_

#include <cstddef>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "tpunet/net.h"

namespace tpunet {

class Bootstrap {
 public:
  virtual ~Bootstrap() = default;

  // coordinator: "host:port". Rank 0 binds and serves it; other ranks
  // connect with retry until TPUNET_BOOTSTRAP_TIMEOUT_MS (default 120s).
  static Status Create(const std::string& coordinator, int rank, int world_size,
                       std::unique_ptr<Bootstrap>* out);

  // Gather `len` bytes from every rank, in rank order, into all (world*len
  // bytes). Every rank must pass the same len. Collective: all ranks call.
  virtual Status AllGather(const void* mine, size_t len, std::vector<uint8_t>* all) = 0;

  // All ranks synchronize (one empty AllGather round).
  virtual Status Barrier() = 0;

  virtual int rank() const = 0;
  virtual int world_size() const = 0;
};

}  // namespace tpunet

#endif  // TPUNET_BOOTSTRAP_H_
