// tpunet transport QoS: traffic classes, weighted-fair wire scheduling, and
// per-tenant admission control (docs/DESIGN.md "Transport QoS").
//
// A production host runs COMPETING tenants on one engine process — bulk
// gradient AllReduce, latency-critical KV-block shipping, control traffic —
// and the paper's per-stream fairness says nothing about isolation BETWEEN
// them. This layer adds it in three pieces:
//
//   * Every comm carries a TRAFFIC CLASS (latency | bulk | control),
//     advertised in the connect preamble (sender's class wins on the far
//     side, like nstreams/min_chunksize) and negotiated across a collective
//     group at wiring time (a disagreement fails every rank typed, the
//     codec/algo-handshake stance).
//   * A process-wide WIRE SCHEDULER replaces first-come chunk dispatch when
//     a wire window is configured (TPUNET_QOS_INFLIGHT_BYTES wire=<bytes>):
//     each data chunk must hold wire credit before its bytes enter the
//     kernel, credit is granted by deficit round-robin over the per-class
//     queues (quantum = TPUNET_QOS_WEIGHTS x 64KiB) with STRICT priority
//     for the control class, and the shared window bounds how much bulk can
//     sit in kernel socket buffers ahead of a latency chunk — the p99
//     queue-wait bound the two-tenant bench gates on. window 0 (default)
//     disables the gate entirely: grants are unconditional and free.
//   * ADMISSION CONTROL: per-class in-flight message-byte budgets
//     (TPUNET_QOS_INFLIGHT_BYTES latency=/bulk=/control=). A send posted
//     over its class budget fails IMMEDIATELY with the typed
//     kQosAdmission (-8, QosAdmissionError) backpressure error — nothing
//     is enqueued, the caller (e.g. the serve router) retries. A class with
//     zero bytes in flight always admits one message, so a message larger
//     than its budget cannot be rejected forever.
//
// Composition with lane striping (docs/DESIGN.md §1c): the weighted stripe
// scheduler changes only WHICH stream a chunk rides, never chunk sizes or
// counts, so wire credit is still acquired per chunk for payload+CRC bytes
// and the per-class budgets see identical charge sequences whether a comm
// is uniform or lane-weighted. DRR grant order and lane weighting compose
// orthogonally: QoS decides WHEN a class's chunk may enter the kernel,
// lanes decide WHERE it goes.
//
// Observability: every decision feeds tpunet_qos_bytes_total{class,dir},
// tpunet_qos_queue_wait_us{class} and tpunet_qos_preempts_total{class}
// (metrics.cc), all telemetry.reset()-able.
#ifndef TPUNET_QOS_H_
#define TPUNET_QOS_H_

#include <atomic>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <string>

#include "tpunet/mutex.h"
#include "tpunet/net.h"

namespace tpunet {

// Values are wire/ABI: the class nibble rides the connect preamble's flags
// word and the collective bootstrap blob, and the ints cross the C ABI.
enum class TrafficClass : uint8_t { kLatency = 0, kBulk = 1, kControl = 2 };
constexpr int kTrafficClassCount = 3;

// "latency" / "bulk" / "control" <-> TrafficClass. Parse returns false on
// unknown names.
bool ParseTrafficClass(const std::string& name, TrafficClass* out);
const char* TrafficClassName(TrafficClass c);

// DRR quantum unit: one weight point buys this many wire bytes per round.
constexpr uint64_t kQosQuantumBytes = 64 << 10;

struct QosConfig {
  // DRR weights (TPUNET_QOS_WEIGHTS "latency=8,bulk=1"). The control class
  // is strict-priority, so its weight is accepted but never consulted.
  uint64_t weights[kTrafficClassCount] = {8, 1, 1};
  // Admission budgets: max in-flight posted-send bytes per class
  // (TPUNET_QOS_INFLIGHT_BYTES "latency=64M,bulk=256M"). 0 = unlimited.
  uint64_t budgets[kTrafficClassCount] = {0, 0, 0};
  // Shared wire window (TPUNET_QOS_INFLIGHT_BYTES "wire=4M"): max bytes of
  // granted-but-unwritten chunk credit across ALL classes. 0 = gate off.
  uint64_t wire_window = 0;
};

// Grammar: comma-separated key=value. Weights: latency|bulk|control = int
// >= 1. Budgets: latency|bulk|control|wire = size with optional K/M/G
// suffix (the fault-spec size grammar). Unknown keys / malformed values
// return kInvalidArgument naming the token — Config.from_env() is the loud
// Python-side gate; the native singleton warns to stderr and keeps its
// defaults rather than crashing engine creation.
Status ParseQosWeights(const std::string& spec, QosConfig* cfg);
Status ParseQosInflightBytes(const std::string& spec, QosConfig* cfg);

// Process-wide scheduler. One instance arbitrates every engine in the
// process — the whole point is cross-tenant isolation, and tenants share
// the process's NIC, not an engine object.
class QosScheduler {
 public:
  explicit QosScheduler(const QosConfig& cfg);
  ~QosScheduler();

  // Env-configured singleton (TPUNET_QOS_WEIGHTS / TPUNET_QOS_INFLIGHT_BYTES
  // read once, at first use). Leaked on purpose: engines may release credit
  // during static teardown.
  static QosScheduler& Get();

  const QosConfig& config() const { return cfg_; }
  bool wire_gate_enabled() const { return cfg_.wire_window > 0; }

  // ---- Admission control (send posting time) ------------------------------
  // Charge `nbytes` against the class budget, or fail typed kQosAdmission
  // WITHOUT recording anything. *recorded is what FinishMessage must later
  // return (0 when the class is unbudgeted — the uncharged fast path).
  // A class with zero in-flight bytes always admits (oversize liveness).
  Status AdmitMessage(TrafficClass cls, uint64_t nbytes, uint64_t* recorded);
  void FinishMessage(TrafficClass cls, uint64_t nbytes);
  uint64_t AdmittedBytes(TrafficClass cls) const;

  // ---- Wire-credit gate (chunk dispatch time) -----------------------------
  // Blocking acquire (BASIC stream workers): parks until the DRR pump
  // grants `nbytes` of wire credit. Returns false — with nothing held —
  // when *aborted flips while waiting (comm poisoned/shut down), checked
  // every 50ms. Records the wait into the class queue-wait histogram.
  bool AcquireWire(TrafficClass cls, uint64_t nbytes,
                   const std::atomic<bool>* aborted);
  // Nonblocking acquire (EPOLL event loop): true = credit held (ticket
  // untouched). false = a ticket was enqueued into the DRR queues; poll it
  // with PollTicket (true = credit now held, ticket consumed) and cancel it
  // with CancelTicket if the segment dies first. With the gate disabled,
  // always true.
  bool TryAcquireWire(TrafficClass cls, uint64_t nbytes, uint64_t* ticket);
  bool PollTicket(uint64_t ticket);
  void CancelTicket(uint64_t ticket);
  // Return `nbytes` of credit (after the chunk's bytes reached the kernel,
  // or on any failure path of a holder).
  void ReleaseWire(TrafficClass cls, uint64_t nbytes);

  // Human-readable config + live-state echo (tpunet_c_qos_state): lets
  // Python pin that env parsing and the native view agree.
  std::string StateText();

  // DRR arithmetic golden (tpunet_c_qos_drr_golden): simulate the grant
  // order for a queue of chunks under `weights_spec` and a wire window from
  // `window_spec` ("wire=64K"). `chunks` is "class:bytes,class:bytes,..."
  // enqueued in order with the window initially full occupied by nothing;
  // completions retire in grant order. Returns the comma-separated class
  // grant order, or empty with *err set on a malformed spec. Pure
  // arithmetic — no threads, no clocks — so tests can pin the scheduler's
  // exact weighted interleave.
  static std::string DrrGolden(const std::string& weights_spec,
                               const std::string& window_spec,
                               const std::string& chunks, std::string* err);

 private:
  struct Waiter {
    TrafficClass cls = TrafficClass::kBulk;
    uint64_t bytes = 0;
    uint64_t seq = 0;     // global FIFO order (preemption accounting)
    uint64_t ticket = 0;  // 0 = blocking waiter (condvar), else EPOLL ticket
    bool granted = false;
  };

  // Grant every waiter the window + DRR arithmetic allows right now.
  void PumpLocked() REQUIRES(mu_);
  bool RoomLocked(uint64_t nbytes) const REQUIRES(mu_);
  void GrantFrontLocked(int cls) REQUIRES(mu_);
  void RemoveWaiterLocked(Waiter* w) REQUIRES(mu_);

  const QosConfig cfg_;
  // Telemetry hooks are suppressed in DrrGolden's throwaway instances so
  // simulations don't pollute the process counters.
  bool report_ = true;

  Mutex mu_;  // leaf: nothing is acquired under it (telemetry is lock-free)
  CondVar cv_;
  std::deque<Waiter*> queues_[kTrafficClassCount] GUARDED_BY(mu_);
  // Ticket storage (EPOLL waiters outlive the Try call); blocking waiters
  // live on their caller's stack.
  std::map<uint64_t, std::unique_ptr<Waiter>> tickets_ GUARDED_BY(mu_);
  uint64_t next_seq_ GUARDED_BY(mu_) = 1;
  uint64_t next_ticket_ GUARDED_BY(mu_) = 1;
  uint64_t wire_inflight_ GUARDED_BY(mu_) = 0;
  uint64_t deficit_[kTrafficClassCount] GUARDED_BY(mu_) = {0, 0, 0};
  int drr_next_ GUARDED_BY(mu_) = 0;   // latency/bulk rotation pointer
  int drr_turn_ GUARDED_BY(mu_) = -1;  // class mid-turn (-1 = pick next)
  // DrrGolden grant log (null in the live singleton).
  std::deque<std::pair<int, uint64_t>>* grant_log_ GUARDED_BY(mu_) = nullptr;

  std::atomic<uint64_t> admitted_[kTrafficClassCount] = {};
};

}  // namespace tpunet

#endif  // TPUNET_QOS_H_
