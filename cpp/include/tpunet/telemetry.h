// tpunet observability: per-request tracing + transport metrics.
//
// TPU-native re-design of the reference's OpenTelemetry stack (SURVEY §5;
// reference: nthread_per_socket_backend.rs:108-212): no third-party SDK,
// one in-process singleton the engines feed through a decorator.
//
// Tracing (reference: root span "BaguaNet-{rank}" nthread:132-137, child
// span per isend/irecv with id+nbytes attrs :529-538, ended at test()
// completion :606): spans are buffered and flushed as Chrome-trace JSON
// (loadable in Perfetto) to TPUNET_TRACE_DIR/tpunet-trace-rank<R>.json.
// Env-gated exactly like the reference (rank 0-7 AND the address var set,
// nthread:108-130).
//
// Metrics (reference: isend/irecv_nbytes histograms with boundaries
// [16,1024,4096,1048576] nthread:139-180, bytes/s observers :343-348,
// in-flight gauge tokio:184-190): counters are always-on atomics; a push
// thread POSTs Prometheus text to a pushgateway at TPUNET_METRICS_ADDR
// ("user:pass@host:port", basic auth, reference utils.rs:180-198) every
// TPUNET_METRICS_INTERVAL_MS (default 1000 — the reference pushed every
// 200 µs, nthread:183-211, which SURVEY flags as a bug we do not copy).
#ifndef TPUNET_TELEMETRY_H_
#define TPUNET_TELEMETRY_H_

#include <cstdint>
#include <memory>
#include <string>

#include "tpunet/net.h"

namespace tpunet {

// Histogram bucket upper bounds in bytes (reference: nthread:139-141), plus
// a +Inf bucket.
constexpr uint64_t kHistBounds[4] = {16, 1024, 4096, 1048576};
constexpr int kHistBuckets = 5;

// Per-stream byte counters cap (streams beyond this lump into the last slot;
// default nstreams is 2-8, so 32 covers every sane config).
constexpr int kMaxStreamStats = 32;

// Fault-injection action slots for tpunet_faults_injected_total (indices
// match FaultAction in src/fault.h; 0 is unused).
constexpr int kFaultActionSlots = 5;

struct MetricsSnapshot {
  uint64_t isend_count = 0;
  uint64_t irecv_count = 0;
  uint64_t isend_bytes = 0;
  uint64_t irecv_bytes = 0;
  uint64_t isend_hist[kHistBuckets] = {0};
  uint64_t irecv_hist[kHistBuckets] = {0};
  uint64_t inflight = 0;        // requests posted but not yet test()ed done
  uint64_t failed_requests = 0;
  // Failure-containment counters (docs/DESIGN.md "Failure model"):
  // injected faults by action, data-stream failovers survived, and CRC32C
  // chunk mismatches detected.
  uint64_t faults_injected[kFaultActionSlots] = {0};
  uint64_t stream_failovers = 0;
  uint64_t crc_errors = 0;
  // Bytes moved per data-stream index, all comms aggregated — the observable
  // form of the rotating-cursor fairness property (the reference exposed
  // per-stream effective-time observers instead, nthread:343-348).
  uint64_t stream_tx_bytes[kMaxStreamStats] = {0};
  uint64_t stream_rx_bytes[kMaxStreamStats] = {0};
  double uptime_s = 0;          // for bytes/s derivation
};

class Telemetry {
 public:
  static Telemetry& Get();

  // Always-on counter hooks (lock-free). Span tracking only when tracing.
  // `owner` disambiguates engine-local request ids across Net instances.
  void OnRequestStart(uint64_t owner, bool is_send, uint64_t comm, uint64_t req,
                      uint64_t nbytes);
  void OnRequestDone(uint64_t owner, uint64_t req, bool failed);
  // Engine hot-path hook: `nbytes` moved on data-stream `stream_idx`
  // (relaxed atomic add; indices >= kMaxStreamStats clamp to the last slot).
  void OnStreamBytes(bool is_send, uint64_t stream_idx, uint64_t nbytes);
  // Failure-containment hooks (cold paths). `action` indexes FaultAction.
  void OnFaultInjected(int action);
  void OnStreamFailover();
  void OnCrcError();

  MetricsSnapshot Snapshot() const;
  // Prometheus text exposition of the snapshot (also what the push thread
  // sends).
  std::string PrometheusText() const;

  bool tracing_enabled() const { return trace_enabled_; }
  // Write buffered spans to the trace file; called on buffer pressure, from
  // tpunet_c_trace_flush(), and at process exit (atexit — the singleton is
  // leaked so its destructor never runs). Returns false when the trace file
  // could not be written (spans are dropped); true on success or when tracing
  // is disabled.
  bool FlushTrace();
  // Stop the push thread and flush; atexit hook (safe to call repeatedly).
  void ShutdownForExit();

  ~Telemetry();

 private:
  Telemetry();
  struct Impl;
  std::unique_ptr<Impl> impl_;
  bool trace_enabled_ = false;
};

// Decorator installed by CreateEngine() around the selected engine so both
// engines (and any future one) report identically.
std::unique_ptr<Net> WrapWithTelemetry(std::unique_ptr<Net> inner);

}  // namespace tpunet

#endif  // TPUNET_TELEMETRY_H_
