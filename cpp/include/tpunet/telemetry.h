// tpunet observability: per-request tracing + transport metrics + deep
// per-stream TCP introspection.
//
// TPU-native re-design of the reference's OpenTelemetry stack (SURVEY §5;
// reference: nthread_per_socket_backend.rs:108-212): no third-party SDK,
// one in-process singleton the engines feed through a decorator.
//
// Tracing (reference: root span "BaguaNet-{rank}" nthread:132-137, child
// span per isend/irecv with id+nbytes attrs :529-538, ended at test()
// completion :606): spans are buffered and flushed as VALID Chrome-trace
// JSON (json.load-able, Perfetto-loadable) to
// TPUNET_TRACE_DIR/tpunet-trace-rank<R>.json. Env-gated like the reference
// (rank 0-7 AND the dir var set, nthread:108-130), or enabled at runtime via
// tpunet_c_trace_set_dir() / tpunet.telemetry.profile(). Besides request
// spans the file carries collective phase spans tagged
// (comm_id, coll_seq, phase) — the cross-rank join key merge_traces() uses
// to align per-rank files into one timeline — and straggler instant events.
//
// Metrics (reference: isend/irecv_nbytes histograms with boundaries
// [16,1024,4096,1048576] nthread:139-180, bytes/s observers :343-348,
// in-flight gauge tokio:184-190): counters are always-on atomics; a push
// thread PUTs Prometheus text to a pushgateway at TPUNET_METRICS_ADDR every
// TPUNET_METRICS_INTERVAL_MS (default 1000), and an on-demand scrape
// listener serves the same exposition at http://:TPUNET_METRICS_PORT/metrics.
//
// TCP introspection: a rate-limited getsockopt(TCP_INFO) sampler on the
// engines' data paths (TPUNET_TCPINFO_INTERVAL_MS per stream slot, default
// 100, 0 disables) exports per-stream RTT / retransmit / cwnd /
// delivery-rate gauges, a Jain's-fairness gauge over windowed per-stream
// bytes, and a straggler detector (smoothed RTT > k× the median across
// active streams -> tpunet_straggler_events_total + a trace instant event).
#ifndef TPUNET_TELEMETRY_H_
#define TPUNET_TELEMETRY_H_

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

#include "tpunet/net.h"

namespace tpunet {

// Histogram bucket upper bounds in bytes (reference: nthread:139-141), plus
// a +Inf bucket.
constexpr uint64_t kHistBounds[4] = {16, 1024, 4096, 1048576};
constexpr int kHistBuckets = 5;

// Stage-latency histogram bounds in microseconds (+Inf bucket appended):
// post->first-wire-byte (queue), first->last wire byte (wire), and
// post->completion (total) land in these.
constexpr uint64_t kStageHistBounds[7] = {50, 200, 1000, 5000, 20000, 100000, 1000000};
constexpr int kStageHistBuckets = 8;

// Per-stream byte counters cap (streams beyond this lump into the last slot;
// default nstreams is 2-8, so 32 covers every sane config).
constexpr int kMaxStreamStats = 32;

// Fault-injection action slots for tpunet_faults_injected_total (indices
// match FaultAction in src/fault.h; 0 is unused).
constexpr int kFaultActionSlots = 5;

// Serving-tier queue-depth gauge slots (tpunet_serve_queue_depth{tier=...}):
// router admission queue, prefill backlog, decode slots+pending.
constexpr int kServeTierCount = 3;

// Elastic-churn rewire phases (tpunet_rewire_duration_us{phase=...}):
// detect, quiesce, rendezvous, rewire — the recovery pipeline's stages
// (docs/DESIGN.md "Elastic churn").
constexpr int kRewirePhaseCount = 4;

// Membership-churn event kinds (tpunet_churn_events_total{kind=...}):
// kill, join, shrink, grow, readmit.
constexpr int kChurnKindCount = 5;

// Live weight-swap phases (tpunet_weight_swap_duration_us{phase=...}):
// announce, broadcast, verify, flip — the publication pipeline's stages
// (docs/DESIGN.md "Live weight updates").
constexpr int kSwapPhaseCount = 4;

// Weight-swap event kinds (tpunet_swap_events_total{kind=...}):
// publish, commit, abort, retry, mismatch.
constexpr int kSwapKindCount = 5;

// QoS traffic-class slots (latency, bulk, control — TrafficClass in qos.h;
// kept as a bare count here so telemetry.h need not include qos.h).
constexpr int kQosClassCount = 3;

// Last getsockopt(TCP_INFO) sample for one stream slot. When several comms
// share a stream index the last-sampled socket wins — gauges describe "a
// live connection at this stream position", which is what stream-skew
// triage needs (per-comm split would be unbounded cardinality).
struct StreamTcpSample {
  uint64_t rtt_us = 0;            // tcpi_rtt
  uint64_t srtt_us = 0;           // EWMA over samples (straggler detector input)
  uint64_t retrans_total = 0;     // tcpi_total_retrans of the sampled socket
  uint64_t cwnd = 0;              // tcpi_snd_cwnd (segments)
  uint64_t delivery_rate_bps = 0; // tcpi_delivery_rate * 8 (0 on old kernels)
  uint64_t min_rtt_us = 0;        // tcpi_min_rtt (0 on old kernels) — the
                                  // per-path RTT floor the static
                                  // TPUNET_STRAGGLER_MIN_RTT_US knob
                                  // approximates; observable per stream so
                                  // heterogeneous-path floors stop being a
                                  // one-size env guess
  bool sampled = false;
};

struct StageHist {
  uint64_t buckets[kStageHistBuckets] = {0};
  uint64_t sum_us = 0;
  uint64_t count = 0;
};

struct MetricsSnapshot {
  uint64_t isend_count = 0;
  uint64_t irecv_count = 0;
  uint64_t isend_bytes = 0;
  uint64_t irecv_bytes = 0;
  uint64_t isend_hist[kHistBuckets] = {0};
  uint64_t irecv_hist[kHistBuckets] = {0};
  uint64_t inflight = 0;        // requests posted but not yet test()ed done
  uint64_t failed_requests = 0;
  // Failure-containment counters (docs/DESIGN.md "Failure model"):
  // injected faults by action, data-stream failovers survived, and CRC32C
  // chunk mismatches detected.
  uint64_t faults_injected[kFaultActionSlots] = {0};
  uint64_t stream_failovers = 0;
  uint64_t crc_errors = 0;
  // Bytes moved per data-stream index, all comms aggregated — the observable
  // form of the rotating-cursor fairness property (the reference exposed
  // per-stream effective-time observers instead, nthread:343-348).
  uint64_t stream_tx_bytes[kMaxStreamStats] = {0};
  uint64_t stream_rx_bytes[kMaxStreamStats] = {0};
  // QoS accounting (docs/DESIGN.md "Transport QoS"): bytes per traffic
  // class and direction (the receiver learns the class from the preamble
  // nibble), time chunks waited for wire credit in the DRR scheduler, and
  // grants that jumped an older waiter of another class.
  uint64_t qos_bytes[kQosClassCount][2] = {};  // [class][tx=0, rx=1]
  StageHist qos_wait_us[kQosClassCount];
  uint64_t qos_preempts[kQosClassCount] = {0};
  // Deep-observability additions (docs/DESIGN.md "Observability"):
  StreamTcpSample stream_tcp_tx[kMaxStreamStats];
  StreamTcpSample stream_tcp_rx[kMaxStreamStats];
  // Jain's index over windowed per-stream bytes, per traffic class — the
  // paper's per-stream fairness claim reported WITHIN a class, so bulk's
  // deliberate deprioritization can't read as striping unfairness.
  double fairness_tx[kQosClassCount] = {1.0, 1.0, 1.0};
  double fairness_rx[kQosClassCount] = {1.0, 1.0, 1.0};
  uint64_t straggler_events = 0;
  StageHist req_queue_us;       // post -> first wire byte
  StageHist req_wire_us;        // first -> last wire byte
  StageHist req_total_us;       // post -> completion
  // Lane-striping accounting (docs/DESIGN.md "Lanes & adaptive striping"):
  // the stripe scheduler's current per-lane weight and measured service
  // rate (last writer wins across comms — like the TCP slots, the gauges
  // describe "a live lane at this index"), payload bytes per lane and
  // direction, and weight-vector epochs published (re-stripe events).
  uint64_t lane_weight[kMaxStreamStats] = {0};
  uint64_t lane_rate_bps[kMaxStreamStats] = {0};
  uint64_t lane_bytes[kMaxStreamStats][2] = {};  // [lane][tx=0, rx=1]
  uint64_t restripe_events = 0;
  // Intra-host shared-memory transport (docs/DESIGN.md "Intra-host shared
  // memory"): payload bytes moved through SHM ring segments per direction
  // (deliberately NOT folded into the TCP stream/QoS byte counters, so
  // "the intra-host stage moved zero TCP bytes" is provable straight off
  // the counters) and futex wake syscalls issued by the ring protocol
  // (bytes/wakeup is the ring's syscalls/MiB analogue).
  uint64_t shm_bytes[2] = {0, 0};  // [tx=0, rx=1]
  uint64_t shm_wakeups = 0;
  // Serving-tier SLO accounting (docs/DESIGN.md "Serving tier"): per-request
  // time-to-first-token and time-per-output-token histograms fed by the
  // router/decode workers through tpunet_c_serve_observe, plus instantaneous
  // per-tier queue depths (tpunet_c_serve_queue_depth).
  StageHist req_ttft_us;        // request admission -> first token
  StageHist req_tpot_us;        // mean inter-token gap after the first
  uint64_t serve_queue_depth[kServeTierCount] = {0};
  // Elastic-churn accounting (docs/DESIGN.md "Elastic churn"): per-phase
  // rewire duration histograms fed through tpunet_c_rewire_observe by the
  // elastic layer, membership-churn events by kind, and the live world
  // size as this rank last saw it (0 until a churn-aware job reports).
  StageHist rewire_us[kRewirePhaseCount];
  uint64_t churn_events[kChurnKindCount] = {0};
  uint64_t world_size = 0;
  // Live weight-update accounting (docs/DESIGN.md "Live weight updates"):
  // per-phase swap duration histograms fed through tpunet_c_swap_observe
  // by the publication layer, swap events by kind, and the checkpoint
  // version this rank serves (0 until a versioned tier reports).
  StageHist swap_us[kSwapPhaseCount];
  uint64_t swap_events[kSwapKindCount] = {0};
  uint64_t weight_version = 0;
  // Zero-copy data-path counters (docs/DESIGN.md "Data path"): wire syscalls
  // indexed by utils.h IoOp (send, recv, sendmsg, recvmsg) and bytes
  // produced by the reduction kernels. syscalls/MiB is derived from these in
  // benchmarks/engine_p2p.py — the fragmentation signal the 1-core sandbox
  // cannot noise out the way it noises GB/s.
  uint64_t engine_syscalls[4] = {0};
  uint64_t reduce_bytes = 0;
  // Compressed-collectives accounting (docs/DESIGN.md "Compressed
  // collectives"): encoded bytes per codec and direction, plus the f32
  // payload bytes the encoded forms stood in for. The wire-compression
  // ratio (tpunet_codec_wire_ratio) is encoded/payload — the noise-immune
  // proof that bf16 halved (int8: quartered) the ring's DCN bytes.
  uint64_t codec_bytes[2][2] = {{0, 0}, {0, 0}};  // [bf16,int8][tx,rx]
  uint64_t codec_payload_bytes[2] = {0, 0};       // [tx,rx]
  // Schedule-dispatch accounting (docs/DESIGN.md "Schedules & algorithm
  // selection"): sequential collective wire rounds executed by this rank
  // per schedule, and dispatch decisions per (collective, resolved
  // schedule). Slot i maps to CollAlgo i+1 (ring, rhd, tree — kAuto never
  // executes); kind slots are CollKind order (allreduce, broadcast). These
  // counters carry the small-message latency claim: ring AllReduce is
  // 2(W-1) rounds where rhd is 2*log2(W') and tree <= 2*ceil(log2 W).
  // Slots 0-2 map to CollAlgo 1-3 (ring, rhd, tree); slots 3-4 are the
  // hierarchical schedule's two stages (algo="hier.intra"/"hier.inter" —
  // the split is the point: hier's claim is that the inter slot, the DCN
  // wire rounds, shrinks while intra rides shared memory); slots 5-6 are
  // the hierarchical AllToAll's two stages (algo="a2a.intra"/"a2a.inter").
  // Selected slots 0-5 map to CollAlgo 1-6 (ring, rhd, tree, hier,
  // hier_a2a, pairwise); kind slots are CollKind order (allreduce,
  // broadcast, alltoall).
  uint64_t coll_steps[7] = {0, 0, 0, 0, 0, 0, 0};
  uint64_t coll_algo_selected[3][6] = {};
  // AllToAll wire bytes per [stage][dir] (tpunet_a2a_bytes_total: stage 0 =
  // intra regroup, 1 = inter DCN transpose, 2 = flat mesh/relay; dir tx=0,
  // rx=1) — the counter family every hierarchical-AllToAll byte claim is
  // gated on (docs/DESIGN.md "Hierarchical AllToAll").
  uint64_t a2a_bytes[3][2] = {};
  double uptime_s = 0;          // for bytes/s derivation
};

class Telemetry {
 public:
  static Telemetry& Get();

  // Always-on counter hooks (lock-free). Span tracking only when tracing.
  // `owner` disambiguates engine-local request ids across Net instances.
  void OnRequestStart(uint64_t owner, bool is_send, uint64_t comm, uint64_t req,
                      uint64_t nbytes);
  void OnRequestDone(uint64_t owner, uint64_t req, bool failed);
  // Engine hot-path hook: `nbytes` moved on data-stream `stream_idx`
  // (relaxed atomic add; indices >= kMaxStreamStats clamp to the last slot).
  // `cls` is the comm's TrafficClass int (default bulk) — it feeds both the
  // per-class byte counters and the class-split fairness windows.
  void OnStreamBytes(bool is_send, uint64_t stream_idx, uint64_t nbytes,
                     int cls = 1);
  // QoS scheduler hooks (qos.cc): one queue-wait sample per gated chunk,
  // and one preemption event per out-of-arrival-order grant.
  void OnQosQueueWait(int cls, uint64_t wait_us);
  void OnQosPreempt(int cls);
  // Rate-limited TCP_INFO sampler: called from the engines' data paths after
  // chunk IO with the live socket. Costs one clock read + one relaxed atomic
  // compare when the slot's sampling window has not elapsed; otherwise does
  // the getsockopt, updates the slot's gauges, and runs the straggler check.
  void MaybeSampleStream(bool is_send, uint64_t stream_idx, int fd);
  // Straggler-detector verdict for one stream slot (relaxed read of the
  // hysteresis flag the sampler maintains) — the lane adaptation loop's
  // demotion trigger (docs/DESIGN.md "Lanes & adaptive striping").
  bool StreamStraggling(bool is_send, uint64_t stream_idx) const;
  // Lane-striping hooks (lane-mode comms only; docs/DESIGN.md "Lanes &
  // adaptive striping"): current stripe weight / measured service rate per
  // lane (gauges, last writer wins), payload bytes per lane and direction,
  // and one restripe event per weight-vector epoch published.
  void OnLaneWeight(uint64_t lane, uint64_t weight);
  void OnLaneRate(uint64_t lane, uint64_t bps);
  void OnLaneBytes(bool is_send, uint64_t lane, uint64_t nbytes);
  void OnRestripe();
  // Intra-host SHM transport hooks (shm_engine.cc): payload bytes moved
  // through a ring segment, and futex wake syscalls the ring issued.
  void OnShmBytes(bool is_send, uint64_t nbytes);
  void OnShmWakeup();
  // Stage-latency accounting, called by the engines when a successful request
  // is consumed by test()/wait(). Timestamps are MonotonicUs(); completion
  // time is "now". post_us == 0 (no stamp) is ignored.
  void OnRequestStages(uint64_t post_us, uint64_t first_wire_us, uint64_t last_wire_us);
  // Collective phase span (collectives.cc): buffered into the trace file as
  // a Chrome-trace X event tagged {comm_id, coll_seq} — the cross-rank join
  // key. No-op when tracing is off (callers should pre-check
  // tracing_enabled() to skip building the phase string).
  void OnCollPhase(uint64_t comm_id, uint64_t coll_seq, const char* phase,
                   uint64_t start_us, uint64_t dur_us, uint64_t nbytes);
  // Failure-containment hooks (cold paths). `action` indexes FaultAction.
  void OnFaultInjected(int action);
  void OnStreamFailover();
  void OnCrcError();
  // Serving-tier SLO hooks (tpunet_c_serve_*): `kind` 0 = TTFT, 1 = TPOT
  // (both microseconds, observed into the request stage-latency bucket
  // layout); `tier` indexes kServeTierCount (router, prefill, decode).
  void OnServeLatency(int kind, uint64_t us);
  void OnServeQueueDepth(int tier, uint64_t depth);
  // Elastic-churn hooks (tpunet_c_rewire_observe / tpunet_c_churn_event /
  // tpunet_c_world_size): `phase` indexes kRewirePhaseCount, `kind` indexes
  // kChurnKindCount, `world` is the live communicator's world size.
  void OnRewirePhase(int phase, uint64_t us);
  void OnChurnEvent(int kind);
  void OnWorldSize(uint64_t world);
  // Live weight-update hooks (tpunet_c_swap_observe / tpunet_c_swap_event /
  // tpunet_c_weight_version): `phase` indexes kSwapPhaseCount, `kind`
  // indexes kSwapKindCount, `version` is the serving checkpoint version.
  void OnSwapPhase(int phase, uint64_t us);
  void OnSwapEvent(int kind);
  void OnWeightVersion(uint64_t version);
  // Bound port of the on-demand /metrics listener (0 = no listener). With
  // TPUNET_METRICS_PORT=0 the listener binds an EPHEMERAL port and this is
  // the only way to learn it (multi-tier loopback tests scrape both tiers).
  int MetricsPort() const;

  MetricsSnapshot Snapshot() const;
  // Prometheus text exposition of the snapshot (also what the push thread
  // sends and the scrape listener serves). Every family carries adjacent
  // # HELP / # TYPE lines (text-format lint clean).
  std::string PrometheusText() const;
  // Zero every counter/histogram/gauge (trace spans and the in-flight gauge
  // are untouched) so tests and benchmark warmups don't bleed into
  // measurement windows. Also restarts the uptime/fairness windows.
  void Reset();

  bool tracing_enabled() const { return trace_enabled_.load(std::memory_order_relaxed); }
  // Runtime-(re)target tracing at `dir` (empty = flush and disable). Used by
  // tpunet_c_trace_set_dir() / telemetry.profile() so a profile can start
  // after the library loaded without TPUNET_TRACE_DIR.
  bool SetTraceDir(const std::string& dir);
  // Write buffered spans to the trace file; called on buffer pressure, from
  // tpunet_c_trace_flush(), and at process exit (atexit — the singleton is
  // leaked so its destructor never runs). The file is valid JSON after every
  // flush. Returns false when the trace file could not be written (spans are
  // dropped); true on success or when tracing is disabled.
  bool FlushTrace();
  // Stop the push/scrape threads and flush; atexit hook (safe to call
  // repeatedly).
  void ShutdownForExit();

  ~Telemetry();

 private:
  Telemetry();
  // Accept loop of the on-demand /metrics listener; owns (and closes) lfd.
  void ScrapeLoop(int lfd);
  struct Impl;
  std::unique_ptr<Impl> impl_;
  std::atomic<bool> trace_enabled_{false};
};

// Decorator installed by CreateEngine() around the selected engine so both
// engines (and any future one) report identically.
std::unique_ptr<Net> WrapWithTelemetry(std::unique_ptr<Net> inner);

}  // namespace tpunet

#endif  // TPUNET_TELEMETRY_H_
