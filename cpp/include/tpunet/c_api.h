/* tpunet stable C ABI.
 *
 * Mirror of the reference's 13 extern "C" functions (reference:
 * src/lib.rs:19-392 bagua_net_c_* and cc/bagua_net.h:37-111), renamed
 * tpunet_c_*, with the reference's quirks fixed:
 *   - no global big-lock serializing every call (reference lib.rs:14-16);
 *   - request ids are freed when test() reports done (reference leaked one
 *     8-byte heap id per request, cc/bagua_net.cc:111-121);
 *   - property strings are owned by the instance and freed with the same
 *     allocator that made them (reference mixed Rust CString with C++
 *     delete, cc/bagua_net.cc:15-21);
 *   - multiple instances allowed (reference: one global singleton);
 *   - tpunet_c_last_error() exposes the failure detail per thread.
 *
 * Error codes (reference doc comments lib.rs:61-63,131-135,290-294):
 *   0 success, -1 null pointer, -2 invalid argument, -3 inner error.
 * Buffer lifetime contract: data passed to isend/irecv must stay alive and
 * unmoved until test() reports the request done (reference lib.rs:251,279).
 */
#ifndef TPUNET_C_API_H_
#define TPUNET_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define TPUNET_OK 0
#define TPUNET_ERR_NULL -1
#define TPUNET_ERR_INVALID -2
#define TPUNET_ERR_INNER -3
/* Failure-model codes (docs/DESIGN.md "Failure model"): */
/* per-chunk CRC32C mismatch (TPUNET_CRC=1) — the request failed but the
 * comm is still usable (not a disconnect). */
#define TPUNET_ERR_CORRUPT -4
/* progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS): zero bytes moved for a
 * full window — treat the peer as stuck (same recovery as dead). */
#define TPUNET_ERR_TIMEOUT -5
/* peer speaks a different tpunet wire-framing version. */
#define TPUNET_ERR_VERSION -6
/* collective wire-codec mismatch (TPUNET_WIRE_DTYPE / wire_dtype): the
 * ranks of a group disagree on the f32 wire compression codec. Raised at
 * communicator wiring time by the codec handshake on EVERY rank, before any
 * payload could be mis-decoded. */
#define TPUNET_ERR_CODEC -7
/* QoS admission backpressure (TPUNET_QOS_INFLIGHT_BYTES): the send's
 * traffic class already has its in-flight byte budget posted. Nothing was
 * enqueued or charged — retry after in-flight work drains (the serve
 * router replays front-of-queue). docs/DESIGN.md "Transport QoS". */
#define TPUNET_ERR_QOS_ADMISSION -8
/* Elastic rewire failure (docs/DESIGN.md "Elastic churn"): a mid-run
 * membership rewire exceeded TPUNET_REWIRE_TIMEOUT_MS or the churn engine
 * aborted recovery. The old communicator is already finalized; the caller
 * owns the retry-or-die decision — never a hang. */
#define TPUNET_ERR_REWIRE -9
/* Live weight-swap failure (docs/DESIGN.md "Live weight updates"): a
 * version publication aborted — publisher/receiver death mid-broadcast,
 * cross-rank CRC32C digest disagreement (flip refused fleet-wide), or the
 * swap exceeding TPUNET_SWAP_TIMEOUT_MS. The PREVIOUS version keeps
 * serving; the partial staged version was discarded. Retryable. */
#define TPUNET_ERR_WEIGHT_SWAP -10

/* 64-byte opaque rendezvous blob: the serialized listen sockaddr, sized to
 * NCCL's handle budget (reference: cc/nccl_types.h:44). Ship it to the
 * connecting side out-of-band (bootstrap). */
typedef struct tpunet_socket_handle {
  uint8_t data[64];
} tpunet_socket_handle_t;

/* Reference: NCCLNetPropertiesC (lib.rs:41-55). Strings are owned by the
 * instance and live until tpunet_c_destroy. */
typedef struct tpunet_net_properties {
  const char* name;
  const char* pci_path;
  uint64_t guid;
  int32_t ptr_support; /* 1 = host memory */
  int32_t speed_mbps;
  int32_t port;
  int32_t max_comms;
} tpunet_net_properties_t;

/* Engine selected by env TPUNET_IMPLEMENT in {BASIC (default), EPOLL}. */
int32_t tpunet_c_create(uintptr_t* out_instance);
/* As tpunet_c_create, pinning the QoS traffic class every comm this engine
 * CONNECTS will carry — traffic_class in {"latency","bulk","control"};
 * NULL or "" defers to TPUNET_TRAFFIC_CLASS (default bulk). The class
 * nibble rides the connect preamble, so the far side's recv comm adopts it
 * (sender's class wins, like nstreams). Unknown names are
 * TPUNET_ERR_INVALID. docs/DESIGN.md "Transport QoS". */
int32_t tpunet_c_create_ex(const char* traffic_class, uintptr_t* out_instance);
int32_t tpunet_c_destroy(uintptr_t* instance);

int32_t tpunet_c_devices(uintptr_t instance, int32_t* ndev);
int32_t tpunet_c_get_properties(uintptr_t instance, int32_t dev,
                                tpunet_net_properties_t* props);

int32_t tpunet_c_listen(uintptr_t instance, int32_t dev,
                        tpunet_socket_handle_t* handle, uintptr_t* listen_comm);
int32_t tpunet_c_connect(uintptr_t instance, int32_t dev,
                         const tpunet_socket_handle_t* handle, uintptr_t* send_comm);
int32_t tpunet_c_accept(uintptr_t instance, uintptr_t listen_comm,
                        uintptr_t* recv_comm);

int32_t tpunet_c_isend(uintptr_t instance, uintptr_t send_comm, const void* data,
                       uint64_t nbytes, uintptr_t* request);
int32_t tpunet_c_irecv(uintptr_t instance, uintptr_t recv_comm, void* data,
                       uint64_t nbytes, uintptr_t* request);
/* done: 0/1 out-flag; nbytes: actual message size once done (may be smaller
 * than the posted recv buffer). On done the request id is consumed. */
int32_t tpunet_c_test(uintptr_t instance, uintptr_t request, uint8_t* done,
                      uint64_t* nbytes);
/* Blocking companion to test(): parks until the request settles (condvar,
 * no CPU burn) and consumes it. nbytes as in tpunet_c_test. */
int32_t tpunet_c_wait(uintptr_t instance, uintptr_t request, uint64_t* nbytes);

int32_t tpunet_c_close_send(uintptr_t instance, uintptr_t send_comm);
int32_t tpunet_c_close_recv(uintptr_t instance, uintptr_t recv_comm);
int32_t tpunet_c_close_listen(uintptr_t instance, uintptr_t listen_comm);

/* Thread-local message for the last TPUNET_ERR_* returned on this thread. */
const char* tpunet_c_last_error(void);

/* ---- Chaos / integrity tooling ----------------------------------------
 * Deterministic fault injection (src/fault.h): parse `spec` (e.g.
 * "stream=1:after_bytes=1M:action=close") and arm it process-wide for every
 * engine's send/recv hot path. One fault at a time; re-arming replaces and
 * resets the byte counters. NULL or "" clears. Returns TPUNET_ERR_INVALID
 * (with tpunet_c_last_error() naming the bad token) on a malformed spec.
 * TPUNET_FAULT_SPEC arms the same slot at engine creation.
 *
 * The spec may also be a ';'-separated SCRIPT whose churn segments
 * ("churn:at_step=N:rank=K:action=kill|join") arm the process-wide churn
 * script (docs/DESIGN.md "Elastic churn") — deterministic scripted
 * membership churn, polled at step boundaries rather than applied on the
 * IO path. Swap segments ("swap:at_step=N:action=publish|corrupt|die")
 * likewise arm the process-wide weight-swap chaos script (docs/DESIGN.md
 * "Live weight updates"). At most one classic fault segment may ride
 * along. */
int32_t tpunet_c_fault_inject(const char* spec);
int32_t tpunet_c_fault_clear(void);
/* One-shot churn-script poll at a step boundary: fires (and consumes) the
 * first armed event with at_step <= step targeting `rank` (or rank=*) and
 * returns its action — 0 none, 1 kill (the polling rank must die NOW),
 * 2 join (a new rank enters the world; supervisor/joiner-side verdict).
 * Fired latches survive engine rebuilds: the rewires a churn script causes
 * must not re-fire the events the job already recovered from. */
int32_t tpunet_c_churn_poll(uint64_t step, int64_t rank);
/* Armed churn events not yet fired (the churn smoke lane's completeness
 * gate: a finished scripted run must report 0). */
int32_t tpunet_c_churn_pending(void);
/* One-shot swap-script poll at a step boundary (weight hot-swap chaos,
 * "swap:at_step=N:action=publish|corrupt|die" segments of the fault
 * script): fires (and consumes) the first armed event with at_step <= step
 * and returns its action — 0 none, 1 publish (the publisher must start a
 * weight publication NOW), 2 corrupt (the polling receiver must corrupt
 * its received weight bytes before digesting — the flip-refusal drill),
 * 3 die (the polling rank must die NOW, mid-broadcast when timed so).
 * Unlike churn there is no rank clause: each process arms its own script
 * via TPUNET_FAULT_SPEC. Fired latches survive swap retries. */
int32_t tpunet_c_swap_poll(uint64_t step);
/* Armed swap events not yet fired (the swap smoke lane's completeness
 * gate: a finished scripted run must report 0). */
int32_t tpunet_c_swap_pending(void);
/* CRC32C (Castagnoli) of `data`, seeded with `seed` (0 = fresh; chain for
 * discontiguous buffers). Exposed for golden-vector tests and so Python
 * tooling can pre-verify payloads against the wire trailers. */
uint32_t tpunet_c_crc32c(const void* data, uint64_t nbytes, uint32_t seed);
/* Stable host identity (never 0): hash of TPUNET_HOST_ID when set (the
 * fake-host override that splits one box into testable "hosts"), else of
 * the kernel boot id, else of the hostname. Two processes report the same
 * id iff they can share a memory segment — the locality verdict behind the
 * SHM transport handshake (TPUNET_SHM=1) and the hierarchical collective's
 * host grouping. Exposed so Python tests can pin the derivation. */
uint64_t tpunet_c_host_id(void);
/* Elementwise reduction dst[i] = a[i] op b[i] over n elements — the
 * runtime-dispatched (SIMD when the CPU has it, scalar otherwise) kernel the
 * ring collectives run post-wire, exposed so SIMD-vs-scalar equivalence
 * goldens can pin it from Python. dst may alias a (in-place accumulate).
 * dtype: 0=f32 1=f64 2=bf16 3=i32 4=i64 5=u8; op: 0=sum 1=prod 2=min 3=max.
 * Returns TPUNET_ERR_INVALID for an unknown dtype/op or a NULL buffer with
 * n > 0. */
int32_t tpunet_c_reduce(void* dst, const void* a, const void* b, uint64_t n,
                        int32_t dtype, int32_t op);
/* ---- Wire codecs (compressed ring collectives) -------------------------
 * The encode/decode kernels the ring runs at every compressed wire hop
 * (codec: 0=f32 passthrough, 1=bf16 RNE, 2=int8 block-scaled — see
 * docs/DESIGN.md "Compressed collectives"), exposed so Python golden tests
 * can pin the wire format and the documented int8 error bound without a
 * socket in sight. n counts f32 ELEMENTS. */
/* Encoded byte count for n f32 elements (0 for an unknown codec). */
uint64_t tpunet_c_codec_wire_bytes(int32_t codec, uint64_t n);
/* Encode n f32 elements from src into dst (dst_cap must be >= the wire
 * byte count; TPUNET_ERR_INVALID otherwise). */
int32_t tpunet_c_codec_encode(int32_t codec, const void* src, uint64_t n,
                              void* dst, uint64_t dst_cap);
/* Decode a wire buffer of n encoded f32 elements into dst (n floats). */
int32_t tpunet_c_codec_decode(int32_t codec, const void* wire, uint64_t n,
                              void* dst);

/* ---- Lane striping (docs/DESIGN.md "Lanes & adaptive striping") ---------
 * Pure views of the weighted stripe scheduler so Python goldens can pin the
 * chunk->stream layout both sides derive — no sockets involved. */
/* Parse a TPUNET_LANES spec ("addr=10.0.0.1:w=4,addr=10.0.1.1:w=1"; a lane
 * may omit either key) and echo the normalized form, one lane per line:
 * "lane=<i> addr=<a|-> w=<n>". Malformed specs are TPUNET_ERR_INVALID with
 * the offending token in tpunet_c_last_error(). Returns the full text
 * length (the tpunet_c_metrics_text buffer-sizing contract). */
int32_t tpunet_c_lane_parse(const char* spec, char* out, uint64_t cap);
/* The chunk->stream assignment a message of `len` bytes gets under the
 * weighted stripe scheduler: `weights` is a comma-separated per-stream
 * weight list (1..255 each; its length is the stream count), `cursor` the
 * comm's rotation cursor at message start. Writes the comma-separated
 * stream index per chunk (empty for len == 0). Both transport engines
 * derive layouts from exactly this arithmetic — the golden tests pin that
 * sender and receiver agree for every (len, min_chunksize, weights, cursor)
 * without layout metadata on the wire. Equal weights reproduce the uniform
 * cursor%nstreams rotation bit-for-bit. */
int32_t tpunet_c_stripe_map(uint64_t len, uint64_t min_chunksize,
                            const char* weights, uint64_t cursor, char* out,
                            uint64_t cap);

/* ---- Collectives (ring communicator over the transport) ----------------
 * The layer NCCL provided above the reference plugin (SURVEY §2.3); here it
 * is in-repo: bootstrap rendezvous + ring AllReduce/ReduceScatter/AllGather/
 * Broadcast/Barrier + the neighbor-exchange step sequence parallelism needs.
 * dtype: 0=f32 1=f64 2=bf16 3=i32 4=i64 5=u8; op: 0=sum 1=prod 2=min 3=max.
 * A communicator is single-threaded (one collective at a time); all ranks
 * must call the same collectives in the same order. */
int32_t tpunet_comm_create(const char* coordinator, int32_t rank, int32_t world_size,
                           uintptr_t* comm);
/* As tpunet_comm_create, selecting the wire compression codec for f32
 * collectives — wire_dtype in {"f32","bf16","int8"}; NULL or "" defers to
 * TPUNET_WIRE_DTYPE (default f32) — and the collective schedule: algo in
 * {"auto","ring","rhd","tree","hier"}; NULL or "" defers to TPUNET_ALGO
 * (default auto). "hier" is the two-level schedule (intra-host stage +
 * one-rank-per-host DCN stage; needs >= 2 hosts with uniform ranks/host by
 * the handshake's host ids, else it runs the ring).
 * "auto" dispatches per (collective, payload bytes, world) through
 * built-in thresholds or the TPUNET_DISPATCH_TABLE JSON written by
 * `busbw_sweep --emit-dispatch` (docs/DESIGN.md "Schedules & algorithm
 * selection"). Unknown names are TPUNET_ERR_INVALID. Cross-rank
 * disagreements fail wiring on EVERY rank: TPUNET_ERR_CODEC for the codec,
 * TPUNET_ERR_INVALID for the algo/dispatch-table handshake (ranks on
 * different schedules deadlock — this fails them loudly first). */
/* traffic_class in {"latency","bulk","control"} selects the QoS lane every
 * comm the communicator wires will carry; NULL or "" defers to
 * TPUNET_TRAFFIC_CLASS (default bulk). The class byte rides the same
 * bootstrap handshake as the codec/algo: a cross-rank disagreement is
 * TPUNET_ERR_INVALID on EVERY rank. */
int32_t tpunet_comm_create_ex(const char* coordinator, int32_t rank,
                              int32_t world_size, const char* wire_dtype,
                              const char* algo, const char* traffic_class,
                              uintptr_t* comm);
/* Negotiated wire codec of a live communicator: 0=f32, 1=bf16, 2=int8. */
int32_t tpunet_comm_wire_dtype(uintptr_t comm, int32_t* wire_dtype);
/* Process-default communicator for callers that cannot thread a handle —
 * the XLA FFI custom-call collectives look it up at CALL time so elastic
 * recovery can re-point it under already-compiled executables. set(0)
 * clears. get returns 0 when unset. */
int32_t tpunet_comm_set_default(uintptr_t comm);
uintptr_t tpunet_comm_get_default(void);
int32_t tpunet_comm_destroy(uintptr_t* comm);
int32_t tpunet_comm_rank(uintptr_t comm, int32_t* rank, int32_t* world_size);
/* sendbuf may equal recvbuf (in-place). count = elements. */
int32_t tpunet_comm_all_reduce(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t count, int32_t dtype, int32_t op);
/* sendbuf: world*recv_count elements; recvbuf: this rank's recv_count. */
int32_t tpunet_comm_reduce_scatter(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                   uint64_t recv_count, int32_t dtype, int32_t op);
/* sendbuf: bytes_per_rank; recvbuf: world*bytes_per_rank rank-ordered. */
int32_t tpunet_comm_all_gather(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t bytes_per_rank);
int32_t tpunet_comm_broadcast(uintptr_t comm, void* buf, uint64_t nbytes, int32_t root);
/* sendbuf: world blocks of bytes_per_rank, block j for rank j; recvbuf:
 * world blocks, block j from rank j. sendbuf may equal recvbuf. */
int32_t tpunet_comm_all_to_all(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t bytes_per_rank);
/* Typed AllToAll: blocks are count_per_rank ELEMENTS of dtype. f32 blocks
 * honor the communicator's negotiated wire codec — every non-self block is
 * encoded once at the source (int8 scale blocks restart per (src,dst)
 * block) and decoded once at the destination, so results are bit-identical
 * across the pairwise / relay / hierarchical routes and each block's error
 * stays inside the |err| <= amax/254 bound. Non-f32 dtypes (and codec f32)
 * ship uncompressed. docs/DESIGN.md "Hierarchical AllToAll". */
int32_t tpunet_comm_all_to_all_typed(uintptr_t comm, const void* sendbuf,
                                     void* recvbuf, uint64_t count_per_rank,
                                     int32_t dtype);
/* Nonblocking byte-oriented AllToAll: enqueues on the communicator's
 * dedicated mesh worker (pairwise/hier routes) or a ring channel (relay
 * route) and returns a ticket for tpunet_comm_ticket_wait/_test — an async
 * AllToAll overlaps async ring AllReduces on disjoint comms. Same
 * buffer-lifetime and submission-order rules as tpunet_comm_iall_reduce. */
int32_t tpunet_comm_iall_to_all(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                uint64_t bytes_per_rank, uint64_t* ticket);
/* Send to (rank+1)%world while receiving from (rank-1+world)%world. */
int32_t tpunet_comm_neighbor_exchange(uintptr_t comm, const void* sendbuf,
                                      uint64_t send_nbytes, void* recvbuf,
                                      uint64_t recv_nbytes, uint64_t* got);
int32_t tpunet_comm_barrier(uintptr_t comm);
/* Nonblocking AllReduce: enqueues on the comm's worker thread, returns a
 * ticket immediately. Buffers must stay alive until ticket_wait returns.
 * Jobs run in submission order; tickets may be waited in any order; a
 * blocking collective issued while tickets are outstanding fences first. */
int32_t tpunet_comm_iall_reduce(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                uint64_t count, int32_t dtype, int32_t op,
                                uint64_t* ticket);
int32_t tpunet_comm_ticket_wait(uintptr_t comm, uint64_t ticket);
int32_t tpunet_comm_ticket_test(uintptr_t comm, uint64_t ticket, uint8_t* done);

/* ---- Telemetry ---------------------------------------------------------
 * Metrics counters are process-global and always on; spans/push/scrape are
 * gated by env (TPUNET_TRACE_DIR / TPUNET_METRICS_ADDR /
 * TPUNET_METRICS_PORT, rank 0-7 — the reference's gating, nthread:108-130).
 * Deep observability (docs/DESIGN.md "Observability"): per-stream
 * TCP_INFO gauges + Jain fairness + straggler events
 * (TPUNET_TCPINFO_INTERVAL_MS, TPUNET_STRAGGLER_FACTOR), request
 * stage-latency histograms (tpunet_req_{queue,wire,total}_us), and
 * collective phase spans tagged (comm_id, coll_seq, phase). */
/* Write the Prometheus text exposition into buf (NUL-terminated, truncated
 * to cap). Returns the full length (excluding NUL), or a TPUNET_ERR_*. */
int32_t tpunet_c_metrics_text(char* buf, uint64_t cap);
/* Zero every metric counter/histogram/gauge (trace spans and the in-flight
 * gauge are untouched) so tests and benchmark warmups don't bleed counters
 * into measurement windows. */
int32_t tpunet_c_metrics_reset(void);
/* Flush buffered trace spans to the trace file (no-op when disabled). The
 * file is valid Chrome-trace JSON after every flush. */
int32_t tpunet_c_trace_flush(void);
/* Runtime-(re)target tracing at `dir` (tpunet.telemetry.profile()): starts
 * tracing even when TPUNET_TRACE_DIR was unset at load. NULL or "" flushes
 * and disables. */
int32_t tpunet_c_trace_set_dir(const char* dir);
/* Bound port of the on-demand /metrics listener, or 0 when no listener is
 * up. TPUNET_METRICS_PORT unset/empty = no listener; an explicit 0 binds an
 * EPHEMERAL port (multi-tier loopback: several processes on one box each
 * get their own listener) whose number only this call can report. */
int32_t tpunet_c_metrics_port(void);
/* Serving-tier SLO observation (docs/DESIGN.md "Serving tier"): record one
 * latency sample into the TTFT (kind 0, tpunet_req_ttft_us) or TPOT
 * (kind 1, tpunet_req_tpot_us) histogram. `us` is microseconds. */
int32_t tpunet_c_serve_observe(int32_t kind, uint64_t us);
/* Set the instantaneous queue-depth gauge of a serving tier
 * (tpunet_serve_queue_depth{tier=...}): 0 = router, 1 = prefill,
 * 2 = decode. */
int32_t tpunet_c_serve_queue_depth(int32_t tier, uint64_t depth);
/* ---- Elastic churn observability (docs/DESIGN.md "Elastic churn") -------
 * Record one rewire-phase duration sample into
 * tpunet_rewire_duration_us{phase=...}: 0 = detect (last good collective ->
 * failure classified / join agreed), 1 = quiesce (old comm finalized),
 * 2 = rendezvous (membership sealed + generation published), 3 = rewire
 * (new communicator wired at the new shape). `us` is microseconds. */
int32_t tpunet_c_rewire_observe(int32_t phase, uint64_t us);
/* Count one membership-churn event into tpunet_churn_events_total{kind=...}:
 * 0 = kill (scripted death fired), 1 = join (join request honored),
 * 2 = shrink (world rebuilt smaller), 3 = grow (world rebuilt larger),
 * 4 = readmit (a recovered decode rank re-entered the serving pool). */
int32_t tpunet_c_churn_event(int32_t kind);
/* Set the tpunet_world_size gauge — the live communicator's world as seen
 * by this rank (the churn suite's "world came back" gate). */
int32_t tpunet_c_world_size(uint64_t world);
/* ---- Live weight updates (docs/DESIGN.md "Live weight updates") ---------
 * Record one weight-swap phase duration sample into
 * tpunet_weight_swap_duration_us{phase=...}: 0 = announce (SWAP_BEGIN
 * frames out / receiver armed), 1 = broadcast (chunked bf16 tree broadcast
 * on the bulk class), 2 = verify (cross-rank CRC32C digest agreement),
 * 3 = flip (new BatchServer built, version live). `us` is microseconds. */
int32_t tpunet_c_swap_observe(int32_t phase, uint64_t us);
/* Count one weight-swap event into tpunet_swap_events_total{kind=...}:
 * 0 = publish (a publication attempt started), 1 = commit (every rank
 * agreed and flipped), 2 = abort (staged version discarded — death or
 * timeout), 3 = retry (a failed publication re-attempted), 4 = mismatch
 * (CRC digest disagreement refused the flip fleet-wide). */
int32_t tpunet_c_swap_event(int32_t kind);
/* Set the tpunet_weight_version gauge — the checkpoint version this rank
 * is serving (the swap smoke lane's "v2 reached every rank" gate). */
int32_t tpunet_c_weight_version(uint64_t version);
/* ---- Flight recorder (docs/DESIGN.md §6c) -------------------------------
 * Dump the per-rank flight-recorder ring to
 * <dir>/tpunet-flightrec-rank<R>.json (dir NULL/"" = TPUNET_TRACE_DIR when
 * set at init, else "."). `reason` (NULL = "api") lands in the dump header.
 * Writes the dump path into out_path (NUL-terminated, truncated to cap) and
 * returns its full length — the tpunet_c_metrics_text buffer-sizing
 * contract. TPUNET_ERR_INVALID when the recorder is disabled
 * (TPUNET_FLIGHTREC_EVENTS=0) or the target is unwritable. */
int32_t tpunet_c_flightrec_dump(const char* dir, const char* reason,
                                char* out_path, uint64_t cap);
/* Recorder occupancy: events ever recorded (the ring cursor — monotonic,
 * NOT clamped to capacity) and ring capacity in slots. Both 0 when the
 * recorder is disabled. Either pointer may be NULL. */
int32_t tpunet_c_flightrec_stats(uint64_t* recorded, uint64_t* capacity);

/* ---- Transport QoS introspection (docs/DESIGN.md "Transport QoS") -------
 * Text echo of the process QoS scheduler's parsed config (weights, budgets,
 * wire window) and live state (admitted/in-flight bytes, queue depths) into
 * buf (NUL-terminated, truncated to cap). Returns the full length
 * (excluding NUL) — the buffer-sizing contract of tpunet_c_metrics_text.
 * Lets Python pin that TPUNET_QOS_WEIGHTS / TPUNET_QOS_INFLIGHT_BYTES
 * parsed to what the operator meant. */
int32_t tpunet_c_qos_state(char* buf, uint64_t cap);
/* Deficit-round-robin arithmetic golden: simulate the wire-credit grant
 * order for `chunks` ("class:bytes,class:bytes,...", queued in order) under
 * `weights` (TPUNET_QOS_WEIGHTS grammar) and `window` ("wire=<bytes>");
 * completions retire in grant order. Writes the comma-separated class grant
 * sequence into out (same sizing contract). Pure arithmetic — no sockets,
 * no clocks — so tests can pin strict control priority and the weighted
 * latency/bulk interleave exactly. Malformed specs are TPUNET_ERR_INVALID
 * with the offending token in tpunet_c_last_error(). */
int32_t tpunet_c_qos_drr_golden(const char* weights, const char* window,
                                const char* chunks, char* out, uint64_t cap);

#ifdef __cplusplus
}
#endif

#endif /* TPUNET_C_API_H_ */
