// Clang thread-safety-analysis (TSA) macros.
//
// The engines and collectives are ~2k LoC of hand-rolled mutex/CV/atomic
// code whose lock discipline was, until this header, enforced only by the
// dynamic tsan/asan lanes — which exercise exactly the interleavings the
// loopback tests happen to hit. These macros let the lock contracts live in
// the type system instead: every lock-protected field names its mutex
// (GUARDED_BY), every must-hold-the-lock function names its precondition
// (REQUIRES), and `make tsa` compiles the tree with clang's
// -Wthread-safety -Werror so a violation is a build break, not a flaky
// nightly report. See docs/DESIGN.md "Concurrency model & lock hierarchy"
// for the repo-wide lock ordering these annotations encode.
//
// Under non-clang compilers (the default g++ build) every macro expands to
// nothing — the annotations are zero-cost documentation there, and the
// tsan/asan lanes keep covering what static analysis cannot (condvar wakeup
// ordering, atomics-based handshakes like Comm::inflight).
//
// Naming follows the capability-based spelling from the clang docs (and
// Abseil): ACQUIRE/RELEASE rather than the legacy EXCLUSIVE_LOCK_FUNCTION/
// UNLOCK_FUNCTION. Analysis-relevant notes:
//   * Attribute arguments are late-parsed: a GUARDED_BY(mu) may name a
//     member declared later in the same class.
//   * The analysis is purely syntactic — REQUIRES(c->mu) at a call site
//     substitutes the caller's argument expression for `c`, so functions
//     taking an object plus one of its sub-parts must take the OWNER as an
//     explicit parameter (see epoll_engine.cc's AdvanceFdLocked(EComm*,
//     FdState*)) or the capability expressions will not match.
//   * ACQUIRED_AFTER/ACQUIRED_BEFORE (lock-ordering declarations) are only
//     checked under -Wthread-safety-beta; they are included in `make tsa`
//     as documentation that the beta lane can later enforce.
#ifndef TPUNET_THREAD_ANNOTATIONS_H_
#define TPUNET_THREAD_ANNOTATIONS_H_

#if defined(__clang__) && !defined(SWIG)
#define TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(x) __attribute__((x))
#else
#define TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(x)  // no-op on gcc/others
#endif

// Type attribute: this class is a lockable capability ("mutex").
#define CAPABILITY(x) TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(capability(x))

// Type attribute: RAII object that acquires in its ctor, releases in dtor.
#define SCOPED_CAPABILITY TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(scoped_lockable)

// Data member: may only be read/written while holding `x`.
#define GUARDED_BY(x) TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(guarded_by(x))

// Pointer member: the POINTED-TO data requires `x` (the pointer itself
// does not).
#define PT_GUARDED_BY(x) TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(pt_guarded_by(x))

// Lock-ordering documentation (checked only under -Wthread-safety-beta).
#define ACQUIRED_BEFORE(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(acquired_before(__VA_ARGS__))
#define ACQUIRED_AFTER(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(acquired_after(__VA_ARGS__))

// Function precondition: caller must hold the named capabilities.
#define REQUIRES(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(requires_capability(__VA_ARGS__))

// Function effect: acquires / releases the named capabilities.
#define ACQUIRE(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(acquire_capability(__VA_ARGS__))
#define RELEASE(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(release_capability(__VA_ARGS__))

// Function effect: acquires the capability iff the return value equals the
// first argument (e.g. TRY_ACQUIRE(true) for a bool TryLock()).
#define TRY_ACQUIRE(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(try_acquire_capability(__VA_ARGS__))

// Function precondition: caller must NOT hold the named capabilities
// (deadlock documentation for self-locking functions).
#define EXCLUDES(...) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(locks_excluded(__VA_ARGS__))

// Runtime assertion that the capability is held (no acquire/release).
#define ASSERT_CAPABILITY(x) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(assert_capability(x))

// Function returns a reference to the named capability.
#define RETURN_CAPABILITY(x) \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(lock_returned(x))

// Escape hatch: the function's locking is deliberately outside what the
// analysis can model. Every use must carry a comment saying why.
#define NO_THREAD_SAFETY_ANALYSIS \
  TPUNET_THREAD_ANNOTATION_ATTRIBUTE_(no_thread_safety_analysis)

#endif  // TPUNET_THREAD_ANNOTATIONS_H_
