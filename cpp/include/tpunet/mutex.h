// Annotated Mutex / MutexLock / CondVar over the std primitives.
//
// Thin wrappers whose only job is carrying the clang thread-safety
// capability attributes (thread_annotations.h) — std::mutex itself has no
// annotations, so code locking it directly is invisible to -Wthread-safety.
// Every lock-protected field and locking function in the C++ core goes
// through these types; `make tsa` then proves the lock discipline at build
// time. Zero overhead over the raw std types on the lock/unlock paths (all
// methods are inline forwarding calls).
//
// CondVar wraps std::condition_variable_any parked directly on the Mutex
// (which is BasicLockable via lock()/unlock()). Vs. std::condition_variable
// + std::unique_lock this costs one extra internal mutex inside libstdc++'s
// condition_variable_any — irrelevant next to the syscall in every park —
// and buys waits expressible as Wait(mu) under an annotation-visible
// capability instead of an opaque unique_lock the analysis cannot track.
//
// No predicate-taking Wait overload on purpose: TSA analyzes a lambda as a
// separate function with no REQUIRES, so guarded reads inside a wait
// predicate would all warn. Callers write the explicit
// `while (!cond) cv.Wait(mu);` loop instead, which the analysis checks
// field-by-field.
#ifndef TPUNET_MUTEX_H_
#define TPUNET_MUTEX_H_

#include <chrono>
#include <condition_variable>
#include <mutex>

#include "tpunet/thread_annotations.h"

namespace tpunet {

class CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;

  void Lock() ACQUIRE() { mu_.lock(); }
  void Unlock() RELEASE() { mu_.unlock(); }
  bool TryLock() TRY_ACQUIRE(true) { return mu_.try_lock(); }

  // BasicLockable spelling so CondVar (condition_variable_any) can park on
  // the Mutex directly. Same capability effects as Lock/Unlock.
  void lock() ACQUIRE() { mu_.lock(); }
  void unlock() RELEASE() { mu_.unlock(); }

 private:
  std::mutex mu_;
};

// RAII lock. The std::adopt_lock_t overload takes ownership of an
// already-held Mutex (pairs with Mutex::TryLock — see
// basic_engine.cc's PumpCtrlUntilRetired).
class SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) ACQUIRE(mu) : mu_(mu) { mu_.Lock(); }
  MutexLock(Mutex& mu, std::adopt_lock_t) REQUIRES(mu) : mu_(mu) {}
  ~MutexLock() RELEASE() { mu_.Unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& mu_;
};

class CondVar {
 public:
  CondVar() = default;
  CondVar(const CondVar&) = delete;
  CondVar& operator=(const CondVar&) = delete;

  // Atomically release `mu`, park, and reacquire before returning. Callers
  // loop on their condition (spurious wakeups, as with the std primitive).
  void Wait(Mutex& mu) REQUIRES(mu) { cv_.wait(mu); }

  // Bounded park; returns false on timeout. The glibc path under this is
  // pthread_cond_timedwait — see cpp/tests/tsan.supp for the one libtsan
  // modeling artifact timed waits still carry.
  bool WaitFor(Mutex& mu, int ms) REQUIRES(mu) {
    return cv_.wait_for(mu, std::chrono::milliseconds(ms)) ==
           std::cv_status::no_timeout;
  }

  void NotifyOne() { cv_.notify_one(); }
  void NotifyAll() { cv_.notify_all(); }

 private:
  std::condition_variable_any cv_;
};

}  // namespace tpunet

#endif  // TPUNET_MUTEX_H_
