// tpunet collectives — topology-aware schedules over the multi-stream
// transport.
//
// The reference provided only point-to-point isend/irecv; NCCL's algorithm
// layer lived above it (SURVEY §2.3: "AllReduce / collectives algorithms —
// absent in-repo, external"). On TPU there is no NCCL to sit under, so
// tpunet owns this layer: AllReduce under three schedules — chunk-pipelined
// ring (reduce-scatter + all-gather), recursive halving-doubling, and
// binomial tree — selected per (collective, payload bytes, world) by the
// dispatch layer (docs/DESIGN.md "Schedules & algorithm selection"), plus
// ring AllGather/ReduceScatter, ring- or tree-Broadcast, Barrier, AllToAll,
// and the neighbor-exchange primitive that sequence-parallel/ring-attention
// layers need. Rendezvous handles travel via the Bootstrap (bootstrap.h).
#ifndef TPUNET_COLLECTIVES_H_
#define TPUNET_COLLECTIVES_H_

#include <cstddef>
#include <cstdint>
#include <memory>
#include <string>

#include "tpunet/net.h"

namespace tpunet {

// Values are ABI: they cross the C layer and the Python binding.
enum class DType : int32_t {
  kF32 = 0,
  kF64 = 1,
  kBF16 = 2,
  kI32 = 3,
  kI64 = 4,
  kU8 = 5,
};

enum class RedOp : int32_t {
  kSum = 0,
  kProd = 1,
  kMin = 2,
  kMax = 3,
};

size_t DTypeSize(DType d);

// A ring communicator: every rank holds a send comm to (rank+1)%world and a
// recv comm from (rank-1+world)%world over the multi-stream transport.
class Communicator {
 public:
  virtual ~Communicator() = default;

  // Collective constructor — all ranks must call with the same coordinator
  // and world_size. Owns its own transport engine instance.
  static Status Create(const std::string& coordinator, int rank, int world_size,
                       std::unique_ptr<Communicator>* out);
  // As above, selecting the wire compression codec for f32 collectives
  // ("f32" / "bf16" / "int8"; empty = TPUNET_WIRE_DTYPE, default f32 — see
  // docs/DESIGN.md "Compressed collectives"). The codec is negotiated over
  // the bootstrap at wiring time: ranks that disagree ALL fail with
  // ErrorKind::kCodec before any payload could be mis-decoded. Unknown
  // names are kInvalidArgument.
  static Status Create(const std::string& coordinator, int rank, int world_size,
                       const std::string& wire_dtype,
                       std::unique_ptr<Communicator>* out);
  // As above, additionally pinning the collective schedule ("auto" / "ring"
  // / "rhd" / "tree" / "hier"; empty = TPUNET_ALGO, default auto —
  // docs/DESIGN.md "Schedules & algorithm selection"; "hier" is the
  // two-level intra-host + inter-host schedule and needs >= 2 hosts with
  // uniform ranks/host by the handshake's host ids, else it runs the
  // ring). "auto" selects per
  // (collective, payload bytes, world): built-in thresholds, overridable by
  // a TPUNET_DISPATCH_TABLE JSON seeded offline by `busbw_sweep
  // --emit-dispatch`. The (algo, table) pair is negotiated over the
  // bootstrap like the codec: ranks that disagree ALL fail at wiring time
  // (two ranks on different schedules would deadlock, not corrupt).
  static Status Create(const std::string& coordinator, int rank, int world_size,
                       const std::string& wire_dtype, const std::string& algo,
                       std::unique_ptr<Communicator>* out);
  // As above, additionally pinning the QoS traffic class ("latency" /
  // "bulk" / "control"; empty = TPUNET_TRAFFIC_CLASS, default bulk —
  // docs/DESIGN.md "Transport QoS"). The class byte rides the same
  // bootstrap handshake as the codec/algo: ranks that disagree ALL fail at
  // wiring time (half a group on the latency lane would silently unbalance
  // the scheduler, so the disagreement is loud instead). Unknown names are
  // kInvalidArgument.
  static Status Create(const std::string& coordinator, int rank, int world_size,
                       const std::string& wire_dtype, const std::string& algo,
                       const std::string& traffic_class,
                       std::unique_ptr<Communicator>* out);

  // sendbuf may equal recvbuf (in-place). count = elements. Blocking
  // AllReduce is exactly IAllReduce+WaitTicket (MPI/NCCL matching rule:
  // one rank's blocking call pairs with another's nonblocking one), so both
  // forms share one ticket sequence and channel schedule.
  virtual Status AllReduce(const void* sendbuf, void* recvbuf, size_t count,
                           DType dtype, RedOp op) = 0;
  // sendbuf holds world*recv_count elements; recvbuf gets this rank's
  // reduced recv_count elements.
  virtual Status ReduceScatter(const void* sendbuf, void* recvbuf, size_t recv_count,
                               DType dtype, RedOp op) = 0;
  // sendbuf holds bytes_per_rank bytes; recvbuf gets world*bytes_per_rank,
  // rank-ordered. Byte-oriented (no dtype needed).
  virtual Status AllGather(const void* sendbuf, void* recvbuf, size_t bytes_per_rank) = 0;
  // In-place broadcast of nbytes from root, pipelined around the ring.
  virtual Status Broadcast(void* buf, size_t nbytes, int root) = 0;
  // AllToAll: sendbuf holds world blocks of bytes_per_rank bytes, block j
  // destined for rank j; recvbuf gets world blocks, block j originating at
  // rank j. sendbuf may equal recvbuf (in-place). Implemented as a
  // store-and-forward relay around the ring (constant connection degree; a
  // block bound d hops ahead travels d hops), so per-rank traffic is
  // W(W-1)/2 blocks vs the (W-1) of an all-pairs topology — the trade the
  // ring makes for not opening W^2 multi-stream socket bundles. This is the
  // primitive Ulysses sequence parallelism and cross-host MoE dispatch ride.
  virtual Status AllToAll(const void* sendbuf, void* recvbuf, size_t bytes_per_rank) = 0;
  // Typed AllToAll: blocks are count_per_rank ELEMENTS of dtype. f32 blocks
  // honor the negotiated wire codec (docs/DESIGN.md "Hierarchical
  // AllToAll"): every non-self block is encoded ONCE at the source (int8
  // scale blocks restart per (src, dst) block) and decoded ONCE at the
  // destination — the encoded bytes forward verbatim through whatever
  // route the schedule picks, so results are bit-identical across the
  // pairwise mesh, the relay, and the two-stage hierarchical transpose,
  // and the per-block error stays inside the documented |err| <= amax/254
  // bound. Non-f32 dtypes (and codec f32) ship uncompressed, exactly like
  // the byte-oriented AllToAll.
  virtual Status AllToAllTyped(const void* sendbuf, void* recvbuf,
                               size_t count_per_rank, DType dtype) = 0;
  // Simultaneous send-to-next / recv-from-prev (the ppermute step of ring
  // attention / sequence parallelism). send_nbytes bytes go to (rank+1)%W;
  // recv buffer receives prev rank's message (recv_nbytes posted capacity;
  // actual size returned in *got if non-null).
  virtual Status NeighborExchange(const void* sendbuf, size_t send_nbytes, void* recvbuf,
                                  size_t recv_nbytes, size_t* got) = 0;
  virtual Status Barrier() = 0;

  // -- Nonblocking collectives ---------------------------------------------
  // The request-depth design the reference transport was built to serve
  // (NCCL keeps <=8 requests in flight per comm, reference
  // cc/nccl_types.h:50): IAllReduce enqueues the collective on the
  // communicator's internal worker threads and returns a ticket immediately,
  // so a trainer can overlap gradient-bucket reduction with backward
  // compute. Tickets are dispatched round-robin over TPUNET_ASYNC_CHANNELS
  // (default 2) independent ring channels, each its own comm pair + worker,
  // so consecutive tickets also overlap each other on the wire (ticket k+1's
  // transfer runs while ticket k reduces). Every rank must submit the same
  // collectives in the same order (MPI semantics) and agree on the channel
  // count — the ticket->channel map is how peers pair messages up; tickets
  // may be waited in any order. The caller must keep sendbuf and
  // recvbuf alive until WaitTicket returns. Blocking collectives issued
  // while tickets are outstanding implicitly fence: they wait for the
  // async queue to drain first, so mixing is well-defined.
  virtual Status IAllReduce(const void* sendbuf, void* recvbuf, size_t count,
                            DType dtype, RedOp op, uint64_t* ticket) = 0;
  // Nonblocking byte-oriented AllToAll. Mesh-routed schedules (pairwise /
  // hierarchical) run on the communicator's dedicated mesh worker — one
  // shared pairwise mesh means mesh jobs serialize in submission order —
  // while ring tickets keep their round-robin channels, so an async
  // AllToAll overlaps async ring AllReduces on disjoint comms instead of
  // queueing behind them. Same buffer-lifetime and submission-order rules
  // as IAllReduce.
  virtual Status IAllToAll(const void* sendbuf, void* recvbuf,
                           size_t bytes_per_rank, uint64_t* ticket) = 0;
  // Blocks until the ticket's collective completes; returns its Status.
  // A ticket can be waited exactly once; unknown tickets are errors.
  virtual Status WaitTicket(uint64_t ticket) = 0;
  // done=true iff the ticket's collective has completed (ticket stays
  // waitable). Unknown/already-waited tickets are errors.
  virtual Status TestTicket(uint64_t ticket, bool* done) = 0;

  virtual int rank() const = 0;
  virtual int world_size() const = 0;
  // Negotiated wire codec: 0 = f32 (uncompressed), 1 = bf16, 2 = int8 —
  // WireCodec values (utils.h). The trainer reads this to route
  // grad_compression through the wire instead of double-casting.
  virtual int32_t wire_codec() const = 0;
};

}  // namespace tpunet

#endif  // TPUNET_COLLECTIVES_H_
