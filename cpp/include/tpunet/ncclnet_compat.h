/* tpunet — NCCL net-plugin ABI compatibility declarations (fresh-written).
 *
 * These declarations reproduce the *shape* of NCCL's public net-plugin ABI so
 * that build/libtpunet.so can double as a drop-in `libnccl-net.so`: an
 * NCCL-style loader dlopens the library and resolves `ncclNetPlugin_v4`
 * (falling back to `ncclNetPlugin_v3`). The reference ships the same two
 * adapters (reference: cc/v4/nccl_net_v4.h:24-62, cc/v3/nccl_net_v3.h:24-61,
 * vendored enums cc/nccl_types.h). Nothing here is copied; the layouts are
 * ABI facts of NCCL's published plugin interface.
 *
 * The only v3/v4 behavioral difference (reference: v3/nccl_net_v3.h:53 vs
 * v4/nccl_net_v4.h:54): v3 `flush` is synchronous, v4 `iflush` returns a
 * request polled via test().
 */
#ifndef TPUNET_NCCLNET_COMPAT_H_
#define TPUNET_NCCLNET_COMPAT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Reference: cc/nccl_types.h:6-12. */
typedef enum {
  ncclSuccess = 0,
  ncclUnhandledCudaError = 1,
  ncclSystemError = 2,
  ncclInternalError = 3,
  ncclInvalidArgument = 4,
  ncclInvalidUsage = 5,
  ncclNumResults = 6
} ncclResult_t;

/* Pointer kinds a plugin may register (reference: cc/nccl_types.h:46-47).
 * tpunet supports host memory only, like the reference (v4/nccl_net_v4.cc:
 * 105-109). */
#define NCCL_PTR_HOST 0x1
#define NCCL_PTR_CUDA 0x2

/* Rendezvous-handle budget and request depth (reference: cc/nccl_types.h:44,
 * :50). Engines must tolerate >= 8 in-flight requests per comm. */
#define NCCL_NET_HANDLE_MAXSIZE 64
#define NCCL_NET_MAX_REQUESTS 8

/* Debug logger injected by the loader at init (reference: cc/nccl_types.h:
 * 52-55). */
typedef enum {
  NCCL_LOG_NONE = 0,
  NCCL_LOG_VERSION = 1,
  NCCL_LOG_WARN = 2,
  NCCL_LOG_INFO = 3,
  NCCL_LOG_ABORT = 4,
  NCCL_LOG_TRACE = 5
} ncclDebugLogLevel;

typedef void (*ncclDebugLogger_t)(ncclDebugLogLevel level, unsigned long flags,
                                  const char* file, int line, const char* fmt,
                                  ...);

/* Device properties returned by getProperties (reference: v4/nccl_net_v4.h +
 * src/lib.rs:41-55 NCCLNetPropertiesC). Strings are owned by the plugin and
 * stay alive for the process lifetime. */
typedef struct {
  char* name;
  char* pciPath;
  uint64_t guid;
  int ptrSupport; /* NCCL_PTR_HOST | NCCL_PTR_CUDA */
  int speed;      /* Mbps */
  int port;
  int maxComms;
} ncclNetProperties_v4_t;

typedef ncclNetProperties_v4_t ncclNetProperties_v3_t;

/* The v4 vtable (reference export: cc/v4/nccl_net_v4.cc:210-226). */
typedef struct {
  const char* name;
  ncclResult_t (*init)(ncclDebugLogger_t logFunction);
  ncclResult_t (*devices)(int* ndev);
  ncclResult_t (*getProperties)(int dev, ncclNetProperties_v4_t* props);
  ncclResult_t (*listen)(int dev, void* handle, void** listenComm);
  ncclResult_t (*connect)(int dev, void* handle, void** sendComm);
  ncclResult_t (*accept)(void* listenComm, void** recvComm);
  ncclResult_t (*regMr)(void* comm, void* data, int size, int type,
                        void** mhandle);
  ncclResult_t (*deregMr)(void* comm, void* mhandle);
  ncclResult_t (*isend)(void* sendComm, void* data, int size, void* mhandle,
                        void** request);
  ncclResult_t (*irecv)(void* recvComm, void* data, int size, void* mhandle,
                        void** request);
  ncclResult_t (*iflush)(void* recvComm, void* data, int size, void* mhandle,
                         void** request);
  ncclResult_t (*test)(void* request, int* done, int* size);
  ncclResult_t (*closeSend)(void* sendComm);
  ncclResult_t (*closeRecv)(void* recvComm);
  ncclResult_t (*closeListen)(void* listenComm);
} ncclNet_v4_t;

/* The v3 vtable (reference export: cc/v3/nccl_net_v3.cc:210-226); synchronous
 * flush instead of iflush. */
typedef struct {
  const char* name;
  ncclResult_t (*init)(ncclDebugLogger_t logFunction);
  ncclResult_t (*devices)(int* ndev);
  ncclResult_t (*getProperties)(int dev, ncclNetProperties_v3_t* props);
  ncclResult_t (*listen)(int dev, void* handle, void** listenComm);
  ncclResult_t (*connect)(int dev, void* handle, void** sendComm);
  ncclResult_t (*accept)(void* listenComm, void** recvComm);
  ncclResult_t (*regMr)(void* comm, void* data, int size, int type,
                        void** mhandle);
  ncclResult_t (*deregMr)(void* comm, void* mhandle);
  ncclResult_t (*isend)(void* sendComm, void* data, int size, void* mhandle,
                        void** request);
  ncclResult_t (*irecv)(void* recvComm, void* data, int size, void* mhandle,
                        void** request);
  ncclResult_t (*flush)(void* recvComm, void* data, int size, void* mhandle);
  ncclResult_t (*test)(void* request, int* done, int* size);
  ncclResult_t (*closeSend)(void* sendComm);
  ncclResult_t (*closeRecv)(void* recvComm);
  ncclResult_t (*closeListen)(void* listenComm);
} ncclNet_v3_t;

/* Exported by libtpunet.so; an NCCL-style loader resolves v4 first, then v3
 * (reference: SURVEY §1 L5→NCCL). */
extern ncclNet_v4_t ncclNetPlugin_v4;
extern ncclNet_v3_t ncclNetPlugin_v3;

#ifdef __cplusplus
}
#endif

#endif /* TPUNET_NCCLNET_COMPAT_H_ */
