// tpunet — OS helpers: NIC discovery, link speed, socket IO, chunk math.
// Reference behavior being reproduced: src/utils.rs (find_interfaces :32-130,
// get_net_if_speed :7-23, nonblocking_write_all/read_exact :132-178,
// chunk_size :200-205, parse_user_pass_and_addr :180-198).
#ifndef TPUNET_UTILS_H_
#define TPUNET_UTILS_H_

#include <sys/socket.h>

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

#include "tpunet/net.h"

namespace tpunet {

struct NicInfo {
  std::string name;
  sockaddr_storage addr = {};
  socklen_t addrlen = 0;
  std::string pci_path;   // resolved from /sys/class/net/<if>/device
  int32_t speed_mbps = 0; // from /sys/class/net/<if>/speed
};

// Enumerate non-loopback up interfaces with an IPv4/IPv6 address, dedup by
// name, honoring:
//   TPUNET_SOCKET_IFNAME / NCCL_SOCKET_IFNAME — "^a,b" prefix-exclude,
//     "=a,b" exact-include, "a,b" prefix-include; default exclude "^docker,lo"
//     (reference: utils.rs:37-49).
//   TPUNET_SOCKET_FAMILY / NCCL_SOCKET_FAMILY — AF_INET / AF_INET6 restrict
//     (reference: utils.rs:33-36,100-103).
std::vector<NicInfo> FindInterfaces();

// Link speed in Mbps from /sys/class/net/<if>/speed; 10000 when unreadable
// (reference: utils.rs:7-23, default :8).
int32_t GetNetIfSpeed(const std::string& ifname);

// max(ceil(total/n), min_chunksize) — both peers compute identical chunk
// boundaries from (len, min_chunksize, nstreams) alone, so the wire carries no
// per-chunk metadata (reference: utils.rs:200-205).
size_t ChunkSize(size_t total, size_t min_chunksize, size_t n);
// Number of chunks a message of `total` bytes splits into (0 for total==0).
size_t ChunkCount(size_t total, size_t chunksize);

// Blocking write/read of exactly n bytes, retrying on EINTR/partial IO.
// A read of 0 bytes means EOF -> error (reference: utils.rs:168-171).
// If `spin` is true the fd is assumed nonblocking and we busy-poll on
// EWOULDBLOCK with sched_yield (the reference's only mode, utils.rs:132-178);
// the default blocking mode is our TPU-host-friendly improvement (no 100% CPU
// burn on a shared trainer host).
Status WriteAll(int fd, const void* buf, size_t n, bool spin = false);
Status ReadExact(int fd, void* buf, size_t n, bool spin = false);

// Read exactly n bytes with a hard wall-clock deadline over the WHOLE read
// (poll + MSG_DONTWAIT recv) — unlike SO_RCVTIMEO, which restarts on every
// byte and lets a slow-loris client stretch a 40-byte read to 40x the
// timeout. Returns IOError on timeout or EOF.
Status ReadExactDeadline(int fd, void* buf, size_t n, int timeout_ms);

// CRC32C (Castagnoli, the iSCSI/ext4 polynomial) over `n` bytes, seeded with
// `crc` (0 for a fresh checksum; chain calls to checksum discontiguous
// buffers). Hardware-accelerated via SSE4.2 when the CPU has it, slicing-by-8
// software fallback otherwise. Golden vector: crc32c("123456789") ==
// 0xE3069283 (RFC 3720 B.4). Used for the per-chunk wire-integrity trailer
// (TPUNET_CRC=1) on data streams.
uint32_t Crc32c(const void* data, size_t n, uint32_t crc = 0);

// "user:pass@host:port" -> (user, pass, addr); user/pass empty when absent
// (reference: utils.rs:180-198).
struct UserPassAddr {
  std::string user, pass, addr;
};
bool ParseUserPassAndAddr(const std::string& s, UserPassAddr* out);

// 8-byte big-endian frame helpers (wire protocol ids + length frames;
// reference: nthread_per_socket_backend.rs:327,395-397 to_be_bytes).
void EncodeU64BE(uint64_t v, uint8_t out[8]);
uint64_t DecodeU64BE(const uint8_t in[8]);

// Env helpers.
std::string GetEnv(const char* name, const std::string& fallback = "");
uint64_t GetEnvU64(const char* name, uint64_t fallback);

// CLOCK_MONOTONIC in microseconds — the shared clock for telemetry stage
// timestamps and trace spans. Monotonic is machine-wide (per-boot), so spans
// from different processes on ONE host share a timeline; cross-host traces
// are aligned by collective tags in merge_traces() instead.
uint64_t MonotonicUs();

// Fork-generation counter: bumps in the child after every fork() (via a
// pthread_atfork handler registered on first call). Threads do not survive
// fork, so anything owning a thread records ForkGeneration() at creation and
// treats a mismatch as "my thread does not exist in this process" — fail fast
// / leak the handle instead of hanging in a queue no one drains or joining a
// pthread that never existed here.
uint64_t ForkGeneration();

// Socket helpers.
Status SetNodelay(int fd);
Status SetNonblocking(int fd);
// Grow SO_SNDBUF/SO_RCVBUF to TPUNET_SOCKET_BUFSIZE bytes (0 = leave kernel
// autotuning alone, the default). Best-effort: the kernel clamps to
// net.core.{w,r}mem_max and never errors the connection over it.
void ApplySocketBufsize(int fd);
// TCP keepalive for dead-peer detection (TPUNET_KEEPALIVE_{IDLE_S,INTVL_S,
// CNT}; idle 0 disables). Best-effort.
void ApplyKeepalive(int fd);
std::string SockaddrToString(const sockaddr_storage& ss, socklen_t len);

}  // namespace tpunet

#endif  // TPUNET_UTILS_H_
