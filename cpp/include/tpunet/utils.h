// tpunet — OS helpers: NIC discovery, link speed, socket IO, chunk math.
// Reference behavior being reproduced: src/utils.rs (find_interfaces :32-130,
// get_net_if_speed :7-23, nonblocking_write_all/read_exact :132-178,
// chunk_size :200-205, parse_user_pass_and_addr :180-198).
#ifndef TPUNET_UTILS_H_
#define TPUNET_UTILS_H_

#include <sys/socket.h>
#include <sys/uio.h>

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

#include "tpunet/net.h"

namespace tpunet {

struct NicInfo {
  std::string name;
  sockaddr_storage addr = {};
  socklen_t addrlen = 0;
  std::string pci_path;   // resolved from /sys/class/net/<if>/device
  int32_t speed_mbps = 0; // from /sys/class/net/<if>/speed
};

// Enumerate non-loopback up interfaces with an IPv4/IPv6 address, dedup by
// name, honoring:
//   TPUNET_SOCKET_IFNAME / NCCL_SOCKET_IFNAME — "^a,b" prefix-exclude,
//     "=a,b" exact-include, "a,b" prefix-include; default exclude "^docker,lo"
//     (reference: utils.rs:37-49).
//   TPUNET_SOCKET_FAMILY / NCCL_SOCKET_FAMILY — AF_INET / AF_INET6 restrict
//     (reference: utils.rs:33-36,100-103).
std::vector<NicInfo> FindInterfaces();

// Link speed in Mbps from /sys/class/net/<if>/speed; 10000 when unreadable
// (reference: utils.rs:7-23, default :8).
int32_t GetNetIfSpeed(const std::string& ifname);

// max(ceil(total/n), min_chunksize) — both peers compute identical chunk
// boundaries from (len, min_chunksize, nstreams) alone, so the wire carries no
// per-chunk metadata (reference: utils.rs:200-205).
size_t ChunkSize(size_t total, size_t min_chunksize, size_t n);
// Number of chunks a message of `total` bytes splits into (0 for total==0).
size_t ChunkCount(size_t total, size_t chunksize);

// Weighted-round-robin slot table for lane striping (docs/DESIGN.md "Lanes
// & adaptive striping"): stream i appears weights[i] times per period
// (sum of weights), interleaved by stride scheduling — at every slot the
// stream with the largest accumulated credit wins (ties break to the lowest
// index), so heavy lanes spread across the period instead of bursting.
// Deterministic: identical weights produce identical tables on both sides
// of a comm, which (with the shared rotating cursor) is what keeps the
// sender's and receiver's chunk->stream maps symmetric without any
// per-chunk wire metadata. Equal weights degenerate to [0, 1, ..., n-1] —
// exactly the uniform rotation. Weights of 0 are treated as 1 (a lane may
// be demoted to the floor but never unscheduled: floor-1 keeps its rate
// measurable for recovery).
std::vector<uint8_t> BuildWrrSlots(const std::vector<uint32_t>& weights);

// ---- Wire-syscall accounting (tpunet_engine_syscalls_total{op,dir}) -------
// Every send/recv-family syscall the engines issue on their data paths bumps
// one relaxed process-wide counter, indexed by the syscall actually made
// (writev/readv are issued as sendmsg/recvmsg so flags apply). The counters
// are what makes the zero-copy work measurable: syscalls/MiB is a number the
// 1-vCPU sandbox cannot noise out the way it noises GB/s.
enum IoOp { kIoSend = 0, kIoRecv = 1, kIoSendmsg = 2, kIoRecvmsg = 3, kIoOpCount = 4 };
void CountIoSyscall(IoOp op);
uint64_t IoSyscallCount(IoOp op);
void ResetIoSyscallCounts();

// Blocking write/read of exactly n bytes, retrying on EINTR/partial IO.
// A read of 0 bytes means EOF -> error (reference: utils.rs:168-171).
// If `spin` is true the fd is assumed nonblocking and we busy-poll on
// EWOULDBLOCK with sched_yield (the reference's only mode, utils.rs:132-178);
// the default blocking mode is our TPU-host-friendly improvement (no 100% CPU
// burn on a shared trainer host). ReadExact passes MSG_WAITALL so a blocking
// chunk read is ONE syscall, not one per kernel-buffer refill — the recv-side
// half of the syscalls/MiB budget (docs/DESIGN.md "Data path").
Status WriteAll(int fd, const void* buf, size_t n, bool spin = false);
Status ReadExact(int fd, void* buf, size_t n, bool spin = false);

// Vectored variants: move every byte described by iov[0..iovcnt) in as few
// sendmsg/recvmsg syscalls as possible (one, in the common case — e.g. a
// chunk payload and its CRC32C trailer coalesce instead of paying separate
// syscalls). The iov array is MUTATED as the cursor advances across partial
// IO; zero-length entries are permitted. Semantics otherwise match
// WriteAll/ReadExact (EINTR retry, spin busy-poll, EOF -> error on read;
// reads use MSG_WAITALL).
Status WritevAll(int fd, struct iovec* iov, int iovcnt, bool spin = false);
Status ReadvExact(int fd, struct iovec* iov, int iovcnt, bool spin = false);

// Read exactly n bytes with a hard wall-clock deadline over the WHOLE read
// (poll + MSG_DONTWAIT recv) — unlike SO_RCVTIMEO, which restarts on every
// byte and lets a slow-loris client stretch a 40-byte read to 40x the
// timeout. Returns IOError on timeout or EOF.
Status ReadExactDeadline(int fd, void* buf, size_t n, int timeout_ms);

// CRC32C (Castagnoli, the iSCSI/ext4 polynomial) over `n` bytes, seeded with
// `crc` (0 for a fresh checksum; chain calls to checksum discontiguous
// buffers). Hardware-accelerated via SSE4.2 when the CPU has it, slicing-by-8
// software fallback otherwise. Golden vector: crc32c("123456789") ==
// 0xE3069283 (RFC 3720 B.4). Used for the per-chunk wire-integrity trailer
// (TPUNET_CRC=1) on data streams.
uint32_t Crc32c(const void* data, size_t n, uint32_t crc = 0);

// ---- Reduction kernels (the collectives' post-wire stage) -----------------
// Elementwise dst[i] = a[i] op b[i] for the wire dtypes. dst may alias a
// (the classic in-place accumulate); the out-of-place collectives pass
// a = caller's sendbuf so no staging copy ever exists. Dispatch is runtime:
// AVX2 lanes for f32/bf16 when the CPU has them (TPUNET_REDUCE_SIMD=0
// forces scalar for bisection), scalar otherwise — the scalar and SIMD
// paths are BITWISE identical, including NaN/inf propagation and bf16
// round-to-nearest-even (pinned by tests/test_wire_vectored.py goldens).
// Above a size threshold the work fans out over a persistent fork-join pool
// (TPUNET_REDUCE_THREADS total shards incl. the caller; 0 = auto), so the
// reduce of ring chunk k keeps pace with the wire moving chunk k+1.
// Every call adds n * element-size to the tpunet_reduce_bytes_total counter.
enum class WireDType : uint8_t { kF32 = 0, kF64, kBF16, kI32, kI64, kU8 };
enum class WireRedOp : uint8_t { kSum = 0, kProd, kMin, kMax };
size_t WireDTypeSize(WireDType d);
void ReduceInto(void* dst, const void* a, const void* b, size_t n,
                WireDType dtype, WireRedOp op);
uint64_t ReduceBytesTotal();
void ResetReduceBytesTotal();

// ---- Wire codecs (compressed ring collectives) ----------------------------
// On-the-wire compression for f32 collective payloads (docs/DESIGN.md
// "Compressed collectives"): the ring encodes each chunk right before isend
// and runs a fused decode+reduce right after irecv, so the ACCUMULATOR stays
// f32 and quantization error enters only at wire hops (EQuARX-style), never
// compounds in the running sum. Two codecs:
//   kBF16 — truncate-with-RNE to bfloat16 (the SAME integer
//     round-to-nearest-even arithmetic as the bf16 reduce kernels, so the
//     wire values are bit-identical to a bf16 cast); 2 bytes/element.
//   kI8 — block-scaled int8: per kI8CodecBlock(=256)-element block, one f32
//     scale amax/127 followed by the rounded int8 quotients. Max elementwise
//     error per wire hop is amax_block/254 (half a quantization step; see
//     DESIGN.md for the derivation). n + 4*ceil(n/256) bytes.
// Dispatch is runtime like ReduceInto: AVX2 bf16 lanes when the CPU has them
// (gated by the same TPUNET_REDUCE_SIMD=0 bisection switch), scalar
// otherwise — bitwise identical either way. Every encode/decode call feeds
// the tpunet_codec_bytes_total{codec,dir} counters plus the payload-byte
// totals behind the tpunet_codec_wire_ratio gauge.
enum class WireCodec : uint8_t { kF32 = 0, kBF16 = 1, kI8 = 2 };
constexpr int kWireCodecCount = 3;
constexpr size_t kI8CodecBlock = 256;  // elements per int8 scale block

// "f32" / "bf16" / "int8" <-> WireCodec. Parse returns false on unknown.
bool ParseWireCodec(const std::string& name, WireCodec* out);
const char* WireCodecName(WireCodec c);

// Encoded byte count for n f32 elements (n*4 for kF32 passthrough).
size_t CodecWireBytes(WireCodec c, size_t n);
// Encode n f32 elements into dst (CodecWireBytes(c, n) bytes).
void CodecEncode(WireCodec c, const float* src, uint8_t* dst, size_t n);
// Decode a wire buffer back to n f32 elements.
void CodecDecode(WireCodec c, const uint8_t* wire, float* dst, size_t n);
// Fused decode+reduce: dst[i] = local[i] op decode(wire)[i], all f32.
// local == nullptr means dst itself (in-place accumulate).
void CodecDecodeReduce(WireCodec c, float* dst, const float* local,
                       const uint8_t* wire, size_t n, WireRedOp op);
// Fused decode+reduce+re-encode for the ring's RS->AG handoff:
//   t       = local op decode(wire)        (f32 accumulate, as above)
//   enc_out = encode(t)                    (the AG phase's step-0 send)
//   dst     = decode(encode(t))            (the QUANTIZED accumulator)
// dst holds the decode of what peers will receive, so every rank
// materializes bit-identical slice values without the AG phase paying a
// separate encode + decode pass over the slice (that pair measured ~1/3 of
// the whole compressed-allreduce overhead). local == nullptr means dst.
void CodecDecodeReduceQuantize(WireCodec c, float* dst, const float* local,
                               const uint8_t* wire, uint8_t* enc_out,
                               size_t n, WireRedOp op);

// Counters behind tpunet_codec_bytes_total{codec,dir} and the
// tpunet_codec_wire_ratio gauge. dir: 0 = tx (encode), 1 = rx (decode).
// Payload totals count the f32 bytes the encoded form stands in for.
uint64_t CodecBytesTotal(WireCodec c, int dir);
uint64_t CodecPayloadBytesTotal(int dir);
void ResetCodecBytesTotals();

// Growable 64-byte-aligned scratch that never zero-fills: reserve() grows
// capacity WITHOUT initializing or preserving contents (it is a landing
// buffer for wire bytes / reduce partials — std::vector::resize would pay an
// O(capacity) zero-fill pass plus first-touch faults for data about to be
// overwritten, the copy class the zero-staging collectives exist to avoid).
// Alignment keeps the SIMD reduce on aligned loads when slices line up.
class ScratchBuf {
 public:
  ScratchBuf() = default;
  ~ScratchBuf();
  ScratchBuf(const ScratchBuf&) = delete;
  ScratchBuf& operator=(const ScratchBuf&) = delete;
  ScratchBuf(ScratchBuf&& o) noexcept : p_(o.p_), cap_(o.cap_) {
    o.p_ = nullptr;
    o.cap_ = 0;
  }
  ScratchBuf& operator=(ScratchBuf&& o) noexcept {
    swap(o);
    return *this;
  }
  uint8_t* data() { return p_; }
  size_t capacity() const { return cap_; }
  void reserve(size_t n);
  void swap(ScratchBuf& o) {
    uint8_t* tp = p_;
    size_t tc = cap_;
    p_ = o.p_;
    cap_ = o.cap_;
    o.p_ = tp;
    o.cap_ = tc;
  }

 private:
  uint8_t* p_ = nullptr;
  size_t cap_ = 0;
};

// "user:pass@host:port" -> (user, pass, addr); user/pass empty when absent
// (reference: utils.rs:180-198).
struct UserPassAddr {
  std::string user, pass, addr;
};
bool ParseUserPassAndAddr(const std::string& s, UserPassAddr* out);

// 8-byte big-endian frame helpers (wire protocol ids + length frames;
// reference: nthread_per_socket_backend.rs:327,395-397 to_be_bytes).
void EncodeU64BE(uint64_t v, uint8_t out[8]);
uint64_t DecodeU64BE(const uint8_t in[8]);

// Env helpers.
std::string GetEnv(const char* name, const std::string& fallback = "");
uint64_t GetEnvU64(const char* name, uint64_t fallback);

// CLOCK_MONOTONIC in microseconds — the shared clock for telemetry stage
// timestamps and trace spans. Monotonic is machine-wide (per-boot), so spans
// from different processes on ONE host share a timeline; cross-host traces
// are aligned by collective tags in merge_traces() instead.
uint64_t MonotonicUs();

// Stable host identity: FNV-1a hash of TPUNET_HOST_ID when set (the
// fake-host override that splits one box into testable "hosts"), else of
// /proc/sys/kernel/random/boot_id (per-boot-unique, shared by every
// process/container on the host), else of gethostname(). Never 0. Two
// processes report the same id iff they can share a memory segment — the
// locality verdict behind the SHM transport handshake and the hierarchical
// collective's host grouping (docs/DESIGN.md "Intra-host shared memory").
uint64_t HostId();

// Fork-generation counter: bumps in the child after every fork() (via a
// pthread_atfork handler registered on first call). Threads do not survive
// fork, so anything owning a thread records ForkGeneration() at creation and
// treats a mismatch as "my thread does not exist in this process" — fail fast
// / leak the handle instead of hanging in a queue no one drains or joining a
// pthread that never existed here.
uint64_t ForkGeneration();

// Socket helpers.
Status SetNodelay(int fd);
Status SetNonblocking(int fd);
// Grow SO_SNDBUF/SO_RCVBUF to TPUNET_SOCKET_BUFSIZE bytes (0 = leave kernel
// autotuning alone, the default). Best-effort: the kernel clamps to
// net.core.{w,r}mem_max and never errors the connection over it.
void ApplySocketBufsize(int fd);
// TCP keepalive for dead-peer detection (TPUNET_KEEPALIVE_{IDLE_S,INTVL_S,
// CNT}; idle 0 disables). Best-effort.
void ApplyKeepalive(int fd);
std::string SockaddrToString(const sockaddr_storage& ss, socklen_t len);

}  // namespace tpunet

#endif  // TPUNET_UTILS_H_
