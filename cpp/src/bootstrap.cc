// tpunet bootstrap implementation: star topology over plain TCP.
// Rank 0 serves; every other rank keeps one persistent connection. Each
// AllGather round: clients send [len u64 | blob], rank 0 checks lengths
// match, concatenates in rank order (own blob included) and fans the result
// back. Wire frames are u64 big-endian like the transport (basic_engine.cc).
#include "tpunet/bootstrap.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <thread>

#include "tpunet/utils.h"

namespace tpunet {
namespace {

constexpr uint64_t kBootstrapMagic = 0x7470626f6f747331ull;  // "tpboots1"

Status ParseHostPort(const std::string& coordinator, sockaddr_storage* addr, socklen_t* alen) {
  size_t colon = coordinator.rfind(':');
  if (colon == std::string::npos) {
    return Status::Inner("coordinator must be host:port, got '" + coordinator + "'");
  }
  std::string host = coordinator.substr(0, colon);
  std::string port = coordinator.substr(colon + 1);
  if (!host.empty() && host.front() == '[' && host.back() == ']') {
    host = host.substr(1, host.size() - 2);  // [v6]:port
  }
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = getaddrinfo(host.c_str(), port.c_str(), &hints, &res);
  if (rc != 0 || !res) {
    return Status::Inner("cannot resolve coordinator '" + coordinator + "': " + gai_strerror(rc));
  }
  memcpy(addr, res->ai_addr, res->ai_addrlen);
  *alen = res->ai_addrlen;
  freeaddrinfo(res);
  return Status::Ok();
}

Status SendFrame(int fd, const void* data, size_t len) {
  uint8_t hdr[8];
  EncodeU64BE(len, hdr);
  Status s = WriteAll(fd, hdr, sizeof(hdr));
  if (!s.ok()) return s;
  if (len == 0) return Status::Ok();
  return WriteAll(fd, data, len);
}

Status RecvFrame(int fd, std::vector<uint8_t>* out, int timeout_ms) {
  uint8_t hdr[8];
  Status s = ReadExactDeadline(fd, hdr, sizeof(hdr), timeout_ms);
  if (!s.ok()) return s;
  uint64_t len = DecodeU64BE(hdr);
  if (len > (1ull << 32)) return Status::Inner("bootstrap frame too large");
  out->resize(len);
  if (len == 0) return Status::Ok();
  return ReadExactDeadline(fd, out->data(), len, timeout_ms);
}

int TimeoutMs() {
  return static_cast<int>(GetEnvU64("TPUNET_BOOTSTRAP_TIMEOUT_MS", 120000));
}

// Rank 0: owns the listening socket and one connection per peer rank.
class RootBootstrap : public Bootstrap {
 public:
  RootBootstrap(int world) : world_(world), peer_fds_(world, -1) {}

  ~RootBootstrap() override {
    for (int fd : peer_fds_) {
      if (fd >= 0) ::close(fd);
    }
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  Status Init(const sockaddr_storage& addr, socklen_t alen) {
    listen_fd_ = ::socket(addr.ss_family, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return Status::TCP("bootstrap socket: " + std::string(strerror(errno)));
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (::bind(listen_fd_, reinterpret_cast<const sockaddr*>(&addr), alen) != 0) {
      return Status::TCP("bootstrap bind: " + std::string(strerror(errno)));
    }
    if (::listen(listen_fd_, 1024) != 0) {
      return Status::TCP("bootstrap listen: " + std::string(strerror(errno)));
    }
    // Collect hellos from all world-1 peers. Poll with the remaining budget
    // before each accept — a blocking accept would never observe the
    // deadline when a rank dies before joining, wedging the coordinator.
    auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(TimeoutMs());
    int connected = 0;
    while (connected < world_ - 1) {
      auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
                           deadline - std::chrono::steady_clock::now())
                           .count();
      if (remaining <= 0) {
        return Status::TCP("bootstrap timed out waiting for " +
                           std::to_string(world_ - 1 - connected) + " rank(s)");
      }
      struct pollfd pfd = {listen_fd_, POLLIN, 0};
      int pr = ::poll(&pfd, 1, static_cast<int>(remaining));
      if (pr < 0 && errno != EINTR) {
        return Status::TCP("bootstrap poll: " + std::string(strerror(errno)));
      }
      if (pr <= 0) continue;  // EINTR or timeout tick: recheck deadline
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (errno == EINTR) continue;
        return Status::TCP("bootstrap accept: " + std::string(strerror(errno)));
      }
      SetNodelay(fd);
      uint8_t hello[16];
      Status s = ReadExactDeadline(fd, hello, sizeof(hello), 10000);
      if (!s.ok() || DecodeU64BE(hello) != kBootstrapMagic) {
        ::close(fd);  // scanner or stray client — ignore
        continue;
      }
      uint64_t peer_rank = DecodeU64BE(hello + 8);
      if (peer_rank == 0 || peer_rank >= static_cast<uint64_t>(world_) ||
          peer_fds_[peer_rank] >= 0) {
        ::close(fd);
        return Status::Inner("bootstrap: bad or duplicate rank " + std::to_string(peer_rank));
      }
      peer_fds_[peer_rank] = fd;
      ++connected;
    }
    return Status::Ok();
  }

  Status AllGather(const void* mine, size_t len, std::vector<uint8_t>* all) override {
    all->assign(world_ * len, 0);
    memcpy(all->data(), mine, len);  // rank 0's own blob
    for (int r = 1; r < world_; ++r) {
      std::vector<uint8_t> blob;
      Status s = RecvFrame(peer_fds_[r], &blob, TimeoutMs());
      if (!s.ok()) return Status::TCP("bootstrap gather from rank " + std::to_string(r) + ": " + s.msg);
      if (blob.size() != len) {
        return Status::Inner("bootstrap length mismatch from rank " + std::to_string(r));
      }
      memcpy(all->data() + r * len, blob.data(), len);
    }
    for (int r = 1; r < world_; ++r) {
      Status s = SendFrame(peer_fds_[r], all->data(), all->size());
      if (!s.ok()) return Status::TCP("bootstrap scatter to rank " + std::to_string(r) + ": " + s.msg);
    }
    return Status::Ok();
  }

  Status Barrier() override {
    uint8_t token = 0;
    std::vector<uint8_t> all;
    return AllGather(&token, 1, &all);
  }

  int rank() const override { return 0; }
  int world_size() const override { return world_; }

 private:
  int world_;
  int listen_fd_ = -1;
  std::vector<int> peer_fds_;
};

// Ranks != 0: one persistent connection to rank 0.
class PeerBootstrap : public Bootstrap {
 public:
  PeerBootstrap(int rank, int world) : rank_(rank), world_(world) {}

  ~PeerBootstrap() override {
    if (fd_ >= 0) ::close(fd_);
  }

  Status Init(const sockaddr_storage& addr, socklen_t alen) {
    auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(TimeoutMs());
    // Retry until the coordinator is up (rank 0 may start last).
    while (true) {
      fd_ = ::socket(addr.ss_family, SOCK_STREAM, 0);
      if (fd_ < 0) return Status::TCP("bootstrap socket: " + std::string(strerror(errno)));
      if (::connect(fd_, reinterpret_cast<const sockaddr*>(&addr), alen) == 0) break;
      ::close(fd_);
      fd_ = -1;
      if (std::chrono::steady_clock::now() > deadline) {
        return Status::TCP("bootstrap: cannot reach coordinator");
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    SetNodelay(fd_);
    uint8_t hello[16];
    EncodeU64BE(kBootstrapMagic, hello);
    EncodeU64BE(static_cast<uint64_t>(rank_), hello + 8);
    return WriteAll(fd_, hello, sizeof(hello));
  }

  Status AllGather(const void* mine, size_t len, std::vector<uint8_t>* all) override {
    Status s = SendFrame(fd_, mine, len);
    if (!s.ok()) return s;
    s = RecvFrame(fd_, all, TimeoutMs());
    if (!s.ok()) return s;
    if (all->size() != static_cast<size_t>(world_) * len) {
      return Status::Inner("bootstrap reply size mismatch");
    }
    return Status::Ok();
  }

  Status Barrier() override {
    uint8_t token = 0;
    std::vector<uint8_t> all;
    return AllGather(&token, 1, &all);
  }

  int rank() const override { return rank_; }
  int world_size() const override { return world_; }

 private:
  int rank_;
  int world_;
  int fd_ = -1;
};

}  // namespace

Status Bootstrap::Create(const std::string& coordinator, int rank, int world_size,
                         std::unique_ptr<Bootstrap>* out) {
  if (world_size < 1 || rank < 0 || rank >= world_size) {
    return Status::Invalid("bad rank/world_size " + std::to_string(rank) + "/" +
                           std::to_string(world_size));
  }
  sockaddr_storage addr;
  socklen_t alen = 0;
  Status s = ParseHostPort(coordinator, &addr, &alen);
  if (!s.ok()) return s;
  if (rank == 0) {
    auto b = std::make_unique<RootBootstrap>(world_size);
    s = b->Init(addr, alen);
    if (!s.ok()) return s;
    *out = std::move(b);
  } else {
    auto b = std::make_unique<PeerBootstrap>(rank, world_size);
    s = b->Init(addr, alen);
    if (!s.ok()) return s;
    *out = std::move(b);
  }
  return Status::Ok();
}

}  // namespace tpunet
