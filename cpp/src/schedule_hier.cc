// Hierarchical two-level AllReduce (HiCCL-style, arxiv 2408.05962) over the
// pairwise mesh:
//
//   1. INTRA-HOST ReduceScatter: the R ranks sharing a host id run a ring
//      reduce-scatter over the mesh comms (which, under TPUNET_SHM=1, are
//      shared-memory ring segments — the stage the hierarchy makes cheap).
//      Local rank index i ends owning shard (i+1) mod R of the R-way
//      partition, fully reduced within the host.
//   2. INTER-HOST stage, one rank per host: the H ranks with the same local
//      index — exactly one per host — AllReduce their owned shard over the
//      DCN. Schedule reuse: the dispatch table / built-ins pick ring or
//      recursive halving-doubling for the SHARD size at world H, so the
//      offline-tuned table drives the inter stage too. Per-rank DCN wire
//      bytes: 2*(S/R)*(H-1)/H — the ~R x cut vs the flat ring's
//      2*S*(W-1)/W that the counter tests gate.
//   3. INTRA-HOST AllGather: the local ring forwards the finished shards
//      byte-verbatim, so every rank of a host materializes identical bytes.
//
// Topology comes from host_ids_ (the Init handshake blob: HostId() per
// rank). Usable = >= 2 distinct hosts AND every host carries the same rank
// count R (shard-parallel inter groups need a full column per shard);
// anything else resolves back to ring in ApplyHierPolicy.
//
// Wire codec (TPUNET_WIRE_DTYPE != f32, f32 payloads): only the INTER stage
// compresses — intra-host hops are memory-cheap by construction, and
// keeping them exact means quantization enters only at DCN hops. The inter
// ring's RS half runs the fused decode+reduce with f32 accumulation; the
// handoff quantizes the owned segment (CodecDecodeReduceQuantize) and the
// AG half forwards those encoded segments VERBATIM, so every member of an
// inter group decodes identical bytes — and the intra AG then spreads those
// identical bytes across the host: all W ranks bit-identical, the PR 5/6
// contract.
//
// Step accounting: every intra wire round bumps hier.intra, every inter
// round hier.inter (dispatch.h CountHierSteps) — the DCN-round shrinkage is
// the claim the counters carry.
#include <string.h>

#include <algorithm>
#include <map>
#include <vector>

#include "coll_comm.h"

namespace tpunet {
namespace internal {

namespace {

// Shard j of an R-way partition of [0, count): [lo, hi).
void ShardRange(size_t count, size_t parts, size_t j, size_t* lo, size_t* hi) {
  *lo = count * j / parts;
  *hi = count * (j + 1) / parts;
}

}  // namespace

// Hosts are ordered by their lowest rank; ranks within a host ascend — every
// rank derives the identical grouping from the identical host_ids_ vector.
// Shared by the hierarchical AllReduce here and the hierarchical AllToAll
// (schedule_a2a.cc), which needs the FULL per-host grouping (t.hosts) to
// address any (host, local index) rank.
HierTopo BuildHierTopo(int rank, const std::vector<uint64_t>& ids) {
  HierTopo t;
  if (ids.empty()) return t;
  std::vector<uint64_t> host_order;
  std::map<uint64_t, std::vector<int>> groups;
  for (int r = 0; r < static_cast<int>(ids.size()); ++r) {
    auto it = groups.find(ids[r]);
    if (it == groups.end()) {
      host_order.push_back(ids[r]);
      groups[ids[r]] = {r};
    } else {
      it->second.push_back(r);  // ascending by construction
    }
  }
  t.H = host_order.size();
  for (size_t h = 0; h < host_order.size(); ++h) {
    t.hosts.push_back(groups[host_order[h]]);
    if (host_order[h] == ids[rank]) t.hi = h;
  }
  t.local = groups[ids[rank]];
  t.R = t.local.size();
  t.uniform = true;
  for (uint64_t h : host_order) {
    if (groups[h].size() != t.R) t.uniform = false;
  }
  for (size_t i = 0; i < t.local.size(); ++i) {
    if (t.local[i] == rank) t.li = i;
  }
  if (t.uniform) {
    for (size_t h = 0; h < host_order.size(); ++h) {
      t.inter.push_back(groups[host_order[h]][t.li]);
    }
  }
  return t;
}

bool ScheduledCommunicator::HierUsable() const {
  if (static_cast<int>(host_ids_.size()) != world_ || world_ < 2) return false;
  HierTopo t = BuildHierTopo(rank_, host_ids_);
  return t.H >= 2 && t.uniform;
}

bool ScheduledCommunicator::HierProfitable() const {
  if (static_cast<int>(host_ids_.size()) != world_ || world_ < 2) return false;
  HierTopo t = BuildHierTopo(rank_, host_ids_);
  // R == 1 makes hier == a flat inter AllReduce — legal under an explicit
  // override, but no reason for auto to leave the tuned ring path.
  return t.H >= 2 && t.uniform && t.R >= 2;
}

// Ring step with distinct send/recv peers over the mesh: irecv first, wait
// both even on error (no abandoned in-flight request may touch a freed
// buffer — the MeshExchange contract).
Status ScheduledCommunicator::MeshShift(int to, const void* sendbuf,
                                        size_t send_nbytes, int from,
                                        void* recvbuf, size_t recv_nbytes) {
  if (to == from) {
    return MeshExchange(to, sendbuf, send_nbytes, recvbuf, recv_nbytes);
  }
  uint64_t rreq = 0, sreq = 0;
  bool rlive = false, slive = false;
  Status st;
  if (recv_nbytes > 0) {
    st = net_->irecv(mesh_recv_[from], recvbuf, recv_nbytes, &rreq);
    if (!st.ok()) return st;
    rlive = true;
  }
  if (send_nbytes > 0) {
    st = net_->isend(mesh_send_[to], sendbuf, send_nbytes, &sreq);
    if (!st.ok()) {
      if (rlive) WaitRequest(rreq, nullptr);
      return st;
    }
    slive = true;
  }
  size_t got = 0;
  Status r_st = rlive ? WaitRequest(rreq, &got) : Status::Ok();
  Status s_st = slive ? WaitRequest(sreq, nullptr) : Status::Ok();
  if (!r_st.ok()) return r_st;
  if (!s_st.ok()) return s_st;
  if (rlive && got != recv_nbytes) {
    return Status::Inner("hier ring step size mismatch: expected " +
                         std::to_string(recv_nbytes) + "B from rank " +
                         std::to_string(from) + ", got " + std::to_string(got) +
                         "B (ranks disagree on collective arguments?)");
  }
  return Status::Ok();
}

// In-place AllReduce over an ordered subgroup: ring reduce-scatter then
// ring all-gather across the group's G-way partition of [0, count). Used
// for the hier INTER stage (inter=true; codec engages for f32) and as the
// building block both intra stages inline around. `idx` is my position in
// `group` (group[idx] == rank_).
Status ScheduledCommunicator::SubgroupAllReduce(const std::vector<int>& group,
                                                size_t idx, uint8_t* data,
                                                size_t count, DType dtype,
                                                RedOp op, bool inter,
                                                uint64_t seq) {
  const size_t G = group.size();
  if (G <= 1 || count == 0) return Status::Ok();
  const size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  const int next = group[(idx + 1) % G];
  const int prev = group[(idx + G - 1) % G];
  const bool codec_on = inter && UseCodec(dtype);
  const WireRedOp wop = ToWireRedOp(op);
  float* data_f = reinterpret_cast<float*>(data);
  const char* kind = inter ? "hier.inter" : "hier.sub";

  // Segment geometry: G-way partition, identical on every member. For the
  // codec path, each segment's encoded form lives at a fixed offset in the
  // assembly buffer (int8 scale blocks restart per segment), so AG hops can
  // forward encoded bytes verbatim.
  std::vector<size_t> seg_lo(G), seg_hi(G), wire_off(G + 1, 0);
  for (size_t j = 0; j < G; ++j) {
    ShardRange(count, G, j, &seg_lo[j], &seg_hi[j]);
    wire_off[j + 1] =
        wire_off[j] +
        (codec_on ? CodecWireBytes(codec_, seg_hi[j] - seg_lo[j]) : 0);
  }
  if (codec_on) mesh_enc_.reserve(wire_off[G]);
  size_t max_seg = 0;
  for (size_t j = 0; j < G; ++j) max_seg = std::max(max_seg, seg_hi[j] - seg_lo[j]);
  mesh_scratch_.reserve(codec_on ? 2 * CodecWireBytes(codec_, max_seg)
                                 : max_seg * esize);

  // ---- Reduce-scatter half: G-1 ring steps. At step t I send segment
  // (idx - t) mod G (my running partial) and receive (idx - t - 1) mod G,
  // folding it into my partial. After G-1 steps I own segment (idx+1) mod G
  // fully reduced.
  for (size_t t = 0; t + 1 < G; ++t) {
    size_t s_j = (idx + G - t) % G;
    size_t r_j = (idx + G - t - 1) % G;
    size_t s_n = seg_hi[s_j] - seg_lo[s_j], r_n = seg_hi[r_j] - seg_lo[r_j];
    PhaseSpan sp(tracing, trace_comm_id_, seq, kind, static_cast<int>(t),
                 s_n * esize);
    CountHierSteps(inter);
    Status st;
    const bool last = t + 2 == G;
    if (codec_on) {
      uint8_t* enc_send = mesh_scratch_.data();
      uint8_t* enc_recv = mesh_scratch_.data() + CodecWireBytes(codec_, max_seg);
      CodecEncode(codec_, data_f + seg_lo[s_j], enc_send, s_n);
      st = MeshShift(next, enc_send, CodecWireBytes(codec_, s_n), prev,
                     enc_recv, CodecWireBytes(codec_, r_n));
      if (!st.ok()) return st;
      if (last) {
        // Handoff: quantize the owned segment, park its encoded bytes in
        // the assembly the AG half forwards verbatim; `data` holds the
        // decode of those bytes — what every peer will materialize.
        CodecDecodeReduceQuantize(codec_, data_f + seg_lo[r_j], nullptr,
                                  enc_recv, mesh_enc_.data() + wire_off[r_j],
                                  r_n, wop);
      } else {
        CodecDecodeReduce(codec_, data_f + seg_lo[r_j], nullptr, enc_recv, r_n,
                          wop);
      }
    } else {
      st = MeshShift(next, data + seg_lo[s_j] * esize, s_n * esize, prev,
                     mesh_scratch_.data(), r_n * esize);
      if (!st.ok()) return st;
      Reduce(data + seg_lo[r_j] * esize, data + seg_lo[r_j] * esize,
             mesh_scratch_.data(), r_n, dtype, op);
    }
  }

  // ---- All-gather half: G-1 ring steps forwarding finished segments. At
  // step t I send segment (idx + 1 - t) mod G and receive (idx - t) mod G.
  // Codec: encoded assembly spans forward verbatim; each member decodes the
  // SAME bytes per segment — bit-identity across the group.
  for (size_t t = 0; t + 1 < G; ++t) {
    size_t s_j = (idx + 1 + G - t) % G;
    size_t r_j = (idx + G - t) % G;
    size_t s_n = seg_hi[s_j] - seg_lo[s_j], r_n = seg_hi[r_j] - seg_lo[r_j];
    PhaseSpan sp(tracing, trace_comm_id_, seq, kind,
                 static_cast<int>(G - 1 + t), s_n * esize);
    CountHierSteps(inter);
    Status st;
    if (codec_on) {
      st = MeshShift(next, mesh_enc_.data() + wire_off[s_j],
                     CodecWireBytes(codec_, s_n), prev,
                     mesh_enc_.data() + wire_off[r_j],
                     CodecWireBytes(codec_, r_n));
      if (!st.ok()) return st;
      CodecDecode(codec_, mesh_enc_.data() + wire_off[r_j], data_f + seg_lo[r_j],
                  r_n);
    } else {
      st = MeshShift(next, data + seg_lo[s_j] * esize, s_n * esize, prev,
                     data + seg_lo[r_j] * esize, r_n * esize);
      if (!st.ok()) return st;
    }
  }
  return Status::Ok();
}

// Halving-doubling subgroup AllReduce: log-depth rounds for the inter-host
// stage when the dispatch layer picks rhd for (shard size, H). Power-of-two
// groups, uncompressed payloads (callers route codec / non-pow2 to the
// subgroup ring). Same vector-halving recursion as schedule_rhd.cc's active
// branch, with subgroup indices in place of virtual ranks.
Status ScheduledCommunicator::SubgroupRhdAllReduce(const std::vector<int>& group,
                                                   size_t idx, uint8_t* data,
                                                   size_t count, DType dtype,
                                                   RedOp op, uint64_t seq) {
  const size_t G = group.size();
  if (G <= 1 || count == 0) return Status::Ok();
  const size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  mesh_scratch_.reserve(((count + 1) / 2) * esize);
  struct Level {
    size_t lo, hi, mid;
    int peer;
    bool keep_low;
  };
  std::vector<Level> levels;
  size_t lo = 0, hi = count;
  int step = 0;
  for (size_t mask = 1; mask < G; mask <<= 1, ++step) {
    const int peer = group[idx ^ mask];
    const size_t mid = lo + (hi - lo) / 2;
    const bool keep_low = (idx & mask) == 0;
    const size_t k_lo = keep_low ? lo : mid, k_hi = keep_low ? mid : hi;
    const size_t s_lo = keep_low ? mid : lo, s_hi = keep_low ? hi : mid;
    PhaseSpan sp(tracing, trace_comm_id_, seq, "hier.inter", step,
                 (s_hi - s_lo) * esize);
    CountHierSteps(/*inter=*/true);
    Status s = MeshExchange(peer, data + s_lo * esize, (s_hi - s_lo) * esize,
                            mesh_scratch_.data(), (k_hi - k_lo) * esize);
    if (!s.ok()) return s;
    Reduce(data + k_lo * esize, data + k_lo * esize, mesh_scratch_.data(),
           k_hi - k_lo, dtype, op);
    levels.push_back({lo, hi, mid, peer, keep_low});
    lo = k_lo;
    hi = k_hi;
  }
  for (int k = static_cast<int>(levels.size()) - 1; k >= 0; --k) {
    const Level& lv = levels[k];
    const size_t sib_lo = lv.keep_low ? lv.mid : lv.lo;
    const size_t sib_hi = lv.keep_low ? lv.hi : lv.mid;
    PhaseSpan sp(tracing, trace_comm_id_, seq, "hier.inter",
                 step + static_cast<int>(levels.size()) - 1 - k,
                 (hi - lo) * esize);
    CountHierSteps(/*inter=*/true);
    Status s = MeshExchange(lv.peer, data + lo * esize, (hi - lo) * esize,
                            data + sib_lo * esize, (sib_hi - sib_lo) * esize);
    if (!s.ok()) return s;
    lo = lv.lo;
    hi = lv.hi;
  }
  return Status::Ok();
}

Status ScheduledCommunicator::DoAllReduceHier(const void* sendbuf, void* recvbuf,
                                              size_t count, DType dtype,
                                              RedOp op, uint64_t seq) {
  const size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  PhaseSpan whole(tracing, trace_comm_id_, seq, "allreduce", -1, count * esize);
  HierTopo t = BuildHierTopo(rank_, host_ids_);
  if (t.H < 2 || !t.uniform) {
    // ApplyHierPolicy keeps this unreachable; belt-and-braces for an
    // explicit override racing an exotic topology.
    return Status::Inner("hier schedule on a non-hierarchical topology");
  }
  Status s = EnsureMeshQuiesced();
  if (!s.ok()) return s;
  uint8_t* data = static_cast<uint8_t*>(recvbuf);
  if (sendbuf != recvbuf) memmove(recvbuf, sendbuf, count * esize);
  if (count == 0) return Status::Ok();

  const size_t R = t.R;
  const int next = t.local[(t.li + 1) % R];
  const int prev = t.local[(t.li + R - 1) % R];

  // ---- Stage 1: intra-host ring ReduceScatter (R-1 memory-cheap rounds).
  // Step arithmetic matches SubgroupAllReduce's RS half; inlined here
  // because stage 3 needs the shards left IN PLACE, not re-gathered.
  size_t max_shard = 0;
  for (size_t j = 0; j < R; ++j) {
    size_t lo, hi;
    ShardRange(count, R, j, &lo, &hi);
    max_shard = std::max(max_shard, hi - lo);
  }
  mesh_scratch_.reserve(max_shard * esize);
  for (size_t st = 0; st + 1 < R; ++st) {
    size_t s_j = (t.li + R - st) % R;
    size_t r_j = (t.li + R - st - 1) % R;
    size_t s_lo, s_hi, r_lo, r_hi;
    ShardRange(count, R, s_j, &s_lo, &s_hi);
    ShardRange(count, R, r_j, &r_lo, &r_hi);
    PhaseSpan sp(tracing, trace_comm_id_, seq, "hier.rs", static_cast<int>(st),
                 (s_hi - s_lo) * esize);
    CountHierSteps(/*inter=*/false);
    s = MeshShift(next, data + s_lo * esize, (s_hi - s_lo) * esize, prev,
                  mesh_scratch_.data(), (r_hi - r_lo) * esize);
    if (!s.ok()) return s;
    Reduce(data + r_lo * esize, data + r_lo * esize, mesh_scratch_.data(),
           r_hi - r_lo, dtype, op);
  }
  const size_t own = (t.li + 1) % R;  // my host-reduced shard

  // ---- Stage 2: inter-host AllReduce of the owned shard, one rank per
  // host. Schedule reuse: resolve ring-vs-rhd for the SHARD size at world H
  // through the same selector the top level uses (hier/tree map onto the
  // ring subgroup — tree's reduce+bcast shape isn't an in-place subgroup
  // primitive here, and recursion would be silly).
  size_t own_lo, own_hi;
  ShardRange(count, R, own, &own_lo, &own_hi);
  if (own_hi > own_lo) {
    CollAlgo inter_algo =
        SelectCollAlgo(dispatch_, CollAlgo::kAuto, CollKind::kAllReduce,
                       (own_hi - own_lo) * esize, static_cast<int>(t.H));
    // rhd needs a power-of-two group and an uncompressed payload (the
    // subgroup ring's verbatim-forwarding AG is where codec bit-identity
    // lives); everything else — including tree/hier verdicts, which have
    // no in-place subgroup shape here — runs the ring. Both move the same
    // 2*(H-1)/H bytes; the table's verdict trades round count only.
    const bool pow2 = (t.H & (t.H - 1)) == 0;
    if (inter_algo == CollAlgo::kRhd && pow2 && !UseCodec(dtype)) {
      s = SubgroupRhdAllReduce(t.inter, t.hi, data + own_lo * esize,
                               own_hi - own_lo, dtype, op, seq);
    } else {
      s = SubgroupAllReduce(t.inter, t.hi, data + own_lo * esize,
                            own_hi - own_lo, dtype, op, /*inter=*/true, seq);
    }
    if (!s.ok()) return s;
  }

  // ---- Stage 3: intra-host ring AllGather (R-1 rounds, bytes forwarded
  // verbatim — cross-rank bit-identity rides on the inter stage's).
  for (size_t st = 0; st + 1 < R; ++st) {
    size_t s_j = (t.li + 1 + R - st) % R;
    size_t r_j = (t.li + R - st) % R;
    size_t s_lo, s_hi, r_lo, r_hi;
    ShardRange(count, R, s_j, &s_lo, &s_hi);
    ShardRange(count, R, r_j, &r_lo, &r_hi);
    PhaseSpan sp(tracing, trace_comm_id_, seq, "hier.ag", static_cast<int>(st),
                 (s_hi - s_lo) * esize);
    CountHierSteps(/*inter=*/false);
    s = MeshShift(next, data + s_lo * esize, (s_hi - s_lo) * esize, prev,
                  data + r_lo * esize, (r_hi - r_lo) * esize);
    if (!s.ok()) return s;
  }
  return Status::Ok();
}

}  // namespace internal
}  // namespace tpunet
