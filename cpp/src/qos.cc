// Transport QoS implementation. See include/tpunet/qos.h for the model.
#include "tpunet/qos.h"

#include <stdio.h>

#include <algorithm>
#include <utility>
#include <vector>

#include "flightrec.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"

namespace tpunet {
namespace {

const char* kClassNames[kTrafficClassCount] = {"latency", "bulk", "control"};

// "123", "64K", "8M", "1G" -> bytes (the fault-spec size grammar).
bool ParseSizeSuffix(const std::string& v, uint64_t* out) {
  if (v.empty()) return false;
  size_t i = 0;
  uint64_t n = 0;
  while (i < v.size() && v[i] >= '0' && v[i] <= '9') {
    n = n * 10 + static_cast<uint64_t>(v[i] - '0');
    ++i;
  }
  if (i == 0) return false;
  if (i + 1 == v.size()) {
    switch (v[i] | 0x20) {
      case 'k': n <<= 10; ++i; break;
      case 'm': n <<= 20; ++i; break;
      case 'g': n <<= 30; ++i; break;
      default: return false;
    }
  }
  if (i != v.size()) return false;
  *out = n;
  return true;
}

// Split "k=v,k=v" and hand each pair to `apply`; empty spec is a no-op.
Status ForEachPair(const std::string& spec, const char* what,
                   Status (*apply)(const std::string&, const std::string&,
                                   QosConfig*),
                   QosConfig* cfg) {
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t comma = spec.find(',', pos);
    if (comma == std::string::npos) comma = spec.size();
    std::string tok = spec.substr(pos, comma - pos);
    pos = comma + 1;
    if (tok.empty()) continue;
    size_t eq = tok.find('=');
    if (eq == std::string::npos) {
      return Status::Invalid(std::string(what) + ": token '" + tok +
                             "' is not key=value");
    }
    Status s = apply(tok.substr(0, eq), tok.substr(eq + 1), cfg);
    if (!s.ok()) return s;
  }
  return Status::Ok();
}

int ClassIndex(const std::string& key) {
  for (int i = 0; i < kTrafficClassCount; ++i) {
    if (key == kClassNames[i]) return i;
  }
  return -1;
}

}  // namespace

bool ParseTrafficClass(const std::string& name, TrafficClass* out) {
  int i = ClassIndex(name);
  if (i < 0) return false;
  *out = static_cast<TrafficClass>(i);
  return true;
}

const char* TrafficClassName(TrafficClass c) {
  int i = static_cast<int>(c);
  return (i >= 0 && i < kTrafficClassCount) ? kClassNames[i] : "?";
}

Status ParseQosWeights(const std::string& spec, QosConfig* cfg) {
  return ForEachPair(
      spec, "TPUNET_QOS_WEIGHTS",
      [](const std::string& key, const std::string& val, QosConfig* c) {
        int i = ClassIndex(key);
        if (i < 0) {
          return Status::Invalid("TPUNET_QOS_WEIGHTS: unknown class '" + key +
                                 "' (expected latency, bulk or control)");
        }
        uint64_t w = 0;
        if (!ParseSizeSuffix(val, &w) || w == 0) {
          return Status::Invalid("TPUNET_QOS_WEIGHTS: weight '" + val +
                                 "' for " + key + " must be an integer >= 1");
        }
        c->weights[i] = w;
        return Status::Ok();
      },
      cfg);
}

Status ParseQosInflightBytes(const std::string& spec, QosConfig* cfg) {
  return ForEachPair(
      spec, "TPUNET_QOS_INFLIGHT_BYTES",
      [](const std::string& key, const std::string& val, QosConfig* c) {
        uint64_t n = 0;
        if (!ParseSizeSuffix(val, &n)) {
          return Status::Invalid("TPUNET_QOS_INFLIGHT_BYTES: bad size '" +
                                 val + "' for " + key +
                                 "' (integer with optional K/M/G)");
        }
        if (key == "wire") {
          c->wire_window = n;
          return Status::Ok();
        }
        int i = ClassIndex(key);
        if (i < 0) {
          return Status::Invalid(
              "TPUNET_QOS_INFLIGHT_BYTES: unknown key '" + key +
              "' (expected latency, bulk, control or wire)");
        }
        c->budgets[i] = n;
        return Status::Ok();
      },
      cfg);
}

QosScheduler::QosScheduler(const QosConfig& cfg) : cfg_(cfg) {}

QosScheduler::~QosScheduler() = default;

QosScheduler& QosScheduler::Get() {
  // Leaked on purpose (engines may release credit during static teardown).
  // A malformed env spec WARNS and keeps defaults here — Config.from_env()
  // is the loud gate (the TPUNET_DISPATCH_TABLE stance); crashing engine
  // creation from a getter would turn a config typo into a hang upstream.
  static QosScheduler* g = [] {
    QosConfig cfg;
    Status ws = ParseQosWeights(GetEnv("TPUNET_QOS_WEIGHTS", ""), &cfg);
    if (!ws.ok()) fprintf(stderr, "[tpunet] ignoring %s\n", ws.msg.c_str());
    Status bs =
        ParseQosInflightBytes(GetEnv("TPUNET_QOS_INFLIGHT_BYTES", ""), &cfg);
    if (!bs.ok()) fprintf(stderr, "[tpunet] ignoring %s\n", bs.msg.c_str());
    return new QosScheduler(cfg);
  }();
  return *g;
}

// ---------------------------------------------------------------------------
// Admission control.

Status QosScheduler::AdmitMessage(TrafficClass cls, uint64_t nbytes,
                                  uint64_t* recorded) {
  *recorded = 0;
  int i = static_cast<int>(cls);
  uint64_t budget = cfg_.budgets[i];
  if (budget == 0) return Status::Ok();  // unbudgeted class: uncharged
  uint64_t cur = admitted_[i].load(std::memory_order_relaxed);
  while (true) {
    // A class with nothing in flight always admits one message, so a
    // message larger than its budget drains eventually instead of being
    // rejected forever.
    if (cur != 0 && cur + nbytes > budget) {
      return Status::QosAdmission(
          "QoS admission: class '" + std::string(TrafficClassName(cls)) +
          "' has " + std::to_string(cur) + "B of its " +
          std::to_string(budget) +
          "B in-flight budget (TPUNET_QOS_INFLIGHT_BYTES) posted; a " +
          std::to_string(nbytes) +
          "B send exceeds it — retry after in-flight work drains");
    }
    if (admitted_[i].compare_exchange_weak(cur, cur + nbytes,
                                           std::memory_order_relaxed)) {
      break;
    }
  }
  *recorded = nbytes;
  return Status::Ok();
}

void QosScheduler::FinishMessage(TrafficClass cls, uint64_t nbytes) {
  if (nbytes == 0) return;
  admitted_[static_cast<int>(cls)].fetch_sub(nbytes,
                                             std::memory_order_relaxed);
}

uint64_t QosScheduler::AdmittedBytes(TrafficClass cls) const {
  return admitted_[static_cast<int>(cls)].load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// Wire-credit gate.

bool QosScheduler::RoomLocked(uint64_t nbytes) const {
  // An empty wire always admits one chunk (a chunk larger than the window
  // must not wedge); otherwise the shared window binds every class.
  return wire_inflight_ == 0 || wire_inflight_ + nbytes <= cfg_.wire_window;
}

void QosScheduler::GrantFrontLocked(int cls) {
  Waiter* w = queues_[cls].front();
  queues_[cls].pop_front();
  wire_inflight_ += w->bytes;
  w->granted = true;
  if (grant_log_) grant_log_->emplace_back(cls, w->bytes);
  if (report_) {
    // report_ also gates the flight recorder: the DRR-golden throwaway sim
    // replays thousands of synthetic grants that would drown the ring.
    flightrec::Record(flightrec::Ev::kQosGrant, static_cast<uint64_t>(cls),
                      w->bytes);
    // Preemption: this grant jumped ahead of an older waiter still queued
    // in another class — the scheduler chose priority over arrival order.
    for (int other = 0; other < kTrafficClassCount; ++other) {
      if (other == cls || queues_[other].empty()) continue;
      if (queues_[other].front()->seq < w->seq) {
        Telemetry::Get().OnQosPreempt(cls);
        break;
      }
    }
  }
}

void QosScheduler::PumpLocked() {
  const int kControlIdx = static_cast<int>(TrafficClass::kControl);
  // Strict priority: control grants ahead of everything, FIFO. While a
  // control chunk is window-blocked, nothing lower may grant either.
  while (!queues_[kControlIdx].empty() &&
         RoomLocked(queues_[kControlIdx].front()->bytes)) {
    GrantFrontLocked(kControlIdx);
  }
  if (!queues_[kControlIdx].empty()) {
    if (report_) {
      flightrec::Record(flightrec::Ev::kQosPause,
                        static_cast<uint64_t>(kControlIdx),
                        queues_[kControlIdx].front()->bytes);
    }
    cv_.NotifyAll();
    return;
  }
  // Deficit round-robin between latency and bulk. A TURN belongs to one
  // class: it earns weight x 64KiB exactly once (at turn start) and spends
  // it front-first until the deficit or the queue runs out. A head that
  // does not fit the shared window PAUSES the turn — the next pump (after
  // a Release) resumes the same turn WITHOUT re-crediting, so weights stay
  // honest under a tight window and neither class can starve: bulk's turn
  // always comes, and always carries its quantum.
  while (true) {
    if (drr_turn_ < 0) {
      bool l = !queues_[0].empty(), b = !queues_[1].empty();
      if (!l && !b) {
        deficit_[0] = deficit_[1] = 0;  // no banking while idle
        break;
      }
      int pick = drr_next_;
      if (queues_[pick].empty()) pick ^= 1;
      drr_next_ = pick ^ 1;  // the other class opens the next turn
      drr_turn_ = pick;
      deficit_[pick] += cfg_.weights[pick] * kQosQuantumBytes;
    }
    int c = drr_turn_;
    while (!queues_[c].empty() && deficit_[c] >= queues_[c].front()->bytes) {
      if (!RoomLocked(queues_[c].front()->bytes)) {
        if (report_) {
          flightrec::Record(flightrec::Ev::kQosPause, static_cast<uint64_t>(c),
                            queues_[c].front()->bytes);
        }
        cv_.NotifyAll();
        return;  // window full mid-turn: resume here on the next pump
      }
      deficit_[c] -= queues_[c].front()->bytes;
      GrantFrontLocked(c);
    }
    if (queues_[c].empty()) deficit_[c] = 0;
    drr_turn_ = -1;  // turn exhausted: rotation picks the next class
  }
  cv_.NotifyAll();
}

void QosScheduler::RemoveWaiterLocked(Waiter* w) {
  auto& q = queues_[static_cast<int>(w->cls)];
  for (auto it = q.begin(); it != q.end(); ++it) {
    if (*it == w) {
      q.erase(it);
      return;
    }
  }
}

bool QosScheduler::AcquireWire(TrafficClass cls, uint64_t nbytes,
                               const std::atomic<bool>* aborted) {
  if (!wire_gate_enabled()) return true;
  uint64_t t0 = MonotonicUs();
  Waiter w;
  w.cls = cls;
  w.bytes = nbytes;
  {
    MutexLock lk(mu_);
    w.seq = next_seq_++;
    queues_[static_cast<int>(cls)].push_back(&w);
    PumpLocked();
    while (!w.granted) {
      if (aborted != nullptr && aborted->load(std::memory_order_acquire)) {
        RemoveWaiterLocked(&w);
        return false;
      }
      cv_.WaitFor(mu_, 50);
    }
  }
  if (report_) Telemetry::Get().OnQosQueueWait(static_cast<int>(cls),
                                               MonotonicUs() - t0);
  return true;
}

bool QosScheduler::TryAcquireWire(TrafficClass cls, uint64_t nbytes,
                                  uint64_t* ticket) {
  if (!wire_gate_enabled()) return true;
  MutexLock lk(mu_);
  auto w = std::make_unique<Waiter>();
  w->cls = cls;
  w->bytes = nbytes;
  w->seq = next_seq_++;
  w->ticket = next_ticket_++;
  Waiter* raw = w.get();
  queues_[static_cast<int>(cls)].push_back(raw);
  PumpLocked();
  if (raw->granted) return true;  // w destroyed; credit held by the caller
  *ticket = raw->ticket;
  tickets_[raw->ticket] = std::move(w);
  return false;
}

bool QosScheduler::PollTicket(uint64_t ticket) {
  MutexLock lk(mu_);
  auto it = tickets_.find(ticket);
  if (it == tickets_.end()) return false;  // cancelled elsewhere: not held
  if (!it->second->granted) PumpLocked();
  if (!it->second->granted) return false;
  tickets_.erase(it);  // credit transfers to the caller
  return true;
}

void QosScheduler::CancelTicket(uint64_t ticket) {
  MutexLock lk(mu_);
  auto it = tickets_.find(ticket);
  if (it == tickets_.end()) return;
  if (it->second->granted) {
    // Granted but never claimed: the credit must flow back.
    wire_inflight_ -= std::min(wire_inflight_, it->second->bytes);
    tickets_.erase(it);
    PumpLocked();
    return;
  }
  RemoveWaiterLocked(it->second.get());
  tickets_.erase(it);
}

void QosScheduler::ReleaseWire(TrafficClass cls, uint64_t nbytes) {
  (void)cls;
  if (!wire_gate_enabled()) return;
  MutexLock lk(mu_);
  wire_inflight_ -= std::min(wire_inflight_, nbytes);
  PumpLocked();
}

// ---------------------------------------------------------------------------
// Introspection + golden simulation.

std::string QosScheduler::StateText() {
  std::string out = "weights";
  for (int i = 0; i < kTrafficClassCount; ++i) {
    out += " " + std::string(kClassNames[i]) + "=" +
           std::to_string(cfg_.weights[i]);
  }
  out += "\nbudgets";
  for (int i = 0; i < kTrafficClassCount; ++i) {
    out += " " + std::string(kClassNames[i]) + "=" +
           std::to_string(cfg_.budgets[i]);
  }
  out += "\nwire_window " + std::to_string(cfg_.wire_window);
  out += "\nadmitted";
  for (int i = 0; i < kTrafficClassCount; ++i) {
    out += " " + std::string(kClassNames[i]) + "=" +
           std::to_string(admitted_[i].load(std::memory_order_relaxed));
  }
  MutexLock lk(mu_);
  out += "\nwire_inflight " + std::to_string(wire_inflight_);
  out += "\nqueued";
  for (int i = 0; i < kTrafficClassCount; ++i) {
    out += " " + std::string(kClassNames[i]) + "=" +
           std::to_string(queues_[i].size());
  }
  out += "\n";
  return out;
}

std::string QosScheduler::DrrGolden(const std::string& weights_spec,
                                    const std::string& window_spec,
                                    const std::string& chunks,
                                    std::string* err) {
  QosConfig cfg;
  Status s = ParseQosWeights(weights_spec, &cfg);
  if (s.ok()) s = ParseQosInflightBytes(window_spec, &cfg);
  if (!s.ok()) {
    *err = s.msg;
    return "";
  }
  if (cfg.wire_window == 0) {
    *err = "DRR golden needs a wire window (window_spec \"wire=<bytes>\")";
    return "";
  }
  QosScheduler sim(cfg);
  sim.report_ = false;  // throwaway instance: keep process counters clean
  std::vector<std::unique_ptr<Waiter>> waiters;
  {
    MutexLock lk(sim.mu_);
    size_t pos = 0;
    while (pos <= chunks.size()) {
      size_t comma = chunks.find(',', pos);
      if (comma == std::string::npos) comma = chunks.size();
      std::string tok = chunks.substr(pos, comma - pos);
      pos = comma + 1;
      if (tok.empty()) continue;
      size_t colon = tok.find(':');
      TrafficClass cls;
      uint64_t bytes = 0;
      if (colon == std::string::npos ||
          !ParseTrafficClass(tok.substr(0, colon), &cls) ||
          !ParseSizeSuffix(tok.substr(colon + 1), &bytes) || bytes == 0) {
        *err = "bad chunk token '" + tok + "' (want class:bytes)";
        return "";
      }
      auto w = std::make_unique<Waiter>();
      w->cls = cls;
      w->bytes = bytes;
      w->seq = sim.next_seq_++;
      sim.queues_[static_cast<int>(cls)].push_back(w.get());
      waiters.push_back(std::move(w));
    }
  }
  // Drive: pump, and whenever the window blocks further grants, retire the
  // oldest granted chunk (grant order == service order in the simulation).
  std::deque<std::pair<int, uint64_t>> log;
  std::string out;
  size_t retired = 0, emitted = 0;
  {
    MutexLock lk(sim.mu_);
    sim.grant_log_ = &log;
    while (emitted < waiters.size()) {
      size_t before = log.size();
      sim.PumpLocked();
      for (; emitted < log.size(); ++emitted) {
        if (!out.empty()) out += ",";
        out += kClassNames[log[emitted].first];
      }
      if (log.size() == before) {
        if (retired >= log.size()) {
          *err = "simulation wedged (chunk larger than the window?)";
          sim.grant_log_ = nullptr;
          return "";
        }
        sim.wire_inflight_ -=
            std::min(sim.wire_inflight_, log[retired].second);
        ++retired;
      }
    }
    sim.grant_log_ = nullptr;
  }
  return out;
}

}  // namespace tpunet
