// Ring schedule: the chunk-pipelined reduce-scatter + all-gather AllReduce
// (2(W-1) wire rounds, busbw-optimal 2(W-1)/W bytes per element), standalone
// ReduceScatter/AllGather phases, and the pipelined Broadcast relay — plus
// the exchange primitives every schedule shares (Exchange, the chunked
// ExchangeReduce pipeline, and the fused codec variants).
//
// The ring is latency-pessimal (linear round count) but owns the large-
// message end: its chunk pipeline overlaps reduction with transfer, the
// codec fuses decode+reduce off the recv slot, and slices forward encoded
// bytes verbatim in the AG phase (cross-rank bit-identical results). The
// per-size selector (dispatch.h) hands small payloads to the rhd/tree
// schedules instead.
#include <string.h>

#include <algorithm>
#include <string>
#include <vector>

#include "coll_comm.h"

namespace tpunet {
namespace internal {

Status ScheduledCommunicator::DoAllReduceRing(const void* sendbuf, void* recvbuf,
                                              size_t count, DType dtype, RedOp op,
                                              RingChannel& ch, uint64_t seq) {
  size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  PhaseSpan whole(tracing, trace_comm_id_, seq, "allreduce", -1, count * esize);
  const uint8_t* src = static_cast<const uint8_t*>(sendbuf);
  uint8_t* data = static_cast<uint8_t*>(recvbuf);
  // Out-of-place with DISJOINT buffers needs no staging copy at all:
  // round 0 sends from the caller's sendbuf, later rounds send the slice
  // reduced the previous round (already in recvbuf), and every reduce
  // reads its local operand from sendbuf while writing into recvbuf —
  // every recvbuf slice is written (by RS or AG) before anything reads
  // it, so the caller's input never needs to be there. Measured 2x
  // on the 128 MiB out-of-place path (PERF_NOTES round 4): the memcpy
  // plus first-touch faulting of a cold 128 MiB destination was as
  // expensive as the whole ring on a 1-core host. Partially-overlapping
  // buffers (C-ABI callers only; the Python binding never does this)
  // keep the safe copy path.
  bool oop = sendbuf != recvbuf;
  if (oop && src < data + count * esize && data < src + count * esize) {
    // Overlapping: stage (memmove — the ranges provably overlap).
    memmove(recvbuf, sendbuf, count * esize);
    oop = false;
  }
  const int W = world_;
  auto off = [&](int i) { return (count * static_cast<size_t>(i)) / W; };

  // vr relabels the ring so this rank finishes the RS phase owning slice
  // `rank`, which the AG phase then circulates.
  const int vr = (rank_ + W - 1) % W;
  const bool codec_on = UseCodec(dtype);
  size_t ag_slot = 0;
  if (codec_on) {
    // Park the AG phase's two wire slots at the BOTTOM of the channel
    // scratch, before any RS chunk slot: the RS final round's fused
    // handoff writes the owned slice's encoded bytes into AG slot 0, and
    // they must survive the RS rounds' own scratch use.
    ag_slot = CodecWireBytes(codec_, (count + W - 1) / W);
    ch.scratch.reserve(2 * ag_slot +
                       4 * CodecWireBytes(codec_, CodecChunkElems()));
  }
  for (int s = 0; s < W - 1; ++s) {
    int sidx = (vr - s + W) % W;
    int ridx = (vr - s - 1 + W) % W;
    size_t sbytes = (off(sidx + 1) - off(sidx)) * esize;
    size_t rbytes = (off(ridx + 1) - off(ridx)) * esize;
    // Round s sends the slice reduced in round s-1; only round 0's send
    // operand still lives in sendbuf on the no-copy path.
    const uint8_t* sptr =
        ((oop && s == 0) ? src : data) + off(sidx) * esize;
    PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, sbytes);
    CountCollSteps(CollAlgo::kRing);
    Status st;
    if (codec_on) {
      // Final round reduces into this rank's owned slice (ridx == rank_):
      // fuse the AG-entry quantize+encode into it.
      uint8_t* fused = (s == W - 2) ? ch.scratch.data() : nullptr;
      st = ExchangeReduceCodec(sptr, sbytes, data + off(ridx) * esize,
                               rbytes, op, ch,
                               oop ? src + off(ridx) * esize : nullptr,
                               fused, 2 * ag_slot);
    } else {
      st = ExchangeReduce(sptr, sbytes, data + off(ridx) * esize,
                          rbytes, dtype, op, ch,
                          oop ? src + off(ridx) * esize : nullptr);
    }
    if (!st.ok()) return st;
  }
  if (codec_on) {
    return AgPhaseCodec(reinterpret_cast<float*>(data), count, ch, seq, tracing);
  }
  for (int s = 0; s < W - 1; ++s) {
    int sidx = (rank_ - s + W) % W;
    int ridx = (rank_ - s - 1 + W) % W;
    size_t sbytes = (off(sidx + 1) - off(sidx)) * esize;
    size_t rbytes = (off(ridx + 1) - off(ridx)) * esize;
    PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, sbytes);
    CountCollSteps(CollAlgo::kRing);
    Status st = Exchange(data + off(sidx) * esize, sbytes, data + off(ridx) * esize,
                         rbytes, nullptr, ch);
    if (!st.ok()) return st;
  }
  return Status::Ok();
}

Status ScheduledCommunicator::ReduceScatter(const void* sendbuf, void* recvbuf,
                                            size_t recv_count, DType dtype,
                                            RedOp op) {
  FenceAsync();
  size_t esize = DTypeSize(dtype);
  if (esize == 0) return Status::Invalid("bad dtype");
  if (recv_count == 0) return Status::Ok();
  const int W = world_;
  if (W == 1) {
    if (sendbuf != recvbuf) memcpy(recvbuf, sendbuf, recv_count * esize);
    return Status::Ok();
  }
  size_t block = recv_count * esize;
  const uint8_t* src = static_cast<const uint8_t*>(sendbuf);
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  const bool tracing = Telemetry::Get().tracing_enabled();
  const uint64_t seq = ++coll_seq_;
  PhaseSpan whole(tracing, trace_comm_id_, seq, "reduce_scatter", -1,
                  static_cast<uint64_t>(W) * block);
  if (out < src + static_cast<size_t>(W) * block && src < out + block) {
    // Overlapping C-ABI buffers: keep the safe full-copy path.
    work_.reserve(static_cast<size_t>(W) * block);
    memcpy(work_.data(), sendbuf, static_cast<size_t>(W) * block);
    const int vr0 = (rank_ + W - 1) % W;
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (vr0 - s + W) % W;
      int ridx = (vr0 - s - 1 + W) % W;
      PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, block);
      CountCollSteps(CollAlgo::kRing);
      Status st = ExchangeReduce(work_.data() + sidx * block, block,
                                 work_.data() + ridx * block, block, dtype, op, channels_[0]);
      if (!st.ok()) return st;
    }
    memcpy(recvbuf, work_.data() + rank_ * block, block);
    return Status::Ok();
  }
  // No staging copy of the W-block input: each round's reduce reads its
  // local operand from the caller's sendbuf; partials land in a 2-block
  // ping-pong scratch (a round's output is the NEXT round's send
  // operand), and the final round — whose target is this rank's owned
  // block — writes straight into recvbuf. Scratch is 2 blocks instead of
  // the previous W, and the O(W·B) memcpy is gone. W=2's single round
  // goes sendbuf->recvbuf directly and needs no scratch at all (resizing
  // it would zero-fill + fault pages for nothing — the cost class this
  // path exists to avoid).
  uint8_t* pb[2] = {nullptr, nullptr};
  if (W > 2) {
    work_.reserve(2 * block);
    pb[0] = work_.data();
    pb[1] = work_.data() + block;
  }  // W==2: single round goes sendbuf->recvbuf, pb never read
  const int vr = (rank_ + W - 1) % W;
  for (int s = 0; s < W - 1; ++s) {
    int sidx = (vr - s + W) % W;
    int ridx = (vr - s - 1 + W) % W;
    const uint8_t* sptr = (s == 0) ? src + sidx * block : pb[(s - 1) & 1];
    uint8_t* optr = (s == W - 2) ? out : pb[s & 1];
    PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, block);
    CountCollSteps(CollAlgo::kRing);
    Status st = ExchangeReduce(sptr, block, optr, block, dtype, op,
                               channels_[0], src + ridx * block);
    if (!st.ok()) return st;
  }
  return Status::Ok();
}

Status ScheduledCommunicator::AllGather(const void* sendbuf, void* recvbuf,
                                        size_t bytes_per_rank) {
  FenceAsync();
  const int W = world_;
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  if (out + rank_ * bytes_per_rank != sendbuf) {
    memcpy(out + rank_ * bytes_per_rank, sendbuf, bytes_per_rank);
  }
  if (W == 1 || bytes_per_rank == 0) return Status::Ok();
  const bool tracing = Telemetry::Get().tracing_enabled();
  const uint64_t seq = ++coll_seq_;
  PhaseSpan whole(tracing, trace_comm_id_, seq, "all_gather", -1,
                  static_cast<uint64_t>(W) * bytes_per_rank);
  for (int s = 0; s < W - 1; ++s) {
    int sidx = (rank_ - s + W) % W;
    int ridx = (rank_ - s - 1 + W) % W;
    PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, bytes_per_rank);
    CountCollSteps(CollAlgo::kRing);
    Status st = Exchange(out + sidx * bytes_per_rank, bytes_per_rank,
                         out + ridx * bytes_per_rank, bytes_per_rank, nullptr, channels_[0]);
    if (!st.ok()) return st;
  }
  return Status::Ok();
}

Status ScheduledCommunicator::DoBroadcastRing(void* buf, size_t nbytes, int root,
                                              uint64_t seq) {
  const int W = world_;
  PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, seq,
                  "broadcast", -1, nbytes);
  uint8_t* data = static_cast<uint8_t*>(buf);
  int dist = (rank_ - root + W) % W;          // hops from root along the ring
  bool is_tail = dist == W - 1;               // last rank forwards nothing
  size_t nchunks = (nbytes + kBcastChunk - 1) / kBcastChunk;
  // Steps counter: one sequential recv round (non-root) + one forward round
  // (non-tail) — the chunked pipeline inside a round is overlap, not extra
  // latency hops.
  CountCollSteps(CollAlgo::kRing, (dist != 0 ? 1 : 0) + (is_tail ? 0 : 1));

  // Pipelined forward: receive chunk c, then send it on while chunk c+1 is
  // in flight — the ring streams instead of store-and-forwarding the
  // whole buffer W-1 times.
  std::vector<uint64_t> pending_sends;
  for (size_t c = 0; c < nchunks; ++c) {
    size_t coff = c * kBcastChunk;
    size_t clen = std::min(kBcastChunk, nbytes - coff);
    if (dist != 0) {
      uint64_t rreq = 0;
      Status st = net_->irecv(channels_[0].recv_comm, data + coff, clen, &rreq);
      if (!st.ok()) return DrainSends(pending_sends, st);
      size_t got = 0;
      st = WaitRequest(rreq, &got);
      if (!st.ok()) return DrainSends(pending_sends, st);
      if (got != clen) {
        return DrainSends(pending_sends, Status::Inner("broadcast chunk size mismatch"));
      }
    }
    if (!is_tail) {
      uint64_t sreq = 0;
      Status st = net_->isend(channels_[0].send_comm, data + coff, clen, &sreq);
      if (!st.ok()) return DrainSends(pending_sends, st);
      pending_sends.push_back(sreq);
    }
  }
  return DrainSends(pending_sends, Status::Ok());
}

// ---------------------------------------------------------------------------
// Exchange primitives (shared by every schedule and the wiring quiesces).

// One pipelined reduce ring step: send `sendbuf` to next while receiving
// the same-size slice from prev in chunks, folding each received chunk
// into `accum` (element count = slice bytes / esize) as soon as it lands —
// chunk i's Reduce overlaps chunk i+1's transfer. Double-buffered scratch;
// all in-flight requests are quiesced before returning, even on error.
// `local` is the left operand of the reduce (accum = local op incoming);
// nullptr = accum itself (the classic in-place accumulate). A distinct
// local lets out-of-place collectives read the caller's sendbuf directly
// and write partials straight into recvbuf — no staging copy anywhere.
Status ScheduledCommunicator::ExchangeReduce(const uint8_t* sendbuf, size_t send_nbytes,
                                             uint8_t* accum, size_t recv_nbytes,
                                             DType dtype, RedOp op, RingChannel& ch,
                                             const uint8_t* local) {
  if (local == nullptr) local = accum;
  if (UseCodec(dtype)) {
    return ExchangeReduceCodec(sendbuf, send_nbytes, accum, recv_nbytes, op,
                               ch, local);
  }
  size_t esize = DTypeSize(dtype);
  size_t chunk = RingChunkBytes() / esize * esize;
  if (chunk == 0 || (send_nbytes <= chunk && recv_nbytes <= chunk)) {
    ch.scratch.reserve(recv_nbytes);
    Status st = Exchange(sendbuf, send_nbytes, ch.scratch.data(), recv_nbytes, nullptr, ch);
    if (!st.ok()) return st;
    Reduce(accum, local, ch.scratch.data(), recv_nbytes / esize, dtype, op);
    return Status::Ok();
  }
  // Send and recv slice sizes can differ (ring slices are count*i/W
  // splits); each side chunks ITS byte count with the shared chunk size,
  // which matches what the peer computes for the same bytes. A chunk-size
  // mismatch between ranks surfaces as a size-mismatch error below.
  size_t ns = (send_nbytes + chunk - 1) / chunk;
  size_t nr = (recv_nbytes + chunk - 1) / chunk;
  size_t n = std::max(ns, nr);
  ch.scratch.reserve(2 * chunk);
  auto slen = [&](size_t i) { return std::min(chunk, send_nbytes - i * chunk); };
  auto rlen = [&](size_t i) { return std::min(chunk, recv_nbytes - i * chunk); };

  uint64_t rreq[2] = {0, 0}, sreq[2] = {0, 0};
  bool rlive[2] = {false, false}, slive[2] = {false, false};
  auto post = [&](size_t i) -> Status {
    int slot = i & 1;
    if (i < nr) {
      Status st =
          net_->irecv(ch.recv_comm, ch.scratch.data() + slot * chunk, rlen(i), &rreq[slot]);
      if (!st.ok()) return st;
      rlive[slot] = true;
    }
    if (i < ns) {
      Status st = net_->isend(ch.send_comm, sendbuf + i * chunk, slen(i), &sreq[slot]);
      if (!st.ok()) return st;
      slive[slot] = true;
    }
    return Status::Ok();
  };
  auto quiesce = [&](Status primary) {
    for (int b = 0; b < 2; ++b) {
      if (rlive[b]) WaitRequest(rreq[b], nullptr);
      if (slive[b]) WaitRequest(sreq[b], nullptr);
    }
    return primary;
  };

  Status st = post(0);
  if (!st.ok()) return quiesce(st);
  for (size_t i = 0; i < n; ++i) {
    int slot = i & 1;
    bool has_r = i < nr;
    if (has_r) {
      size_t got = 0;
      st = WaitRequest(rreq[slot], &got);
      rlive[slot] = false;
      if (!st.ok()) return quiesce(st);
      if (got != rlen(i)) {
        return quiesce(Status::Inner(
            "ring step size mismatch: expected " + std::to_string(rlen(i)) +
            "B chunk, got " + std::to_string(got) +
            "B (ranks disagree on collective arguments or TPUNET_RING_CHUNKSIZE?)"));
      }
    }
    if (i + 1 < n) {
      st = post(i + 1);  // keep the wire busy while we reduce chunk i
      if (!st.ok()) return quiesce(st);
    }
    if (has_r) {
      Reduce(accum + i * chunk, local + i * chunk,
             ch.scratch.data() + slot * chunk, rlen(i) / esize, dtype, op);
    }
    if (i < ns) {
      st = WaitRequest(sreq[slot], nullptr);
      slive[slot] = false;
      if (!st.ok()) return quiesce(st);
    }
  }
  return Status::Ok();
}

// Payload elements per pipeline chunk, sized so the WIRE chunk — not the
// payload chunk — lands on the tuned TPUNET_RING_CHUNKSIZE granularity:
// the ring's per-chunk costs (ctrl frames, request churn, stream
// scheduling) are paid per chunk regardless of its size, so a compressed
// chunk must carry as many wire bytes as an uncompressed one or
// compression halves the bytes but none of the per-chunk overhead
// (measured: payload-sized bf16 chunks left the whole RS phase at f32
// speed). int8 chunks stay multiples of the scale block so the per-chunk
// encoding is byte-identical to a whole-slice encode (the fused RS->AG
// handoff and the AG receiver both rely on that).
size_t ScheduledCommunicator::CodecChunkElems() const {
  size_t ce;
  switch (codec_) {
    case WireCodec::kBF16:
      ce = RingChunkBytes() / 2;  // 2 wire bytes per element
      break;
    case WireCodec::kI8:
      ce = RingChunkBytes() & ~(kI8CodecBlock - 1);  // ~1 wire byte/element
      if (ce < kI8CodecBlock) ce = kI8CodecBlock;
      break;
    default:
      ce = RingChunkBytes() / 4;
      break;
  }
  return std::max<size_t>(ce, 1);
}

// Codec variant of ExchangeReduce for f32 payloads (docs/DESIGN.md
// "Compressed collectives"): each chunk is ENCODED into a scratch slot
// right before its isend and runs a FUSED decode+reduce straight off the
// recv slot — the accumulator (and the local operand) stay f32, so
// quantization error enters once per wire hop and never compounds in the
// running sum. Chunk boundaries are computed over ELEMENT counts exactly
// like the uncompressed path, so both peers derive identical per-chunk
// wire sizes from their own payload byte counts; a rank disagreement
// surfaces as the same size-mismatch error. Double-buffered recv AND send
// slots (the encode is a staging copy the zero-copy f32 path avoids —
// that copy is the price of shipping half/quarter the bytes).
// `fused_enc` (optional): run the RS->AG handoff kernel on every received
// chunk — the accumulator comes out QUANTIZED (bit-identical to what peers
// will decode) and its encoded form lands at fused_enc, laid out exactly
// like a whole-slice encode, ready to be the AG phase's first send.
// `scratch_off`: byte offset into ch.scratch below which the caller has
// staged bytes this call must not clobber.
Status ScheduledCommunicator::ExchangeReduceCodec(
    const uint8_t* sendbuf, size_t send_nbytes, uint8_t* accum, size_t recv_nbytes,
    RedOp op, RingChannel& ch, const uint8_t* local, uint8_t* fused_enc,
    size_t scratch_off) {
  if (local == nullptr) local = accum;  // classic in-place accumulate
  const float* send_f = reinterpret_cast<const float*>(sendbuf);
  float* acc_f = reinterpret_cast<float*>(accum);
  const float* loc_f = reinterpret_cast<const float*>(local);
  const WireRedOp wop = ToWireRedOp(op);
  const size_t send_n = send_nbytes / 4;
  const size_t recv_n = recv_nbytes / 4;
  const size_t chunk_elems = CodecChunkElems();

  if (send_n <= chunk_elems && recv_n <= chunk_elems) {
    size_t rw = CodecWireBytes(codec_, recv_n);
    size_t sw = CodecWireBytes(codec_, send_n);
    ch.scratch.reserve(scratch_off + rw + sw);
    uint8_t* rbuf = ch.scratch.data() + scratch_off;
    uint8_t* sbuf = rbuf + rw;
    CodecEncode(codec_, send_f, sbuf, send_n);
    Status st = Exchange(sbuf, sw, rbuf, rw, nullptr, ch);
    if (!st.ok()) return st;
    if (fused_enc != nullptr) {
      CodecDecodeReduceQuantize(codec_, acc_f, loc_f, rbuf, fused_enc, recv_n, wop);
    } else {
      CodecDecodeReduce(codec_, acc_f, loc_f, rbuf, recv_n, wop);
    }
    return Status::Ok();
  }

  const size_t ns = (send_n + chunk_elems - 1) / chunk_elems;
  const size_t nr = (recv_n + chunk_elems - 1) / chunk_elems;
  const size_t n = std::max(ns, nr);
  const size_t slot_bytes = CodecWireBytes(codec_, chunk_elems);
  // 2 recv + 2 send wire slots, after whatever the caller staged below
  // scratch_off (DoAllReduceRing parks the AG slots there — reserve only
  // grows, so their bytes survive this call).
  ch.scratch.reserve(scratch_off + 4 * slot_bytes);
  uint8_t* base = ch.scratch.data() + scratch_off;
  auto rbuf = [&](size_t i) { return base + (i & 1) * slot_bytes; };
  auto sbuf = [&](size_t i) { return base + (2 + (i & 1)) * slot_bytes; };
  auto selems = [&](size_t i) { return std::min(chunk_elems, send_n - i * chunk_elems); };
  auto relems = [&](size_t i) { return std::min(chunk_elems, recv_n - i * chunk_elems); };

  uint64_t rreq[2] = {0, 0}, sreq[2] = {0, 0};
  bool rlive[2] = {false, false}, slive[2] = {false, false};
  auto post = [&](size_t i) -> Status {
    int slot = i & 1;
    if (i < nr) {
      Status st = net_->irecv(ch.recv_comm, rbuf(i),
                              CodecWireBytes(codec_, relems(i)), &rreq[slot]);
      if (!st.ok()) return st;
      rlive[slot] = true;
    }
    if (i < ns) {
      // Encode right before the isend: slot (i&1)'s previous send (i-2)
      // was waited at the tail of iteration i-2, so the staging bytes are
      // free to overwrite, and the encode of chunk i overlaps the wire
      // moving chunk i-1.
      CodecEncode(codec_, send_f + i * chunk_elems, sbuf(i), selems(i));
      Status st = net_->isend(ch.send_comm, sbuf(i),
                              CodecWireBytes(codec_, selems(i)), &sreq[slot]);
      if (!st.ok()) return st;
      slive[slot] = true;
    }
    return Status::Ok();
  };
  auto quiesce = [&](Status primary) {
    for (int b = 0; b < 2; ++b) {
      if (rlive[b]) WaitRequest(rreq[b], nullptr);
      if (slive[b]) WaitRequest(sreq[b], nullptr);
    }
    return primary;
  };

  Status st = post(0);
  if (!st.ok()) return quiesce(st);
  for (size_t i = 0; i < n; ++i) {
    int slot = i & 1;
    bool has_r = i < nr;
    if (has_r) {
      size_t got = 0;
      st = WaitRequest(rreq[slot], &got);
      rlive[slot] = false;
      if (!st.ok()) return quiesce(st);
      if (got != CodecWireBytes(codec_, relems(i))) {
        return quiesce(Status::Inner(
            "ring step size mismatch: expected " +
            std::to_string(CodecWireBytes(codec_, relems(i))) +
            "B encoded chunk, got " + std::to_string(got) +
            "B (ranks disagree on collective arguments, TPUNET_RING_CHUNKSIZE "
            "or TPUNET_WIRE_DTYPE?)"));
      }
    }
    if (i + 1 < n) {
      st = post(i + 1);  // keep the wire busy while we decode+reduce chunk i
      if (!st.ok()) return quiesce(st);
    }
    if (has_r) {
      if (fused_enc != nullptr) {
        // Chunks are block-aligned (CodecChunkElems), so the wire offset
        // of chunk i inside the whole-slice encoding is exact.
        CodecDecodeReduceQuantize(codec_, acc_f + i * chunk_elems,
                                  loc_f + i * chunk_elems, rbuf(i),
                                  fused_enc + CodecWireBytes(codec_, i * chunk_elems),
                                  relems(i), wop);
      } else {
        CodecDecodeReduce(codec_, acc_f + i * chunk_elems, loc_f + i * chunk_elems,
                          rbuf(i), relems(i), wop);
      }
    }
    if (i < ns) {
      st = WaitRequest(sreq[slot], nullptr);
      slive[slot] = false;
      if (!st.ok()) return quiesce(st);
    }
  }
  return Status::Ok();
}

// Codec variant of the AllReduce AG phase ("AllGather passthrough":
// encode-only, no reduce). Slices travel ENCODED, and the encoded bytes
// are forwarded VERBATIM hop to hop while each rank decodes a private f32
// copy — so every rank materializes BIT-IDENTICAL values for every slice
// (the cross-rank determinism trainers assert on) and no hop ever
// re-quantizes. Precondition: the RS final round's fused handoff
// (CodecDecodeReduceQuantize) already QUANTIZED the owned slice in `data`
// and parked its encoded bytes in scratch slot 0 — what the owner keeps
// equals what every peer decodes, and this phase starts with zero codec
// passes of its own over the owned slice. Net effect: one quantization of
// each fully-reduced slice, on top of the RS phase's one-per-hop.
Status ScheduledCommunicator::AgPhaseCodec(float* data, size_t count, RingChannel& ch,
                                           uint64_t seq, bool tracing) {
  const int W = world_;
  auto off = [&](int i) { return (count * static_cast<size_t>(i)) / W; };
  const size_t max_elems = (count + W - 1) / W;
  const size_t slot_bytes = CodecWireBytes(codec_, max_elems);
  ch.scratch.reserve(2 * slot_bytes);  // no-op: DoAllReduceRing pre-reserved
  uint8_t* slots[2] = {ch.scratch.data(), ch.scratch.data() + slot_bytes};
  int cur = 0;  // slot 0 holds enc(owned slice), courtesy of the RS fusion
  for (int s = 0; s < W - 1; ++s) {
    int sidx = (rank_ - s + W) % W;
    int ridx = (rank_ - s - 1 + W) % W;
    size_t sw = CodecWireBytes(codec_, off(sidx + 1) - off(sidx));
    size_t relems = off(ridx + 1) - off(ridx);
    size_t rw = CodecWireBytes(codec_, relems);
    PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, sw);
    CountCollSteps(CollAlgo::kRing);
    // The slice sent at step s+1 is exactly the one received at step s
    // (sidx_{s+1} == ridx_s), so the received wire bytes ping-pong into
    // the next step's send slot untouched.
    Status st = Exchange(slots[cur], sw, slots[1 - cur], rw, nullptr, ch);
    if (!st.ok()) return st;
    CodecDecode(codec_, slots[1 - cur], data + off(ridx), relems);
    cur = 1 - cur;
  }
  return Status::Ok();
}

// One ring step: recv from prev into recvbuf while sending sendbuf to
// next. Posts the irecv first; BOTH requests are waited before returning —
// even on error — because an abandoned in-flight request would let the
// caller free a buffer the stream workers still touch. When got==nullptr
// the step is fixed-size and a short receive (ranks disagreeing on counts)
// is an error, not silent stale-tail corruption.
Status ScheduledCommunicator::Exchange(const void* sendbuf, size_t send_nbytes,
                                       void* recvbuf, size_t recv_nbytes,
                                       size_t* got, RingChannel& ch) {
  uint64_t rreq = 0, sreq = 0;
  Status st = net_->irecv(ch.recv_comm, recvbuf, recv_nbytes, &rreq);
  if (!st.ok()) return st;
  st = net_->isend(ch.send_comm, sendbuf, send_nbytes, &sreq);
  if (!st.ok()) {
    WaitRequest(rreq, nullptr);  // quiesce the posted recv before unwinding
    return st;
  }
  size_t rgot = 0;
  Status r_st = WaitRequest(rreq, &rgot);
  Status s_st = WaitRequest(sreq, nullptr);
  if (!r_st.ok()) return r_st;
  if (!s_st.ok()) return s_st;
  if (got) {
    *got = rgot;
  } else if (rgot != recv_nbytes) {
    return Status::Inner("ring step size mismatch: expected " + std::to_string(recv_nbytes) +
                         "B from prev rank, got " + std::to_string(rgot) +
                         "B (ranks disagree on collective arguments?)");
  }
  return Status::Ok();
}

// Wait out every pending send (ignoring their status) before surfacing
// `primary` — never abandon in-flight requests that reference caller
// buffers.
Status ScheduledCommunicator::DrainSends(std::vector<uint64_t>& reqs, Status primary) {
  for (uint64_t req : reqs) {
    Status st = WaitRequest(req, nullptr);
    if (primary.ok() && !st.ok()) primary = st;
  }
  reqs.clear();
  return primary;
}

}  // namespace internal
}  // namespace tpunet
