// tpunet flight recorder (docs/DESIGN.md §6c "Flight recorder & postmortem").
//
// A per-rank, always-on, lock-free fixed-size ring of structured events fed
// from the transport/collective/QoS/elastic hot paths. When a collective
// hangs or a rewire blows its deadline, the counters say THAT it failed;
// the recorder says what every phase of every rank was doing when it did.
//
// Hot-path cost: one relaxed fetch_add on the ring cursor plus a handful of
// relaxed stores into the claimed slot (every payload field is a relaxed
// atomic so a dump racing a writer is well-defined, not UB). No locks, no
// allocation, no branches beyond the enabled check. The per-slot `seq` word
// is release-stored LAST (value = global index + 1) so the dumper can
// detect torn slots: read seq, copy the payload, re-read seq — a mismatch
// means a writer lapped the slot mid-copy and the event is dropped (counted
// in the dump header as "torn").
//
// Ring size: TPUNET_FLIGHTREC_EVENTS slots (default 16384, rounded up to a
// power of two; 0 disables recording entirely). The ring is allocated once
// on first use and leaked on purpose — events may arrive during static
// teardown, exactly like the Telemetry singleton.
//
// Dumps (self-describing JSON, schema "tpunet-flightrec-v1") are written to
// <dir>/tpunet-flightrec-rank<R>.json:
//   - on every terminal verdict (watchdog timeout, CRC corruption, rewire /
//     weight-swap deadline) at the site where the typed error is raised,
//     rate-limited to one dump per second;
//   - on SIGUSR2 (handler installed when the recorder initializes enabled);
//   - on demand via tpunet_c_flightrec_dump / telemetry.flightrec_dump().
// The dump path is async-signal-safe: raw open/write/close with hand-rolled
// integer formatting, no malloc, no locks — the SIGUSR2 handler writes the
// file directly from signal context.
//
// Compile-time kill switch: -DTPUNET_FLIGHTREC_DISABLED compiles every
// Record() to nothing — the baseline the recorder-overhead budget in
// docs/DESIGN.md is measured against.
#ifndef TPUNET_FLIGHTREC_H_
#define TPUNET_FLIGHTREC_H_

#include <atomic>
#include <cstdint>

namespace tpunet {
namespace flightrec {

// Event kinds. Values are stable across dumps (the postmortem tool keys on
// the names the dumper emits, but the wire-stable byte keeps dumps from
// mixed-version fleets mergeable).
enum class Ev : uint8_t {
  kCollSubmit = 1,   // a=kind (CollKind), b=algo (CollAlgo), c=nbytes
  kPhaseEnter = 2,   // a=comm_id, b=coll_seq, c=nbytes, d=step, name=phase kind
  kPhaseExit = 3,    // a=comm_id, b=coll_seq, c=nbytes, d=step, name=phase kind
  kWireSend = 4,     // a=stream idx, b=chunk nbytes, d=traffic class
  kWireRecv = 5,     // a=stream idx, b=chunk nbytes, d=traffic class
  kQosGrant = 6,     // a=class, b=granted nbytes
  kQosPause = 7,     // a=class, b=front nbytes (wire window full, queue parked)
  kQosWait = 8,      // a=class, b=wait us
  kQosPreempt = 9,   // a=class (grant jumped an older waiter)
  kFailover = 10,    // data-stream failover survived
  kRestripe = 11,    // lane weight-vector epoch published
  kRewirePhase = 12, // a=phase (kRewirePhaseCount order), b=us
  kSwapPhase = 13,   // a=phase (kSwapPhaseCount order), b=us
  kCrcError = 14,    // per-chunk CRC32C mismatch detected
  kFault = 15,       // a=action (FaultAction) — injected fault fired
  kReqStart = 16,    // a=comm, b=request id, c=nbytes, d=is_send
  kReqDone = 17,     // a=request id, d=failed
  kVerdict = 18,     // a=ErrorKind int, name=verdict label — terminal error
};

struct Event {
  // 0 = never written; else the claiming writer's global index + 1,
  // release-stored after the payload (the dumper's torn-slot check).
  std::atomic<uint64_t> seq{0};
  std::atomic<uint64_t> t_us{0};
  std::atomic<uint64_t> a{0}, b{0}, c{0};
  // Static string literal (phase kind, verdict label) or nullptr. Literals
  // only: the dumper dereferences it at dump time, possibly from a signal
  // handler, so the pointee must be immortal.
  std::atomic<const char*> name{nullptr};
  std::atomic<uint32_t> d{0};
  std::atomic<uint8_t> kind{0};
};

struct Ring {
  std::atomic<uint64_t> cursor{0};  // total events ever claimed
  uint64_t mask = 0;                // capacity - 1 (capacity is a power of 2)
  uint64_t capacity = 0;
  Event* slots = nullptr;
};

namespace internal {
// nullptr until InitRing() runs; stays nullptr forever when the recorder is
// disabled (TPUNET_FLIGHTREC_EVENTS=0) — g_disabled distinguishes the two.
extern std::atomic<Ring*> g_ring;
extern std::atomic<bool> g_disabled;
Ring* InitRing();  // idempotent; returns nullptr when disabled
void RecordIn(Ring* r, Ev kind, uint64_t a, uint64_t b, uint64_t c, uint32_t d,
              const char* name);
}  // namespace internal

// Hot-path event append. Safe from any thread at any time (including during
// static teardown — the ring is leaked). No-op when disabled.
inline void Record(Ev kind, uint64_t a, uint64_t b = 0, uint64_t c = 0,
                   uint32_t d = 0, const char* name = nullptr) {
#ifdef TPUNET_FLIGHTREC_DISABLED
  (void)kind; (void)a; (void)b; (void)c; (void)d; (void)name;
#else
  Ring* r = internal::g_ring.load(std::memory_order_acquire);
  if (r == nullptr) {
    if (internal::g_disabled.load(std::memory_order_relaxed)) return;
    r = internal::InitRing();
    if (r == nullptr) return;
  }
  internal::RecordIn(r, kind, a, b, c, d, name);
#endif
}

// Write the ring to <dir>/tpunet-flightrec-rank<R>.json (dir nullptr/"" =
// the directory resolved at init: TPUNET_TRACE_DIR when set, else ".").
// `reason` lands in the dump header; it is consumed synchronously (only
// Record/DumpOnVerdict retain name pointers, so only those require
// literals) but must not contain JSON-hostile characters — the dumper
// writes it verbatim. Returns the full dump-path length (0 when the
// recorder never initialized, is disabled, or the target is unwritable)
// and NUL-truncates the path into out_path when cap allows — the
// tpunet_c_metrics_text buffer-sizing contract. Async-signal-safe when
// dir is nullptr.
int Dump(const char* dir, const char* reason, char* out_path, uint64_t cap);

// Terminal-verdict dump: records a kVerdict event and dumps to the default
// directory, rate-limited to one dump per second so an error storm (every
// rank's every request timing out at once) produces one file, not a disk
// flood. `reason` must be a static literal.
void DumpOnVerdict(const char* reason, uint64_t err_kind);

// Recorder occupancy: events ever recorded (cursor) and ring capacity
// (0/0 when disabled). For tests and tpunet_c_flightrec_stats.
void Stats(uint64_t* recorded, uint64_t* capacity);

}  // namespace flightrec
}  // namespace tpunet

#endif  // TPUNET_FLIGHTREC_H_
