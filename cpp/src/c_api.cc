// tpunet C ABI implementation. See c_api.h for the contract and the list of
// reference quirks deliberately fixed here (reference: src/lib.rs:19-392).
#include "tpunet/c_api.h"

#include <string.h>

#include <algorithm>
#include <atomic>
#include <map>
#include <memory>
#include <mutex>
#include <vector>

#include "id_map.h"
#include "tpunet/net.h"

namespace {

using tpunet::Net;
using tpunet::NetProperties;
using tpunet::SocketHandle;
using tpunet::Status;

thread_local std::string g_last_error;

int32_t Fail(int32_t code, const std::string& msg) {
  g_last_error = msg;
  return code;
}

int32_t FromStatus(const Status& s) {
  if (s.ok()) return TPUNET_OK;
  if (s.kind == tpunet::ErrorKind::kInvalidArgument) {
    return Fail(TPUNET_ERR_INVALID, s.msg);
  }
  return Fail(TPUNET_ERR_INNER, s.msg);
}

// An instance: the engine plus a property cache that owns the name/pci_path
// strings handed out through the ABI (reference kept a similar cache but
// freed Rust-allocated strings with C++ delete, cc/bagua_net.cc:8-31; here
// one allocator owns everything).
struct Instance {
  std::unique_ptr<Net> net;
  std::mutex props_mu;
  // One cached entry per device, reused across calls — properties are static
  // per NIC, and reusing bounds the cache (a poll-properties loop must not
  // grow memory for the instance lifetime).
  std::map<int32_t, std::unique_ptr<NetProperties>> props_cache;
};

tpunet::IdMap<std::shared_ptr<Instance>> g_instances;
std::atomic<uint64_t> g_next_instance_id{1};

std::shared_ptr<Instance> GetInstance(uintptr_t id) {
  std::shared_ptr<Instance> inst;
  g_instances.Get(id, &inst);
  return inst;
}

}  // namespace

extern "C" {

int32_t tpunet_c_create(uintptr_t* out_instance) {
  if (!out_instance) return Fail(TPUNET_ERR_NULL, "out_instance is null");
  auto inst = std::make_shared<Instance>();
  inst->net = tpunet::CreateEngine();
  if (!inst->net) return Fail(TPUNET_ERR_INNER, "engine creation failed");
  uint64_t id = g_next_instance_id.fetch_add(1);
  g_instances.Put(id, inst);
  *out_instance = id;
  return TPUNET_OK;
}

int32_t tpunet_c_destroy(uintptr_t* instance) {
  if (!instance) return Fail(TPUNET_ERR_NULL, "instance is null");
  std::shared_ptr<Instance> inst;
  if (!g_instances.Take(*instance, &inst)) {
    return Fail(TPUNET_ERR_INVALID, "unknown instance");
  }
  *instance = 0;
  return TPUNET_OK;
}

int32_t tpunet_c_devices(uintptr_t instance, int32_t* ndev) {
  if (!ndev) return Fail(TPUNET_ERR_NULL, "ndev is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  *ndev = inst->net->devices();
  return TPUNET_OK;
}

int32_t tpunet_c_get_properties(uintptr_t instance, int32_t dev,
                                tpunet_net_properties_t* props) {
  if (!props) return Fail(TPUNET_ERR_NULL, "props is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  std::lock_guard<std::mutex> lk(inst->props_mu);
  auto it = inst->props_cache.find(dev);
  if (it == inst->props_cache.end()) {
    auto p = std::make_unique<NetProperties>();
    Status s = inst->net->get_properties(dev, p.get());
    if (!s.ok()) return FromStatus(s);
    it = inst->props_cache.emplace(dev, std::move(p)).first;
  }
  const NetProperties& p = *it->second;  // strings live until destroy
  props->name = p.name.c_str();
  props->pci_path = p.pci_path.c_str();
  props->guid = p.guid;
  props->ptr_support = p.ptr_support;
  props->speed_mbps = p.speed_mbps;
  props->port = p.port;
  props->max_comms = p.max_comms;
  return TPUNET_OK;
}

int32_t tpunet_c_listen(uintptr_t instance, int32_t dev,
                        tpunet_socket_handle_t* handle, uintptr_t* listen_comm) {
  if (!handle || !listen_comm) return Fail(TPUNET_ERR_NULL, "null out param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  SocketHandle h;
  uint64_t id = 0;
  Status s = inst->net->listen(dev, &h, &id);
  if (!s.ok()) return FromStatus(s);
  // Marshal: only the sockaddr bytes travel; length is derived from the
  // family on the far side (see basic_engine.cc AddrLenForFamily).
  memset(handle->data, 0, sizeof(handle->data));
  memcpy(handle->data, &h.addr, std::min(sizeof(handle->data), sizeof(h.addr)));
  *listen_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_connect(uintptr_t instance, int32_t dev,
                         const tpunet_socket_handle_t* handle, uintptr_t* send_comm) {
  if (!handle || !send_comm) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  SocketHandle h;
  memcpy(&h.addr, handle->data, sizeof(handle->data));
  h.addrlen = 0;  // derived from family by the engine
  uint64_t id = 0;
  Status s = inst->net->connect(dev, h, &id);
  if (!s.ok()) return FromStatus(s);
  *send_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_accept(uintptr_t instance, uintptr_t listen_comm, uintptr_t* recv_comm) {
  if (!recv_comm) return Fail(TPUNET_ERR_NULL, "recv_comm is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->accept(listen_comm, &id);
  if (!s.ok()) return FromStatus(s);
  *recv_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_isend(uintptr_t instance, uintptr_t send_comm, const void* data,
                       uint64_t nbytes, uintptr_t* request) {
  if (!request || (nbytes > 0 && !data)) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->isend(send_comm, data, nbytes, &id);
  if (!s.ok()) return FromStatus(s);
  *request = id;
  return TPUNET_OK;
}

int32_t tpunet_c_irecv(uintptr_t instance, uintptr_t recv_comm, void* data,
                       uint64_t nbytes, uintptr_t* request) {
  if (!request || (nbytes > 0 && !data)) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->irecv(recv_comm, data, nbytes, &id);
  if (!s.ok()) return FromStatus(s);
  *request = id;
  return TPUNET_OK;
}

int32_t tpunet_c_test(uintptr_t instance, uintptr_t request, uint8_t* done,
                      uint64_t* nbytes) {
  if (!done) return Fail(TPUNET_ERR_NULL, "done is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  bool d = false;
  size_t n = 0;
  Status s = inst->net->test(request, &d, &n);
  if (!s.ok()) return FromStatus(s);
  *done = d ? 1 : 0;
  if (nbytes) *nbytes = n;
  return TPUNET_OK;
}

int32_t tpunet_c_close_send(uintptr_t instance, uintptr_t send_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_send(send_comm));
}

int32_t tpunet_c_close_recv(uintptr_t instance, uintptr_t recv_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_recv(recv_comm));
}

int32_t tpunet_c_close_listen(uintptr_t instance, uintptr_t listen_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_listen(listen_comm));
}

const char* tpunet_c_last_error(void) { return g_last_error.c_str(); }

}  // extern "C"
