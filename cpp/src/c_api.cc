// tpunet C ABI implementation. See c_api.h for the contract and the list of
// reference quirks deliberately fixed here (reference: src/lib.rs:19-392).
#include "tpunet/c_api.h"

#include <stdlib.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <map>
#include <memory>
#include <vector>

#include "fault.h"
#include "flightrec.h"
#include "id_map.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/qos.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace {

using tpunet::Net;
using tpunet::NetProperties;
using tpunet::SocketHandle;
using tpunet::Status;

thread_local std::string g_last_error;

int32_t Fail(int32_t code, const std::string& msg) {
  g_last_error = msg;
  return code;
}

int32_t FromStatus(const Status& s) {
  if (s.ok()) return TPUNET_OK;
  switch (s.kind) {
    case tpunet::ErrorKind::kInvalidArgument:
      return Fail(TPUNET_ERR_INVALID, s.msg);
    case tpunet::ErrorKind::kCorruption:
      return Fail(TPUNET_ERR_CORRUPT, s.msg);
    case tpunet::ErrorKind::kTimeout:
      return Fail(TPUNET_ERR_TIMEOUT, s.msg);
    case tpunet::ErrorKind::kVersion:
      return Fail(TPUNET_ERR_VERSION, s.msg);
    case tpunet::ErrorKind::kCodec:
      return Fail(TPUNET_ERR_CODEC, s.msg);
    case tpunet::ErrorKind::kQosAdmission:
      return Fail(TPUNET_ERR_QOS_ADMISSION, s.msg);
    default:
      return Fail(TPUNET_ERR_INNER, s.msg);
  }
}

// An instance: the engine plus a property cache that owns the name/pci_path
// strings handed out through the ABI (reference kept a similar cache but
// freed Rust-allocated strings with C++ delete, cc/bagua_net.cc:8-31; here
// one allocator owns everything).
struct Instance {
  std::unique_ptr<Net> net;
  tpunet::Mutex props_mu;  // leaf lock
  // One cached entry per device, reused across calls — properties are static
  // per NIC, and reusing bounds the cache (a poll-properties loop must not
  // grow memory for the instance lifetime).
  std::map<int32_t, std::unique_ptr<NetProperties>> props_cache GUARDED_BY(props_mu);
};

tpunet::IdMap<std::shared_ptr<Instance>> g_instances;
std::atomic<uint64_t> g_next_instance_id{1};

std::shared_ptr<Instance> GetInstance(uintptr_t id) {
  std::shared_ptr<Instance> inst;
  g_instances.Get(id, &inst);
  return inst;
}

}  // namespace

extern "C" {

int32_t tpunet_c_create(uintptr_t* out_instance) {
  return tpunet_c_create_ex(nullptr, out_instance);
}

int32_t tpunet_c_create_ex(const char* traffic_class, uintptr_t* out_instance) {
  if (!out_instance) return Fail(TPUNET_ERR_NULL, "out_instance is null");
  tpunet::TrafficClass cls = tpunet::TrafficClass::kBulk;
  bool have_cls = traffic_class != nullptr && *traffic_class != '\0';
  if (have_cls && !tpunet::ParseTrafficClass(traffic_class, &cls)) {
    return Fail(TPUNET_ERR_INVALID,
                std::string("unknown traffic_class \"") + traffic_class +
                    "\" (expected latency, bulk or control)");
  }
  auto inst = std::make_shared<Instance>();
  inst->net = tpunet::CreateEngine();
  if (!inst->net) return Fail(TPUNET_ERR_INNER, "engine creation failed");
  if (have_cls) inst->net->set_traffic_class(static_cast<int32_t>(cls));
  uint64_t id = g_next_instance_id.fetch_add(1);
  g_instances.Put(id, inst);
  *out_instance = id;
  return TPUNET_OK;
}

int32_t tpunet_c_destroy(uintptr_t* instance) {
  if (!instance) return Fail(TPUNET_ERR_NULL, "instance is null");
  std::shared_ptr<Instance> inst;
  if (!g_instances.Take(*instance, &inst)) {
    return Fail(TPUNET_ERR_INVALID, "unknown instance");
  }
  *instance = 0;
  return TPUNET_OK;
}

int32_t tpunet_c_devices(uintptr_t instance, int32_t* ndev) {
  if (!ndev) return Fail(TPUNET_ERR_NULL, "ndev is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  *ndev = inst->net->devices();
  return TPUNET_OK;
}

int32_t tpunet_c_get_properties(uintptr_t instance, int32_t dev,
                                tpunet_net_properties_t* props) {
  if (!props) return Fail(TPUNET_ERR_NULL, "props is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  tpunet::MutexLock lk(inst->props_mu);
  auto it = inst->props_cache.find(dev);
  if (it == inst->props_cache.end()) {
    auto p = std::make_unique<NetProperties>();
    Status s = inst->net->get_properties(dev, p.get());
    if (!s.ok()) return FromStatus(s);
    it = inst->props_cache.emplace(dev, std::move(p)).first;
  }
  const NetProperties& p = *it->second;  // strings live until destroy
  props->name = p.name.c_str();
  props->pci_path = p.pci_path.c_str();
  props->guid = p.guid;
  props->ptr_support = p.ptr_support;
  props->speed_mbps = p.speed_mbps;
  props->port = p.port;
  props->max_comms = p.max_comms;
  return TPUNET_OK;
}

int32_t tpunet_c_listen(uintptr_t instance, int32_t dev,
                        tpunet_socket_handle_t* handle, uintptr_t* listen_comm) {
  if (!handle || !listen_comm) return Fail(TPUNET_ERR_NULL, "null out param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  SocketHandle h;
  uint64_t id = 0;
  Status s = inst->net->listen(dev, &h, &id);
  if (!s.ok()) return FromStatus(s);
  // Marshal: only the sockaddr bytes travel; length is derived from the
  // family on the far side (see basic_engine.cc AddrLenForFamily).
  memset(handle->data, 0, sizeof(handle->data));
  memcpy(handle->data, &h.addr, std::min(sizeof(handle->data), sizeof(h.addr)));
  *listen_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_connect(uintptr_t instance, int32_t dev,
                         const tpunet_socket_handle_t* handle, uintptr_t* send_comm) {
  if (!handle || !send_comm) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  SocketHandle h;
  memcpy(&h.addr, handle->data, sizeof(handle->data));
  h.addrlen = 0;  // derived from family by the engine
  uint64_t id = 0;
  Status s = inst->net->connect(dev, h, &id);
  if (!s.ok()) return FromStatus(s);
  *send_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_accept(uintptr_t instance, uintptr_t listen_comm, uintptr_t* recv_comm) {
  if (!recv_comm) return Fail(TPUNET_ERR_NULL, "recv_comm is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->accept(listen_comm, &id);
  if (!s.ok()) return FromStatus(s);
  *recv_comm = id;
  return TPUNET_OK;
}

int32_t tpunet_c_isend(uintptr_t instance, uintptr_t send_comm, const void* data,
                       uint64_t nbytes, uintptr_t* request) {
  if (!request || (nbytes > 0 && !data)) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->isend(send_comm, data, nbytes, &id);
  if (!s.ok()) return FromStatus(s);
  *request = id;
  return TPUNET_OK;
}

int32_t tpunet_c_irecv(uintptr_t instance, uintptr_t recv_comm, void* data,
                       uint64_t nbytes, uintptr_t* request) {
  if (!request || (nbytes > 0 && !data)) return Fail(TPUNET_ERR_NULL, "null param");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  uint64_t id = 0;
  Status s = inst->net->irecv(recv_comm, data, nbytes, &id);
  if (!s.ok()) return FromStatus(s);
  *request = id;
  return TPUNET_OK;
}

int32_t tpunet_c_test(uintptr_t instance, uintptr_t request, uint8_t* done,
                      uint64_t* nbytes) {
  if (!done) return Fail(TPUNET_ERR_NULL, "done is null");
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  bool d = false;
  size_t n = 0;
  Status s = inst->net->test(request, &d, &n);
  if (!s.ok()) return FromStatus(s);
  *done = d ? 1 : 0;
  if (nbytes) *nbytes = n;
  return TPUNET_OK;
}

int32_t tpunet_c_wait(uintptr_t instance, uintptr_t request, uint64_t* nbytes) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  size_t n = 0;
  Status s = inst->net->wait(request, &n);
  if (!s.ok()) return FromStatus(s);
  if (nbytes) *nbytes = n;
  return TPUNET_OK;
}

int32_t tpunet_c_close_send(uintptr_t instance, uintptr_t send_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_send(send_comm));
}

int32_t tpunet_c_close_recv(uintptr_t instance, uintptr_t recv_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_recv(recv_comm));
}

int32_t tpunet_c_close_listen(uintptr_t instance, uintptr_t listen_comm) {
  auto inst = GetInstance(instance);
  if (!inst) return Fail(TPUNET_ERR_INVALID, "unknown instance");
  return FromStatus(inst->net->close_listen(listen_comm));
}

const char* tpunet_c_last_error(void) { return g_last_error.c_str(); }

int32_t tpunet_c_fault_inject(const char* spec) {
  if (spec == nullptr || *spec == '\0') {
    tpunet::DisarmFault();
    return TPUNET_OK;
  }
  tpunet::FaultSpec f;
  bool has_fault = false;
  std::vector<tpunet::ChurnEvent> churn;
  std::vector<tpunet::SwapEvent> swap;
  Status s = tpunet::ParseFaultScript(spec, &f, &has_fault, &churn, &swap);
  if (!s.ok()) return FromStatus(s);
  if (has_fault) tpunet::ArmFault(f);
  if (!churn.empty()) tpunet::ArmChurnScript(churn);
  if (!swap.empty()) tpunet::ArmSwapScript(swap);
  return TPUNET_OK;
}

int32_t tpunet_c_fault_clear(void) {
  tpunet::DisarmFault();
  return TPUNET_OK;
}

int32_t tpunet_c_churn_poll(uint64_t step, int64_t rank) {
  return static_cast<int32_t>(tpunet::ChurnPoll(step, rank));
}

int32_t tpunet_c_churn_pending(void) { return tpunet::ChurnPending(); }

int32_t tpunet_c_swap_poll(uint64_t step) {
  return static_cast<int32_t>(tpunet::SwapPoll(step));
}

int32_t tpunet_c_swap_pending(void) { return tpunet::SwapPending(); }

uint32_t tpunet_c_crc32c(const void* data, uint64_t nbytes, uint32_t seed) {
  if (data == nullptr && nbytes > 0) return 0;
  return tpunet::Crc32c(data, static_cast<size_t>(nbytes), seed);
}

uint64_t tpunet_c_host_id(void) { return tpunet::HostId(); }

int32_t tpunet_c_reduce(void* dst, const void* a, const void* b, uint64_t n,
                        int32_t dtype, int32_t op) {
  if (dtype < 0 || dtype > 5) return Fail(TPUNET_ERR_INVALID, "bad dtype");
  if (op < 0 || op > 3) return Fail(TPUNET_ERR_INVALID, "bad op");
  if (n > 0 && (dst == nullptr || a == nullptr || b == nullptr)) {
    return Fail(TPUNET_ERR_INVALID, "null buffer with n > 0");
  }
  tpunet::ReduceInto(dst, a, b, static_cast<size_t>(n),
                     static_cast<tpunet::WireDType>(dtype),
                     static_cast<tpunet::WireRedOp>(op));
  return TPUNET_OK;
}

uint64_t tpunet_c_codec_wire_bytes(int32_t codec, uint64_t n) {
  if (codec < 0 || codec >= tpunet::kWireCodecCount) return 0;
  return tpunet::CodecWireBytes(static_cast<tpunet::WireCodec>(codec),
                                static_cast<size_t>(n));
}

int32_t tpunet_c_codec_encode(int32_t codec, const void* src, uint64_t n,
                              void* dst, uint64_t dst_cap) {
  if (codec < 0 || codec >= tpunet::kWireCodecCount) {
    return Fail(TPUNET_ERR_INVALID, "bad codec");
  }
  if (n > 0 && (src == nullptr || dst == nullptr)) {
    return Fail(TPUNET_ERR_NULL, "null buffer with n > 0");
  }
  auto c = static_cast<tpunet::WireCodec>(codec);
  if (dst_cap < tpunet::CodecWireBytes(c, static_cast<size_t>(n))) {
    return Fail(TPUNET_ERR_INVALID, "dst_cap smaller than the encoded size");
  }
  tpunet::CodecEncode(c, static_cast<const float*>(src),
                      static_cast<uint8_t*>(dst), static_cast<size_t>(n));
  return TPUNET_OK;
}

int32_t tpunet_c_codec_decode(int32_t codec, const void* wire, uint64_t n,
                              void* dst) {
  if (codec < 0 || codec >= tpunet::kWireCodecCount) {
    return Fail(TPUNET_ERR_INVALID, "bad codec");
  }
  if (n > 0 && (wire == nullptr || dst == nullptr)) {
    return Fail(TPUNET_ERR_NULL, "null buffer with n > 0");
  }
  tpunet::CodecDecode(static_cast<tpunet::WireCodec>(codec),
                      static_cast<const uint8_t*>(wire),
                      static_cast<float*>(dst), static_cast<size_t>(n));
  return TPUNET_OK;
}

}  // extern "C"

// ---- Collectives ABI ------------------------------------------------------

#include "tpunet/collectives.h"

namespace {

tpunet::IdMap<std::shared_ptr<tpunet::Communicator>> g_comms;
std::atomic<uint64_t> g_next_comm_id{1};

std::shared_ptr<tpunet::Communicator> GetComm(uintptr_t id) {
  std::shared_ptr<tpunet::Communicator> c;
  g_comms.Get(id, &c);
  return c;
}

bool ValidDType(int32_t d) { return d >= 0 && d <= 5; }
bool ValidOp(int32_t o) { return o >= 0 && o <= 3; }

// Process-default communicator id (0 = unset). The FFI custom-call
// collectives read it at call time so elastic recovery can swap the
// communicator under already-compiled executables.
std::atomic<uintptr_t> g_default_comm{0};

}  // namespace

extern "C" {

int32_t tpunet_comm_create(const char* coordinator, int32_t rank, int32_t world_size,
                           uintptr_t* comm) {
  return tpunet_comm_create_ex(coordinator, rank, world_size, nullptr, nullptr,
                               nullptr, comm);
}

int32_t tpunet_comm_create_ex(const char* coordinator, int32_t rank,
                              int32_t world_size, const char* wire_dtype,
                              const char* algo, const char* traffic_class,
                              uintptr_t* comm) {
  if (!coordinator || !comm) return Fail(TPUNET_ERR_NULL, "null param");
  std::unique_ptr<tpunet::Communicator> c;
  Status s = tpunet::Communicator::Create(coordinator, rank, world_size,
                                          wire_dtype ? wire_dtype : "",
                                          algo ? algo : "",
                                          traffic_class ? traffic_class : "",
                                          &c);
  if (!s.ok()) return FromStatus(s);
  uint64_t id = g_next_comm_id.fetch_add(1);
  g_comms.Put(id, std::shared_ptr<tpunet::Communicator>(std::move(c)));
  *comm = id;
  return TPUNET_OK;
}

int32_t tpunet_comm_wire_dtype(uintptr_t comm, int32_t* wire_dtype) {
  if (!wire_dtype) return Fail(TPUNET_ERR_NULL, "wire_dtype is null");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  *wire_dtype = c->wire_codec();
  return TPUNET_OK;
}

int32_t tpunet_comm_destroy(uintptr_t* comm) {
  if (!comm) return Fail(TPUNET_ERR_NULL, "comm is null");
  std::shared_ptr<tpunet::Communicator> c;
  if (!g_comms.Take(*comm, &c)) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  // A destroyed comm must not remain the process default — a racing FFI
  // call would fetch a dead id (GetComm then fails loudly, but clear it
  // so the precondition error is the one callers see).
  uintptr_t expect = *comm;
  g_default_comm.compare_exchange_strong(expect, 0);
  *comm = 0;
  return TPUNET_OK;
}

int32_t tpunet_comm_set_default(uintptr_t comm) {
  if (comm != 0) {
    std::shared_ptr<tpunet::Communicator> c;
    if (!g_comms.Get(comm, &c)) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  }
  g_default_comm.store(comm);
  return TPUNET_OK;
}

uintptr_t tpunet_comm_get_default(void) { return g_default_comm.load(); }

int32_t tpunet_comm_rank(uintptr_t comm, int32_t* rank, int32_t* world_size) {
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  if (rank) *rank = c->rank();
  if (world_size) *world_size = c->world_size();
  return TPUNET_OK;
}

int32_t tpunet_comm_all_reduce(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t count, int32_t dtype, int32_t op) {
  if (count > 0 && (!sendbuf || !recvbuf)) return Fail(TPUNET_ERR_NULL, "null buffer");
  if (!ValidDType(dtype) || !ValidOp(op)) return Fail(TPUNET_ERR_INVALID, "bad dtype/op");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->AllReduce(sendbuf, recvbuf, count, static_cast<tpunet::DType>(dtype),
                                 static_cast<tpunet::RedOp>(op)));
}

int32_t tpunet_comm_reduce_scatter(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                   uint64_t recv_count, int32_t dtype, int32_t op) {
  if (recv_count > 0 && (!sendbuf || !recvbuf)) return Fail(TPUNET_ERR_NULL, "null buffer");
  if (!ValidDType(dtype) || !ValidOp(op)) return Fail(TPUNET_ERR_INVALID, "bad dtype/op");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->ReduceScatter(sendbuf, recvbuf, recv_count,
                                     static_cast<tpunet::DType>(dtype),
                                     static_cast<tpunet::RedOp>(op)));
}

int32_t tpunet_comm_all_gather(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t bytes_per_rank) {
  if (bytes_per_rank > 0 && (!sendbuf || !recvbuf)) return Fail(TPUNET_ERR_NULL, "null buffer");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->AllGather(sendbuf, recvbuf, bytes_per_rank));
}

int32_t tpunet_comm_broadcast(uintptr_t comm, void* buf, uint64_t nbytes, int32_t root) {
  if (nbytes > 0 && !buf) return Fail(TPUNET_ERR_NULL, "null buffer");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->Broadcast(buf, nbytes, root));
}

int32_t tpunet_comm_all_to_all(uintptr_t comm, const void* sendbuf, void* recvbuf,
                               uint64_t bytes_per_rank) {
  if (bytes_per_rank > 0 && (!sendbuf || !recvbuf)) return Fail(TPUNET_ERR_NULL, "null buffer");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->AllToAll(sendbuf, recvbuf, bytes_per_rank));
}

int32_t tpunet_comm_all_to_all_typed(uintptr_t comm, const void* sendbuf,
                                     void* recvbuf, uint64_t count_per_rank,
                                     int32_t dtype) {
  if (count_per_rank > 0 && (!sendbuf || !recvbuf)) {
    return Fail(TPUNET_ERR_NULL, "null buffer");
  }
  if (!ValidDType(dtype)) return Fail(TPUNET_ERR_INVALID, "bad dtype");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->AllToAllTyped(sendbuf, recvbuf, count_per_rank,
                                     static_cast<tpunet::DType>(dtype)));
}

int32_t tpunet_comm_iall_to_all(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                uint64_t bytes_per_rank, uint64_t* ticket) {
  if (!ticket || (bytes_per_rank > 0 && (!sendbuf || !recvbuf))) {
    return Fail(TPUNET_ERR_NULL, "null param");
  }
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->IAllToAll(sendbuf, recvbuf, bytes_per_rank, ticket));
}

int32_t tpunet_comm_neighbor_exchange(uintptr_t comm, const void* sendbuf,
                                      uint64_t send_nbytes, void* recvbuf,
                                      uint64_t recv_nbytes, uint64_t* got) {
  if ((send_nbytes > 0 && !sendbuf) || (recv_nbytes > 0 && !recvbuf)) {
    return Fail(TPUNET_ERR_NULL, "null buffer");
  }
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  size_t g = 0;
  Status s = c->NeighborExchange(sendbuf, send_nbytes, recvbuf, recv_nbytes, &g);
  if (!s.ok()) return FromStatus(s);
  if (got) *got = g;
  return TPUNET_OK;
}

int32_t tpunet_comm_iall_reduce(uintptr_t comm, const void* sendbuf, void* recvbuf,
                                uint64_t count, int32_t dtype, int32_t op,
                                uint64_t* ticket) {
  if (!ticket || (count > 0 && (!sendbuf || !recvbuf))) {
    return Fail(TPUNET_ERR_NULL, "null param");
  }
  if (!ValidDType(dtype) || !ValidOp(op)) return Fail(TPUNET_ERR_INVALID, "bad dtype/op");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->IAllReduce(sendbuf, recvbuf, count,
                                  static_cast<tpunet::DType>(dtype),
                                  static_cast<tpunet::RedOp>(op), ticket));
}

int32_t tpunet_comm_ticket_wait(uintptr_t comm, uint64_t ticket) {
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->WaitTicket(ticket));
}

int32_t tpunet_comm_ticket_test(uintptr_t comm, uint64_t ticket, uint8_t* done) {
  if (!done) return Fail(TPUNET_ERR_NULL, "done is null");
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  bool d = false;
  Status s = c->TestTicket(ticket, &d);
  if (!s.ok()) return FromStatus(s);
  *done = d ? 1 : 0;
  return TPUNET_OK;
}

int32_t tpunet_comm_barrier(uintptr_t comm) {
  auto c = GetComm(comm);
  if (!c) return Fail(TPUNET_ERR_INVALID, "unknown comm");
  return FromStatus(c->Barrier());
}

int32_t tpunet_c_metrics_text(char* buf, uint64_t cap) {
  if (!buf && cap > 0) return Fail(TPUNET_ERR_NULL, "buf is null");
  std::string text = tpunet::Telemetry::Get().PrometheusText();
  if (cap > 0) {
    uint64_t n = std::min<uint64_t>(text.size(), cap - 1);
    memcpy(buf, text.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int32_t>(text.size());
}

int32_t tpunet_c_metrics_reset(void) {
  tpunet::Telemetry::Get().Reset();
  return TPUNET_OK;
}

int32_t tpunet_c_trace_flush(void) {
  if (!tpunet::Telemetry::Get().FlushTrace()) {
    return Fail(TPUNET_ERR_INNER, "trace file unwritable; spans dropped");
  }
  return TPUNET_OK;
}

int32_t tpunet_c_trace_set_dir(const char* dir) {
  if (!tpunet::Telemetry::Get().SetTraceDir(dir ? dir : "")) {
    return Fail(TPUNET_ERR_INNER, "trace flush failed while retargeting");
  }
  return TPUNET_OK;
}

int32_t tpunet_c_metrics_port(void) {
  return tpunet::Telemetry::Get().MetricsPort();
}

int32_t tpunet_c_serve_observe(int32_t kind, uint64_t us) {
  if (kind < 0 || kind > 1) {
    return Fail(TPUNET_ERR_INVALID, "kind must be 0 (ttft) or 1 (tpot)");
  }
  tpunet::Telemetry::Get().OnServeLatency(kind, us);
  return TPUNET_OK;
}

int32_t tpunet_c_serve_queue_depth(int32_t tier, uint64_t depth) {
  if (tier < 0 || tier >= tpunet::kServeTierCount) {
    return Fail(TPUNET_ERR_INVALID,
                "tier must be 0 (router), 1 (prefill) or 2 (decode)");
  }
  tpunet::Telemetry::Get().OnServeQueueDepth(tier, depth);
  return TPUNET_OK;
}

int32_t tpunet_c_rewire_observe(int32_t phase, uint64_t us) {
  if (phase < 0 || phase >= tpunet::kRewirePhaseCount) {
    return Fail(TPUNET_ERR_INVALID,
                "phase must be 0 (detect), 1 (quiesce), 2 (rendezvous) or "
                "3 (rewire)");
  }
  tpunet::Telemetry::Get().OnRewirePhase(phase, us);
  return TPUNET_OK;
}

int32_t tpunet_c_churn_event(int32_t kind) {
  if (kind < 0 || kind >= tpunet::kChurnKindCount) {
    return Fail(TPUNET_ERR_INVALID,
                "kind must be 0 (kill), 1 (join), 2 (shrink), 3 (grow) or "
                "4 (readmit)");
  }
  tpunet::Telemetry::Get().OnChurnEvent(kind);
  return TPUNET_OK;
}

int32_t tpunet_c_world_size(uint64_t world) {
  tpunet::Telemetry::Get().OnWorldSize(world);
  return TPUNET_OK;
}

int32_t tpunet_c_swap_observe(int32_t phase, uint64_t us) {
  if (phase < 0 || phase >= tpunet::kSwapPhaseCount) {
    return Fail(TPUNET_ERR_INVALID,
                "phase must be 0 (announce), 1 (broadcast), 2 (verify) or "
                "3 (flip)");
  }
  tpunet::Telemetry::Get().OnSwapPhase(phase, us);
  return TPUNET_OK;
}

int32_t tpunet_c_swap_event(int32_t kind) {
  if (kind < 0 || kind >= tpunet::kSwapKindCount) {
    return Fail(TPUNET_ERR_INVALID,
                "kind must be 0 (publish), 1 (commit), 2 (abort), 3 (retry) "
                "or 4 (mismatch)");
  }
  tpunet::Telemetry::Get().OnSwapEvent(kind);
  return TPUNET_OK;
}

int32_t tpunet_c_weight_version(uint64_t version) {
  tpunet::Telemetry::Get().OnWeightVersion(version);
  return TPUNET_OK;
}

int32_t tpunet_c_flightrec_dump(const char* dir, const char* reason,
                                char* out_path, uint64_t cap) {
  if (!out_path && cap > 0) return Fail(TPUNET_ERR_NULL, "out_path is null");
  // The ring initializes lazily on first Record; an on-demand dump before
  // any traffic must still produce a (header-only) file.
  if (tpunet::flightrec::internal::InitRing() == nullptr) {
    return Fail(TPUNET_ERR_INVALID,
                "flight recorder disabled (TPUNET_FLIGHTREC_EVENTS=0)");
  }
  // The reason lands verbatim inside a JSON string in the dump header:
  // sanitize the caller-supplied text instead of trusting it.
  char clean[64];
  const char* src = reason != nullptr && reason[0] != '\0' ? reason : "api";
  size_t n = 0;
  for (; src[n] != '\0' && n < sizeof(clean) - 1; ++n) {
    char ch = src[n];
    bool ok = (ch >= 'a' && ch <= 'z') || (ch >= 'A' && ch <= 'Z') ||
              (ch >= '0' && ch <= '9') || ch == '_' || ch == '-' ||
              ch == '.' || ch == ' ' || ch == ':';
    clean[n] = ok ? ch : '_';
  }
  clean[n] = '\0';
  int len = tpunet::flightrec::Dump(dir, clean, out_path, cap);
  if (len <= 0) {
    return Fail(TPUNET_ERR_INVALID, "flight recorder dump target unwritable");
  }
  return len;
}

int32_t tpunet_c_flightrec_stats(uint64_t* recorded, uint64_t* capacity) {
  tpunet::flightrec::Stats(recorded, capacity);
  return TPUNET_OK;
}

int32_t tpunet_c_lane_parse(const char* spec, char* out, uint64_t cap) {
  if ((!out && cap > 0) || !spec) return Fail(TPUNET_ERR_NULL, "null param");
  std::vector<tpunet::LaneSpec> lanes;
  Status s = tpunet::ParseLaneSpec(spec, &lanes);
  if (!s.ok()) return FromStatus(s);
  std::string text;
  for (size_t i = 0; i < lanes.size(); ++i) {
    text += "lane=" + std::to_string(i) + " addr=" +
            (lanes[i].addr.empty() ? "-" : lanes[i].addr) +
            " w=" + std::to_string(lanes[i].weight) + "\n";
  }
  if (cap > 0) {
    uint64_t n = std::min<uint64_t>(text.size(), cap - 1);
    memcpy(out, text.data(), n);
    out[n] = '\0';
  }
  return static_cast<int32_t>(text.size());
}

int32_t tpunet_c_stripe_map(uint64_t len, uint64_t min_chunksize,
                            const char* weights, uint64_t cursor, char* out,
                            uint64_t cap) {
  if ((!out && cap > 0) || !weights) return Fail(TPUNET_ERR_NULL, "null param");
  if (min_chunksize == 0) return Fail(TPUNET_ERR_INVALID, "min_chunksize must be >= 1");
  std::vector<uint32_t> w;
  std::string tok;
  std::string spec(weights);
  for (size_t pos = 0; pos <= spec.size(); ++pos) {
    if (pos < spec.size() && spec[pos] != ',') {
      tok += spec[pos];
      continue;
    }
    if (tok.empty()) return Fail(TPUNET_ERR_INVALID, "empty weight in list");
    char* end = nullptr;
    unsigned long v = strtoul(tok.c_str(), &end, 10);
    if ((end && *end != '\0') || v < 1 || v > 255) {
      return Fail(TPUNET_ERR_INVALID, "weight \"" + tok + "\" must be 1..255");
    }
    w.push_back(static_cast<uint32_t>(v));
    tok.clear();
  }
  if (w.empty() || w.size() > 256) {
    return Fail(TPUNET_ERR_INVALID, "weight list must name 1..256 streams");
  }
  // Exactly the engines' derivation: shared chunk math, then the WRR
  // slot-table walk from the cursor (uniform weights degenerate to
  // cursor % nstreams — the pre-lane rotation).
  size_t csize = tpunet::ChunkSize(len, min_chunksize, w.size());
  size_t nchunks = tpunet::ChunkCount(len, csize);
  std::vector<uint8_t> slots = tpunet::BuildWrrSlots(w);
  std::string text;
  for (size_t i = 0; i < nchunks; ++i) {
    if (i) text += ",";
    text += std::to_string(slots[(cursor + i) % slots.size()]);
  }
  if (cap > 0) {
    uint64_t n = std::min<uint64_t>(text.size(), cap - 1);
    memcpy(out, text.data(), n);
    out[n] = '\0';
  }
  return static_cast<int32_t>(text.size());
}

int32_t tpunet_c_qos_state(char* buf, uint64_t cap) {
  if (!buf && cap > 0) return Fail(TPUNET_ERR_NULL, "buf is null");
  std::string text = tpunet::QosScheduler::Get().StateText();
  if (cap > 0) {
    uint64_t n = std::min<uint64_t>(text.size(), cap - 1);
    memcpy(buf, text.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int32_t>(text.size());
}

int32_t tpunet_c_qos_drr_golden(const char* weights, const char* window,
                                const char* chunks, char* out, uint64_t cap) {
  if ((!out && cap > 0) || !chunks) return Fail(TPUNET_ERR_NULL, "null param");
  std::string err;
  std::string order = tpunet::QosScheduler::DrrGolden(
      weights ? weights : "", window ? window : "", chunks, &err);
  if (!err.empty()) return Fail(TPUNET_ERR_INVALID, err);
  if (cap > 0) {
    uint64_t n = std::min<uint64_t>(order.size(), cap - 1);
    memcpy(out, order.data(), n);
    out[n] = '\0';
  }
  return static_cast<int32_t>(order.size());
}

}  // extern "C"
