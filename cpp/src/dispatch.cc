// Schedule dispatch: built-in thresholds, the TPUNET_DISPATCH_TABLE JSON
// loader, and the per-algo counters. See dispatch.h for the contract.
#include "dispatch.h"

#include <atomic>
#include <cctype>
#include <cstdio>
#include <cstring>

#include "tpunet/utils.h"

namespace tpunet {

namespace {

// Built-in size thresholds (bytes). Coarse on purpose: they encode the
// step-count asymptotics the paperwork can defend anywhere ("The Big
// Send-off": rings collapse for small/medium messages at scale), not this
// box's microseconds — the tuned numbers come from `busbw_sweep
// --emit-dispatch` via TPUNET_DISPATCH_TABLE.
//   tree:  2*ceil(log2 W) rounds, one rank's worth of bytes per round —
//          wins while the per-round latency dominates (tiny payloads).
//   rhd:   2*log2(W') rounds moving 2*(W'-1)/W' * S bytes total (the same
//          bandwidth optimality as the ring, at log instead of linear
//          round count) — the small/medium sweet spot.
//   ring:  linear rounds but a mature chunk pipeline (reduce overlaps
//          transfer, vectored IO, codec fusion) — keeps the large end.
constexpr uint64_t kTreeMaxAllReduce = 8ull << 10;    // <= 8 KiB
constexpr uint64_t kRhdMaxAllReduce = 256ull << 10;   // <= 256 KiB
constexpr uint64_t kTreeMaxBroadcast = 1ull << 20;    // <= 1 MiB

CollAlgo SelectBuiltin(CollKind coll, uint64_t nbytes, int world) {
  // AllToAll: the direct pairwise mesh is the flat default at every size
  // (minimum wire bytes); ApplyHierPolicy upgrades it to the two-stage
  // hierarchical transpose on a usable topology, and the communicator's
  // mesh_max_world guard routes oversized worlds to the ring relay.
  if (coll == CollKind::kAllToAll) return CollAlgo::kPairwise;
  // W <= 2: every schedule degenerates to the same one exchange (ring
  // 2(W-1)=2 rounds, rhd 2, tree 2) and the ring channel is already wired —
  // never pay mesh wiring for zero step savings.
  if (world <= 2) return CollAlgo::kRing;
  if (coll == CollKind::kAllReduce) {
    if (nbytes <= kTreeMaxAllReduce) return CollAlgo::kTree;
    if (nbytes <= kRhdMaxAllReduce) return CollAlgo::kRhd;
    return CollAlgo::kRing;
  }
  // Broadcast: binomial tree is ceil(log2 W) store-and-forward hops vs the
  // ring relay's W-1; the pipelined ring only catches up once the payload
  // is deep enough to stream many chunks.
  if (nbytes <= kTreeMaxBroadcast) return CollAlgo::kTree;
  return CollAlgo::kRing;
}

// ---- Minimal JSON scanner for the dispatch-table schema --------------------
// Hand-rolled on purpose (no third-party deps in the native core). Supports
// exactly what --emit-dispatch writes: one object with scalar fields and one
// "entries" array of flat objects. Anything deeper is a loud error.

struct Cursor {
  const char* p;
  const char* end;
};

void SkipWs(Cursor* c) {
  while (c->p < c->end && std::isspace(static_cast<unsigned char>(*c->p))) ++c->p;
}

bool Eat(Cursor* c, char ch) {
  SkipWs(c);
  if (c->p < c->end && *c->p == ch) {
    ++c->p;
    return true;
  }
  return false;
}

Status ParseJsonString(Cursor* c, std::string* out) {
  SkipWs(c);
  if (c->p >= c->end || *c->p != '"') {
    return Status::Invalid("dispatch table: expected a JSON string");
  }
  ++c->p;
  out->clear();
  while (c->p < c->end && *c->p != '"') {
    if (*c->p == '\\') {
      return Status::Invalid("dispatch table: escaped strings are not supported");
    }
    out->push_back(*c->p++);
  }
  if (c->p >= c->end) return Status::Invalid("dispatch table: unterminated string");
  ++c->p;  // closing quote
  return Status::Ok();
}

Status ParseJsonU64(Cursor* c, uint64_t* out) {
  SkipWs(c);
  const char* start = c->p;
  uint64_t v = 0;
  while (c->p < c->end && std::isdigit(static_cast<unsigned char>(*c->p))) {
    v = v * 10 + static_cast<uint64_t>(*c->p - '0');
    ++c->p;
  }
  if (c->p == start) {
    return Status::Invalid("dispatch table: expected a non-negative integer");
  }
  *out = v;
  return Status::Ok();
}

// Skip one scalar value for tolerated-but-unused keys ("version", comment
// strings). Nested arrays/objects under unknown keys are rejected — this
// parser is for one schema, not for JSON.
Status SkipScalar(Cursor* c) {
  SkipWs(c);
  if (c->p < c->end && *c->p == '"') {
    std::string s;
    return ParseJsonString(c, &s);
  }
  const char* start = c->p;
  while (c->p < c->end && (std::isalnum(static_cast<unsigned char>(*c->p)) ||
                           *c->p == '-' || *c->p == '.' || *c->p == '+')) {
    ++c->p;
  }
  if (c->p == start) {
    return Status::Invalid("dispatch table: unsupported value (nested arrays/"
                           "objects are only allowed under \"entries\")");
  }
  return Status::Ok();
}

Status ParseEntry(Cursor* c, DispatchEntry* e) {
  if (!Eat(c, '{')) return Status::Invalid("dispatch table: expected '{' starting an entry");
  bool saw_coll = false, saw_algo = false;
  if (!Eat(c, '}')) {
    do {
      std::string key;
      Status s = ParseJsonString(c, &key);
      if (!s.ok()) return s;
      if (!Eat(c, ':')) return Status::Invalid("dispatch table: expected ':' after key \"" + key + "\"");
      if (key == "coll") {
        std::string v;
        s = ParseJsonString(c, &v);
        if (!s.ok()) return s;
        if (v == "allreduce") {
          e->coll = CollKind::kAllReduce;
        } else if (v == "broadcast") {
          e->coll = CollKind::kBroadcast;
        } else if (v == "alltoall") {
          e->coll = CollKind::kAllToAll;
        } else {
          return Status::Invalid("dispatch table: unknown collective \"" + v +
                                 "\" (expected allreduce, broadcast or "
                                 "alltoall)");
        }
        saw_coll = true;
      } else if (key == "algo") {
        std::string v;
        s = ParseJsonString(c, &v);
        if (!s.ok()) return s;
        CollAlgo a;
        if (!ParseCollAlgo(v, &a) || a == CollAlgo::kAuto) {
          return Status::Invalid("dispatch table: unknown algo \"" + v +
                                 "\" (expected ring, rhd, tree, hier, "
                                 "hier_a2a or pairwise)");
        }
        e->algo = a;
        saw_algo = true;
      } else if (key == "world") {
        uint64_t v = 0;
        s = ParseJsonU64(c, &v);
        if (!s.ok()) return s;
        e->world = static_cast<int>(v);
      } else if (key == "max_bytes") {
        s = ParseJsonU64(c, &e->max_bytes);
        if (!s.ok()) return s;
      } else {
        s = SkipScalar(c);
        if (!s.ok()) return s;
      }
    } while (Eat(c, ','));
    if (!Eat(c, '}')) return Status::Invalid("dispatch table: expected '}' closing an entry");
  }
  if (!saw_coll || !saw_algo) {
    return Status::Invalid("dispatch table: entry missing required \"coll\"/\"algo\" keys");
  }
  return Status::Ok();
}

std::atomic<uint64_t> g_coll_steps[kCollAlgoCount] = {};
std::atomic<uint64_t> g_coll_selected[kCollKindCount][kCollAlgoCount] = {};
// Hier stage rounds: [0] intra-host, [1] inter-host (DCN).
std::atomic<uint64_t> g_hier_steps[2] = {};
// Hierarchical-AllToAll stage rounds: [0] intra, [1] inter (DCN).
std::atomic<uint64_t> g_a2a_steps[2] = {};
// AllToAll wire bytes per [stage][dir] (dispatch.h CountA2aBytes).
std::atomic<uint64_t> g_a2a_bytes[kA2aStageCount][2] = {};

}  // namespace

bool ParseCollAlgo(const std::string& name, CollAlgo* out) {
  if (name == "auto") {
    *out = CollAlgo::kAuto;
  } else if (name == "ring") {
    *out = CollAlgo::kRing;
  } else if (name == "rhd") {
    *out = CollAlgo::kRhd;
  } else if (name == "tree") {
    *out = CollAlgo::kTree;
  } else if (name == "hier") {
    *out = CollAlgo::kHier;
  } else if (name == "hier_a2a") {
    *out = CollAlgo::kHierA2a;
  } else if (name == "pairwise") {
    *out = CollAlgo::kPairwise;
  } else {
    return false;
  }
  return true;
}

const char* CollAlgoName(CollAlgo a) {
  switch (a) {
    case CollAlgo::kAuto:
      return "auto";
    case CollAlgo::kRing:
      return "ring";
    case CollAlgo::kRhd:
      return "rhd";
    case CollAlgo::kTree:
      return "tree";
    case CollAlgo::kHier:
      return "hier";
    case CollAlgo::kHierA2a:
      return "hier_a2a";
    case CollAlgo::kPairwise:
      return "pairwise";
  }
  return "?";
}

const char* CollKindName(CollKind c) {
  switch (c) {
    case CollKind::kAllReduce:
      return "allreduce";
    case CollKind::kBroadcast:
      return "broadcast";
    case CollKind::kAllToAll:
      return "alltoall";
  }
  return "?";
}

Status ParseDispatchTable(const std::string& json, DispatchTable* out) {
  out->entries.clear();
  out->loaded = false;
  Cursor c{json.data(), json.data() + json.size()};
  if (!Eat(&c, '{')) return Status::Invalid("dispatch table: expected a top-level JSON object");
  bool saw_entries = false;
  if (!Eat(&c, '}')) {
    do {
      std::string key;
      Status s = ParseJsonString(&c, &key);
      if (!s.ok()) return s;
      if (!Eat(&c, ':')) return Status::Invalid("dispatch table: expected ':' after key \"" + key + "\"");
      if (key == "entries") {
        if (!Eat(&c, '[')) return Status::Invalid("dispatch table: \"entries\" must be an array");
        saw_entries = true;
        if (!Eat(&c, ']')) {
          do {
            DispatchEntry e;
            s = ParseEntry(&c, &e);
            if (!s.ok()) return s;
            out->entries.push_back(e);
          } while (Eat(&c, ','));
          if (!Eat(&c, ']')) return Status::Invalid("dispatch table: expected ']' closing \"entries\"");
        }
      } else {
        s = SkipScalar(&c);
        if (!s.ok()) return s;
      }
    } while (Eat(&c, ','));
    if (!Eat(&c, '}')) return Status::Invalid("dispatch table: expected '}' closing the table");
  }
  SkipWs(&c);
  if (c.p != c.end) return Status::Invalid("dispatch table: trailing bytes after the table object");
  if (!saw_entries) return Status::Invalid("dispatch table: missing \"entries\" array");
  out->loaded = true;
  return Status::Ok();
}

Status LoadDispatchTableFile(const std::string& path, DispatchTable* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) {
    return Status::Invalid("TPUNET_DISPATCH_TABLE: cannot open \"" + path + "\"");
  }
  std::string text;
  char buf[4096];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0) text.append(buf, n);
  std::fclose(f);
  Status s = ParseDispatchTable(text, out);
  if (!s.ok()) return Status::Invalid(s.msg + " (TPUNET_DISPATCH_TABLE=" + path + ")");
  out->crc = Crc32c(text.data(), text.size());
  return Status::Ok();
}

CollAlgo SelectCollAlgo(const DispatchTable& table, CollAlgo override_algo,
                        CollKind coll, uint64_t nbytes, int world) {
  if (override_algo != CollAlgo::kAuto) return override_algo;
  if (table.loaded) {
    for (const DispatchEntry& e : table.entries) {
      if (e.coll != coll) continue;
      if (e.world != 0 && e.world != world) continue;
      if (e.max_bytes != 0 && nbytes > e.max_bytes) continue;
      return e.algo;
    }
  }
  return SelectBuiltin(coll, nbytes, world);
}

CollAlgo ApplyHierPolicy(CollAlgo a, CollKind coll, uint64_t nbytes,
                         bool usable, bool profitable, bool builtin_auto) {
  if (coll == CollKind::kAllToAll) {
    // "hier" names the hierarchical shape of BOTH collectives; rhd/tree
    // verdicts have no AllToAll meaning and degrade to the pairwise mesh
    // (deterministically, so every rank agrees).
    if (a == CollAlgo::kHier) a = CollAlgo::kHierA2a;
    if (a == CollAlgo::kRhd || a == CollAlgo::kTree) a = CollAlgo::kPairwise;
    if (a == CollAlgo::kHierA2a) {
      return usable ? a : CollAlgo::kPairwise;
    }
    // Built-in auto: a usable hierarchy upgrades the pairwise mesh to the
    // two-stage transpose at every size — per-rank DCN connections drop
    // from R(H-1) to H-1 and the per-peer shards aggregate R-fold (the
    // latency lever for small, skewed MoE dispatch shards).
    if (builtin_auto && usable && a == CollAlgo::kPairwise) {
      return CollAlgo::kHierA2a;
    }
    return a;
  }
  // kHierA2a / kPairwise are AllToAll shapes; on the reduce-side
  // collectives they read as their closest analogue before the normal
  // policy applies.
  if (a == CollAlgo::kHierA2a) a = CollAlgo::kHier;
  if (a == CollAlgo::kPairwise) a = CollAlgo::kRing;
  if (coll != CollKind::kAllReduce) {
    return a == CollAlgo::kHier ? CollAlgo::kRing : a;
  }
  if (a == CollAlgo::kHier) return usable ? a : CollAlgo::kRing;
  // Built-in auto: the large-message band (where the ring keeps the flat
  // crown) goes hierarchical on a profitable topology — same thresholds
  // that hand rhd the middle band.
  if (builtin_auto && profitable && a == CollAlgo::kRing &&
      nbytes > kRhdMaxAllReduce) {
    return CollAlgo::kHier;
  }
  return a;
}

void CountCollSteps(CollAlgo a, uint64_t n) {
  g_coll_steps[static_cast<int>(a)].fetch_add(n, std::memory_order_relaxed);
}

void CountHierSteps(bool inter, uint64_t n) {
  g_hier_steps[inter ? 1 : 0].fetch_add(n, std::memory_order_relaxed);
}

uint64_t HierStepsTotal(bool inter) {
  return g_hier_steps[inter ? 1 : 0].load(std::memory_order_relaxed);
}

void CountA2aSteps(bool inter, uint64_t n) {
  g_a2a_steps[inter ? 1 : 0].fetch_add(n, std::memory_order_relaxed);
}

uint64_t A2aStepsTotal(bool inter) {
  return g_a2a_steps[inter ? 1 : 0].load(std::memory_order_relaxed);
}

void CountA2aBytes(int stage, int dir, uint64_t nbytes) {
  if (stage < 0 || stage >= kA2aStageCount) return;
  g_a2a_bytes[stage][dir & 1].fetch_add(nbytes, std::memory_order_relaxed);
}

uint64_t A2aBytesTotal(int stage, int dir) {
  if (stage < 0 || stage >= kA2aStageCount) return 0;
  return g_a2a_bytes[stage][dir & 1].load(std::memory_order_relaxed);
}

void CountCollAlgoSelected(CollKind c, CollAlgo a) {
  g_coll_selected[static_cast<int>(c)][static_cast<int>(a)].fetch_add(
      1, std::memory_order_relaxed);
}

uint64_t CollStepsTotal(CollAlgo a) {
  return g_coll_steps[static_cast<int>(a)].load(std::memory_order_relaxed);
}

uint64_t CollAlgoSelectedTotal(CollKind c, CollAlgo a) {
  return g_coll_selected[static_cast<int>(c)][static_cast<int>(a)].load(
      std::memory_order_relaxed);
}

void ResetCollDispatchCounters() {
  for (auto& v : g_coll_steps) v.store(0, std::memory_order_relaxed);
  for (auto& v : g_hier_steps) v.store(0, std::memory_order_relaxed);
  for (auto& v : g_a2a_steps) v.store(0, std::memory_order_relaxed);
  for (auto& per_stage : g_a2a_bytes) {
    for (auto& v : per_stage) v.store(0, std::memory_order_relaxed);
  }
  for (auto& per_kind : g_coll_selected) {
    for (auto& v : per_kind) v.store(0, std::memory_order_relaxed);
  }
}

}  // namespace tpunet
