// tpunet — ncclNet-shaped vtable shim over the tpunet C ABI.
//
// TPU-native re-design of the reference's two plugin adapters (reference:
// cc/v4/nccl_net_v4.cc and cc/v3/nccl_net_v3.cc, exported vtables at
// :210-226 of each): every baguaNet*_vN forwarded to a process singleton and
// mapped nonzero results to ncclInternalError. This shim does the same over
// tpunet_c_*, so build/libtpunet.so doubles as a drop-in libnccl-net.so for
// NCCL-style harnesses (BASELINE config 1: loopback isend/irecv validation
// through the vtable alone).
//
// Reference quirks deliberately fixed here:
//   - comm/request handles are the engine ids biased by +1 and packed into
//     the void* itself — no heap allocation, so nothing leaks (the reference
//     heap-allocated a uintptr_t per request and never freed it,
//     cc/bagua_net.cc:88,107 vs :111-121);
//   - errors keep their kind: TPUNET_ERR_INVALID -> ncclInvalidArgument
//     (the reference collapsed everything to ncclInternalError).
#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <mutex>

#include "tpunet/c_api.h"
#include "tpunet/ncclnet_compat.h"

namespace {

ncclDebugLogger_t g_logger = nullptr;
uintptr_t g_instance = 0;
std::once_flag g_once;
int32_t g_create_rc = TPUNET_OK;

void Log(ncclDebugLogLevel level, const char* fmt, ...) {
  if (g_logger == nullptr) return;
  char msg[512];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(msg, sizeof(msg), fmt, ap);
  va_end(ap);
  g_logger(level, ~0ul, __FILE__, __LINE__, "%s", msg);
}

ncclResult_t MapRc(int32_t rc) {
  switch (rc) {
    case TPUNET_OK:
      return ncclSuccess;
    case TPUNET_ERR_NULL:
    case TPUNET_ERR_INVALID:
      return ncclInvalidArgument;
    default:
      return ncclInternalError;
  }
}

// Engine ids are plain uint64 tokens; bias by +1 so a valid handle is never
// NULL (NCCL treats NULL comms/requests as absent).
void* PackId(uintptr_t id) { return reinterpret_cast<void*>(id + 1); }
uintptr_t UnpackId(void* handle) {
  return reinterpret_cast<uintptr_t>(handle) - 1;
}

ncclResult_t EnsureInstance() {
  std::call_once(g_once, [] { g_create_rc = tpunet_c_create(&g_instance); });
  if (g_create_rc != TPUNET_OK) {
    Log(NCCL_LOG_WARN, "tpunet: engine create failed: %s",
        tpunet_c_last_error());
    return ncclInternalError;
  }
  return ncclSuccess;
}

ncclResult_t ShimInit(ncclDebugLogger_t logFunction) {
  g_logger = logFunction;
  ncclResult_t r = EnsureInstance();
  if (r == ncclSuccess) Log(NCCL_LOG_INFO, "tpunet: ncclNet shim initialized");
  return r;
}

ncclResult_t ShimDevices(int* ndev) {
  if (ndev == nullptr) return ncclInvalidArgument;
  if (EnsureInstance() != ncclSuccess) return ncclInternalError;
  int32_t n = 0;
  int32_t rc = tpunet_c_devices(g_instance, &n);
  *ndev = n;
  return MapRc(rc);
}

ncclResult_t ShimGetProperties(int dev, ncclNetProperties_v4_t* props) {
  if (props == nullptr) return ncclInvalidArgument;
  if (EnsureInstance() != ncclSuccess) return ncclInternalError;
  tpunet_net_properties_t p = {};
  int32_t rc = tpunet_c_get_properties(g_instance, dev, &p);
  if (rc != TPUNET_OK) return MapRc(rc);
  // tpunet owns the strings for the instance lifetime (c_api.h contract), so
  // handing out the pointers matches NCCL's expectation.
  props->name = const_cast<char*>(p.name);
  props->pciPath = const_cast<char*>(p.pci_path);
  props->guid = p.guid;
  props->ptrSupport = NCCL_PTR_HOST;
  props->speed = p.speed_mbps;
  props->port = p.port;
  props->maxComms = p.max_comms;
  return ncclSuccess;
}

ncclResult_t ShimListen(int dev, void* handle, void** listenComm) {
  if (handle == nullptr || listenComm == nullptr) return ncclInvalidArgument;
  if (EnsureInstance() != ncclSuccess) return ncclInternalError;
  static_assert(sizeof(tpunet_socket_handle_t) == NCCL_NET_HANDLE_MAXSIZE,
                "rendezvous handle must fit NCCL's 64-byte budget");
  uintptr_t id = 0;
  int32_t rc = tpunet_c_listen(
      g_instance, dev, static_cast<tpunet_socket_handle_t*>(handle), &id);
  if (rc != TPUNET_OK) return MapRc(rc);
  *listenComm = PackId(id);
  return ncclSuccess;
}

ncclResult_t ShimConnect(int dev, void* handle, void** sendComm) {
  if (handle == nullptr || sendComm == nullptr) return ncclInvalidArgument;
  if (EnsureInstance() != ncclSuccess) return ncclInternalError;
  uintptr_t id = 0;
  int32_t rc = tpunet_c_connect(
      g_instance, dev, static_cast<const tpunet_socket_handle_t*>(handle), &id);
  if (rc != TPUNET_OK) return MapRc(rc);
  *sendComm = PackId(id);
  return ncclSuccess;
}

ncclResult_t ShimAccept(void* listenComm, void** recvComm) {
  if (listenComm == nullptr || recvComm == nullptr) return ncclInvalidArgument;
  uintptr_t id = 0;
  int32_t rc = tpunet_c_accept(g_instance, UnpackId(listenComm), &id);
  if (rc != TPUNET_OK) return MapRc(rc);
  *recvComm = PackId(id);
  return ncclSuccess;
}

// Host memory needs no registration; reject device pointers like the
// reference (v4/nccl_net_v4.cc:105-109).
ncclResult_t ShimRegMr(void* /*comm*/, void* /*data*/, int /*size*/, int type,
                       void** mhandle) {
  if (type != NCCL_PTR_HOST) return ncclInternalError;
  if (mhandle != nullptr) *mhandle = nullptr;
  return ncclSuccess;
}

ncclResult_t ShimDeregMr(void* /*comm*/, void* /*mhandle*/) {
  return ncclSuccess;
}

ncclResult_t ShimIsend(void* sendComm, void* data, int size, void* /*mhandle*/,
                       void** request) {
  if (sendComm == nullptr || request == nullptr || size < 0)
    return ncclInvalidArgument;
  uintptr_t req = 0;
  int32_t rc = tpunet_c_isend(g_instance, UnpackId(sendComm), data,
                              static_cast<uint64_t>(size), &req);
  if (rc != TPUNET_OK) return MapRc(rc);
  *request = PackId(req);
  return ncclSuccess;
}

ncclResult_t ShimIrecv(void* recvComm, void* data, int size, void* /*mhandle*/,
                       void** request) {
  if (recvComm == nullptr || request == nullptr || size < 0)
    return ncclInvalidArgument;
  uintptr_t req = 0;
  int32_t rc = tpunet_c_irecv(g_instance, UnpackId(recvComm), data,
                              static_cast<uint64_t>(size), &req);
  if (rc != TPUNET_OK) return MapRc(rc);
  *request = PackId(req);
  return ncclSuccess;
}

// Host memory only: there is never device memory to flush. The reference
// erred here (v4/nccl_net_v4.cc:145-149); NCCL only flushes NCCL_PTR_CUDA
// buffers, which regMr already rejects, so this is unreachable either way.
ncclResult_t ShimIflush(void* /*recvComm*/, void* /*data*/, int /*size*/,
                        void* /*mhandle*/, void** /*request*/) {
  return ncclInternalError;
}

ncclResult_t ShimFlushV3(void* /*recvComm*/, void* /*data*/, int /*size*/,
                         void* /*mhandle*/) {
  return ncclInternalError;
}

ncclResult_t ShimTest(void* request, int* done, int* size) {
  if (request == nullptr || done == nullptr) return ncclInvalidArgument;
  uint8_t d = 0;
  uint64_t nbytes = 0;
  int32_t rc = tpunet_c_test(g_instance, UnpackId(request), &d, &nbytes);
  if (rc != TPUNET_OK) return MapRc(rc);
  *done = d;
  if (size != nullptr) *size = static_cast<int>(nbytes);
  return ncclSuccess;
}

ncclResult_t ShimCloseSend(void* sendComm) {
  if (sendComm == nullptr) return ncclInvalidArgument;
  return MapRc(tpunet_c_close_send(g_instance, UnpackId(sendComm)));
}

ncclResult_t ShimCloseRecv(void* recvComm) {
  if (recvComm == nullptr) return ncclInvalidArgument;
  return MapRc(tpunet_c_close_recv(g_instance, UnpackId(recvComm)));
}

ncclResult_t ShimCloseListen(void* listenComm) {
  if (listenComm == nullptr) return ncclInvalidArgument;
  return MapRc(tpunet_c_close_listen(g_instance, UnpackId(listenComm)));
}

}  // namespace

extern "C" {

ncclNet_v4_t ncclNetPlugin_v4 = {
    "TPUNet",      ShimInit,      ShimDevices,   ShimGetProperties,
    ShimListen,    ShimConnect,   ShimAccept,    ShimRegMr,
    ShimDeregMr,   ShimIsend,     ShimIrecv,     ShimIflush,
    ShimTest,      ShimCloseSend, ShimCloseRecv, ShimCloseListen,
};

ncclNet_v3_t ncclNetPlugin_v3 = {
    "TPUNet",      ShimInit,      ShimDevices,   ShimGetProperties,
    ShimListen,    ShimConnect,   ShimAccept,    ShimRegMr,
    ShimDeregMr,   ShimIsend,     ShimIrecv,     ShimFlushV3,
    ShimTest,      ShimCloseSend, ShimCloseRecv, ShimCloseListen,
};

}  // extern "C"
